"""Backfill action (reference actions/backfill/backfill.go:40-73): every
pending BestEffort task (empty resource request) goes to the first node that
passes predicates."""

from __future__ import annotations

import logging

from ..api import TaskStatus
from ..framework import Action, register_action
from ..utils.scheduler_helper import get_node_list

logger = logging.getLogger(__name__)


class BackfillAction(Action):
    def name(self) -> str:
        return "backfill"

    def execute(self, ssn) -> None:
        for job in ssn.jobs.values():
            for task in list(
                job.task_status_index.get(TaskStatus.PENDING, {}).values()
            ):
                if not task.init_resreq.is_empty():
                    # Reference parity: backfill only places tasks with an
                    # EMPTY resource request (BestEffort), backfill.go:45-49.
                    continue
                for node in get_node_list(ssn.nodes):
                    try:
                        ssn.predicate_fn(task, node)
                    except Exception:
                        continue
                    try:
                        ssn.allocate(task, node.name)
                    except Exception:
                        logger.exception(
                            "Failed to bind Task %s on %s", task.uid, node.name
                        )
                        continue
                    break


register_action(BackfillAction())
