"""Successor recovery: reconcile the bind-intent journal against
cluster truth after a leader change (doc/design/robustness.md,
failover section).

A leader that dies mid-bind-drain leaves the cluster in a state only
the journal can classify: some of its dispatched binds landed, some
never will, and a gang may sit below its minMember with no process
left that knows which members were in flight. This pass runs on the
SUCCESSOR, after lease acquisition and before its first scheduling
cycle, and is deliberately independent of the scheduler cache — it
reads cluster truth directly (the mirror ingests whatever it repairs
through ordinary watch events), the same first-principles discipline
as the simulator's InvariantChecker.

Per-task decision table (doc/design/robustness.md carries the prose
version):

| journal mark | cluster truth             | class      | action |
|--------------|---------------------------|------------|--------|
| applied      | any                       | applied    | none — the dead leader confirmed the bind |
| failed       | any                       | failed     | none — the dead leader already reverted/resynced it |
| (none)       | pod bound to intent node  | applied    | none — bind landed, the applied mark was lost in the crash |
| (none)       | pod bound elsewhere       | superseded | none — a later intent (or leader) owns the placement |
| (none)       | pod missing               | vanished   | none — the world moved on |
| (none)       | pod still unbound         | lost       | gang repair (below), else requeued to normal scheduling |

Gang repair (the all-or-nothing constraint may never stay
half-satisfied): lost tasks are grouped per job; a job whose BOUND
member count sits strictly between 0 and minMember is repaired by
**re-driving** each lost bind to its journaled node when the node is
still present, ready, and fits (an independent capacity recount — the
successor must not oversubscribe while repairing), or — when
completion cannot reach minMember — by **evicting** the partial
placement (every bound member deleted; the controller analog recreates
the gang whole). Re-drives are themselves journaled under the
successor's identity before being issued, so recovery is re-entrant
if the successor crashes too.

Every scanned predecessor record is removed once classified; the
journal after a recovery pass contains only the successor's own
(self-cleaning) re-drive intents.

PRECONDITION — the caller holds leadership. Recovery runs after lease
acquisition (Scheduler.run under the elector) and treats every
surviving intent as a DEAD leader's. Running it beside a live leader
(e.g. ``--once`` without election against a cluster that has an
elected scheduler) would classify that leader's still-draining binds
as lost and prune its journal — but that deployment already races the
live leader on every bind it makes; the single-scheduler assumption is
the same one scheduling itself carries there.
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..api import NodeInfo, Resource, TaskInfo

logger = logging.getLogger(__name__)

# Per-task reconciliation outcomes (the metric's label universe).
RECOVERY_OUTCOMES = (
    "applied", "failed", "redriven", "requeued", "evicted",
    "superseded", "vanished",
)

# Snapshot of the most recent recovery pass for /debug/vars (the
# handler has no scheduler reference; module global like
# scheduler.ACTIVE_WATCHDOG). Written once at successor startup.
LAST_RECOVERY: Optional[dict] = None


@dataclass
class RecoveryReport:
    """Outcome of one successor recovery pass."""

    leader: str
    intents_scanned: int = 0
    tasks_classified: int = 0
    outcomes: Dict[str, int] = field(default_factory=dict)
    # Pod keys re-driven to their journaled nodes / evicted to restore
    # gang atomicity (the sim harness schedules controller-analog
    # recreations for the evicted ones).
    redriven: List[dict] = field(default_factory=list)
    evicted: List[dict] = field(default_factory=list)
    gangs_repaired: List[str] = field(default_factory=list)
    gangs_evicted: List[str] = field(default_factory=list)
    errors: int = 0
    duration_ms: float = 0.0

    def count(self, outcome: str, n: int = 1) -> None:
        if n:
            self.outcomes[outcome] = self.outcomes.get(outcome, 0) + n
            self.tasks_classified += n

    def summary(self) -> dict:
        """Flight-record / trace / debug-vars blob (canonical-JSON
        friendly: plain types, sorted-stable content)."""
        return {
            "leader": self.leader,
            "intents_scanned": self.intents_scanned,
            "tasks_classified": self.tasks_classified,
            "outcomes": dict(sorted(self.outcomes.items())),
            "redriven": list(self.redriven),
            "evicted": list(self.evicted),
            "gangs_repaired": list(self.gangs_repaired),
            "gangs_evicted": list(self.gangs_evicted),
            "errors": self.errors,
            "duration_ms": round(self.duration_ms, 3),
        }


def _pod_bound(pod) -> bool:
    """Does this pod hold a node from the CLUSTER's point of view?"""
    from ..api import PodPhase

    return bool(pod.spec.node_name) and pod.status.phase not in (
        PodPhase.SUCCEEDED, PodPhase.FAILED
    )


def reconcile_journal(cluster: object, identity: str) -> RecoveryReport:
    """Classify every surviving bind intent against cluster truth and
    repair what the dead leader left half-done. ``identity`` stamps the
    successor's own re-drive intents. The scans are deliberately
    cluster-wide: the capacity recount behind gang re-drives must see
    EVERY bound pod's usage, whatever its namespace.

    Never raises: recovery is best-effort by construction — an error
    on one intent is counted and the pass continues, because a
    successor that refuses to start over a malformed record is a worse
    failure mode than the one being repaired."""
    global LAST_RECOVERY

    t0 = time.monotonic()
    report = RecoveryReport(leader=identity)
    try:
        intents = cluster.list_bind_intents()
    except Exception:
        logger.exception("recovery: journal scan failed; nothing to do")
        report.errors += 1
        report.duration_ms = (time.monotonic() - t0) * 1e3
        LAST_RECOVERY = report.summary()
        return report
    report.intents_scanned = len(intents)

    # -- cluster truth, one scan -----------------------------------------
    pods = list(cluster.list_objects("Pod"))
    pod_by_uid = {p.uid: p for p in pods}
    node_alloc: Dict[str, object] = {}
    node_used: Dict[str, object] = {}
    for node in cluster.list_objects("Node"):
        ni = NodeInfo(node)
        if not ni.ready():
            continue
        node_alloc[node.name] = ni.allocatable
        node_used[node.name] = Resource.empty()
    job_bound: Dict[str, List] = {}
    for pod in pods:
        if not _pod_bound(pod):
            continue
        ti = TaskInfo(pod)
        if ti.node_name in node_used:
            node_used[ti.node_name].add(ti.resreq)
        if ti.job:
            job_bound.setdefault(ti.job, []).append(pod)
    min_member: Dict[str, int] = {}
    for pg in cluster.list_objects("PodGroup"):
        min_member[f"{pg.namespace}/{pg.name}"] = pg.spec.min_member

    # -- classification ---------------------------------------------------
    # uid -> intent task dict still unbound (gang-repair input).
    # Keyed by uid, LATER seq wins: the same task can appear in two
    # open records (a failed bind whose 'failed' mark was lost, then a
    # resync re-dispatch) and a duplicate would double-book the
    # capacity recount and double-count the pod toward minMember.
    lost_tasks: Dict[str, dict] = {}
    scanned_seqs: List[int] = []
    for rec in intents:
        scanned_seqs.append(rec.get("seq", 0))
        try:
            marks = rec.get("marks", {}) or {}
            for gang, minm in sorted(
                (rec.get("gangs", {}) or {}).items()
            ):
                # Journal fallback for gang thresholds whose PodGroup
                # died with the leader (the live PodGroup wins).
                min_member.setdefault(gang, int(minm))
            for task in rec.get("tasks", []):
                uid = task.get("uid")
                mark = marks.get(uid)
                if mark in ("applied", "failed"):
                    report.count(mark)
                    continue
                pod = pod_by_uid.get(uid)
                if pod is None:
                    report.count("vanished")
                elif not pod.spec.node_name:
                    lost_tasks[uid] = task
                elif pod.spec.node_name == task.get("node"):
                    # Bind landed; the crash ate the applied mark.
                    # Cluster truth is the authority — applied.
                    report.count("applied")
                else:
                    report.count("superseded")
        except Exception:
            # The never-raises contract: one malformed record (schema
            # drift, a hand-edited annotation) is counted and skipped —
            # it must not pin the whole journal forever.
            logger.exception(
                "recovery: malformed intent record seq=%s skipped",
                rec.get("seq"),
            )
            report.errors += 1
    lost_by_job: Dict[str, List[dict]] = {}
    for uid in sorted(lost_tasks):
        task = lost_tasks[uid]
        lost_by_job.setdefault(task.get("job") or "", []).append(task)

    # -- gang repair -------------------------------------------------------
    for job_key in sorted(lost_by_job):
        try:
            entries = sorted(lost_by_job[job_key], key=lambda t: t["pod"])
            minm = min_member.get(job_key, 0)
            bound = len(job_bound.get(job_key, []))
            if minm <= 1 or bound <= 0 or bound >= minm:
                # No atomicity constraint at stake: unbound tasks simply
                # re-enter normal scheduling on the successor's first cycle.
                report.count("requeued", len(entries))
                continue
            # Partial gang. Plan completion: re-drive each lost bind to its
            # journaled node when it still exists, is ready, and fits an
            # independent capacity recount (reserving as we plan, so two
            # re-drives cannot double-book the same headroom).
            plan = []
            unplaceable = []
            for task in entries:
                pod = pod_by_uid[task["uid"]]
                node = task.get("node") or ""
                alloc = node_alloc.get(node)
                if alloc is None:
                    unplaceable.append(task)
                    continue
                req = TaskInfo(pod).resreq
                projected = node_used[node].clone().add(req)
                if projected.less_equal(alloc):
                    node_used[node] = projected
                    plan.append((task, pod, req))
                else:
                    unplaceable.append(task)
            if bound + len(plan) >= minm and plan:
                seq = _journal_redrive(cluster, identity, job_key, minm, plan)
                done = 0
                for task, pod, req in plan:
                    try:
                        cluster.bind_pod(pod, task["node"])
                    except Exception:
                        logger.exception(
                            "recovery: re-drive of %s -> %s failed",
                            task["pod"], task["node"],
                        )
                        report.errors += 1
                        report.count("requeued")
                        # Give the failed re-drive's reservation back: the
                        # headroom is real and later gangs may need it.
                        node_used[task["node"]].sub(req)
                        _mark_quiet(cluster, seq, task["uid"], "failed")
                        continue
                    done += 1
                    report.count("redriven")
                    report.redriven.append(
                        {"pod": task["pod"], "node": task["node"],
                         "job": job_key}
                    )
                    # Now a bound member: if completion still falls short
                    # the eviction arm must tear this one down too.
                    job_bound.setdefault(job_key, []).append(pod)
                    _mark_quiet(cluster, seq, task["uid"], "applied")
                report.count("requeued", len(unplaceable))
                if bound + done >= minm:
                    report.gangs_repaired.append(job_key)
                    continue
                # Re-drives failed under us: fall through to eviction so
                # the gang never stays half-satisfied.
            else:
                # Abandoned plan: roll its reservations back — leaving them
                # booked would make LATER gangs' journaled nodes look full
                # and spuriously route repairable gangs into eviction.
                for task, _pod, req in plan:
                    node_used[task["node"]].sub(req)
                report.count("requeued", len(plan) + len(unplaceable))
            _evict_partial_gang(cluster, job_key, job_bound, report, node_used)
        except Exception:
            # Same never-raises contract as classification: one
            # gang's repair blowing up must not abort the pass for
            # every other gang (or the journal prune below).
            logger.exception(
                "recovery: gang repair for %s failed", job_key
            )
            report.errors += 1

    # -- prune the predecessor's records (one batched sweep) ---------------
    try:
        cluster.remove_bind_intents(scanned_seqs)
    except Exception:
        logger.exception("recovery: journal prune sweep failed")
        report.errors += 1

    report.duration_ms = (time.monotonic() - t0) * 1e3
    _export(report)
    return report


def _journal_redrive(cluster, identity, job_key, minm, plan) -> Optional[int]:
    """Journal the recovery's own re-drive batch before issuing it —
    recovery must be as crash-tolerant as the dispatch it repairs."""
    try:
        return cluster.append_bind_intent({
            "leader": identity,
            "tasks": [
                {"uid": t["uid"], "pod": t["pod"], "node": t["node"],
                 "job": job_key}
                for t, _pod, _req in plan
            ],
            "gangs": {job_key: minm},
        })
    except Exception:
        logger.exception("recovery: re-drive journal append failed")
        return None


def _mark_quiet(cluster, seq, uid, outcome) -> None:
    if seq is None:
        return
    try:
        cluster.mark_bind_intent(seq, uid, outcome)
    except Exception:
        logger.exception("recovery: re-drive mark failed for %s", uid)


def _evict_partial_gang(cluster, job_key, job_bound, report,
                        node_used) -> None:
    """All-or-nothing restoration, the destructive arm: the gang cannot
    reach minMember, so every bound member is deleted (the controller
    analog recreates the gang whole and it re-schedules atomically).
    Each deletion credits the capacity ledger back — later gangs in the
    same pass must see the freed headroom, not a stale full node."""
    victims = sorted(
        job_bound.get(job_key, []), key=lambda p: (p.namespace, p.name)
    )
    for pod in victims:
        ti = TaskInfo(pod)
        try:
            cluster.delete_pod(pod)
        except Exception:
            logger.exception(
                "recovery: eviction of %s/%s failed",
                pod.namespace, pod.name,
            )
            report.errors += 1
            continue
        if ti.node_name in node_used:
            node_used[ti.node_name].sub(ti.resreq)
        report.count("evicted")
        report.evicted.append(
            {"pod": f"{pod.namespace}/{pod.name}", "job": job_key}
        )
    if victims:
        report.gangs_evicted.append(job_key)


def _export(report: RecoveryReport) -> None:
    """Metrics + the /debug/vars snapshot (never raises)."""
    global LAST_RECOVERY

    try:
        from .. import metrics

        for outcome in sorted(report.outcomes):
            metrics.register_failover_recovery(
                outcome, report.outcomes[outcome]
            )
    except Exception:  # pragma: no cover - metrics must never kill
        logger.exception("recovery metric update failed")
    LAST_RECOVERY = report.summary()
    if report.tasks_classified or report.intents_scanned:
        logger.warning(
            "successor recovery: %d intent(s), %d task(s) reconciled "
            "%s; gangs repaired=%s evicted=%s",
            report.intents_scanned, report.tasks_classified,
            dict(sorted(report.outcomes.items())),
            report.gangs_repaired, report.gangs_evicted,
        )
