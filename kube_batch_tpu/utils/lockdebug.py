"""Runtime lock-order race harness (``KBT_LOCK_DEBUG=1``) and
guarded-write witness (``KBT_LOCK_DEBUG=2``).

The static half of the story — ``tools/kbtlint``'s lock-order and
guarded-by passes — proves ordering and lock ownership over the sites
it can resolve; this module asserts them over the acquisitions and
writes that actually HAPPEN. With ``KBT_LOCK_DEBUG=1`` the project's
named locks are wrapped in order-asserting proxies:

- every ``A held while acquiring B`` acquisition records the edge
  ``A→B`` with the traceback of its first witness;
- acquiring ``A`` while holding ``B`` after ``B→A`` was ever observed
  raises :class:`LockOrderViolation` carrying BOTH acquisition
  tracebacks — the exact forensics PR 7 needed a production deadlock
  to obtain;
- acquiring anything while holding a **leaf** lock (the cache fence
  lock) raises immediately — the fence path must never join a lock
  queue, because it runs precisely when a wedged cycle may be
  deadlocked holding the mutex;
- re-acquiring a held non-reentrant ``Lock`` raises instead of
  deadlocking silently.

``KBT_LOCK_DEBUG=2`` keeps everything level 1 does and additionally
arms the **write-witness**: shared-state classes register their
lock-guarded attributes at the end of ``__init__`` via
:func:`witness_writes` (same named-lock identities as ``wrap_lock``),
and every subsequent ``obj.attr = ...`` of a registered attribute on a
thread NOT holding the named lock raises
:class:`GuardedWriteViolation` with the writing site — the runtime
twin of kbtlint's guarded-by inference, catching the unguarded writes
the static pass cannot resolve (dynamic dispatch, exec'd plugins).
``KBT_LOCK_WITNESS_SAMPLE=N`` checks every Nth guarded write (default
1 = all) when the full check is too hot for a soak.

Off by default and zero-cost when off: ``wrap_lock`` returns the raw
lock and ``witness_writes`` is a no-op unless the env flag is set at
construction time. The chaos/micro smoke suites run with
``KBT_LOCK_DEBUG=2`` (Makefile), so every injected fault storm doubles
as a lock-order AND write-ownership soak. Violations are additionally
collected in :data:`VIOLATIONS` for harness-level assertions.

Condition variables: pass a wrapped lock to ``threading.Condition`` —
the proxy implements ``_release_save``/``_acquire_restore``/
``_is_owned``, so ``wait()`` keeps the held-stack bookkeeping exact
across the release/reacquire pair.
"""

from __future__ import annotations

import os
import threading
import traceback
from typing import Dict, List, Tuple

LOCK_DEBUG_ENV = "KBT_LOCK_DEBUG"

# Locks that must be leaves: nothing may be acquired while one is held
# (mirrors tools/kbtlint/lock_order.LEAF_LOCK_ATTRS).
LEAF_LOCKS = frozenset({"cache.fence_lock"})

_MAX_VIOLATIONS = 100


class LockOrderViolation(AssertionError):
    """Two named locks were acquired in both orders (or a leaf lock
    was held across another acquisition). Message carries the
    tracebacks of both acquisition sites."""


class GuardedWriteViolation(AssertionError):
    """A registered lock-guarded attribute was written by a thread not
    holding its named lock (``KBT_LOCK_DEBUG=2``). Message carries the
    writing site."""


# (held_name, acquired_name) -> formatted traceback of first witness
_edges: Dict[Tuple[str, str], str] = {}
_edges_lock = threading.Lock()  # raw on purpose: the meta-lock
_tls = threading.local()

VIOLATIONS: List[str] = []


def level() -> int:
    raw = os.environ.get(LOCK_DEBUG_ENV, "0")
    try:
        return max(0, int(raw))
    except ValueError:
        return 0


def enabled() -> bool:
    return level() >= 1


def witness_enabled() -> bool:
    return level() >= 2


def reset() -> None:
    """Clear recorded edges/violations and the witness sample cache
    (tests; each harness run starts from an empty order history)."""
    with _edges_lock:
        _edges.clear()
        del VIOLATIONS[:]
    _witness_sample_cached[0] = 0


def _held() -> List[List]:
    held = getattr(_tls, "held", None)
    if held is None:
        held = _tls.held = []
    return held


def _site() -> str:
    # Drop the lockdebug frames themselves: the caller wants to see
    # WHO acquired, not the proxy plumbing.
    frames = traceback.format_stack()[:-3]
    return "".join(frames[-12:])


def _violate(message: str, exc_type=LockOrderViolation) -> None:
    if len(VIOLATIONS) < _MAX_VIOLATIONS:
        VIOLATIONS.append(message)
    raise exc_type(message)


def _check_order(name: str, reentrant: bool) -> None:
    """Order assertions for acquiring ``name`` with the current held
    stack; called BEFORE blocking on the real lock so a would-be
    deadlock surfaces as an exception, not a hang."""
    held = _held()
    for entry in held:
        if entry[0] == name:
            if reentrant:
                return  # re-entry: no new edges
            _violate(
                f"self-deadlock: non-reentrant lock {name!r} "
                f"re-acquired by the thread already holding it\n"
                f"second acquisition:\n{_site()}"
            )
    if not held:
        return  # nothing held: no ordering to assert
    # Steady state must stay CHEAP: a bind storm nests
    # cache.mutex→cluster.store thousands of times per cycle, so the
    # stack capture (the expensive part) only happens for a new edge's
    # first witness or an actual violation — re-walking a known edge
    # costs two dict lookups.
    for entry in held:
        held_name = entry[0]
        if held_name in LEAF_LOCKS:
            _violate(
                f"leaf-lock violation: acquiring {name!r} while "
                f"holding leaf lock {held_name!r} (the fence path must "
                f"never join a lock queue)\nacquisition:\n{_site()}"
            )
        edge = (held_name, name)
        reverse = (name, held_name)
        with _edges_lock:
            reverse_site = _edges.get(reverse)
            known = edge in _edges
        if reverse_site is not None:
            _violate(
                f"lock-order violation: {held_name!r} held while "
                f"acquiring {name!r}, but the opposite order was "
                f"observed earlier\n--- this acquisition "
                f"({held_name} -> {name}):\n{_site()}\n--- first "
                f"acquisition of the reverse order ({name} -> "
                f"{held_name}):\n{reverse_site}"
            )
        if not known:
            site = _site()
            with _edges_lock:
                _edges.setdefault(edge, site)


def _push(name: str) -> None:
    held = _held()
    for entry in held:
        if entry[0] == name:
            entry[1] += 1
            return
    held.append([name, 1])


def _pop(name: str) -> None:
    held = _held()
    for i in range(len(held) - 1, -1, -1):
        if held[i][0] == name:
            held[i][1] -= 1
            if held[i][1] <= 0:
                del held[i]
            return


class _OrderAssertingLock:
    """Proxy over a Lock/RLock asserting acquisition order."""

    _reentrant = False

    def __init__(self, name: str, inner):
        self._name = name
        self._inner = inner

    def acquire(self, blocking: bool = True, timeout: float = -1):
        _check_order(self._name, self._reentrant)
        got = self._inner.acquire(blocking, timeout)
        if got:
            _push(self._name)
        return got

    def release(self) -> None:
        self._inner.release()
        _pop(self._name)

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    # -- Condition integration (threading.Condition duck-typing) ----------

    def _release_save(self):
        held = _held()
        count = 0
        for entry in held:
            if entry[0] == self._name:
                count = entry[1]
                break
        for i in range(len(held) - 1, -1, -1):
            if held[i][0] == self._name:
                del held[i]
                break
        save = self._release_save_inner()
        return (save, count)

    def _release_save_inner(self):
        inner_save = getattr(self._inner, "_release_save", None)
        if inner_save is not None:
            return inner_save()
        self._inner.release()
        return None

    def _acquire_restore(self, state) -> None:
        save, count = state
        _check_order(self._name, self._reentrant)
        inner_restore = getattr(self._inner, "_acquire_restore", None)
        if inner_restore is not None:
            inner_restore(save)
        else:
            self._inner.acquire()
        held = _held()
        held.append([self._name, max(1, count)])

    def _is_owned(self) -> bool:
        inner_owned = getattr(self._inner, "_is_owned", None)
        if inner_owned is not None:
            return inner_owned()
        # Plain Lock: owned iff this thread's held stack says so.
        return any(e[0] == self._name for e in _held())

    def locked(self):
        return self._inner.locked()

    def __repr__(self) -> str:
        return f"<OrderAssertingLock {self._name!r} over {self._inner!r}>"


class _OrderAssertingRLock(_OrderAssertingLock):
    _reentrant = True


def wrap_lock(name: str, lock=None):
    """Wrap ``lock`` (default: a new ``threading.Lock``) in an
    order-asserting proxy when ``KBT_LOCK_DEBUG=1``; return it raw
    otherwise. ``name`` is the stable identity order edges are keyed
    on — use dotted ``component.lock`` names."""
    if lock is None:
        lock = threading.Lock()
    if not enabled():
        return lock
    # An RLock reports its type via repr ("<unlocked _thread.RLock...");
    # isinstance against the factory types is version-fragile, so key
    # on the canonical constructors.
    if isinstance(lock, type(threading.RLock())):
        return _OrderAssertingRLock(name, lock)
    return _OrderAssertingLock(name, lock)


# -- guarded-write witness (KBT_LOCK_DEBUG=2) --------------------------------

WITNESS_SAMPLE_ENV = "KBT_LOCK_WITNESS_SAMPLE"

# (class, lock_name, attrs) -> generated witness subclass, so every
# instance of one registration shape shares one class object.
_witness_classes: Dict[tuple, type] = {}
_witness_counter = [0]  # guarded-write serial for sampling
_witness_sample_cached = [0]  # 0 = unresolved


def _witness_sample() -> int:
    if not _witness_sample_cached[0]:
        raw = os.environ.get(WITNESS_SAMPLE_ENV, "1")
        try:
            _witness_sample_cached[0] = max(1, int(raw))
        except ValueError:
            _witness_sample_cached[0] = 1
    return _witness_sample_cached[0]


def _holds(lock_name: str) -> bool:
    return any(entry[0] == lock_name for entry in _held())


def _witness_check(cls_name: str, lock_name: str, attr: str) -> None:
    if not witness_enabled():
        # A witnessed instance outlives an env change (tests lower the
        # level on teardown; the class swap is permanent) — the check
        # must track the LIVE level, not the level at registration.
        return
    _witness_counter[0] += 1
    if _witness_counter[0] % _witness_sample():
        return
    if _holds(lock_name):
        return
    _violate(
        f"guarded-write violation: {cls_name}.{attr} written without "
        f"holding {lock_name!r}\nwrite site:\n{_site()}",
        exc_type=GuardedWriteViolation,
    )


def witness_writes(obj, lock_name: str, attrs) -> None:
    """Arm the write-witness on ``obj``: any later ``obj.<attr> = ...``
    for ``attr`` in ``attrs`` on a thread not holding ``lock_name``
    raises :class:`GuardedWriteViolation`. No-op below
    ``KBT_LOCK_DEBUG=2``. Call at the END of ``__init__`` — writes
    before arming are construction (happens-before publication) and
    exempt by design."""
    if not witness_enabled():
        return
    cls = type(obj)
    key = (cls, lock_name, frozenset(attrs))
    wcls = _witness_classes.get(key)
    if wcls is None:
        guarded = frozenset(attrs)

        def __setattr__(self, name, value, _cls=cls, _g=guarded,
                        _lock=lock_name):
            if name in _g:
                _witness_check(_cls.__name__, _lock, name)
            _cls.__setattr__(self, name, value)

        wcls = type(
            f"{cls.__name__}(witnessed)", (cls,),
            {"__setattr__": __setattr__, "__module__": cls.__module__},
        )
        _witness_classes[key] = wcls
    obj.__class__ = wcls
