#!/usr/bin/env bash
# Cluster e2e runner — the reference hack/run-e2e-kind.sh analog
# (/root/reference/hack/run-e2e-kind.sh:46-82: cluster up, CRDs +
# default queue installed, scheduler launched against it, spec run,
# teardown).
#
# Fake mode (default, zero dependencies):
#   ./hack/run-e2e.sh
#   Starts the in-repo fake Kubernetes API server (the kubemark analog)
#   and drives the real scheduler CLI against it via tools/run_e2e.py.
#
# Real-cluster mode:
#   KUBECONFIG=~/.kube/config ./hack/run-e2e.sh real
#   Requires kubectl. Installs the CRDs and default queue, launches the
#   scheduler against the cluster, applies a minMember=3 gang, waits for
#   it to run, and tears the test resources down. Works against any
#   conformant cluster (kind: `kind create cluster` first).
set -euo pipefail
cd "$(dirname "$0")/.."

MODE="${1:-fake}"

if [ "$MODE" = "fake" ]; then
    exec env JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= \
        python tools/run_e2e.py "${@:2}"
fi

[ "$MODE" = "real" ] || { echo "usage: $0 [fake|real]" >&2; exit 2; }
: "${KUBECONFIG:?real mode needs KUBECONFIG}"
command -v kubectl >/dev/null || { echo "kubectl not found" >&2; exit 2; }

NS=tpu-batch-e2e
cleanup() {
    kubectl delete namespace "$NS" --ignore-not-found >/dev/null 2>&1 || true
    [ -n "${SCHED_PID:-}" ] && kill "$SCHED_PID" 2>/dev/null || true
}
trap cleanup EXIT

# CRDs + default queue (reference run-e2e-kind.sh:70-79).
kubectl apply -f config/crds/
kubectl apply -f - <<'YAML'
apiVersion: scheduling.incubator.k8s.io/v1alpha1
kind: Queue
metadata:
  name: default
spec:
  weight: 1
YAML

# Scheduler against the cluster (reference run-e2e-kind.sh:82).
env JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= \
    python -m kube_batch_tpu \
    --kubeconfig "$KUBECONFIG" \
    --scheduler-conf config/tpu-batch-conf.yaml \
    --listen-address 127.0.0.1:0 &
SCHED_PID=$!

kubectl create namespace "$NS"
kubectl apply -n "$NS" -f - <<'YAML'
apiVersion: scheduling.incubator.k8s.io/v1alpha1
kind: PodGroup
metadata:
  name: e2e-gang
spec:
  minMember: 3
  queue: default
YAML
for i in 0 1 2; do
kubectl apply -n "$NS" -f - <<YAML
apiVersion: v1
kind: Pod
metadata:
  name: e2e-p$i
  annotations:
    scheduling.k8s.io/group-name: e2e-gang
spec:
  schedulerName: tpu-batch
  containers:
  - name: main
    image: registry.k8s.io/pause:3.9
    resources:
      requests: {cpu: 100m, memory: 64Mi}
YAML
done

echo "waiting for the gang to schedule..."
for _ in $(seq 60); do
    n=$(kubectl get pods -n "$NS" \
        -o jsonpath='{range .items[*]}{.spec.nodeName}{"\n"}{end}' \
        | grep -c . || true)
    [ "$n" -ge 3 ] && { echo "PASS: $n/3 pods scheduled"; exit 0; }
    sleep 2
done
echo "FAIL: gang did not schedule in 120s" >&2
kubectl get pods -n "$NS" -o wide >&2
exit 1
