"""Top-K candidate-sparsified solver tests (solver/topk.py +
kernels.solve_sparse + the end-to-end wiring).

Parity contract (doc/design/sparse-candidate-solver.md): when every
class's slab covers its whole eligible set (K >= cand_total, e.g.
K >= N) the sparse solve is BIT-IDENTICAL to the dense solve —
assignment vector and node-idle accounting. With truncated slabs the
refill stage restores full-N fidelity for whatever the slab rounds
could not place, so per-job success, total placements, and capacity
accounting match the dense solve across randomized churn; exact node
identity within score-quantum ties is not a contract (the reference
greedy tie-breaks randomly, scheduler_helper.go:188-208).
"""

import os

import numpy as np
import pytest

import jax.numpy as jnp

import kube_batch_tpu.actions  # noqa: F401 (registers actions)
import kube_batch_tpu.plugins  # noqa: F401 (registers plugins)
from kube_batch_tpu.api import PodPhase, build_resource_list
from kube_batch_tpu.solver import (
    jit_compilation_count,
    make_inputs,
    select_candidates,
    solve,
    solve_jit,
    solve_sparse,
    tensorize,
    topk_config,
)
from kube_batch_tpu.solver.masks import CombinedMask

from tests.actions.test_actions import (
    DEFAULT_TIERS_ARGS,
    make_cache,
    make_tiers,
    req,
    run_action,
)
from kube_batch_tpu.framework import close_session, open_session
from kube_batch_tpu.utils.test_utils import (
    build_node,
    build_pod,
    build_pod_group,
    build_queue,
)


def trivial_mask(T, N, group_rows=None, task_group=None):
    return CombinedMask(
        node_ok=np.ones(N, bool),
        task_group=(
            np.zeros(T, np.int32) if task_group is None else task_group
        ),
        group_rows=(
            np.ones((1, N), bool) if group_rows is None else group_rows
        ),
        pair_idx=np.zeros((0,), np.int32),
        pair_rows=np.zeros((0, N), bool),
    )


def solver_kw(task_req, node_idle, *, jobs_of=10):
    task_req = np.asarray(task_req, np.float32)
    node_idle = np.asarray(node_idle, np.float32)
    T, R = task_req.shape
    N = node_idle.shape[0]
    return dict(
        task_req=jnp.asarray(task_req),
        task_fit=jnp.asarray(task_req),
        task_rank=jnp.arange(T, dtype=jnp.int32),
        task_job=jnp.asarray(np.arange(T) // jobs_of, jnp.int32),
        task_queue=jnp.zeros(T, jnp.int32),
        node_idle=jnp.asarray(node_idle),
        node_releasing=jnp.zeros_like(jnp.asarray(node_idle)),
        node_cap=jnp.asarray(node_idle),
        node_task_count=jnp.zeros(N, jnp.int32),
        node_max_tasks=jnp.zeros(N, jnp.int32),
        queue_deserved=jnp.full((1, R), jnp.inf, jnp.float32),
        queue_allocated=jnp.zeros((1, R), jnp.float32),
        eps=jnp.full((R,), 10.0, jnp.float32),
        lr_weight=jnp.asarray(1.0, jnp.float32),
        br_weight=jnp.asarray(1.0, jnp.float32),
    )


def select_for(task_req, node_idle, k, mask=None, score_rows=None,
               task_valid=None):
    task_req = np.asarray(task_req, np.float32)
    node_idle = np.asarray(node_idle, np.float32)
    T = task_req.shape[0]
    N = node_idle.shape[0]
    if mask is None:
        mask = trivial_mask(T, N)
    return select_candidates(
        mask, score_rows or {}, task_req, task_req,
        node_idle, node_idle, np.zeros_like(node_idle),
        np.zeros(N, np.int32), np.zeros(N, np.int32),
        np.array([10.0, 10.0], np.float32), 1.0, 1.0, k,
    )


def sparse_inputs(kw, cs):
    return make_inputs(
        **kw,
        task_cand=jnp.asarray(cs.task_cand),
        cand_idx=jnp.asarray(cs.cand_idx),
        cand_static=jnp.asarray(cs.cand_static),
        cand_info=jnp.asarray(cs.cand_info),
    )


def random_case(seed, T=60, N=16, cap=6000):
    rng = np.random.RandomState(seed)
    task_req = np.c_[
        rng.choice([250, 500, 1000], T), rng.choice([256, 512], T)
    ].astype(np.float32)
    node_idle = np.c_[
        rng.choice([cap, 2 * cap], N), np.full(N, 1e7)
    ].astype(np.float32)
    return task_req, node_idle


class TestTopkConfig:
    def test_env_forced_and_disabled(self, monkeypatch):
        monkeypatch.setenv("KBT_SOLVER_TOPK", "12")
        tk = topk_config(10, 10)
        assert tk.enabled and tk.k == 16  # pow2-bucketed
        for off in ("0", "off", "dense"):
            monkeypatch.setenv("KBT_SOLVER_TOPK", off)
            assert not topk_config(10**6, 10**5).enabled

    def test_size_policy(self, monkeypatch):
        monkeypatch.delenv("KBT_SOLVER_TOPK", raising=False)
        assert not topk_config(100, 100).enabled       # small problem
        assert not topk_config(20000, 200).enabled     # k covers nodes
        assert topk_config(20000, 5000).enabled


class TestSelection:
    def test_gang_members_share_one_class(self):
        # 30 tasks of 3 distinct shapes -> 3 classes, slab rows shared.
        task_req = np.tile(
            np.asarray(
                [[250, 256], [500, 256], [1000, 512]], np.float32
            ),
            (10, 1),
        )
        node_idle = np.full((8, 2), 32000.0, np.float32)
        node_idle[:, 1] = 1e7
        cs = select_for(task_req, node_idle, k=4)
        assert cs.stats["classes"] == 3
        assert len(np.unique(cs.task_cand)) == 3
        same = cs.task_cand[0::3]
        assert (same == same[0]).all()

    def test_slabs_ascend_with_sentinel_padding(self):
        task_req, node_idle = random_case(3, T=20, N=6)
        cs = select_for(task_req, node_idle, k=16)  # k > N: padding
        N = node_idle.shape[0]
        for row in cs.cand_idx:
            real = row[row < N]
            assert (np.diff(real) > 0).all()      # strictly ascending
            assert (row[len(real):] == N).all()   # sentinels last

    def test_eligibility_excludes_never_fitting_nodes(self):
        # One tiny node can never hold the 2-cpu tasks: it must not
        # appear in any slab and cand_total must not count it.
        task_req = np.full((8, 2), [2000.0, 256.0], np.float32)
        node_idle = np.full((4, 2), 8000.0, np.float32)
        node_idle[:, 1] = 1e7
        node_idle[2, 0] = 100.0  # never fits
        cs = select_for(task_req, node_idle, k=4)
        assert (cs.cand_idx != 2).all()
        assert (cs.cand_info[0] == 3).all()

    def test_infeasible_group_has_empty_slab(self):
        task_req = np.full((4, 2), [500.0, 256.0], np.float32)
        node_idle = np.full((4, 2), 8000.0, np.float32)
        mask = trivial_mask(
            4, 4, group_rows=np.zeros((1, 4), bool)
        )
        cs = select_for(task_req, node_idle, k=2, mask=mask)
        assert (cs.cand_idx == 4).all()
        assert (cs.cand_info[0] == 0).all()
        assert (cs.cand_info[1] == 0).all()


def job_placed_counts(assigned, jobs_of=10):
    a = np.asarray(assigned)
    placed = a >= 0
    jobs = np.arange(len(a)) // jobs_of
    return np.bincount(jobs[placed], minlength=jobs.max() + 1)


class TestSparseParity:
    @pytest.mark.parametrize("seed", [0, 1, 2, 3])
    def test_bit_equal_when_slab_covers_nodes(self, seed):
        task_req, node_idle = random_case(seed)
        kw = solver_kw(task_req, node_idle)
        cs = select_for(task_req, node_idle, k=16)  # K = pow2(N) >= N
        dense = solve(make_inputs(**kw))
        sparse = solve_sparse(sparse_inputs(kw, cs), tail_bucket=16)
        np.testing.assert_array_equal(
            np.asarray(dense.assigned), np.asarray(sparse.assigned)
        )
        np.testing.assert_array_equal(
            np.asarray(dense.node_idle), np.asarray(sparse.node_idle)
        )
        assert int(sparse.refills) == 0

    @pytest.mark.parametrize("k", [8, 64])
    def test_randomized_churn_parity(self, k):
        """Across churn cycles (placed tasks leave, idle shrinks by the
        dense solve's accounting), sparse and dense place the same
        per-job counts with identical capacity totals."""
        rng = np.random.RandomState(11)
        T, N = 80, 16
        task_req = np.c_[
            rng.choice([250, 500, 1000], T), rng.choice([256, 512], T)
        ].astype(np.float32)
        node_idle = np.c_[
            rng.choice([4000, 8000], N), np.full(N, 1e7)
        ].astype(np.float32)
        valid = np.ones(T, bool)
        for cycle in range(4):
            kw = solver_kw(task_req, node_idle)
            kw["task_valid"] = jnp.asarray(valid)
            cs = select_for(task_req, node_idle, k=k)
            dense = solve(make_inputs(**kw))
            sparse = solve_sparse(sparse_inputs(kw, cs), tail_bucket=16)
            a_d = np.asarray(dense.assigned)
            a_s = np.asarray(sparse.assigned)
            assert (a_d >= 0).sum() == (a_s >= 0).sum(), f"cycle {cycle}"
            np.testing.assert_array_equal(
                job_placed_counts(a_d), job_placed_counts(a_s),
                err_msg=f"per-job success diverged in cycle {cycle}",
            )
            # Capacity: never negative, and total consumption identical.
            idle_s = np.asarray(sparse.node_idle)
            assert (idle_s > -10.0).all()
            np.testing.assert_allclose(
                idle_s.sum(axis=0),
                np.asarray(dense.node_idle).sum(axis=0),
                atol=1e-2,
            )
            # Churn: placed tasks leave; the cluster keeps the DENSE
            # accounting so both paths see the same next snapshot.
            valid = valid & (a_d < 0)
            node_idle = np.asarray(dense.node_idle).copy()
            if not valid.any():
                break

    def test_exhaustion_refill_places_like_dense(self):
        """K=2 slabs on a capacity-tight cluster: slab exhaustion must
        route through refill (never false job breaks) and land the same
        placement count as dense."""
        for seed in range(4):
            rng = np.random.RandomState(seed)
            T, N = 60, 12
            task_req = np.c_[
                rng.choice([250, 500, 1000], T),
                rng.choice([256, 512], T),
            ].astype(np.float32)
            node_idle = np.c_[
                np.full(N, 4000.0), np.full(N, 1e7)
            ].astype(np.float32)
            kw = solver_kw(task_req, node_idle)
            cs = select_for(task_req, node_idle, k=2)
            assert cs.stats["truncated_classes"] > 0
            dense = solve(make_inputs(**kw))
            sparse = solve_sparse(sparse_inputs(kw, cs), tail_bucket=8)
            assert int(sparse.refills) > 0
            assert (
                (np.asarray(sparse.assigned) >= 0).sum()
                == (np.asarray(dense.assigned) >= 0).sum()
            )

    def test_complete_slab_exhaustion_breaks_job_like_dense(self):
        # Job 0: task 0 fits nowhere (too big) -> job break must also
        # gate task 1 (its job-mate); job 1 places. Identical on both
        # paths, including with a COMPLETE slab (cand_total <= K).
        task_req = np.asarray(
            [[50000.0, 256.0], [100.0, 256.0],
             [100.0, 256.0], [100.0, 256.0]],
            np.float32,
        )
        node_idle = np.asarray([[4000.0, 1e7], [4000.0, 1e7]], np.float32)
        kw = solver_kw(task_req, node_idle, jobs_of=2)
        cs = select_for(task_req, node_idle, k=2)
        dense = solve(make_inputs(**kw))
        sparse = solve_sparse(sparse_inputs(kw, cs), tail_bucket=4)
        np.testing.assert_array_equal(
            np.asarray(dense.assigned), np.asarray(sparse.assigned)
        )
        assert int(np.asarray(sparse.assigned)[1]) == -1  # job-broken


class TestSparseActionEndToEnd:
    def _build(self, action, solver, monkeypatch):
        monkeypatch.setenv("KBT_SOLVER", solver)
        c = make_cache()
        c.add_queue(build_queue("default"))
        for j in range(8):
            c.add_node(build_node(
                f"n{j}", build_resource_list(cpu="4", memory="8Gi")
            ))
        for g in range(4):
            c.add_pod_group(build_pod_group(
                f"pg{g}", namespace="ns", min_member=1
            ))
            for i in range(6):
                c.add_pod(build_pod(
                    "ns", f"pg{g}-p{i}", "", PodPhase.PENDING, req(),
                    group_name=f"pg{g}",
                ))
        run_action(c, action)
        assert c.wait_for_side_effects()
        return c

    @pytest.mark.parametrize("solver", ["jax", "native"])
    def test_sparse_cycle_binds_and_reports(self, solver, monkeypatch):
        from kube_batch_tpu.actions import allocate_tpu as atpu
        from kube_batch_tpu.metrics import metrics as m

        if solver == "native":
            from kube_batch_tpu.native import native_available

            if not native_available():
                pytest.skip("no native toolchain")
        monkeypatch.setenv("KBT_SOLVER_TOPK", "4")
        before = m.solver_sparse_solves.get()
        c = self._build("allocate_tpu", solver, monkeypatch)
        stats = dict(atpu.last_stats)
        assert len(c.binder.binds) == 24
        assert stats.get("sparse_engaged") is True
        assert stats.get("sparse_k") == 4
        assert m.solver_sparse_solves.get() == before + 1

    def test_dense_policy_small_cluster_no_sparse(self, monkeypatch):
        from kube_batch_tpu.actions import allocate_tpu as atpu

        monkeypatch.delenv("KBT_SOLVER_TOPK", raising=False)
        c = self._build("allocate_tpu", "jax", monkeypatch)
        stats = dict(atpu.last_stats)
        assert len(c.binder.binds) == 24
        assert stats.get("sparse_engaged") is False
        assert stats.get("sparse_fallback_reason") == "small-problem"


class TestSparseRetraceGuard:
    """Zero new jit compilations across steady/delta SPARSE cycles —
    the sparse twin of tests/solver/test_retrace_guard.py: candidate
    axes (class pow2 buckets, fixed K, task-bucketed task_cand) must
    stay inside their shape buckets under churn."""

    def test_zero_new_compilations_sparse_cycles(self, monkeypatch):
        from tests.solver.test_retrace_guard import one_cycle
        from tests.unit.test_cycle_pipeline import build_cluster

        monkeypatch.setenv("KBT_SOLVER_TOPK", "8")
        monkeypatch.setenv("KBT_SOLVER", "jax")
        c = build_cluster(seed=47, groups=6, per_group=40, nodes=8)
        tiers = make_tiers(*DEFAULT_TIERS_ARGS)
        for _ in range(3):
            one_cycle(c, tiers, churn=2)
        warm = jit_compilation_count()
        assert warm > 0
        for cycle in range(6):
            one_cycle(c, tiers, churn=2)
            now = jit_compilation_count()
            assert now == warm, (
                f"sparse cycle {cycle} minted {now - warm} new jit "
                "compilation(s)"
            )
        c.shutdown()


class TestSparseDeviceCache:
    def test_slab_fields_patch_and_reuse(self, monkeypatch):
        """Candidate slabs ride the device-resident snapshot cache like
        every other field: steady cycles reuse (zero slab bytes), churn
        patches/re-uploads, and the pack reports slab_bytes_shipped."""
        from kube_batch_tpu.solver.device_cache import last_pack_stats
        from tests.unit.test_cycle_pipeline import build_cluster

        monkeypatch.setenv("KBT_SOLVER_TOPK", "8")
        c = build_cluster(seed=51, groups=6, per_group=40, nodes=8)
        tiers = make_tiers(*DEFAULT_TIERS_ARGS)

        ssn = open_session(c, tiers)
        inputs, _ = tensorize(ssn)
        assert inputs is not None
        assert int(inputs.cand_idx.shape[0]) > 0
        stats = dict(last_pack_stats)
        assert stats["field_outcomes"]["cand_idx"] == "upload"  # cold
        assert stats["slab_bytes_shipped"] > 0
        close_session(ssn)

        ssn = open_session(c, tiers)
        inputs2, _ = tensorize(ssn)
        stats2 = dict(last_pack_stats)
        # Nothing changed: every cand field reuses its resident buffer.
        for f in ("cand_idx", "cand_static", "cand_info"):
            assert stats2["field_outcomes"][f] == "reuse", (f, stats2)
        assert stats2["slab_bytes_shipped"] == 0
        # And the solver consumes the resident slabs bit-exactly.
        result = solve_jit(inputs2)
        assert result.refills is not None
        close_session(ssn)
        c.shutdown()


class TestNativeSparse:
    """Native sparse loop parity (greedy_allocate_sparse vs the masked
    loop) — placement counts and capacity on randomized instances,
    including forced exhaustion/widen rounds."""

    @pytest.fixture(autouse=True)
    def _need_native(self):
        from kube_batch_tpu.native import native_available

        if not native_available():
            pytest.skip("no native toolchain")

    def _np_inputs(self, task_req, node_idle, cs=None, jobs_of=10):
        from kube_batch_tpu.solver.kernels import SolverInputs

        T, R = task_req.shape
        N = node_idle.shape[0]
        kw = dict(
            task_req=task_req, task_fit=task_req,
            task_rank=np.arange(T, dtype=np.int32),
            task_job=(np.arange(T) // jobs_of).astype(np.int32),
            task_queue=np.zeros(T, np.int32),
            task_valid=np.ones(T, bool),
            task_group=np.zeros(T, np.int32),
            node_feas=np.ones(N, bool),
            group_feas=np.ones((1, N), bool),
            pair_idx=np.zeros((0,), np.int32),
            pair_feas=np.zeros((0, N), bool),
            score_idx=np.zeros((0,), np.int32),
            score_rows=np.zeros((0, N), np.float32),
            node_idle=node_idle, node_releasing=np.zeros_like(node_idle),
            node_cap=node_idle, node_task_count=np.zeros(N, np.int32),
            node_max_tasks=np.zeros(N, np.int32),
            queue_deserved=np.full((1, R), np.inf, np.float32),
            queue_allocated=np.zeros((1, R), np.float32),
            eps=np.array([10.0, 10.0], np.float32),
            lr_weight=np.float32(1.0), br_weight=np.float32(1.0),
        )
        if cs is not None:
            kw.update(
                task_cand=cs.task_cand, cand_idx=cs.cand_idx,
                cand_static=cs.cand_static, cand_info=cs.cand_info,
            )
        return SolverInputs(**kw)

    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_sparse_matches_masked_counts(self, seed):
        from kube_batch_tpu.native import last_solve_stats, solve_native

        task_req, node_idle = random_case(seed, T=120, N=20)
        cs = select_for(task_req, node_idle, k=4)
        a_m, p_m = solve_native(self._np_inputs(task_req, node_idle))
        assert last_solve_stats["sparse"] is False
        a_s, p_s = solve_native(
            self._np_inputs(task_req, node_idle, cs)
        )
        assert last_solve_stats["sparse"] is True
        assert p_s == p_m
        # Capacity respected under the sparse assignment.
        used = np.zeros_like(node_idle)
        for t, n in enumerate(a_s):
            if n >= 0:
                used[n] += task_req[t]
        assert (used <= node_idle + 10.0).all()

    def test_cap_saturation_breaks_job_like_masked(self):
        """Pod-count caps saturating MID-SOLVE must break a job exactly
        like the masked loop: snapshot-time feasibility said the class
        had open nodes, but by the time its task arrives every feasible
        node is cap-saturated — the job-mate in another class must NOT
        place (regression: the sparse loop used to consult only the
        snapshot-time census and placed the mate)."""
        from kube_batch_tpu.native import solve_native
        from kube_batch_tpu.solver.kernels import SolverInputs

        N = 3
        # t0/t1: filler singleton jobs that saturate nodes 0/1 (cap 1
        # task each). t2 (job 2, group 0): feasible only on 0/1 — by
        # its turn both are capped. t3 (job 2, group 1): node 2 is free
        # and feasible, but the job is broken by t2.
        task_req = np.asarray(
            [[100.0, 64.0], [100.0, 64.0],
             [200.0, 64.0], [300.0, 64.0]],
            np.float32,
        )
        task_group = np.asarray([0, 0, 0, 1], np.int32)
        group_feas = np.asarray(
            [[True, True, False], [True, True, True]]
        )
        node_idle = np.asarray(
            [[4000.0, 1e6], [4000.0, 1e6], [4000.0, 1e6]], np.float32
        )
        kw = dict(
            task_req=task_req, task_fit=task_req,
            task_rank=np.arange(4, dtype=np.int32),
            task_job=np.asarray([0, 1, 2, 2], np.int32),
            task_queue=np.zeros(4, np.int32),
            task_valid=np.ones(4, bool),
            task_group=task_group,
            node_feas=np.ones(N, bool),
            group_feas=group_feas,
            pair_idx=np.zeros((0,), np.int32),
            pair_feas=np.zeros((0, N), bool),
            score_idx=np.zeros((0,), np.int32),
            score_rows=np.zeros((0, N), np.float32),
            node_idle=node_idle,
            node_releasing=np.zeros_like(node_idle),
            node_cap=node_idle,
            node_task_count=np.zeros(N, np.int32),
            node_max_tasks=np.asarray([1, 1, 0], np.int32),
            queue_deserved=np.full((1, 2), np.inf, np.float32),
            queue_allocated=np.zeros((1, 2), np.float32),
            eps=np.array([10.0, 10.0], np.float32),
            lr_weight=np.float32(1.0), br_weight=np.float32(1.0),
        )
        mask = CombinedMask(
            node_ok=np.ones(N, bool), task_group=task_group,
            group_rows=group_feas, pair_idx=np.zeros((0,), np.int32),
            pair_rows=np.zeros((0, N), bool),
        )
        cs = select_candidates(
            mask, {}, task_req, task_req, node_idle, node_idle,
            np.zeros_like(node_idle), np.zeros(N, np.int32),
            np.asarray([1, 1, 0], np.int32),
            np.array([10.0, 10.0], np.float32), 1.0, 1.0, 4,
        )
        a_m, p_m = solve_native(SolverInputs(**kw))
        a_s, p_s = solve_native(SolverInputs(
            **kw, task_cand=cs.task_cand, cand_idx=cs.cand_idx,
            cand_static=cs.cand_static, cand_info=cs.cand_info,
        ))
        np.testing.assert_array_equal(a_s, a_m)
        assert a_s[3] == -1  # job broken by t2's cap-saturated class
        assert p_s == p_m == 2
        # The jax sparse/dense pair must agree WITH EACH OTHER (caps
        # re-checked against current state inside the rounds on both
        # paths). Note they legitimately differ from the sequential
        # loops here: in batched round 1 t3 wins node 2 BEFORE t2's cap
        # exhaustion materializes in round 2, and a job break cannot
        # retroactively unplace a same-or-earlier-round accept (the
        # documented batched-vs-sequential divergence). The parity
        # contract is sparse == dense per backend, not jax == native.
        kwj = {
            k: jnp.asarray(v) if isinstance(v, np.ndarray) else v
            for k, v in kw.items()
        }
        dense = solve(make_inputs(**kwj))
        sparse = solve_sparse(make_inputs(
            **kwj, task_cand=jnp.asarray(cs.task_cand),
            cand_idx=jnp.asarray(cs.cand_idx),
            cand_static=jnp.asarray(cs.cand_static),
            cand_info=jnp.asarray(cs.cand_info),
        ), tail_bucket=4)
        np.testing.assert_array_equal(
            np.asarray(dense.assigned), np.asarray(sparse.assigned)
        )

    def test_exhaustion_widens_and_still_places(self):
        from kube_batch_tpu.native import last_solve_stats, solve_native

        rng = np.random.RandomState(7)
        T, N = 200, 24
        task_req = np.c_[
            rng.choice([250, 500, 1000], T), rng.choice([256, 512], T)
        ].astype(np.float32)
        node_idle = np.c_[
            np.full(N, 6000.0), np.full(N, 1e7)
        ].astype(np.float32)
        cs = select_for(task_req, node_idle, k=2)
        a_m, p_m = solve_native(self._np_inputs(task_req, node_idle))
        a_s, p_s = solve_native(
            self._np_inputs(task_req, node_idle, cs)
        )
        assert last_solve_stats["refill_rounds"] > 0
        assert p_s == p_m


def test_tensorize_emits_slabs_when_forced(monkeypatch):
    """tensorize builds + pads candidate slabs under KBT_SOLVER_TOPK,
    with the sentinel moved to the PADDED node count."""
    monkeypatch.setenv("KBT_SOLVER_TOPK", "4")
    c = make_cache()
    c.add_queue(build_queue("default"))
    for j in range(5):
        c.add_node(build_node(
            f"n{j}", build_resource_list(cpu="4", memory="8Gi")
        ))
    c.add_pod_group(build_pod_group("pg0", namespace="ns", min_member=1))
    for i in range(10):
        c.add_pod(build_pod(
            "ns", f"p{i}", "", PodPhase.PENDING, req(), group_name="pg0"
        ))
    ssn = open_session(c, make_tiers(*DEFAULT_TIERS_ARGS))
    inputs, ctx = tensorize(ssn)
    s = inputs.unpack()
    Np = int(s.node_idle.shape[0])
    cand = np.asarray(s.cand_idx)
    assert cand.shape[0] > 0
    assert cand.shape[1] == 4
    assert ((cand == Np) | (cand < len(ctx.nodes))).all()
    assert int(np.asarray(s.task_cand).max()) < cand.shape[0]
    close_session(ssn)
    c.shutdown()


def test_env_disabled_stays_dense(monkeypatch):
    monkeypatch.setenv("KBT_SOLVER_TOPK", "off")
    task_req, node_idle = random_case(0, T=20, N=8)
    assert not topk_config(20, 8).enabled
    # os.environ must not leak into other tests (monkeypatch handles it).
    assert os.environ["KBT_SOLVER_TOPK"] == "off"
