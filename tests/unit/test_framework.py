"""Framework unit tests: conf loading, arguments, priority queue, combinators.

Ports reference pkg/scheduler/util_test.go:27 (conf YAML),
framework/arguments_test.go:30, util/priority_queue semantics.
"""

import pytest

from kube_batch_tpu.conf import DEFAULT_SCHEDULER_CONF, parse_scheduler_conf
from kube_batch_tpu.framework import Arguments
from kube_batch_tpu.scheduler import load_scheduler_conf
from kube_batch_tpu.utils import PriorityQueue


class TestConf:
    def test_parse_default(self):
        conf = parse_scheduler_conf(DEFAULT_SCHEDULER_CONF)
        assert conf.actions == "allocate, backfill"
        assert len(conf.tiers) == 2
        assert [p.name for p in conf.tiers[0].plugins] == ["priority", "gang"]
        assert [p.name for p in conf.tiers[1].plugins] == [
            "drf", "predicates", "proportion", "nodeorder",
        ]
        # defaults: everything enabled
        assert conf.tiers[0].plugins[0].enabled_job_order is True

    def test_disabled_flags(self):
        conf = parse_scheduler_conf(
            """
actions: "allocate"
tiers:
- plugins:
  - name: gang
    jobOrderDisabled: true
    preemptableDisabled: true
"""
        )
        opt = conf.tiers[0].plugins[0]
        assert opt.enabled_job_order is False
        assert opt.enabled_preemptable is False
        assert opt.enabled_job_ready is True

    def test_arguments_passthrough(self):
        conf = parse_scheduler_conf(
            """
actions: "allocate"
tiers:
- plugins:
  - name: nodeorder
    arguments:
      leastrequested.weight: 2
"""
        )
        assert conf.tiers[0].plugins[0].arguments == {"leastrequested.weight": "2"}

    def test_unknown_action_is_hard_error(self):
        import kube_batch_tpu.actions  # noqa: F401

        with pytest.raises(ValueError):
            load_scheduler_conf('actions: "nonexistent"\ntiers: []')

    def test_load_actions(self):
        import kube_batch_tpu.actions  # noqa: F401

        actions, tiers = load_scheduler_conf(DEFAULT_SCHEDULER_CONF)
        assert [a.name() for a in actions] == ["allocate", "backfill"]


class TestArguments:
    def test_get_int(self):
        args = Arguments({"a": "5", "bad": "x"})
        assert args.get_int("a", 1) == 5
        assert args.get_int("bad", 1) == 1
        assert args.get_int("missing", 7) == 7

    def test_get_bool(self):
        args = Arguments({"t": "true", "f": "false", "bad": "maybe"})
        assert args.get_bool("t") is True
        assert args.get_bool("f") is False
        assert args.get_bool("bad", True) is True


class TestPriorityQueue:
    def test_orders_by_less_fn(self):
        q = PriorityQueue(lambda a, b: a < b)
        for x in (5, 1, 3):
            q.push(x)
        assert [q.pop(), q.pop(), q.pop()] == [1, 3, 5]

    def test_stable_on_ties(self):
        q = PriorityQueue(lambda a, b: a[0] < b[0])
        q.push((1, "first"))
        q.push((1, "second"))
        assert q.pop()[1] == "first"

    def test_pop_empty_returns_none(self):
        assert PriorityQueue(lambda a, b: a < b).pop() is None
