from .api import ADDED, DELETED, MODIFIED, ClusterAPI, InProcessCluster
from .errors import (
    ClusterAPIError,
    ClusterUnavailableError,
    ObjectGoneError,
    TerminalClusterError,
    TransientClusterError,
    retry_transient,
)

__all__ = [
    "ADDED", "DELETED", "MODIFIED", "ClusterAPI", "InProcessCluster",
    "KubeCluster", "KubeConfig",
    "ClusterAPIError", "TransientClusterError", "ClusterUnavailableError",
    "TerminalClusterError", "ObjectGoneError", "retry_transient",
]


def __getattr__(name):
    # Lazy: the real-cluster adapter pulls in yaml/ssl; embedders of the
    # decision core alone must not pay that import (PEP 562).
    if name in ("KubeCluster", "KubeConfig"):
        from . import kube

        return getattr(kube, name)
    raise AttributeError(name)
