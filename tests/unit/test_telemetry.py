"""Long-horizon telemetry: rollup math, sketch bounds, ring wraparound,
the scheduler feed, and the HTTP surface (doc/design/observability.md
§4)."""

import json
import math
import random
import urllib.request

from kube_batch_tpu import metrics
from kube_batch_tpu.obs.telemetry import (
    TELEMETRY,
    QuantileSketch,
    Telemetry,
    collect_fairness,
    collect_watermarks,
)


# -- quantile sketch ---------------------------------------------------------

def test_sketch_relative_error_bound():
    """The DDSketch contract: any quantile estimate is within alpha
    relative error of the true order statistic."""
    rng = random.Random(7)
    sketch = QuantileSketch(alpha=0.05)
    values = [rng.uniform(0.01, 500.0) for _ in range(20_000)]
    for v in values:
        sketch.add(v)
    values.sort()
    for q in (0.01, 0.25, 0.5, 0.9, 0.99, 0.999):
        true = values[int(q * (len(values) - 1))]
        est = sketch.quantile(q)
        assert abs(est - true) / true <= 0.0501, (q, est, true)


def test_sketch_wide_dynamic_range():
    """Log buckets keep the bound across 9 orders of magnitude (bytes
    watermarks vs ms phases share the implementation)."""
    rng = random.Random(3)
    sketch = QuantileSketch(alpha=0.05)
    values = [10 ** rng.uniform(-3, 9) for _ in range(5_000)]
    for v in values:
        sketch.add(v)
    values.sort()
    for q in (0.1, 0.5, 0.95):
        true = values[int(q * (len(values) - 1))]
        assert abs(sketch.quantile(q) - true) / true <= 0.0501


def test_sketch_zero_and_negative():
    """Non-positive values (idle phase ms, signed drift) are tracked
    exactly at their min, not log-bucketed into garbage."""
    sketch = QuantileSketch()
    for v in (-0.5, 0.0, 0.0):
        sketch.add(v)
    sketch.add(10.0)
    assert sketch.count == 4
    assert sketch.quantile(0.0) == -0.5
    assert abs(sketch.quantile(1.0) - 10.0) / 10.0 <= 0.051


def test_sketch_empty_and_single():
    sketch = QuantileSketch()
    assert sketch.quantile(0.5) == 0.0
    sketch.add(42.0)
    assert abs(sketch.quantile(0.5) - 42.0) / 42.0 <= 0.051


def test_sketch_bucket_collapse_bounded():
    """Past max_buckets the lowest buckets merge; memory stays bounded
    and the tail keeps its error bound."""
    sketch = QuantileSketch(alpha=0.05, max_buckets=32)
    rng = random.Random(1)
    values = [10 ** rng.uniform(-6, 6) for _ in range(3_000)]
    for v in values:
        sketch.add(v)
    assert len(sketch.buckets) <= 32
    values.sort()
    true99 = values[int(0.99 * (len(values) - 1))]
    assert abs(sketch.quantile(0.99) - true99) / true99 <= 0.0501


# -- window rollup -----------------------------------------------------------

def test_window_boundaries_and_stats():
    t = Telemetry(window_cycles=4, max_windows=16, raw_capacity=32)
    for c in range(10):
        t.observe_values({"x": float(c)}, cycle=c)
    ws = t.windows()
    assert len(ws) == 2 and t.windows_rolled == 2
    w0, w1 = ws
    assert (w0["start_cycle"], w0["end_cycle"], w0["cycles"]) == (0, 3, 4)
    assert (w1["start_cycle"], w1["end_cycle"], w1["cycles"]) == (4, 7, 4)
    k = w0["keys"]["x"]
    assert k["count"] == 4 and k["min"] == 0.0 and k["max"] == 3.0
    assert k["sum"] == 6.0 and k["mean"] == 1.5
    # Cycles 8, 9 sit in the open window.
    assert t.cycles_observed == 10
    assert "x" in t.snapshot()["open_window_keys"]


def test_window_ring_wraparound_counts_drops():
    t = Telemetry(window_cycles=2, max_windows=4, raw_capacity=8)
    for c in range(20):
        t.observe_values({"x": 1.0}, cycle=c)
    t.flush()  # rolls are deferred one sample; close the final window
    assert t.windows_rolled == 10
    assert len(t.windows()) == 4
    assert t.windows_dropped == 6
    # Oldest surviving window reflects the drop.
    assert t.windows()[0]["start_cycle"] == 12
    # Raw ring keeps only the newest raw_capacity samples.
    raw = t.raw()
    assert len(raw) == 8 and raw[0]["cycle"] == 12


def test_sparse_keys_roll_independently():
    """A key absent from some cycles still rolls with its own count."""
    t = Telemetry(window_cycles=4, max_windows=8)
    for c in range(4):
        values = {"always": 1.0}
        if c % 2 == 0:
            values["sometimes"] = float(c)
        t.observe_values(values, cycle=c)
    t.flush()
    w = t.windows()[0]["keys"]
    assert w["always"]["count"] == 4
    assert w["sometimes"]["count"] == 2


def test_annotate_cycle_merges_into_open_window():
    t = Telemetry(window_cycles=2, max_windows=8)
    t.observe_values({"x": 1.0}, cycle=0)
    t.annotate_cycle({"extra": 5.0})
    t.observe_values({"x": 2.0}, cycle=1)
    # Cycle 1 fills the window, but its post-cycle annotation must
    # still land in it — the roll is deferred to the next sample.
    t.annotate_cycle({"boundary": 7.0})
    t.observe_values({"x": 3.0}, cycle=2)
    w = t.windows()[0]["keys"]
    assert w["extra"]["count"] == 1 and w["extra"]["max"] == 5.0
    assert w["boundary"]["count"] == 1 and w["boundary"]["max"] == 7.0
    assert t.raw()[0]["extra"] == 5.0


def test_flush_keeps_final_boundary_annotations():
    """Run length a multiple of the window size: the final cycle's
    annotations sit past the full window and must still be flushed to
    the detectors, not dropped."""
    t = Telemetry(window_cycles=2, max_windows=8)
    for c in range(4):
        t.observe_values({"x": 1.0}, cycle=c)
    t.annotate_cycle({"violation": 1.0})
    t.flush()
    ws = t.windows()
    assert len(ws) == 2
    assert ws[1]["keys"]["violation"]["count"] == 1


def test_annotation_only_window_has_numeric_start():
    """A window that only ever saw annotate_cycle content (every cycle
    in it errored before the observe feed) still rolls with a numeric
    start_cycle — detector midpoint arithmetic must never meet None."""
    t = Telemetry(window_cycles=2, max_windows=8)
    t.annotate_cycle({"sim_cycle_errors": 1.0})
    t.flush()
    ws = t.windows()
    assert len(ws) == 1
    assert isinstance(ws[0]["start_cycle"], int)
    assert ws[0]["keys"]["sim_cycle_errors"]["count"] == 1


def test_flush_closes_tail_window():
    t = Telemetry(window_cycles=100, max_windows=8)
    for c in range(5):
        t.observe_values({"x": float(c)}, cycle=c)
    assert not t.windows()
    t.flush()
    ws = t.windows()
    assert len(ws) == 1 and ws[0]["cycles"] == 5
    assert ws[0]["end_cycle"] == 4


# -- probes ------------------------------------------------------------------

def test_watermarks_present_and_numeric():
    values = collect_watermarks()
    for key in ("alloc_blocks", "tracer_ring", "flight_ring",
                "metrics_series", "explain_verdicts"):
        assert key in values, key
    assert all(
        isinstance(v, float) and not math.isnan(v)
        for v in values.values()
    )
    assert values["alloc_blocks"] > 0


def test_fairness_probe_two_queues():
    from kube_batch_tpu.api import PodPhase, build_resource_list
    from kube_batch_tpu.cache import SchedulerCache
    from kube_batch_tpu.utils.test_utils import (
        build_node,
        build_pod,
        build_pod_group,
        build_queue,
    )

    cache = SchedulerCache()
    cache.add_queue(build_queue("q0", weight=1))
    cache.add_queue(build_queue("q1", weight=2))
    for i in range(2):
        cache.add_node(build_node(
            f"n{i}", build_resource_list(cpu="8", memory="16Gi", pods=110)
        ))
    cache.add_pod_group(build_pod_group(
        "pg0", namespace="t", min_member=1, queue="q0"
    ))
    cache.add_pod(build_pod(
        "t", "p0", "n0", PodPhase.RUNNING,
        build_resource_list(cpu="2", memory="1Gi"), group_name="pg0",
    ))
    state = {}
    drift = collect_fairness(cache, state)
    assert set(drift) == {"fairness_drift:q0", "fairness_drift:q1"}
    # q0 holds 2 of 16 CPU, weight 1 of 3 -> under its ~5.3 CPU
    # water-filled share; q1 holds nothing. Under-service = negative
    # drift (benign: the soak detector bounds the POSITIVE side).
    assert drift["fairness_drift:q0"] <= 0.0
    assert drift["fairness_drift:q1"] <= 0.0
    # Node-total memo primed.
    assert state["n_nodes"] == 2

    # Over-serve q0 past its deserved share: 12 of 16 CPU against a
    # ~5.3 CPU share -> clearly positive drift.
    for i in range(1, 6):
        cache.add_pod(build_pod(
            "t", f"p{i}", f"n{i % 2}", PodPhase.RUNNING,
            build_resource_list(cpu="2", memory="1Gi"),
            group_name="pg0",
        ))
    drift = collect_fairness(cache, state)
    assert drift["fairness_drift:q0"] > 0.2, drift
    cache.shutdown()


def test_fairness_single_queue_skipped():
    from kube_batch_tpu.cache import SchedulerCache
    from kube_batch_tpu.utils.test_utils import build_queue

    cache = SchedulerCache()
    cache.add_queue(build_queue("only", weight=1))
    assert collect_fairness(cache, {}) == {}
    cache.shutdown()


# -- the scheduler feed ------------------------------------------------------

def test_observe_scheduler_cycle_extracts_record_and_updates_gauges():
    t = Telemetry(window_cycles=4, max_windows=8)
    rec = {
        "e2e_ms": 12.5,
        "phases_ms": {"open_session": 1.5, "action:allocate_tpu": 9.0},
        "solver": {"placed": 10, "tasks": 12, "rounds": 2},
    }
    values = t.observe_scheduler_cycle(rec)
    assert values["e2e_ms"] == 12.5
    assert values["phase_ms:open_session"] == 1.5
    assert values["solver:placed"] == 10.0
    assert "alloc_blocks" in values
    from kube_batch_tpu.metrics.metrics import (
        process_rss_bytes,
        telemetry_ring_occupancy,
    )

    assert telemetry_ring_occupancy.get() >= 1.0
    if "rss_bytes" in values:
        assert process_rss_bytes.get() == values["rss_bytes"]


def test_scheduler_run_once_feeds_global_telemetry():
    """The production wiring: one run_once = one telemetry cycle."""
    from kube_batch_tpu.cache import SchedulerCache
    from kube_batch_tpu.scheduler import Scheduler
    from kube_batch_tpu.utils.test_utils import build_queue

    TELEMETRY.configure(window_cycles=2, max_windows=8, raw_capacity=16)
    cache = SchedulerCache()
    cache.add_queue(build_queue("default", weight=1))
    sched = Scheduler(cache, schedule_period=0.01)
    before = TELEMETRY.cycles_observed
    assert sched.run_once_guarded()
    assert sched.run_once_guarded()
    assert TELEMETRY.cycles_observed == before + 2
    assert "e2e_ms" in TELEMETRY.raw()[-1]
    # The heap-proportional probes run on the every-64th "expensive"
    # cadence — cycle 0 carries them, cycle 1 does not.
    assert "alloc_blocks" in TELEMETRY.raw()[0]
    assert "alloc_blocks" not in TELEMETRY.raw()[-1]
    cache.shutdown()
    TELEMETRY.reset()


def test_telemetry_env_kill_switch(monkeypatch):
    from kube_batch_tpu.cache import SchedulerCache
    from kube_batch_tpu.scheduler import Scheduler

    monkeypatch.setenv("KBT_TELEMETRY", "0")
    TELEMETRY.configure(window_cycles=2, max_windows=8)
    cache = SchedulerCache()
    sched = Scheduler(cache, schedule_period=0.01)
    assert sched.run_once_guarded()
    assert TELEMETRY.cycles_observed == 0
    cache.shutdown()
    TELEMETRY.reset()


# -- flight dump + HTTP surface ----------------------------------------------

def test_flight_dump_embeds_telemetry():
    from kube_batch_tpu.obs import RECORDER

    TELEMETRY.configure(window_cycles=2, max_windows=8)
    for c in range(4):
        TELEMETRY.observe_values({"x": float(c)}, cycle=c)
    TELEMETRY.flush()
    dump = RECORDER.dump(reason="test")
    telem = dump["telemetry"]
    assert telem["cycles_observed"] == 4
    assert len(telem["windows"]) == 2
    json.dumps(dump, sort_keys=True)  # canonical-JSON safe
    TELEMETRY.reset()


def test_debug_timeseries_and_vars_endpoints():
    from kube_batch_tpu.cli.server import start_metrics_server

    TELEMETRY.configure(window_cycles=2, max_windows=8)
    TELEMETRY.observe_values({"e2e_ms": 5.0}, cycle=0)
    TELEMETRY.observe_values({"e2e_ms": 7.0}, cycle=1)
    TELEMETRY.flush()
    server, _thread = start_metrics_server("127.0.0.1:0")
    port = server.server_address[1]
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/debug/timeseries"
        ) as resp:
            ts = json.loads(resp.read())
        assert ts["cycles_observed"] == 2
        assert ts["windows"][0]["keys"]["e2e_ms"]["count"] == 2
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/debug/vars"
        ) as resp:
            dv = json.loads(resp.read())
        assert dv["telemetry"]["cycles_observed"] == 2
        assert "alloc_blocks" in dv["watermarks"]
    finally:
        server.shutdown()
        TELEMETRY.reset()


def test_ms_buckets_resolution():
    """The cycle-shaped histograms carry ms-scale buckets: a 50 ms and
    a 150 ms cycle must land in different buckets (with DefBuckets both
    straddled the same 0.1/0.25 span as everything else)."""
    from bisect import bisect_left

    from kube_batch_tpu.metrics.metrics import (
        action_scheduling_latency,
        e2e_scheduling_latency,
    )

    h = e2e_scheduling_latency
    in_range = [b for b in h.buckets if 0.005 <= b <= 0.5]
    assert len(in_range) >= 10, h.buckets
    assert bisect_left(h.buckets, 0.05) != bisect_left(h.buckets, 0.15)
    assert action_scheduling_latency.buckets == h.buckets
