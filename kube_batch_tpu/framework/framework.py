"""Session lifecycle: OpenSession / CloseSession.

Mirrors reference framework/framework.go (:30 OpenSession builds plugins from
tiers and runs OnSessionOpen with per-plugin timing; :55 CloseSession).

Divergence (intended-behavior fix): the reference runs its JobValid filter
inside openSession BEFORE tiers/plugins are installed (framework.go:31-32 vs
session.go:89-108), so gang's JobValidFn can never fire there — dead code.
Here validation runs after OnSessionOpen, so invalid gangs are dropped with
an Unschedulable condition as intended.
"""

from __future__ import annotations

import logging
import time
from typing import List

from .. import metrics
from ..conf import Tier
from ..obs import span
from .arguments import Arguments
from .plugins import get_plugin_builder
from .session import Session

logger = logging.getLogger(__name__)


def open_session(cache, tiers: List[Tier], micro: bool = False) -> Session:
    ssn = Session(cache, tiers, micro=micro)
    ssn._open()

    for tier in tiers:
        for opt in tier.plugins:
            builder = get_plugin_builder(opt.name)
            if builder is None:
                logger.error("Failed to get plugin %s.", opt.name)
                continue
            plugin = builder(Arguments(opt.arguments))
            ssn.plugins[plugin.name()] = plugin

    with span("plugins_open"):
        for plugin in ssn.plugins.values():
            start = time.perf_counter()
            plugin.on_session_open(ssn)
            metrics.update_plugin_duration(
                plugin.name(), "OnSessionOpen", time.perf_counter() - start
            )

    ssn._validate_jobs()
    return ssn


def close_session(ssn: Session) -> None:
    # Drain guard: an overlapped allocate_tpu solve still in flight must
    # complete before the session's world view is torn down under it.
    ssn.drain_inflight_solve()
    # Close runs under the GC guard like the action body: plugin
    # OnSessionClose plus the status write-back allocate ~O(#jobs)
    # short-lived objects, and a generational collection landing inside
    # them showed up as close-time jitter (close_ms 2.1 -> 17.7 ms
    # between r5 runs). Nested guards are no-ops, so callers that
    # already hold one (scheduler.run_once, bench) are unchanged;
    # standalone callers get the deferral + bounded exit collection.
    from ..utils import deferred_gc

    with deferred_gc():
        with span("plugins_close"):
            for plugin in ssn.plugins.values():
                start = time.perf_counter()
                plugin.on_session_close(ssn)
                metrics.update_plugin_duration(
                    plugin.name(), "OnSessionClose",
                    time.perf_counter() - start,
                )
        ssn._close()
