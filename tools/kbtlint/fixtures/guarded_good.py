"""Known-good guarded-by fixture: every access of ``state`` holds the
lock — including through a private ``_locked`` helper whose call sites
all hold it (the entry-held fixed point), and construction writes in
``__init__`` (exempt)."""

import threading


class Breaker:
    def __init__(self):
        self._lock = threading.Lock()
        self.state = "closed"
        self.config = "static"  # never written post-init: no guard

    def open(self):
        with self._lock:
            self._set("open")

    def close(self):
        with self._lock:
            self._set("closed")

    def half_open(self):
        with self._lock:
            self._set("half-open")

    def read(self):
        with self._lock:
            return self.state

    def describe(self):
        return self.config  # unguarded read of an immutable attr: fine

    def _set(self, state):
        # Lock held by every caller (inferred, not declared).
        self.state = state
