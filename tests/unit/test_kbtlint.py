"""kbtlint (tools/kbtlint): fixture snippets per pass (known-bad →
finding, known-good → clean), the allowlist roundtrip, the PR 7
fence/mutex regression fixture, the censuses against the live tree,
and the regression coverage for the bring-up fixes the passes surfaced
(doc/design/static-analysis.md)."""

import json
import os
import subprocess
import sys

import pytest

from tools.kbtlint import (
    census,
    core,
    dirty_ledger,
    guarded_by,
    jit_hygiene,
    lock_order,
    replay_det,
    shape_contracts,
)
from tools.kbtlint.selftest import run_selftest

REPO = core.REPO
FIXTURES = os.path.join(REPO, "tools", "kbtlint", "fixtures")


def fixture_project(name):
    with open(os.path.join(FIXTURES, name)) as f:
        return core.load_snippet(f.read(), rel=f"fixtures/{name}")


# -- lock-order --------------------------------------------------------------


class TestLockOrder:
    def test_cycle_detected(self):
        findings = lock_order.run(fixture_project("lock_cycle_bad.py"))
        assert any("lock-order cycle" in f.message for f in findings)
        # Both contributing edges are named.
        assert sum("cycle" in f.message for f in findings) >= 2

    def test_pr7_fence_mutex_shape(self):
        """The regression fixture reproduces PR 7's deadlock through a
        helper call — the pass must see it via the call graph, not just
        textual nesting."""
        findings = lock_order.run(fixture_project("fence_mutex_bad.py"))
        assert any("leaf-lock violation" in f.message for f in findings)
        assert any("_fence_lock" in f.message for f in findings)

    def test_blocking_under_mutex(self):
        findings = lock_order.run(fixture_project("mutex_blocking_bad.py"))
        assert any("blocking call" in f.message for f in findings)
        assert any("join()" in f.message for f in findings)

    def test_known_good_clean(self):
        assert lock_order.run(fixture_project("lock_good.py")) == []

    def test_string_join_not_flagged(self):
        project = core.load_snippet(
            "import threading\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self.mutex = threading.RLock()\n"
            "    def fmt(self, parts):\n"
            "        with self.mutex:\n"
            "            return ', '.join(parts)\n"
        )
        assert lock_order.run(project) == []

    def test_self_deadlock_on_plain_lock(self):
        project = core.load_snippet(
            "import threading\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self.l = threading.Lock()\n"
            "    def boom(self):\n"
            "        with self.l:\n"
            "            with self.l:\n"
            "                pass\n"
        )
        findings = lock_order.run(project)
        assert any("self-deadlock" in f.message for f in findings)

    def test_real_tree_has_no_unallowlisted_findings(self):
        project = core.load_project()
        findings = lock_order.run(project)
        entries = core.load_allowlist()
        kept, _, _ = core.apply_allowlist(findings, entries)
        assert kept == [], [f.render() for f in kept]


# -- dirty-ledger ------------------------------------------------------------


class TestDirtyLedger:
    def test_unstamped_mutation_flagged(self):
        findings = dirty_ledger.run(fixture_project("ledger_bad.py"))
        assert any("unstamped allocation" in f.message for f in findings)

    def test_transitive_stamp_accepted(self):
        assert dirty_ledger.run(fixture_project("ledger_good.py")) == []

    def test_cache_package_clean(self):
        project = core.load_project()
        findings = dirty_ledger.run(project)
        entries = core.load_allowlist()
        kept, _, _ = core.apply_allowlist(findings, entries)
        assert kept == [], [f.render() for f in kept]


# -- jit-hygiene -------------------------------------------------------------


class TestJitHygiene:
    def test_known_bad(self):
        findings = jit_hygiene.run(fixture_project("jit_bad.py"))
        messages = [f.message for f in findings]
        assert any("branch on a traced value" in m for m in messages)
        assert any("host sync" in m for m in messages)
        assert any("donated-buffer reuse" in m for m in messages)

    def test_known_good(self):
        assert jit_hygiene.run(fixture_project("jit_good.py")) == []

    def test_shape_branch_untainted(self):
        project = core.load_snippet(
            "import jax\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    if x.shape[0] > 2:\n"
            "        return x\n"
            "    return x * 2\n"
        )
        assert jit_hygiene.run(project) == []

    def test_solver_package_clean(self):
        project = core.load_project()
        findings = jit_hygiene.run(project)
        entries = core.load_allowlist()
        kept, _, _ = core.apply_allowlist(findings, entries)
        assert kept == [], [f.render() for f in kept]


# -- guarded-by --------------------------------------------------------------


class TestGuardedBy:
    def test_unguarded_write_flagged(self):
        findings = guarded_by.run(fixture_project("guarded_bad.py"))
        assert any("guarded-by violation" in f.message for f in findings)
        assert any("racy_reset" in f.message for f in findings)

    def test_locked_helper_inference_accepted(self):
        """_set() never takes the lock itself — every call site holds
        it, and the entry-held fixed point must see that."""
        assert guarded_by.run(fixture_project("guarded_good.py")) == []

    def test_init_writes_exempt(self):
        project = core.load_snippet(
            "import threading\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self.x = 0\n"  # pre-publication: exempt
            "    def a(self):\n"
            "        with self._lock:\n"
            "            self.x = 1\n"
            "    def b(self):\n"
            "        with self._lock:\n"
            "            self.x = 2\n"
            "    def c(self):\n"
            "        with self._lock:\n"
            "            self.x = 3\n"
            "    def d(self):\n"
            "        with self._lock:\n"
            "            return self.x\n"
        )
        assert guarded_by.run(project) == []

    def test_below_evidence_threshold_quiet(self):
        # Two guarded + one unguarded access: too thin to infer.
        project = core.load_snippet(
            "import threading\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "    def a(self):\n"
            "        with self._lock:\n"
            "            self.x = 1\n"
            "    def b(self):\n"
            "        with self._lock:\n"
            "            self.x = 2\n"
            "    def c(self):\n"
            "        self.x = 3\n"
        )
        assert guarded_by.run(project) == []

    def test_mutating_call_counts_once(self):
        """Regression: ``self.items.append(...)`` is ONE access (a
        write through the attribute), not a write plus a re-walked
        read — double-counting inflated the inference evidence and
        duplicated findings."""
        project = core.load_snippet(
            "import threading\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self.items = []\n"
            "    def a(self):\n"
            "        with self._lock:\n"
            "            self.items.append(1)\n"
            "    def b(self):\n"
            "        with self._lock:\n"
            "            self.items.append(2)\n"
            "    def c(self):\n"
            "        with self._lock:\n"
            "            self.items.append(3)\n"
            "    def d(self):\n"
            "        with self._lock:\n"
            "            self.items.append(4)\n"
            "    def racy(self):\n"
            "        self.items.append(5)\n"
        )
        findings = guarded_by.run(project)
        assert len(findings) == 1, [f.render() for f in findings]
        assert "4/5 accesses" in findings[0].message

    def test_real_tree_clean(self):
        project = core.load_project()
        findings = guarded_by.run(project)
        entries = core.load_allowlist()
        kept, _, _ = core.apply_allowlist(findings, entries)
        assert kept == [], [f.render() for f in kept]


# -- replay-determinism ------------------------------------------------------


class TestReplayDeterminism:
    def test_all_taint_classes_flagged(self):
        findings = replay_det.run(fixture_project("replay_bad.py"))
        messages = [f.message for f in findings]
        assert any("wall-clock read time()" in m for m in messages)
        assert any("module-level RNG" in m for m in messages)
        assert any("os.environ read" in m for m in messages)
        assert any("iteration over an unordered set" in m for m in messages)
        assert any("id()-keyed ordering" in m for m in messages)
        assert any("set.pop()" in m for m in messages)

    def test_sanctioned_forms_clean(self):
        assert replay_det.run(fixture_project("replay_good.py")) == []

    def test_duration_clocks_exempt(self):
        project = core.load_snippet(
            "import time\n"
            "def f():\n"
            "    return time.perf_counter() - time.monotonic()\n"
        )
        assert replay_det.run(project) == []

    def test_sorted_set_iteration_clean(self):
        project = core.load_snippet(
            "def f(xs):\n"
            "    s = set(xs)\n"
            "    return [x for x in sorted(s)]\n"
        )
        assert replay_det.run(project) == []

    def test_real_tree_clean_modulo_allowlist(self):
        project = core.load_project()
        findings = replay_det.run(project)
        entries = core.load_allowlist()
        kept, _, _ = core.apply_allowlist(findings, entries)
        assert kept == [], [f.render() for f in kept]

    def test_reachability_covers_warm_and_sim(self):
        project = core.load_project()
        reachable = replay_det._reachable(project)
        assert any("solver/warm.py" in key for key in reachable)
        assert any("sim/harness.py" in key for key in reachable)


# -- shape-contracts ---------------------------------------------------------


class TestShapeContracts:
    def test_every_check_fires_on_bad_fixture(self):
        findings = shape_contracts.run(fixture_project("contracts_bad.py"))
        messages = [f.message for f in findings]
        assert any("no entry in the contract table" in m for m in messages)
        assert any("stale contract row" in m for m in messages)
        assert any("comment declares shape" in m for m in messages)
        assert any("_ROW_AXIS says axis" in m for m in messages)
        assert any("producer dict never ships it" in m for m in messages)
        assert any("out of range" in m for m in messages)

    def test_good_fixture_clean(self):
        assert shape_contracts.run(fixture_project("contracts_good.py")) == []

    def test_real_tree_clean(self):
        project = core.load_project()
        findings = shape_contracts.run(project)
        entries = core.load_allowlist()
        kept, _, _ = core.apply_allowlist(findings, entries)
        assert kept == [], [f.render() for f in kept]

    def test_tables_cover_live_namedtuples(self):
        """The declaration table and the real NamedTuples agree — the
        runtime import view, complementing the AST view the pass uses."""
        from kube_batch_tpu.solver import contracts
        from kube_batch_tpu.solver.kernels import PackedInputs, SolverInputs

        assert set(SolverInputs._fields) == set(
            contracts.SOLVER_INPUT_CONTRACTS
        )
        assert set(PackedInputs._fields) == set(
            contracts.PACKED_INPUT_CONTRACTS
        )

    def test_row_axis_matches_device_cache(self):
        from kube_batch_tpu.solver import contracts, device_cache

        declared = {
            name: c["row_axis"]
            for name, c in contracts.PACKED_INPUT_CONTRACTS.items()
        }
        assert declared == device_cache._ROW_AXIS

    def test_runtime_validator_roundtrip(self):
        import numpy as np

        from kube_batch_tpu.solver import contracts

        T, N, R, Q, G = 4, 3, 2, 1, 1
        arrays = {
            "task_f32": np.zeros((2, T, R), np.float32),
            "task_i32": np.zeros((6, T), np.int32),
            "node_f32": np.zeros((3, N, R), np.float32),
            "node_i32": np.zeros((3, N), np.int32),
            "group_feas": np.zeros((G, N), bool),
            "pair_idx": np.zeros((0,), np.int32),
            "pair_feas": np.zeros((0, N), bool),
            "score_idx": np.zeros((0,), np.int32),
            "score_rows": np.zeros((0, N), np.float32),
            "queue_f32": np.zeros((2, Q, R), np.float32),
            "misc": np.zeros((R + 2,), np.float32),
        }
        bound = contracts.validate_packed(arrays)
        assert bound["T"] == T and bound["N"] == N and bound["R"] == R

    def test_runtime_validator_catches_dim_disagreement(self):
        import numpy as np

        import pytest as _pytest

        from kube_batch_tpu.solver import contracts

        arrays = {
            "task_f32": np.zeros((2, 4, 2), np.float32),
            # T=5 here disagrees with T=4 above.
            "task_i32": np.zeros((6, 5), np.int32),
        }
        with _pytest.raises(contracts.ContractViolation, match="bound to"):
            contracts._validate(
                arrays,
                {k: contracts.PACKED_INPUT_CONTRACTS[k] for k in arrays},
                "test",
            )

    def test_runtime_validator_catches_dtype(self):
        import numpy as np

        import pytest as _pytest

        from kube_batch_tpu.solver import contracts

        arrays = {"task_f32": np.zeros((2, 4, 2), np.float64)}
        with _pytest.raises(contracts.ContractViolation, match="dtype"):
            contracts._validate(
                arrays,
                {"task_f32": contracts.PACKED_INPUT_CONTRACTS["task_f32"]},
                "test",
            )

    def test_tensorize_validates_under_env(self, monkeypatch):
        """KBT_CHECK_CONTRACTS=1 through the REAL tensorize producer:
        the live arrays satisfy the table."""
        monkeypatch.setenv("KBT_CHECK_CONTRACTS", "1")
        import kube_batch_tpu.actions  # noqa: F401 (registers actions)
        import kube_batch_tpu.plugins  # noqa: F401 (registers plugins)
        from kube_batch_tpu.framework import close_session, open_session
        from kube_batch_tpu.solver.snapshot import tensorize

        from tests.actions.test_actions import DEFAULT_TIERS_ARGS, make_tiers
        from tests.unit.test_cycle_pipeline import build_cluster

        cluster = build_cluster()
        ssn = open_session(cluster, make_tiers(*DEFAULT_TIERS_ARGS))
        try:
            inputs, ctx = tensorize(ssn, device=False)
            assert inputs is not None
        finally:
            close_session(ssn)


# -- allowlist ---------------------------------------------------------------


class TestAllowlist:
    def test_roundtrip(self, tmp_path):
        path = tmp_path / "allow.json"
        path.write_text(json.dumps([
            {"pass": "lock-order", "file": "a.py", "match": "cycle",
             "reason": "known false positive: ..."},
        ]))
        entries = core.load_allowlist(str(path))
        finding = core.Finding("lock-order", "a.py", 1, "lock-order cycle: x")
        kept, suppressed, stale = core.apply_allowlist([finding], entries)
        assert kept == [] and len(suppressed) == 1 and stale == []

    def test_stale_entry_reported(self):
        entries = [core.AllowEntry("census", "x.md", "nope", "r")]
        kept, suppressed, stale = core.apply_allowlist([], entries)
        assert stale == entries

    def test_reason_mandatory(self, tmp_path):
        path = tmp_path / "allow.json"
        path.write_text(json.dumps([
            {"pass": "census", "file": "x.md", "match": "m", "reason": " "},
        ]))
        with pytest.raises(core.AllowlistError):
            core.load_allowlist(str(path))

    def test_committed_allowlist_loads(self):
        core.load_allowlist()  # malformed JSON / missing reasons raise


# -- census ------------------------------------------------------------------


class TestCensus:
    def test_tree_census_clean(self):
        project = core.load_project()
        findings = census.run(project)
        entries = core.load_allowlist()
        kept, _, _ = core.apply_allowlist(findings, entries)
        assert kept == [], [f.render() for f in kept]

    def test_env_table_nontrivial(self):
        names, _ = census.read_marked_table(census.CONFIG_DOC, "env-vars")
        assert names is not None and len(names) >= 15
        assert "KBT_SOLVER_TOPK" in names
        assert "KBT_LOCK_DEBUG" in names

    def test_seeded_violation_detected(self):
        names, line = census.read_marked_table(census.CONFIG_DOC, "env-vars")
        seeded = census.compare_census(
            "KBT env-var", names | {"KBT_NOT_DOCUMENTED"}, names,
            census.CONFIG_DOC, line,
        )
        assert any("KBT_NOT_DOCUMENTED" in f.message for f in seeded)

    def test_stale_doc_row_detected(self):
        names, line = census.read_marked_table(census.CONFIG_DOC, "env-vars")
        dropped = sorted(names)[0]
        seeded = census.compare_census(
            "KBT env-var", names - {dropped}, names,
            census.CONFIG_DOC, line,
        )
        assert any("stale row" in f.message for f in seeded)

    def test_registry_load_matches_runtime(self):
        # The standalone metrics load must agree with the imported
        # registry (the runtime twin in test_metrics_census.py).
        from kube_batch_tpu import metrics

        assert census._load_registry_names() == set(
            metrics.REGISTRY.names()
        )


# -- driver / self-test ------------------------------------------------------


class TestDriver:
    def test_selftest_green(self):
        assert run_selftest() == []

    def test_cli_exit_codes(self):
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        proc = subprocess.run(
            [sys.executable, "-m", "tools.kbtlint"],
            cwd=REPO, capture_output=True, text=True, env=env, timeout=120,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        proc = subprocess.run(
            [sys.executable, "-m", "tools.kbtlint", "--self-test"],
            cwd=REPO, capture_output=True, text=True, env=env, timeout=120,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr

    def test_single_pass_run_ignores_other_passes_allowlist(self):
        """Regression: `--pass lock-order` must not report the
        replay-determinism allowlist entries as stale — only entries
        whose pass actually ran can have legitimately matched
        nothing."""
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        proc = subprocess.run(
            [sys.executable, "-m", "tools.kbtlint", "--pass", "lock-order"],
            cwd=REPO, capture_output=True, text=True, env=env, timeout=120,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "STALE" not in proc.stdout


# -- typecheck ratchet -------------------------------------------------------


class TestTypecheckBaseline:
    def test_in_baseline(self):
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "typecheck.py")],
            cwd=REPO, capture_output=True, text=True, timeout=300,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr

    def test_ledger_shape(self):
        with open(os.path.join(REPO, "tools", "typecheck_baseline.json")) as f:
            ledger = json.load(f)
        assert ledger["tool"]
        assert ledger["note"]
        assert all(
            isinstance(v, int) and v >= 0 for v in ledger["files"].values()
        )
