"""Scheduler core loop.

Mirrors reference pkg/scheduler/scheduler.go (:35 struct, :45 NewScheduler,
:63 Run — wait.Until(runOnce, period), :88 runOnce: OpenSession → execute
configured actions in order → CloseSession, with per-action latency metrics)
and pkg/scheduler/util.go (:44 loadSchedulerConf, :32 defaultSchedulerConf).
"""

from __future__ import annotations

import logging
import threading
import time
from typing import List, Optional, Tuple

from . import metrics
from .conf import DEFAULT_SCHEDULER_CONF, Tier, parse_scheduler_conf
from .framework import Action, close_session, get_action, open_session
from .obs import RECORDER, export_trace, span
from .obs.tracer import TRACER, maybe_enable_from_env
from .utils import deferred_gc

logger = logging.getLogger(__name__)


def load_scheduler_conf(confstr: str) -> Tuple[List[Action], List[Tier]]:
    """YAML policy → (ordered actions, plugin tiers). Misconfigured action
    names are a hard error (reference scheduler/util.go:44-72)."""
    conf = parse_scheduler_conf(confstr)
    actions: List[Action] = []
    for name in conf.actions.split(","):
        name = name.strip()
        if not name:
            continue
        action, found = get_action(name)
        if not found:
            raise ValueError(f"failed to find Action {name}, ignore it")
        actions.append(action)
    return actions, conf.tiers


class _WallClock:
    """Default scheduler pacing: real time. The simulator injects
    ``sim.clock.VirtualClock`` (same surface) to drive thousands of
    cycles in virtual time; ``real`` gates wall-clock-bounded side work
    (the think-time side-effect drain)."""

    real = True

    def now(self) -> float:
        return time.perf_counter()

    def wait(self, event: threading.Event, seconds: float) -> bool:
        if seconds <= 0:
            return event.is_set()
        return event.wait(seconds)


class Scheduler:
    # Per-cycle error backoff (capped exponential): a persistently
    # failing cycle must not busy-spin the loop, and a transient fault
    # (an injected bind storm, a wedged backend probe) must not kill the
    # process — the reference's wait.Until keeps the loop alive the same
    # way.
    CYCLE_ERROR_BACKOFF_BASE = 0.5
    CYCLE_ERROR_BACKOFF_MAX = 30.0

    def __init__(
        self,
        cache,
        scheduler_conf: Optional[str] = None,
        schedule_period: float = 1.0,
        clock=None,
    ):
        """scheduler_conf: YAML policy string or path to one; defaults to the
        reference default policy (allocate, backfill; 2 plugin tiers)."""
        # Ensure builtin registries are populated (blank-import analog,
        # reference cmd/kube-batch/main.go:33-35).
        from . import actions as _actions  # noqa: F401
        from . import plugins as _plugins  # noqa: F401

        self.cache = cache
        self.schedule_period = schedule_period
        self.clock = clock or _WallClock()
        self._error_streak = 0
        self._cycle_count = 0
        # KBT_TRACE_DIR arms the span tracer for the whole loop; the
        # trace file is written on loop exit and on cycle errors.
        maybe_enable_from_env()
        # Per-cycle telemetry feed (KBT_TELEMETRY=0 disables).
        from .obs.telemetry import telemetry_enabled_from_env

        self._telemetry = telemetry_enabled_from_env()
        confstr = scheduler_conf or DEFAULT_SCHEDULER_CONF
        if "\n" not in confstr and confstr.endswith((".yaml", ".yml")):
            with open(confstr) as f:
                confstr = f.read()
        self.actions, self.tiers = load_scheduler_conf(confstr)

    def run_once_guarded(self) -> bool:
        """One cycle that cannot kill the loop: exceptions are logged,
        counted (``scheduler_cycle_errors_total``), and folded into the
        error streak that drives :meth:`cycle_error_backoff`. Returns
        True iff the cycle completed. Shared by :meth:`run` and the
        simulator's cycle driver, so a sim fault run exercises exactly
        the production error path."""
        try:
            self.run_once()
        except Exception as exc:
            self._error_streak += 1
            metrics.register_cycle_error()
            # Flight-recorder forensics: the open cycle record absorbs
            # the failing phase + traceback and is committed to the
            # ring; a dump file lands in KBT_FLIGHT_DIR when set, and a
            # Chrome trace alongside it when tracing is armed.
            RECORDER.record_error(exc)
            RECORDER.dump_on_error()
            export_trace(tag="trace-cycle-error")
            logger.exception(
                "scheduling cycle failed (streak %d, next backoff %.1fs)",
                self._error_streak, self.cycle_error_backoff(),
            )
            return False
        self._error_streak = 0
        return True

    def cycle_error_backoff(self) -> float:
        """Current retry delay: base * 2^(streak-1), capped."""
        if self._error_streak <= 0:
            return 0.0
        return min(
            self.CYCLE_ERROR_BACKOFF_BASE * (2 ** (self._error_streak - 1)),
            self.CYCLE_ERROR_BACKOFF_MAX,
        )

    def run(self, stop_event: Optional[threading.Event] = None) -> None:
        """reference scheduler.go:63-85"""
        from .obs import install_sigusr1

        stop = stop_event or threading.Event()
        clock = self.clock
        # Live-process forensics: SIGUSR1 dumps the flight-recorder ring
        # (no-op on non-main threads — the sim drives cycles directly).
        install_sigusr1()
        self.cache.run(stop)
        self.cache.wait_for_cache_sync(stop)
        while not stop.is_set():
            start = clock.now()
            if not self.run_once_guarded():
                clock.wait(stop, self.cycle_error_backoff())
                continue
            elapsed = clock.now() - start
            remaining = max(0.0, self.schedule_period - elapsed)
            if remaining > 0 and clock.real:
                # Think-time drain: absorb this cycle's async bind/evict
                # backlog while the loop would otherwise sleep, so the
                # next cycle's overlapped solve window starts from an
                # empty side-effect queue (allocate_tpu parks on the
                # same queue inside the solve's shadow). Sliced waits so
                # the stop event stays responsive mid-drain.
                deadline = time.perf_counter() + remaining
                try:
                    while not stop.is_set():
                        left = deadline - time.perf_counter()
                        if left <= 0:
                            break
                        if self.cache.wait_for_side_effects(
                            timeout=min(0.2, left)
                        ):
                            break
                except Exception:
                    logger.exception("think-time side-effect drain failed")
                remaining = max(0.0, deadline - time.perf_counter())
            clock.wait(stop, remaining)
        # Loop exit with tracing armed (KBT_TRACE_DIR): persist the
        # buffered spans so an operator-stopped run leaves a trace.
        export_trace(tag="trace")

    def run_once(self) -> None:
        """One scheduling cycle (reference scheduler.go:88-103). GC is
        deferred for the cycle's duration — collections triggered by the
        apply phase's allocation burst otherwise stop the world mid-cycle
        (~350 ms at 50k tasks); the deferred collection runs in the
        scheduler's think-time gap instead (utils/gc_guard.py).

        Instrumented end to end: every phase runs under a tracer span
        and stamps the flight recorder's open cycle record, so an error
        dump names the phase that raised and the Chrome trace shows the
        phase timeline across the overlap window's worker threads."""
        cycle = self._cycle_count
        self._cycle_count += 1
        TRACER.begin_cycle(cycle)
        RECORDER.begin_cycle(cycle)
        cycle_start = time.perf_counter()
        with span("cycle"):
            with deferred_gc():
                RECORDER.phase("open_session")
                t0 = time.perf_counter()
                with span("open_session"):
                    ssn = open_session(self.cache, self.tiers)
                RECORDER.phase_done(
                    "open_session", (time.perf_counter() - t0) * 1e3
                )
                try:
                    for action in self.actions:
                        name = action.name()
                        RECORDER.phase(f"action:{name}")
                        action_start = time.perf_counter()
                        with span(f"action:{name}"):
                            action.initialize()
                            action.execute(ssn)
                            action.un_initialize()
                        elapsed = time.perf_counter() - action_start
                        metrics.update_action_duration(name, elapsed)
                        RECORDER.phase_done(
                            f"action:{name}", elapsed * 1e3
                        )
                except BaseException:
                    # Pin the phase that actually raised before the
                    # finally's close_session overwrites it — the error
                    # dump must name the FAILING phase.
                    RECORDER.mark_failed_phase()
                    raise
                finally:
                    RECORDER.phase("close_session")
                    t0 = time.perf_counter()
                    with span("close_session"):
                        close_session(ssn)
                    RECORDER.phase_done(
                        "close_session", (time.perf_counter() - t0) * 1e3
                    )
        e2e = time.perf_counter() - cycle_start
        metrics.update_e2e_duration(e2e)
        RECORDER.phase("done")
        rec = RECORDER.end_cycle(e2e_ms=round(e2e * 1e3, 3))
        # Long-horizon telemetry: fold this cycle's record + resource
        # watermarks into the time-series (obs/telemetry.py). Guarded —
        # a probe failure must never fail a cycle.
        if self._telemetry:
            try:
                from .obs.telemetry import TELEMETRY

                TELEMETRY.observe_scheduler_cycle(rec, cache=self.cache)
            except Exception:
                logger.exception("telemetry cycle feed failed")
