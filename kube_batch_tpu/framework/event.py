"""Session events (reference framework/event.go:24-32)."""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Optional

from ..api import TaskInfo


@dataclass
class Event:
    task: TaskInfo


@dataclass
class EventHandler:
    allocate_func: Optional[Callable[[Event], None]] = None
    deallocate_func: Optional[Callable[[Event], None]] = None
    # TPU-native extension: batched forms, called ONCE with the full event
    # list by Session.allocate_batch. A handler that provides the batch
    # form must make it equivalent to folding allocate_func over the
    # events; handlers without one get the per-event fallback.
    batch_allocate_func: Optional[Callable[[list], None]] = None
