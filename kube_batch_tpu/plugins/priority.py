"""Priority plugin (reference plugins/priority/priority.go:39-82):
TaskOrderFn by task priority (PodSpec.Priority), JobOrderFn by job priority
(PodGroup PriorityClass, resolved in cache snapshot)."""

from __future__ import annotations

from ..framework import Plugin, register_plugin_builder


class PriorityPlugin(Plugin):
    def __init__(self, arguments=None):
        self.arguments = arguments or {}

    def name(self) -> str:
        return "priority"

    def on_session_open(self, ssn) -> None:
        def task_order_fn(l, r) -> int:
            if l.priority == r.priority:
                return 0
            return -1 if l.priority > r.priority else 1

        ssn.add_task_order_fn(self.name(), task_order_fn)

        def batch_task_order_key(tasks):
            import numpy as np

            # Ascending key ≡ task_order_fn: higher priority first.
            return np.asarray([-t.priority for t in tasks], np.float64)

        ssn.add_batch_task_order_key_fn(self.name(), batch_task_order_key)

        def job_order_fn(l, r) -> int:
            if l.priority > r.priority:
                return -1
            if l.priority < r.priority:
                return 1
            return 0

        ssn.add_job_order_fn(self.name(), job_order_fn)

        def batch_job_order_key(jobs):
            import numpy as np

            # Ascending key ≡ job_order_fn: higher priority first.
            return np.asarray([-j.priority for j in jobs], np.float64)

        ssn.add_batch_job_order_key_fn(self.name(), batch_job_order_key)


register_plugin_builder("priority", lambda args: PriorityPlugin(args))
