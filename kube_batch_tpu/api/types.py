"""Core scheduling types: task status machine + validation results.

Mirrors reference pkg/scheduler/api/types.go (:23 TaskStatus enum,
:111 ValidateResult) and helpers.go (:62 AllocatedStatus).
TaskStatus is an IntEnum so it can live directly in snapshot tensors.
"""

from __future__ import annotations

from dataclasses import dataclass
from enum import IntEnum


class TaskStatus(IntEnum):
    """Status of a task (reference types.go:23-58)."""

    PENDING = 0      # task not started; pod not yet assigned
    ALLOCATED = 1    # resources assigned within a Session, not yet bound
    PIPELINED = 2    # assigned onto releasing resources; waits for release
    BINDING = 3      # bind request sent, not yet confirmed
    BOUND = 4        # bound to host
    RUNNING = 5      # task running
    RELEASING = 6    # being deleted / resources releasing
    SUCCEEDED = 7
    FAILED = 8
    UNKNOWN = 9


# Statuses whose resources are held on a node (reference helpers.go:62-75).
ALLOCATED_STATUSES = frozenset(
    {TaskStatus.BOUND, TaskStatus.BINDING, TaskStatus.RUNNING, TaskStatus.ALLOCATED}
)


def allocated_status(status: TaskStatus) -> bool:
    return status in ALLOCATED_STATUSES


def validate_status_update(old: TaskStatus, new: TaskStatus) -> None:
    """Status-transition guard (reference types.go validateStatusUpdate — the
    reference currently allows all transitions; kept as a seam)."""
    return None


class NodePhase:
    """Node readiness phase (reference types.go NodePhase)."""

    READY = "Ready"
    NOT_READY = "NotReady"


@dataclass
class ValidateResult:
    """Result of a JobValid callback (reference types.go:111-118)."""

    passed: bool
    reason: str = ""
    message: str = ""
