"""Density perf harness smoke test (kube_batch_tpu/perf.py — the kubemark
equivalent, reference test/e2e/benchmark.go:54). Small scale so the suite
stays fast; the real runs go through ``python -m kube_batch_tpu.perf``."""

import json

from kube_batch_tpu.perf import percentiles, run_density


def test_percentiles_shape():
    p = percentiles([1.0, 2.0, 3.0, 4.0, 5.0])
    assert p["Perc50"] == 3.0
    assert p["Perc100"] == 5.0
    assert percentiles([])["Perc99"] == 0.0


def test_density_small_cluster_runs_all_pods():
    artifact = run_density(
        total_pods=40,
        nodes=8,
        pods_per_group=10,
        schedule_period=0.05,
        kubelet_delay=0.01,
        timeout=60.0,
    )
    assert artifact["pods_running"] == 40
    assert artifact["pods_scheduled"] == 40
    labels = [d["label"] for d in artifact["dataItems"]]
    assert labels == [
        "create_to_scheduled_ms",
        "scheduled_to_running_ms",
        "running_to_watched_ms",
        "e2e_ms",
    ]
    e2e = artifact["dataItems"][3]
    assert e2e["Perc100"] >= e2e["Perc50"] > 0
    # Artifact is JSON-serializable (driver writes it to disk).
    json.dumps(artifact)


def test_multitenant_small_cluster_reclaims_and_backfills():
    """CI-size run of the BASELINE config (5) scenario: tenant B fully
    admitted via reclaim, best-effort pods backfilled, evictions > 0."""
    from kube_batch_tpu.perf import run_multitenant

    art = run_multitenant(
        nodes=4, pods_per_group=4, node_cpu="4", pod_cpu="1",
        besteffort_pods=2, schedule_period=0.05, timeout=60,
    )
    assert art["tenant_b_running"] == art["config"]["tenant_b_pods"]
    assert art["besteffort_backfilled"] == 2
    assert art["tenant_a_evicted"] > 0
    assert art["dataItems"][0]["Perc100"] > 0
