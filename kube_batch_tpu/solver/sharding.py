"""Multi-chip sharded solve: the production scale-out path.

The reference's only scale mechanism is a 16-goroutine fan-out over nodes
(reference util/scheduler_helper.go:84,137). The TPU-native analog shards
the NODE axis — the cluster-size scale axis — across a 1-D
``jax.sharding.Mesh``: every [T, N] intermediate (feasibility mask, score
matrix, bid keys) partitions by node shard, task-major vectors stay
replicated, and the global per-task argmax over nodes plus the assignment
scatter induce the cross-shard collectives, which XLA emits under GSPMD
(no hand-written collectives; they ride ICI on real hardware).

Used by ``actions/allocate_tpu`` when more than one device is visible and
by ``__graft_entry__.dryrun_multichip`` (the driver's multi-chip check).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .kernels import PackedInputs, SolverInputs, solve, solve_auto, solve_staged

NODE_AXIS = "nodes"

# SolverInputs fields whose FIRST axis is the node axis.
_NODE_MAJOR = (
    "node_feas", "node_idle", "node_releasing", "node_cap",
    "node_task_count", "node_max_tasks",
)
# SolverInputs fields whose SECOND axis is the node axis ([G|P|S, N] rows).
_NODE_MINOR = ("group_feas", "pair_feas", "score_rows")
# PackedInputs stacks node tables as [k, N, ...]: node axis is axis 1.
_PACKED_NODE_MINOR = ("node_f32", "node_i32") + _NODE_MINOR


def init_distributed(coordinator_address=None, num_processes=None,
                     process_id=None):
    """Join a multi-HOST jax runtime (DCN scale-out) before building the
    mesh. After this, ``jax.devices()`` spans every host's chips and
    ``default_mesh()``/``solve_sharded`` work unchanged — XLA lays intra-
    host collectives on ICI and inter-host legs on DCN under GSPMD; the
    solver code has no host awareness at all.

    SPMD contract: EVERY process of the distributed runtime must execute
    every sharded solve (jax multi-process collectives block until all
    participants arrive). This is therefore an API for symmetric solver
    deployments — e.g. a dedicated solver job whose replicas all call
    ``solve_sharded`` on identical inputs — NOT for scheduler replicas
    behind leader election, where only the leader would solve and the
    first collective would deadlock. The scheduler server deliberately
    does not auto-join a distributed runtime for that reason.

    Parameters default to the JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES
    / JAX_PROCESS_ID environment (the jax.distributed convention). No-op
    when no coordinator is configured (single-host mode)."""
    import os

    coordinator_address = coordinator_address or os.environ.get(
        "JAX_COORDINATOR_ADDRESS"
    )
    if not coordinator_address:
        return False
    # Idempotent: a retry path or second defensive join must not crash
    # (jax.distributed.initialize raises if called twice).
    if jax.distributed.is_initialized():
        return True
    if num_processes is None:
        env_n = os.environ.get("JAX_NUM_PROCESSES", "")
        num_processes = int(env_n) if env_n else None
    if process_id is None:
        env_id = os.environ.get("JAX_PROCESS_ID", "")
        process_id = int(env_id) if env_id else None
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    return True


def default_mesh(devices=None):
    """A 1-D node-axis mesh over ``devices`` (default: all visible
    devices), or None when only one device exists (single-chip solves
    need no mesh)."""
    devices = jax.devices() if devices is None else list(devices)
    if len(devices) < 2:
        return None
    return Mesh(np.asarray(devices), (NODE_AXIS,))


def shardings_for(inputs, mesh: Mesh):
    """A pytree of NamedShardings matching ``inputs`` (SolverInputs or
    PackedInputs): node-axis fields partitioned over the mesh, everything
    else replicated."""
    rep = NamedSharding(mesh, P())
    major = NamedSharding(mesh, P(NODE_AXIS))
    minor = NamedSharding(mesh, P(None, NODE_AXIS))
    cls = type(inputs)

    def spec(f, sh):
        # Optional fields (candidate slabs on legacy bundles) may be
        # None; the sharding pytree must mirror that or device_put's
        # treedefs mismatch. Candidate slabs are class-row tables (node
        # IDS, not node columns), so they replicate.
        return None if getattr(inputs, f, None) is None else sh

    if isinstance(inputs, PackedInputs):
        return cls(**{
            f: spec(f, minor if f in _PACKED_NODE_MINOR else rep)
            for f in cls._fields
        })
    return cls(**{
        f: spec(
            f,
            major if f in _NODE_MAJOR
            else minor if f in _NODE_MINOR else rep,
        )
        for f in cls._fields
    })


def pad_nodes(inputs, multiple: int):
    """Pad the node axis up to a multiple of ``multiple`` so shards are
    even. Padded nodes are infeasible (node_feas False) and empty, so the
    solver can never assign to them; padded mask/score rows are
    False/zero.

    On the production path this is an identity: ``tensorize`` buckets the
    node axis to multiples of 256 (snapshot.py), divisible by any
    power-of-two mesh, so the eager pad ops below only run for raw
    unbucketed inputs (tests, tools)."""
    if isinstance(inputs, PackedInputs):
        n = inputs.node_f32.shape[1]
    else:
        n = inputs.node_idle.shape[0]
    pad = (-n) % multiple
    if pad == 0:
        return inputs

    def pad_axis(x, axis):
        widths = [(0, 0)] * x.ndim
        widths[axis] = (0, pad)
        return jnp.pad(x, widths)

    if isinstance(inputs, PackedInputs):
        return inputs._replace(**{
            f: pad_axis(getattr(inputs, f), 1) for f in _PACKED_NODE_MINOR
        })
    repl = {f: pad_axis(getattr(inputs, f), 0) for f in _NODE_MAJOR}
    repl.update(
        {f: pad_axis(getattr(inputs, f), 1) for f in _NODE_MINOR}
    )
    return inputs._replace(**repl)


# Weakrefs to jitted GSPMD steps for the retrace census (see
# spmd._jitted_steps — weak so eviction still frees the executable).
_jitted_steps: list = []


@functools.lru_cache(maxsize=32)
def _sharded_step(mesh: Mesh, shardings, staged, max_rounds, tail_bucket):
    if staged is None:
        fn = solve_auto
    elif staged:
        fn = functools.partial(solve_staged, tail_bucket=tail_bucket)
    else:
        fn = solve
    # allow_pallas=False: pallas_call has no GSPMD partitioning rule, so
    # under a node-sharded mesh it would force XLA to gather the [T, N]
    # operands whole onto every device (or fail to lower) — the fused
    # kernel is a single-device optimization; the sharded path keeps the
    # jnp chain, which partitions cleanly.
    import weakref

    step = jax.jit(
        lambda x: fn(x, max_rounds=max_rounds, allow_pallas=False),
        in_shardings=(shardings,),
    )
    _jitted_steps.append(weakref.ref(step))
    return step


def _staged_for_shape(inputs, staged):
    """Resolve the ``staged=None`` shape dispatch (solve_auto's rule)
    statically so both sharded implementations pick the same solver."""
    if staged is not None:
        return staged
    from .kernels import _STAGED_MIN_NODES, _STAGED_MIN_TASKS

    if isinstance(inputs, PackedInputs):
        T, N = inputs.task_f32.shape[1], inputs.node_f32.shape[1]
    else:
        T, N = inputs.task_req.shape[0], inputs.node_idle.shape[0]
    return N >= _STAGED_MIN_NODES and T >= _STAGED_MIN_TASKS


def sharded_step(
    inputs,
    mesh: Mesh,
    max_rounds: int = 256,
    staged=None,
    tail_bucket: int = 3072,
    impl: str = "spmd",
):
    """Return ``(step_fn, device_inputs)``: inputs padded and device_put
    onto the mesh ONCE, plus the cached jitted step to run on them. Use
    this when solving the same snapshot repeatedly (benchmarks, re-solve
    loops) so the host→device transfer is not re-paid per call.

    ``impl='spmd'`` (default) is the hierarchical shard_map solver
    (solver/spmd.py): node columns sharded, node/queue tables
    replicated, per-commit communication limited to a two-[T]-vector
    all_gather. ``impl='gspmd'`` keeps the legacy auto-partitioned
    single-device program (collective-dominated at scale; retained for
    A/B and as the fallback surface)."""
    inputs = pad_nodes(inputs, mesh.size)
    if impl == "spmd":
        from .spmd import _spmd_step, spmd_shardings_for

        shardings = spmd_shardings_for(inputs, mesh)
        inputs = jax.device_put(inputs, shardings)
        step = _spmd_step(
            mesh, _staged_for_shape(inputs, staged), max_rounds,
            tail_bucket,
        )
        return step, inputs
    shardings = shardings_for(inputs, mesh)
    inputs = jax.device_put(inputs, shardings)
    step = _sharded_step(mesh, shardings, staged, max_rounds, tail_bucket)
    return step, inputs


def solve_sharded(
    inputs,
    mesh: Mesh = None,
    max_rounds: int = 256,
    staged=None,
    tail_bucket: int = 3072,
    impl: str = "spmd",
):
    """Run the batched solve with the node axis sharded over ``mesh``.

    ``staged``: None dispatches by shape (like ``solve_auto``), True
    forces the staged solver, False the full-width one. Falls back to the
    single-device jitted path when no mesh is available. Same semantics
    and results as the single-device solve — sharding changes layout, not
    the program. ``impl`` selects the hierarchical shard_map solver
    (default) or the legacy GSPMD auto-partitioning (see
    :func:`sharded_step`).

    Candidate-sparsified inputs (topk slabs present) always take the
    single-device sparse jit, mesh or not: the slab rounds do O(T·K)
    work and materialize no [T, N] structures, so one device running
    the sparse program beats N/s-sharded dense rounds whenever
    K·s < N (the production regime), while candidate gathers inside
    shard_map would force per-round cross-shard node-row collectives.
    The sharded SPMD solvers remain the dense scale path.
    """
    if mesh is None:
        mesh = default_mesh()
    if mesh is not None and staged is None:
        # Shape probe only — no unpack() (its eager per-field slices
        # cost real milliseconds outside a jit).
        cand = getattr(inputs, "cand_idx", None)
        if cand is not None and cand.shape[0] > 0:
            mesh = None
    if mesh is None:
        # Single device: reuse the module-level cached jits.
        from .kernels import solve_full_jit, solve_jit, solve_staged_jit

        if staged is None:
            return solve_jit(inputs, max_rounds=max_rounds)
        if staged:
            return solve_staged_jit(
                inputs, max_rounds=max_rounds, tail_bucket=tail_bucket
            )
        return solve_full_jit(inputs, max_rounds=max_rounds)

    step, inputs = sharded_step(
        inputs, mesh, max_rounds=max_rounds, staged=staged,
        tail_bucket=tail_bucket, impl=impl,
    )
    return step(inputs)
