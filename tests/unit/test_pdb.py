"""PodDisruptionBudget as a legacy gang source (reference
event_handlers.go:662-773): a PDB owned by a controller defines
minAvailable for that controller's pods with no PodGroup involved.
Handlers are fed through the same entry points the watch dispatcher uses,
per the reference test pattern (allocate_test.go:164-176)."""

import queue as queue_mod

import pytest
import yaml

import kube_batch_tpu.actions  # noqa: F401
import kube_batch_tpu.plugins  # noqa: F401
from kube_batch_tpu.api import (
    ObjectMeta,
    PodDisruptionBudget,
    PodPhase,
    build_resource_list,
)
from kube_batch_tpu.cache.util import job_terminated
from kube_batch_tpu.cli.manifests import parse_manifest
from kube_batch_tpu.utils.test_utils import (
    build_node,
    build_pod,
    build_queue,
)

from tests.actions.test_actions import drain, make_cache, run_action


def make_pdb(name="pdb1", ns="ns", owner="ctrl-1", min_available=3):
    return PodDisruptionBudget(
        metadata=ObjectMeta(name=name, namespace=ns, owner_uid=owner),
        min_available=min_available,
    )


def owned_pod(name, owner="ctrl-1", phase=PodPhase.PENDING):
    # No group annotation: the pod files under its controller UID via the
    # shadow-PodGroup path, the same key the PDB claims.
    return build_pod(
        "ns", name, "", phase,
        build_resource_list(cpu="1", memory="1Gi"),
        owner_uid=owner,
    )


class TestPdbHandlers:
    def test_add_pdb_creates_job_on_default_queue(self):
        c = make_cache()
        c.add_pdb(make_pdb(min_available=2))
        job = c.jobs["ctrl-1"]
        assert job.min_available == 2
        assert job.queue == c.default_queue
        assert job.pod_group is None and job.pdb is not None

    def test_pdb_then_pods_share_one_job(self):
        c = make_cache()
        c.add_pdb(make_pdb(min_available=2))
        for i in range(2):
            c.add_pod(owned_pod(f"p{i}"))
        job = c.jobs["ctrl-1"]
        assert len(job.tasks) == 2
        # The PDB's minAvailable survives pod arrival (no shadow PodGroup
        # overwrite once the job exists).
        assert job.min_available == 2
        assert job.pod_group is None

    def test_pods_then_pdb_overrides_shadow_min(self):
        c = make_cache()
        for i in range(3):
            c.add_pod(owned_pod(f"p{i}"))
        assert c.jobs["ctrl-1"].min_available == 1  # shadow PodGroup default
        c.add_pdb(make_pdb(min_available=3))
        assert c.jobs["ctrl-1"].min_available == 3

    def test_snapshot_includes_pdb_only_job(self):
        c = make_cache()
        c.add_queue(build_queue("default"))
        c.add_pdb(make_pdb())
        c.add_pod(owned_pod("p0"))
        snap = c.snapshot()
        assert "ctrl-1" in snap.jobs
        assert snap.jobs["ctrl-1"].pdb is not None

    def test_update_pdb_changes_min_available(self):
        c = make_cache()
        c.add_pdb(make_pdb(min_available=2))
        c.update_pdb(make_pdb(min_available=2), make_pdb(min_available=5))
        assert c.jobs["ctrl-1"].min_available == 5

    def test_delete_pdb_queues_cleanup(self):
        c = make_cache()
        c.add_pdb(make_pdb())
        c.delete_pdb(make_pdb())
        job = c.jobs["ctrl-1"]
        assert job.pdb is None
        assert job_terminated(job)  # no tasks, no spec left
        # queued for the cleanup loop (reference deleteJob path)
        assert not c.deleted_jobs.empty()

    def test_delete_pdb_stamps_dirty_ledger(self):
        """Regression for a kbtlint dirty-ledger bring-up finding:
        delete_pdb dropped the job's gang spec with NO ledger stamp —
        the delta-aware tensorize would keep serving the job's old
        min-available verdicts (PR 8 staleness class). The stamp must
        survive a fully-absorbed ledger, so drain AND absorb first."""
        c = make_cache()
        c.add_queue(build_queue("default"))
        c.add_pdb(make_pdb())
        c.add_pod(owned_pod("p0"))
        snap = c.snapshot()
        assert "ctrl-1" in snap.dirty_jobs
        # Simulate the tensorize refresh consuming the backlog — only
        # a fresh stamp can re-dirty the name now.
        c.note_full_absorbed(snap.dirty_jobs, snap.dirty_nodes)
        c.delete_pdb(make_pdb())
        snap2 = c.snapshot()
        assert "ctrl-1" in snap2.dirty_jobs

    def test_ownerless_pdb_ignored(self):
        # Ordinary (label-selector) disruption budgets have no controller
        # owner and are not gang sources: skipped quietly, no job.
        c = make_cache()
        c.add_pdb(make_pdb(owner=""))
        assert not c.jobs


class TestPdbGangScheduling:
    """VERDICT r1 item 6 'done' criterion: a PDB-defined gang schedules
    without a PodGroup."""

    def _cluster(self, n_pods):
        c = make_cache()
        c.add_queue(build_queue("default"))
        c.add_node(build_node(
            "n1", build_resource_list(cpu="8", memory="16Gi", pods=110)
        ))
        c.add_pdb(make_pdb(min_available=3))
        for i in range(n_pods):
            c.add_pod(owned_pod(f"p{i}"))
        return c

    def test_pdb_gang_schedules(self):
        c = self._cluster(3)
        run_action(c, "allocate")
        assert len(drain(c.binder.channel, 3)) == 3

    def test_pdb_gang_starves_below_min(self):
        # 2 pods < minAvailable 3: gang JobValid drops the job at session
        # open; nothing binds.
        c = self._cluster(2)
        run_action(c, "allocate")
        with pytest.raises(queue_mod.Empty):
            c.binder.channel.get(timeout=0.5)

    def test_pdb_gang_schedules_via_tpu_action(self):
        c = self._cluster(3)
        run_action(c, "allocate_tpu")
        assert len(drain(c.binder.channel, 3)) == 3


PDB_YAML = """
apiVersion: policy/v1
kind: PodDisruptionBudget
metadata:
  name: my-pdb
  namespace: ns
  ownerReferences:
  - uid: ctrl-9
    controller: true
    kind: Job
    name: my-job
spec:
  minAvailable: 4
"""


class TestPdbManifests:
    def test_policy_v1_pdb_parses(self):
        kind, pdb = parse_manifest(yaml.safe_load(PDB_YAML))
        assert kind == "PodDisruptionBudget"
        assert pdb.min_available == 4
        assert pdb.metadata.owner_uid == "ctrl-9"

    def test_percentage_min_available_skipped(self):
        # A percentage budget is a real-world disruption budget, not a
        # gang spec: the document loads as a no-op instead of failing the
        # whole manifest file.
        doc = yaml.safe_load(PDB_YAML)
        doc["spec"]["minAvailable"] = "50%"
        assert parse_manifest(doc) == (None, None)

    def test_ownerless_pdb_manifest_skipped(self):
        doc = yaml.safe_load(PDB_YAML)
        del doc["metadata"]["ownerReferences"]
        assert parse_manifest(doc) == (None, None)
