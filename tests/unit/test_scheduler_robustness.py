"""Scheduler guarded-cycle robustness: a failing cycle must not kill
the loop — it is logged, counted (``scheduler_cycle_errors_total``),
and retried with capped exponential backoff."""

import threading

from kube_batch_tpu import metrics
from kube_batch_tpu.cache import SchedulerCache
from kube_batch_tpu.scheduler import Scheduler
from kube_batch_tpu.sim.clock import VirtualClock
from kube_batch_tpu.utils.test_utils import (
    FakeBinder,
    FakeEvictor,
    FakeStatusUpdater,
    FakeVolumeBinder,
)


def make_scheduler(clock=None):
    cache = SchedulerCache(
        binder=FakeBinder(),
        evictor=FakeEvictor(),
        status_updater=FakeStatusUpdater(),
        volume_binder=FakeVolumeBinder(),
    )
    return Scheduler(cache, schedule_period=0.01, clock=clock)


class TestGuardedCycle:
    def test_errors_counted_and_backoff_caps(self):
        s = make_scheduler()
        s.run_once = lambda: (_ for _ in ()).throw(RuntimeError("boom"))
        before = metrics.metrics.scheduler_cycle_errors.get()
        assert s.cycle_error_backoff() == 0.0
        seen = []
        for _ in range(12):
            assert s.run_once_guarded() is False
            seen.append(s.cycle_error_backoff())
        assert metrics.metrics.scheduler_cycle_errors.get() == before + 12
        # 0.5, 1, 2, 4, ... capped at CYCLE_ERROR_BACKOFF_MAX.
        assert seen[0] == Scheduler.CYCLE_ERROR_BACKOFF_BASE
        assert seen[1] == 2 * seen[0]
        assert seen[-1] == Scheduler.CYCLE_ERROR_BACKOFF_MAX
        assert max(seen) == Scheduler.CYCLE_ERROR_BACKOFF_MAX

    def test_success_resets_streak(self):
        s = make_scheduler()
        s.run_once = lambda: (_ for _ in ()).throw(RuntimeError("boom"))
        for _ in range(3):
            s.run_once_guarded()
        assert s.cycle_error_backoff() > 0
        s.run_once = lambda: None
        assert s.run_once_guarded() is True
        assert s.cycle_error_backoff() == 0.0

    def test_run_loop_survives_failing_cycles(self):
        """The loop keeps going through a crash streak (on a virtual
        clock, so the exponential backoffs cost no wall time) and still
        runs healthy cycles afterwards."""
        clock = VirtualClock()
        s = make_scheduler(clock=clock)
        stop = threading.Event()
        calls = []

        def flaky():
            calls.append(clock.now())
            if len(calls) <= 4:
                raise RuntimeError("injected cycle failure")
            if len(calls) >= 7:
                stop.set()

        s.run_once = flaky
        t = threading.Thread(target=s.run, args=(stop,), daemon=True)
        t.start()
        t.join(timeout=10)
        assert not t.is_alive()
        assert len(calls) >= 7
        # Virtual time advanced through the backoffs: 0.5+1+2+4 from
        # the error streak alone.
        assert clock.now() >= 7.5
