"""Density / pod-startup-latency perf harness — the kubemark equivalent.

The reference measures scheduler performance with hollow-node kubemark
clusters (test/kubemark/start-kubemark.sh) and a density e2e
(test/e2e/benchmark.go:54 "Schedule Density Job"): schedule TotalPodCount
pods, watch each pod's lifecycle, compute create→scheduled,
scheduled→running, running→watched, and e2e percentiles
(test/e2e/metric_util.go:45-59), and emit a versioned perf JSON artifact
(benchmark.go:117-148). This module is that harness against the in-process
hollow cluster (cluster/api.py InProcessCluster with simulated kubelets):
simulated kubelets, real scheduler — same trade as kubemark.

Run: ``python -m kube_batch_tpu.perf --pods 3000 --nodes 100 --out perf.json``
(the 3k-pods-on-100-hollow-nodes scale is the reference's design intent,
doc/design/Benchmark/kubemark/kubemark-benchmarking.md:40).
"""

from __future__ import annotations

import argparse
import json
import threading
import time
from typing import Dict, List, Optional

from .api import PodPhase, build_resource_list
from .cache import SchedulerCache
from .cluster import InProcessCluster
from .scheduler import Scheduler
from .utils.test_utils import (
    build_node,
    build_pod,
    build_pod_group,
    build_queue,
)

# Perf artifact schema version (reference test/e2e/util.go:57
# currentApiCallMetricsVersion = "v1").
PERF_VERSION = "v1"


def percentiles(values: List[float]) -> Dict[str, float]:
    """P50/P90/P99/P100 like the reference (metric_util.go:45-52)."""
    if not values:
        return {"Perc50": 0.0, "Perc90": 0.0, "Perc99": 0.0, "Perc100": 0.0}
    xs = sorted(values)
    n = len(xs)
    return {
        "Perc50": xs[n // 2],
        "Perc90": xs[min(n - 1, (n * 90) // 100)],
        "Perc99": xs[min(n - 1, (n * 99) // 100)],
        "Perc100": xs[-1],
    }


class PodWatchRecorder:
    """Watches pod lifecycle events and records phase timestamps
    (benchmark.go:66-113: watch-based scheduled/run/watch capture)."""

    def __init__(self, cluster: InProcessCluster):
        self.lock = threading.Lock()
        self.created: Dict[str, float] = {}
        self.scheduled: Dict[str, float] = {}
        self.running: Dict[str, float] = {}
        self.watched: Dict[str, float] = {}
        cluster.add_watch(self._on_event)

    def _key(self, pod) -> str:
        return f"{pod.metadata.namespace}/{pod.metadata.name}"

    def _on_event(self, kind: str, event_type: str, obj) -> None:
        if kind != "Pod":
            return
        now = time.time()
        key = self._key(obj)
        with self.lock:
            if event_type == "ADDED":
                self.created.setdefault(key, now)
                return
            if obj.spec.node_name and key not in self.scheduled:
                self.scheduled[key] = now
            if obj.status.phase == PodPhase.RUNNING and key not in self.running:
                self.running[key] = now
                self.watched[key] = now

    def all_running(self, keys) -> bool:
        with self.lock:
            return all(k in self.running for k in keys)


def run_density(
    total_pods: int = 100,
    nodes: int = 100,
    pods_per_group: int = 10,
    min_member_frac: float = 1.0,
    node_cpu: str = "32",
    node_memory: str = "128Gi",
    pods_per_node: int = 110,
    pod_cpu: str = "100m",
    pod_memory: str = "128Mi",
    schedule_period: float = 0.1,
    kubelet_delay: float = 0.05,
    scheduler_conf: Optional[str] = None,
    timeout: float = 300.0,
) -> Dict:
    """Schedule ``total_pods`` gang pods onto hollow nodes; return the
    perf artifact dict (latencies in ms)."""
    cluster = InProcessCluster(
        simulate_kubelet=True, kubelet_delay=kubelet_delay
    )
    recorder = PodWatchRecorder(cluster)
    cache = SchedulerCache(cluster=cluster)

    cluster.create_queue(build_queue("default", weight=1))
    for j in range(nodes):
        cluster.create_node(build_node(
            f"hollow-{j}",
            build_resource_list(
                cpu=node_cpu, memory=node_memory, pods=pods_per_node
            ),
        ))

    # Scheduler first, pods second: pods ARRIVE while the scheduler
    # runs (the kubemark flow, test/e2e/benchmark.go:49-60), so the
    # creation timestamps the latency percentiles are measured from and
    # the wall clock describe the same window. The committed r3
    # artifact had wall_seconds 0.159 against e2e P50 ~2,005 ms — the
    # old pre-load-then-start order put the benchmark's own setup time
    # inside every pod's e2e (VERDICT r3 weakness 7).
    sched = Scheduler(cache, scheduler_conf, schedule_period=schedule_period)
    stop = threading.Event()
    thread = threading.Thread(target=sched.run, args=(stop,), daemon=True)
    thread.start()

    keys = []
    start = time.time()
    groups = max(1, total_pods // max(1, pods_per_group))
    t = 0
    for g in range(groups):
        size = pods_per_group if g < groups - 1 else total_pods - t
        if size <= 0:
            break
        min_member = max(1, int(size * min_member_frac))
        cluster.create_pod_group(build_pod_group(
            f"density-{g}", namespace="perf", min_member=min_member
        ))
        for i in range(size):
            pod = build_pod(
                "perf", f"density-{g}-{i}", "", PodPhase.PENDING,
                build_resource_list(cpu=pod_cpu, memory=pod_memory),
                group_name=f"density-{g}",
            )
            cluster.create_pod(pod)
            keys.append(f"perf/{pod.metadata.name}")
            t += 1

    deadline = start + timeout
    while time.time() < deadline and not recorder.all_running(keys):
        time.sleep(0.05)
    wall = time.time() - start
    stop.set()
    thread.join(timeout=10)

    with recorder.lock:
        create_to_sched = [
            (recorder.scheduled[k] - recorder.created[k]) * 1e3
            for k in keys if k in recorder.scheduled
        ]
        sched_to_run = [
            (recorder.running[k] - recorder.scheduled[k]) * 1e3
            for k in keys if k in recorder.running and k in recorder.scheduled
        ]
        run_to_watch = [
            (recorder.watched[k] - recorder.running[k]) * 1e3
            for k in keys if k in recorder.watched
        ]
        e2e = [
            (recorder.watched[k] - recorder.created[k]) * 1e3
            for k in keys if k in recorder.watched
        ]
        scheduled_count = len(recorder.scheduled)
        running_count = len(recorder.running)

    return {
        "version": PERF_VERSION,
        "metric": "pod_startup_latency",
        "config": {
            "total_pods": total_pods,
            "nodes": nodes,
            "pods_per_group": pods_per_group,
            "schedule_period_s": schedule_period,
            "kubelet_delay_s": kubelet_delay,
        },
        "pods_scheduled": scheduled_count,
        "pods_running": running_count,
        "wall_seconds": round(wall, 3),
        "pods_per_second": round(running_count / wall, 1) if wall else 0.0,
        "dataItems": [
            {"label": "create_to_scheduled_ms", **percentiles(create_to_sched)},
            {"label": "scheduled_to_running_ms", **percentiles(sched_to_run)},
            {"label": "running_to_watched_ms", **percentiles(run_to_watch)},
            {"label": "e2e_ms", **percentiles(e2e)},
        ],
    }


MULTITENANT_CONF = """
actions: "reclaim, {allocate_action}, backfill"
tiers:
- plugins:
  - name: priority
  - name: gang
  - name: conformance
- plugins:
  - name: drf
  - name: predicates
  - name: proportion
  - name: nodeorder
"""


def run_multitenant(
    nodes: int = 100,
    pods_per_group: int = 10,
    node_cpu: str = "32",
    node_memory: str = "128Gi",
    pods_per_node: int = 110,
    pod_cpu: str = "1",
    pod_memory: str = "1Gi",
    besteffort_pods: int = 20,
    schedule_period: float = 0.1,
    kubelet_delay: float = 0.05,
    timeout: float = 300.0,
    allocate_action: str = "allocate",
) -> Dict:
    """BASELINE.json config (5): multi-tenant cluster with backfill and
    reclaim at kubemark-style scale (hollow kubelets, real scheduler).

    Phase 1: tenant A (weight 1) saturates the cluster with gangs plus a
    batch of best-effort (zero-request) pods that only backfill can
    place. Phase 2: tenant B (weight 3) arrives; proportion's deserved
    shares flip queue A to reclaimable and B's gangs must run via
    cross-queue reclaim (reference test/e2e queue.go:26 behavior, at
    perf scale). The artifact reports B's admission latency percentiles
    and the eviction count."""
    cluster = InProcessCluster(
        simulate_kubelet=True, kubelet_delay=kubelet_delay
    )
    recorder = PodWatchRecorder(cluster)
    cache = SchedulerCache(cluster=cluster)

    cluster.create_queue(build_queue("tenant-a", weight=1))
    cluster.create_queue(build_queue("tenant-b", weight=3))
    for j in range(nodes):
        cluster.create_node(build_node(
            f"hollow-{j}",
            build_resource_list(
                cpu=node_cpu, memory=node_memory, pods=pods_per_node
            ),
        ))

    # Tenant A: enough gang pods to consume every CPU. minMember is half
    # the gang — members above minAvailable are reclaimable (gang's
    # ReclaimableFn protects exactly the minAvailable floor,
    # gang.go:70-93); a full-gang tenant would be reclaim-proof.
    from .api.resource_info import parse_quantity

    node_milli = parse_quantity(node_cpu) * 1000
    pod_milli = parse_quantity(pod_cpu) * 1000
    pods_a = int(nodes * node_milli // pod_milli)
    a_keys = []
    groups_a = max(1, pods_a // pods_per_group)
    for g in range(groups_a):
        cluster.create_pod_group(build_pod_group(
            f"tena-{g}", namespace="perf",
            min_member=max(1, pods_per_group // 2),
            queue="tenant-a",
        ))
        for i in range(pods_per_group):
            pod = build_pod(
                "perf", f"tena-{g}-{i}", "", PodPhase.PENDING,
                build_resource_list(cpu=pod_cpu, memory=pod_memory),
                group_name=f"tena-{g}",
            )
            cluster.create_pod(pod)
            a_keys.append(f"perf/{pod.metadata.name}")
    # Best-effort pods: zero requests, placeable only by backfill.
    # Explicit minMember=1 groups on tenant-a (a groupless pod would get
    # a shadow group on the nonexistent 'default' queue).
    be_keys = []
    for i in range(besteffort_pods):
        cluster.create_pod_group(build_pod_group(
            f"be-{i}", namespace="perf", min_member=1, queue="tenant-a",
        ))
        pod = build_pod(
            "perf", f"be-{i}", "", PodPhase.PENDING,
            build_resource_list(), group_name=f"be-{i}",
        )
        cluster.create_pod(pod)
        be_keys.append(f"perf/{pod.metadata.name}")

    sched = Scheduler(
        cache,
        MULTITENANT_CONF.format(allocate_action=allocate_action),
        schedule_period=schedule_period,
    )
    stop = threading.Event()
    thread = threading.Thread(target=sched.run, args=(stop,), daemon=True)
    start = time.time()
    thread.start()
    deadline = start + timeout / 2
    while time.time() < deadline and not recorder.all_running(
        a_keys + be_keys
    ):
        time.sleep(0.05)

    # Phase 2: tenant B deserves 3/4 of the cluster; its gangs can only
    # run by reclaiming tenant A's pods.
    pods_b = pods_a // 2
    b_keys = []
    b_start = time.time()
    groups_b = max(1, pods_b // pods_per_group)
    for g in range(groups_b):
        cluster.create_pod_group(build_pod_group(
            f"tenb-{g}", namespace="perf", min_member=pods_per_group,
            queue="tenant-b",
        ))
        for i in range(pods_per_group):
            pod = build_pod(
                "perf", f"tenb-{g}-{i}", "", PodPhase.PENDING,
                build_resource_list(cpu=pod_cpu, memory=pod_memory),
                group_name=f"tenb-{g}",
            )
            cluster.create_pod(pod)
            b_keys.append(f"perf/{pod.metadata.name}")

    deadline = time.time() + timeout / 2
    while time.time() < deadline and not recorder.all_running(b_keys):
        time.sleep(0.05)
    wall = time.time() - start
    stop.set()
    thread.join(timeout=10)

    with recorder.lock:
        b_admission = [
            (recorder.running[k] - b_start) * 1e3
            for k in b_keys if k in recorder.running
        ]
        a_running = sum(1 for k in a_keys if k in recorder.running)
        be_running = sum(1 for k in be_keys if k in recorder.running)
        b_running = sum(1 for k in b_keys if k in recorder.running)
    evicted = sum(
        1 for k in a_keys
        if cluster.get_pod("perf", k.split("/", 1)[1]) is None
    )

    return {
        "version": PERF_VERSION,
        "metric": "multitenant_reclaim",
        "config": {
            "nodes": nodes,
            "tenant_a_pods": pods_a,
            "tenant_b_pods": pods_b,
            "besteffort_pods": besteffort_pods,
            "weights": {"tenant-a": 1, "tenant-b": 3},
            "allocate_action": allocate_action,
        },
        "tenant_a_running_initial": a_running,
        "besteffort_backfilled": be_running,
        "tenant_b_running": b_running,
        "tenant_a_evicted": evicted,
        "wall_seconds": round(wall, 3),
        "dataItems": [
            {"label": "tenant_b_admission_ms", **percentiles(b_admission)},
        ],
    }


def run_multitenant_compare(**kw) -> Dict:
    """BASELINE config (5) with BOTH allocate actions, side by side
    (VERDICT r4 item 7): the batched-solver loop (allocate_tpu) and the
    reference-parity greedy loop (allocate) on the identical scenario,
    so "matching-or-beating" on tenant-b admission latency is evaluable
    from one artifact. The tpu-batch run is the headline; the greedy run
    is the reference row (reference test/e2e queue.go:26-69 semantics at
    kubemark-benchmarking.md:40 scale)."""
    tpu = run_multitenant(allocate_action="allocate_tpu", **kw)
    ref = run_multitenant(allocate_action="allocate", **kw)

    def p(art, q):
        return art["dataItems"][0][q]

    def complete(art):
        return art["tenant_b_running"] == art["config"]["tenant_b_pods"]

    artifact = dict(tpu)
    artifact["metric"] = "multitenant_reclaim_compare"
    artifact["reference_loop"] = {
        "config": ref["config"],
        "tenant_a_running_initial": ref["tenant_a_running_initial"],
        "besteffort_backfilled": ref["besteffort_backfilled"],
        "tenant_b_running": ref["tenant_b_running"],
        "tenant_a_evicted": ref["tenant_a_evicted"],
        "wall_seconds": ref["wall_seconds"],
        "dataItems": ref["dataItems"],
    }
    # Percentiles of a run that hit its deadline cover only the pods
    # that made it — comparing a censored distribution against a
    # complete one would flatter the censored side. Ratios only when
    # both runs admitted every tenant-b pod.
    artifact["comparison"] = {
        "tpu_admission_complete": complete(tpu),
        "reference_admission_complete": complete(ref),
    }
    if complete(tpu) and complete(ref):
        artifact["comparison"].update({
            "tenant_b_admission_p50_speedup": round(
                p(ref, "Perc50") / p(tpu, "Perc50"), 3
            ) if p(tpu, "Perc50") else None,
            "tenant_b_admission_p99_speedup": round(
                p(ref, "Perc99") / p(tpu, "Perc99"), 3
            ) if p(tpu, "Perc99") else None,
        })
    else:
        artifact["comparison"]["incomparable_reason"] = (
            "a run hit its convergence deadline before admitting every "
            "tenant-b pod; its percentiles are censored"
        )
    return artifact


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--pods", type=int, default=100,
                    help="total pods (reference benchmark.go:50 uses 100)")
    ap.add_argument("--nodes", type=int, default=100)
    ap.add_argument("--group-size", type=int, default=10)
    ap.add_argument("--min-member-frac", type=float, default=1.0)
    ap.add_argument("--period", type=float, default=0.1)
    ap.add_argument("--kubelet-delay", type=float, default=0.05)
    ap.add_argument("--timeout", type=float, default=300.0,
                    help="total convergence budget, seconds, PER scenario run "
                         "(multitenant splits it between its two phases; "
                         "multitenant-compare runs the scenario twice, so "
                         "worst-case wall is 2x this)")
    ap.add_argument("--conf", default=None, help="scheduler policy YAML path")
    ap.add_argument("--out", default=None, help="write perf JSON artifact")
    ap.add_argument(
        "--scenario",
        choices=("density", "multitenant", "multitenant-compare"),
        default="density",
        help="density = BASELINE config kubemark density; multitenant = "
             "BASELINE config (5): two weighted queues, backfill of "
             "best-effort pods, cross-queue reclaim; multitenant-compare "
             "= the same scenario run twice (allocate_tpu, then the "
             "reference-parity greedy allocate) with both admission "
             "distributions in one artifact",
    )
    args = ap.parse_args(argv)

    if args.scenario.startswith("multitenant"):
        # These density-only knobs would be silently dropped — refuse
        # instead so results never misrepresent the requested config.
        if args.conf or args.pods != 100 or args.min_member_frac != 1.0:
            ap.error(
                "--pods/--min-member-frac/--conf apply to the density "
                "scenario only (multitenant sizes tenants from the "
                "cluster and pins the reclaim policy)"
            )
        runner = (
            run_multitenant_compare
            if args.scenario == "multitenant-compare"
            else run_multitenant
        )
        artifact = runner(
            nodes=args.nodes,
            pods_per_group=args.group_size,
            schedule_period=args.period,
            kubelet_delay=args.kubelet_delay,
            timeout=args.timeout,
        )
    else:
        artifact = run_density(
            total_pods=args.pods,
            nodes=args.nodes,
            pods_per_group=args.group_size,
            min_member_frac=args.min_member_frac,
            schedule_period=args.period,
            kubelet_delay=args.kubelet_delay,
            scheduler_conf=args.conf,
            timeout=args.timeout,
        )
    line = json.dumps(artifact)
    print(line)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")


if __name__ == "__main__":
    main()
