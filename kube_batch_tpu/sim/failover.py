"""Leader-kill emulation: the process-death seam of the failover drill.

The `crash` fault injects an in-cycle EXCEPTION — the guarded loop
absorbs it and the same process retries. `leader-kill` models the
failure class PR 7 deliberately stopped at: the leader PROCESS dies
mid-flight, nothing fences, nothing unwinds, and whatever subset of
its dispatched side effects already reached the cluster is simply...
there. A successor must take the lease and reconcile
(cache/recovery.py).

In-process we cannot kill threads, so death is emulated at the one
place it is observable: the cluster boundary. Each scheduler instance
talks to the shared :class:`InProcessCluster` through its own
:class:`SimClusterEndpoint`; killing the leader arms a per-cut-point
write policy on its endpoint, and after the cycle the endpoint is
finalized (everything refused, watch detached) and the instance
discarded. The scheduler thread itself runs the cycle to completion —
every write a dead process "would have issued" is refused, so the
cluster-visible outcome is exactly a process that died at the cut
point, while the cycle stays deterministic and replayable.

Cut points and their write policies (doc/design/robustness.md):

| cut                   | journal append | binds        | applied marks | status writes |
|-----------------------|----------------|--------------|---------------|---------------|
| `pre-solve`           | refused        | refused      | refused       | refused       |
| `post-solve-pre-drain`| land           | refused      | refused       | refused       |
| `mid-bind-drain`      | land           | hash subset  | follow bind, hash subset dropped | refused |
| `mid-close`           | land           | land         | land          | refused       |

The mid-bind-drain subset is decided per pod by a pure
``blake2b(seed, cycle, uid)`` hash — the same determinism regime as
the `bind` fault seam: bind side effects run concurrently on the
cache's worker pool, so "first K then dead" would be timing-dependent,
while a hash-selected subset is an equally valid half-applied batch
and replays bit-identically. A slice of the landed binds additionally
loses its applied MARK (crash between bind and mark), exercising the
recovery table's "unmarked but bound = applied" row.
"""

from __future__ import annotations

import logging
import threading
from typing import List, Optional

from ..api import Pod, PodCondition, PodGroup
from ..cluster import ClusterAPI
from .faults import _hash01

logger = logging.getLogger(__name__)

# Seeded kill cut points, in cycle order.
CUT_POINTS = (
    "pre-solve", "post-solve-pre-drain", "mid-bind-drain", "mid-close",
)

# mid-bind-drain hash policy: h < _H_BIND_LANDS → bind + mark land;
# h < _H_MARK_LOST → bind lands, applied mark lost in the crash;
# else → the bind never left the dying process.
_H_BIND_LANDS = 0.40
_H_MARK_LOST = 0.60


class SimProcessDead(RuntimeError):
    """A write issued by a scheduler instance the drill has declared
    dead — in reality this instruction would never have executed."""


class SimClusterEndpoint(ClusterAPI):
    """One scheduler instance's connection to the shared cluster.

    Alive: pure delegation. Kill armed: per-operation policy above.
    Finalized (post-failover): every operation refuses — the process
    is gone; reads return empty so stray worker threads drain quietly.
    """

    supports_bind_journal = True

    def __init__(self, inner, seed: int, fault_injector=None):
        self.inner = inner
        self.seed = seed
        # Event-stream fault seam (sim/faults.py): when set, watch
        # handlers registered through this endpoint are wrapped in the
        # injector's delivery interceptor (drop/dup/reorder/stale), and
        # list_for_relist consults its relist-fail seam.
        self.fault_injector = fault_injector
        self._cut: Optional[str] = None
        self._kill_cycle = -1
        self._dead = False
        self._handlers: List = []
        # original handler -> the interceptor wrapper registered for it
        # (remove_watch is handed the original; see add_watch).
        self._wrapped: dict = {}
        # Deterministic forensics for the trace's failover block —
        # byte-compared at replay, and incremented from the cache's
        # CONCURRENT side-effect workers, so the += must be atomic
        # (a lost increment would read as replay divergence).
        self._count_lock = threading.Lock()
        self.binds_refused = 0
        self.marks_dropped = 0

    def _count_refused(self) -> None:
        with self._count_lock:
            self.binds_refused += 1

    def _count_mark_dropped(self) -> None:
        with self._count_lock:
            self.marks_dropped += 1

    # -- drill control -------------------------------------------------------

    def arm_kill(self, cut: str, cycle: int) -> None:
        if cut not in CUT_POINTS:
            raise ValueError(f"unknown leader-kill cut {cut!r}")
        self._cut = cut
        self._kill_cycle = cycle

    def finalize_death(self) -> None:
        """The instance is now fully dead: refuse everything and stop
        observing the cluster (a dead process holds no watch)."""
        self._dead = True
        for handler in self._handlers:
            try:
                self.inner.remove_watch(handler)
            except Exception:  # pragma: no cover - teardown best-effort
                logger.exception("failover watch detach failed")
        self._handlers = []

    # -- policy --------------------------------------------------------------

    def _bind_fate(self, uid: str) -> str:
        """'lands' | 'mark-lost' | 'refused' for one bind of the kill
        cycle (pure hash — see module docstring)."""
        h = _hash01(self.seed, "leader-kill", self._kill_cycle, uid)
        if h < _H_BIND_LANDS:
            return "lands"
        if h < _H_MARK_LOST:
            return "mark-lost"
        return "refused"

    def _refuse(self, what: str):
        raise SimProcessDead(
            f"dead leader (cut={self._cut}) cannot {what}"
        )

    @property
    def _killed(self) -> bool:
        return self._dead or self._cut is not None

    # -- reads / watches -----------------------------------------------------

    def list_objects(self, kind: str) -> list:
        if self._dead:
            return []
        return self.inner.list_objects(kind)

    def list_for_relist(self, kind: str) -> list:
        """The cache's reconcile-read seam: the injector's relist-fail
        fault raises a typed TransientClusterError here — the harness's
        own bookkeeping reads go through list_objects and never see
        it."""
        if self._dead:
            return []
        if self.fault_injector is not None:
            self.fault_injector.on_relist(kind)
        return self.inner.list_objects(kind)

    def current_resource_version(self) -> int:
        return self.inner.current_resource_version()

    def get_pod(self, namespace: str, name: str) -> Optional[Pod]:
        if self._dead:
            return None
        return self.inner.get_pod(namespace, name)

    def add_watch(self, handler: object) -> None:
        registered = handler
        if self.fault_injector is not None:
            registered = self.fault_injector.wrap_watch_handler(handler)
            # remove_watch gets the ORIGINAL handler back; remember
            # which wrapper was registered for it, or the detach would
            # silently match nothing and the watch would keep firing.
            self._wrapped[handler] = registered
        self._handlers.append(registered)
        self.inner.add_watch(registered)

    def remove_watch(self, handler: object) -> None:
        registered = self._wrapped.pop(handler, handler)
        try:
            self._handlers.remove(registered)
        except ValueError:
            pass
        self.inner.remove_watch(registered)

    # -- binds ---------------------------------------------------------------

    def bind_pod(self, pod: Pod, hostname: str) -> None:
        if self._dead or self._cut in ("pre-solve", "post-solve-pre-drain"):
            self._count_refused()
            self._refuse(f"bind {pod.namespace}/{pod.name}")
        if self._cut == "mid-bind-drain":
            if self._bind_fate(pod.uid) == "refused":
                self._count_refused()
                self._refuse(f"bind {pod.namespace}/{pod.name}")
        self.inner.bind_pod(pod, hostname)

    def delete_pod(self, pod: Pod) -> None:
        # Evictions of a killed leader silently never execute (the
        # caller's success/failure branches are both artifacts of a
        # process that no longer exists; its mirror is discarded).
        if self._killed:
            return
        self.inner.delete_pod(pod)

    # -- status writes (dropped at every cut: the process died before
    # its close-phase write-backs could land) --------------------------------

    def update_pod_condition(self, pod: Pod, condition: PodCondition) -> None:
        if self._killed:
            return
        self.inner.update_pod_condition(pod, condition)

    def update_pod_group(self, pg: PodGroup) -> None:
        if self._killed:
            return
        self.inner.update_pod_group(pg)

    def record_event(self, obj: object, event_type: str, reason: str,
                     message: str) -> None:
        if self._killed:
            return  # forensics-only channel; drop quietly
        self.inner.record_event(obj, event_type, reason, message)

    # -- volumes -------------------------------------------------------------

    def assume_pod_volumes(self, pod: Pod, hostname: str) -> bool:
        if self._dead:
            return True
        return self.inner.assume_pod_volumes(pod, hostname)

    def release_pod_volumes(self, pod: Pod) -> None:
        if self._dead:
            return
        self.inner.release_pod_volumes(pod)

    def wait_pod_volumes_bound(self, pod: Pod, timeout: float) -> bool:
        if self._dead:
            return False
        return self.inner.wait_pod_volumes_bound(pod, timeout)

    # -- bind-intent journal -------------------------------------------------

    def append_bind_intent(self, record: dict) -> int:
        # pre-solve dies before dispatch reaches the journal; every
        # other cut dies after the synchronous append landed.
        if self._dead or self._cut == "pre-solve":
            self._refuse("append bind intent")
        return self.inner.append_bind_intent(record)

    def mark_bind_intent(self, seq: int, task_uid: str,
                         outcome: str) -> bool:
        if self._dead or self._cut in (
            "pre-solve", "post-solve-pre-drain"
        ):
            # Dropped, not raised: a dead process's mark simply never
            # executed — the intent stays open for recovery.
            self._count_mark_dropped()
            return False
        if self._cut == "mid-bind-drain":
            fate = self._bind_fate(task_uid)
            if fate == "mark-lost" and outcome == "applied":
                # The bind landed but the process died before the
                # applied mark — recovery must classify from truth.
                self._count_mark_dropped()
                return False
            if fate == "refused":
                # Its bind was refused as dead; the 'failed' mark the
                # side-effect error path now tries to write would never
                # have executed either.
                self._count_mark_dropped()
                return False
        return self.inner.mark_bind_intent(seq, task_uid, outcome)

    def list_bind_intents(self) -> list:
        if self._dead:
            return []
        return self.inner.list_bind_intents()

    def remove_bind_intent(self, seq: int) -> None:
        if self._killed:
            return  # a dead leader prunes nothing
        self.inner.remove_bind_intent(seq)

    # -- leases (delegated; the harness drives takeover explicitly) ----------

    def try_acquire_lease(self, *args: object, **kwargs: object) -> bool:
        if self._killed:
            self._refuse("renew lease")
        return self.inner.try_acquire_lease(*args, **kwargs)

    def release_lease(self, *args: object, **kwargs: object) -> None:
        if self._killed:
            # Process death releases nothing — that is the point: the
            # successor must wait out the TTL.
            return
        return self.inner.release_lease(*args, **kwargs)
