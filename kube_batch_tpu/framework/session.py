"""Session: the per-cycle world view and decision surface.

Mirrors reference framework/session.go (:37 struct, :63 openSession,
:119 closeSession, :146 jobStatus, :194 Pipeline, :237 Allocate,
:294 dispatch, :321 Evict, :361 UpdateJobCondition) and
framework/session_plugins.go (tiered combinator dispatch).

The Session holds a deep-cloned snapshot; Allocate/Pipeline/Evict mutate the
snapshot and fire plugin event handlers; gang dispatch happens the moment a
job becomes Ready (session.go:281-289). This object is also what gets
vectorized into the dense tensor snapshot for the TPU solver (ops.snapshot).
"""

from __future__ import annotations

import logging
import time as _time
import uuid as _uuid
from typing import Callable, Dict, List, Optional

from .. import metrics
from ..api import (
    POD_GROUP_CONDITION_UNSCHEDULABLE,
    JobInfo,
    NodeInfo,
    PodGroupCondition,
    PodGroupPhase,
    QueueInfo,
    TaskInfo,
    TaskStatus,
    ValidateResult,
    allocated_status,
)
from ..conf import Tier
from .event import Event, EventHandler

logger = logging.getLogger(__name__)

# Sub-phase wall times of the most recent allocate_batch (bench/perf
# forensics; the allocate_tpu action folds these into its last_stats).
last_apply_stats: dict = {}


def _move_tasks_logged(job, tasks, status):
    """Bulk status move with the sequential loop's failure semantics: a
    group-level error degrades to per-task moves where each failure is
    logged and skipped instead of aborting the job's whole group."""
    try:
        job.update_tasks_status(tasks, status)
    except Exception:
        for task in tasks:
            try:
                job.update_task_status(task, status)
            except Exception:
                logger.exception(
                    "Failed to move Task %s to %s", task.uid, status
                )


class Session:
    def __init__(self, cache, tiers: Optional[List[Tier]] = None):
        self.uid = str(_uuid.uuid4())
        self.cache = cache
        self.jobs: Dict[str, JobInfo] = {}
        self.nodes: Dict[str, NodeInfo] = {}
        self.queues: Dict[str, QueueInfo] = {}
        self.backlog: List[JobInfo] = []
        self.tiers: List[Tier] = tiers or []

        self.plugins: Dict[str, object] = {}
        self.event_handlers: List[EventHandler] = []
        self.job_order_fns: Dict[str, Callable] = {}
        self.queue_order_fns: Dict[str, Callable] = {}
        self.task_order_fns: Dict[str, Callable] = {}
        self.predicate_fns: Dict[str, Callable] = {}
        self.batch_predicate_fns: Dict[str, Callable] = {}
        self.batch_task_order_key_fns: Dict[str, Callable] = {}
        self.preemptable_fns: Dict[str, Callable] = {}
        self.reclaimable_fns: Dict[str, Callable] = {}
        self.overused_fns: Dict[str, Callable] = {}
        self.job_ready_fns: Dict[str, Callable] = {}
        self.job_pipelined_fns: Dict[str, Callable] = {}
        self.job_valid_fns: Dict[str, Callable] = {}
        self.node_order_fns: Dict[str, List] = {}
        # TPU-solver seams: batched [T, N] score builders, per-queue budget
        # vectors, and weights for the scorers the kernel recomputes per
        # round (consumed by solver/snapshot.py).
        self.batch_node_order_fns: Dict[str, List] = {}
        self.queue_budget_fns: Dict[str, Callable] = {}
        # plugin name -> {scorer key -> weight} for in-kernel scorers
        self.solver_score_weights: Dict[str, Dict[str, float]] = {}

    # ------------------------------------------------------------------ open

    def _open(self) -> None:
        """reference session.go:63-117"""
        snapshot = self.cache.snapshot()
        self.jobs = snapshot.jobs
        self.nodes = snapshot.nodes
        self.queues = snapshot.queues

    def _validate_jobs(self) -> None:
        """Drop invalid jobs, persisting an Unschedulable condition
        (reference session.go:89-108). Called after plugins are opened so
        JobValid callbacks are installed."""
        for job in list(self.jobs.values()):
            vr = self.job_valid(job)
            if vr is not None and not vr.passed:
                cond = PodGroupCondition(
                    type=POD_GROUP_CONDITION_UNSCHEDULABLE,
                    status="True",
                    transition_id=self.uid,
                    reason=vr.reason,
                    message=vr.message,
                )
                try:
                    self.update_job_condition(job, cond)
                except KeyError:
                    logger.exception("failed to update job condition")
                del self.jobs[job.uid]

    def _close(self) -> None:
        """reference session.go:119-144"""
        for job in self.jobs.values():
            if job.pod_group is None:
                self.cache.record_job_status_event(job)
                continue
            job.pod_group.status = self._job_status(job)
            try:
                self.cache.update_job_status(job)
            except Exception:
                logger.exception(
                    "failed to update job <%s/%s>", job.namespace, job.name
                )
        self.jobs = {}
        self.nodes = {}
        self.backlog = []
        self.plugins = {}
        self.event_handlers = []
        self.job_order_fns = {}
        self.queue_order_fns = {}
        self.task_order_fns = {}
        self.predicate_fns = {}
        self.batch_predicate_fns = {}
        self.batch_task_order_key_fns = {}
        self.preemptable_fns = {}
        self.reclaimable_fns = {}
        self.overused_fns = {}
        self.job_ready_fns = {}
        self.job_pipelined_fns = {}
        self.job_valid_fns = {}
        self.node_order_fns = {}
        self.batch_node_order_fns = {}
        self.queue_budget_fns = {}
        self.solver_score_weights = {}

    def _job_status(self, job: JobInfo):
        """Recompute PodGroup status (reference session.go:146-184)."""
        status = job.pod_group.status
        unschedulable = any(
            c.type == POD_GROUP_CONDITION_UNSCHEDULABLE
            and c.status == "True"
            and c.transition_id == self.uid
            for c in status.conditions
        )
        if job.task_status_index.get(TaskStatus.RUNNING) and unschedulable:
            status.phase = PodGroupPhase.UNKNOWN
        else:
            allocated = sum(
                len(tasks)
                for st, tasks in job.task_status_index.items()
                if allocated_status(st)
            )
            if allocated >= job.pod_group.spec.min_member:
                status.phase = PodGroupPhase.RUNNING
            else:
                status.phase = PodGroupPhase.PENDING
        status.running = len(job.task_status_index.get(TaskStatus.RUNNING, {}))
        status.failed = len(job.task_status_index.get(TaskStatus.FAILED, {}))
        status.succeeded = len(job.task_status_index.get(TaskStatus.SUCCEEDED, {}))
        return status

    # ------------------------------------------------------- state mutation

    def statement(self) -> "Statement":
        from .statement import Statement

        return Statement(self)

    def pipeline(self, task: TaskInfo, hostname: str) -> None:
        """Place onto releasing resources, session-only (session.go:194-234)."""
        job = self.jobs.get(task.job)
        if job is None:
            raise KeyError(f"failed to find job {task.job} when pipelining")
        job.update_task_status(task, TaskStatus.PIPELINED)
        task.node_name = hostname
        node = self.nodes.get(hostname)
        if node is None:
            raise KeyError(f"failed to find node {hostname}")
        node.add_task(task)
        for eh in self.event_handlers:
            if eh.allocate_func is not None:
                eh.allocate_func(Event(task))

    def allocate(self, task: TaskInfo, hostname: str) -> None:
        """Allocate in-session; dispatch the whole gang once JobReady
        (reference session.go:237-292)."""
        self.cache.allocate_volumes(task, hostname)
        job = self.jobs.get(task.job)
        if job is None:
            raise KeyError(f"failed to find job {task.job}")
        job.update_task_status(task, TaskStatus.ALLOCATED)
        task.node_name = hostname
        node = self.nodes.get(hostname)
        if node is None:
            raise KeyError(f"failed to find node {hostname}")
        node.add_task(task)
        for eh in self.event_handlers:
            if eh.allocate_func is not None:
                eh.allocate_func(Event(task))
        if self.job_ready(job):
            # Copy: dispatch mutates the Allocated index while we iterate.
            for t in list(
                job.task_status_index.get(TaskStatus.ALLOCATED, {}).values()
            ):
                self.dispatch(t)

    def allocate_batch(self, pairs) -> int:
        """Apply a solved assignment set in one pass: the batched
        equivalent of calling :meth:`allocate` per task, for the
        allocate_tpu apply phase (VERDICT r2: 50k sequential allocate()
        calls dominate the cycle).

        ``pairs`` is ``[(task, hostname), ...]`` in global priority order.
        Semantics preserved vs the sequential loop:

        - per-task volume assumption and node/job bookkeeping, in order;
        - plugin event handlers observe every allocation (batched form
          when the handler provides one, per-event otherwise);
        - gang dispatch: a job whose allocations make it JobReady has ALL
          its Allocated tasks dispatched (sequentially this happens the
          moment the gang crosses minAvailable and then after each later
          allocate — the end state, every Allocated task of a ready job
          dispatched, is identical);
        - per-task failures are logged and skipped, not fatal.

        Returns the number of tasks allocated.

        Thin wrapper: groups the pairs per hostname and delegates to
        :meth:`allocate_batch_grouped` (one implementation of the apply
        tail — events, handlers, gang dispatch — not two to keep in
        sync). allocate_tpu builds the groups itself from the solver's
        arrays and calls the grouped form directly."""
        staged: Dict[str, list] = {}  # hostname -> [tasks]
        for task, hostname in pairs:
            group = staged.get(hostname)
            if group is None:
                group = staged[hostname] = []
            group.append(task)
        return self.allocate_batch_grouped(
            [(hostname, tasks, None) for hostname, tasks in staged.items()]
        )

    def allocate_batch_grouped(self, node_groups) -> int:
        """Apply a solved assignment set from PRE-GROUPED per-node lists
        — the zero-regroup fast path for allocate_tpu, whose fit guard
        already computed the per-node segmentation with numpy.

        ``node_groups`` is ``[(hostname, [tasks], delta)]`` where
        ``delta`` is the group's precomputed aggregate resreq (or None);
        tasks carry no node_name yet. Semantics are
        :meth:`allocate_batch`'s (volumes, status moves, node
        accounting, plugin events, gang dispatch); only the staging
        differs: per-node loops replace the 50k per-task dict passes.
        Returns the number of tasks allocated."""
        last_apply_stats.clear()
        t0 = _time.perf_counter()
        alloc_groups: List[tuple] = []  # (hostname, node, [tasks], delta)
        for hostname, tasks, delta in node_groups:
            node = self.nodes.get(hostname)
            if node is None:
                logger.warning("failed to find node %s", hostname)
                continue
            ok = self.cache.allocate_volumes_batch(tasks, hostname)
            for task in ok:
                task.node_name = hostname
            alloc_groups.append((
                hostname, node, ok, delta if len(ok) == len(tasks) else None
            ))
        # Per-job ALLOCATED moves: group with one argsort-free pass
        # (tasks of one job may span many nodes).
        by_job: Dict[str, list] = {}
        for _, _, tasks, _ in alloc_groups:
            for task in tasks:
                group = by_job.get(task.job)
                if group is None:
                    group = by_job[task.job] = []
                group.append(task)
        jobs_by_uid: Dict[str, JobInfo] = {}
        for uid, group in by_job.items():
            job = self.jobs.get(uid)
            if job is None:
                logger.warning("failed to find job %s", uid)
                continue
            jobs_by_uid[uid] = job
            _move_tasks_logged(job, group, TaskStatus.ALLOCATED)
        t1 = _time.perf_counter()
        last_apply_stats["stage_ms"] = (t1 - t0) * 1e3

        events: List[Event] = []
        for hostname, node, tasks, delta in alloc_groups:
            if delta is not None:
                try:
                    node.add_tasks_prevalidated(tasks, delta)
                    for task in tasks:
                        events.append(Event(task))
                    continue
                except Exception:
                    logger.exception(
                        "prevalidated group rejected by node %s; "
                        "falling back to guarded add", hostname,
                    )
            placed_list = node.add_tasks_with_fallback(tasks)
            for task in placed_list:
                events.append(Event(task))
        t2 = _time.perf_counter()
        last_apply_stats["account_ms"] = (t2 - t1) * 1e3
        if not events:
            return 0
        for eh in self.event_handlers:
            if eh.batch_allocate_func is not None:
                eh.batch_allocate_func(events)
            elif eh.allocate_func is not None:
                for ev in events:
                    eh.allocate_func(ev)
        t3 = _time.perf_counter()
        last_apply_stats["handlers_ms"] = (t3 - t2) * 1e3

        dispatch_groups: List[tuple] = []
        for uid, job in jobs_by_uid.items():
            if self.job_ready(job):
                dispatch_groups.append((job, list(
                    job.task_status_index.get(
                        TaskStatus.ALLOCATED, {}
                    ).values()
                )))
        if dispatch_groups:
            self.dispatch_batch_grouped(dispatch_groups)
        last_apply_stats["dispatch_ms"] = (
            _time.perf_counter() - t3
        ) * 1e3
        return len(events)

    def dispatch_batch_grouped(self, groups) -> None:
        """Bind ready gangs from per-job groups: bulk BINDING moves per
        job (no regrouping pass), one batched metrics observe, one
        bind_batch submission."""
        all_ready: List[TaskInfo] = []
        for job, tasks in groups:
            ready: List[TaskInfo] = []
            for task in tasks:
                # bind_volumes is a no-op for ready-volume tasks (the
                # overwhelming majority: claims-less pods).
                if not task.volume_ready:
                    try:
                        self.cache.bind_volumes(task)
                    except Exception:
                        logger.exception(
                            "Failed to bind volumes of %s", task.uid
                        )
                        continue
                ready.append(task)
            _move_tasks_logged(job, ready, TaskStatus.BINDING)
            all_ready.extend(ready)
        # Latency is measured creation → dispatch (reference
        # session.go:316), so capture `now` here; but observe only the
        # tasks whose cache bookkeeping ACCEPTED the bind (the callback
        # fires from the bookkeeping worker), so validation failures and
        # node-rejected reverts don't inflate scheduled counts.
        now = _time.time()
        self.cache.bind_batch(
            all_ready,
            on_accepted=lambda accepted: (
                metrics.update_task_schedule_durations([
                    max(0.0, now - t.pod.metadata.creation_timestamp)
                    for t in accepted
                ])
            ),
        )

    def dispatch(self, task: TaskInfo) -> None:
        """Bind one gang member (reference session.go:294-318)."""
        self.cache.bind_volumes(task)
        self.cache.bind(task, task.node_name)
        job = self.jobs.get(task.job)
        if job is None:
            raise KeyError(f"failed to find job {task.job}")
        job.update_task_status(task, TaskStatus.BINDING)
        # Time from pod creation to bind (reference session.go:316).
        metrics.update_task_schedule_duration(
            max(0.0, _time.time() - task.pod.metadata.creation_timestamp)
        )

    def dispatch_batch(self, tasks: List[TaskInfo]) -> None:
        """Bind a whole ready gang with one cache round trip (one mutex
        hold, one async side-effect job) instead of per-task dispatch.
        Thin wrapper: groups per job and delegates to
        :meth:`dispatch_batch_grouped`."""
        by_job: Dict[str, list] = {}
        for task in tasks:
            group = by_job.get(task.job)
            if group is None:
                group = by_job[task.job] = []
            group.append(task)
        groups = []
        for uid, group in by_job.items():
            job = self.jobs.get(uid)
            if job is None:
                logger.warning("failed to find job %s", uid)
                continue
            groups.append((job, group))
        if groups:
            self.dispatch_batch_grouped(groups)

    def evict(self, reclaimee: TaskInfo, reason: str) -> None:
        """Direct eviction (reference session.go:321-358)."""
        self.cache.evict(reclaimee, reason)
        job = self.jobs.get(reclaimee.job)
        if job is None:
            raise KeyError(f"failed to find job {reclaimee.job}")
        job.update_task_status(reclaimee, TaskStatus.RELEASING)
        node = self.nodes.get(reclaimee.node_name)
        if node is not None:
            node.update_task(reclaimee)
        for eh in self.event_handlers:
            if eh.deallocate_func is not None:
                eh.deallocate_func(Event(reclaimee))

    def update_job_condition(self, job_info: JobInfo, cond: PodGroupCondition) -> None:
        """reference session.go:361-383"""
        job = self.jobs.get(job_info.uid)
        if job is None:
            raise KeyError(
                f"failed to find job <{job_info.namespace}/{job_info.name}>"
            )
        if job.pod_group is None:
            # Legacy PDB-sourced jobs have no PodGroup to carry conditions
            # (the reference would nil-deref here, session.go:368 — we log
            # instead; the diagnosis still reaches the user via events).
            logger.debug(
                "job <%s/%s> has no PodGroup; dropping condition %s",
                job.namespace, job.name, cond.type,
            )
            return
        for i, c in enumerate(job.pod_group.status.conditions):
            if c.type == cond.type:
                job.pod_group.status.conditions[i] = cond
                return
        job.pod_group.status.conditions.append(cond)

    def add_event_handler(self, eh: EventHandler) -> None:
        self.event_handlers.append(eh)

    # ------------------------------------------- callback registration API

    def add_job_order_fn(self, name, fn):
        self.job_order_fns[name] = fn

    def add_queue_order_fn(self, name, fn):
        self.queue_order_fns[name] = fn

    def add_task_order_fn(self, name, fn):
        self.task_order_fns[name] = fn

    def add_predicate_fn(self, name, fn):
        self.predicate_fns[name] = fn

    def add_batch_predicate_fn(self, name, fn):
        """TPU-native extension: vectorized predicate producing a
        solver BatchMask (or legacy [T,N] bool array) for a whole task
        batch at once (consumed by solver.snapshot)."""
        self.batch_predicate_fns[name] = fn

    def add_batch_task_order_key_fn(self, name, fn):
        """TPU-native extension: (tasks) -> ascending sort-key array
        equivalent to the plugin's task_order_fn, enabling vectorized
        task ordering in the snapshot path."""
        self.batch_task_order_key_fns[name] = fn

    def add_preemptable_fn(self, name, fn):
        self.preemptable_fns[name] = fn

    def add_reclaimable_fn(self, name, fn):
        self.reclaimable_fns[name] = fn

    def add_overused_fn(self, name, fn):
        self.overused_fns[name] = fn

    def add_job_ready_fn(self, name, fn):
        self.job_ready_fns[name] = fn

    def add_job_pipelined_fn(self, name, fn):
        self.job_pipelined_fns[name] = fn

    def add_job_valid_fn(self, name, fn):
        self.job_valid_fns[name] = fn

    def add_node_order_fn(self, name, fn, weight: float = 1.0):
        """Node scorers; (task, node) -> float, higher is better. The
        reference plumbs k8s PriorityConfigs (session_plugins.go:354-369);
        here scorers are plain weighted functions, and plugins may also
        attach a ``batch_fn`` via add_batch_node_order_fn for the TPU path."""
        self.node_order_fns.setdefault(name, []).append((fn, weight))

    def add_batch_node_order_fn(self, name, fn, weight: float = 1.0):
        """Batched scorer: (tasks, nodes) -> np.ndarray [T, N] of 0..10
        scores, summed (weighted) into the solver's static score matrix."""
        self.batch_node_order_fns.setdefault(name, []).append((fn, weight))

    def add_queue_budget_fn(self, name, fn):
        """Queue budget vectors for the solver: (queue) ->
        (deserved: Resource, allocated: Resource) or None if the plugin has
        no opinion (proportion's water-filled shares, proportion.go:100-147)."""
        self.queue_budget_fns[name] = fn

    # ------------------------------------------------- tiered combinators
    # reference framework/session_plugins.go

    def _enabled(self, flag: Optional[bool]) -> bool:
        return bool(flag)

    def reclaimable(self, reclaimer: TaskInfo, reclaimees: List[TaskInfo]):
        """Intersection within a tier; first deciding tier wins
        (session_plugins.go:80-119)."""
        return self._evictable(
            reclaimer, reclaimees, self.reclaimable_fns, "enabled_reclaimable"
        )

    def preemptable(self, preemptor: TaskInfo, preemptees: List[TaskInfo]):
        """session_plugins.go:121-162"""
        return self._evictable(
            preemptor, preemptees, self.preemptable_fns, "enabled_preemptable"
        )

    def _evictable(self, evictor, evictees, fns, flag_attr):
        # Go-nil semantics matter here (session_plugins.go:80-119): a plugin
        # answering "no victims" (nil) poisons every later intersection, and a
        # tier only decides when its running intersection is non-empty.
        victims: Optional[List[TaskInfo]] = None
        init = False
        for tier in self.tiers:
            for plugin in tier.plugins:
                if not self._enabled(getattr(plugin, flag_attr)):
                    continue
                fn = fns.get(plugin.name)
                if fn is None:
                    continue
                candidates = fn(evictor, evictees) or None  # empty → Go nil
                if not init:
                    victims = candidates
                    init = True
                elif victims:
                    cand_uids = {c.uid for c in (candidates or [])}
                    victims = [v for v in victims if v.uid in cand_uids] or None
            if victims is not None:
                return victims
        return victims or []

    def overused(self, queue: QueueInfo) -> bool:
        """Any-true across all tiers (session_plugins.go:164-179)."""
        for tier in self.tiers:
            for plugin in tier.plugins:
                fn = self.overused_fns.get(plugin.name)
                if fn is not None and fn(queue):
                    return True
        return False

    def job_ready(self, obj) -> bool:
        """All-true (session_plugins.go:182-200)."""
        for tier in self.tiers:
            for plugin in tier.plugins:
                if not self._enabled(plugin.enabled_job_ready):
                    continue
                fn = self.job_ready_fns.get(plugin.name)
                if fn is not None and not fn(obj):
                    return False
        return True

    def job_pipelined(self, obj) -> bool:
        """All-true (session_plugins.go:202-221)."""
        for tier in self.tiers:
            for plugin in tier.plugins:
                if not self._enabled(plugin.enabled_job_pipelined):
                    continue
                fn = self.job_pipelined_fns.get(plugin.name)
                if fn is not None and not fn(obj):
                    return False
        return True

    def job_valid(self, obj) -> Optional[ValidateResult]:
        """First failure wins (session_plugins.go:224-240)."""
        for tier in self.tiers:
            for plugin in tier.plugins:
                fn = self.job_valid_fns.get(plugin.name)
                if fn is None:
                    continue
                vr = fn(obj)
                if vr is not None and not vr.passed:
                    return vr
        return None

    def job_order_fn(self, l: JobInfo, r: JobInfo) -> bool:
        """First nonzero comparison; creation-time+UID tiebreak
        (session_plugins.go:243-267)."""
        for tier in self.tiers:
            for plugin in tier.plugins:
                if not self._enabled(plugin.enabled_job_order):
                    continue
                fn = self.job_order_fns.get(plugin.name)
                if fn is None:
                    continue
                j = fn(l, r)
                if j != 0:
                    return j < 0
        if l.creation_timestamp == r.creation_timestamp:
            return l.uid < r.uid
        return l.creation_timestamp < r.creation_timestamp

    def queue_order_fn(self, l: QueueInfo, r: QueueInfo) -> bool:
        """session_plugins.go:270-295"""
        for tier in self.tiers:
            for plugin in tier.plugins:
                if not self._enabled(plugin.enabled_queue_order):
                    continue
                fn = self.queue_order_fns.get(plugin.name)
                if fn is None:
                    continue
                j = fn(l, r)
                if j != 0:
                    return j < 0
        lt = l.queue.metadata.creation_timestamp
        rt = r.queue.metadata.creation_timestamp
        if lt == rt:
            return l.uid < r.uid
        return lt < rt

    def task_compare_fns(self, l: TaskInfo, r: TaskInfo) -> int:
        """session_plugins.go:298-315"""
        for tier in self.tiers:
            for plugin in tier.plugins:
                if not self._enabled(plugin.enabled_task_order):
                    continue
                fn = self.task_order_fns.get(plugin.name)
                if fn is None:
                    continue
                j = fn(l, r)
                if j != 0:
                    return j
        return 0

    def task_order_fn(self, l: TaskInfo, r: TaskInfo) -> bool:
        """session_plugins.go:318-331"""
        res = self.task_compare_fns(l, r)
        if res != 0:
            return res < 0
        lt = l.pod.metadata.creation_timestamp
        rt = r.pod.metadata.creation_timestamp
        if lt == rt:
            return l.uid < r.uid
        return lt < rt

    def predicate_fn(self, task: TaskInfo, node: NodeInfo) -> None:
        """All must pass; raises PredicateError on failure
        (session_plugins.go:334-351)."""
        for tier in self.tiers:
            for plugin in tier.plugins:
                if not self._enabled(plugin.enabled_predicate):
                    continue
                fn = self.predicate_fns.get(plugin.name)
                if fn is None:
                    continue
                fn(task, node)  # raises on failure

    def node_prioritizers(self) -> List:
        """Concat enabled scorers (session_plugins.go:354-369)."""
        configs: List = []
        for tier in self.tiers:
            for plugin in tier.plugins:
                if not self._enabled(plugin.enabled_node_order):
                    continue
                configs.extend(self.node_order_fns.get(plugin.name, []))
        return configs

    # ------------------------------------------- TPU-solver tier gating
    # The batched seams honor the same per-tier enable flags as their
    # scalar counterparts, so allocate and allocate_tpu see identical
    # policy for a given scheduler conf.

    def batch_task_order_keys(self, tasks):
        """List of ascending key arrays (tier order) reproducing
        task_order_fn, or None if an enabled task-order plugin has no
        batch key form (callers then fall back to comparison sorting)."""
        keys: List = []
        for tier in self.tiers:
            for plugin in tier.plugins:
                if not self._enabled(plugin.enabled_task_order):
                    continue
                if self.task_order_fns.get(plugin.name) is None:
                    continue
                kfn = self.batch_task_order_key_fns.get(plugin.name)
                if kfn is None:
                    return None
                keys.append(kfn(tasks))
        return keys

    def batch_predicates(self) -> List:
        """(name, fn) of enabled batched predicates, tier-gated like
        predicate_fn."""
        out: List = []
        for tier in self.tiers:
            for plugin in tier.plugins:
                if not self._enabled(plugin.enabled_predicate):
                    continue
                fn = self.batch_predicate_fns.get(plugin.name)
                if fn is not None:
                    out.append((plugin.name, fn))
        return out

    def scalar_only_predicates(self) -> List:
        """(name, fn) of enabled scalar predicates that have NO batched
        form (fallback path for unported plugins)."""
        out: List = []
        for tier in self.tiers:
            for plugin in tier.plugins:
                if not self._enabled(plugin.enabled_predicate):
                    continue
                if plugin.name in self.batch_predicate_fns:
                    continue
                fn = self.predicate_fns.get(plugin.name)
                if fn is not None:
                    out.append((plugin.name, fn))
        return out

    def batch_node_prioritizers(self) -> List:
        """(fn, weight) of enabled batched scorers, tier-gated like
        node_prioritizers."""
        configs: List = []
        for tier in self.tiers:
            for plugin in tier.plugins:
                if not self._enabled(plugin.enabled_node_order):
                    continue
                configs.extend(self.batch_node_order_fns.get(plugin.name, []))
        return configs

    def solver_dynamic_weights(self) -> Dict[str, float]:
        """Merged in-kernel scorer weights from plugins whose node-order is
        enabled (zeroed otherwise, matching node_prioritizers gating)."""
        merged: Dict[str, float] = {}
        for tier in self.tiers:
            for plugin in tier.plugins:
                if not self._enabled(plugin.enabled_node_order):
                    continue
                for key, w in self.solver_score_weights.get(
                    plugin.name, {}
                ).items():
                    merged[key] = merged.get(key, 0.0) + w
        return merged

    def __repr__(self) -> str:
        return (
            f"Session {self.uid}: jobs={len(self.jobs)}, "
            f"nodes={len(self.nodes)}, queues={len(self.queues)}"
        )
