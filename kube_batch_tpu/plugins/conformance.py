"""Conformance plugin (reference plugins/conformance/conformance.go:40-65):
never evict system-critical PriorityClass pods or kube-system pods during
preempt/reclaim."""

from __future__ import annotations

from ..framework import Plugin, register_plugin_builder

SYSTEM_CLUSTER_CRITICAL = "system-cluster-critical"
SYSTEM_NODE_CRITICAL = "system-node-critical"
KUBE_SYSTEM_NAMESPACE = "kube-system"


class ConformancePlugin(Plugin):
    def __init__(self, arguments=None):
        self.arguments = arguments or {}

    def name(self) -> str:
        return "conformance"

    def on_session_open(self, ssn) -> None:
        def evictable_fn(evictor, evictees):
            victims = []
            for evictee in evictees:
                class_name = evictee.pod.spec.priority_class_name
                if (
                    class_name in (SYSTEM_CLUSTER_CRITICAL, SYSTEM_NODE_CRITICAL)
                    or evictee.namespace == KUBE_SYSTEM_NAMESPACE
                ):
                    continue
                victims.append(evictee)
            return victims

        ssn.add_preemptable_fn(self.name(), evictable_fn)
        ssn.add_reclaimable_fn(self.name(), evictable_fn)


register_plugin_builder("conformance", lambda args: ConformancePlugin(args))
