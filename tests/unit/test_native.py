"""Native greedy baseline (native/greedy.cpp via ctypes).

Parity is asserted against a pure-numpy transcription of the same loop
(per-task sequential best-node scan with LeastRequested+Balanced scores,
epsilon fit, queue Overused gating) — the shared contract both mirror is
the reference allocate loop (allocate.go:43-191)."""

import numpy as np
import pytest

try:
    from kube_batch_tpu.native import greedy_allocate, native_available
    HAVE_NATIVE = native_available()
except Exception:  # pragma: no cover - no toolchain
    HAVE_NATIVE = False

pytestmark = pytest.mark.skipif(
    not HAVE_NATIVE, reason="native toolchain unavailable"
)


def numpy_greedy(task_req, task_queue, node_idle, node_cap, qd, qa, eps,
                 lr_w=1.0, br_w=1.0):
    idle = node_idle.astype(np.float64).copy()
    qalloc = qa.astype(np.float64).copy()
    cap = node_cap.astype(np.float64)
    out = np.full(len(task_req), -1, np.int32)
    for t in range(len(task_req)):
        req = task_req[t].astype(np.float64)
        q = int(task_queue[t])
        if 0 <= q < len(qd) and np.all(qd[q] - qalloc[q] < eps):
            continue
        best, best_s = -1, -1.0
        for n in range(len(idle)):
            if not np.all(req - idle[n] < eps):
                continue
            rem = idle[n] - req
            cm = cap[n][:2]
            safe = np.where(cm > 0, cm, 1.0)
            lr = float(np.mean(
                np.where(cm > 0, np.maximum(rem[:2], 0) * 10.0 / safe, 0.0)
            ))
            frac = np.where(cm > 0, 1.0 - rem[:2] / safe, 1.0)
            br = 0.0 if np.any(frac >= 1.0) else (
                10.0 - abs(frac[0] - frac[1]) * 10.0
            )
            s = lr_w * lr + br_w * br
            if s > best_s:
                best_s, best = s, n
        if best >= 0:
            idle[best] -= req
            if 0 <= q < len(qd):
                qalloc[q] += req
            out[t] = best
    return out


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_native_matches_numpy_reference(seed):
    rng = np.random.RandomState(seed)
    T, N, Q, R = 120, 10, 2, 2
    task_req = np.c_[
        rng.choice([250, 500, 1000, 2000], T),
        rng.choice([256, 1024, 4096], T),
    ].astype(np.float32)
    task_queue = rng.randint(0, Q, T).astype(np.int32)
    node_idle = np.c_[
        rng.choice([4000, 8000, 16000], N), np.full(N, 32768)
    ].astype(np.float32)
    eps = np.asarray([10.0, 10.0], np.float32)
    qd = np.asarray([[20000.0, 0.0], [np.inf, np.inf]], np.float32)
    qa = np.zeros((Q, R), np.float32)

    got, placed = greedy_allocate(
        task_req, task_queue, node_idle, node_idle, qd, qa, eps
    )
    want = numpy_greedy(task_req, task_queue, node_idle, node_idle, qd, qa,
                        eps)
    np.testing.assert_array_equal(got, want)
    assert placed == int((want >= 0).sum())


def test_queue_overused_gates_tasks():
    # Queue 0 already at deserved: its task skipped; queue 1 placed.
    task_req = np.asarray([[100.0, 0.0], [100.0, 0.0]], np.float32)
    task_queue = np.asarray([0, 1], np.int32)
    node_idle = np.asarray([[1000.0, 1e9]], np.float32)
    eps = np.asarray([10.0, 10.0], np.float32)
    qd = np.asarray([[500.0, 0.0], [np.inf, np.inf]], np.float32)
    qa = np.asarray([[500.0, 0.0], [0.0, 0.0]], np.float32)
    out, placed = greedy_allocate(
        task_req, task_queue, node_idle, node_idle, qd, qa, eps
    )
    assert out[0] == -1 and out[1] == 0 and placed == 1


class TestSolveNative:
    """greedy_allocate_masked via solve_native: the production CPU
    fallback consuming the full factorized snapshot (VERDICT r1 item 7)."""

    def _session_inputs(self, n_groups=4, per_group=8, n_nodes=4):
        import kube_batch_tpu.actions  # noqa: F401
        import kube_batch_tpu.plugins  # noqa: F401
        from kube_batch_tpu.api import PodPhase, build_resource_list
        from kube_batch_tpu.framework import open_session
        from kube_batch_tpu.solver import tensorize
        from kube_batch_tpu.utils.test_utils import (
            FakeBinder, FakeEvictor, FakeStatusUpdater, FakeVolumeBinder,
            build_node, build_pod, build_pod_group, build_queue,
        )
        from kube_batch_tpu.cache import SchedulerCache
        from tests.actions.test_actions import DEFAULT_TIERS_ARGS, make_tiers

        cache = SchedulerCache(
            binder=FakeBinder(), evictor=FakeEvictor(),
            status_updater=FakeStatusUpdater(),
            volume_binder=FakeVolumeBinder(),
        )
        cache.add_queue(build_queue("q0", weight=1))
        for j in range(n_nodes):
            cache.add_node(build_node(
                f"n{j}", build_resource_list(cpu="8", memory="32Gi", pods=110)
            ))
        for g in range(n_groups):
            cache.add_pod_group(build_pod_group(
                f"pg{g}", namespace="ns", min_member=1, queue="q0"
            ))
            for i in range(per_group):
                cache.add_pod(build_pod(
                    "ns", f"pg{g}-p{i}", "", PodPhase.PENDING,
                    build_resource_list(cpu="500m", memory="512Mi"),
                    group_name=f"pg{g}",
                ))
        ssn = open_session(cache, make_tiers(*DEFAULT_TIERS_ARGS))
        inputs, ctx = tensorize(ssn)
        return ssn, inputs, ctx

    def test_native_respects_capacity_and_mask(self):
        from kube_batch_tpu.native import solve_native

        ssn, inputs, ctx = self._session_inputs()
        assigned, placed = solve_native(inputs)
        T, N = len(ctx.tasks), len(ctx.nodes)
        # Padded rows never receive assignments; real rows only go to
        # real, feasible nodes.
        assert (assigned[T:] == -1).all()
        s = inputs.unpack()
        req = np.asarray(s.task_req)
        idle0 = np.asarray(s.node_idle)
        eps = np.asarray(s.eps)
        used = np.zeros_like(idle0)
        for i in range(T):
            j = int(assigned[i])
            if j < 0:
                continue
            assert j < N
            assert ctx.mask.row(i)[j]
            used[j] += req[i]
        assert np.all(used - idle0 < eps[None, :] + 1e-3)
        # Uncontended cluster (32 cpu vs 16 requested): everything places.
        assert placed == T

    def test_native_matches_jax_solver_placement_count(self):
        from kube_batch_tpu.native import solve_native
        from kube_batch_tpu.solver import solve_jit

        ssn, inputs, ctx = self._session_inputs(
            n_groups=3, per_group=10, n_nodes=2
        )
        native_assigned, native_placed = solve_native(inputs)
        jax_assigned = np.asarray(solve_jit(inputs).assigned)
        # Different algorithms (sequential greedy vs round auction) may
        # pick different nodes, but on a uniform-request instance the
        # placement count is determined by capacity alone.
        assert native_placed == int((jax_assigned >= 0).sum())

    def test_allocate_tpu_native_route_end_to_end(self, monkeypatch):
        """KBT_SOLVER=native drives the whole action through greedy.cpp;
        outcomes must match the pure-greedy action's bind count."""
        import kube_batch_tpu.actions  # noqa: F401
        import kube_batch_tpu.plugins  # noqa: F401
        from kube_batch_tpu.api import PodPhase, build_resource_list
        from kube_batch_tpu.utils.test_utils import (
            build_node, build_pod, build_pod_group, build_queue,
        )
        from tests.actions.test_actions import drain, make_cache, run_action

        def cluster():
            c = make_cache()
            c.add_queue(build_queue("default"))
            c.add_pod_group(build_pod_group(
                "pg1", namespace="ns", min_member=3
            ))
            for i in range(5):
                c.add_pod(build_pod(
                    "ns", f"p{i}", "", PodPhase.PENDING,
                    build_resource_list(cpu="1", memory="1Gi"),
                    group_name="pg1",
                ))
            c.add_node(build_node(
                "n1", build_resource_list(cpu="4", memory="8Gi", pods=110)
            ))
            c.add_node(build_node(
                "n2", build_resource_list(cpu="2", memory="4Gi", pods=110)
            ))
            return c

        monkeypatch.setenv("KBT_SOLVER", "native")
        c_native = cluster()
        run_action(c_native, "allocate_tpu")
        # Binds apply asynchronously (cache.bind fires the Binder on a
        # worker thread): drain the channel, don't peek at the dict.
        assert len(drain(c_native.binder.channel, 5)) == 5
        monkeypatch.setenv("KBT_SOLVER", "jax")
        c_jax = cluster()
        run_action(c_jax, "allocate_tpu")
        assert len(drain(c_jax.binder.channel, 5)) == 5


def numpy_masked(task_req, task_fit, task_queue, task_job, task_valid,
                 task_group, node_feas, group_feas, pair_idx, pair_feas,
                 score_idx, score_rows, node_idle, node_cap, ntask0,
                 max_tasks, qd, qa, eps, lr_w=1.0, br_w=1.0):
    """Pure-numpy transcription of greedy_allocate_masked's scan semantics
    (the contract the heap fast path must reproduce exactly)."""
    idle = node_idle.astype(np.float64).copy()
    qalloc = qa.astype(np.float64).copy()
    ntask = ntask0.astype(np.int64).copy()
    cap = node_cap.astype(np.float64)
    T, N = len(task_req), len(node_idle)
    out = np.full(T, -1, np.int32)
    job_failed = np.zeros(T, bool)
    pair_map = {int(i): k for k, i in enumerate(pair_idx)}
    score_map = {int(i): k for k, i in enumerate(score_idx)}
    for t in range(T):
        if not task_valid[t]:
            continue
        j = int(task_job[t])
        if 0 <= j < T and job_failed[j]:
            continue
        req = task_req[t].astype(np.float64)
        fit = task_fit[t].astype(np.float64)
        q = int(task_queue[t])
        if 0 <= q < len(qd) and np.all(qd[q] - qalloc[q] < eps):
            continue
        grow = group_feas[task_group[t]] if 0 <= task_group[t] < len(group_feas) else None
        prow = pair_feas[pair_map[t]] if t in pair_map else None
        srow = score_rows[score_map[t]] if t in score_map else None
        best, best_s, any_feas = -1, -1.0e300, False
        for n in range(N):
            if not node_feas[n]:
                continue
            if grow is not None and not grow[n]:
                continue
            if prow is not None and not prow[n]:
                continue
            if max_tasks[n] > 0 and ntask[n] >= max_tasks[n]:
                continue
            any_feas = True
            if not np.all(fit - idle[n] < eps):
                continue
            rem = idle[n] - req
            cm = cap[n][:2]
            safe = np.where(cm > 0, cm, 1.0)
            lr = float(np.mean(
                np.where(cm > 0, np.maximum(rem[:2], 0) * 10.0 / safe, 0.0)
            ))
            frac = np.where(cm > 0, 1.0 - rem[:2] / safe, 1.0)
            br = 0.0 if np.any(frac >= 1.0) else (
                10.0 - abs(frac[0] - frac[1]) * 10.0
            )
            s = lr_w * lr + br_w * br
            if srow is not None:
                s += float(srow[n])
            if s > best_s:
                best_s, best = s, n
        if best < 0:
            if not any_feas and 0 <= j < T:
                job_failed[j] = True
            continue
        idle[best] -= req
        ntask[best] += 1
        if 0 <= q < len(qd):
            qalloc[q] += req
        out[t] = best
    return out


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_masked_heap_path_matches_scan_semantics(seed):
    """Randomized exact-parity: signature classes big enough to take the
    heap fast path must produce byte-identical assignments to the
    sequential scan transcription (same argmax, same job-break)."""
    import ctypes

    from kube_batch_tpu.native.greedy import _load
    lib = _load()
    lib.greedy_set_heap_threshold.argtypes = [ctypes.c_int64]
    lib.greedy_set_heap_threshold(0)  # force the heap path on small shapes
    try:
        _run_masked_parity(lib, seed)
    finally:
        lib.greedy_set_heap_threshold(1 << 20)


def _run_masked_parity(lib, seed):
    rng = np.random.RandomState(seed)
    T, N, Q, R, G = 160, 12, 3, 2, 2
    # few distinct requests -> large signature classes (heap path active)
    reqs = np.asarray([[500, 512], [1000, 1024], [2000, 2048]], np.float32)
    pick = rng.randint(0, 3, T)
    task_req = reqs[pick]
    task_fit = task_req.copy()
    # a few tasks fit-check a larger footprint (init containers)
    grow_fit = rng.rand(T) < 0.1
    task_fit[grow_fit] *= 1.5
    task_queue = rng.randint(0, Q, T).astype(np.int32)
    task_job = (np.arange(T, dtype=np.int32) // 8)  # 8-task gangs
    task_valid = np.ones(T, np.uint8)
    task_valid[rng.rand(T) < 0.05] = 0
    task_group = rng.randint(0, G, T).astype(np.int32)
    node_feas = (rng.rand(N) > 0.1).astype(np.uint8)
    group_feas = (rng.rand(G, N) > 0.2).astype(np.uint8)
    # sparse private predicate rows on ~6% of tasks (ascending idx)
    pidx = np.sort(rng.choice(T, size=max(1, T // 16), replace=False))
    pair_idx = pidx.astype(np.int32)
    pair_feas = (rng.rand(len(pidx), N) > 0.3).astype(np.uint8)
    # sparse static score rows on a few tasks
    sidx = np.sort(rng.choice(T, size=4, replace=False))
    score_idx = sidx.astype(np.int32)
    score_rows = rng.rand(4, N).astype(np.float32) * 5.0
    node_idle = np.c_[
        rng.choice([4000, 8000, 16000], N), rng.choice([8192, 32768], N)
    ].astype(np.float32)
    node_cap = node_idle.copy()
    ntask0 = np.zeros(N, np.int32)
    max_tasks = rng.choice([0, 3, 8], N).astype(np.int32)
    qd = np.full((Q, R), np.inf, np.float32)
    qd[0] = [6000.0, 999999.0]  # queue 0 budget-capped
    qa = np.zeros((Q, R), np.float32)
    eps = np.asarray([10.0, 10.0], np.float32)

    out = np.empty(T, np.int32)
    placed = lib.greedy_allocate_masked(
        np.ascontiguousarray(task_req), np.ascontiguousarray(task_fit),
        task_queue, task_job, task_valid, task_group,
        node_feas, np.ascontiguousarray(group_feas),
        pair_idx, np.ascontiguousarray(pair_feas),
        score_idx, np.ascontiguousarray(score_rows),
        np.ascontiguousarray(node_idle), np.ascontiguousarray(node_cap),
        ntask0, max_tasks, qd, qa, eps, 1.0, 1.0,
        T, N, Q, R, G, len(pair_idx), len(score_idx), out,
    )
    want = numpy_masked(
        task_req, task_fit, task_queue, task_job, task_valid, task_group,
        node_feas, group_feas, pair_idx, pair_feas, score_idx, score_rows,
        node_idle, node_cap, ntask0, max_tasks, qd, qa, eps,
    )
    np.testing.assert_array_equal(out, want)
    assert placed == int((want >= 0).sum())


class TestWedgedBackendProtection:
    """VERDICT r2 weak #4: the scheduling loop must complete even on a
    host where jax backend resolution would hang forever. The guarded
    gateway (utils.backend.ensure_live_backend) must route allocate_tpu
    to the native solver WITHOUT any cold in-process jax call."""

    def test_run_once_completes_with_wedged_backend(self, monkeypatch):
        import kube_batch_tpu.actions  # noqa: F401
        import kube_batch_tpu.plugins  # noqa: F401
        from kube_batch_tpu.actions import allocate_tpu as atpu
        from kube_batch_tpu.utils import backend
        from kube_batch_tpu.api import PodPhase, build_resource_list
        from kube_batch_tpu.utils.test_utils import (
            build_node, build_pod, build_pod_group, build_queue,
        )
        from tests.actions.test_actions import drain, make_cache, run_action

        # Simulate the wedged host: no backend initialized yet, bounded
        # probe finds nothing, and any attempt at *cold* in-process
        # resolution is an error (the real thing would hang forever).
        monkeypatch.delenv("KBT_SOLVER", raising=False)
        monkeypatch.setattr(backend, "_live_backend_devices", None)
        monkeypatch.setattr(backend, "initialized_device_count", lambda: 0)
        monkeypatch.setattr(
            backend, "probe_default_backend",
            lambda **kw: 0,
        )
        forced = {}
        monkeypatch.setattr(
            backend, "force_cpu_devices",
            lambda n: forced.setdefault("n", n) or True,
        )

        c = make_cache()
        c.add_queue(build_queue("default"))
        c.add_pod_group(build_pod_group("pg1", namespace="ns", min_member=2))
        for i in range(2):
            c.add_pod(build_pod(
                "ns", f"p{i}", "", PodPhase.PENDING,
                build_resource_list(cpu="1", memory="1Gi"),
                group_name="pg1",
            ))
        c.add_node(build_node(
            "n1", build_resource_list(cpu="4", memory="8Gi", pods=110)
        ))
        run_action(c, "allocate_tpu")
        assert len(drain(c.binder.channel, 2)) == 2
        # the wedged path forced CPU and routed native
        assert forced == {"n": 1}
        assert atpu.last_stats["backend"] == "native"
        # memoized: the probe is not re-paid next cycle
        assert backend._live_backend_devices is not None
