from .gc_guard import deferred_gc
from .priority_queue import PriorityQueue
