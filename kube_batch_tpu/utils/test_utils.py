"""Builders + fake side-effect seams for cluster-less tests.

Mirrors reference pkg/scheduler/util/test_utils.go:
- BuildNode/BuildPod/BuildResourceList builders (:33-91).
- FakeBinder/FakeEvictor record calls into maps + channels (:95-133);
  FakeStatusUpdater/FakeVolumeBinder no-op (:136-163).
Used by both the test suite and the synthetic benchmark generators.
"""

from __future__ import annotations

import queue
import threading
from typing import Dict, List, Optional

from ..api import (
    GROUP_NAME_ANNOTATION_KEY,
    Container,
    Node,
    NodeSpec,
    NodeStatus,
    ObjectMeta,
    Pod,
    PodGroup,
    PodGroupSpec,
    PodSpec,
    PodStatus,
    Queue,
    QueueSpec,
    ResourceList,
)


def build_node(
    name: str,
    alloc: ResourceList,
    labels: Optional[Dict[str, str]] = None,
    capacity: Optional[ResourceList] = None,
) -> Node:
    """reference test_utils.go:33-46"""
    return Node(
        metadata=ObjectMeta(name=name, labels=dict(labels or {})),
        spec=NodeSpec(),
        status=NodeStatus(allocatable=dict(alloc), capacity=dict(capacity or alloc)),
    )


def build_pod(
    namespace: str,
    name: str,
    node_name: str,
    phase: str,
    req: ResourceList,
    group_name: str = "",
    labels: Optional[Dict[str, str]] = None,
    selector: Optional[Dict[str, str]] = None,
    priority: Optional[int] = None,
    owner_uid: str = "",
) -> Pod:
    """reference test_utils.go:49-81"""
    annotations = {}
    if group_name:
        annotations[GROUP_NAME_ANNOTATION_KEY] = group_name
    return Pod(
        metadata=ObjectMeta(
            name=name,
            namespace=namespace,
            uid=f"{namespace}-{name}",
            labels=dict(labels or {}),
            annotations=annotations,
            owner_uid=owner_uid,
        ),
        spec=PodSpec(
            node_name=node_name,
            node_selector=dict(selector or {}),
            containers=[Container(requests=dict(req))],
            priority=priority,
        ),
        status=PodStatus(phase=phase),
    )


def build_pod_group(
    name: str,
    namespace: str = "default",
    min_member: int = 1,
    queue: str = "default",
    priority_class_name: str = "",
) -> PodGroup:
    return PodGroup(
        metadata=ObjectMeta(name=name, namespace=namespace),
        spec=PodGroupSpec(
            min_member=min_member,
            queue=queue,
            priority_class_name=priority_class_name,
        ),
    )


def build_queue(name: str, weight: int = 1, capability=None) -> Queue:
    return Queue(
        metadata=ObjectMeta(name=name, namespace=""),
        spec=QueueSpec(weight=weight, capability=capability),
    )


class FakeBinder:
    """Records binds (reference test_utils.go:95-114)."""

    def __init__(self):
        self.binds: Dict[str, str] = {}
        self.channel: "queue.Queue[str]" = queue.Queue()
        self._lock = threading.Lock()

    def bind(self, pod: Pod, hostname: str) -> None:
        with self._lock:
            key = f"{pod.namespace}/{pod.name}"
            self.binds[key] = hostname
            self.channel.put(key)


class FakeEvictor:
    """Records evictions (reference test_utils.go:117-133)."""

    def __init__(self):
        self.evicts: List[str] = []
        self.channel: "queue.Queue[str]" = queue.Queue()
        self._lock = threading.Lock()

    def evict(self, pod: Pod) -> None:
        with self._lock:
            key = f"{pod.namespace}/{pod.name}"
            self.evicts.append(key)
            self.channel.put(key)


class FakeStatusUpdater:
    """No-op (reference test_utils.go:136-147)."""

    def update_pod_condition(self, pod: Pod, condition) -> None:
        return None

    def update_pod_group(self, pg: PodGroup) -> None:
        return None


class FakeVolumeBinder:
    """No-op (reference test_utils.go:150-163). Marks volumes ready like
    DefaultVolumeBinder's no-cluster behavior, so fakes exercise the same
    fast bind path production takes for claims-less pods."""

    def allocate_volumes(self, task, hostname: str) -> None:
        task.volume_ready = True

    def bind_volumes(self, task) -> None:
        return None

    def release_volumes(self, task) -> None:
        return None
