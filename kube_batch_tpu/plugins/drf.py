"""DRF plugin: Dominant Resource Fairness across jobs.

Mirrors reference plugins/drf/drf.go:
- Per-job share = max over resources of allocated/clusterTotal (:161-172).
- PreemptableFn: victim ok if preemptor's post-transfer share stays below (or
  within shareDelta of) the victim's (:85-108).
- JobOrderFn: lower share first (:115-132).
- Event handlers keep allocated+share incrementally updated (:137-157).
"""

from __future__ import annotations

import os
from typing import Dict

from ..api import JobInfo, Resource, share as share_fn
from ..framework import EventHandler, Plugin, register_plugin_builder


def _total_key(total: Resource):
    """Hashable identity of the cluster capacity a fold was computed
    against — shares are ratios, so any capacity move invalidates
    every cached share at once."""
    return (
        total.milli_cpu, total.memory,
        tuple(sorted((total.scalar_resources or {}).items())),
    )


def fold_reuse_enabled(cache) -> bool:
    """Cross-session plugin fold reuse (KBT_FOLD_REUSE, default on):
    requires the real scheduler cache's ``plugin_fold`` store."""
    return (
        getattr(cache, "plugin_fold", None) is not None
        and os.environ.get("KBT_FOLD_REUSE", "1") != "0"
    )

SHARE_DELTA = 0.000001  # reference drf.go:29


class _DrfAttr:
    __slots__ = ("allocated", "share")

    def __init__(self):
        self.allocated = Resource.empty()
        self.share = 0.0


class DrfPlugin(Plugin):
    def __init__(self, arguments=None):
        self.arguments = arguments or {}
        self.total_resource = Resource.empty()
        self.job_attrs: Dict[str, _DrfAttr] = {}

    def name(self) -> str:
        return "drf"

    def _calculate_share(self, allocated: Resource, total: Resource) -> float:
        res = 0.0
        for rn in total.resource_names():
            s = share_fn(allocated.get(rn), total.get(rn))
            if s > res:
                res = s
        return res

    def _update_share(self, attr: _DrfAttr) -> None:
        attr.share = self._calculate_share(attr.allocated, self.total_resource)

    def on_session_open(self, ssn) -> None:
        # Shared per-session aggregate (one O(nodes) pass for all
        # plugins, not one each).
        self.total_resource = ssn.total_node_allocatable()

        # Bulk share computation: one numpy pass over the cpu/mem
        # columns instead of a per-job resource_names walk (the per-job
        # form was a measurable slice of every steady-cycle open at
        # 500+ jobs). Scalar resources — rare — fold in per name.
        # Semantics identical to _calculate_share/share_fn: r == 0 →
        # 1.0 if l > 0 else 0.0.
        import numpy as np

        jobs = list(ssn.jobs.values())
        total = self.total_resource
        total_key = _total_key(total)

        # Cross-session fold reuse: an unchanged job keeps its snapshot
        # clone (same identity, same _ver — any mutation rides a _ver
        # bump and re-clones), so the _DrfAttr minted for it last open
        # — the share AND the allocated clone the event handlers fold
        # into — is still exact and is reused wholesale. Steady-state
        # micro opens then pay share math only for the churned jobs.
        store = (
            ssn.cache.plugin_fold if fold_reuse_enabled(ssn.cache) else None
        )
        cached = store.get("drf") if store is not None else None
        if cached is not None and cached["total"] != total_key:
            cached = None  # capacity moved: every cached share is stale
        prev: Dict[str, tuple] = (
            cached["entries"] if cached is not None else {}
        )
        miss = []
        for job in jobs:
            ent = prev.get(job.uid)
            if ent is not None and ent[0] is job and ent[1] == job._ver:
                self.job_attrs[job.uid] = ent[2]
            else:
                miss.append(job)

        M = len(miss)
        share = np.zeros(M, dtype=np.float64)

        def fold(vals, cap):
            nonlocal share
            if cap == 0:
                np.maximum(share, (vals > 0).astype(np.float64), out=share)
            else:
                np.maximum(share, vals / cap, out=share)

        if M:
            fold(
                np.fromiter(
                    (j.allocated.milli_cpu for j in miss), np.float64,
                    count=M,
                ),
                total.milli_cpu,
            )
            fold(
                np.fromiter(
                    (j.allocated.memory for j in miss), np.float64, count=M
                ),
                total.memory,
            )
            for name in (total.scalar_resources or ()):
                fold(
                    np.fromiter(
                        (
                            (j.allocated.scalar_resources or {}).get(name, 0.0)
                            for j in miss
                        ),
                        np.float64, count=M,
                    ),
                    total.scalar_resources[name],
                )
        shares = share.tolist()
        for i, job in enumerate(miss):
            attr = _DrfAttr()
            # JobInfo.allocated IS the sum of allocated-status task
            # resreqs (maintained by add/delete/update_task_status), so
            # re-summing 50k tasks per cycle (drf.go:66-73's per-task
            # walk) collapses to one aggregate clone per job.
            attr.allocated = job.allocated.clone()
            attr.share = shares[i]
            self.job_attrs[job.uid] = attr
            prev[job.uid] = (job, job._ver, attr)
        if store is not None:
            if len(prev) > len(jobs) + 1024:
                # Deleted jobs leave inert entries behind (a reused uid
                # misses on clone identity); bound the store instead of
                # paying a live-set walk every open.
                prev = {
                    uid: prev[uid] for uid in self.job_attrs
                    if uid in prev
                }
            store["drf"] = {"total": total_key, "entries": prev}

        def preemptable_fn(preemptor, preemptees):
            victims = []
            latt = self.job_attrs[preemptor.job]
            lalloc = latt.allocated.clone().add(preemptor.resreq)
            ls = self._calculate_share(lalloc, self.total_resource)
            allocations: Dict[str, Resource] = {}
            for preemptee in preemptees:
                if preemptee.job not in allocations:
                    allocations[preemptee.job] = self.job_attrs[
                        preemptee.job
                    ].allocated.clone()
                ralloc = allocations[preemptee.job].sub(preemptee.resreq)
                rs = self._calculate_share(ralloc, self.total_resource)
                if ls < rs or abs(ls - rs) <= SHARE_DELTA:
                    victims.append(preemptee)
            return victims

        ssn.add_preemptable_fn(self.name(), preemptable_fn)

        def job_order_fn(l: JobInfo, r: JobInfo) -> int:
            ls, rs = self.job_attrs[l.uid].share, self.job_attrs[r.uid].share
            if ls == rs:
                return 0
            return -1 if ls < rs else 1

        ssn.add_job_order_fn(self.name(), job_order_fn)

        def batch_job_order_key(jobs):
            import numpy as np

            # Ascending key ≡ job_order_fn: lower share first.
            return np.asarray(
                [self.job_attrs[j.uid].share for j in jobs], np.float64
            )

        ssn.add_batch_job_order_key_fn(self.name(), batch_job_order_key)

        def on_allocate(event):
            attr = self.job_attrs[event.task.job]
            attr.allocated.add(event.task.resreq)
            self._update_share(attr)

        def on_deallocate(event):
            attr = self.job_attrs[event.task.job]
            attr.allocated.sub(event.task.resreq)
            self._update_share(attr)

        def on_allocate_batch(batches):
            # Aggregate fold of on_allocate: the share math is
            # associative over a batch, so each JobBatchEvent costs ONE
            # Resource add + one share update — ~#jobs work for a
            # 50k-task apply instead of 50k per-task handler calls
            # (drf.go:137-157's per-event form).
            for b in batches:
                attr = self.job_attrs[b.job.uid]
                attr.allocated.add(b.delta)
                self._update_share(attr)

        def on_evict_batch(batches):
            # Aggregate fold of on_deallocate (exact: deltas are sums
            # of integral milli/byte quantities).
            for b in batches:
                attr = self.job_attrs[b.job.uid]
                attr.allocated.sub(b.delta)
                self._update_share(attr)

        ssn.add_event_handler(
            EventHandler(
                allocate_func=on_allocate,
                deallocate_func=on_deallocate,
                batch_allocate_func=on_allocate_batch,
                batch_deallocate_func=on_evict_batch,
            )
        )

    def on_session_close(self, ssn) -> None:
        self.total_resource = Resource.empty()
        self.job_attrs = {}


register_plugin_builder("drf", lambda args: DrfPlugin(args))
