"""Pass 3: jit hygiene inside traced code.

Finds functions compiled by ``jax.jit`` / ``shard_map`` — via
decorator (``@jax.jit``, ``@functools.partial(jax.jit, ...)``) or
registration (``X = jax.jit(fn, ...)``, ``shard_map(fn, ...)``) — and
taint-checks their bodies (plus same-module helpers they call, with
call-site-accurate parameter taint):

- **python branching on traced values**: ``if``/``while``/``assert``/
  ``for`` over a tainted expression raises TracerBoolConversionError
  at trace time on the lucky path and silently bakes in one branch on
  the unlucky one (a value that happens to be concrete under
  ``interpret=True`` testing, traced in production);
- **host syncs**: ``np.asarray``/``np.array``/``float``/``int``/
  ``bool`` on traced values, ``.item()``/``.tolist()``/
  ``.block_until_ready()``/``jax.device_get`` — a device→host block
  point inside the program defeats the async dispatch the cycle
  overlap window depends on;
- **donated-buffer reuse**: a caller passing a buffer into a
  module-level jit registered with ``donate_argnums`` and then
  reading the same variable afterwards — the donated buffer's memory
  may already be aliased by the output.

Static arguments (``static_argnames``) are untainted; so are shape/
dtype/ndim/size attribute reads, ``len``/``isinstance``/``type`` and
``is``/``is not`` comparisons — branching on those is exactly how
shape-polymorphic jit code is SUPPOSED to branch.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .core import (
    Finding,
    Project,
    ProjectFile,
    attr_chain,
    call_name,
    register_pass,
)

PASS_ID = "jit-hygiene"

STATIC_ATTRS = frozenset({"shape", "dtype", "ndim", "size", "sharding"})
STATIC_CALLS = frozenset({"isinstance", "len", "type", "issubclass",
                          "hasattr", "callable", "range", "enumerate",
                          "zip"})
HOST_CONVERSIONS = frozenset({"float", "int", "bool", "complex"})
HOST_METHODS = frozenset({"item", "tolist", "block_until_ready"})
HOST_NP_FUNCS = frozenset({"asarray", "array", "copy", "save", "savez"})
MAX_HELPER_DEPTH = 4


@dataclass
class JitRoot:
    func: ast.AST
    rel: str
    name: str
    static_names: Set[str]
    donate_argnums: Tuple[int, ...] = ()
    registered_as: Optional[str] = None  # module-level jitted name


def _const_str_tuple(node: ast.AST) -> Set[str]:
    out: Set[str] = set()
    if isinstance(node, (ast.Tuple, ast.List)):
        for elt in node.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
                out.add(elt.value)
    elif isinstance(node, ast.Constant) and isinstance(node.value, str):
        out.add(node.value)
    return out


def _const_int_tuple(node: ast.AST) -> Tuple[int, ...]:
    out: List[int] = []
    if isinstance(node, (ast.Tuple, ast.List)):
        for elt in node.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, int):
                out.append(elt.value)
    elif isinstance(node, ast.Constant) and isinstance(node.value, int):
        out.append(node.value)
    return tuple(out)


def _is_jit_callable(node: ast.AST) -> bool:
    """``jax.jit`` / ``jit`` / ``shard_map`` reference."""
    chain = attr_chain(node)
    if chain is None:
        return False
    return chain[-1] in ("jit", "shard_map")


def _jit_call_statics(call: ast.Call) -> Tuple[Set[str], Tuple[int, ...]]:
    statics: Set[str] = set()
    donate: Tuple[int, ...] = ()
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            statics |= _const_str_tuple(kw.value)
        elif kw.arg == "donate_argnums":
            donate = _const_int_tuple(kw.value)
    return statics, donate


def _collect_roots(pf: ProjectFile) -> Tuple[List[JitRoot], Dict[str, JitRoot]]:
    """Jit roots in one module + {module-level jitted name: root} for
    the donated-reuse call-site check."""
    defs: Dict[str, ast.AST] = {}
    for node in ast.walk(pf.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs[node.name] = node

    roots: List[JitRoot] = []
    registered: Dict[str, JitRoot] = {}
    seen: Set[int] = set()

    def add_root(func_node, statics, donate, registered_as=None):
        root = JitRoot(
            func=func_node, rel=pf.rel, name=func_node.name,
            static_names=statics, donate_argnums=donate,
            registered_as=registered_as,
        )
        if registered_as:
            # The generic jax.jit(fn) walk may have claimed the body
            # already — the NAME binding (and its donate_argnums) must
            # still register for the call-site reuse check.
            registered[registered_as] = root
        if id(func_node) in seen:
            return
        seen.add(id(func_node))
        roots.append(root)

    # Decorated: @jax.jit / @functools.partial(jax.jit, ...)
    for node in ast.walk(pf.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for deco in node.decorator_list:
            if _is_jit_callable(deco):
                add_root(node, set(), ())
            elif isinstance(deco, ast.Call):
                if _is_jit_callable(deco.func):
                    statics, donate = _jit_call_statics(deco)
                    add_root(node, statics, donate)
                elif call_name(deco) == "partial" and deco.args and \
                        _is_jit_callable(deco.args[0]):
                    statics, donate = _jit_call_statics(deco)
                    add_root(node, statics, donate)

    # Registered: X = jax.jit(fn, ...) / jax.jit(fn, ...) anywhere /
    # shard_map(fn, mesh, ...).
    for node in ast.walk(pf.tree):
        if not isinstance(node, ast.Call) or not _is_jit_callable(node.func):
            continue
        if not node.args:
            continue
        target = node.args[0]
        if isinstance(target, ast.Name) and target.id in defs:
            statics, donate = _jit_call_statics(node)
            add_root(defs[target.id], statics, donate)

    # Names bound at module level to a jit call (donated-reuse check).
    for node in pf.tree.body:
        if (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
            and isinstance(node.value, ast.Call)
            and _is_jit_callable(node.value.func)
            and node.value.args
            and isinstance(node.value.args[0], ast.Name)
            and node.value.args[0].id in defs
        ):
            statics, donate = _jit_call_statics(node.value)
            add_root(defs[node.value.args[0].id], statics, donate,
                     registered_as=node.targets[0].id)

    return roots, registered


class _TaintChecker:
    """Per-function taint walk. One instance per (function, taint
    signature); helper calls recurse with call-site arg taint."""

    def __init__(self, pf: ProjectFile, defs: Dict[str, ast.AST],
                 findings: List[Finding],
                 memo: Dict[Tuple[int, frozenset], bool],
                 depth: int):
        self.pf = pf
        self.defs = defs
        self.findings = findings
        self.memo = memo
        self.depth = depth
        self.tainted: Set[str] = set()
        self.returns_tainted = False

    # -- taint of expressions ------------------------------------------------

    def expr_tainted(self, node: ast.AST) -> bool:
        if isinstance(node, ast.Name):
            return node.id in self.tainted
        if isinstance(node, ast.Constant):
            return False
        if isinstance(node, ast.Attribute):
            if node.attr in STATIC_ATTRS:
                return False
            return self.expr_tainted(node.value)
        if isinstance(node, ast.Compare):
            if all(isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops):
                return False
            return self.expr_tainted(node.left) or any(
                self.expr_tainted(c) for c in node.comparators
            )
        if isinstance(node, ast.Call):
            return self.call_tainted(node)
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            return any(self.expr_tainted(e) for e in node.elts)
        if isinstance(node, ast.Dict):
            return any(
                self.expr_tainted(v)
                for v in list(node.keys) + list(node.values)
                if v is not None
            )
        if isinstance(node, ast.Subscript):
            return self.expr_tainted(node.value) or self.expr_tainted(
                node.slice
            )
        if isinstance(node, ast.Slice):
            return any(
                self.expr_tainted(p)
                for p in (node.lower, node.upper, node.step)
                if p is not None
            )
        if isinstance(node, ast.BoolOp):
            return any(self.expr_tainted(v) for v in node.values)
        if isinstance(node, ast.BinOp):
            return self.expr_tainted(node.left) or self.expr_tainted(
                node.right
            )
        if isinstance(node, ast.UnaryOp):
            return self.expr_tainted(node.operand)
        if isinstance(node, ast.IfExp):
            return (
                self.expr_tainted(node.body)
                or self.expr_tainted(node.orelse)
            )
        if isinstance(node, ast.Starred):
            return self.expr_tainted(node.value)
        if isinstance(node, (ast.GeneratorExp, ast.ListComp, ast.SetComp)):
            return any(
                self.expr_tainted(gen.iter) for gen in node.generators
            ) or self.expr_tainted(node.elt)
        if isinstance(node, ast.JoinedStr):
            return False
        # Unknown expression shape: assume traced (conservative for
        # branching, which is the dangerous direction).
        return any(
            self.expr_tainted(c)
            for c in ast.iter_child_nodes(node)
            if isinstance(c, ast.expr)
        )

    def call_tainted(self, node: ast.Call) -> bool:
        name = call_name(node)
        args_tainted = any(self.expr_tainted(a) for a in node.args) or any(
            self.expr_tainted(kw.value) for kw in node.keywords
        )
        if name in STATIC_CALLS:
            return False
        # Same-module helper: recurse with call-site taint for an
        # accurate return taint (and to scan the helper's own body).
        helper = self.defs.get(name) if isinstance(node.func, ast.Name) else None
        if helper is not None and self.depth < MAX_HELPER_DEPTH:
            return self._analyze_helper(helper, node)
        if isinstance(node.func, ast.Attribute):
            # Method on a traced value (x.sum(), x.astype()...) stays
            # traced; method on an untraced receiver with untraced
            # args is host-side.
            return self.expr_tainted(node.func.value) or args_tainted
        return args_tainted

    # -- statement walk ------------------------------------------------------

    def flag(self, node: ast.AST, message: str) -> None:
        self.findings.append(
            Finding(PASS_ID, self.pf.rel, node.lineno, message)
        )

    def check_host_sync(self, node: ast.Call) -> None:
        name = call_name(node)
        if name in HOST_CONVERSIONS and isinstance(node.func, ast.Name):
            if node.args and self.expr_tainted(node.args[0]):
                self.flag(node, (
                    f"host sync in jit code: {name}() forces a "
                    f"device→host transfer of a traced value"
                ))
            return
        if name in HOST_METHODS and isinstance(node.func, ast.Attribute):
            if self.expr_tainted(node.func.value):
                self.flag(node, (
                    f"host sync in jit code: .{name}() on a traced value"
                ))
            return
        if name == "device_get":
            self.flag(node, "host sync in jit code: jax.device_get()")
            return
        if name in HOST_NP_FUNCS and isinstance(node.func, ast.Attribute):
            chain = attr_chain(node.func)
            if chain is not None and chain[0] in ("np", "numpy"):
                if any(self.expr_tainted(a) for a in node.args):
                    self.flag(node, (
                        f"host sync in jit code: np.{name}() on a "
                        f"traced value materializes it on the host"
                    ))

    def walk(self, stmts: Sequence[ast.stmt]) -> None:
        for stmt in stmts:
            if isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
                value = stmt.value
                if value is not None:
                    self.scan_calls(value)
                    tainted = self.expr_tainted(value)
                    targets = (
                        stmt.targets
                        if isinstance(stmt, ast.Assign) else [stmt.target]
                    )
                    for target in targets:
                        self.assign_taint(target, tainted)
            elif isinstance(stmt, ast.If):
                self.scan_calls(stmt.test)
                if self.expr_tainted(stmt.test):
                    self.flag(stmt, (
                        "python branch on a traced value in jit code "
                        "(`if` over a tracer; use jnp.where / lax.cond)"
                    ))
                self.walk(stmt.body)
                self.walk(stmt.orelse)
            elif isinstance(stmt, ast.While):
                self.scan_calls(stmt.test)
                if self.expr_tainted(stmt.test):
                    self.flag(stmt, (
                        "python loop condition on a traced value in jit "
                        "code (use lax.while_loop)"
                    ))
                self.walk(stmt.body)
                self.walk(stmt.orelse)
            elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                self.scan_calls(stmt.iter)
                if self.expr_tainted(stmt.iter):
                    self.flag(stmt, (
                        "python iteration over a traced value in jit "
                        "code (use lax.fori_loop / scan)"
                    ))
                self.assign_taint(stmt.target, False)
                self.walk(stmt.body)
                self.walk(stmt.orelse)
            elif isinstance(stmt, ast.Assert):
                self.scan_calls(stmt.test)
                if self.expr_tainted(stmt.test):
                    self.flag(stmt, (
                        "assert on a traced value in jit code (checks "
                        "nothing once traced; use checkify or a static "
                        "shape assert)"
                    ))
            elif isinstance(stmt, ast.Return):
                if stmt.value is not None:
                    self.scan_calls(stmt.value)
                    if self.expr_tainted(stmt.value):
                        self.returns_tainted = True
            elif isinstance(stmt, ast.Expr):
                self.scan_calls(stmt.value)
            elif isinstance(stmt, ast.With):
                for item in stmt.items:
                    self.scan_calls(item.context_expr)
                self.walk(stmt.body)
            elif isinstance(stmt, ast.Try):
                self.walk(stmt.body)
                for handler in stmt.handlers:
                    self.walk(handler.body)
                self.walk(stmt.orelse)
                self.walk(stmt.finalbody)
            elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # Nested def: analyzed when called (helper path); its
                # free variables share this scope's taint, which the
                # helper analysis approximates via call-site args.
                continue
            # remaining statements: no taint flow we track

    def scan_calls(self, expr: ast.AST) -> None:
        for node in ast.walk(expr):
            if isinstance(node, ast.Call):
                self.check_host_sync(node)

    def assign_taint(self, target: ast.AST, tainted: bool) -> None:
        if isinstance(target, ast.Name):
            if tainted:
                self.tainted.add(target.id)
            else:
                self.tainted.discard(target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self.assign_taint(elt, tainted)
        # attribute/subscript writes: no name-level taint to track

    def _analyze_helper(self, helper: ast.AST, call: ast.Call) -> bool:
        params = [a.arg for a in helper.args.args]
        arg_taint: Set[str] = set()
        for i, arg in enumerate(call.args):
            if i < len(params) and self.expr_tainted(arg):
                arg_taint.add(params[i])
        for kw in call.keywords:
            if kw.arg in params and self.expr_tainted(kw.value):
                arg_taint.add(kw.arg)
        key = (id(helper), frozenset(arg_taint))
        if key in self.memo:
            return self.memo[key]
        self.memo[key] = True  # cycle guard: assume tainted while open
        sub = _TaintChecker(self.pf, self.defs, self.findings, self.memo,
                            self.depth + 1)
        sub.tainted = set(arg_taint)
        sub.walk(helper.body)
        self.memo[key] = sub.returns_tainted
        return sub.returns_tainted


def _check_donated_reuse(pf: ProjectFile,
                         registered: Dict[str, JitRoot],
                         findings: List[Finding]) -> None:
    donating = {
        name: root.donate_argnums
        for name, root in registered.items() if root.donate_argnums
    }
    if not donating:
        return
    for node in ast.walk(pf.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        _scan_donated_in_function(pf, node, donating, findings)


def _scan_donated_in_function(pf, func, donating, findings) -> None:
    # Statement-order scan: after `r = jitted(buf, ...)` with buf in a
    # donated position, a later read of `buf` (before reassignment) is
    # a use of freed/aliased device memory.
    donated_vars: Dict[str, int] = {}  # name -> donation line

    def visit(stmts):
        for stmt in stmts:
            for node in ast.walk(stmt):
                if isinstance(node, ast.Call) and isinstance(
                    node.func, ast.Name
                ) and node.func.id in donating:
                    for idx in donating[node.func.id]:
                        if idx < len(node.args) and isinstance(
                            node.args[idx], ast.Name
                        ):
                            donated_vars[node.args[idx].id] = node.lineno
            # reads after donation
            for node in ast.walk(stmt):
                if (
                    isinstance(node, ast.Name)
                    and isinstance(node.ctx, ast.Load)
                    and node.id in donated_vars
                    and node.lineno > donated_vars[node.id]
                ):
                    findings.append(Finding(
                        PASS_ID, pf.rel, node.lineno,
                        f"donated-buffer reuse: {node.id!r} was passed "
                        f"in a donate_argnums position at line "
                        f"{donated_vars[node.id]} and read again — the "
                        f"buffer may already alias the jit output",
                    ))
                    del donated_vars[node.id]
            # reassignment clears the donation
            if isinstance(stmt, ast.Assign):
                for target in stmt.targets:
                    if isinstance(target, ast.Name):
                        donated_vars.pop(target.id, None)

    visit(func.body)


@register_pass(PASS_ID)
def run(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    for pf in project.files:
        roots, registered = _collect_roots(pf)
        if not roots:
            continue
        defs: Dict[str, ast.AST] = {}
        for node in ast.walk(pf.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                defs[node.name] = node
        memo: Dict[Tuple[int, frozenset], bool] = {}
        analyzed: Set[int] = set()
        for root in roots:
            if id(root.func) in analyzed:
                continue
            analyzed.add(id(root.func))
            checker = _TaintChecker(pf, defs, findings, memo, depth=0)
            checker.tainted = {
                a.arg for a in root.func.args.args
                if a.arg not in root.static_names
            }
            checker.walk(root.func.body)
        _check_donated_reuse(pf, registered, findings)
    # One finding per (file, line, message): the same helper analyzed
    # under several taint signatures re-reports identical sites.
    unique = sorted(set(findings), key=lambda f: (f.file, f.line, f.message))
    return unique
