#!/usr/bin/env bash
# TPU tunnel watcher: probe cheaply on a loop; the moment the chip
# answers, fire the full validation runbook (tools/tpu_validation.py)
# and exit. The tunnel is intermittent (alive ~75 min in round 3), so
# validation must launch within one probe interval of it waking.
#
# Usage: tools/tpu_watch.sh [out.json] [max_hours]
set -u
cd "$(dirname "$0")/.."
OUT="${1:-tpu_validation_r4.json}"
MAX_HOURS="${2:-11}"
DEADLINE=$(( $(date +%s) + MAX_HOURS * 3600 ))

while [ "$(date +%s)" -lt "$DEADLINE" ]; do
  N=$(timeout 90 python -c "
from kube_batch_tpu.utils.backend import probe_default_backend
print(probe_default_backend(timeout=60))" 2>/dev/null | tail -1)
  if [ "${N:-0}" -gt 0 ] 2>/dev/null; then
    echo "$(date -u +%FT%TZ) tunnel alive ($N devices) — running validation" >&2
    python tools/tpu_validation.py --out "$OUT"
    RC=$?
    echo "$(date -u +%FT%TZ) validation rc=$RC" >&2
    # rc=0 means the runbook completed with a live device; rc=1 means
    # the tunnel died between probe and runbook — keep watching.
    [ "$RC" -eq 0 ] && exit 0
  fi
  sleep 240
done
echo "$(date -u +%FT%TZ) watcher deadline reached; tunnel never answered" >&2
exit 1
