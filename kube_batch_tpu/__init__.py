"""tpu-batch: a TPU-native batch/gang scheduler.

Capability surface of kube-batch (gang scheduling, multi-tenant queues, DRF /
proportional fair share, priority, preemption, reclaim, backfill, action/plugin
policy engine), with the per-task greedy allocate loop replaced by a batched
assignment solve on TPU via JAX/XLA.

Package layout (mirrors the reference's layer map, SURVEY.md §1):

- ``api``        — in-memory domain model (reference: pkg/scheduler/api)
- ``cache``      — cluster mirror + snapshot + bind/evict seams (pkg/scheduler/cache)
- ``framework``  — Session, Statement, plugin/action registries (pkg/scheduler/framework)
- ``plugins``    — gang, drf, proportion, priority, predicates, nodeorder, conformance
- ``actions``    — allocate, allocate_tpu, backfill, preempt, reclaim
- ``ops``        — JAX kernels: feasibility masks, scoring, batched assignment solver
- ``parallel``   — device mesh / sharding for multi-chip solves
- ``utils``      — priority queue, scheduler helpers
- ``metrics``    — scheduling latency/counter metrics
- ``conf``       — scheduler policy configuration (YAML-compatible with kube-batch-conf.yaml)
- ``cli``        — process entry point, flags, metrics server
"""

__version__ = "0.1.0"
