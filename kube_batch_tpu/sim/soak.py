"""Soak-mode leak/drift detectors over the telemetry windows.

``sim --soak`` runs the simulator for a long horizon (100k virtual
cycles is the reference tier) with the telemetry layer recording every
cycle, then fits trends over the rolled windows (``obs/telemetry.py``):

- **growth detectors** fit a least-squares line to each resource
  watermark series (RSS, allocator blocks, JAX live buffers, jit cache
  entries, device-resident snapshot bytes, metrics label-series
  cardinality, verdict-registry size) and trip when the fit shows a
  *sustained, explained, material* climb — slope positive, R^2 above a
  noise gate, and the projected growth over the fitted span past BOTH
  an absolute floor and a relative floor. The three gates together are
  what makes the detector noise-aware: a GC sawtooth fails R^2, a
  one-off allocation step fails the slope fit, a 2 MB wiggle on a 200 MB
  heap fails the floors.
- **drift detectors** bound a series instead: per-queue fairness drift
  (allocated minus water-filled deserved share) must keep its windowed
  mean inside ``bound`` — sustained breach over ``patience``
  consecutive windows trips (one overshoot window is one gang landing;
  three in a row is systematic unfairness). Invariant-violation and
  cycle-error series are bounded at zero.

Warmup windows are skipped (caches, pools, and jit compilation
legitimately grow early); the fit runs on the post-warmup tail.

A trip names the offending series, the fitted slope/R^2/growth, the
window where the climb steepened, and a **replay-bisect pointer**: the
JSONL trace (when recorded) replays bit-exactly, so
``sim --replay <trace>`` with ``--replay-cycles`` clamped to the
suspect window's end reproduces the exact state just past the
inflection — halve from there.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple


@dataclass
class GrowthPolicy:
    """Trip thresholds for one watermark series."""

    abs_floor: float          # minimum projected growth over the fit span
    rel_floor: float = 0.05   # ... and as a fraction of the baseline
    r2_min: float = 0.6       # fit quality gate (noise fails this)


@dataclass
class DriftPolicy:
    """Bound for one drift series (checked on window means)."""

    bound: float              # |windowed mean| must stay <= bound
    patience: int = 3         # consecutive breaching windows to trip
    signed: bool = True       # False: only positive breach trips
    warmup_exempt: bool = False  # hard invariant: no warmup skip


# Default soak policy. Keys match the telemetry watermark probes;
# ``fairness_drift:*`` matches per-queue series by prefix. Floors are
# deliberately generous — a soak failure must mean a real leak, not an
# allocator mood; the injected-leak tests pin that the gates still
# catch a genuine linear climb.
GROWTH_POLICY: Dict[str, GrowthPolicy] = {
    "rss_bytes": GrowthPolicy(abs_floor=32 * 1024 * 1024, rel_floor=0.08),
    "alloc_blocks": GrowthPolicy(abs_floor=200_000, rel_floor=0.05),
    "jax_live_buffers": GrowthPolicy(abs_floor=2_000, rel_floor=0.25),
    "jax_device_memory_bytes": GrowthPolicy(
        abs_floor=16 * 1024 * 1024, rel_floor=0.10
    ),
    "jit_cache_entries": GrowthPolicy(
        # ANY steady post-warmup growth in compiled variants is a
        # retrace leak; 8 entries is far past jitter.
        abs_floor=8, rel_floor=0.0, r2_min=0.5
    ),
    "device_resident_bytes": GrowthPolicy(
        abs_floor=8 * 1024 * 1024, rel_floor=0.20
    ),
    "metrics_series": GrowthPolicy(abs_floor=64, rel_floor=0.10),
    "explain_verdicts": GrowthPolicy(abs_floor=256, rel_floor=0.50),
    # Placement-ledger occupancy (obs/latency.py): entries must die
    # with their pods/jobs — sustained linear growth here is a per-pod
    # ledger leak, exactly the class the metrics-GC pattern forbids.
    "latency_entries": GrowthPolicy(abs_floor=512, rel_floor=0.50),
    # Carried-backlog depth (solver/warm.py): the jobs subset solves
    # rotate through between periodic cycles. Congestion legitimately
    # holds it high and bursty — but a sustained LINEAR climb means
    # arrivals outpace the micro steady state's drain budget and the
    # scheduler is quietly falling behind (placements still land, just
    # ever later). Floors sized so saturation plateaus and burst waves
    # pass while an unbounded admission leak trips.
    "carried_backlog_depth": GrowthPolicy(
        abs_floor=64, rel_floor=0.50, r2_min=0.7
    ),
}

DRIFT_POLICY: Dict[str, DriftPolicy] = {
    "fairness_drift:": DriftPolicy(bound=0.35, patience=3, signed=False),
    # Per-queue p99 arrival→bind placement latency (virtual seconds,
    # obs/latency.py): a slow scheduling-latency regression must fail
    # a soak instead of hiding — same trip semantics as fairness
    # drift. The bound is generous (2 virtual minutes): saturation
    # waves legitimately push p99 to many cycles; a systematic climb
    # past the bound for `patience` windows is a scheduler regression.
    "placement_p99:": DriftPolicy(bound=120.0, patience=3, signed=False),
    # Zero-bound series are hard invariants, not steady-state
    # properties — a cycle error in the first quarter of the run is as
    # fatal as one at the end, so they opt out of the warmup skip.
    "invariant_violations": DriftPolicy(
        bound=0.0, patience=1, warmup_exempt=True
    ),
    "sim_cycle_errors": DriftPolicy(
        bound=0.0, patience=1, warmup_exempt=True
    ),
    # Serving SLO-miss rate (cumulative, obs/latency.py; emitted only
    # once serving placements exist): attainment drift — a regression
    # that slowly erodes serving placement latency — must fail a soak
    # the same way fairness drift does. Bound = twice the default
    # violation budget (1 - KBT_SERVING_ATTAINMENT_TARGET).
    "serving_slo_miss_rate": DriftPolicy(
        bound=0.02, patience=3, signed=False
    ),
    # Placement-quality scorecard (obs/quality.py). Unfairness =
    # 1 - Jain index over per-queue satisfaction ratios: transient
    # imbalance is normal while gangs land, but a windowed mean past
    # the bound for `patience` windows means the scheduler is
    # systematically over-serving some queues — the drift the ROADMAP
    # item-1 quality gate exists to catch. Bound is generous for the
    # same reason fairness_drift's is: a trip must mean a regression,
    # not one gang's worth of overshoot.
    "quality:unfairness": DriftPolicy(
        bound=0.5, patience=3, signed=False
    ),
    # Disruption churn: evictions + re-binds per placement over each
    # scorecard interval. Steady-state churn near zero is the
    # contract; a sustained windowed mean above 1.0 means the
    # scheduler is thrashing (every placement paid for by more than
    # one disruption).
    "quality:churn_per_placement": DriftPolicy(
        bound=1.0, patience=3, signed=False
    ),
}

# Fraction of windows treated as warmup (jit compiles, pool growth).
WARMUP_FRAC = 0.25
# Minimum post-warmup windows for a meaningful fit.
MIN_WINDOWS = 8


@dataclass
class DetectorResult:
    series: str
    kind: str                       # "growth" | "drift"
    tripped: bool
    message: str
    slope_per_kcycle: Optional[float] = None
    r2: Optional[float] = None
    growth: Optional[float] = None
    baseline: Optional[float] = None
    suspect_cycles: Optional[Tuple[int, int]] = None
    windows_fit: int = 0

    def to_dict(self) -> dict:
        out = {
            "series": self.series,
            "kind": self.kind,
            "tripped": self.tripped,
            "message": self.message,
            "windows_fit": self.windows_fit,
        }
        if self.slope_per_kcycle is not None:
            out["slope_per_kcycle"] = round(self.slope_per_kcycle, 4)
        if self.r2 is not None:
            out["r2"] = round(self.r2, 4)
        if self.growth is not None:
            out["growth"] = round(self.growth, 3)
        if self.baseline is not None:
            out["baseline"] = round(self.baseline, 3)
        if self.suspect_cycles is not None:
            out["suspect_cycles"] = list(self.suspect_cycles)
        return out


def fit_linear(points: Sequence[Tuple[float, float]]):
    """Least-squares (slope, intercept, r2) over (x, y) points."""
    n = len(points)
    if n < 2:
        return 0.0, points[0][1] if points else 0.0, 0.0
    sx = sum(p[0] for p in points)
    sy = sum(p[1] for p in points)
    mx, my = sx / n, sy / n
    sxx = sum((p[0] - mx) ** 2 for p in points)
    sxy = sum((p[0] - mx) * (p[1] - my) for p in points)
    if sxx == 0:
        return 0.0, my, 0.0
    slope = sxy / sxx
    intercept = my - slope * mx
    syy = sum((p[1] - my) ** 2 for p in points)
    if syy == 0:
        # A perfectly flat series: zero slope explains it perfectly,
        # but report r2=0 so "no variance" can never pass an r2 gate.
        return slope, intercept, 0.0
    ss_res = sum(
        (p[1] - (intercept + slope * p[0])) ** 2 for p in points
    )
    return slope, intercept, max(0.0, 1.0 - ss_res / syy)


def _windows_series(windows: List[dict], key: str, stat: str):
    """(mid_cycle, stat, start, end) per window carrying ``key``."""
    out = []
    for w in windows:
        ks = w["keys"].get(key)
        if ks is None:
            continue
        out.append((
            (w["start_cycle"] + w["end_cycle"]) / 2.0,
            float(ks[stat]),
            w["start_cycle"],
            w["end_cycle"],
        ))
    return out


def check_growth(
    windows: List[dict], key: str, policy: GrowthPolicy,
    warmup_frac: float = WARMUP_FRAC,
) -> Optional[DetectorResult]:
    """Fit the post-warmup windowed means of ``key``; trip on a
    sustained material climb. None when the series is absent or too
    short to judge."""
    pts = _windows_series(windows, key, "mean")
    if len(pts) < MIN_WINDOWS:
        return None
    skip = int(len(pts) * warmup_frac)
    tail = pts[skip:]
    if len(tail) < MIN_WINDOWS:
        tail = pts[-MIN_WINDOWS:]
    xy = [(p[0], p[1]) for p in tail]
    slope, _intercept, r2 = fit_linear(xy)
    span = xy[-1][0] - xy[0][0]
    growth = slope * span
    baseline = sum(p[1] for p in tail[:3]) / min(3, len(tail))
    rel_gate = policy.rel_floor * max(abs(baseline), 1e-9)
    tripped = (
        slope > 0
        and r2 >= policy.r2_min
        and growth >= policy.abs_floor
        and growth >= rel_gate
    )
    suspect = None
    if tripped:
        # The window with the steepest single-step climb post-warmup:
        # the bisect entry point.
        best, best_delta = tail[-1], -1.0
        for prev, cur in zip(tail, tail[1:]):
            delta = cur[1] - prev[1]
            if delta > best_delta:
                best_delta, best = delta, cur
        suspect = (int(best[2]), int(best[3]))
    msg = (
        f"{key}: slope {slope * 1000:+.3f}/kcycle over "
        f"{len(tail)} windows (r2 {r2:.2f}), projected growth "
        f"{growth:,.1f} from baseline {baseline:,.1f}"
    )
    return DetectorResult(
        series=key, kind="growth", tripped=tripped, message=msg,
        slope_per_kcycle=slope * 1000.0, r2=r2, growth=growth,
        baseline=baseline, suspect_cycles=suspect,
        windows_fit=len(tail),
    )


def check_drift(
    windows: List[dict], key: str, policy: DriftPolicy,
    warmup_frac: float = WARMUP_FRAC,
) -> Optional[DetectorResult]:
    """Bound ``key``'s windowed mean; trip on ``patience`` consecutive
    breaching windows past warmup."""
    pts = _windows_series(windows, key, "mean")
    if not pts:
        return None
    skip = (
        int(len(pts) * warmup_frac)
        if len(pts) >= MIN_WINDOWS and not policy.warmup_exempt
        else 0
    )
    tail = pts[skip:]
    streak = 0
    streak_start = 0
    worst = 0.0
    suspect = None
    tripped = False
    for mid, mean, start, end in tail:
        breach = (
            abs(mean) > policy.bound if policy.signed
            else mean > policy.bound
        )
        if breach:
            if streak == 0:
                streak_start = int(start)
            streak += 1
            if abs(mean) > abs(worst):
                worst = mean
            if streak >= policy.patience and not tripped:
                # The bisect pointer names the FIRST streak that
                # tripped, not whichever isolated window had the worst
                # mean — an isolated spike that never met patience is
                # noise, not the systematic drift being flagged.
                tripped = True
                suspect = (streak_start, int(end))
        else:
            streak = 0
    msg = (
        f"{key}: worst windowed mean {worst:+.4f} vs bound "
        f"{policy.bound:+.4f} ({len(tail)} windows, "
        f"patience {policy.patience})"
    )
    return DetectorResult(
        series=key, kind="drift", tripped=tripped, message=msg,
        growth=worst, suspect_cycles=suspect if tripped else None,
        windows_fit=len(tail),
    )


def run_detectors(
    windows: List[dict],
    growth_policy: Optional[Dict[str, GrowthPolicy]] = None,
    drift_policy: Optional[Dict[str, DriftPolicy]] = None,
    warmup_frac: float = WARMUP_FRAC,
) -> List[DetectorResult]:
    """Evaluate every policy entry against the rolled windows. Series
    absent from the run are skipped (probe not available), not failed."""
    growth_policy = GROWTH_POLICY if growth_policy is None else growth_policy
    drift_policy = DRIFT_POLICY if drift_policy is None else drift_policy
    keys = set()
    for w in windows:
        keys.update(w["keys"])
    results: List[DetectorResult] = []
    for key, policy in sorted(growth_policy.items()):
        r = check_growth(windows, key, policy, warmup_frac)
        if r is not None:
            results.append(r)
    for prefix, policy in sorted(drift_policy.items()):
        matches = (
            sorted(k for k in keys if k.startswith(prefix))
            if prefix.endswith(":") else ([prefix] if prefix in keys else [])
        )
        for key in matches:
            r = check_drift(windows, key, policy, warmup_frac)
            if r is not None:
                results.append(r)
    return results


@dataclass
class SoakVerdict:
    detectors: List[DetectorResult] = field(default_factory=list)
    telemetry_dump: Optional[str] = None
    trace_path: Optional[str] = None

    @property
    def tripped(self) -> List[DetectorResult]:
        return [d for d in self.detectors if d.tripped]

    def to_dict(self) -> dict:
        return {
            "detectors": [d.to_dict() for d in self.detectors],
            "tripped": [d.series for d in self.tripped],
            "telemetry_dump": self.telemetry_dump,
            "replay_bisect": self.replay_hints(),
        }

    def replay_hints(self) -> List[str]:
        """One actionable line per trip: where to point the replay."""
        hints = []
        for d in self.tripped:
            if d.suspect_cycles and self.trace_path:
                a, b = d.suspect_cycles
                hints.append(
                    f"{d.series}: breach steepens in cycles {a}..{b} — "
                    f"bisect with `python -m kube_batch_tpu sim "
                    f"--replay {self.trace_path} --replay-cycles {b}` "
                    f"(replay is bit-exact; halve from there)"
                )
            elif d.suspect_cycles:
                a, b = d.suspect_cycles
                hints.append(
                    f"{d.series}: breach steepens in cycles {a}..{b} — "
                    f"re-run with --trace to get a bisectable recording"
                )
            else:
                hints.append(f"{d.series}: {d.message}")
        return hints
