"""TPU solver tests: kernel units + allocate_tpu behavior parity.

Kernel tests exercise the pure-JAX pieces directly; parity tests run the
same fake-cluster scenarios as the greedy allocate suite through the
``allocate_tpu`` action and assert the identical observable outcomes
(bind counts, per-node capacity, gang all-or-nothing, proportion splits).
Greedy breaks score ties randomly (scheduler_helper.go:188-208), so parity
is asserted on outcome invariants, not exact node picks.
"""

import numpy as np
import pytest

import kube_batch_tpu.actions  # noqa: F401
import kube_batch_tpu.plugins  # noqa: F401
from kube_batch_tpu.api import PodPhase, build_resource_list
from kube_batch_tpu.solver import (
    less_equal,
    make_inputs,
    segmented_cumsum,
    solve,
    solve_staged,
    tensorize,
)

from tests.actions.test_actions import (
    DEFAULT_TIERS_ARGS,
    drain,
    make_cache,
    make_tiers,
    req,
    run_action,
)
from kube_batch_tpu.framework import close_session, open_session
from kube_batch_tpu.utils.test_utils import (
    build_node,
    build_pod,
    build_pod_group,
    build_queue,
)

import jax.numpy as jnp


class TestKernelPieces:
    def test_less_equal_epsilon(self):
        eps = jnp.asarray([10.0, 10.0])
        a = jnp.asarray([[100.0, 50.0]])
        # strictly less, within-epsilon equal, and over-epsilon greater
        assert bool(less_equal(a, jnp.asarray([[200.0, 60.0]]), eps))
        assert bool(less_equal(a, jnp.asarray([[95.0, 45.0]]), eps))
        assert not bool(less_equal(a, jnp.asarray([[80.0, 50.0]]), eps))

    def test_segmented_cumsum_resets(self):
        x = jnp.asarray([[1.0], [2.0], [3.0], [4.0]])
        is_start = jnp.asarray([True, False, True, False])
        out = np.asarray(segmented_cumsum(x, is_start))
        np.testing.assert_allclose(out[:, 0], [1.0, 3.0, 3.0, 7.0])

    def test_segmented_cumsum_scalar(self):
        x = jnp.ones((5,), jnp.int32)
        is_start = jnp.asarray([True, False, False, True, False])
        out = np.asarray(segmented_cumsum(x, is_start))
        np.testing.assert_array_equal(out, [1, 2, 3, 1, 2])

    def _inputs(self, task_req, node_idle, feas=None, **kw):
        task_req = jnp.asarray(task_req, jnp.float32)
        node_idle = jnp.asarray(node_idle, jnp.float32)
        T, R = task_req.shape
        N = node_idle.shape[0]
        defaults = dict(
            task_req=task_req,
            task_fit=task_req,
            task_rank=jnp.arange(T, dtype=jnp.int32),
            task_job=jnp.arange(T, dtype=jnp.int32),  # one job per task
            task_queue=jnp.zeros(T, jnp.int32),
            node_idle=node_idle,
            node_releasing=jnp.zeros_like(node_idle),
            node_cap=node_idle,
            node_task_count=jnp.zeros(N, jnp.int32),
            node_max_tasks=jnp.zeros(N, jnp.int32),
            queue_deserved=jnp.full((1, R), jnp.inf, jnp.float32),
            queue_allocated=jnp.zeros((1, R), jnp.float32),
            eps=jnp.full((R,), 10.0, jnp.float32),
            lr_weight=jnp.asarray(1.0, jnp.float32),
            br_weight=jnp.asarray(1.0, jnp.float32),
        )
        defaults.update(kw)
        return make_inputs(feas=feas, **defaults)

    def test_all_fit_single_round_spread(self):
        # 2 identical tasks, 2 empty identical nodes: spread is not required
        # by greedy semantics, but both must place.
        inputs = self._inputs(
            [[1000.0, 1024.0]] * 2, [[2000.0, 4096.0]] * 2
        )
        res = solve(inputs)
        assigned = np.asarray(res.assigned)
        assert (assigned >= 0).all()
        # Capacity respected.
        for j in range(2):
            assert (assigned == j).sum() <= 2

    def test_conflict_resolution_respects_capacity(self):
        # 3 tasks of 1 cpu, one node with 2 cpus: exactly 2 place.
        inputs = self._inputs(
            [[1000.0, 0.0]] * 3, [[2000.0, 1e9]]
        )
        res = solve(inputs)
        assigned = np.asarray(res.assigned)
        assert (assigned == 0).sum() == 2
        assert (assigned == -1).sum() == 1
        # Priority order: ranks 0,1 won, rank 2 lost.
        assert assigned[2] == -1

    def test_infeasible_mask_blocks(self):
        feas = jnp.asarray([[False]])
        inputs = self._inputs([[100.0, 0.0]], [[2000.0, 1e9]], feas=feas)
        res = solve(inputs)
        assert int(res.assigned[0]) == -1

    def test_pair_rows_and_into_group_mask(self):
        # A private pair row must AND with the group/column mask, not
        # replace it: group mask forbids node 0, pair row allows both.
        inputs = self._inputs(
            [[100.0, 0.0]],
            [[2000.0, 1e9], [2000.0, 1e9]],
            feas=jnp.asarray([[False, True]]),
            pair_idx=jnp.asarray([0], jnp.int32),
            pair_feas=jnp.asarray([[True, True]]),
        )
        res = solve(inputs)
        assert int(res.assigned[0]) == 1

    def test_max_tasks_cap(self):
        inputs = self._inputs(
            [[100.0, 0.0]] * 3,
            [[10000.0, 1e9]],
            node_max_tasks=jnp.asarray([2], jnp.int32),
        )
        res = solve(inputs)
        assert (np.asarray(res.assigned) >= 0).sum() == 2

    def test_queue_overused_stops_queue(self):
        # Queue already at its deserved share: nothing places.
        R = 2
        inputs = self._inputs(
            [[100.0, 0.0]],
            [[10000.0, 1e9]],
            queue_deserved=jnp.asarray([[1000.0, 1e6]], jnp.float32),
            queue_allocated=jnp.asarray([[1000.0, 1e6]], jnp.float32),
        )
        res = solve(inputs)
        assert int(res.assigned[0]) == -1

    def test_idle_updated(self):
        inputs = self._inputs([[1500.0, 0.0]], [[2000.0, 1e9]])
        res = solve(inputs)
        assert int(res.assigned[0]) == 0
        np.testing.assert_allclose(
            np.asarray(res.node_idle)[0, 0], 500.0, atol=1e-3
        )

    def test_multi_round_progress(self):
        # 4 tasks that all prefer the emptier node; capacity forces rounds.
        inputs = self._inputs(
            [[1000.0, 1024.0]] * 4,
            [[2000.0, 4096.0], [2000.0, 4096.0]],
        )
        res = solve(inputs)
        assigned = np.asarray(res.assigned)
        assert (assigned >= 0).all()
        assert (assigned == 0).sum() == 2
        assert (assigned == 1).sum() == 2


class TestStagedSolver:
    """solve_staged must reach the same outcome invariants as solve, even
    with a tail bucket far below T (forcing head->tail compaction and
    multiple tail stages)."""

    _inputs = TestKernelPieces._inputs

    def test_matches_full_small_bucket(self):
        inputs = self._inputs(
            [[1000.0, 1024.0]] * 4,
            [[2000.0, 4096.0], [2000.0, 4096.0]],
        )
        full = solve(inputs)
        staged = solve_staged(inputs, tail_bucket=2)
        np.testing.assert_array_equal(
            np.asarray(full.assigned) >= 0,
            np.asarray(staged.assigned) >= 0,
        )
        np.testing.assert_allclose(
            np.asarray(full.node_idle), np.asarray(staged.node_idle),
            atol=1e-3,
        )

    def test_multi_stage_drain(self):
        # 6 identical tasks, 3 nodes of capacity 2, bucket=2: the tail
        # must compact+drain repeatedly until all place.
        inputs = self._inputs(
            [[1000.0, 0.0]] * 6,
            [[2000.0, 1e9]] * 3,
        )
        res = solve_staged(inputs, tail_bucket=2)
        assigned = np.asarray(res.assigned)
        assert (assigned >= 0).all()
        for j in range(3):
            assert (assigned == j).sum() == 2

    def test_infeasible_task_fails_in_tail(self):
        inputs = self._inputs(
            [[100.0, 0.0], [50000.0, 0.0]],
            [[2000.0, 1e9], [1000.0, 1e9]],
        )
        res = solve_staged(inputs, tail_bucket=1)
        assigned = np.asarray(res.assigned)
        assert assigned[0] >= 0
        assert assigned[1] == -1

    def test_queue_budget_respected(self):
        inputs = self._inputs(
            [[100.0, 0.0]] * 4,
            [[10000.0, 1e9]],
            # Overused (proportion.go:198) needs deserved <= allocated on
            # EVERY dim, so the mem dim must be trivially satisfied (0).
            queue_deserved=jnp.asarray([[250.0, 0.0]], jnp.float32),
            queue_allocated=jnp.asarray([[0.0, 0.0]], jnp.float32),
        )
        res = solve_staged(inputs, tail_bucket=2)
        # 250m deserved: tasks accepted while allocated < deserved,
        # overshoot by at most one task like the greedy Overused gate.
        assert 2 <= (np.asarray(res.assigned) >= 0).sum() <= 3

    def test_randomized_equivalence_with_full(self):
        rng = np.random.RandomState(7)
        T, N = 40, 12
        task_req = np.c_[
            rng.choice([250, 500, 1000], T), rng.choice([256, 512], T)
        ].astype(np.float32)
        node_idle = np.c_[
            rng.choice([4000, 8000], N), np.full(N, 1e7)
        ].astype(np.float32)
        inputs = self._inputs(task_req, node_idle)
        full = solve(inputs)
        staged = solve_staged(inputs, tail_bucket=8)
        # Same number placed; per-node loads within capacity for both.
        assert (
            (np.asarray(staged.assigned) >= 0).sum()
            == (np.asarray(full.assigned) >= 0).sum()
        )
        assert (np.asarray(staged.node_idle) > -10.0).all()


class TestAllocateTpuParity:
    """The greedy TestAllocate scenarios, run through allocate_tpu."""

    def test_gang_fits_and_binds(self):
        c = make_cache()
        c.add_queue(build_queue("default"))
        c.add_pod_group(build_pod_group("pg1", namespace="ns", min_member=3))
        for i in range(3):
            c.add_pod(build_pod("ns", f"p{i}", "", PodPhase.PENDING, req(),
                                group_name="pg1"))
        c.add_node(build_node("n1", build_resource_list(cpu="2", memory="4Gi")))
        c.add_node(build_node("n2", build_resource_list(cpu="2", memory="4Gi")))

        run_action(c, "allocate_tpu")
        binds = drain(c.binder.channel, 3)
        assert len(binds) == 3
        assert set(c.binder.binds) == {"ns/p0", "ns/p1", "ns/p2"}
        per_node = {}
        for pod_key, host in c.binder.binds.items():
            per_node[host] = per_node.get(host, 0) + 1
        assert all(v <= 2 for v in per_node.values())

    def test_gang_starved_binds_nothing(self):
        c = make_cache()
        c.add_queue(build_queue("default"))
        c.add_pod_group(build_pod_group("pg1", namespace="ns", min_member=3))
        for i in range(3):
            c.add_pod(build_pod("ns", f"p{i}", "", PodPhase.PENDING, req(),
                                group_name="pg1"))
        c.add_node(build_node("n1", build_resource_list(cpu="2", memory="4Gi")))

        run_action(c, "allocate_tpu")
        assert drain(c.binder.channel, 1, timeout=0.3) == []
        assert not c.binder.binds

    def test_idle_queue_without_jobs_does_not_crash(self):
        # proportion builds queue attrs only for job-bearing queues
        # (reference proportion.go:66-99), and the greedy loop discovers
        # queues from jobs — so a tenant queue created ahead of its
        # first jobs must not crash tensorize's queue ordering
        # (regression: every allocate_tpu cycle KeyError'd on the idle
        # queue in the multitenant perf scenario).
        c = make_cache()
        c.add_queue(build_queue("default", weight=1))
        c.add_queue(build_queue("tenant-b", weight=3))  # no jobs yet
        c.add_pod_group(build_pod_group("pg1", namespace="ns", min_member=1))
        c.add_pod(build_pod("ns", "p0", "", PodPhase.PENDING, req(),
                            group_name="pg1"))
        c.add_node(build_node("n1", build_resource_list(cpu="2", memory="4Gi")))

        run_action(c, "allocate_tpu")
        binds = drain(c.binder.channel, 1)
        assert len(binds) == 1

    def test_two_jobs_share_cluster(self):
        c = make_cache()
        c.add_queue(build_queue("default"))
        for g in ("pg1", "pg2"):
            c.add_pod_group(build_pod_group(g, namespace="ns", min_member=1))
            for i in range(2):
                c.add_pod(build_pod("ns", f"{g}-p{i}", "", PodPhase.PENDING,
                                    req(), group_name=g))
        c.add_node(build_node("n1", build_resource_list(cpu="2", memory="4Gi")))
        c.add_node(build_node("n2", build_resource_list(cpu="2", memory="4Gi")))

        run_action(c, "allocate_tpu")
        binds = drain(c.binder.channel, 4)
        assert len(binds) == 4

    def test_queue_capacity_multi_tenant(self):
        c = make_cache()
        c.add_queue(build_queue("q1", weight=3))
        c.add_queue(build_queue("q2", weight=1))
        for g, q, n in (("pg1", "q1", 4), ("pg2", "q2", 4)):
            c.add_pod_group(build_pod_group(g, namespace="ns", min_member=1,
                                            queue=q))
            for i in range(n):
                c.add_pod(build_pod("ns", f"{g}-p{i}", "", PodPhase.PENDING,
                                    req(mem="10Mi"), group_name=g))
        c.add_node(build_node("n1", build_resource_list(cpu="4", memory="8Gi")))

        run_action(c, "allocate_tpu")
        drain(c.binder.channel, 4)
        q1_binds = sum(1 for k in c.binder.binds if k.startswith("ns/pg1"))
        q2_binds = sum(1 for k in c.binder.binds if k.startswith("ns/pg2"))
        assert q1_binds == 3
        assert q2_binds == 1

    def test_node_selector_respected(self):
        c = make_cache()
        c.add_queue(build_queue("default"))
        c.add_pod_group(build_pod_group("pg1", namespace="ns", min_member=1))
        c.add_pod(build_pod("ns", "p0", "", PodPhase.PENDING, req(),
                            group_name="pg1",
                            selector={"zone": "a"}))
        c.add_node(build_node("n1", build_resource_list(cpu="4", memory="8Gi"),
                              labels={"zone": "b"}))
        c.add_node(build_node("n2", build_resource_list(cpu="4", memory="8Gi"),
                              labels={"zone": "a"}))

        run_action(c, "allocate_tpu")
        binds = drain(c.binder.channel, 1)
        assert binds == ["ns/p0"]
        assert c.binder.binds["ns/p0"] == "n2"

    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_randomized_no_regression_vs_greedy(self, seed):
        """Random small clusters. Greedy breaks score ties RANDOMLY
        (scheduler_helper.go:188-208) so its placement count varies run to
        run — exact count parity is not a contract even between two greedy
        runs. The solver contract asserted here: (a) every TPU bind
        respects node capacity, (b) the batched solver never places fewer
        pods than a deterministically-seeded greedy run."""
        rng = np.random.RandomState(seed)
        rng_state = (
            rng.randint(0, 4, size=4),          # extra cpus per node
            rng.randint(1, 6, size=3),          # pods per group
            rng.randint(100, 1900, size=(3, 8)),  # per-pod cpu millis
        )

        def build(action):
            c = make_cache()
            c.add_queue(build_queue("default"))
            for j in range(4):
                c.add_node(build_node(
                    f"n{j}",
                    build_resource_list(cpu=str(2 + int(rng_state[0][j])),
                                        memory="16Gi", pods=16),
                ))
            for g in range(3):
                c.add_pod_group(build_pod_group(
                    f"pg{g}", namespace="ns", min_member=1))
                for i in range(int(rng_state[1][g])):
                    cpu_m = int(rng_state[2][g][i])
                    c.add_pod(build_pod(
                        "ns", f"pg{g}-p{i}", "", PodPhase.PENDING,
                        build_resource_list(cpu=f"{cpu_m}m", memory="128Mi"),
                        group_name=f"pg{g}"))
            run_action(c, action)
            return c

        # Greedy's tie-break is random.choice over max-score nodes and its
        # parallel scorer sums floats in thread-completion order, so its
        # count is not run-to-run deterministic even when seeded. Pin the
        # tie-break to first-best so the >= contract below cannot flake.
        import kube_batch_tpu.utils.scheduler_helper as _sh

        class _FirstBest:
            def choice(self, seq):
                return seq[0]

        orig_rng = _sh._rng
        _sh._rng = _FirstBest()
        try:
            greedy = build("allocate")
        finally:
            _sh._rng = orig_rng
        # Binds execute on the cache's async side-effect pool; barrier both
        # caches before counting or the comparison races the pool.
        assert greedy.wait_for_side_effects()
        greedy_count = len(greedy.binder.binds)
        tpu = build("allocate_tpu")
        assert tpu.wait_for_side_effects()
        tpu_count = len(tpu.binder.binds)

        # (a) capacity respected per node
        cpu_cap = {f"n{j}": (2 + int(rng_state[0][j])) * 1000
                   for j in range(4)}
        cpu_of = {}
        for g in range(3):
            for i in range(8):
                cpu_of[f"ns/pg{g}-p{i}"] = int(rng_state[2][g][i])
        used = {}
        for pod_key, host in tpu.binder.binds.items():
            used[host] = used.get(host, 0) + cpu_of[pod_key]
        for host, total in used.items():
            assert total <= cpu_cap[host] + 10  # epsilon

        # (b) no placement regression vs greedy
        assert tpu_count >= greedy_count


class TestBatchApplyEquivalence:
    """allocate_batch (the vectorized apply path) must leave cache and
    session in exactly the state the per-task ssn.allocate loop produces
    for the same solved assignment set."""

    def _build(self, seed=7):
        rng = np.random.RandomState(seed)
        c = make_cache()
        c.add_queue(build_queue("qa", weight=1))
        c.add_queue(build_queue("qb", weight=2))
        sizes = rng.choice([250, 500, 1000, 2000], size=24)
        for j in range(5):
            c.add_node(build_node(
                f"n{j}", build_resource_list(cpu="6", memory="24Gi",
                                             pods=110)))
        for g in range(4):
            c.add_pod_group(build_pod_group(
                f"pg{g}", namespace="ns", min_member=3,
                queue="qa" if g % 2 else "qb"))
            for i in range(6):
                t = g * 6 + i
                c.add_pod(build_pod(
                    "ns", f"pg{g}-p{i}", "", PodPhase.PENDING,
                    build_resource_list(cpu=f"{int(sizes[t])}m",
                                        memory="512Mi"),
                    group_name=f"pg{g}"))
        return c

    @staticmethod
    def _state(c, ssn):
        nodes = {
            name: (n.idle.milli_cpu, n.idle.memory, n.used.milli_cpu,
                   sorted(n.tasks))
            for name, n in ssn.nodes.items()
        }
        statuses = {
            t.uid: t.status.name
            for job in ssn.jobs.values()
            for t in job.tasks.values()
        }
        return nodes, statuses, dict(c.binder.binds)

    def test_batch_matches_sequential(self):
        import jax

        with jax.default_device(jax.devices("cpu")[0]):
            from kube_batch_tpu.solver import solve_jit

            results = []
            for mode in ("batch", "sequential"):
                c = self._build()
                ssn = open_session(c, make_tiers(*DEFAULT_TIERS_ARGS))
                inputs, ctx = tensorize(ssn)
                assigned = np.asarray(solve_jit(inputs).assigned)
                sel = [i for i in range(len(ctx.tasks)) if assigned[i] >= 0]
                assert sel, "solver placed nothing; test is vacuous"
                if mode == "batch":
                    ssn.allocate_batch(
                        [(ctx.tasks[i], ctx.nodes[assigned[i]].name)
                         for i in sel]
                    )
                else:
                    for i in sel:
                        ssn.allocate(ctx.tasks[i],
                                     ctx.nodes[assigned[i]].name)
                assert c.wait_for_side_effects()
                results.append(self._state(c, ssn))
                close_session(ssn)

        batch, sequential = results
        assert batch[0] == sequential[0]  # node accounting identical
        assert batch[1] == sequential[1]  # task statuses identical
        assert batch[2] == sequential[2]  # bound pods identical
