"""Solver fault containment: degradation ladder plumbing, circuit
breaker, and solve deadlines.

The scheduler's availability contract (doc/design/robustness.md): an
accelerator failure degrades scheduling QUALITY, never scheduler
LIVENESS. Three cooperating pieces live here, consumed by
``actions/allocate_tpu.py`` and ``scheduler.py``:

- **Typed failures + deadline waits.** :class:`SolveFailed` /
  :class:`SolveTimeout` are what ``AsyncSolveHandle.fetch`` raises
  (memoized — a handle that failed once keeps failing the same way);
  :func:`call_with_deadline` runs a blocking materialization on a
  detached daemon thread so a hung device sync can be ABANDONED at the
  budget instead of wedging the cycle loop (the late result, if it
  ever arrives, is discarded).

- **Circuit breaker** (:data:`BREAKER`). M consecutive device-path
  failures open it; while open, allocate_tpu pins cycles straight to
  the native floor (no device dispatch, no per-cycle failure latency).
  After a cooldown measured in CYCLES (wall time would break sim
  replay determinism) the breaker half-opens and runs a bounded canary
  probe — a tiny last-good jitted solve, the in-cycle analog of the
  ``ensure_live_backend`` startup probe — and re-closes on success.
  The probe is synchronous but deadline-bounded, so re-promotion costs
  at most ``probe_timeout`` once per cooldown window.

- **Fault-injection seam** (:func:`set_device_fault_hook`). The
  deterministic simulator arms a hook that raises (``solver-exc`` /
  ``backend-loss``) or outsleeps the budget (``solver-hang``) inside
  the device-solve materialization and the canary probe — planned from
  the seeded fault stream, so chaos runs replay bit-identically.

The solve budget is derived from the driving scheduler's
``schedule_period`` (stamped via :func:`configure_from_period` at
Scheduler construction; the simulator then overrides it with a small
real-time budget so injected hangs cost fractions of a second);
``KBT_SOLVE_BUDGET`` overrides both for operators.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Callable, Optional

import numpy as np

from ..utils.lockdebug import witness_writes, wrap_lock

logger = logging.getLogger(__name__)


class SolveFailed(RuntimeError):
    """A solve attempt failed (wraps the original exception). Raised
    consistently by ``AsyncSolveHandle.fetch`` — including on re-fetch
    of a handle whose first fetch raised (the failure is memoized; a
    consumed concurrent.futures future would otherwise raise a
    different error the second time)."""


class SolveTimeout(SolveFailed):
    """The solve exceeded its deadline budget and was abandoned."""


# -- deadline-bounded waits ---------------------------------------------------


def call_with_deadline(fn, timeout: float, label: str = "solve"):
    """Run ``fn()`` on a detached daemon thread; return its result or
    raise within ``timeout`` seconds. On expiry raises
    :class:`SolveTimeout` and ABANDONS the thread — it keeps running
    (there is no way to cancel a foreign blocking call) but its late
    result/exception is discarded, never delivered. The caller must
    treat whatever the call was reading as quarantined."""
    box: dict = {}
    done = threading.Event()

    def runner():
        try:
            box["result"] = fn()
        except BaseException as exc:  # delivered to the waiter below
            box["error"] = exc
        finally:
            done.set()

    thread = threading.Thread(
        target=runner, daemon=True, name=f"kbt-deadline-{label}"
    )
    thread.start()
    if not done.wait(timeout):
        raise SolveTimeout(
            f"{label} exceeded its {timeout:.3f}s budget; abandoned "
            f"(late result will be discarded)"
        )
    if "error" in box:
        raise box["error"]
    return box.get("result")


# -- solve budget -------------------------------------------------------------

# Default when no scheduler has stamped a period-derived budget and no
# env override exists: generous enough for a cold-compile first solve,
# small enough that a wedged backend costs one budget, not forever.
DEFAULT_SOLVE_BUDGET = 30.0

_config = {"solve_budget": None}


def configure(solve_budget: Optional[float] = None) -> None:
    """Stamp the process-wide solve budget. ``None`` clears back to
    the default. Callers: ``Scheduler.__init__`` (period-derived, via
    :func:`configure_from_period`) and the simulator (small real-time
    budget — constructed AFTER its Scheduler, so its stamp wins)."""
    _config["solve_budget"] = solve_budget


def configure_from_period(schedule_period: float) -> float:
    """Derive + stamp the solve budget from the scheduler's cycle
    period: generous enough that a healthy solve (cold compiles
    included) never trips it, bounded so a wedged backend costs one
    budget. Returns the stamped value."""
    budget = max(DEFAULT_SOLVE_BUDGET, 10.0 * float(schedule_period))
    configure(budget)
    return budget


def solve_budget() -> float:
    """Effective fetch deadline: ``KBT_SOLVE_BUDGET`` env wins, then
    the configured (scheduler-derived) value, then the default."""
    env = os.environ.get("KBT_SOLVE_BUDGET")
    if env:
        try:
            return float(env)
        except ValueError:
            logger.warning("unparseable KBT_SOLVE_BUDGET=%r ignored", env)
    return _config["solve_budget"] or DEFAULT_SOLVE_BUDGET


# -- fault-injection seam (deterministic simulator) ---------------------------

# callable(stage: str) -> None; stage is "solve" (device-solve
# materialization) or "probe" (breaker canary). May raise to fail the
# stage or sleep past the budget to simulate a hang. None in production.
_DEVICE_FAULT_HOOK: Optional[Callable[[str], None]] = None


def set_device_fault_hook(hook: Optional[Callable[[str], None]]) -> None:
    global _DEVICE_FAULT_HOOK
    _DEVICE_FAULT_HOOK = hook


def device_fault_hook() -> Optional[Callable[[str], None]]:
    return _DEVICE_FAULT_HOOK


# callable(assigned: np.ndarray) -> np.ndarray; the simulator's
# solver-corrupt fault TAMPERS with a device rung's fetched assignment
# vector here — modeling a silent miscompute rather than a raise/hang —
# so the post-solve validation layer (solver/validate.py) has a real
# corrupted result to reject. None in production. Applied to device
# rungs only: the native floor's result is host-computed and is the
# trusted fallback the ladder descends to.
_RESULT_TAMPER_HOOK: Optional[Callable] = None


def set_result_tamper_hook(hook: Optional[Callable]) -> None:
    global _RESULT_TAMPER_HOOK
    _RESULT_TAMPER_HOOK = hook


def apply_result_tamper(assigned: object) -> object:
    """Run the sim's result-tamper hook, if armed (device rungs only —
    see the allocate_tpu ladder)."""
    hook = _RESULT_TAMPER_HOOK
    if hook is None:
        return assigned
    return hook(assigned)


# -- ladder helpers -----------------------------------------------------------


def strip_candidates(inputs):
    """Dense-rung inputs from sparse-rung inputs: drop the top-K
    candidate slabs so ``solve_sharded``/``solve_auto`` dispatch the
    dense program. The replacement fields are HOST numpy empties (the
    same shapes dense tensorize produces) — a wedged device must not be
    touched just to build the fallback bundle."""
    if getattr(inputs, "cand_idx", None) is None:
        return inputs
    return inputs._replace(
        cand_idx=np.zeros((0, 1), dtype=np.int32),
        cand_static=np.zeros((0, 1), dtype=np.float32),
        cand_info=np.zeros((3, 0), dtype=np.int32),
    )


# Most recent ladder descent (one small dict, overwritten per fallback):
# the /debug/vars "one-curl visibility into degraded mode" surface.
last_fallback: dict = {}


def note_fallback(frm: str, to: str, reason: str, exc: str = "") -> None:
    last_fallback.clear()
    last_fallback.update(
        {"from": frm, "to": to, "reason": reason, "exc": exc,
         "ts": time.time()}
    )


# -- circuit breaker ----------------------------------------------------------

STATE_CLOSED = "closed"
STATE_OPEN = "open"
STATE_HALF_OPEN = "half-open"


def _canary_probe(timeout: float) -> bool:
    """Bounded device-health probe: re-run the cached solver jit on a
    tiny canary input and force the one device→host sync. True iff the
    whole round trip completes within ``timeout``. Consults the sim
    fault hook first so injected backend loss fails the probe
    deterministically."""
    hook = _DEVICE_FAULT_HOOK
    if hook is not None:
        hook("probe")  # raises while the injected fault window is open

    def run():
        import jax.numpy as jnp

        from .kernels import make_inputs, solve_jit

        inputs = make_inputs(
            task_req=jnp.asarray([[1.0, 1.0]], jnp.float32),
            task_fit=jnp.asarray([[1.0, 1.0]], jnp.float32),
            task_rank=jnp.zeros(1, jnp.int32),
            task_job=jnp.zeros(1, jnp.int32),
            task_queue=jnp.zeros(1, jnp.int32),
            node_idle=jnp.asarray([[4.0, 4.0]], jnp.float32),
            node_releasing=jnp.zeros((1, 2), jnp.float32),
            node_cap=jnp.asarray([[4.0, 4.0]], jnp.float32),
            node_task_count=jnp.zeros(1, jnp.int32),
            node_max_tasks=jnp.zeros(1, jnp.int32),
            queue_deserved=jnp.full((1, 2), jnp.inf, jnp.float32),
            queue_allocated=jnp.zeros((1, 2), jnp.float32),
            eps=jnp.full((2,), 1e-3, jnp.float32),
            lr_weight=jnp.asarray(1.0, jnp.float32),
            br_weight=jnp.asarray(0.0, jnp.float32),
        )
        result = solve_jit(inputs, max_rounds=4)
        np.asarray(result.assigned)  # the device→host block point
        return True

    return bool(call_with_deadline(run, timeout, label="canary-probe"))


class CircuitBreaker:
    """Closed → (M consecutive device failures) → open → (cooldown
    cycles, then canary probe) → half-open → closed | open.

    Cycle-counted cooldown, synchronous bounded probe: both choices are
    what keep a chaos-sim run (and its replay) bit-deterministic — no
    wall-clock races decide which cycle re-promotes. ``pin_open`` is
    the operator/bench override: stay open unconditionally (no probe)
    until ``unpin``."""

    def __init__(
        self,
        failure_threshold: int = 3,
        cooldown_cycles: int = 8,
        probe: Optional[Callable[[float], bool]] = None,
        probe_timeout: float = 5.0,
    ):
        self._lock = wrap_lock("solver.breaker")
        self.failure_threshold = int(
            os.environ.get("KBT_BREAKER_THRESHOLD", failure_threshold)
        )
        self.cooldown_cycles = int(
            os.environ.get("KBT_BREAKER_COOLDOWN", cooldown_cycles)
        )
        self.probe = probe or _canary_probe
        self.probe_timeout = probe_timeout
        self.state = STATE_CLOSED
        self.failure_streak = 0
        self.trips = 0
        self.reclosures = 0
        self.probes_ok = 0
        self.probes_failed = 0
        self.last_failure: Optional[dict] = None
        self._cooldown_left = 0
        self._opened_ts: Optional[float] = None
        self._pinned_reason: Optional[str] = None
        # KBT_LOCK_DEBUG=2 write-witness: every transition field is
        # lock-guarded by contract (no-op below level 2).
        witness_writes(self, "solver.breaker", (
            "state", "failure_streak", "trips", "reclosures",
            "probes_ok", "probes_failed", "last_failure",
            "_cooldown_left", "_opened_ts", "_pinned_reason",
        ))

    # -- transitions (callers hold no lock) ----------------------------------

    def _set_state(self, state: str, transition: bool = True) -> None:
        """Lock held by caller. ``transition=False`` updates the state
        gauge without counting a transition — pin/unpin are operator
        overrides, and ``solver_breaker_transitions_total``'s documented
        semantics are quarantine trips / canary re-promotions only."""
        if state == self.state:
            return
        self.state = state
        try:
            from .. import metrics

            metrics.update_breaker_state(state, transition=transition)
        except Exception:  # pragma: no cover - metrics must never kill
            logger.exception("breaker metric update failed")

    def record_device_failure(self, reason: str, exc: str = "",
                              open_now: bool = False) -> None:
        """One device-path solve failed (exception or abandoned on
        timeout). Opens the breaker at the threshold; a half-open
        failure re-opens immediately. ``open_now`` skips the threshold
        — a solve ABANDONED on timeout left a wedged device sync behind
        it, and re-dispatching next cycle just to time out again costs
        a full budget per cycle, so quarantine immediately."""
        with self._lock:
            self.failure_streak += 1
            self.last_failure = {
                "reason": reason, "exc": exc, "ts": time.time(),
            }
            should_open = (
                open_now
                or self.state == STATE_HALF_OPEN
                or (
                    self.state == STATE_CLOSED
                    and self.failure_streak >= self.failure_threshold
                )
            )
            if should_open:
                self.trips += 1
                self._cooldown_left = self.cooldown_cycles
                self._opened_ts = time.time()
                self._set_state(STATE_OPEN)
                logger.error(
                    "solver circuit breaker OPEN after %d consecutive "
                    "device failures (last: %s %s); pinning cycles to "
                    "the native floor for %d cycles",
                    self.failure_streak, reason, exc, self._cooldown_left,
                )

    def record_device_success(self) -> None:
        with self._lock:
            self.failure_streak = 0

    def allow_device(self) -> bool:
        """Gate consulted once per cycle BEFORE tensorize. Closed →
        True. Open → tick the cooldown; when it expires, half-open and
        run the bounded canary probe synchronously: success re-closes
        (this very cycle runs on the device again), failure re-opens
        with a fresh cooldown."""
        with self._lock:
            if self._pinned_reason is not None:
                return False
            if self.state == STATE_CLOSED:
                return True
            if self.state == STATE_OPEN:
                self._cooldown_left -= 1
                if self._cooldown_left > 0:
                    return False
                self._set_state(STATE_HALF_OPEN)
            # half-open: probe outside the state flip but under the
            # lock — one loop, one breaker; a concurrent /debug/vars
            # reader uses state_dict() which takes the lock briefly.
            probe = self.probe
            timeout = min(self.probe_timeout, max(0.1, solve_budget()))
        ok = False
        try:
            ok = bool(probe(timeout))
        except Exception as exc:
            logger.warning("breaker canary probe raised: %s", exc)
        with self._lock:
            if ok:
                self.probes_ok += 1
                self.reclosures += 1
                self.failure_streak = 0
                self._opened_ts = None
                self._set_state(STATE_CLOSED)
                logger.warning(
                    "solver circuit breaker re-CLOSED: canary probe "
                    "succeeded; device path re-promoted"
                )
                return True
            self.probes_failed += 1
            self._cooldown_left = self.cooldown_cycles
            self._set_state(STATE_OPEN)
            return False

    def pin_open(self, reason: str) -> None:
        """Hold the breaker open unconditionally (no cooldown, no
        probe) — the bench ``degraded`` point and operator overrides."""
        with self._lock:
            self._pinned_reason = reason
            if self._opened_ts is None:
                self._opened_ts = time.time()
            self._set_state(STATE_OPEN, transition=False)

    def unpin(self) -> None:
        with self._lock:
            self._pinned_reason = None
            self._opened_ts = None
            self.failure_streak = 0
            self._cooldown_left = 0
            self._set_state(STATE_CLOSED, transition=False)

    def state_dict(self) -> dict:
        """/debug/vars + flight-record snapshot."""
        with self._lock:
            return {
                "state": self.state,
                "failure_streak": self.failure_streak,
                "failure_threshold": self.failure_threshold,
                "trips": self.trips,
                "reclosures": self.reclosures,
                "cooldown_cycles_left": max(0, self._cooldown_left),
                "quarantine_age_seconds": (
                    round(time.time() - self._opened_ts, 3)
                    if self._opened_ts is not None else None
                ),
                "probes": {
                    "ok": self.probes_ok, "failed": self.probes_failed,
                },
                "pinned": self._pinned_reason,
                "last_failure": (
                    dict(self.last_failure) if self.last_failure else None
                ),
            }


BREAKER = CircuitBreaker()


def reset_breaker(**kwargs) -> CircuitBreaker:
    """Fresh breaker (tests, and each simulator run — breaker state
    must not leak from a recording run into its replay)."""
    global BREAKER
    BREAKER = CircuitBreaker(**kwargs)
    last_fallback.clear()
    try:
        from .. import metrics

        metrics.update_breaker_state(STATE_CLOSED, transition=False)
    except Exception:  # pragma: no cover
        pass
    return BREAKER
