#!/usr/bin/env bash
# Disposable-real-cluster e2e: the reference hack/run-e2e-kind.sh:46-82
# analog, end to end. Creates a kind cluster, installs the CRDs and the
# Helm chart (scheduler image built and side-loaded), runs a gang spec
# and a preempt spec via kubectl against the REAL apiserver (its
# validation/RBAC/conflict behavior — what the in-repo fake cannot
# prove), then tears everything down.
#
# Requirements (documented, NOT vendored): docker, kind, kubectl, helm.
# This script cannot run in network-restricted sandboxes; CI wires it
# as an optional job (.github/workflows/ci.yml, workflow_dispatch).
#
# Usage: ./hack/run-e2e-kind.sh [--keep]
set -euo pipefail
cd "$(dirname "$0")/.."

CLUSTER=tpu-batch-e2e
NS=tpu-batch-e2e
KEEP="${1:-}"

for bin in docker kind kubectl helm; do
    command -v "$bin" >/dev/null || { echo "$bin not found" >&2; exit 2; }
done

cleanup() {
    rc=$?
    if [ "$rc" -ne 0 ]; then
        echo "==== scheduler logs ====" >&2
        kubectl logs -n kube-system deploy/tpu-batch --tail=100 >&2 || true
        kubectl get pods -n "$NS" -o wide >&2 || true
        kubectl get podgroups -n "$NS" -o yaml >&2 || true
    fi
    [ "$KEEP" = "--keep" ] || kind delete cluster --name "$CLUSTER" || true
}
trap cleanup EXIT

# -- cluster up (reference run-e2e-kind.sh:46-52) -------------------------
kind create cluster --name "$CLUSTER" --wait 120s
kubectl config use-context "kind-$CLUSTER"

# -- scheduler image + chart (reference :66-79, helm path) ----------------
docker build -f deployment/images/Dockerfile -t tpu-batch:latest .
kind load docker-image tpu-batch:latest --name "$CLUSTER"
kubectl apply -f config/crds/
helm install tpu-batch deployment/tpu-batch --namespace kube-system \
    --set image.repository=tpu-batch --set image.tag=latest \
    --set image.pullPolicy=IfNotPresent
kubectl rollout status -n kube-system deploy/tpu-batch --timeout=120s

kubectl create namespace "$NS"

wait_scheduled() { # name-prefix count timeout-seconds
    local prefix=$1 want=$2 budget=$3 n
    for _ in $(seq "$((budget / 2))"); do
        n=$(kubectl get pods -n "$NS" \
            -o jsonpath='{range .items[?(@.spec.nodeName)]}{.metadata.name}{"\n"}{end}' \
            | grep -c "^$prefix" || true)
        [ "$n" -ge "$want" ] && return 0
        sleep 2
    done
    return 1
}

# -- spec 1: gang all-or-nothing (reference test/e2e gang specs) ----------
kubectl apply -n "$NS" -f - <<'YAML'
apiVersion: scheduling.incubator.k8s.io/v1alpha2
kind: PodGroup
metadata:
  name: gang
spec:
  minMember: 3
  queue: default
YAML
for i in 0 1 2; do
kubectl apply -n "$NS" -f - <<YAML
apiVersion: v1
kind: Pod
metadata:
  name: gang-p$i
  annotations:
    scheduling.k8s.io/group-name: gang
spec:
  schedulerName: tpu-batch
  containers:
  - name: main
    image: registry.k8s.io/pause:3.9
    resources:
      requests: {cpu: 100m, memory: 64Mi}
YAML
done
wait_scheduled gang- 3 120 \
    && echo "PASS: gang 3/3 scheduled" \
    || { echo "FAIL: gang did not schedule" >&2; exit 1; }

# -- spec 2: priority preemption (reference test/e2e preempt spec) --------
# Fill the single kind node with a low-priority gang sized from its
# allocatable CPU, then submit a high-priority gang; with the preempt
# policy the high gang must evict and run.
kubectl apply -f - <<'YAML'
apiVersion: scheduling.k8s.io/v1
kind: PriorityClass
metadata:
  name: e2e-high
value: 1000
YAML

# allocatable.cpu is either bare cores ("8") or millicores ("7910m").
RAW_CPU=$(kubectl get node -o jsonpath='{.items[0].status.allocatable.cpu}')
case "$RAW_CPU" in
    *m) ALLOC_MILLI=${RAW_CPU%m};;
    *)  ALLOC_MILLI=$((RAW_CPU * 1000));;
esac
# Leave headroom for system pods; use 500m victims.
VICTIMS=$(( (ALLOC_MILLI - 1500) / 500 )); [ "$VICTIMS" -ge 2 ] || VICTIMS=2

kubectl apply -n "$NS" -f - <<YAML
apiVersion: scheduling.incubator.k8s.io/v1alpha2
kind: PodGroup
metadata:
  name: low
spec:
  minMember: $VICTIMS
  queue: default
YAML
for i in $(seq 0 $((VICTIMS - 1))); do
kubectl apply -n "$NS" -f - <<YAML
apiVersion: v1
kind: Pod
metadata:
  name: low-p$i
  annotations:
    scheduling.k8s.io/group-name: low
spec:
  schedulerName: tpu-batch
  containers:
  - name: main
    image: registry.k8s.io/pause:3.9
    resources:
      requests: {cpu: 500m, memory: 64Mi}
YAML
done
wait_scheduled low- "$VICTIMS" 120 \
    || { echo "FAIL: low-priority gang did not schedule" >&2; exit 1; }

# Switch the scheduler to the preempt policy for phase 2.
kubectl create configmap tpu-batch-preempt-conf -n kube-system \
    --from-literal=tpu-batch-conf.yaml="$(printf '%s\n' \
        'actions: "preempt, allocate, backfill"' \
        'tiers:' \
        '- plugins:' \
        '  - name: priority' \
        '  - name: gang' \
        '  - name: conformance' \
        '- plugins:' \
        '  - name: drf' \
        '  - name: predicates' \
        '  - name: proportion' \
        '  - name: nodeorder')"
helm upgrade tpu-batch deployment/tpu-batch --namespace kube-system \
    --reuse-values --set scheduler.policyConfigMap=tpu-batch-preempt-conf
kubectl rollout status -n kube-system deploy/tpu-batch --timeout=120s

kubectl apply -n "$NS" -f - <<'YAML'
apiVersion: scheduling.incubator.k8s.io/v1alpha2
kind: PodGroup
metadata:
  name: high
spec:
  minMember: 2
  queue: default
  priorityClassName: e2e-high
YAML
for i in 0 1; do
kubectl apply -n "$NS" -f - <<YAML
apiVersion: v1
kind: Pod
metadata:
  name: high-p$i
  annotations:
    scheduling.k8s.io/group-name: high
spec:
  schedulerName: tpu-batch
  priorityClassName: e2e-high
  containers:
  - name: main
    image: registry.k8s.io/pause:3.9
    resources:
      requests: {cpu: 500m, memory: 64Mi}
YAML
done
wait_scheduled high- 2 180 \
    && echo "PASS: high-priority gang preempted its way in" \
    || { echo "FAIL: high-priority gang did not schedule" >&2; exit 1; }

echo "ALL PASS"
