"""Shared plugin utilities.

Mirrors reference pkg/scheduler/plugins/util/util.go: the PodLister analog
(session pods with session-assigned node names projected on, :31-85) used by
pod-(anti)affinity evaluation, plus the predicate failure type.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from ..api import NodeInfo, Pod, TaskInfo, TaskStatus


class PredicateError(Exception):
    """A predicate rejection; carries a machine-readable reason."""

    def __init__(self, reason: str, message: str = ""):
        self.reason = reason
        self.message = message or reason
        super().__init__(self.message)


# Statuses that make a task "present" for (anti-)affinity evaluation: on a
# node now or headed there this session (includes PIPELINED, unlike
# api.allocated_status — a pipelined group-mate must anchor affinity).
PLACED_STATUSES = (
    TaskStatus.RUNNING,
    TaskStatus.ALLOCATED,
    TaskStatus.PIPELINED,
    TaskStatus.BINDING,
    TaskStatus.BOUND,
)


class SessionPodLister:
    """Lists session pods with the session's current node assignment
    (reference plugins/util/util.go:31-85: pods whose task moved in-session
    get a copy with NodeName updated)."""

    def __init__(self, ssn):
        self.ssn = ssn

    def tasks(self) -> List[TaskInfo]:
        out = []
        for job in self.ssn.jobs.values():
            out.extend(job.tasks.values())
        return out

    def pods_on_node(self, node_name: str) -> List[TaskInfo]:
        out = []
        for task in self.tasks():
            if task.node_name == node_name and task.status in PLACED_STATUSES:
                out.append(task)
        return out


def match_label_selector(selector: Dict[str, str], labels: Dict[str, str]) -> bool:
    """Plain equality-based selector match."""
    return all(labels.get(k) == v for k, v in selector.items())


def match_node_selector_terms(expressions: Optional[List[Dict]], labels: Dict[str, str]) -> bool:
    """Evaluate node-affinity match expressions (In/NotIn/Exists/DoesNotExist)."""
    if not expressions:
        return True
    for expr in expressions:
        key = expr.get("key", "")
        op = expr.get("operator", "In")
        values = expr.get("values", []) or []
        if op == "In":
            if labels.get(key) not in values:
                return False
        elif op == "NotIn":
            if labels.get(key) in values:
                return False
        elif op == "Exists":
            if key not in labels:
                return False
        elif op == "DoesNotExist":
            if key in labels:
                return False
        else:
            return False
    return True
