"""Known-good replay-determinism fixture: the sanctioned forms of
every taint class — seeded generator, sorted set walks, duration
clocks."""

import random
import time


def record_cycle(events, seed):
    rng = random.Random(seed)           # seeded generator: fine
    t0 = time.monotonic()               # duration clock: fine
    pending = set(events)
    ordered = [event for event in sorted(pending)]
    by_name = sorted((e for e in pending), key=str)
    elapsed = time.monotonic() - t0
    return rng.random(), ordered, by_name, elapsed
