"""Statement: two-phase commit over evict/pipeline operations.

Mirrors reference framework/statement.go (:28 struct, :37 Evict applies the
session-level effect immediately and records the op, :113 Pipeline, :198
Discard undoes in reverse order, :212 Commit applies the real cache evictions
— pipeline ops are session-only so commit is a no-op for them :156).

Used by the preempt action so a failed gang preemption rolls back cleanly.
"""

from __future__ import annotations

import logging
from typing import List, Tuple

from ..api import TaskInfo, TaskStatus
from .event import Event

logger = logging.getLogger(__name__)


class Statement:
    def __init__(self, ssn):
        self.ssn = ssn
        self.operations: List[Tuple[str, tuple]] = []

    # -- recorded operations -------------------------------------------------

    def evict(self, reclaimee: TaskInfo, reason: str) -> None:
        """Session-level evict now; cache evict deferred to commit
        (statement.go:37-69)."""
        job = self.ssn.jobs.get(reclaimee.job)
        if job is not None:
            job.update_task_status(reclaimee, TaskStatus.RELEASING)
            self.ssn._touched_jobs.add(reclaimee.job)
        node = self.ssn.nodes.get(reclaimee.node_name)
        if node is not None:
            node.update_task(reclaimee)
            self.ssn._touched_nodes.add(reclaimee.node_name)
        for eh in self.ssn.event_handlers:
            if eh.deallocate_func is not None:
                eh.deallocate_func(Event(reclaimee))
        self.operations.append(("evict", (reclaimee, reason)))

    def pipeline(self, task: TaskInfo, hostname: str) -> None:
        """statement.go:113-154"""
        job = self.ssn.jobs.get(task.job)
        if job is not None:
            job.update_task_status(task, TaskStatus.PIPELINED)
            self.ssn._touched_jobs.add(task.job)
        task.node_name = hostname
        node = self.ssn.nodes.get(hostname)
        if node is not None:
            self.ssn._touched_nodes.add(hostname)
            try:
                node.add_task(task)
            except ValueError:
                logger.exception(
                    "failed to pipeline task %s/%s to %s",
                    task.namespace, task.name, hostname,
                )
        for eh in self.ssn.event_handlers:
            if eh.allocate_func is not None:
                eh.allocate_func(Event(task))
        self.operations.append(("pipeline", (task, hostname)))

    # -- undo ops (statement.go:83-110, :159-195) ----------------------------

    def _unevict(self, reclaimee: TaskInfo) -> None:
        job = self.ssn.jobs.get(reclaimee.job)
        if job is not None:
            job.update_task_status(reclaimee, TaskStatus.RUNNING)
            self.ssn._touched_jobs.add(reclaimee.job)
        node = self.ssn.nodes.get(reclaimee.node_name)
        if node is not None:
            node.update_task(reclaimee)
            self.ssn._touched_nodes.add(reclaimee.node_name)
        for eh in self.ssn.event_handlers:
            if eh.allocate_func is not None:
                eh.allocate_func(Event(reclaimee))

    def _unpipeline(self, task: TaskInfo) -> None:
        job = self.ssn.jobs.get(task.job)
        if job is not None:
            job.update_task_status(task, TaskStatus.PENDING)
            self.ssn._touched_jobs.add(task.job)
        node = self.ssn.nodes.get(task.node_name)
        if node is not None:
            self.ssn._touched_nodes.add(task.node_name)
            try:
                node.remove_task(task)
            except KeyError:
                logger.exception(
                    "failed to unpipeline task %s/%s", task.namespace, task.name
                )
        task.node_name = ""
        for eh in self.ssn.event_handlers:
            if eh.deallocate_func is not None:
                eh.deallocate_func(Event(task))

    def _commit_evict(self, reclaimee: TaskInfo, reason: str) -> None:
        """statement.go:71-81"""
        try:
            self.ssn.cache.evict(reclaimee, reason)
        except Exception:
            logger.exception(
                "cache evict failed for %s/%s; rolling back",
                reclaimee.namespace, reclaimee.name,
            )
            self._unevict(reclaimee)

    # -- transaction ends ----------------------------------------------------

    def discard(self) -> None:
        """Undo in reverse (statement.go:198-209). Drains any in-flight
        async solve first: a rollback must not race an outstanding
        device computation over the same session snapshot."""
        self.ssn.drain_inflight_solve()
        for name, args in reversed(self.operations):
            if name == "evict":
                self._unevict(args[0])
            elif name == "pipeline":
                self._unpipeline(args[0])
        self.operations = []

    def commit(self) -> None:
        """Apply real cache evictions (statement.go:212-222). Drains
        any in-flight async solve first (see :meth:`discard`)."""
        self.ssn.drain_inflight_solve()
        for name, args in self.operations:
            if name == "evict":
                self._commit_evict(args[0], args[1])
            # pipeline is session-only (statement.go:156-157)
        self.operations = []
