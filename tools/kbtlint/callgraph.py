"""Approximate project call graph for reachability questions.

Both graph consumers ask the same shape of question: "may calling this
function (transitively) do X" — acquire a lock, stamp the dirty
ledger. The resolution is deliberately name-based and conservative:

- ``self.m(...)`` resolves to methods named ``m`` — preferring the
  caller's own class, then any class in the caller's module, then a
  project-unique method of that name (mixins split classes across
  files: ``SchedulerCache`` methods live in cache.py AND
  event_handlers.py).
- ``obj.m(...)`` resolves to a project-unique method/function named
  ``m`` — unless ``m`` is in the stoplist of ultra-common names, where
  name-matching would wire unrelated code together (``.get`` on a
  queue is not ``Registry.get``).
- ``f(...)`` resolves to a module-level function in the caller's
  module, then a project-unique one.

Unresolved calls contribute nothing (under-approximation); common-name
calls are skipped (avoiding over-approximation). Both error directions
exist — this is a lint, not a verifier — but the fixed point over the
resolved edges catches every same-named in-project chain, which is
what the PR 7/PR 8 bug classes were.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Set, Tuple

from .core import FuncDef, Project, call_name, iter_functions

# Method names too generic to resolve by name across the project.
COMMON_NAMES = frozenset({
    "get", "put", "pop", "add", "remove", "update", "set", "clear",
    "items", "keys", "values", "append", "extend", "discard", "copy",
    "clone", "submit", "wait", "notify", "notify_all", "acquire",
    "release", "start", "join", "run", "name", "close", "open", "read",
    "write", "sort", "index", "count", "format", "strip", "split",
    "setdefault", "difference_update", "union", "encode", "decode",
})


@dataclass
class CallSite:
    name: str
    recv_self: bool  # receiver is `self`/`cls`
    bare: bool  # plain `f(...)` (no receiver)
    node: ast.Call


@dataclass
class FuncEntry:
    fd: FuncDef
    calls: List[CallSite] = field(default_factory=list)


def get_callgraph(project: Project) -> "CallGraph":
    """One CallGraph per Project: lock-order and dirty-ledger both need
    it, and construction (plus the transitive fixed points) is the
    expensive half of a driver run."""
    graph = getattr(project, "_kbtlint_callgraph", None)
    if graph is None:
        graph = CallGraph(project)
        project._kbtlint_callgraph = graph
    return graph


class CallGraph:
    def __init__(self, project: Project):
        self.entries: Dict[str, FuncEntry] = {}
        # name -> [FuncEntry...] across the project
        self.by_name: Dict[str, List[FuncEntry]] = {}
        # (rel, name) -> [FuncEntry...] in one module
        self.by_module_name: Dict[Tuple[str, str], List[FuncEntry]] = {}
        for pf in project.files:
            for fd in iter_functions(pf):
                entry = FuncEntry(fd=fd)
                entry.calls = _collect_calls(fd.node)
                self.entries[fd.key] = entry
                self.by_name.setdefault(fd.name, []).append(entry)
                self.by_module_name.setdefault(
                    (fd.rel, fd.name), []
                ).append(entry)

    def resolve(self, caller: FuncEntry, site: CallSite) -> List[FuncEntry]:
        name = site.name
        if site.recv_self:
            same_class = [
                e for e in self.by_name.get(name, ())
                if e.fd.cls is not None and e.fd.cls == caller.fd.cls
            ]
            if same_class:
                return same_class
            # Mixin split: methods of one runtime class under different
            # class names across the package (EventHandlersMixin +
            # SchedulerCache). Any method of that name counts.
            methods = [
                e for e in self.by_name.get(name, ()) if e.fd.cls is not None
            ]
            return methods
        if site.bare:
            local = self.by_module_name.get((caller.fd.rel, name), [])
            if local:
                return local
            cands = self.by_name.get(name, [])
            return cands if len(cands) == 1 else []
        # obj.m(...): every project def of that non-common name — an
        # over-approximation (interface + N implementations all count),
        # which is the right direction for "may this call acquire X".
        if name in COMMON_NAMES:
            return []
        return list(self.by_name.get(name, ()))

    def transitive_marks(self, direct: Dict[str, Set[str]]) -> Dict[str, Set[str]]:
        """Fixed point: propagate per-function mark sets (e.g. lock ids
        the function may acquire) backward along call edges — a caller
        inherits its callees' marks."""
        marks: Dict[str, Set[str]] = {
            key: set(direct.get(key, ())) for key in self.entries
        }
        changed = True
        while changed:
            changed = False
            for key, entry in self.entries.items():
                acc = marks[key]
                before = len(acc)
                for site in entry.calls:
                    for callee in self.resolve(entry, site):
                        acc |= marks.get(callee.fd.key, set())
                if len(acc) != before:
                    changed = True
        return marks


def _collect_calls(func_node: ast.AST) -> List[CallSite]:
    sites: List[CallSite] = []
    for node in ast.walk(func_node):
        if not isinstance(node, ast.Call):
            continue
        name = call_name(node)
        if name is None:
            continue
        fn = node.func
        recv_self = bare = False
        if isinstance(fn, ast.Name):
            bare = True
        elif isinstance(fn, ast.Attribute):
            recv = fn.value
            recv_self = isinstance(recv, ast.Name) and recv.id in (
                "self", "cls"
            )
        sites.append(
            CallSite(name=name, recv_self=recv_self, bare=bare, node=node)
        )
    return sites
