"""Flight recorder: a fixed-size ring of per-cycle forensic records.

Aggregate Prometheus counters say THAT cycles are slow or failing; the
flight recorder says WHICH phase, with the solver's own attribution
(sparse engagement / refill rounds / fallback reason, device-cache
bytes shipped, verdict counts) and — on a cycle error — the failing
phase plus the full traceback, captured at the moment
``Scheduler.run_once_guarded`` absorbed it.

Dump triggers (doc/design/observability.md):
- cycle error in the guarded loop (written to ``KBT_FLIGHT_DIR`` when
  set; always kept in the ring either way);
- ``SIGUSR1`` (``install_sigusr1``), for a live process that is
  misbehaving but not erroring;
- the metrics HTTP server's ``/debug/flightrecorder`` endpoint;
- the simulator, alongside its JSONL trace, on any invariant violation
  or cycle error.

Records are canonical JSON (sorted keys) so dumps diff cleanly; values
that do not serialize are repr()'d rather than dropped.
"""

from __future__ import annotations

import json
import logging
import os
import signal
import threading
import time
import traceback
from collections import deque
from typing import List, Optional

from ..utils.lockdebug import witness_writes, wrap_lock

logger = logging.getLogger(__name__)

DUMP_VERSION = 1
FLIGHT_DIR_ENV = "KBT_FLIGHT_DIR"
FLIGHT_CAPACITY_ENV = "KBT_FLIGHT_CAPACITY"
DEFAULT_CAPACITY = 256


def _jsonable(obj):
    """Best-effort canonical-JSON coercion (numpy scalars, exceptions,
    arbitrary objects) — a forensic record must never fail to dump."""
    try:
        json.dumps(obj)
        return obj
    except (TypeError, ValueError):
        pass
    item = getattr(obj, "item", None)  # numpy scalars
    if callable(item):
        try:
            return _jsonable(item())
        except Exception:
            pass
    if isinstance(obj, dict):
        return {str(k): _jsonable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple, set, frozenset)):
        return [_jsonable(v) for v in obj]
    return repr(obj)


class FlightRecorder:
    def __init__(self, capacity: Optional[int] = None):
        if capacity is None:
            capacity = int(
                os.environ.get(FLIGHT_CAPACITY_ENV, DEFAULT_CAPACITY)
            )
        self.capacity = capacity
        self._ring: deque = deque(maxlen=capacity)
        self._lock = wrap_lock("obs.flightrecorder")
        self._seq = 0
        self._open: Optional[dict] = None
        self.started_at = time.time()
        self.last_cycle_ts: Optional[float] = None
        self.error_count = 0
        # KBT_LOCK_DEBUG=2 write-witness (no-op otherwise).
        witness_writes(self, "obs.flightrecorder", (
            "_seq", "_open", "last_cycle_ts", "error_count",
        ))

    # -- per-cycle lifecycle ------------------------------------------------

    def begin_cycle(self, cycle=None, kind: str = "periodic") -> dict:
        """Open this cycle's record; phases and annotations accumulate
        into it until :meth:`end_cycle` commits it to the ring.
        ``kind`` distinguishes the periodic loop from the event-driven
        micro-cycle fast path (``periodic`` | ``micro``)."""
        with self._lock:
            prev = self._open
            if prev is not None:
                # An unguarded caller raised past end_cycle: keep the
                # interrupted record rather than silently dropping it.
                prev["abandoned"] = True
                prev["ok"] = False
                self._ring.append(prev)
            self._seq += 1
            rec = {
                "seq": self._seq,
                "cycle": cycle if cycle is not None else self._seq - 1,
                "cycle_kind": kind,
                "t_start": time.time(),
                "phase": "start",
                "phases_ms": {},
            }
            self._open = rec
            return rec

    def phase(self, name: str) -> None:
        """Mark the phase the cycle is currently in — this is what an
        error dump reports as the failing phase. All mutations of the
        open record take the lock: snapshot()/dump() copy it from HTTP
        worker threads (and the SIGUSR1 dump thread) concurrently."""
        with self._lock:
            rec = self._open
            if rec is not None:
                rec["phase"] = name

    def phase_done(self, name: str, ms: float) -> None:
        with self._lock:
            rec = self._open
            if rec is not None:
                rec["phases_ms"][name] = round(float(ms), 3)

    def annotate(self, key: str, value) -> None:
        """Attach a forensic blob (solver stats, verdict counts, device
        cache) to the open record; no-op when no cycle is open (direct
        ``action.execute`` callers outside a scheduler loop)."""
        payload = _jsonable(value)
        with self._lock:
            rec = self._open
            if rec is not None:
                rec[key] = payload

    def mark_failed_phase(self) -> None:
        """Pin the currently-marked phase as the FAILING one — called
        from an except block before guard layers (a finally-close) move
        the phase on. :meth:`record_error` then reports it."""
        with self._lock:
            rec = self._open
            if rec is not None:
                rec["failed_phase"] = rec.get("phase")

    def record_error(self, exc: BaseException) -> dict:
        """Fold an absorbed cycle error into the open record (creating
        one if the failure predates begin_cycle) and commit it."""
        tb = traceback.format_exception(type(exc), exc, exc.__traceback__)
        with self._lock:
            rec = self._open
        if rec is None:
            rec = self.begin_cycle()
        with self._lock:
            # A guard layer (close_session in a finally) may have moved
            # the phase on after the failure — the pinned failing phase
            # wins.
            failed = rec.pop("failed_phase", None)
            if failed:
                rec["phase"] = failed
            rec["error"] = f"{type(exc).__name__}: {exc}"
            rec["traceback"] = tb
            self.error_count += 1
        return self.end_cycle(ok=False)

    def end_cycle(self, ok: bool = True, **extra) -> Optional[dict]:
        # Coerce outside the lock (can be arbitrarily nested), commit
        # atomically: a dump taken mid-commit must see the cycle either
        # still open or in the ring — never in neither.
        extra = {key: _jsonable(value) for key, value in extra.items()}
        with self._lock:
            rec = self._open
            if rec is None:
                return None
            self._open = None
            rec["t_end"] = time.time()
            rec["ok"] = bool(ok)
            rec.update(extra)
            self.last_cycle_ts = rec["t_end"]
            self._ring.append(rec)
        return rec

    # -- dumping ------------------------------------------------------------

    def snapshot(self) -> List[dict]:
        with self._lock:
            records = list(self._ring)
            open_rec = self._open
            if open_rec is not None:
                # Copy one level deep (phases_ms keeps being written by
                # the cycle thread) while still under the lock.
                open_rec = {
                    k: (dict(v) if isinstance(v, dict) else v)
                    for k, v in open_rec.items()
                }
                open_rec["in_flight"] = True
        if open_rec is not None:
            records.append(open_rec)
        return records

    def dump(self, reason: str = "on-demand") -> dict:
        out = {
            "type": "flightrecorder",
            "version": DUMP_VERSION,
            "reason": reason,
            "pid": os.getpid(),
            "dumped_at": time.time(),
            "started_at": self.started_at,
            "capacity": self.capacity,
            "cycle_errors": self.error_count,
            "records": _jsonable(self.snapshot()),
        }
        # Trajectory context rides along with the per-cycle forensics:
        # the newest telemetry rollup windows (obs/telemetry.py) say
        # whether the dumped cycles sit on a flat line or a trend.
        try:
            from .telemetry import TELEMETRY

            if TELEMETRY.cycles_observed:
                out["telemetry"] = _jsonable(
                    TELEMETRY.snapshot(recent_raw=32, recent_windows=64)
                )
        except Exception:  # pragma: no cover - dump must never fail
            logger.exception("telemetry embed in flight dump failed")
        # Placement-latency context: the ledger's engagement summary
        # (stage p99s, per-queue p99, requeue counters) + audit-ring
        # meta ride along, so an error dump answers "were pods waiting,
        # and how long" without a second endpoint scrape.
        try:
            from .latency import AUDIT, LEDGER

            if LEDGER.enabled and LEDGER.stamped:
                out["latency"] = _jsonable({
                    **LEDGER.summary(), "audit": AUDIT.meta(),
                })
        except Exception:  # pragma: no cover - dump must never fail
            logger.exception("latency embed in flight dump failed")
        return out

    def dump_json(self, reason: str = "on-demand") -> str:
        """Canonical JSON (sorted keys) of the whole ring."""
        return json.dumps(self.dump(reason), sort_keys=True)

    def dump_to(self, path: str, reason: str = "on-demand") -> str:
        parent = os.path.dirname(os.path.abspath(path))
        os.makedirs(parent, exist_ok=True)
        # Write-then-rename: dumps are picked up by pollers (the
        # SIGUSR1 workflow watches the directory for the dump name)
        # which must never see a half-written file. The scratch name is
        # a dotfile carrying neither the reason nor the target name so
        # name-based watchers cannot match it.
        tmp = os.path.join(
            parent,
            f".flightdump-{os.getpid()}-{threading.get_ident()}.tmp",
        )
        with open(tmp, "w") as f:
            f.write(self.dump_json(reason) + "\n")
        os.replace(tmp, path)
        return path

    def dump_on_error(self, directory: Optional[str] = None) -> Optional[str]:
        """Error-path dump: write to ``directory`` (default
        ``KBT_FLIGHT_DIR``) when one is configured; the ring keeps the
        record regardless."""
        directory = directory or os.environ.get(FLIGHT_DIR_ENV)
        if not directory:
            return None
        with self._lock:
            seq = self._seq
        path = os.path.join(
            directory, f"flight-{os.getpid()}-err-{seq}.json"
        )
        try:
            self.dump_to(path, reason="cycle-error")
        except OSError:
            logger.exception("flight-recorder error dump failed")
            return None
        logger.error("flight recorder dumped to %s", path)
        return path

    def reset(self) -> None:
        with self._lock:
            self._ring.clear()
            self._open = None
            self._seq = 0
            self.error_count = 0
            self.last_cycle_ts = None


RECORDER = FlightRecorder()


def install_sigusr1(directory: Optional[str] = None) -> bool:
    """SIGUSR1 → dump the global recorder to ``directory`` (default
    ``KBT_FLIGHT_DIR``, falling back to the process cwd). Returns False
    on platforms/threads where the handler cannot be installed."""

    def _dump():
        target = directory or os.environ.get(FLIGHT_DIR_ENV) or "."
        path = os.path.join(
            target, f"flight-{os.getpid()}-sigusr1-{int(time.time())}.json"
        )
        try:
            RECORDER.dump_to(path, reason="sigusr1")
            logger.info("flight recorder dumped to %s (SIGUSR1)", path)
        except OSError:
            logger.exception("SIGUSR1 flight dump failed")

    def _handler(signum, frame):  # pragma: no cover - exercised via kill
        # The handler runs ON the interrupted main thread, which may be
        # holding the recorder's (non-reentrant) lock mid-cycle — dump
        # from a fresh thread so the handler returns immediately and
        # the lock drains normally.
        threading.Thread(
            target=_dump, name="flight-sigusr1-dump", daemon=True
        ).start()

    try:
        signal.signal(signal.SIGUSR1, _handler)
        return True
    except (ValueError, OSError, AttributeError):
        # Non-main thread or platform without SIGUSR1.
        return False
