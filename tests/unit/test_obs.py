"""Observability layer tests: span tracer (thread safety, cross-thread
nesting, export), flight-recorder ring (wraparound, error capture,
SIGUSR1 dump roundtrip), and the /debug HTTP surface.
"""

import json
import os
import signal
import threading
import time
import urllib.request
from urllib.error import HTTPError

import pytest

from kube_batch_tpu.obs.flightrecorder import FlightRecorder, install_sigusr1
from kube_batch_tpu.obs.tracer import Tracer


# ------------------------------------------------------------------ tracer


def test_disabled_span_records_nothing():
    t = Tracer()
    with t.span("a"):
        with t.span("b"):
            pass
    assert t.events() == []
    assert t.spans_recorded == 0


def test_span_nesting_and_args():
    t = Tracer()
    t.enable()
    t.begin_cycle(7)
    with t.span("outer"):
        with t.span("inner", k=64):
            pass
    events = {e["name"]: e for e in t.events()}
    assert set(events) == {"outer", "inner"}
    outer, inner = events["outer"], events["inner"]
    assert inner["args"]["parent"] == outer["args"]["sid"]
    assert outer["args"]["parent"] == 0
    assert inner["args"]["cycle"] == 7
    assert inner["args"]["k"] == 64
    assert inner["ph"] == "X"
    assert inner["dur"] >= 0


def test_complete_records_retroactive_span():
    t = Tracer()
    t.enable()
    t0 = time.perf_counter()
    time.sleep(0.001)
    with t.span("parent"):
        t.complete("apply", t0)
    by_name = {e["name"]: e for e in t.events()}
    assert by_name["apply"]["args"]["parent"] == (
        by_name["parent"]["args"]["sid"]
    )
    assert by_name["apply"]["dur"] >= 1000  # >= 1ms in us


def test_worker_spans_nest_under_the_right_cycle():
    """Spans opened on worker threads (the overlapped solve/apply
    pattern) adopt the submitting span's id and the cycle stamp."""
    t = Tracer()
    t.enable()
    t.begin_cycle(3)
    results = []

    barrier = threading.Barrier(4)

    with t.span("cycle_span"):
        token = t.capture()

        def worker(i):
            # Barrier: all four workers are alive at once, so their
            # thread idents are guaranteed distinct (idents can be
            # reused once a thread exits).
            barrier.wait(timeout=10)
            with t.adopt(token), t.span(f"worker-{i}"):
                time.sleep(0.002)
            results.append(i)

        threads = [
            threading.Thread(target=worker, args=(i,)) for i in range(4)
        ]
        for th in threads:
            th.start()
        for th in threads:
            th.join()

    events = {e["name"]: e for e in t.events()}
    cycle_sid = events["cycle_span"]["args"]["sid"]
    tids = set()
    for i in range(4):
        ev = events[f"worker-{i}"]
        assert ev["args"]["parent"] == cycle_sid
        assert ev["args"]["cycle"] == 3
        tids.add(ev["tid"])
    assert len(tids) == 4  # genuinely distinct tracks
    assert sorted(results) == [0, 1, 2, 3]


def test_adopted_spans_keep_the_capturing_cycle():
    """Async side effects drain in the NEXT cycle's overlap window by
    design — their spans must still stamp the cycle that queued them,
    not whatever the scheduler thread advanced the counter to."""
    t = Tracer()
    t.enable()
    t.begin_cycle(5)
    with t.span("submitter"):
        token = t.capture()
    t.begin_cycle(6)  # scheduler moved on before the worker drained

    def worker():
        with t.adopt(token), t.span("late-side-effect"):
            with t.span("nested"):
                pass

    th = threading.Thread(target=worker)
    th.start()
    th.join()
    events = {e["name"]: e for e in t.events()}
    assert events["submitter"]["args"]["cycle"] == 5
    assert events["late-side-effect"]["args"]["cycle"] == 5
    assert events["nested"]["args"]["cycle"] == 5
    # A fresh span on the main thread sees the advanced cycle.
    with t.span("current"):
        pass
    assert {e["name"]: e for e in t.events()}["current"]["args"][
        "cycle"
    ] == 6


def test_tracer_thread_safety_under_contention():
    """Many threads spanning concurrently: every span is recorded, no
    event is torn/corrupt."""
    t = Tracer(capacity=100_000)
    t.enable()
    n_threads, per_thread = 8, 200

    def hammer(k):
        for i in range(per_thread):
            with t.span("s", thread=k, i=i):
                pass

    threads = [
        threading.Thread(target=hammer, args=(k,))
        for k in range(n_threads)
    ]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    events = t.events()
    assert len(events) == n_threads * per_thread
    assert t.spans_recorded == n_threads * per_thread
    sids = [e["args"]["sid"] for e in events]
    assert len(set(sids)) == len(sids)  # unique span ids


def test_event_ring_caps_memory():
    t = Tracer(capacity=10)
    t.enable()
    for i in range(25):
        with t.span(f"s{i}"):
            pass
    assert len(t.events()) == 10
    assert t.spans_recorded == 25
    assert t.dropped == 15
    # The ring keeps the NEWEST spans.
    assert t.events()[-1]["name"] == "s24"


def test_export_chrome_trace(tmp_path):
    t = Tracer()
    t.enable()
    with t.span("a"):
        with t.span("b"):
            pass
    path = t.export(str(tmp_path / "trace.json"))
    with open(path) as f:
        doc = json.load(f)
    events = doc["traceEvents"]
    xs = [e for e in events if e.get("ph") == "X"]
    metas = [e for e in events if e.get("ph") == "M"]
    assert {e["name"] for e in xs} == {"a", "b"}
    assert metas and metas[0]["name"] == "thread_name"


# --------------------------------------------------------- flight recorder


def test_ring_buffer_wraparound():
    fr = FlightRecorder(capacity=4)
    for i in range(10):
        fr.begin_cycle(i)
        fr.phase("open_session")
        fr.phase_done("open_session", 1.0)
        fr.end_cycle(e2e_ms=float(i))
    records = fr.snapshot()
    assert len(records) == 4
    assert [r["cycle"] for r in records] == [6, 7, 8, 9]
    assert all(r["ok"] for r in records)
    # seq keeps counting monotonically across wraps.
    assert [r["seq"] for r in records] == [7, 8, 9, 10]


def test_error_capture_pins_failing_phase():
    fr = FlightRecorder(capacity=8)
    fr.begin_cycle(0)
    fr.phase("action:allocate_tpu")
    try:
        raise RuntimeError("kaboom")
    except RuntimeError as exc:
        # Scheduler's finally moves the phase on; the pinned
        # failed phase must win in the committed record.
        fr.mark_failed_phase()
        fr.phase("close_session")
        fr.record_error(exc)
    last = fr.snapshot()[-1]
    assert last["ok"] is False
    assert last["phase"] == "action:allocate_tpu"
    assert "RuntimeError: kaboom" in last["error"]
    assert any("kaboom" in line for line in last["traceback"])
    assert fr.error_count == 1


def test_annotate_and_open_record_in_dump():
    fr = FlightRecorder(capacity=4)
    fr.begin_cycle(0)
    fr.annotate("solver", {"backend": "native", "placed": 10})
    dump = json.loads(fr.dump_json("test"))
    assert dump["type"] == "flightrecorder"
    assert dump["records"][-1]["in_flight"] is True
    assert dump["records"][-1]["solver"]["backend"] == "native"
    # Canonical: dumps twice byte-identically (modulo dumped_at).
    fr.end_cycle()


def test_annotate_coerces_unserializable_values():
    import numpy as np

    fr = FlightRecorder(capacity=2)
    fr.begin_cycle(0)
    fr.annotate("solver", {
        "placed": np.int64(5), "frac": np.float32(0.5),
        "obj": object(),
    })
    fr.end_cycle()
    dump = json.loads(fr.dump_json("test"))
    solver = dump["records"][-1]["solver"]
    assert solver["placed"] == 5
    assert isinstance(solver["obj"], str)


def test_sigusr1_dump_roundtrip(tmp_path):
    fr_dir = str(tmp_path)
    from kube_batch_tpu.obs.flightrecorder import RECORDER

    RECORDER.begin_cycle(0)
    RECORDER.phase("action:allocate_tpu")
    RECORDER.end_cycle(e2e_ms=1.0)
    installed = install_sigusr1(fr_dir)
    if not installed:
        pytest.skip("cannot install SIGUSR1 handler on this platform")
    try:
        os.kill(os.getpid(), signal.SIGUSR1)
        deadline = time.time() + 5.0
        dumps = []
        while time.time() < deadline:
            dumps = [
                f for f in os.listdir(fr_dir) if "sigusr1" in f
            ]
            if dumps:
                break
            time.sleep(0.02)
        assert dumps, "SIGUSR1 produced no dump file"
        with open(os.path.join(fr_dir, dumps[0])) as f:
            doc = json.load(f)
        assert doc["reason"] == "sigusr1"
        assert doc["records"], "dump carries no records"
        assert doc["records"][-1]["phases_ms"] is not None
    finally:
        signal.signal(signal.SIGUSR1, signal.SIG_DFL)


# ------------------------------------------------------------ HTTP surface


@pytest.fixture
def debug_server():
    from kube_batch_tpu.cli import start_metrics_server

    server, _thread = start_metrics_server("127.0.0.1:0")
    port = server.server_address[1]
    yield f"http://127.0.0.1:{port}"
    server.shutdown()


def _get(url):
    with urllib.request.urlopen(url, timeout=5) as resp:
        return resp.status, resp.read().decode()


def test_healthz_and_debug_vars(debug_server):
    status, body = _get(f"{debug_server}/healthz")
    assert status == 200 and body == "ok\n"
    status, body = _get(f"{debug_server}/debug/vars")
    assert status == 200
    doc = json.loads(body)
    assert doc["version"]
    assert doc["uptime_seconds"] >= 0
    assert "cycle_errors" in doc
    assert "last_cycle_age_seconds" in doc


def test_debug_flightrecorder_endpoint(debug_server):
    from kube_batch_tpu.obs.flightrecorder import RECORDER

    RECORDER.begin_cycle(0)
    RECORDER.end_cycle()
    status, body = _get(f"{debug_server}/debug/flightrecorder")
    assert status == 200
    doc = json.loads(body)
    assert doc["type"] == "flightrecorder"
    assert doc["records"]


def test_unknown_path_gets_404_with_body(debug_server):
    with pytest.raises(HTTPError) as err:
        _get(f"{debug_server}/nope/nothing")
    assert err.value.code == 404
    body = err.value.read().decode()
    assert "/nope/nothing" in body  # NOT a silent empty 404


def test_debug_jobs_unknown_job_404(debug_server):
    with pytest.raises(HTTPError) as err:
        _get(f"{debug_server}/debug/jobs/ns/ghost")
    assert err.value.code == 404
    assert "ns/ghost" in err.value.read().decode()
