"""Retrace-regression guard: steady/delta cycles must not mint new jit
compilations.

The whole device-resident design leans on shape stability — task/node/
group/pair axes are bucketed (snapshot._task_bucket/_pow2/128s) and the
patch row axis is power-of-two bucketed — so a long-running scheduler
compiles a bounded set of programs and then runs trace-free. A shape or
dtype drift anywhere in the pack (a field stacked in a different order,
an un-bucketed axis, a float64 leak) would silently reintroduce
per-cycle tracing: ~seconds of XLA compile inside a ~10 ms cycle
budget. This test pins the invariant with the compilation-cache
counters (``jit_compilation_count``: solve jits + device-cache patch
jits) across churning cycles that stay inside their buckets.
"""

import numpy as np
import pytest

import kube_batch_tpu.actions  # noqa: F401 (registers actions)
import kube_batch_tpu.plugins  # noqa: F401 (registers plugins)
from kube_batch_tpu.framework import close_session, open_session
from kube_batch_tpu.solver import (
    jit_compilation_count,
    solve_jit,
    solve_sharded,
    tensorize,
)

from tests.actions.test_actions import DEFAULT_TIERS_ARGS, make_tiers
from tests.unit.test_cycle_pipeline import build_cluster


WARM_CYCLES = 3   # cold pack + first patch buckets + solve compile
GUARD_CYCLES = 6  # steady/delta cycles that must stay trace-free


def one_cycle(cache, tiers, churn, solver=None):
    """One tensorize → solve → apply-some cycle; churn keeps every axis
    inside its shape bucket (fixed task count per step, fixed node
    fan-out) so no re-jit is legitimate."""
    solver = solver or solve_jit
    ssn = open_session(cache, tiers)
    inputs, ctx = tensorize(ssn)
    placed = 0
    if inputs is not None:
        result = solver(inputs)
        assigned = np.asarray(result.assigned)
        # Apply a FIXED-SIZE slice of the assignment through the
        # session so the mirror churns by the same amount every cycle.
        pairs = []
        for i in np.nonzero(assigned[: len(ctx.tasks)] >= 0)[0][:churn]:
            pairs.append((ctx.tasks[i], ctx.nodes[assigned[i]].name))
        if pairs:
            placed = ssn.allocate_batch(pairs)
    assert cache.wait_for_side_effects()
    assert cache.wait_for_bookkeeping()
    close_session(ssn)
    return placed


def test_zero_new_compilations_across_steady_delta_cycles():
    # 240 pending tasks: stays inside the 256-row task bucket for the
    # whole run (churn of 2/cycle drains 18 by the end).
    c = build_cluster(seed=43, groups=6, per_group=40, nodes=8)
    tiers = make_tiers(*DEFAULT_TIERS_ARGS)
    for _ in range(WARM_CYCLES):
        one_cycle(c, tiers, churn=2)
    warm = jit_compilation_count()
    assert warm > 0  # the solve jit at least compiled once
    for cycle in range(GUARD_CYCLES):
        one_cycle(c, tiers, churn=2)
        now = jit_compilation_count()
        assert now == warm, (
            f"cycle {cycle} minted {now - warm} new jit compilation(s) "
            "— a shape/dtype drift reintroduced per-cycle tracing"
        )
    c.shutdown()


def test_zero_new_compilations_with_serving_rows_present():
    """Serving twin (doc/design/serving.md): SLO-constrained jobs add
    feasibility-mask group rows and per-task score rows to the pack.
    With a fixed set of constraint signatures the group axis is as
    shape-stable as every other axis — steady/delta cycles over a mixed
    serving+batch snapshot on a labeled (heterogeneous) node pool must
    stay trace-free after warmup."""
    from kube_batch_tpu.api.serving import (
        CAPACITY_TYPE_LABEL_KEY,
        RESERVED_ONLY_ANNOTATION_KEY,
        SLO_SECONDS_ANNOTATION_KEY,
        TOPOLOGY_TIER_LABEL_KEY,
        WORKLOAD_CLASS_ANNOTATION_KEY,
    )
    from kube_batch_tpu.api import PodPhase, build_resource_list
    from kube_batch_tpu.utils.test_utils import build_node, build_pod

    c = build_cluster(seed=53, groups=6, per_group=40, nodes=6)
    # Heterogeneous extension of the pool: labeled spot + tiered nodes
    # so the serving rows are genuinely non-trivial.
    for j, labels in enumerate((
        {CAPACITY_TYPE_LABEL_KEY: "spot"},
        {TOPOLOGY_TIER_LABEL_KEY: "2"},
    )):
        c.add_node(build_node(
            f"hn{j}",
            build_resource_list(cpu="16", memory="64Gi", pods=110),
            labels=labels,
        ))
    # One serving deployment (shared constraint signature) riding an
    # existing pod group's queue: 8 replicas, reserved-only + SLO.
    for i in range(8):
        pod = build_pod(
            "ns", f"serve-{i}", "", PodPhase.PENDING,
            build_resource_list(cpu="250m", memory="256Mi"),
            group_name="pg0",
        )
        pod.metadata.annotations.update({
            WORKLOAD_CLASS_ANNOTATION_KEY: "serving",
            SLO_SECONDS_ANNOTATION_KEY: "2.0",
            RESERVED_ONLY_ANNOTATION_KEY: "1",
        })
        c.add_pod(pod)
    tiers = make_tiers(
        ["priority", "gang", "conformance"],
        ["drf", "predicates", "proportion", "nodeorder", "serving"],
    )
    for _ in range(WARM_CYCLES):
        one_cycle(c, tiers, churn=2)
    warm = jit_compilation_count()
    assert warm > 0
    for cycle in range(GUARD_CYCLES):
        one_cycle(c, tiers, churn=2)
        now = jit_compilation_count()
        assert now == warm, (
            f"serving cycle {cycle} minted {now - warm} new jit "
            "compilation(s) — the serving mask/score rows broke the "
            "shape-stability contract"
        )
    c.shutdown()


def test_zero_new_compilations_sharded_sparse_cycles(monkeypatch):
    """The sharded-sparse twin: steady/delta cycles through the
    task-sharded shard_map sparse solve (forced slabs + flat mode on
    the 8-device mesh) must compile a bounded step set during warmup
    and then go flat — the sharded step AND the replicated-placement
    patch jits are all in the `jit_compilation_count` census
    (spmd._jitted_steps weakrefs + patch_jit_cache_size)."""
    import jax

    if len(jax.devices()) < 2:
        pytest.skip("needs the multi-device virtual CPU mesh")
    monkeypatch.setenv("KBT_SOLVER_TOPK", "8")
    monkeypatch.setenv("KBT_SPARSE_SHARD_MODE", "flat")
    from kube_batch_tpu.solver import sharding as sharding_mod

    c = build_cluster(seed=47, groups=6, per_group=40, nodes=8)
    tiers = make_tiers(*DEFAULT_TIERS_ARGS)
    for _ in range(WARM_CYCLES):
        one_cycle(c, tiers, churn=2, solver=solve_sharded)
    assert sharding_mod.last_dispatch.get("mode") == "flat"
    warm = jit_compilation_count()
    assert warm > 0
    for cycle in range(GUARD_CYCLES):
        one_cycle(c, tiers, churn=2, solver=solve_sharded)
        now = jit_compilation_count()
        assert now == warm, (
            f"sharded sparse cycle {cycle} minted {now - warm} new jit "
            "compilation(s) — a shape/dtype/layout drift reintroduced "
            "per-cycle tracing on the sharded path"
        )
    c.shutdown()
