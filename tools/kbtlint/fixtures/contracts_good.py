"""Known-good shape-contracts fixture: a self-contained miniature of
the contract surface — tables, NamedTuple comment contracts, row-axis
map, producer dict, in-range stack indexing."""

from typing import NamedTuple

SOLVER_INPUT_CONTRACTS = {
    "task_req": {"shape": ["T", "R"], "dtype": "f32"},
}

PACKED_INPUT_CONTRACTS = {
    "task_f32": {"shape": [2, "T", "R"], "dtype": "f32",
                 "row_axis": 1, "donated": True},
    "task_i32": {"shape": [6, "T"], "dtype": "i32",
                 "row_axis": 1, "donated": True},
    "node_f32": {"shape": [3, "N", "R"], "dtype": "f32",
                 "row_axis": 1, "donated": True},
    "node_i32": {"shape": [3, "N"], "dtype": "i32",
                 "row_axis": 1, "donated": True},
    "misc": {"shape": ["R+2"], "dtype": "f32",
             "row_axis": 0, "donated": True},
}

_ROW_AXIS = {
    "task_f32": 1,
    "task_i32": 1,
    "node_f32": 1,
    "node_i32": 1,
    "misc": 0,
}


class SolverInputs(NamedTuple):
    task_req: object  # f32[T, R] request rows


class PackedInputs(NamedTuple):
    task_f32: object  # [2, T, R] req, fit
    task_i32: object  # i32[6, T] rank, queue, job, group, valid, cand
    node_f32: object  # [3, N, R] idle, releasing, cap
    node_i32: object  # [3, N] task_count, max_tasks, feas
    misc: object      # f32[R+2] eps, weights


def pack(stack, task_req, task_fit, task_rows, nodes, node_rows, misc):
    return {
        "task_f32": stack([task_req, task_fit]),
        "task_i32": stack(task_rows),
        "node_f32": stack(nodes),
        "node_i32": stack(node_rows),
        "misc": stack(misc),
    }


def unpack(p):
    return p.task_i32[5], p.node_f32[2], p.task_f32[0]
