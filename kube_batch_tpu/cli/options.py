"""Process flags.

Mirrors reference cmd/kube-batch/app/options/options.go (:33 ServerOption,
:59 AddFlags, :83 CheckOptionOrDie, :91 RegisterOptions → global ServerOpts
:48). The kubeconfig/master flags become --cluster-state (the standalone
substrate: a YAML snapshot loaded into the in-process cluster).
"""

from __future__ import annotations

import argparse
from dataclasses import dataclass
from typing import List, Optional

from ..api.objects import DEFAULT_SCHEDULER_NAME
DEFAULT_SCHEDULER_PERIOD = 1.0  # seconds (reference options.go:29)
DEFAULT_QUEUE = "default"       # reference options.go:30
DEFAULT_LISTEN_ADDRESS = ":8080"  # reference options.go:31

# Leader-election lease timings (reference app/server.go:49-53).
LEASE_DURATION = 15.0
RENEW_DEADLINE = 10.0
RETRY_PERIOD = 5.0


@dataclass
class ServerOption:
    """reference options.go:33-56"""

    cluster_state: str = ""          # standalone in-process cluster seed
    master: str = ""                 # k8s API server URL (reference --master)
    kubeconfig: str = ""             # kubeconfig path (reference --kubeconfig)
    scheduler_name: str = DEFAULT_SCHEDULER_NAME
    scheduler_conf: str = ""
    schedule_period: float = DEFAULT_SCHEDULER_PERIOD
    # Matches the --leader-elect flag default (standalone single-process is
    # the common case); the reference's flag also defaults to false.
    enable_leader_election: bool = False
    lock_object_namespace: str = ""
    default_queue: str = DEFAULT_QUEUE
    listen_address: str = DEFAULT_LISTEN_ADDRESS
    enable_priority_class: bool = True
    print_version: bool = False
    simulate_kubelet: bool = True
    once: bool = False               # run one cycle and exit (debugging aid)
    # Bounded accelerator-backend probe at startup (seconds); a wedged
    # tunnel must cost one startup delay, not a frozen scheduling loop.
    backend_probe_timeout: int = 60

    def check_option_or_die(self) -> None:
        """reference options.go:83-89"""
        if self.enable_leader_election and not self.lock_object_namespace:
            raise ValueError(
                "lock-object-namespace must not be nil when LeaderElection is enabled"
            )


# Global registered options (reference options.go:46-48 ServerOpts; read by
# the cache for EnablePriorityClass, cache.go:369,384).
ServerOpts: Optional[ServerOption] = None


def register_options(opt: ServerOption) -> None:
    """reference options.go:91-95"""
    global ServerOpts
    ServerOpts = opt


def add_flags(parser: argparse.ArgumentParser) -> None:
    """reference options.go:59-80"""
    parser.add_argument(
        "--cluster-state", default="",
        help="YAML file describing nodes/queues/podgroups/pods to load into "
             "the in-process cluster (standalone mode)")
    parser.add_argument(
        "--master", default="",
        help="The address of the Kubernetes API server (overrides any "
             "value in kubeconfig)")
    parser.add_argument(
        "--kubeconfig", default="",
        help="Path to kubeconfig file with authorization and master "
             "location information; enables real-cluster mode")
    parser.add_argument(
        "--scheduler-name", default=DEFAULT_SCHEDULER_NAME,
        help="tpu-batch will handle pods whose .spec.SchedulerName is same as "
             "scheduler-name")
    parser.add_argument(
        "--scheduler-conf", default="",
        help="The absolute path of scheduler configuration file")
    parser.add_argument(
        "--schedule-period", type=float, default=DEFAULT_SCHEDULER_PERIOD,
        help="The period between each scheduling cycle, seconds")
    parser.add_argument(
        "--default-queue", default=DEFAULT_QUEUE,
        help="The default queue name of the job")
    parser.add_argument(
        "--leader-elect", action="store_true", default=False,
        help="Start a leader election client and gain leadership before "
             "executing the main loop")
    parser.add_argument(
        "--lock-object-namespace", default="",
        help="Define the namespace (lock directory) of the lock object")
    parser.add_argument(
        "--listen-address", default=DEFAULT_LISTEN_ADDRESS,
        help="The address to listen on for HTTP requests (/metrics)")
    parser.add_argument(
        "--priority-class", dest="priority_class", action="store_true",
        default=True,
        help="Enable PriorityClass to provide the capacity of preemption at "
             "pod group level")
    parser.add_argument(
        "--no-priority-class", dest="priority_class", action="store_false")
    parser.add_argument(
        "--no-simulate-kubelet", dest="simulate_kubelet", action="store_false",
        default=True,
        help="Disable the hollow-kubelet simulation (bound pods will stay "
             "Pending until an external agent runs them)")
    parser.add_argument(
        "--once", action="store_true", default=False,
        help="Run a single scheduling cycle and exit")
    parser.add_argument(
        "--backend-probe-timeout", type=int, default=60,
        help="Seconds to wait for the accelerator backend to resolve at "
             "startup (in a bounded subprocess); on timeout the scheduler "
             "forces CPU devices and native solver routing instead of "
             "risking a frozen first cycle")
    parser.add_argument(
        "--version", action="store_true", default=False,
        help="Show version and quit")


def parse_options(argv: Optional[List[str]] = None) -> ServerOption:
    parser = argparse.ArgumentParser(prog="tpu-batch")
    add_flags(parser)
    ns = parser.parse_args(argv)
    return ServerOption(
        cluster_state=ns.cluster_state,
        master=ns.master,
        kubeconfig=ns.kubeconfig,
        scheduler_name=ns.scheduler_name,
        scheduler_conf=ns.scheduler_conf,
        schedule_period=ns.schedule_period,
        enable_leader_election=ns.leader_elect,
        lock_object_namespace=ns.lock_object_namespace,
        default_queue=ns.default_queue,
        listen_address=ns.listen_address,
        enable_priority_class=ns.priority_class,
        print_version=ns.version,
        simulate_kubelet=ns.simulate_kubelet,
        once=ns.once,
        backend_probe_timeout=ns.backend_probe_timeout,
    )
