"""Property-based solver invariants (hypothesis).

The parity suites check specific scenarios; these drive RANDOM instances
through one jitted shape (so each example reuses the compiled program)
and assert the invariants every schedule must satisfy regardless of
scores or conflicts:

- assignments land only on mask-feasible nodes that fit,
- per-node usage never exceeds initial idle (+epsilon),
- pod-count caps (node_max_tasks) are respected,
- invalid (padded) tasks are never assigned,
- the native CPU fallback satisfies the same invariants on the same
  instance.
"""

import numpy as np
import pytest

# Optional dependency: some images ship without hypothesis — the module
# must SKIP cleanly, not fail tier-1 collection.
hypothesis = pytest.importorskip("hypothesis")
from hypothesis import given, settings, strategies as st  # noqa: E402

import jax.numpy as jnp  # noqa: E402

from kube_batch_tpu.solver import make_inputs, solve_jit

try:
    from kube_batch_tpu.native import native_available, solve_native
    HAVE_NATIVE = native_available()
except Exception:  # pragma: no cover - no toolchain
    HAVE_NATIVE = False

T, N, R = 64, 16, 2
EPS = 10.0


def build(seed):
    rng = np.random.RandomState(seed)
    task_req = np.c_[
        rng.choice([250, 500, 1000, 2000], T),
        rng.choice([256, 512, 2048], T),
    ].astype(np.float32)
    feas = rng.rand(T, N) > rng.uniform(0.0, 0.6)
    idle = np.c_[
        rng.choice([1000, 4000, 8000], N),
        rng.choice([2048, 8192], N),
    ].astype(np.float32)
    valid = rng.rand(T) > 0.1
    queue = rng.randint(0, 2, T).astype(np.int32)
    max_tasks = rng.choice([0, 3], N).astype(np.int32)
    deserved = np.asarray(
        [[rng.choice([3000.0, np.inf]), np.inf], [np.inf, np.inf]],
        np.float32,
    )
    inputs = make_inputs(
        feas=jnp.asarray(feas),
        task_req=jnp.asarray(task_req),
        task_fit=jnp.asarray(task_req),
        task_rank=jnp.arange(T, dtype=jnp.int32),
        task_job=jnp.asarray(rng.randint(0, 8, T), jnp.int32),
        task_queue=jnp.asarray(queue),
        task_valid=jnp.asarray(valid),
        node_idle=jnp.asarray(idle),
        node_releasing=jnp.zeros((N, R), jnp.float32),
        node_cap=jnp.asarray(idle),
        node_task_count=jnp.zeros(N, jnp.int32),
        node_max_tasks=jnp.asarray(max_tasks),
        queue_deserved=jnp.asarray(deserved),
        queue_allocated=jnp.zeros((2, R), jnp.float32),
        eps=jnp.full((R,), EPS, jnp.float32),
        lr_weight=jnp.asarray(1.0, jnp.float32),
        br_weight=jnp.asarray(1.0, jnp.float32),
    )
    return inputs, task_req, feas, idle, valid, max_tasks


def check_invariants(assigned, task_req, feas, idle, valid, max_tasks,
                     label):
    used = np.zeros_like(idle)
    counts = np.zeros(N, np.int64)
    for t in range(T):
        j = int(assigned[t])
        if j < 0:
            continue
        assert valid[t], f"{label}: invalid task {t} assigned"
        assert j < N, f"{label}: task {t} assigned past node table"
        assert feas[t, j], f"{label}: task {t} on masked node {j}"
        used[j] += task_req[t]
        counts[j] += 1
    assert np.all(used - idle < EPS + 1e-3), (
        f"{label}: node over-committed", used, idle
    )
    capped = max_tasks > 0
    assert np.all(counts[capped] <= max_tasks[capped]), (
        f"{label}: pod-count cap exceeded", counts, max_tasks
    )


@settings(max_examples=25, deadline=None)
@given(st.integers(min_value=0, max_value=10_000))
def test_solver_invariants_random_instances(seed):
    inputs, task_req, feas, idle, valid, max_tasks = build(seed)
    assigned = np.asarray(solve_jit(inputs).assigned)
    check_invariants(assigned, task_req, feas, idle, valid, max_tasks, "jax")
    if HAVE_NATIVE:
        n_assigned, _ = solve_native(inputs)
        check_invariants(
            n_assigned, task_req, feas, idle, valid, max_tasks, "native"
        )


# Staged solver at a forced-small tail bucket: the head/tail compaction
# machinery (top-k compaction, subset-local job blocking, multi-stage
# outer loop) must satisfy the same invariants and place the same number
# of tasks as the full-width solver on every instance.
from kube_batch_tpu.solver import solve_staged_jit


@settings(max_examples=25, deadline=None)
@given(st.integers(min_value=0, max_value=10_000))
def test_staged_solver_matches_full_on_random_instances(seed):
    inputs, task_req, feas, idle, valid, max_tasks = build(seed)
    full = np.asarray(solve_jit(inputs).assigned)
    staged = np.asarray(solve_staged_jit(inputs, tail_bucket=16).assigned)
    check_invariants(
        staged, task_req, feas, idle, valid, max_tasks, "staged"
    )
    assert (staged >= 0).sum() == (full >= 0).sum(), (
        "staged and full solvers placed different counts",
        int((staged >= 0).sum()), int((full >= 0).sum()),
    )
