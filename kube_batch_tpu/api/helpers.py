"""Domain-model helpers.

Mirrors reference pkg/scheduler/api/helpers.go (:26 PodKey, :35 getTaskStatus)
and pkg/apis/utils/utils.go (:26 GetController).
"""

from __future__ import annotations

from .objects import Pod, PodPhase
from .types import TaskStatus

# Attribute pod_key memoizes on the pod object (cleared alongside the
# predicates plugin's pod memos by plugins.predicates.clear_pod_caches,
# so bench burst simulations measure true first-touch cost).
POD_KEY_CACHE_ATTR = "_key"


def pod_key(pod: Pod) -> str:
    """Unique key of a pod (reference helpers.go:26-33).

    Memoized on the pod object: uid and namespace/name are immutable
    for a pod's lifetime (k8s semantics), and this runs once per task
    per node-accounting touch — ~150k times per 50k-task apply, where
    the double attribute chase was measurable."""
    key = pod.__dict__.get(POD_KEY_CACHE_ATTR)
    if key is None:
        key = pod.metadata.uid or f"{pod.namespace}/{pod.name}"
        pod.__dict__[POD_KEY_CACHE_ATTR] = key
    return key


def get_task_status(pod: Pod) -> TaskStatus:
    """Pod phase → TaskStatus (reference helpers.go:35-60)."""
    phase = pod.status.phase
    if phase == PodPhase.RUNNING:
        if pod.metadata.deletion_timestamp is not None:
            return TaskStatus.RELEASING
        return TaskStatus.RUNNING
    if phase == PodPhase.PENDING:
        if pod.metadata.deletion_timestamp is not None:
            return TaskStatus.RELEASING
        if pod.spec.node_name:
            return TaskStatus.BOUND
        return TaskStatus.PENDING
    if phase == PodPhase.UNKNOWN:
        return TaskStatus.UNKNOWN
    if phase == PodPhase.SUCCEEDED:
        return TaskStatus.SUCCEEDED
    if phase == PodPhase.FAILED:
        return TaskStatus.FAILED
    return TaskStatus.UNKNOWN


def get_controller_uid(pod: Pod) -> str:
    """Controller owner UID, used to key shadow PodGroups
    (reference apis/utils/utils.go:26-38)."""
    return pod.metadata.owner_uid or ""
