"""TPU batched-assignment solver.

The genuinely new component of the rebuild (SURVEY.md §7 step 6): the
reference's per-task greedy allocate loop re-expressed as dense tensor ops —
feasibility mask, cost matrix, round-based conflict-resolved assignment —
jitted for TPU, with a sharded multi-chip variant.
"""

from .kernels import (
    SolverInputs,
    SolverResult,
    dynamic_scores,
    less_equal,
    segmented_cumsum,
    solve,
    solve_jit,
)
from .snapshot import ResourceLayout, SnapshotContext, tensorize

__all__ = [
    "SolverInputs",
    "SolverResult",
    "ResourceLayout",
    "SnapshotContext",
    "dynamic_scores",
    "less_equal",
    "segmented_cumsum",
    "solve",
    "solve_jit",
    "tensorize",
]
