"""Cycle-scoped garbage-collection deferral.

A 50k-task apply allocates ~100k short-lived objects (events, clones,
dict entries); CPython's generational GC triggers multiple collections
inside the scheduling cycle, and full collections scan the ~1M-object
cluster mirror — measured ~350 ms of the cold 50k apply (r4 profile),
indistinguishable from "slow bookkeeping" until isolated.

The Go reference pays this as concurrent GC; CPython stops the world.
``deferred_gc()`` moves the cost off the critical path: collection is
disabled for the duration of the cycle and a bounded young-generation
collection runs on exit — in the scheduler's think-time gap, where a
pause costs nothing. Nesting is safe (only the outermost guard
re-enables); an exception still restores GC.
"""

from __future__ import annotations

import gc
from contextlib import contextmanager

_depth = 0


@contextmanager
def deferred_gc(collect_generation: int = 1):
    """Disable GC for the guarded block; on exit, re-enable and run one
    ``gc.collect(collect_generation)`` (default: young+middle
    generations — bounded, does not scan the full mirror). Pass -1 to
    skip the exit collection entirely."""
    global _depth
    _depth += 1
    was_enabled = gc.isenabled()
    if was_enabled:
        gc.disable()
    try:
        yield
    finally:
        _depth -= 1
        if was_enabled and _depth == 0:
            gc.enable()
            if collect_generation >= 0:
                gc.collect(collect_generation)
