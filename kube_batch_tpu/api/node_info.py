"""NodeInfo: per-node aggregated scheduling state.

Mirrors reference pkg/scheduler/api/node_info.go:
- Releasing / Idle / Used dual accounting (:36-44) so the scheduler can plan
  onto resources that are still being released ("Pipelined" placements).
- AddTask status-dependent accounting (:174-206): Releasing → take idle AND
  count releasing; Pipelined → consume releasing (not idle); default → take
  idle. RemoveTask is the exact inverse (:209-235).
- OutOfSync / NotReady state when accounting underflows (:107-131,:161-171).
"""

from __future__ import annotations

import logging
from dataclasses import dataclass
from typing import Dict, List, Optional

from .helpers import POD_KEY_CACHE_ATTR, pod_key
from .job_info import TaskInfo
from .objects import Node, Pod
from .resource_info import Resource
from .serving import DEFAULT_NODE_CLASS, NodeClass, node_class_from_labels
from .types import NodePhase, TaskStatus

logger = logging.getLogger(__name__)


@dataclass
class NodeState:
    phase: str = NodePhase.NOT_READY
    reason: str = ""


class NodeInfo:
    """Node-level aggregated information (reference node_info.go:28-47)."""

    def __init__(self, node: Optional[Node] = None):
        self.name = ""
        # The backing k8s Node object. CONTRACT: in-place mutations of
        # this object (spec/conditions/labels/taints) are invisible to
        # the predicates plugin's static-verdict memo, which keys on
        # (id(node), _node_obj_ver) — deliver every change through
        # :meth:`set_node` (the watch ingest path does), even when
        # re-delivering the same object reference, so the generation
        # bumps and the memo re-evaluates. Code that tweaks
        # ``node_info.node`` directly between cycles will keep serving
        # the stale verdict indefinitely.
        self.node: Optional[Node] = None
        self.state = NodeState()
        self.releasing = Resource.empty()
        self.idle = Resource.empty()
        self.used = Resource.empty()
        self.allocatable = Resource.empty()
        self.capability = Resource.empty()
        self.tasks: Dict[str, TaskInfo] = {}
        # Mutation counter for the cache's COW snapshot pool (see
        # JobInfo._ver): bumped by every accounting mutator.
        self._ver = 0
        # Generation of the backing k8s object: bumped ONLY when a
        # watch update lands (set_node) — including in-place mutations
        # re-delivered as the same reference (InProcessCluster does
        # this). Keys the predicates plugin's static-node-verdict memo;
        # _ver cannot (it bumps on every bind).
        self._node_obj_ver = 0
        # Node-class descriptor (api/serving.py): derived from labels
        # here and on every set_node; immutable, so clones share it.
        self.node_class: NodeClass = DEFAULT_NODE_CLASS
        if node is not None:
            self.name = node.name
            self.node = node
            self.idle = Resource.from_resource_list(node.status.allocatable)
            self.allocatable = Resource.from_resource_list(node.status.allocatable)
            self.capability = Resource.from_resource_list(node.status.capacity)
            self.node_class = node_class_from_labels(node.metadata.labels)
        self._set_node_state(node)

    # -- state --------------------------------------------------------------

    def ready(self) -> bool:
        return self.state.phase == NodePhase.READY

    def _set_node_state(self, node: Optional[Node]) -> None:
        """reference node_info.go:107-131"""
        self._ver += 1
        if node is None:
            self.state = NodeState(NodePhase.NOT_READY, "UnInitialized")
            return
        if not self.used.less_equal(
            Resource.from_resource_list(node.status.allocatable)
        ):
            self.state = NodeState(NodePhase.NOT_READY, "OutOfSync")
            return
        self.state = NodeState(NodePhase.READY, "")

    def set_node(self, node: Node) -> None:
        """Recompute accounting from a fresh node object
        (reference node_info.go:134-159). This is the ONLY path that
        bumps ``_node_obj_ver`` — any in-place mutation of the backing
        object must be re-delivered through here to be observed by the
        predicates static-verdict memo (see the ``node`` attribute
        contract in ``__init__``)."""
        self._ver += 1
        self._node_obj_ver += 1
        self._set_node_state(node)
        if not self.ready():
            return
        self.name = node.name
        self.node = node
        self.node_class = node_class_from_labels(node.metadata.labels)
        self.allocatable = Resource.from_resource_list(node.status.allocatable)
        self.capability = Resource.from_resource_list(node.status.capacity)
        self.idle = Resource.from_resource_list(node.status.allocatable)
        self.used = Resource.empty()
        self.releasing = Resource.empty()
        for task in self.tasks.values():
            if task.status == TaskStatus.RELEASING:
                self.releasing.add(task.resreq)
            self.idle.sub(task.resreq)
            self.used.add(task.resreq)

    # -- task accounting ----------------------------------------------------

    def _allocate_idle_resource(self, ti: TaskInfo) -> None:
        """reference node_info.go:161-171"""
        if ti.resreq.less_equal(self.idle):
            self.idle.sub(ti.resreq)
            return
        self.state = NodeState(NodePhase.NOT_READY, "OutOfSync")
        raise ValueError("Selected node NotReady")

    def add_task(self, task: TaskInfo) -> None:
        """reference node_info.go:174-206; node holds a CLONE of the task so
        later status changes don't corrupt node accounting (:181-183)."""
        key = pod_key(task.pod)
        if key in self.tasks:
            raise ValueError(
                f"task <{task.namespace}/{task.name}> already on node <{self.name}>"
            )
        ti = task.clone()
        self._ver += 1
        if self.node is not None:
            if ti.status == TaskStatus.RELEASING:
                self._allocate_idle_resource(ti)
                self.releasing.add(ti.resreq)
            elif ti.status == TaskStatus.PIPELINED:
                self.releasing.sub(ti.resreq)
            else:
                self._allocate_idle_resource(ti)
            self.used.add(ti.resreq)
        self.tasks[key] = ti

    def add_tasks(self, tasks: List[TaskInfo]) -> None:
        """Batched :meth:`add_task` for same-status bulk placement (the
        apply phase): one aggregate idle/used update for the whole group
        instead of per-task Resource arithmetic. Only statuses on the
        default accounting branch (not Releasing/Pipelined) qualify, and
        pod keys must be unique across both the node and the batch.

        All-or-nothing: on any precondition failure it raises WITHOUT
        touching node state — notably, a failed aggregate fit check does
        NOT mark the node OutOfSync, because the single group epsilon is
        stricter than the per-task epsilon chain and the per-task
        fallback may still place everything on a healthy node."""
        if not tasks:
            return
        clones = []
        seen = set()
        for task in tasks:
            key = pod_key(task.pod)
            if key in self.tasks or key in seen:
                raise ValueError(
                    f"task <{task.namespace}/{task.name}> already on "
                    f"node <{self.name}>"
                )
            seen.add(key)
            if task.status in (TaskStatus.RELEASING, TaskStatus.PIPELINED):
                raise ValueError(
                    f"add_tasks only takes default-branch statuses, got "
                    f"{task.status.name}"
                )
            clones.append((key, task.clone()))
        if self.node is not None:
            delta = Resource.empty()
            for _, ti in clones:
                delta.add(ti.resreq)
            if not delta.less_equal(self.idle):
                raise ValueError(
                    f"batch of {len(clones)} tasks does not fit node "
                    f"<{self.name}> in aggregate"
                )
            self.idle.sub(delta)
            self.used.add(delta)
        self._ver += 1
        for key, ti in clones:
            self.tasks[key] = ti

    def add_tasks_prevalidated(
        self, tasks: List[TaskInfo], delta: "Resource"
    ) -> None:
        """Session-apply fast path: place a uniform default-branch group
        whose aggregate fit the solver's apply guard ALREADY verified,
        with ``delta`` its precomputed resreq sum. Stores the tasks
        THEMSELVES, not clones — only valid on session-lifetime nodes,
        where node entries and the session's task objects die together
        at close (the authoritative cache mirror must keep using
        add_task/add_tasks, whose clones protect accounting across
        cycles). Raises like :meth:`add_tasks` on duplicates or an
        aggregate misfit, without touching node state."""
        if not tasks:
            return
        new = {}
        node_tasks = self.tasks
        setdefault = new.setdefault
        for task in tasks:
            # Inline pod_key incl. its memo write: the function-call
            # overhead alone was measurable at 50k tasks per apply, and
            # the cold burst is exactly the first touch of every pod.
            pod = task.pod
            key = pod.__dict__.get(POD_KEY_CACHE_ATTR)
            if key is None:
                key = pod.metadata.uid or f"{pod.namespace}/{pod.name}"
                pod.__dict__[POD_KEY_CACHE_ATTR] = key
            # setdefault doubles as the intra-batch duplicate check.
            if key in node_tasks or setdefault(key, task) is not task:
                raise ValueError(
                    f"task <{task.namespace}/{task.name}> already on "
                    f"node <{self.name}>"
                )
        if len(new) != len(tasks):
            # Same task object listed twice slips past setdefault.
            raise ValueError(
                f"duplicate tasks in prevalidated batch for "
                f"node <{self.name}>"
            )
        if self.node is not None:
            if not delta.less_equal(self.idle):
                raise ValueError(
                    f"batch of {len(new)} tasks does not fit node "
                    f"<{self.name}> in aggregate"
                )
            self.idle.sub(delta)
            self.used.add(delta)
        self._ver += 1
        node_tasks.update(new)

    def add_tasks_with_fallback(self, tasks: List[TaskInfo]) -> List[TaskInfo]:
        """Batch-add with sequential per-task fallback, returning the
        tasks actually placed. The fallback covers the cases the strict
        batch path rejects (aggregate epsilon, mixed statuses, duplicate
        keys): per-task failures are logged and skipped, exactly like the
        sequential apply loop. Shared by Session.allocate_batch and
        SchedulerCache.bind_batch so the fallback policy lives next to
        the accounting it protects."""
        if len(tasks) > 1:
            # Degenerate single-task groups (e.g. a gang spread
            # one-task-per-node) skip the batch machinery and fall
            # through to the sequential loop directly.
            try:
                self.add_tasks(tasks)
                return list(tasks)
            except Exception:
                pass
        placed: List[TaskInfo] = []
        for task in tasks:
            try:
                self.add_task(task)
            except Exception:
                logger.exception(
                    "failed to place task <%s/%s> on node <%s>",
                    task.namespace, task.name, self.name,
                )
                continue
            placed.append(task)
        return placed

    def remove_task(self, ti: TaskInfo) -> None:
        """reference node_info.go:209-235"""
        key = pod_key(ti.pod)
        task = self.tasks.get(key)
        if task is None:
            raise KeyError(
                f"failed to find task <{ti.namespace}/{ti.name}> "
                f"on host <{self.name}>"
            )
        self._ver += 1
        if self.node is not None:
            if task.status == TaskStatus.RELEASING:
                self.releasing.sub(task.resreq)
                self.idle.add(task.resreq)
            elif task.status == TaskStatus.PIPELINED:
                self.releasing.add(task.resreq)
            else:
                self.idle.add(task.resreq)
            self.used.sub(task.resreq)
        del self.tasks[key]

    def update_task(self, ti: TaskInfo) -> None:
        """reference node_info.go:238-244"""
        self.remove_task(ti)
        self.add_task(ti)

    def clone(self) -> "NodeInfo":
        """Deep copy for the per-cycle snapshot (reference
        node_info.go:92-100). The reference rebuilds accounting by
        re-adding every task; here the already-consistent incremental
        vectors are copied directly — same result (idle/used/releasing
        are invariants of the task set) without re-parsing the node's
        quantity strings on every 1 Hz snapshot."""
        res = NodeInfo.__new__(NodeInfo)
        res._ver = 0
        res._node_obj_ver = self._node_obj_ver
        res.name = self.name
        res.node = self.node
        res.node_class = self.node_class  # immutable; clones share
        res.state = NodeState(self.state.phase, self.state.reason)
        res.releasing = self.releasing.clone()
        res.idle = self.idle.clone()
        res.used = self.used.clone()
        res.allocatable = self.allocatable.clone()
        res.capability = self.capability.clone()
        res.tasks = {k: t.clone() for k, t in self.tasks.items()}
        return res

    def pods(self) -> List[Pod]:
        return [t.pod for t in self.tasks.values()]

    def __repr__(self) -> str:
        return (
            f"Node ({self.name}): idle <{self.idle}>, used <{self.used}>, "
            f"releasing <{self.releasing}>, "
            f"state <phase {self.state.phase}, reason {self.state.reason}>"
        )
