"""Resource vector arithmetic.

Semantics mirror the reference's pkg/scheduler/api/resource_info.go:
- CPU tracked in millicores, memory in bytes, scalar resources in milli-units
  (resource_info.go:73-90 NewResource uses MilliValue for cpu and scalars).
- Epsilon-tolerant comparisons with min thresholds (resource_info.go:68-70:
  minMilliCPU=10, minMilliScalarResources=10, minMemory=10MiB;
  LessEqual resource_info.go:254-277).
- Sub raises when the subtrahend does not fit (resource_info.go:143-160).
- MaxTaskNum is predicate-only and excluded from arithmetic
  (resource_info.go:35-37).
"""

from __future__ import annotations

import re
from types import MappingProxyType
from typing import Dict, List, Optional, Tuple, Union

# Canonical resource names (k8s-compatible spellings).
RESOURCE_CPU = "cpu"
RESOURCE_MEMORY = "memory"
RESOURCE_PODS = "pods"
# reference: resource_info.go:41-43
GPU_RESOURCE_NAME = "nvidia.com/gpu"
# TPU-native addition: same scalar-resource treatment as GPUs.
TPU_RESOURCE_NAME = "google.com/tpu"

# Epsilons, reference resource_info.go:68-70.
MIN_MILLI_CPU = 10.0
MIN_MILLI_SCALAR = 10.0
MIN_MEMORY = 10.0 * 1024 * 1024

_QUANTITY_RE = re.compile(r"^([+-]?[0-9.]+(?:[eE][+-]?[0-9]+)?)([a-zA-Z]*)$")

_SUFFIX_MULTIPLIERS = {
    "": 1.0,
    "m": 1e-3,
    "k": 1e3,
    "M": 1e6,
    "G": 1e9,
    "T": 1e12,
    "P": 1e15,
    "E": 1e18,
    "Ki": 2.0**10,
    "Mi": 2.0**20,
    "Gi": 2.0**30,
    "Ti": 2.0**40,
    "Pi": 2.0**50,
    "Ei": 2.0**60,
}


def parse_quantity(q: Union[str, int, float]) -> float:
    """Parse a k8s-style quantity ('100m', '2Gi', 3) into a float base value."""
    if isinstance(q, (int, float)):
        return float(q)
    m = _QUANTITY_RE.match(q.strip())
    if not m:
        raise ValueError(f"invalid quantity: {q!r}")
    value, suffix = m.groups()
    if suffix not in _SUFFIX_MULTIPLIERS:
        raise ValueError(f"invalid quantity suffix: {q!r}")
    return float(value) * _SUFFIX_MULTIPLIERS[suffix]


ResourceList = Dict[str, Union[str, int, float]]


def build_resource_list(cpu=None, memory=None, pods=None, **scalars) -> ResourceList:
    """Convenience builder for a resource list (mirrors test_utils.go:84-91)."""
    rl: ResourceList = {}
    if cpu is not None:
        rl[RESOURCE_CPU] = cpu
    if memory is not None:
        rl[RESOURCE_MEMORY] = memory
    if pods is not None:
        rl[RESOURCE_PODS] = pods
    rl.update(scalars)
    return rl


class Resource:
    """A resource vector: millicores, bytes of memory, and named scalars."""

    __slots__ = ("milli_cpu", "memory", "scalar_resources", "max_task_num")

    def __init__(
        self,
        milli_cpu: float = 0.0,
        memory: float = 0.0,
        scalar_resources: Optional[Dict[str, float]] = None,
        max_task_num: int = 0,
    ):
        self.milli_cpu = float(milli_cpu)
        self.memory = float(memory)
        self.scalar_resources: Optional[Dict[str, float]] = (
            dict(scalar_resources) if scalar_resources else None
        )
        self.max_task_num = max_task_num

    # -- constructors -------------------------------------------------------

    @classmethod
    def empty(cls) -> "Resource":
        return cls()

    @classmethod
    def from_resource_list(cls, rl: Optional[ResourceList]) -> "Resource":
        """Build from a resource list (reference resource_info.go:72-90).

        CPU and scalar quantities are converted to milli-units; memory to bytes;
        'pods' feeds max_task_num.
        """
        r = cls()
        if not rl:
            return r
        for name, quant in rl.items():
            value = parse_quantity(quant)
            if name == RESOURCE_CPU:
                r.milli_cpu += value * 1000.0
            elif name == RESOURCE_MEMORY:
                r.memory += value
            elif name == RESOURCE_PODS:
                r.max_task_num += int(value)
            else:
                r.add_scalar(name, value * 1000.0)
        return r

    def clone(self) -> "Resource":
        # Snapshot-critical path: ~126k clones per 50k-task cycle (the
        # defensive deep-copy contract the mutation-detector test pins).
        # Bypass __init__'s float()/dict() normalization — fields of an
        # existing Resource are already normalized.
        c = object.__new__(Resource)
        c.milli_cpu = self.milli_cpu
        c.memory = self.memory
        sr = self.scalar_resources
        c.scalar_resources = dict(sr) if sr else None
        c.max_task_num = self.max_task_num
        return c

    # -- predicates ---------------------------------------------------------

    def is_empty(self) -> bool:
        """All dimensions below epsilon (resource_info.go:93-105)."""
        if not (self.milli_cpu < MIN_MILLI_CPU and self.memory < MIN_MEMORY):
            return False
        for quant in (self.scalar_resources or {}).values():
            if quant >= MIN_MILLI_SCALAR:
                return False
        return True

    def is_zero(self, name: str) -> bool:
        """One dimension below epsilon (resource_info.go:107-125)."""
        if name == RESOURCE_CPU:
            return self.milli_cpu < MIN_MILLI_CPU
        if name == RESOURCE_MEMORY:
            return self.memory < MIN_MEMORY
        if self.scalar_resources is None:
            return True
        if name not in self.scalar_resources:
            raise KeyError(f"unknown resource {name!r}")
        return self.scalar_resources[name] < MIN_MILLI_SCALAR

    # -- arithmetic (in place, returning self, like the reference) ----------

    def add(self, rr: "Resource") -> "Resource":
        self.milli_cpu += rr.milli_cpu
        self.memory += rr.memory
        for name, quant in (rr.scalar_resources or {}).items():
            if self.scalar_resources is None:
                self.scalar_resources = {}
            self.scalar_resources[name] = self.scalar_resources.get(name, 0.0) + quant
        return self

    def sub(self, rr: "Resource") -> "Resource":
        """Subtract; raises if rr does not fit (resource_info.go:143-160)."""
        if not rr.less_equal(self):
            raise ValueError(
                f"Resource is not sufficient to do operation: <{self}> sub <{rr}>"
            )
        self.milli_cpu -= rr.milli_cpu
        self.memory -= rr.memory
        if rr.scalar_resources:
            if self.scalar_resources is None:
                return self
            for name, quant in rr.scalar_resources.items():
                self.scalar_resources[name] = (
                    self.scalar_resources.get(name, 0.0) - quant
                )
        return self

    def multi(self, ratio: float) -> "Resource":
        self.milli_cpu *= ratio
        self.memory *= ratio
        for name in self.scalar_resources or {}:
            self.scalar_resources[name] *= ratio
        return self

    def set_max_resource(self, rr: Optional["Resource"]) -> None:
        """Per-dimension max (resource_info.go:162-188)."""
        if rr is None:
            return
        if rr.milli_cpu > self.milli_cpu:
            self.milli_cpu = rr.milli_cpu
        if rr.memory > self.memory:
            self.memory = rr.memory
        if rr.scalar_resources:
            if self.scalar_resources is None:
                self.scalar_resources = dict(rr.scalar_resources)
                return
            for name, quant in rr.scalar_resources.items():
                if quant > self.scalar_resources.get(name, 0.0):
                    self.scalar_resources[name] = quant

    def fit_delta(self, rr: "Resource") -> "Resource":
        """Availability minus request minus epsilon; negative dims mean
        insufficient (resource_info.go:190-214)."""
        if rr.milli_cpu > 0:
            self.milli_cpu -= rr.milli_cpu + MIN_MILLI_CPU
        if rr.memory > 0:
            self.memory -= rr.memory + MIN_MEMORY
        for name, quant in (rr.scalar_resources or {}).items():
            if self.scalar_resources is None:
                self.scalar_resources = {}
            if quant > 0:
                self.scalar_resources[name] = (
                    self.scalar_resources.get(name, 0.0) - quant - MIN_MILLI_SCALAR
                )
        return self

    # -- comparisons --------------------------------------------------------

    def less(self, rr: "Resource") -> bool:
        """Strictly less in every dimension (resource_info.go:226-251)."""
        if not (self.milli_cpu < rr.milli_cpu and self.memory < rr.memory):
            return False
        if self.scalar_resources is None:
            return rr.scalar_resources is not None
        for name, quant in self.scalar_resources.items():
            if rr.scalar_resources is None:
                return False
            if quant >= rr.scalar_resources.get(name, 0.0):
                return False
        return True

    def less_equal(self, rr: "Resource") -> bool:
        """Epsilon-tolerant <= in every dimension (resource_info.go:253-277)."""
        is_less = (
            self.milli_cpu < rr.milli_cpu
            or abs(rr.milli_cpu - self.milli_cpu) < MIN_MILLI_CPU
        ) and (self.memory < rr.memory or abs(rr.memory - self.memory) < MIN_MEMORY)
        if not is_less:
            return False
        if self.scalar_resources is None:
            return True
        for name, quant in self.scalar_resources.items():
            if rr.scalar_resources is None:
                return False
            rr_quant = rr.scalar_resources.get(name, 0.0)
            if not (quant < rr_quant or abs(rr_quant - quant) < MIN_MILLI_SCALAR):
                return False
        return True

    def diff(self, rr: "Resource") -> Tuple["Resource", "Resource"]:
        """Return (increased, decreased) vs rr (resource_info.go:279-312)."""
        increased = Resource.empty()
        decreased = Resource.empty()
        if self.milli_cpu > rr.milli_cpu:
            increased.milli_cpu = self.milli_cpu - rr.milli_cpu
        else:
            decreased.milli_cpu = rr.milli_cpu - self.milli_cpu
        if self.memory > rr.memory:
            increased.memory = self.memory - rr.memory
        else:
            decreased.memory = rr.memory - self.memory
        for name, quant in (self.scalar_resources or {}).items():
            rr_quant = (rr.scalar_resources or {}).get(name, 0.0)
            if quant > rr_quant:
                increased.add_scalar(name, quant - rr_quant)
            else:
                decreased.add_scalar(name, rr_quant - quant)
        return increased, decreased

    # -- accessors ----------------------------------------------------------

    def get(self, name: str) -> float:
        if name == RESOURCE_CPU:
            return self.milli_cpu
        if name == RESOURCE_MEMORY:
            return self.memory
        if self.scalar_resources is None:
            return 0.0
        return self.scalar_resources.get(name, 0.0)

    def resource_names(self) -> List[str]:
        return [RESOURCE_CPU, RESOURCE_MEMORY] + list(self.scalar_resources or {})

    def add_scalar(self, name: str, quantity: float) -> None:
        self.set_scalar(name, (self.scalar_resources or {}).get(name, 0.0) + quantity)

    def set_scalar(self, name: str, quantity: float) -> None:
        if self.scalar_resources is None:
            self.scalar_resources = {}
        self.scalar_resources[name] = quantity

    # -- dunder helpers (not in the reference; used by tests) ----------------

    def __eq__(self, other: object) -> bool:
        if not isinstance(other, Resource):
            return NotImplemented
        return (
            self.milli_cpu == other.milli_cpu
            and self.memory == other.memory
            and (self.scalar_resources or {}) == (other.scalar_resources or {})
        )

    def __hash__(self):  # pragma: no cover - Resources are mutable; identity hash
        return id(self)

    def __repr__(self) -> str:
        parts = [f"cpu {self.milli_cpu:.2f}", f"memory {self.memory:.2f}"]
        for name, quant in (self.scalar_resources or {}).items():
            parts.append(f"{name} {quant:.2f}")
        return ", ".join(parts)


class FrozenResource(Resource):
    """Immutable :class:`Resource` view.

    Task request vectors (TaskInfo.resreq / init_resreq) are frozen at
    construction so every clone on the snapshot/bookkeeping hot path can
    SHARE them instead of deep-copying (~150k Resource copies per
    50k-task cycle otherwise). Freezing makes the sharing safe by
    construction: any in-place mutation attempt raises instead of
    silently corrupting every holder. ``clone()`` (inherited) returns a
    regular mutable Resource, so ``resreq.clone().add(...)`` patterns
    keep working."""

    __slots__ = ()

    def _frozen(self, *args, **kwargs):
        raise TypeError(
            "Resource is frozen (task request vectors are shared across "
            "clones); use clone() to get a mutable copy"
        )

    __setattr__ = _frozen
    add = _frozen
    sub = _frozen
    multi = _frozen
    set_max_resource = _frozen
    fit_delta = _frozen
    add_scalar = _frozen
    set_scalar = _frozen


def freeze_resource(r: Resource) -> Resource:
    """Freeze in place (no copy): the scalar dict becomes a read-only
    mapping view and the __class__ switches to the slots-compatible
    immutable subclass, so both attribute rebinding AND in-place dict
    mutation raise."""
    if r.scalar_resources is not None:
        r.scalar_resources = MappingProxyType(r.scalar_resources)
    r.__class__ = FrozenResource
    return r


def min_resource(l: Resource, r: Resource) -> Resource:
    """Per-dimension min (reference api/helpers/helpers.go:28)."""
    out = Resource.empty()
    out.milli_cpu = min(l.milli_cpu, r.milli_cpu)
    out.memory = min(l.memory, r.memory)
    # Sorted so the scalar dict's insertion order is byte-stable across
    # processes (kbtlint replay-determinism: string set order is hash-
    # randomized, and a downstream layout iterating it would drift).
    for name in sorted(
        set(l.scalar_resources or {}) | set(r.scalar_resources or {})
    ):
        out.set_scalar(name, min(l.get(name), r.get(name)))
    return out


def share(l: float, r: float) -> float:
    """Safe ratio l/r (reference api/helpers/helpers.go:43-55)."""
    if r == 0:
        return 1.0 if l > 0 else 0.0
    return l / r
