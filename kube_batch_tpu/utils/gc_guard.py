"""Cycle-scoped garbage-collection deferral.

A 50k-task apply allocates ~100k short-lived objects (events, clones,
dict entries); CPython's generational GC triggers multiple collections
inside the scheduling cycle, and full collections scan the ~1M-object
cluster mirror — measured ~350 ms of the cold 50k apply (r4 profile),
indistinguishable from "slow bookkeeping" until isolated.

The Go reference pays this as concurrent GC; CPython stops the world.
``deferred_gc()`` moves the cost off the critical path: collection is
disabled for the duration of the cycle and a bounded young-generation
collection runs on exit — in the scheduler's think-time gap, where a
pause costs nothing. Nesting is safe (only the outermost guard
re-enables); an exception still restores GC. GC state is process-wide,
so the guard is too: a lock serializes the depth/enable bookkeeping and
the OUTERMOST enter records whether GC was on, so concurrent guards
from different threads (e.g. scheduler cycle + side-effect worker)
cannot strand GC disabled.

The exit collection runs WHILE HOLDING the guard lock, decided by the
last exiter (advisor r5: the earlier collect-after-release re-check
only narrowed the race — a thread entering between the re-check and the
collection's end still ate a stop-the-world pause inside its
"GC-free" cycle). The trade: a concurrent guard entry now blocks for
the duration of the exit collection — bounded, young-generation-only,
and in the exiter's think time — which is strictly better than an
unbounded pause landing mid-cycle. The lock is reentrant so a finalizer
that somehow enters a guard during the collection cannot deadlock."""

from __future__ import annotations

import gc
import threading
from contextlib import contextmanager

from .lockdebug import wrap_lock

_lock = wrap_lock("utils.gc_guard", threading.RLock())
_depth = 0
_outer_was_enabled = False


@contextmanager
def deferred_gc(collect_generation: int = 1):
    """Disable GC for the guarded block; on exit, re-enable and run one
    ``gc.collect(collect_generation)`` (default: young+middle
    generations — bounded, does not scan the full mirror). Pass -1 to
    skip the exit collection entirely."""
    global _depth, _outer_was_enabled
    with _lock:
        if _depth == 0:
            _outer_was_enabled = gc.isenabled()
            if _outer_was_enabled:
                gc.disable()
        _depth += 1
    try:
        yield
    finally:
        with _lock:
            _depth -= 1
            if _depth == 0 and _outer_was_enabled:
                gc.enable()
                if collect_generation >= 0:
                    # Under the lock, by the last exiter (see module
                    # docstring): an entering thread waits here instead
                    # of collecting mid-cycle later.
                    gc.collect(collect_generation)
