"""Per-task node filter/score helpers used by the greedy actions.

Mirrors reference pkg/scheduler/util/scheduler_helper.go (:63 PredicateNodes,
:89 PrioritizeNodes weighted sum, :174 SortNodes, :188 SelectBestNode random
among max). The reference parallelizes with 16 goroutines; the greedy Python
path is the measured baseline only — the production path is the batched TPU
solve in ops/, which replaces this entire per-task machinery.
"""

from __future__ import annotations

import random
from typing import Callable, Dict, List, Sequence, Tuple

from ..api import NodeInfo, TaskInfo

# (node_name, score) pairs, higher is better.
HostPriorityList = List[Tuple[str, float]]


def predicate_nodes(
    task: TaskInfo, nodes: Sequence[NodeInfo], fn: Callable
) -> List[NodeInfo]:
    """Nodes passing the predicate; fn raises on failure
    (scheduler_helper.go:63-86)."""
    out: List[NodeInfo] = []
    for node in nodes:
        try:
            fn(task, node)
        except Exception:
            continue
        out.append(node)
    return out


def prioritize_nodes(
    task: TaskInfo,
    nodes: Sequence[NodeInfo],
    prioritizers: Sequence[Tuple[Callable, float]],
) -> HostPriorityList:
    """Weighted score sum per node (scheduler_helper.go:89-171)."""
    result: HostPriorityList = []
    for node in nodes:
        score = 0.0
        for fn, weight in prioritizers:
            score += weight * fn(task, node)
        result.append((node.name, score))
    return result


def sort_nodes(
    priority_list: HostPriorityList, nodes_info: Dict[str, NodeInfo]
) -> List[NodeInfo]:
    """Nodes in descending score order (scheduler_helper.go:174-185)."""
    ordered = sorted(priority_list, key=lambda hp: hp[1], reverse=True)
    return [nodes_info[name] for name, _ in ordered]


# Module-scoped RNG so tests can pin tie-breaking without mutating the
# process-wide stdlib random state.
_rng = random.Random()


def select_best_node(priority_list: HostPriorityList) -> str:
    """Highest score, random among ties (scheduler_helper.go:188-208)."""
    if not priority_list:
        raise ValueError("empty priority list")
    max_score = max(s for _, s in priority_list)
    best = [name for name, s in priority_list if s == max_score]
    return _rng.choice(best)


def get_node_list(nodes: Dict[str, NodeInfo]) -> List[NodeInfo]:
    """Stable order for determinism (reference returns map order,
    scheduler_helper.go:211-216)."""
    return [nodes[name] for name in sorted(nodes)]


class FeasibilityMemo:
    """Cycle-scoped, spec-keyed cache of predicate-feasible node lists.

    Actions that scan all nodes per pending task (reclaim claimants and
    their gang sims, extended backfill) pay O(tasks x nodes) predicate
    calls per cycle; at 1k nodes x 16k claimants that WAS reclaim
    throughput (perf-multitenant r4). Tasks with equal constraint specs
    provably share a verdict for the SPEC-driven predicates, so they
    share one pass.

    Soundness limits, all handled here:

    - tasks with host ports or inter-pod (anti-)affinity are never
      cached (their verdict depends on what else is on the node, which
      changes mid-cycle);
    - the pod-count predicate (check_max_task_num) is dynamic for
      EVERYONE — pipelines add node tasks mid-cycle — so cached lists
      are re-filtered against the CURRENT count at every use. A node the
      build-time pass excluded that later gains headroom stays excluded
      (conservative: self-corrects next cycle); a node that filled up is
      dropped at use time (never over-placed).
    """

    def __init__(self, ssn):
        self.ssn = ssn
        self._entries: List[tuple] = []  # (spec, nodes)

    @staticmethod
    def _cacheable(spec) -> bool:
        if any(c.ports for c in spec.containers):
            return False
        aff = spec.affinity
        return aff is None or not (aff.pod_affinity or aff.pod_anti_affinity)

    @staticmethod
    def _has_headroom(node: NodeInfo) -> bool:
        cap = node.allocatable.max_task_num
        return not (0 < cap <= len(node.tasks))

    def feasible(self, task) -> List[NodeInfo]:
        spec = task.pod.spec
        if self._cacheable(spec):
            for seen_spec, nodes in self._entries:
                if (
                    spec.node_selector == seen_spec.node_selector
                    and spec.affinity == seen_spec.affinity
                    and spec.tolerations == seen_spec.tolerations
                ):
                    return [n for n in nodes if self._has_headroom(n)]
        nodes = []
        for node in get_node_list(self.ssn.nodes):
            try:
                self.ssn.predicate_fn(task, node)
            except Exception:
                continue
            nodes.append(node)
        if self._cacheable(spec):
            self._entries.append((spec, nodes))
        return nodes
