#!/usr/bin/env python
"""Cluster e2e driver — the hack/run-e2e-kind.sh analog (reference
hack/run-e2e-kind.sh:46-82: bring up a cluster, install CRDs + default
queue, run the scheduler binary against it, run a gang spec, tear down).

Fake mode (default, no cluster needed): starts the in-repo fake
Kubernetes API server (kube_batch_tpu.utils.fake_kube — the kubemark
analog: real scheduler, simulated kubelet), writes a kubeconfig, launches
the REAL scheduler CLI (``python -m kube_batch_tpu --kubeconfig ...``) as
a subprocess, seeds a queue, nodes, and a minMember=3 gang through the
API, and asserts all three pods get Binding-POSTed and flip Running.

Real mode: point hack/run-e2e.sh at a kubeconfig — it applies
config/crds/ + the default queue with kubectl and runs this flow against
the live API server.

Usage: python tools/run_e2e.py [--pods N] [--min-member M] [--timeout S]
Exit code 0 = gang scheduled; 1 = failure (scheduler log tail printed).
"""

import argparse
import json
import os
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from kube_batch_tpu.utils.fake_kube import (  # noqa: E402
    GROUP,
    FakeKube,
    node_doc,
    pod_doc,
)


def write_kubeconfig(path: str, server: str) -> None:
    cfg = {
        "apiVersion": "v1",
        "kind": "Config",
        "current-context": "e2e",
        "contexts": [
            {"name": "e2e", "context": {"cluster": "e2e", "user": "e2e"}}
        ],
        "clusters": [{"name": "e2e", "cluster": {"server": server}}],
        "users": [{"name": "e2e", "user": {}}],
    }
    with open(path, "w") as f:
        json.dump(cfg, f)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--pods", type=int, default=3)
    ap.add_argument("--min-member", type=int, default=3)
    ap.add_argument("--timeout", type=float, default=60.0)
    ap.add_argument("--conf", default=os.path.join(
        REPO, "config", "tpu-batch-conf.yaml"
    ))
    args = ap.parse_args()

    fake = FakeKube()
    print(f"fake API server: {fake.url}")

    # Default queue (reference config/queue/default.yaml).
    fake.create("Queue", {
        "apiVersion": f"{GROUP}/v1alpha1", "kind": "Queue",
        "metadata": {"name": "default"}, "spec": {"weight": 1},
    })
    for i in range(2):
        fake.create("Node", node_doc(f"n{i}", cpu="4"))

    kubeconfig = tempfile.NamedTemporaryFile(
        suffix=".kubeconfig", delete=False
    )
    kubeconfig.close()
    write_kubeconfig(kubeconfig.name, fake.url)

    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "PALLAS_AXON_POOL_IPS": "",
        "PYTHONPATH": REPO,
    })
    log = tempfile.NamedTemporaryFile(
        mode="w+", suffix=".log", delete=False
    )
    sched = subprocess.Popen(
        [sys.executable, "-m", "kube_batch_tpu",
         "--kubeconfig", kubeconfig.name,
         "--scheduler-conf", args.conf,
         "--listen-address", "127.0.0.1:0",
         "--schedule-period", "0.5"],
        stdout=log, stderr=subprocess.STDOUT, env=env, cwd=REPO,
    )
    try:
        time.sleep(1.0)  # let list+watch establish

        # The gang spec (reference example/job.yaml: one PodGroup,
        # minMember=3, one queue).
        fake.create("PodGroup", {
            "apiVersion": f"{GROUP}/v1alpha1", "kind": "PodGroup",
            "metadata": {"name": "e2e-gang", "namespace": "default"},
            "spec": {"minMember": args.min_member, "queue": "default"},
        })
        for i in range(args.pods):
            fake.create(
                "Pod", pod_doc(f"e2e-p{i}", group="e2e-gang")
            )

        deadline = time.time() + args.timeout
        while time.time() < deadline:
            if sched.poll() is not None:
                print("FAIL: scheduler exited early")
                break
            with fake.lock:
                done = len(fake.bindings) >= args.pods
                running = sum(
                    1 for p in fake.objects["Pod"].values()
                    if p["status"]["phase"] == "Running"
                )
            if done and running >= args.pods:
                print(
                    f"PASS: {len(fake.bindings)}/{args.pods} pods bound "
                    f"and Running: {sorted(fake.bindings)}"
                )
                return 0
            time.sleep(0.2)
        print(f"FAIL: bindings after {args.timeout}s: {fake.bindings}")
        log.flush()
        with open(log.name) as f:
            tail = f.read()[-3000:]
        print("--- scheduler log tail ---")
        print(tail)
        return 1
    finally:
        sched.terminate()
        try:
            sched.wait(10)
        except subprocess.TimeoutExpired:
            sched.kill()
        fake.close()
        os.unlink(kubeconfig.name)


if __name__ == "__main__":
    sys.exit(main())
