"""Phase 1 of the candidate-sparsified solve: top-K node selection.

At 50k tasks x 5k nodes every dense solver structure is [T, N] — a f32
score matrix alone is ~1 GB — which caps scale far short of the 200k x
20k shapes the roadmap targets (~16 GB, infeasible). But the bid/commit
dynamics only ever LAND a task on one of a handful of best-scoring
feasible nodes (Tesserae's placement policies, PAPERS.md: candidate sets
of a few dozen nodes preserve placement quality; CvxCluster gets its
100-1000x from exactly this granularity structure). So one cheap fused
pass here — host-side NumPy, at snapshot time — scores every candidate
CLASS against the snapshot's initial idle state and keeps its top-K
candidate nodes; the solver's rounds then run on gathered [T, K] slabs
(kernels._sparse_round / native greedy_allocate_sparse).

A candidate CLASS dedups tasks that provably share a score surface:
same predicate feasibility group, same req/fit rows, and no private
pair/score rows (tasks WITH private rows become singleton classes that
keep their rows). Gang members instantiated from one pod template all
land in one class, so selection work scales with the number of DISTINCT
task shapes (dozens to hundreds), not tasks.

Selection eligibility is ``feasible AND fits-at-initial-idle AND
pod-count-capacity-open``: idle only shrinks and pod counts only grow
during a solve, so a node outside that set can NEVER accept the class's
tasks — which yields the solver's exactness invariant: a class whose
eligible set has <= K nodes gets a COMPLETE slab (``cand_info[0]``,
the refill gauge), and slab exhaustion for it is bit-identical to the
dense solver's no-fit verdict. Truncated classes route exhausted tasks
to the refill stage instead (kernels._dense_tail), never to a false
job break.

``KBT_SOLVER_TOPK`` overrides the policy: an integer forces that K at
any problem size; ``0``/``off``/``dense`` disables sparsification.
"""

from __future__ import annotations

import hashlib
import os
from dataclasses import dataclass
from typing import Dict, Optional

import numpy as np

from .kernels import (
    _KEY_BIAS,
    _KEY_HASH_BITS,
    CPU_DIM,
    MAX_PRIORITY,
    MEM_DIM,
    SCORE_QUANTUM,
)

# Sparsification pays off once the dense [T, N] structures dominate and
# the slab is a real subset; below these the dense solvers win outright.
# The task floor is a PRODUCT bound, not a task count: a 500-task
# arrival batch against 5 000 nodes is 2.5 M dense score cells (~13 ms
# native) where selection costs C·N for a handful of classes — exactly
# the warm steady-cycle shape, so small-T/large-N problems sparsify too.
_SPARSE_MIN_TASKS = 64
_SPARSE_MIN_CELLS = 1 << 20
_SPARSE_MIN_NODES = 1024
DEFAULT_K = 64

# Selection itself costs O(C * N); if class dedup degenerates (every
# task a distinct shape) that approaches the dense pass it is meant to
# replace, so the policy falls back to dense past this budget.
_CLASS_BUDGET_FACTOR = 4

# Deterministic top-K tie rule, shared with the device path
# (solver/select_device.py): larger key first, equal keys -> smaller
# node id. The host realizes it by partitioning on an int64 composite
# ``(skey << 31) + (2^31-1 - node_id)`` (skey tops out below 2^30, so
# the composite never overflows and ineligible rows stay negative);
# the device gets the identical rule for free from ``lax.top_k``'s
# lower-index-first preference. Without this, argpartition's choice at
# the k-th boundary was unspecified on quantized-score ties.
_TIE_BITS = 31


@dataclass(frozen=True)
class TopKConfig:
    """Resolved candidate-sparsification policy for one snapshot."""

    k: int
    enabled: bool
    reason: str


def _pow2(n: int) -> int:
    if n <= 0:
        return 1
    return 1 << (n - 1).bit_length()


def topk_config(n_tasks: int, n_nodes: int) -> TopKConfig:
    """Resolve K and the sparse on/off decision for a (T, N) snapshot.

    K is power-of-two bucketed (like the task-axis shape buckets) so a
    configured K never mints per-value jit variants."""
    raw = os.environ.get("KBT_SOLVER_TOPK", "").strip().lower()
    if raw in ("0", "off", "dense", "disable", "disabled", "false"):
        return TopKConfig(0, False, "env-disabled")
    k = DEFAULT_K
    forced = False
    if raw:
        try:
            k = max(1, int(raw))
            forced = True
        except ValueError:
            pass
    k = _pow2(k)
    if forced:
        return TopKConfig(k, True, "env-forced")
    if (
        n_tasks < _SPARSE_MIN_TASKS
        or n_nodes < _SPARSE_MIN_NODES
        or n_tasks * n_nodes < _SPARSE_MIN_CELLS
    ):
        return TopKConfig(k, False, "small-problem")
    if 4 * k >= n_nodes:
        return TopKConfig(k, False, "k-covers-nodes")
    return TopKConfig(k, True, "size-policy")


@dataclass
class CandidateSet:
    """Selection output, pre-padding (node sentinel = N unpadded)."""

    task_cand: np.ndarray    # i32[T] class id per task
    cand_idx: np.ndarray     # i32[C, K] candidate node ids ascending
    cand_static: np.ndarray  # f32[C, K] static score slab
    cand_info: np.ndarray    # i32[3, C] total / any_feas / fits_releasing
    stats: dict


def _layout_sig_token():
    """Solver layout token folded into the selection-cache signatures
    (host AND device): a mesh/mode/rack-map change reshuffles which
    node block each shard owns, so carried key rows must invalidate
    with the same ``mesh-changed`` semantics as the warm plan."""
    try:
        from .sharding import prospective_layout_token

        return prospective_layout_token()
    except Exception:  # pragma: no cover - sharding import must not kill
        return None


def _sel_hash(c_ids: np.ndarray, n_ids: np.ndarray) -> np.ndarray:
    """Decorrelated per-(class, node) hash in [0, 1024) — the selection
    analog of kernels._bid_hash. Spreads equal-scored classes across
    DIFFERENT slabs so a homogeneous cluster does not herd every class
    onto the same K nodes (the selection-level form of the bid-key
    tie-break rationale)."""
    x = (c_ids.astype(np.uint32) * np.uint32(2654435761)) ^ (
        n_ids.astype(np.uint32) * np.uint32(0x9E3779B9)
    )
    x = x ^ (x >> np.uint32(13))
    x = x * np.uint32(2246822519)
    return (
        (x >> np.uint32(8)) & np.uint32((1 << _KEY_HASH_BITS) - 1)
    ).astype(np.int64)


def _dyn_score_np(req, idle, cap, lr_w, br_w):
    """[C, N] LeastRequested + Balanced in f32 NumPy — the selection
    mirror of kernels._dyn_score_core (selection quality only; kernel
    rounds rescore against evolving idle on-device). Written as 2-D
    per-dimension passes: the [C, N, 2] broadcast temporaries were most
    of the selection pass's cost at warm steady-cycle shapes (small C,
    large N)."""
    ten = np.float32(MAX_PRIORITY)
    lr_acc = None
    fracs = []
    over = None
    for d in (CPU_DIM, MEM_DIM):
        req_d = req[:, d:d + 1].astype(np.float32)        # [C, 1]
        idle_d = idle[None, :, d].astype(np.float32)      # [1, N]
        cap_d = cap[None, :, d].astype(np.float32)
        pos = cap_d > 0
        safe_cap = np.where(pos, cap_d, np.float32(1.0))
        remaining = idle_d - req_d                        # [C, N]
        lr = np.where(
            pos, np.maximum(remaining, 0.0) * ten / safe_cap,
            np.float32(0.0),
        )
        lr_acc = lr if lr_acc is None else lr_acc + lr
        frac = np.where(pos, 1.0 - remaining / safe_cap, np.float32(1.0))
        fracs.append(frac)
        o = frac >= 1.0
        over = o if over is None else (over | o)
    lr_score = lr_acc * np.float32(0.5)
    diff = np.abs(fracs[0] - fracs[1])
    br_score = np.where(over, np.float32(0.0), ten - diff * ten)
    return (
        np.float32(lr_w) * lr_score + np.float32(br_w) * br_score
    ).astype(np.float32)


class _SelectionCache:
    """Cross-cycle per-class selection-key rows (stored on the
    scheduler cache as ``_topk_sel_cache``).

    A class's [N] integer key row is a pure function of (its feasibility
    row, its req/fit rows, per-node idle/cap/count/max, eps, weights,
    its class index). The feas/req/fit inputs are content-addressed by
    digest; the node inputs by the shared node scan's (identity, _ver)
    fingerprint — so a warm steady cycle recomputes each cached row
    only at the columns whose node actually changed (the placement
    wave), O(C·churn) instead of O(C·N). Any drift — new class shapes,
    changed weights, an unfingerprintable call — misses to the exact
    full computation, so cached and fresh selections are bit-identical
    by construction."""

    __slots__ = ("sig", "node_objs", "node_ids", "node_vers", "rows",
                 "dedup_key", "dedup")

    def __init__(self):
        self.sig = None
        # The fingerprinted node objects are PINNED here (like
        # _TensorizeCache.node_objs): a pinned object's id can never be
        # recycled under a new clone, so the id array stays an exact
        # identity witness even across cycles where selection is
        # skipped (warm-noop, dense-path, deferred micro) and the
        # previous clones would otherwise be freed.
        self.node_objs = None
        self.node_ids = None
        self.node_vers = None
        self.rows: Dict[tuple, np.ndarray] = {}
        # Content-addressed class dedup: digest of the [T, 2+2R] key
        # matrix -> its np.unique decomposition. The lexsort behind
        # np.unique(axis=0) is O(T log T) over 6 columns (seconds at
        # 1M tasks) while a steady cycle's task CONTENT rarely moves —
        # node churn never touches it. An exact digest hit replays the
        # identical (rep_idx, task_cand); any content change misses to
        # the full unique.
        self.dedup_key = None
        self.dedup = None


def _sel_cache_of(holder) -> Optional[_SelectionCache]:
    if holder is None:
        return None
    sc = getattr(holder, "_topk_sel_cache", None)
    if sc is None:
        sc = _SelectionCache()
        try:
            holder._topk_sel_cache = sc
        except Exception:
            return None
    return sc


def _skey_block(req_rows, fit_rows, class_ids, cols,
                idle32, cap32, eps32, cap_ok0, feas_cols,
                lr_w, br_w):
    """Integer selection keys for ``class_ids`` × ``cols`` (global node
    indexes): eligibility-masked quantized score + class/node hash —
    exactly the full pass's math on a column subset (elementwise ops
    only, so subset and full computation are bit-identical)."""
    R = req_rows.shape[1]
    idle_c = idle32[cols]                              # [M, R]
    cap_c = cap32[cols]
    fit_ok = np.ones((req_rows.shape[0], len(cols)), dtype=bool)
    for d in range(R):
        fit_ok &= fit_rows[:, d:d + 1] - idle_c[None, :, d] < eps32[d]
    elig = feas_cols & fit_ok & cap_ok0[cols][None, :]
    score = _dyn_score_np(req_rows, idle_c, cap_c, lr_w, br_w)
    q = np.clip(
        np.round(score / np.float32(SCORE_QUANTUM)).astype(np.int64)
        + _KEY_BIAS,
        0, (1 << 20) - 1,
    )
    skey = (q << _KEY_HASH_BITS) | _sel_hash(
        np.asarray(class_ids, np.int64)[:, None],
        np.asarray(cols, np.int64)[None, :],
    )
    return np.where(elig, skey, -1)


def _skey_priv_row(req_row, fit_row, class_id,
                   idle32, cap32, eps32, cap_ok0, feas_row, srow,
                   lr_w, br_w):
    """One class's key row with its private static score row folded in
    before quantization — the dense ``dynamic + static`` chain."""
    R = req_row.shape[1]
    N = idle32.shape[0]
    fit_ok = np.ones((1, N), dtype=bool)
    for d in range(R):
        fit_ok &= fit_row[:, d:d + 1] - idle32[None, :, d] < eps32[d]
    elig = feas_row & fit_ok & cap_ok0[None, :]
    score = _dyn_score_np(req_row, idle32, cap32, lr_w, br_w) + srow
    q = np.clip(
        np.round(score / np.float32(SCORE_QUANTUM)).astype(np.int64)
        + _KEY_BIAS,
        0, (1 << 20) - 1,
    )
    skey = (q << _KEY_HASH_BITS) | _sel_hash(
        np.asarray([class_id], np.int64)[:, None],
        np.arange(N, dtype=np.int64)[None, :],
    )
    return np.where(elig, skey, -1)[0]


def select_candidates(
    mask: "CombinedMask",         # masks.CombinedMask (unpadded)
    score_rows_map: Dict[int, np.ndarray],
    task_req: np.ndarray,         # f32[T, R] rank-ordered
    task_fit: np.ndarray,         # f32[T, R]
    node_idle: np.ndarray,        # [N, R]
    node_cap: np.ndarray,         # [N, R]
    node_releasing: np.ndarray,   # [N, R]
    node_task_count: np.ndarray,  # i32[N]
    node_max_tasks: np.ndarray,   # i32[N]
    eps: np.ndarray,              # [R]
    lr_weight: float,
    br_weight: float,
    k: int,
    cache_holder: Optional[object] = None,
    # (ids i64[N], vers i64[N], [NodeInfo] pins) or None
    node_fp: Optional[tuple] = None,
    # select_device.SelectionDeviceState or None
    device_state: Optional["SelectionDeviceState"] = None,
) -> Optional[CandidateSet]:
    """Run the fused feasibility + static-score selection pass.

    Returns None (→ dense solve, with the reason in the caller's stats)
    when class dedup degenerates past the selection budget."""
    T, R = task_req.shape
    N = node_idle.shape[0]
    k = min(_pow2(k), _pow2(N))

    # ---- class dedup: (feasibility group, private-row id, req, fit) ----
    priv = np.full(T, -1, np.int64)
    if len(mask.pair_idx):
        priv[mask.pair_idx] = mask.pair_idx
    if score_rows_map:
        for i in score_rows_map:
            priv[int(i)] = int(i)
    # Exact float32 keys: group/priv ids stay < 2^24 (tasks per snapshot
    # are far below that), req/fit are already f32 rows.
    key_mat = np.column_stack([
        mask.task_group.astype(np.float32),
        priv.astype(np.float32),
        task_req.astype(np.float32),
        task_fit.astype(np.float32),
    ])
    sc0 = _sel_cache_of(cache_holder)
    dedup_key = None
    if sc0 is not None:
        dedup_key = hashlib.blake2b(
            key_mat.tobytes(), digest_size=16
        ).digest()
    if sc0 is not None and sc0.dedup_key == dedup_key:
        rep_idx, task_cand = sc0.dedup
    else:
        _, rep_idx, task_cand = np.unique(
            key_mat, axis=0, return_index=True, return_inverse=True
        )
        task_cand = task_cand.reshape(-1).astype(np.int32)
        rep_idx = rep_idx.astype(np.int64)
        if sc0 is not None:
            sc0.dedup_key = dedup_key
            sc0.dedup = (rep_idx, task_cand)
    C = len(rep_idx)
    if C * N > max(_CLASS_BUDGET_FACTOR * T * k, 1 << 22):
        return None

    idle32 = np.ascontiguousarray(node_idle, np.float32)
    cap32 = np.ascontiguousarray(node_cap, np.float32)
    eps32 = np.asarray(eps, np.float32)
    cap_ok0 = (node_max_tasks == 0) | (node_task_count < node_max_tasks)
    has_releasing = bool(np.asarray(node_releasing).any())
    rel32 = (
        np.ascontiguousarray(node_releasing, np.float32)
        if has_releasing else None
    )
    rep_fit = task_fit[rep_idx].astype(np.float32)
    rep_req = task_req[rep_idx].astype(np.float32)
    rep_priv = priv[rep_idx]

    cand_idx = np.full((C, k), N, np.int32)
    cand_static = np.zeros((C, k), np.float32)
    cand_info = np.zeros((3, C), np.int32)

    def _mk_stats(cache_hits_, extra):
        slab_bytes = (
            cand_idx.nbytes + cand_static.nbytes + cand_info.nbytes
            + task_cand.nbytes
        )
        stats = {
            "classes": int(C),
            "k": int(k),
            "slab_bytes": int(slab_bytes),
            # What the dense path would materialize per round on device:
            # the [T, N] bool mask and f32 score/key matrices.
            "dense_mask_bytes": int(T) * int(N),
            "dense_score_bytes": int(T) * int(N) * 4,
            "truncated_classes": int((cand_info[0] > k).sum()),
            # Cross-cycle selection-cache effectiveness (classes whose
            # key rows were reused with only churned columns recomputed).
            "sel_cache_hits": int(cache_hits_),
        }
        stats.update(extra)
        return stats

    # --- device-resident selection (solver/select_device.py) ------------
    # Scores, key rows, and the top-K extraction run on the accelerator
    # against the resident node stacks; everything below this branch is
    # the host path, which stays bit-equal by construction and serves
    # as the labeled fallback.
    dev_res = None
    select_path = "host"
    if device_state is not None:
        from .select_device import device_select_enabled, select_rows

        if not device_select_enabled():
            select_path = "host:env-disabled"
        elif has_releasing:
            select_path = "host:releasing"
        else:
            dev_res = select_rows(
                device_state, mask, rep_idx, rep_req, rep_fit, rep_priv,
                score_rows_map, idle32, cap32, eps32, cap_ok0,
                lr_weight, br_weight, k, N, node_fp=node_fp,
            )
            select_path = (
                "device" if dev_res is not None
                else "host:device-unavailable"
            )
    if dev_res is not None:
        cand_idx = dev_res["cand_idx"]
        cand_info[0] = np.minimum(
            dev_res["elig_count"], np.iinfo(np.int32).max
        )
        cand_info[1] = dev_res["any_feas"]
        # Private static rows ride the slab exactly like the host path.
        for ci in np.nonzero(rep_priv >= 0)[0]:
            p = int(rep_priv[ci])
            if p not in score_rows_map:
                continue
            srow = np.asarray(score_rows_map[p], np.float32)
            row = cand_idx[ci]
            sel = row < N
            cand_static[ci, sel] = srow[row[sel]]
        try:
            from .. import metrics

            metrics.register_device_selection()
        except Exception:  # pragma: no cover - metrics must never kill
            pass
        stats = _mk_stats(dev_res["cache_hits"], {
            "select_path": select_path,
            "sel_rows_rebuilt": int(dev_res["rows_rebuilt"]),
            "sel_cols_patched": int(dev_res["cols_patched"]),
        })
        return CandidateSet(
            task_cand, cand_idx, cand_static, cand_info, stats
        )

    # Cross-cycle key-row cache (see _SelectionCache): usable only when
    # the caller provided a node fingerprint and the cluster holds no
    # Releasing capacity (the releasing column is not cached).
    sc = _sel_cache_of(cache_holder) if node_fp is not None else None
    changed_cols = None
    sig = (N, int(k), R, eps32.tobytes(),
           float(lr_weight), float(br_weight), _layout_sig_token())
    if sc is not None and not has_releasing:
        ids, vers, node_objs = node_fp
        if (
            sc.sig == sig
            and sc.node_ids is not None
            and len(sc.node_ids) == N
        ):
            changed_cols = np.nonzero(
                (ids != sc.node_ids) | (vers != sc.node_vers)
            )[0]
        else:
            sc.rows = {}
            changed_cols = None
        sc.sig = sig
        sc.node_objs = node_objs
        sc.node_ids = ids
        sc.node_vers = vers
    elif sc is not None:
        sc.rows = {}
        sc.node_objs = None
        sc.node_ids = None

    node_ids = np.arange(N, dtype=np.int64)
    # Composite tie term (see _TIE_BITS): smaller node id -> larger
    # low bits, so equal-skey boundary picks match lax.top_k's.
    tie_lo = (np.int64(1) << _TIE_BITS) - 1 - node_ids
    new_rows: Dict[tuple, np.ndarray] = {}
    cache_hits = 0
    chunk = max(1, min(C, (1 << 22) // max(N, 1)))
    for c0 in range(0, C, chunk):
        c1 = min(c0 + chunk, C)
        rows = c1 - c0
        feas = mask.rows_for(rep_idx[c0:c1])                 # [rows, N]
        fit_chunk = rep_fit[c0:c1]
        req_chunk = rep_req[c0:c1]

        # Per-class cache resolution: digest the content inputs, reuse
        # the cached key row with only the changed columns recomputed.
        skey = None
        row_keys = {}
        misses = list(range(rows))
        if sc is not None and not has_releasing:
            skey = np.empty((rows, N), dtype=np.int64)
            misses = []
            hit_locals = []
            for local in range(rows):
                ci = c0 + local
                if rep_priv[ci] >= 0:
                    misses.append(local)  # private rows: never cached
                    continue
                key = (ci, hashlib.blake2b(
                    feas[local].tobytes()
                    + fit_chunk[local].tobytes()
                    + req_chunk[local].tobytes(),
                    digest_size=16,
                ).digest())
                row_keys[local] = key
                row = (
                    sc.rows.get(key) if changed_cols is not None else None
                )
                if row is None:
                    misses.append(local)
                    continue
                skey[local] = row
                hit_locals.append(local)
            if hit_locals and changed_cols is not None and len(changed_cols):
                sub = _skey_block(
                    req_chunk[hit_locals], fit_chunk[hit_locals],
                    [c0 + lo for lo in hit_locals], changed_cols,
                    idle32, cap32, eps32, cap_ok0,
                    feas[hit_locals][:, changed_cols],
                    lr_weight, br_weight,
                )
                for i, local in enumerate(hit_locals):
                    skey[local][changed_cols] = sub[i]
            cache_hits += len(hit_locals)

        # Singleton classes keep their private static score rows — the
        # slab ships the gathered values so the kernel adds them exactly
        # like the dense `dynamic + static` chain. Their key rows fold
        # the addend into the score before quantization (never cached),
        # computed individually so the bulk block never computes them
        # twice.
        srows = {}
        if misses:
            if skey is None:
                skey = np.empty((rows, N), dtype=np.int64)
            priv_misses = []
            plain = []
            for local in misses:
                p = int(rep_priv[c0 + local])
                if p >= 0 and p in score_rows_map:
                    priv_misses.append((local, p))
                else:
                    plain.append(local)
            if plain:
                # Full computation for the plain miss rows — identical
                # math to the cached path (elementwise ops on the full
                # column set).
                full = _skey_block(
                    req_chunk[plain], fit_chunk[plain],
                    [c0 + lo for lo in plain], node_ids,
                    idle32, cap32, eps32, cap_ok0,
                    feas[plain],
                    lr_weight, br_weight,
                )
                for i, local in enumerate(plain):
                    skey[local] = full[i]
            for local, p in priv_misses:
                srow = np.asarray(score_rows_map[p], np.float32)
                srows[local] = srow
                skey[local] = _skey_priv_row(
                    req_chunk[local:local + 1],
                    fit_chunk[local:local + 1], c0 + local,
                    idle32, cap32, eps32, cap_ok0,
                    feas[local:local + 1], srow,
                    lr_weight, br_weight,
                )

        for local, key in row_keys.items():
            new_rows[key] = skey[local].copy()

        elig_count = (skey >= 0).sum(axis=1)
        cand_info[0, c0:c1] = np.minimum(
            elig_count, np.iinfo(np.int32).max
        )
        cand_info[1, c0:c1] = (feas & cap_ok0[None, :]).any(axis=1)
        if has_releasing:
            rel_ok = np.ones((rows, N), dtype=bool)
            for d in range(R):
                rel_ok &= (
                    fit_chunk[:, d:d + 1] - rel32[None, :, d] < eps32[d]
                )
            cand_info[2, c0:c1] = (rel_ok & feas).any(axis=1)

        if k < N:
            skey2 = (skey << _TIE_BITS) + tie_lo[None, :]
            part = np.argpartition(skey2, N - k, axis=1)[:, N - k:]
            pkey = np.take_along_axis(skey2, part, axis=1)
        else:
            part = np.broadcast_to(node_ids[None, :], (rows, N)).copy()
            pkey = np.take_along_axis(skey, part, axis=1)
        part = part.astype(np.int32)
        part[pkey < 0] = N           # ineligible picks → sentinel
        part.sort(axis=1)            # ascending node id, sentinels last
        cand_idx[c0:c1, : part.shape[1]] = part[:, :k]
        for local, srow in srows.items():
            row = cand_idx[c0 + local]
            sel = row < N
            cand_static[c0 + local, sel] = srow[row[sel]]

    if sc is not None and not has_releasing:
        sc.rows = {
            key: row for key, row in new_rows.items() if row is not None
        }

    stats = _mk_stats(cache_hits, {"select_path": select_path})
    return CandidateSet(task_cand, cand_idx, cand_static, cand_info, stats)
