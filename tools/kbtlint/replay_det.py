"""Pass 6: replay-determinism lint (the bit-equal-replay class,
mechanical).

The simulator's record/replay guarantee — replaying a trace reproduces
every placement byte-for-byte — plus the warm-start state machine's
"bit-parity with a cold scheduler" invariant were each re-proved by
hand in PRs 4-8. This pass states the mechanical core: on any code
path reachable from the sim record/replay stack, the warm-start state
machine, or solver verdict production, nothing may consult a source
that differs between a recording run and its replay:

- **absolute wall-clock reads** — ``time.time()``/``time_ns()``/
  ``datetime.now()`` and friends. Duration clocks (``perf_counter``/
  ``monotonic``/``process_time``) are exempt by rule: they measure
  elapsed time for stats and deadlines, both outside the bit-equal
  contract (placements are the verified quantity; a deadline trip is
  a fault the trace records as an event);
- **module-level RNG** — ``random.x(...)`` / ``np.random.x(...)``
  (seeded ``random.Random(seed)`` / ``np.random.default_rng(seed)``
  instances resolve through a variable receiver and are fine);
- **environment reads** — ``os.environ[...]`` / ``.get`` /
  ``os.getenv``: an env difference between record and replay silently
  changes behavior with no trace-header witness;
- **unordered iteration** — ``for x in <set>`` (set literals,
  ``set()``/``frozenset()`` constructions, locals assigned from one,
  set-algebra binops) and ``<set>.pop()``: string-hash randomization
  makes the order differ across PROCESSES, which is exactly the
  record-vs-replay boundary. ``sorted(<set>)`` is the fix and is not
  flagged;
- **id()-keyed ordering** — ``sorted(key=id)`` / ``.sort(key=id)`` /
  ``min/max(key=id)`` (including through a lambda): id order is
  allocation order, different every run. id()-keyed *lookup* is fine
  (deterministic within a process) and not flagged.

Reachability: forward closure over the project call graph from every
function in ``ROOT_PREFIXES`` (sim/, solver/warm.py, the allocate
action's verdict production). Observability sinks (obs/, metrics/) and
the CLI are exempt: their OUTPUT is explicitly outside the bit-equal
contract — placements are the replay-verified quantity — and wall
clocks are their job.

The runtime twin is the replay harness itself (``sim --replay`` diffs
placements byte-for-byte; the soak detectors replay-bisect any drift);
this pass is the static front door that catches the class before a
soak has to.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set

from .callgraph import get_callgraph
from .core import (
    Finding,
    Project,
    attr_chain,
    call_name,
    iter_functions,
    register_pass,
)

PASS_ID = "replay-determinism"

# Forward-closure roots: record/replay, warm-start, verdict production.
ROOT_PREFIXES = (
    "kube_batch_tpu/sim/",
    "kube_batch_tpu/solver/warm.py",
    "kube_batch_tpu/actions/allocate_tpu.py",
)

# Reachable-but-exempt: observability output is outside the bit-equal
# replay contract (placements are the verified quantity), and the CLI /
# lockdebug layers are process plumbing.
EXEMPT_PREFIXES = (
    "kube_batch_tpu/obs/",
    "kube_batch_tpu/metrics/",
    "kube_batch_tpu/cli/",
    "kube_batch_tpu/utils/lockdebug.py",
    "kube_batch_tpu/utils/gc_guard.py",
)

# ABSOLUTE clocks only. Duration clocks (perf_counter/monotonic/
# process_time) measure elapsed time for stats and deadlines — both
# outside the bit-equal contract (placements are the verified
# quantity; a deadline trip is a fault the trace records as an event).
# Absolute time is what leaks into records, filenames, and carried
# state.
WALLCLOCK_NAMES = frozenset({
    "time", "time_ns", "now", "utcnow", "today",
})
WALLCLOCK_RECEIVERS = frozenset({"time", "datetime", "date"})

SET_CTORS = frozenset({"set", "frozenset"})
SET_BINOPS = (ast.BitOr, ast.BitAnd, ast.BitXor, ast.Sub)
ORDERING_CALLS = frozenset({"sorted", "min", "max", "sort"})


def _receiver_chain(node: ast.Call) -> Optional[List[str]]:
    if isinstance(node.func, ast.Attribute):
        return attr_chain(node.func.value)
    return None


def _is_wallclock(node: ast.Call) -> bool:
    name = call_name(node)
    if name not in WALLCLOCK_NAMES:
        return False
    recv = _receiver_chain(node)
    if recv is None:
        # Bare ``time()``/``now()`` could be a local helper — the
        # resolver stays quiet.
        return isinstance(node.func, ast.Name) and name == "time_ns"
    return recv[-1] in WALLCLOCK_RECEIVERS


_SEEDED_RNG_CTORS = frozenset({
    # Constructing a SEEDED generator through the module is the
    # sanctioned pattern; only draws from module-global state flag.
    "Random", "SystemRandom", "default_rng", "Generator", "RandomState",
})


def _is_module_rng(node: ast.Call) -> bool:
    if call_name(node) in _SEEDED_RNG_CTORS:
        return False
    recv = _receiver_chain(node)
    if not recv:
        return False
    if recv == ["random"]:
        return True
    if len(recv) >= 2 and recv[-2:] == ["np", "random"]:
        return True
    if len(recv) >= 2 and recv[-2:] == ["numpy", "random"]:
        return True
    return False


def _is_env_read(node: ast.AST) -> bool:
    if isinstance(node, ast.Call):
        name = call_name(node)
        if name == "getenv":
            recv = _receiver_chain(node)
            return recv == ["os"] or recv is None and isinstance(
                node.func, ast.Name
            )
        if name == "get":
            recv = _receiver_chain(node)
            return recv == ["os", "environ"]
        return False
    if isinstance(node, ast.Subscript) and isinstance(node.ctx, ast.Load):
        return attr_chain(node.value) == ["os", "environ"]
    return False


class _FunctionScanner:
    """Taint sites within one function body."""

    def __init__(self, fd, findings: List[Finding]):
        self.fd = fd
        self.findings = findings
        self.set_locals: Set[str] = set()
        self._collect_set_locals(fd.node)

    def _flag(self, node: ast.AST, what: str, fix: str) -> None:
        self.findings.append(Finding(
            PASS_ID, self.fd.rel, node.lineno,
            f"replay nondeterminism: {what} in {self.fd.qualname} on a "
            f"replay-reachable path — {fix}",
        ))

    # -- set-typed local inference -------------------------------------------

    def _is_set_expr(self, expr: ast.AST) -> bool:
        if isinstance(expr, ast.Set):
            return True
        if isinstance(expr, ast.SetComp):
            return True
        if isinstance(expr, ast.Call) and call_name(expr) in SET_CTORS:
            return True
        if isinstance(expr, ast.BinOp) and isinstance(
            expr.op, SET_BINOPS
        ):
            return self._is_set_expr(expr.left) or self._is_set_expr(
                expr.right
            )
        if isinstance(expr, ast.Name):
            return expr.id in self.set_locals
        if isinstance(expr, ast.Call) and call_name(expr) in (
            "union", "intersection", "difference", "symmetric_difference",
        ):
            recv = (
                expr.func.value
                if isinstance(expr.func, ast.Attribute) else None
            )
            return recv is not None and self._is_set_expr(recv)
        return False

    def _collect_set_locals(self, func_node: ast.AST) -> None:
        # Two passes so ``a = set(); b = a | other`` resolves.
        for _ in range(2):
            for node in ast.walk(func_node):
                if isinstance(node, ast.Assign) and len(node.targets) == 1:
                    target = node.targets[0]
                    if isinstance(target, ast.Name) and self._is_set_expr(
                        node.value
                    ):
                        self.set_locals.add(target.id)

    # -- scan ----------------------------------------------------------------

    def scan(self) -> None:
        # A comprehension handed straight to sorted() is the sanctioned
        # fix — its generator must not flag.
        sanctioned: Set[int] = set()
        for node in ast.walk(self.fd.node):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "sorted"
            ):
                for arg in node.args[:1]:
                    if isinstance(arg, (ast.GeneratorExp, ast.ListComp,
                                        ast.SetComp)):
                        sanctioned.add(id(arg))
        for node in ast.walk(self.fd.node):
            if isinstance(node, ast.Call):
                self._scan_call(node)
            elif isinstance(node, ast.Subscript) and _is_env_read(node):
                self._flag(
                    node, "os.environ read",
                    "read once at startup (or record it in the trace "
                    "header) so record and replay agree",
                )
            elif isinstance(node, (ast.For, ast.AsyncFor)):
                self._scan_iteration(node.iter)
            elif isinstance(node, (ast.ListComp, ast.SetComp,
                                   ast.DictComp, ast.GeneratorExp)):
                if id(node) in sanctioned:
                    continue
                for gen in node.generators:
                    self._scan_iteration(gen.iter)

    def _scan_call(self, node: ast.Call) -> None:
        name = call_name(node)
        if _is_wallclock(node):
            self._flag(
                node, f"wall-clock read {name}()",
                "replay cannot reproduce it; use the virtual clock / "
                "cycle counter, or keep it out of verdict-affecting "
                "state",
            )
            return
        if _is_module_rng(node):
            self._flag(
                node, f"module-level RNG call random.{name}()",
                "use a seeded Generator carried by the harness",
            )
            return
        if _is_env_read(node):
            self._flag(
                node, "os.environ read",
                "read once at startup (or record it in the trace "
                "header) so record and replay agree",
            )
            return
        if name in ORDERING_CALLS:
            self._scan_ordering(node)
        # set.pop() pops an arbitrary element.
        if name == "pop" and isinstance(node.func, ast.Attribute):
            if self._is_set_expr(node.func.value) and not node.args:
                self._flag(
                    node, "set.pop()",
                    "pop order is hash order — pop from a sorted list "
                    "instead",
                )

    def _scan_ordering(self, node: ast.Call) -> None:
        for kw in node.keywords:
            if kw.arg != "key":
                continue
            key = kw.value
            uses_id = False
            if isinstance(key, ast.Name) and key.id == "id":
                uses_id = True
            elif isinstance(key, ast.Lambda):
                for sub in ast.walk(key.body):
                    if (
                        isinstance(sub, ast.Call)
                        and isinstance(sub.func, ast.Name)
                        and sub.func.id == "id"
                    ):
                        uses_id = True
                        break
            if uses_id:
                self._flag(
                    node, f"id()-keyed ordering in {call_name(node)}()",
                    "id order is allocation order — key on a stable "
                    "field (uid, name) instead",
                )

    def _scan_iteration(self, iter_expr: ast.AST) -> None:
        if self._is_set_expr(iter_expr):
            self._flag(
                iter_expr, "iteration over an unordered set",
                "wrap in sorted(...) so record and replay walk the "
                "same order",
            )


def _reachable(project: Project) -> Set[str]:
    """Function keys forward-reachable from the root modules."""
    graph = get_callgraph(project)
    roots: List[str] = []
    in_repo = False
    for key, entry in graph.entries.items():
        rel = entry.fd.rel.replace("\\", "/")
        if rel.startswith("kube_batch_tpu/") or rel.startswith("tools/"):
            in_repo = True
        if rel.startswith(ROOT_PREFIXES):
            roots.append(key)
    if not in_repo:
        # Fixture/snippet project: every function is a root — the
        # fixture IS the replay path under test.
        roots = list(graph.entries)
    seen: Set[str] = set(roots)
    frontier = list(roots)
    while frontier:
        key = frontier.pop()
        entry = graph.entries[key]
        for site in entry.calls:
            for callee in graph.resolve(entry, site):
                if callee.fd.key not in seen:
                    seen.add(callee.fd.key)
                    frontier.append(callee.fd.key)
    return seen


@register_pass(PASS_ID)
def run(project: Project) -> List[Finding]:
    reachable = _reachable(project)
    findings: List[Finding] = []
    for pf in project.files:
        rel = pf.rel.replace("\\", "/")
        if rel.startswith(EXEMPT_PREFIXES):
            continue
        if rel.startswith("tools/") or rel == "bench.py":
            continue  # drivers run outside the record/replay boundary
        for fd in iter_functions(pf):
            if fd.key not in reachable:
                continue
            _FunctionScanner(fd, findings).scan()
    findings.sort(key=lambda f: (f.file, f.line, f.message))
    return findings
