"""Metrics census drift guard.

doc/design/metrics.md carries a hand-maintained census of every metric
the scheduler exposes. It has been edited across several PRs and WILL
rot the first time someone registers a metric without a row (or prunes
one without deleting its row). This test parses the census tables and
asserts exact two-way agreement with ``metrics.REGISTRY`` — loudly
naming the drifted metric either way. Runs in ``make ci`` via
``make test``.
"""

import os
import re

from kube_batch_tpu import metrics

DOC_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)),
    os.pardir, os.pardir, "doc", "design", "metrics.md",
)

# A census row: "| `metric_name` | type | labels | meaning |".
_ROW_RE = re.compile(r"^\|\s*`([a-z0-9_]+)`\s*\|")


def census_names():
    names = []
    with open(DOC_PATH) as f:
        for line in f:
            m = _ROW_RE.match(line.strip())
            if m:
                names.append(m.group(1))
    return names


def test_census_parses_nontrivially():
    names = census_names()
    # Sanity: the parser found the tables (guards against a doc
    # reformat silently matching nothing and vacuously passing).
    assert len(names) >= 20, names
    assert "e2e_scheduling_latency_seconds" in names


def test_census_has_no_duplicates():
    names = census_names()
    dupes = {n for n in names if names.count(n) > 1}
    assert not dupes, f"duplicate census rows: {sorted(dupes)}"


def test_registry_matches_census_exactly():
    doc = set(census_names())
    registry = set(metrics.REGISTRY.names())
    missing_rows = registry - doc
    stale_rows = doc - registry
    assert not missing_rows, (
        "metrics registered without a census row in "
        f"doc/design/metrics.md: {sorted(missing_rows)}"
    )
    assert not stale_rows, (
        "census rows in doc/design/metrics.md with no registered "
        f"metric: {sorted(stale_rows)}"
    )
