"""Solver fault containment (doc/design/robustness.md): degradation
ladder, deadline-bounded fetch with late-result discard, circuit
breaker, loop watchdog, leadership fencing, and the resync terminal
cap. An accelerator failure must degrade scheduling QUALITY, never
scheduler LIVENESS."""

import threading
import time
from concurrent.futures import Future, ThreadPoolExecutor

import numpy as np
import pytest

from kube_batch_tpu.actions import allocate_tpu as atpu
from kube_batch_tpu.actions.allocate_tpu import AsyncSolveHandle
from kube_batch_tpu.api import PodPhase, build_resource_list
from kube_batch_tpu.cache.cache import CacheFencedError
from kube_batch_tpu.metrics import metrics as m
from kube_batch_tpu.obs import RECORDER
from kube_batch_tpu.obs import explain
from kube_batch_tpu.scheduler import LoopWatchdog, Scheduler
from kube_batch_tpu.solver import containment
from kube_batch_tpu.solver.containment import (
    CircuitBreaker,
    SolveFailed,
    SolveTimeout,
    call_with_deadline,
)
from kube_batch_tpu.utils.test_utils import (
    build_node,
    build_pod,
    build_pod_group,
    build_queue,
)

from tests.actions.test_actions import make_cache, req, run_action


@pytest.fixture(autouse=True)
def _fresh_containment():
    """Breaker/hook/budget are process-global; every test starts (and
    leaves) them pristine."""
    containment.reset_breaker()
    containment.set_device_fault_hook(None)
    containment.configure(None)
    explain.clear()
    yield
    containment.reset_breaker()
    containment.set_device_fault_hook(None)
    containment.configure(None)
    explain.clear()


# ---------------------------------------------------------------- deadline


class TestCallWithDeadline:
    def test_returns_result(self):
        assert call_with_deadline(lambda: 41 + 1, 1.0) == 42

    def test_propagates_exception(self):
        with pytest.raises(ValueError):
            call_with_deadline(
                lambda: (_ for _ in ()).throw(ValueError("x")), 1.0
            )

    def test_timeout_abandons_and_discards_late_result(self):
        done = threading.Event()

        def slow():
            time.sleep(0.3)
            done.set()
            return "late"

        t0 = time.perf_counter()
        with pytest.raises(SolveTimeout):
            call_with_deadline(slow, 0.05, label="t")
        # Raised at the budget, well before the call finished.
        assert time.perf_counter() - t0 < 0.25
        assert not done.is_set()
        # The abandoned thread completes later; its result went nowhere.
        assert done.wait(2.0)


# -------------------------------------------------------- fetch memoization


class _SlowResult:
    """jax-path stand-in whose device→host sync hangs."""

    rounds = 1
    refills = None
    stages = None

    def __init__(self, delay, value):
        self.delay = delay
        self.value = value
        self.materialized = threading.Event()

    @property
    def assigned(self):
        time.sleep(self.delay)
        self.materialized.set()
        return self.value


class TestFetchMemoization:
    def test_failed_fetch_memoized_as_typed_error(self):
        h = AsyncSolveHandle("native")
        fut = Future()
        fut.set_exception(ValueError("device exploded"))
        h._future = fut
        with pytest.raises(SolveFailed) as e1:
            h.fetch()
        assert isinstance(e1.value.__cause__, ValueError)
        assert h.failed() and h._future is None  # detached
        # Second fetch re-raises the MEMOIZED failure, same type — never
        # a consumed-future error.
        with pytest.raises(SolveFailed) as e2:
            h.fetch()
        assert "already failed" in str(e2.value)

    def test_timeout_abandons_jax_handle_and_discards_late_result(self):
        h = AsyncSolveHandle("jax-test")
        slow = _SlowResult(0.3, np.asarray([0, 1]))
        h._result = slow
        with pytest.raises(SolveTimeout):
            h.fetch(timeout=0.05)
        assert h.failed() and h._result is None  # detached
        # The hung sync eventually completes on its abandoned thread…
        assert slow.materialized.wait(2.0)
        # …but the handle keeps raising: the late result is discarded.
        with pytest.raises(SolveFailed):
            h.fetch()
        assert h.done()

    def test_native_timeout_abandons_worker(self):
        pool = ThreadPoolExecutor(1)
        h = AsyncSolveHandle("native")
        h._future = pool.submit(
            lambda: (time.sleep(0.3), None) and None
        )
        with pytest.raises(SolveTimeout):
            h.fetch(timeout=0.05)
        with pytest.raises(SolveFailed):
            h.fetch(timeout=5.0)
        pool.shutdown(wait=True)

    def test_keyboard_interrupt_not_swallowed(self):
        """Ctrl-C at the block point must terminate, not be absorbed by
        the ladder as a 'device failure'."""
        h = AsyncSolveHandle("native")
        fut = Future()
        fut.set_exception(KeyboardInterrupt())
        h._future = fut
        with pytest.raises(KeyboardInterrupt):
            h.fetch()

    def test_fault_hook_failure_is_typed(self):
        h = AsyncSolveHandle("jax-test")
        h._result = _SlowResult(0.0, np.asarray([0]))
        h._fault_hook = lambda stage: (_ for _ in ()).throw(
            RuntimeError("injected")
        )
        with pytest.raises(SolveFailed) as e:
            h.fetch(timeout=1.0)
        assert isinstance(e.value.__cause__, RuntimeError)


# ------------------------------------------------------------------ ladder


def _build_pending_cluster(groups=4, pods=6, nodes=8):
    c = make_cache()
    c.add_queue(build_queue("default"))
    for j in range(nodes):
        c.add_node(build_node(
            f"n{j}", build_resource_list(cpu="4", memory="8Gi")
        ))
    for g in range(groups):
        c.add_pod_group(build_pod_group(
            f"pg{g}", namespace="ns", min_member=1
        ))
        for i in range(pods):
            c.add_pod(build_pod(
                "ns", f"pg{g}-p{i}", "", PodPhase.PENDING, req(),
                group_name=f"pg{g}",
            ))
    return c


class TestDegradationLadder:
    def test_mid_cycle_exception_degrades_not_fails(self, monkeypatch):
        """The acceptance path: a solver exception mid-cycle produces a
        COMPLETED cycle with tasks placed via a lower rung, the rung
        sequence visible in stats + flight record + metrics."""
        monkeypatch.setenv("KBT_SOLVER", "jax")
        monkeypatch.delenv("KBT_SOLVER_TOPK", raising=False)
        calls = []

        def hook(stage):
            if stage == "solve" and not calls:
                calls.append(stage)
                raise RuntimeError("injected device fault")

        containment.set_device_fault_hook(hook)
        before = m.solver_fallback.get(("dense", "native", "exception"))
        RECORDER.begin_cycle()
        c = _build_pending_cluster()
        run_action(c, "allocate_tpu")
        assert c.wait_for_side_effects()
        rec = RECORDER.end_cycle()
        # Cycle completed and placed every task, on the floor rung.
        assert len(c.binder.binds) == 24
        ladder = atpu.last_stats["solve_ladder"]
        assert [(e["rung"], e["outcome"]) for e in ladder] == [
            ("dense", "exception"), ("native", "ok"),
        ]
        assert ladder[0]["exc"] == "RuntimeError"
        assert atpu.last_stats["solve_degraded"] is True
        assert atpu.last_stats["backend"] == "native"
        # Flight record carries the same sequence.
        assert rec["solver"]["ladder"] == ladder
        assert rec["solver"]["degraded"] is True
        # Metric with {from,to,reason} labels.
        assert m.solver_fallback.get(
            ("dense", "native", "exception")
        ) == before + 1
        assert containment.last_fallback["reason"] == "exception"
        c.shutdown()

    def test_sparse_rung_falls_to_dense_first(self, monkeypatch):
        monkeypatch.setenv("KBT_SOLVER", "jax")
        monkeypatch.setenv("KBT_SOLVER_TOPK", "4")
        calls = []

        def hook(stage):
            if stage == "solve" and len(calls) < 1:
                calls.append(stage)
                raise RuntimeError("injected")

        containment.set_device_fault_hook(hook)
        c = _build_pending_cluster()
        run_action(c, "allocate_tpu")
        assert c.wait_for_side_effects()
        ladder = atpu.last_stats["solve_ladder"]
        assert [(e["rung"], e["outcome"]) for e in ladder] == [
            ("sparse", "exception"), ("dense", "ok"),
        ]
        assert len(c.binder.binds) == 24
        c.shutdown()

    def test_timeout_jumps_to_native_and_opens_breaker(self, monkeypatch):
        monkeypatch.setenv("KBT_SOLVER", "jax")
        monkeypatch.delenv("KBT_SOLVER_TOPK", raising=False)
        containment.configure(solve_budget=0.15)

        def hook(stage):
            if stage == "solve":
                time.sleep(0.6)  # outsleep the budget

        containment.set_device_fault_hook(hook)
        c = _build_pending_cluster()
        run_action(c, "allocate_tpu")
        assert c.wait_for_side_effects()
        ladder = atpu.last_stats["solve_ladder"]
        assert [(e["rung"], e["outcome"]) for e in ladder] == [
            ("dense", "timeout"), ("native", "ok"),
        ]
        assert len(c.binder.binds) == 24
        # An abandoned solve quarantines the device path immediately.
        assert containment.BREAKER.state == "open"

        # Next cycle (fresh pending work): breaker pins straight to
        # native — no device dispatch, no per-cycle failure latency.
        containment.set_device_fault_hook(None)
        c.add_pod_group(build_pod_group("pgx", namespace="ns",
                                        min_member=1))
        c.add_pod(build_pod("ns", "pgx-p0", "", PodPhase.PENDING, req(),
                            group_name="pgx"))
        run_action(c, "allocate_tpu")
        assert atpu.last_stats.get("breaker_pinned") is True
        assert atpu.last_stats["backend"] == "native"
        assert atpu.last_stats["solve_ladder"] == [
            {"rung": "native", "outcome": "ok"}
        ]
        c.shutdown()

    def test_rescued_cycle_keeps_failure_streak(self, monkeypatch):
        """A sparse failure rescued by the dense rung is still a
        device-path failure: if the rescue reset the streak, a
        persistently broken sparse program would burn a failed dispatch
        every cycle forever without ever opening the breaker."""
        monkeypatch.setenv("KBT_SOLVER", "jax")
        monkeypatch.setenv("KBT_SOLVER_TOPK", "4")
        containment.reset_breaker(failure_threshold=2, cooldown_cycles=8)
        state = {}

        def hook(stage):
            if stage == "solve" and state.pop("armed", False):
                raise RuntimeError("sparse-only fault")

        containment.set_device_fault_hook(hook)
        c = _build_pending_cluster()
        state["armed"] = True
        run_action(c, "allocate_tpu")
        assert [
            (e["rung"], e["outcome"])
            for e in atpu.last_stats["solve_ladder"]
        ] == [("sparse", "exception"), ("dense", "ok")]
        assert containment.BREAKER.failure_streak == 1

        c.add_pod_group(build_pod_group("pgx", namespace="ns",
                                        min_member=1))
        c.add_pod(build_pod("ns", "pgx-p0", "", PodPhase.PENDING, req(),
                            group_name="pgx"))
        state["armed"] = True
        run_action(c, "allocate_tpu")
        assert containment.BREAKER.state == "open"
        c.shutdown()

    def test_synchronous_dispatch_exception_contained(self, monkeypatch):
        """A launch that raises SYNCHRONOUSLY (trace/compile error,
        device lost at dispatch — before any fetch) must descend the
        ladder like an async failure, not escape the cycle."""
        monkeypatch.setenv("KBT_SOLVER", "jax")
        monkeypatch.delenv("KBT_SOLVER_TOPK", raising=False)
        orig = atpu.AllocateTpuAction._launch_rung

        def boom(self, rung, inputs, ctx):
            if rung != "native":
                raise RuntimeError("device lost at dispatch")
            return orig(self, rung, inputs, ctx)

        monkeypatch.setattr(atpu.AllocateTpuAction, "_launch_rung", boom)
        c = _build_pending_cluster()
        run_action(c, "allocate_tpu")
        assert c.wait_for_side_effects()
        assert len(c.binder.binds) == 24
        ladder = atpu.last_stats["solve_ladder"]
        assert ladder[-1] == {"rung": "native", "outcome": "ok"}
        assert any(
            e["rung"] == "dense" and e["outcome"] == "exception"
            for e in ladder
        )
        assert atpu.last_stats["backend"] == "native"
        assert containment.BREAKER.failure_streak >= 1
        c.shutdown()

    def test_device_tensorize_exception_contained(self, monkeypatch):
        """A device pack that raises (dead backend during the
        host→device upload) re-tensorizes host-side and solves on the
        native floor, quarantining via the breaker."""
        monkeypatch.setenv("KBT_SOLVER", "jax")
        monkeypatch.delenv("KBT_SOLVER_TOPK", raising=False)
        orig = atpu.tensorize

        def boom(ssn, device=True, **kw):
            if device:
                raise RuntimeError("backend dead during upload")
            return orig(ssn, device=device, **kw)

        monkeypatch.setattr(atpu, "tensorize", boom)
        before = m.solver_fallback.get(("device", "native", "tensorize"))
        c = _build_pending_cluster()
        run_action(c, "allocate_tpu")
        assert c.wait_for_side_effects()
        assert len(c.binder.binds) == 24
        assert atpu.last_stats["backend"] == "native"
        assert atpu.last_stats["solve_ladder"] == [
            {"rung": "native", "outcome": "ok"}
        ]
        assert m.solver_fallback.get(
            ("device", "native", "tensorize")
        ) == before + 1
        assert containment.BREAKER.failure_streak >= 1
        assert containment.last_fallback["reason"] == "tensorize"
        c.shutdown()


# ----------------------------------------------------------------- breaker


class TestCircuitBreaker:
    def test_opens_at_threshold_and_recloses_via_probe(self):
        probe_ok = [False]
        b = CircuitBreaker(
            failure_threshold=3, cooldown_cycles=2,
            probe=lambda t: probe_ok[0],
        )
        b.record_device_failure("exception", exc="E")
        b.record_device_failure("exception", exc="E")
        assert b.state == "closed" and b.allow_device()
        b.record_device_failure("exception", exc="E")
        assert b.state == "open"
        # Cooldown ticks per cycle: one pinned cycle, then half-open +
        # probe; a failing probe re-opens with a fresh cooldown.
        assert b.allow_device() is False
        assert b.allow_device() is False  # probe ran and failed
        assert b.state == "open" and b.probes_failed == 1
        # Fault clears: cooldown again, then the probe re-promotes.
        probe_ok[0] = True
        assert b.allow_device() is False
        assert b.allow_device() is True
        assert b.state == "closed" and b.reclosures == 1
        assert b.allow_device() is True

    def test_success_resets_streak(self):
        b = CircuitBreaker(failure_threshold=3)
        b.record_device_failure("exception")
        b.record_device_failure("exception")
        b.record_device_success()
        b.record_device_failure("exception")
        assert b.state == "closed"

    def test_timeout_opens_immediately(self):
        b = CircuitBreaker(failure_threshold=3)
        b.record_device_failure("timeout", open_now=True)
        assert b.state == "open" and b.trips == 1

    def test_pin_open_blocks_until_unpinned(self):
        b = CircuitBreaker(cooldown_cycles=1, probe=lambda t: True)
        b.pin_open("bench-degraded")
        for _ in range(5):
            assert b.allow_device() is False
        assert b.state_dict()["pinned"] == "bench-degraded"
        b.unpin()
        assert b.allow_device() is True

    def test_state_dict_shape(self):
        b = CircuitBreaker()
        b.record_device_failure("exception", exc="XlaRuntimeError",
                                open_now=True)
        d = b.state_dict()
        assert d["state"] == "open"
        assert d["last_failure"]["exc"] == "XlaRuntimeError"
        assert d["quarantine_age_seconds"] is not None
        assert d["cooldown_cycles_left"] > 0


# ---------------------------------------------------------------- watchdog


class TestLoopWatchdog:
    def test_trips_once_per_wedged_cycle(self):
        trips = []
        before = m.scheduler_watchdog_trips.get()
        wd = LoopWatchdog(budget=0.1, on_trip=trips.append)
        now = time.monotonic()
        wd.cycle_begin(0)
        assert wd.check(now=now) is False  # within budget
        assert wd.check(now=now + 1.0) is True
        assert wd.check(now=now + 2.0) is False  # once per cycle
        assert len(trips) == 1 and "cycle 0" in trips[0]
        assert wd.last_trip["cycle"] == 0
        # A NEW wedged cycle trips again.
        wd.cycle_end()
        wd.cycle_begin(1)
        assert wd.check(now=now + 9.0) is True
        assert m.scheduler_watchdog_trips.get() == before + 2

    def test_no_trip_when_idle_or_healthy(self):
        wd = LoopWatchdog(budget=0.05, on_trip=None)
        assert wd.check() is False  # nothing in flight
        wd.cycle_begin(0)
        wd.cycle_end()
        assert wd.check(now=time.monotonic() + 9.0) is False

    def test_trip_fences_cache_and_hooks_via_scheduler(self):
        from kube_batch_tpu.cache import SchedulerCache
        from kube_batch_tpu.utils.test_utils import (
            FakeBinder,
            FakeEvictor,
            FakeStatusUpdater,
            FakeVolumeBinder,
        )

        cache = SchedulerCache(
            binder=FakeBinder(), evictor=FakeEvictor(),
            status_updater=FakeStatusUpdater(),
            volume_binder=FakeVolumeBinder(),
        )
        s = Scheduler(cache, schedule_period=0.01)
        fenced = []
        s.fence_hooks.append(fenced.append)
        wd = LoopWatchdog(budget=0.01, on_trip=s._on_watchdog_trip)
        wd.cycle_begin(7)
        assert wd.check(now=time.monotonic() + 1.0) is True
        assert fenced and "cycle 7" in fenced[0]
        assert cache.fence_reason() is not None
        with pytest.raises(CacheFencedError):
            cache.bind(type("T", (), {"uid": "t1"})(), "n1")
        cache.shutdown()

    def test_trip_stops_standalone_run_loop(self):
        """Without leader election there is no lost-leadership event to
        end the loop: a trip must stop the run loop itself, or a fenced
        standalone scheduler spins CacheFencedError cycles forever
        while reporting healthy."""
        from kube_batch_tpu.cache import SchedulerCache
        from kube_batch_tpu.utils.test_utils import (
            FakeBinder,
            FakeEvictor,
            FakeStatusUpdater,
            FakeVolumeBinder,
        )

        cache = SchedulerCache(
            binder=FakeBinder(), evictor=FakeEvictor(),
            status_updater=FakeStatusUpdater(),
            volume_binder=FakeVolumeBinder(),
        )
        s = Scheduler(cache, schedule_period=0.01)
        stop = threading.Event()
        s._run_stop = stop  # what run() stamps before starting the dog
        s._on_watchdog_trip("watchdog: cycle 3 exceeded budget")
        assert stop.is_set()
        assert cache.fence_reason() is not None
        cache.shutdown()


# ----------------------------------------------------------------- fencing


class TestCacheFencing:
    def _bound_cluster(self):
        c = make_cache()
        c.add_queue(build_queue("default"))
        c.add_node(build_node(
            "n1", build_resource_list(cpu="8", memory="8Gi")
        ))
        c.add_pod_group(build_pod_group("pg", namespace="ns",
                                        min_member=1))
        c.add_pod(build_pod("ns", "p1", "", PodPhase.PENDING, req(),
                            group_name="pg"))
        return c

    def test_fenced_bind_refused(self):
        c = self._bound_cluster()
        task = next(iter(next(iter(c.jobs.values())).tasks.values()))
        before = m.cache_binds_fenced.get()
        c.fence("lease lost")
        with pytest.raises(CacheFencedError):
            c.bind(task, "n1")
        assert c.bind_batch([task]) == []
        assert m.cache_binds_fenced.get() == before + 2
        assert not c.binder.binds
        c.shutdown()

    def test_fenced_side_effect_thread_refuses_late_bind(self):
        """The zombie-leader case: a bind side effect QUEUED before the
        fence must not reach the cluster after it."""
        c = self._bound_cluster()
        job = next(iter(c.jobs.values()))
        task = next(iter(job.tasks.values()))
        snapshot = task.clone()
        c.fence("watchdog: cycle 3 hung")
        before = m.cache_binds_fenced.get()
        # Call the side-effect half directly — this is exactly what a
        # worker thread of the deposed leader would execute.
        c._bind_side_effect(task.pod, "n1", snapshot)
        assert not c.binder.binds
        assert m.cache_binds_fenced.get() == before + 1
        # The task is NOT resynced either: it belongs to the successor.
        assert c.err_tasks.empty()
        c.shutdown()

    def test_fenced_evict_refused(self):
        c = self._bound_cluster()
        task = next(iter(next(iter(c.jobs.values())).tasks.values()))
        c.fence("deposed")
        with pytest.raises(CacheFencedError):
            c.evict(task, "preempted")
        assert not c.evictor.evicts
        c.shutdown()

    def test_unfence_restores(self):
        c = self._bound_cluster()
        c.fence("x")
        c.unfence()
        assert c.fence_reason() is None
        task = next(iter(next(iter(c.jobs.values())).tasks.values()))
        c.bind(task, "n1")
        assert c.wait_for_side_effects()
        assert len(c.binder.binds) == 1
        c.shutdown()


class TestElectorFencing:
    def test_fence_releases_lease_and_signals_loss(self, tmp_path):
        from kube_batch_tpu.cli.server import LeaderElector

        el = LeaderElector(str(tmp_path), identity="wedged-1")
        assert el.try_acquire() is True
        import os

        assert os.path.exists(el.lock_path)
        lost = threading.Event()
        el._lost = lost
        el.fence("watchdog: cycle 12 exceeded budget")
        assert not os.path.exists(el.lock_path)
        assert lost.is_set()
        assert el.is_leader is False
        assert el.fenced_reason.startswith("watchdog")
        # A healthy successor takes the lease IMMEDIATELY — no waiting
        # out the lease duration behind a zombie's renewals.
        el2 = LeaderElector(str(tmp_path), identity="healthy-2")
        assert el2.try_acquire() is True
        # And the fenced identity cannot re-acquire.
        assert el.try_acquire() is False


# ------------------------------------------------------------ /debug/vars


class TestDebugVarsRobustness:
    def test_one_curl_degraded_visibility(self):
        import json
        import urllib.request

        from kube_batch_tpu.cli import start_metrics_server

        containment.BREAKER.record_device_failure(
            "timeout", exc="SolveTimeout", open_now=True
        )
        containment.note_fallback("dense", "native", "timeout",
                                  exc="SolveTimeout")
        server, _thread = start_metrics_server("127.0.0.1:0")
        try:
            port = server.server_address[1]
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/debug/vars", timeout=5
            ) as resp:
                doc = json.loads(resp.read().decode())
        finally:
            server.shutdown()
        rb = doc["robustness"]
        assert rb["breaker"]["state"] == "open"
        assert rb["breaker"]["quarantine_age_seconds"] is not None
        assert rb["last_fallback"]["reason"] == "timeout"
        assert rb["solve_budget_seconds"] > 0
        assert "watchdog_trips" in rb
        assert "cache_fence" in rb


# ---------------------------------------------------------- resync terminal


class TestResyncTerminalCap:
    def test_poisoned_task_dropped_and_named(self):
        c = make_cache()
        c.add_queue(build_queue("default"))
        c.add_node(build_node(
            "n1", build_resource_list(cpu="8", memory="8Gi")
        ))
        c.add_pod_group(build_pod_group("pg", namespace="ns",
                                        min_member=1))
        c.add_pod(build_pod("ns", "p1", "", PodPhase.PENDING, req(),
                            group_name="pg"))
        c._max_resync_attempts = 4

        def always_fails(task):
            raise RuntimeError("permanently poisoned")

        c._sync_task = always_fails
        job = next(iter(c.jobs.values()))
        task = next(iter(job.tasks.values()))
        before = m.task_resync_terminal.get()
        c._resync_task(task.clone())
        # Drain until quiescent: each pass re-queues with attempt+1
        # until the cap drops the task terminally.
        for _ in range(c._max_resync_attempts + 2):
            c.drain_resync_queue()
            if c.err_tasks.empty():
                break
        assert c.err_tasks.empty()
        assert m.task_resync_terminal.get() == before + 1
        verdict = explain.get_verdict(task.job)
        assert verdict is not None
        assert verdict.reason == "resync-terminal"
        # The standalone verdict counts the drops, so the reason gauge
        # (summing verdict.unassigned) can actually go nonzero.
        assert verdict.unassigned == 1
        assert "ns/p1" in verdict.detail["resync_terminal"]
        assert (
            verdict.detail["resync_terminal"]["ns/p1"]["attempts"]
            >= c._max_resync_attempts
        )
        c.shutdown()

    def test_terminal_gauge_survives_busy_cycles(self, monkeypatch):
        """The sticky standalone resync-terminal verdict must keep the
        reason gauge nonzero on BUSY cycles too — its task is never in
        ctx.tasks, so without the explicit fold the absent-reason
        zeroing erases the bucket whenever other jobs keep the solver
        busy."""
        monkeypatch.delenv("KBT_SOLVER", raising=False)
        c = make_cache()
        c.add_queue(build_queue("default"))
        c.add_node(build_node(
            "n1", build_resource_list(cpu="8", memory="8Gi")
        ))
        # The poisoned job: a best-effort pod (empty resreq) stays
        # PENDING in the cache but is excluded from tensorize, exactly
        # the shape a terminally-dropped task leaves behind.
        c.add_pod_group(build_pod_group("pgdead", namespace="ns",
                                        min_member=1))
        c.add_pod(build_pod("ns", "pdead", "", PodPhase.PENDING,
                            build_resource_list(), group_name="pgdead"))
        dead_job = next(
            j for j in c.jobs.values() if j.name == "pgdead"
        )
        explain.note_resync_terminal(
            dead_job.uid, "ns", "pgdead", "ns/pdead", attempts=8
        )
        # Busy-cycle work: a schedulable pod from another job.
        c.add_pod_group(build_pod_group("pgbusy", namespace="ns",
                                        min_member=1))
        c.add_pod(build_pod("ns", "pbusy", "", PodPhase.PENDING, req(),
                            group_name="pgbusy"))
        run_action(c, "allocate_tpu")
        assert c.wait_for_side_effects()
        assert len(c.binder.binds) == 1  # the cycle was busy, not idle
        assert m.unschedulable_tasks.get(("resync-terminal",)) == 1.0
        c.shutdown()

    def test_recovering_task_not_dropped(self):
        c = make_cache()
        c.add_queue(build_queue("default"))
        c.add_node(build_node(
            "n1", build_resource_list(cpu="8", memory="8Gi")
        ))
        c.add_pod_group(build_pod_group("pg", namespace="ns",
                                        min_member=1))
        c.add_pod(build_pod("ns", "p1", "", PodPhase.PENDING, req(),
                            group_name="pg"))
        attempts = []

        def flaky(task):
            attempts.append(task.uid)
            if len(attempts) < 3:
                raise RuntimeError("transient")

        c._sync_task = flaky
        task = next(iter(next(iter(c.jobs.values())).tasks.values()))
        before = m.task_resync_terminal.get()
        c._resync_task(task.clone())
        for _ in range(6):
            if c.drain_resync_queue():
                break
        assert len(attempts) == 3  # third reconcile succeeded
        assert m.task_resync_terminal.get() == before
        c.shutdown()
