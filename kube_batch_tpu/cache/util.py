"""Shadow PodGroups for plain pods scheduled without a group.

Mirrors reference pkg/scheduler/cache/util.go (:28 shadowPodGroup,
:40 createShadowPodGroup: minMember=1, job key = controller UID if owned,
else pod UID).
"""

from __future__ import annotations

from typing import Optional

from ..api import (
    ObjectMeta,
    Pod,
    PodGroup,
    PodGroupSpec,
    get_controller_uid,
)

SHADOW_POD_GROUP_ANNOTATION = "kube-batch/shadow-pod-group"


def shadow_pod_group(pg: Optional[PodGroup]) -> bool:
    """reference util.go:28-36"""
    if pg is None:
        return True
    return SHADOW_POD_GROUP_ANNOTATION in pg.metadata.annotations


def create_shadow_pod_group(pod: Pod) -> PodGroup:
    """reference util.go:40-56"""
    job_id = get_controller_uid(pod) or pod.uid
    return PodGroup(
        metadata=ObjectMeta(
            name=job_id,
            namespace=pod.namespace,
            annotations={SHADOW_POD_GROUP_ANNOTATION: "true"},
            creation_timestamp=pod.metadata.creation_timestamp,
        ),
        spec=PodGroupSpec(min_member=1),
    )


def job_terminated(job) -> bool:
    """A job is terminated when its scheduling spec is gone — pod group
    absent (or shadow) and no legacy PDB attached — and no tasks remain
    (reference api/helpers.go:101-106, cache.go:556-585)."""
    return (
        shadow_pod_group(job.pod_group)
        and getattr(job, "pdb", None) is None
        and len(job.tasks) == 0
    )
