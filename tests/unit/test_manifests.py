"""k8s-manifest loader (cli/manifests.py): a kube-batch user's CRD YAML
(PodGroup/Queue in scheduling.incubator.k8s.io v1alpha1 or v1alpha2, core
v1 Pod/Node) must load and schedule end-to-end."""

import threading
import time

import pytest
import yaml

from kube_batch_tpu.api import GROUP_NAME_ANNOTATION_KEY, PodPhase
from kube_batch_tpu.cache import SchedulerCache
from kube_batch_tpu.cli.manifests import apply_manifests, parse_manifest
from kube_batch_tpu.cluster import InProcessCluster
from kube_batch_tpu.scheduler import Scheduler

MANIFESTS = f"""
apiVersion: scheduling.incubator.k8s.io/v1alpha1
kind: Queue
metadata:
  name: default
spec:
  weight: 4
---
apiVersion: scheduling.incubator.k8s.io/v1alpha2
kind: PodGroup
metadata:
  name: qj-1
  namespace: default
spec:
  minMember: 2
  queue: default
---
apiVersion: v1
kind: Node
metadata:
  name: node-a
  labels: {{zone: a}}
status:
  allocatable: {{cpu: "4", memory: 8Gi, pods: "20"}}
  capacity: {{cpu: "4", memory: 8Gi, pods: "20"}}
---
apiVersion: v1
kind: Pod
metadata:
  name: qj-1-0
  namespace: default
  annotations:
    {GROUP_NAME_ANNOTATION_KEY}: qj-1
spec:
  containers:
  - name: main
    resources:
      requests: {{cpu: 500m, memory: 256Mi}}
---
apiVersion: v1
kind: Pod
metadata:
  name: qj-1-1
  namespace: default
  annotations:
    {GROUP_NAME_ANNOTATION_KEY}: qj-1
spec:
  tolerations:
  - key: dedicated
    operator: Equal
    value: ml
    effect: NoSchedule
  containers:
  - name: main
    resources:
      requests: {{cpu: 500m, memory: 256Mi}}
"""


def test_both_crd_versions_parse():
    docs = list(yaml.safe_load_all(MANIFESTS))
    kinds = [parse_manifest(d)[0] for d in docs]
    assert kinds == ["Queue", "PodGroup", "Node", "Pod", "Pod"]
    _, queue = parse_manifest(docs[0])
    assert queue.spec.weight == 4
    _, pg = parse_manifest(docs[1])
    assert pg.spec.min_member == 2
    _, pod = parse_manifest(docs[4])
    assert pod.spec.tolerations[0].value == "ml"
    assert pod.metadata.annotations[GROUP_NAME_ANNOTATION_KEY] == "qj-1"


def test_unsupported_version_rejected():
    with pytest.raises(ValueError, match="unsupported"):
        parse_manifest({
            "apiVersion": "scheduling.incubator.k8s.io/v1beta1",
            "kind": "PodGroup",
        })


def test_manifests_schedule_end_to_end():
    cluster = InProcessCluster(simulate_kubelet=True)
    n = apply_manifests(cluster, yaml.safe_load_all(MANIFESTS))
    assert n == 5
    cache = SchedulerCache(cluster=cluster)
    sched = Scheduler(cache, schedule_period=0.05)
    stop = threading.Event()
    t = threading.Thread(target=sched.run, args=(stop,), daemon=True)
    t.start()
    deadline = time.time() + 20
    done = False
    while time.time() < deadline:
        pods = cluster.list_objects("Pod")
        if all(p.status.phase == PodPhase.RUNNING for p in pods):
            done = True
            break
        time.sleep(0.05)
    stop.set()
    t.join(timeout=5)
    assert done, [
        (p.metadata.name, p.status.phase, p.spec.node_name)
        for p in cluster.list_objects("Pod")
    ]
    for p in cluster.list_objects("Pod"):
        assert p.spec.node_name == "node-a"


AFFINITY_POD = """
apiVersion: v1
kind: Pod
metadata:
  name: aff-pod
  namespace: default
spec:
  affinity:
    nodeAffinity:
      requiredDuringSchedulingIgnoredDuringExecution:
        nodeSelectorTerms:
        - matchExpressions:
          - {key: zone, operator: In, values: [a]}
        - matchExpressions:
          - {key: zone, operator: In, values: [b]}
  containers:
  - name: main
    resources:
      requests: {cpu: 100m}
"""


def test_node_affinity_terms_or_semantics():
    """k8s ORs across nodeSelectorTerms: a pod asking zone-a OR zone-b must
    match a zone-b node (advisor finding: flattening made this an
    unsatisfiable conjunction)."""
    from kube_batch_tpu.plugins.util import match_node_selector_terms

    _, pod = parse_manifest(yaml.safe_load(AFFINITY_POD))
    terms = pod.spec.affinity.node_required
    assert len(terms) == 2 and isinstance(terms[0], list)
    assert match_node_selector_terms(terms, {"zone": "a"})
    assert match_node_selector_terms(terms, {"zone": "b"})
    assert not match_node_selector_terms(terms, {"zone": "c"})
    # flat shorthand still accepted as a single conjunction term
    flat = [{"key": "zone", "operator": "In", "values": ["a"]}]
    assert match_node_selector_terms(flat, {"zone": "a"})
    assert not match_node_selector_terms(flat, {"zone": "b"})


def test_node_affinity_match_fields_rejected():
    doc = yaml.safe_load(AFFINITY_POD)
    terms = doc["spec"]["affinity"]["nodeAffinity"][
        "requiredDuringSchedulingIgnoredDuringExecution"
    ]["nodeSelectorTerms"]
    terms[0]["matchFields"] = [
        {"key": "metadata.name", "operator": "In", "values": ["n1"]}
    ]
    with pytest.raises(ValueError, match="matchFields"):
        parse_manifest(doc)


POD_AFFINITY_POD = """
apiVersion: v1
kind: Pod
metadata:
  name: anti-pod
  namespace: default
spec:
  affinity:
    podAntiAffinity:
      requiredDuringSchedulingIgnoredDuringExecution:
      - topologyKey: kubernetes.io/hostname
        labelSelector:
          matchLabels: {app: web}
          matchExpressions:
          - {key: tier, operator: In, values: [frontend]}
  containers:
  - name: main
    resources:
      requests: {cpu: 100m}
"""


def test_pod_affinity_match_expressions_parsed():
    """Advisor finding: matchExpressions were silently dropped, letting
    must-spread pods co-locate. They are now parsed and evaluated."""
    from kube_batch_tpu.plugins.util import match_affinity_term

    _, pod = parse_manifest(yaml.safe_load(POD_AFFINITY_POD))
    term = pod.spec.affinity.pod_anti_affinity[0]
    assert term["match_expressions"][0]["key"] == "tier"
    assert match_affinity_term(term, {"app": "web", "tier": "frontend"})
    assert not match_affinity_term(term, {"app": "web", "tier": "backend"})
    assert not match_affinity_term(term, {"tier": "frontend"})


def test_pod_affinity_unsupported_topology_rejected():
    doc = yaml.safe_load(POD_AFFINITY_POD)
    doc["spec"]["affinity"]["podAntiAffinity"][
        "requiredDuringSchedulingIgnoredDuringExecution"
    ][0]["topologyKey"] = "topology.kubernetes.io/zone"
    with pytest.raises(ValueError, match="topologyKey"):
        parse_manifest(doc)


def test_pod_affinity_unknown_selector_field_rejected():
    doc = yaml.safe_load(POD_AFFINITY_POD)
    doc["spec"]["affinity"]["podAntiAffinity"][
        "requiredDuringSchedulingIgnoredDuringExecution"
    ][0]["labelSelector"]["matchFoo"] = {}
    with pytest.raises(ValueError, match="matchFoo"):
        parse_manifest(doc)
