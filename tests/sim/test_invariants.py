"""The invariant checker must CATCH seeded violations.

Each test manufactures a corrupted (or contract-violating) cache state
— some reachable only by bypassing the guarded accounting paths, which
is the point: the checker is the independent auditor that notices when
those guards ever fail over a long horizon.
"""

import pytest

from kube_batch_tpu.api import (
    PodPhase,
    TaskInfo,
    build_resource_list,
    pod_key,
)
from kube_batch_tpu.cache import SchedulerCache
from kube_batch_tpu.sim.invariants import InvariantChecker, water_fill
from kube_batch_tpu.utils.test_utils import (
    FakeBinder,
    FakeEvictor,
    FakeStatusUpdater,
    FakeVolumeBinder,
    build_node,
    build_pod,
    build_pod_group,
    build_queue,
)


def make_cache():
    return SchedulerCache(
        binder=FakeBinder(),
        evictor=FakeEvictor(),
        status_updater=FakeStatusUpdater(),
        volume_binder=FakeVolumeBinder(),
    )


def req(cpu="1", mem="1Gi"):
    return build_resource_list(cpu=cpu, memory=mem)


def add_running(cache, name, node, cpu="1", mem="1Gi", group=None):
    pod = build_pod("sim", name, node, PodPhase.RUNNING, req(cpu, mem),
                    group_name=group)
    cache.add_pod(pod)
    return pod


def kinds(violations):
    return sorted({v.invariant for v in violations})


class TestCleanState:
    def test_healthy_cluster_has_no_violations(self):
        c = make_cache()
        c.add_queue(build_queue("default"))
        c.add_node(build_node("n1", req("4", "8Gi")))
        c.add_pod_group(build_pod_group("g1", namespace="sim",
                                        min_member=2))
        add_running(c, "g1-0", "n1", group="g1")
        add_running(c, "g1-1", "n1", group="g1")
        checker = InvariantChecker()
        assert checker.check(c, cycle=0) == []


class TestOversubscribe:
    def test_catches_node_over_allocatable(self):
        c = make_cache()
        c.add_node(build_node("n1", req("2", "4Gi")))
        add_running(c, "p1", "n1", cpu="1500m")
        # Corrupt: smuggle a second task past the accounting guard so
        # the node holds 3 CPU against 2 allocatable.
        rogue = TaskInfo(build_pod(
            "sim", "p2", "n1", PodPhase.RUNNING, req("1500m")
        ))
        node = c.nodes["n1"]
        node.tasks[pod_key(rogue.pod)] = rogue
        checker = InvariantChecker()
        found = checker.check(c, cycle=3)
        assert "oversubscribe" in kinds(found)
        assert any(v.subject == "n1" and v.cycle == 3 for v in found)

    def test_catches_used_accounting_drift(self):
        c = make_cache()
        c.add_node(build_node("n1", req("4", "8Gi")))
        add_running(c, "p1", "n1")
        # Drift the maintained aggregate away from the task recount.
        c.nodes["n1"].used.milli_cpu += 700
        found = InvariantChecker().check(c, cycle=0)
        assert "oversubscribe" in kinds(found)


class TestGangAtomicity:
    def _split_gang(self):
        c = make_cache()
        c.add_node(build_node("n1", req("8", "16Gi")))
        c.add_pod_group(build_pod_group("g1", namespace="sim",
                                        min_member=4))
        add_running(c, "g1-0", "n1", group="g1")
        add_running(c, "g1-1", "n1", group="g1")
        for i in (2, 3):
            c.add_pod(build_pod("sim", f"g1-{i}", "", PodPhase.PENDING,
                                req(), group_name="g1"))
        return c

    def test_catches_partially_dispatched_gang(self):
        c = self._split_gang()
        found = InvariantChecker().check(c, cycle=1)
        assert kinds(found) == ["gang"]
        assert found[0].subject == "sim/g1"

    def test_fault_degraded_gang_is_exempt_until_whole(self):
        c = self._split_gang()
        checker = InvariantChecker()
        checker.mark_degraded("sim/g1", cycle=0)
        assert checker.check(c, cycle=1) == []
        # Made whole again (the pending pods get bound) -> exemption
        # expires...
        for i in (2, 3):
            bound = build_pod("sim", f"g1-{i}", "n1", PodPhase.RUNNING,
                              req(), group_name="g1")
            c.update_pod(bound, bound)
        assert checker.check(c, cycle=2) == []
        assert "sim/g1" not in checker.degraded
        # ...so a LATER split on the same gang is a violation again.
        c.delete_pod(c.jobs["sim/g1"].tasks["sim-g1-3"].pod)
        c.delete_pod(c.jobs["sim/g1"].tasks["sim-g1-2"].pod)
        found = checker.check(c, cycle=3)
        assert kinds(found) == ["gang"]


class TestConservation:
    def test_catches_double_bind(self):
        c = make_cache()
        c.add_node(build_node("n1", req("4", "8Gi")))
        c.add_node(build_node("n2", req("4", "8Gi")))
        pod = add_running(c, "p1", "n1")
        # Corrupt: the same task accounted on a second node.
        ghost = TaskInfo(pod)
        c.nodes["n2"].tasks[pod_key(pod)] = ghost
        found = InvariantChecker().check(c, cycle=0)
        assert "conservation" in kinds(found)
        assert any("double-bind" in v.message for v in found)

    def test_catches_resource_holder_missing_from_node(self):
        c = make_cache()
        c.add_node(build_node("n1", req("4", "8Gi")))
        pod = add_running(c, "p1", "n1")
        # Corrupt: node forgot the task but the job still holds it.
        del c.nodes["n1"].tasks[pod_key(pod)]
        found = InvariantChecker().check(c, cycle=0)
        assert "conservation" in kinds(found)
        assert any("missing from its node" in v.message for v in found)

    def test_catches_pending_task_holding_node_capacity(self):
        c = make_cache()
        c.add_node(build_node("n1", req("4", "8Gi")))
        pending = TaskInfo(build_pod("sim", "p1", "", PodPhase.PENDING,
                                     req()))
        c.add_pod(pending.pod)
        c.nodes["n1"].tasks[pod_key(pending.pod)] = pending
        found = InvariantChecker().check(c, cycle=0)
        assert any(
            "PENDING task still accounted" in v.message for v in found
        )


class TestQueueShares:
    def test_water_fill_matches_weighted_split(self):
        from kube_batch_tpu.api import Resource

        total = Resource(milli_cpu=9000)
        deserved = water_fill(
            total,
            {"a": 2, "b": 1},
            {"a": Resource(milli_cpu=9000),
             "b": Resource(milli_cpu=9000)},
        )
        assert deserved["a"].milli_cpu == pytest.approx(6000)
        assert deserved["b"].milli_cpu == pytest.approx(3000)

    def test_catches_new_allocation_beyond_deserved(self):
        c = make_cache()
        c.add_queue(build_queue("qa", weight=1))
        c.add_queue(build_queue("qb", weight=1))
        c.add_node(build_node("n1", req("10", "10Gi")))
        # qa: eight singletons running, past its deserved half on BOTH
        # dimensions (the plugin's OverusedFn contract is per-queue
        # all-dims coverage); qb: equal pending demand.
        for i in range(8):
            c.add_pod_group(build_pod_group(f"a{i}", namespace="sim",
                                            min_member=1, queue="qa"))
            add_running(c, f"a{i}-0", "n1", group=f"a{i}")
        c.add_pod_group(build_pod_group("b0", namespace="sim",
                                        min_member=8, queue="qb"))
        for i in range(8):
            c.add_pod(build_pod("sim", f"b0-{i}", "", PodPhase.PENDING,
                                req(), group_name="b0"))
        checker = InvariantChecker()
        # Baseline pass records per-queue allocation, flags nothing.
        assert checker.check(c, cycle=0) == []
        # qa GAINS another singleton while already past its deserved
        # share in every dimension -> the fairness contract is broken.
        c.add_pod_group(build_pod_group("a9", namespace="sim",
                                        min_member=1, queue="qa"))
        add_running(c, "a9-0", "n1", group="a9")
        found = checker.check(c, cycle=1)
        assert kinds(found) == ["queue-share"]
        assert found[0].subject == "qa"

    def test_single_dimension_overshoot_is_not_flagged(self):
        """The reference OverusedFn blocks a queue only when allocated
        covers deserved in EVERY dimension — a cpu-saturated but
        memory-light queue legitimately keeps gaining cpu. The
        100k-cycle soak caught the checker's earlier any-dimension
        form flagging ~1/1000 cycles under a cpu-bound mix."""
        c = make_cache()
        c.add_queue(build_queue("qa", weight=1))
        c.add_queue(build_queue("qb", weight=1))
        c.add_node(build_node("n1", req("10", "100Gi")))
        # qa far past deserved on cpu (8 of a deserved 5) but way
        # under on memory (8 Gi of a deserved 50 Gi).
        for i in range(8):
            c.add_pod_group(build_pod_group(f"a{i}", namespace="sim",
                                            min_member=1, queue="qa"))
            add_running(c, f"a{i}-0", "n1", group=f"a{i}")
        c.add_pod_group(build_pod_group("b0", namespace="sim",
                                        min_member=8, queue="qb"))
        for i in range(8):
            c.add_pod(build_pod("sim", f"b0-{i}", "", PodPhase.PENDING,
                                req(), group_name="b0"))
        checker = InvariantChecker()
        assert checker.check(c, cycle=0) == []
        c.add_pod_group(build_pod_group("a9", namespace="sim",
                                        min_member=1, queue="qa"))
        add_running(c, "a9-0", "n1", group="a9")
        assert checker.check(c, cycle=1) == []
