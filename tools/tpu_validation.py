#!/usr/bin/env python
"""One-shot TPU validation runbook.

Everything in this repo that is gated on REAL TPU hardware, runnable the
moment the accelerator becomes reachable:

1. backend probe (bounded; aborts with a clear message when the tunnel
   is wedged rather than hanging),
2. bench.py at every config with the jax kernel on device (the headline
   BASELINE.md target: <100 ms at 50k x 5k, >=10x the native loop),
3. Pallas fused-bid kernel: compiled (non-interpret) parity vs the jnp
   chain, then an A/B of KBT_PALLAS=1 vs the default path at the
   headline scale — the data for deciding whether Pallas becomes the
   default (VERDICT r1 item 5).

Writes one JSON report (default tpu_validation.json) and prints a
summary. Usage: python tools/tpu_validation.py [--out FILE] [--skip-bench]
"""

import argparse
import json
import os
import subprocess
import sys
import time

# Build-round suffix for committed trace artifacts; bump per round so
# evidence files carry their provenance.
ROUND = "r5"

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def probe():
    from kube_batch_tpu.utils.backend import probe_default_backend

    return probe_default_backend(timeout=120, attempts=2, backoff=15,
                                 total_budget=270)


def run_bench(config, env_extra=None, timeout=900):
    env = dict(os.environ)
    env.update(env_extra or {})
    try:
        proc = subprocess.run(
            [sys.executable, "bench.py", "--config", config],
            capture_output=True, text=True, timeout=timeout, cwd=REPO,
            env=env,
        )
    except subprocess.TimeoutExpired:
        # One slow step must not lose the report (docstring contract).
        return {"error": f"timeout after {timeout}s"}
    line = (proc.stdout.strip().splitlines() or [""])[-1]
    try:
        return json.loads(line)
    except ValueError:
        return {"error": proc.stderr[-1000:], "rc": proc.returncode}


def run_null_dispatch(timeout=300):
    """Tunnel overhead in isolation: a trivial jitted call moves ~no data
    and does ~no compute, so its steady-state dispatch+fetch wall time IS
    the fixed per-call tunnel cost. Reported separately from the headline
    so the on-device compute share is measured, not inferred (VERDICT r3
    'README provenance' finding)."""
    code = """
import json, time
import jax, jax.numpy as jnp

f = jax.jit(lambda x: x + 1)
x = jnp.zeros((8,), jnp.int32)
for _ in range(2):  # compile + executable-upload warmups
    jax.block_until_ready(f(x))
reps = []
for _ in range(20):
    t0 = time.perf_counter()
    jax.block_until_ready(f(x))
    reps.append((time.perf_counter() - t0) * 1e3)
reps.sort()
print(json.dumps({
    "null_dispatch_ms_median": round(reps[len(reps) // 2], 2),
    "null_dispatch_ms_min": round(reps[0], 2),
    "null_dispatch_ms_max": round(reps[-1], 2),
    "platform": jax.devices()[0].platform,
}))
"""
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, text=True,
            timeout=timeout, cwd=REPO,
        )
    except subprocess.TimeoutExpired:
        return {"error": f"timeout after {timeout}s"}
    line = (proc.stdout.strip().splitlines() or [""])[-1]
    try:
        return json.loads(line)
    except ValueError:
        return {"error": proc.stderr[-1000:], "rc": proc.returncode}


def run_traced_bench(trace_dir, timeout=1800):
    """Headline bench with a jax.profiler trace captured into trace_dir,
    then compressed to a committable artifact (traces/tpu_trace_<round>.tar.gz)
    so the device-compute decomposition is backed by evidence in-repo."""
    import shutil
    import tarfile

    if os.path.isdir(trace_dir):
        shutil.rmtree(trace_dir)
    result = run_bench("large", env_extra=None, timeout=timeout)
    # run_bench doesn't pass --profile; trace in a dedicated run so a
    # profiler failure can't lose the bench number.
    try:
        proc = subprocess.run(
            [sys.executable, "bench.py", "--config", "large",
             "--profile", trace_dir],
            capture_output=True, text=True, timeout=timeout, cwd=REPO,
        )
        if proc.returncode == 0 and os.path.isdir(trace_dir):
            out = os.path.join(REPO, "traces")
            os.makedirs(out, exist_ok=True)
            tar_path = os.path.join(out, f"tpu_trace_{ROUND}.tar.gz")
            with tarfile.open(tar_path, "w:gz") as tar:
                tar.add(trace_dir, arcname=f"tpu_trace_{ROUND}")
            result["trace_artifact"] = os.path.relpath(tar_path, REPO)
            # Only the tarball is meant for the repo; leaving the raw
            # profile next to it invites `git add traces/` to stage it.
            shutil.rmtree(trace_dir, ignore_errors=True)
        else:
            result["trace_error"] = proc.stderr[-800:]
    except subprocess.TimeoutExpired:
        result["trace_error"] = f"trace run timeout after {timeout}s"
    return result


def run_pallas_parity(timeout=600):
    """Compiled (non-interpret) pallas_bid parity on the device."""
    code = """
import json
import numpy as np
import jax.numpy as jnp
import sys
sys.path.insert(0, %r)
from tests.solver.test_pallas import jnp_reference_bid, _random_case
from kube_batch_tpu.solver.pallas_kernels import pallas_bid, TILE_T

ok = True
# Base cases, an UNALIGNED task axis (internal padding), and STATIC
# score rows (the standard nodeorder config) — all compiled on TPU.
for seed, T in ((0, 2 * TILE_T), (1, 2 * TILE_T), (2, TILE_T + 57)):
    case = _random_case(seed, T=T, N=256)
    args = (case["task_fit"], case["task_req"], case["task_ok"],
            case["feas"], case["idle"], case["cap"], case["cap_ok"],
            case["eps"], case["lr_w"], case["br_w"])
    bid_p, any_p = pallas_bid(*args, interpret=False)  # compiled on TPU
    bid_r, any_r = jnp_reference_bid(*args)
    ok &= bool((np.asarray(bid_p) == np.asarray(bid_r)).all())
    ok &= bool((np.asarray(any_p) == np.asarray(any_r)).all())

import jax.numpy as jnp
case = _random_case(7, T=2 * TILE_T, N=256)
rng = np.random.RandomState(107)
static = jnp.asarray(rng.uniform(0, 10, (2 * TILE_T, 256)).astype(np.float32))
args = (case["task_fit"], case["task_req"], case["task_ok"], case["feas"],
        case["idle"], case["cap"], case["cap_ok"], case["eps"],
        case["lr_w"], case["br_w"])
bid_p, any_p = pallas_bid(*args, static_score=static, interpret=False)
bid_r, any_r = jnp_reference_bid(*args, static_score=static)
ok &= bool((np.asarray(bid_p) == np.asarray(bid_r)).all())
ok &= bool((np.asarray(any_p) == np.asarray(any_r)).all())
print(json.dumps({"pallas_compiled_parity": ok}))
""" % REPO
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, text=True,
            timeout=timeout, cwd=REPO,
        )
    except subprocess.TimeoutExpired:
        return {"error": f"timeout after {timeout}s"}
    line = (proc.stdout.strip().splitlines() or [""])[-1]
    try:
        return json.loads(line)
    except ValueError:
        return {"error": proc.stderr[-1000:], "rc": proc.returncode}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="tpu_validation.json")
    ap.add_argument("--skip-bench", action="store_true")
    args = ap.parse_args()

    report = {"started": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())}
    n = probe()
    report["devices"] = n
    if n == 0:
        report["status"] = "tunnel unreachable; nothing hardware-gated ran"
        print(json.dumps(report, indent=2))
        with open(args.out, "w") as f:
            json.dump(report, f, indent=2)
        return 1

    # Null dispatch FIRST: it is the cheapest run and the tunnel dies
    # unpredictably — the decomposition denominator must not be the
    # casualty of a mid-runbook wedge.
    report["null_dispatch"] = run_null_dispatch()

    if not args.skip_bench:
        report["bench"] = {}
        for cfg in ("small", "medium"):
            report["bench"][cfg] = run_bench(cfg, timeout=900)
        # Headline large run doubles as the profiler-trace capture; the
        # compressed trace lands in traces/ as a committable artifact.
        report["bench"]["large"] = run_traced_bench(
            os.path.join(REPO, "traces", f"{ROUND}_profile"), timeout=1800
        )
        report["bench_pallas_large"] = run_bench(
            "large", env_extra={"KBT_PALLAS": "1"}, timeout=1500
        )
    report["pallas"] = run_pallas_parity()

    null_ms = (report.get("null_dispatch") or {}).get(
        "null_dispatch_ms_median"
    )
    head = (report.get("bench", {}) or {}).get("large", {}).get("value")
    if isinstance(null_ms, (int, float)) and isinstance(head, (int, float)):
        report["device_compute_est_ms"] = round(head - null_ms, 1)

    large = (report.get("bench", {}) or {}).get("large", {})
    report["headline_ms"] = large.get("value")
    report["vs_baseline"] = large.get("vs_baseline")
    report["target_met"] = bool(
        isinstance(large.get("value"), (int, float))
        and large["value"] < 100
        and large.get("device") == "tpu"
    )
    # Secondary bar (VERDICT r4 item 1): device compute <100 ms with the
    # tunnel's fixed dispatch cost measured separately, not inferred.
    report["device_target_met"] = bool(
        isinstance(report.get("device_compute_est_ms"), (int, float))
        and report["device_compute_est_ms"] < 100
        and large.get("device") == "tpu"
    )
    with open(args.out, "w") as f:
        json.dump(report, f, indent=2)
    print(json.dumps(report, indent=2))
    # rc contract (tpu_watch.sh keys on it): 0 only when the headline
    # bench genuinely ran on the TPU. A tunnel that answered the probe
    # but died mid-runbook must read as failure so the watcher keeps
    # watching instead of retiring on a useless report.
    if args.skip_bench:
        return 0
    return 0 if large.get("device") == "tpu" else 1


if __name__ == "__main__":
    sys.exit(main())
