#!/usr/bin/env python
"""tpu-batch benchmark harness.

Reproduces the BASELINE.json synthetic configs (1k pods x 100 nodes,
10k x 1k, 50k x 5k gang mix) through the REAL pipeline: SchedulerCache event
ingest -> Session open (plugins) -> tensorize -> batched TPU solve. The
baseline is the NATIVE (C++) reimplementation of the reference's greedy
allocate loop (kube_batch_tpu/native/csrc/greedy.cpp), measured outright at the headline scale
on the same snapshot arrays — the fair stand-in for the reference's
compiled Go loop. The Python greedy action is also timed on the small
config as a sanity datapoint (and as extrapolation fallback when no
native toolchain exists).

Prints ONE JSON line:
  {"metric": ..., "value": <ms>, "unit": "ms", "vs_baseline": <speedup>, ...}

- value: headline 50k x 5k batched solve latency (ms, device solve,
  steady-state after compile; host snapshot time reported separately).
- vs_baseline: measured-native-greedy-ms / tpu-solve-ms.

Usage: python bench.py [--quick] [--config small|medium|large]
"""

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, ".")


# Backend-probe provenance for the output JSON ("device_provenance"):
# attempts, outcomes, and whether this process was forced onto CPU.
PROBE_INFO = {"forced_cpu": False, "attempts": []}


def _ensure_live_backend(require_accelerator=False):
    """The tunneled TPU backend can be down/wedged; a bench that hangs or
    crashes records nothing. Probe device init in a SUBPROCESS with a hard
    timeout (an in-process probe would wedge this process too), retrying
    with backoff — a transient tunnel outage must not turn a TPU round
    into a useless CPU number (round-1 lesson: BENCH_r01 recorded 0.1x on
    CPU). Only after every attempt fails re-exec the bench on CPU so a
    result is always produced (the JSON carries the actual platform in
    its "device" field) — unless ``require_accelerator``
    (--require-accelerator / TPU_BATCH_BENCH_REQUIRE_DEVICE=1), which
    fails LOUDLY instead: an on-device artifact was demanded, a silent
    CPU number would be worse than no number."""
    from kube_batch_tpu.utils.backend import (
        force_cpu_devices,
        last_probe_stats,
        probe_default_backend,
    )

    if os.environ.get("_KBT_BENCH_CPU") == "1":
        if require_accelerator:
            print(json.dumps({
                "error": "accelerator required but this process was "
                         "already forced onto the CPU fallback",
            }))
            sys.exit(3)
        # Fallback child: drop the wedged non-CPU factory before any
        # backend resolution (env alone does not stop it from dialing).
        force_cpu_devices(1)
        # The parent's probe evidence rode through the re-exec — the
        # CPU artifact must still say WHY it is a CPU artifact.
        inherited = os.environ.get("_KBT_BENCH_PROBE", "")
        if inherited:
            try:
                PROBE_INFO.update(json.loads(inherited))
            except ValueError:
                pass
        PROBE_INFO["forced_cpu"] = True
        return
    # Cumulative probe budget ~4.5 min: a wedged tunnel hangs each probe
    # to its full timeout, and the large-config CPU fallback still needs
    # ~3 min of runway inside the driver's own deadline.
    n = probe_default_backend(
        timeout=120, attempts=4, backoff=30, total_budget=270
    )
    PROBE_INFO["attempts"] = list(last_probe_stats.get("attempts", []))
    PROBE_INFO["probe_devices"] = n
    platform = last_probe_stats.get("platform", "")
    if require_accelerator and n > 0 and platform == "cpu":
        # A live backend whose default platform is the host CPU is
        # still not an accelerator — requiring a device means exactly
        # that (the round-6 ask: no silent CPU artifacts).
        print(
            "bench: accelerator REQUIRED but the default jax backend "
            "is cpu-only; refusing to record a CPU artifact",
            file=sys.stderr,
        )
        print(json.dumps({
            "error": "accelerator required but only the cpu backend "
                     "is available",
            "probe": PROBE_INFO,
        }))
        sys.exit(3)
    if n > 0:
        return
    if require_accelerator:
        print(
            "bench: accelerator REQUIRED but unreachable within the "
            "probe budget; refusing the silent CPU fallback",
            file=sys.stderr,
        )
        print(json.dumps({
            "error": "accelerator required but unavailable",
            "probe": PROBE_INFO,
        }))
        sys.exit(3)
    print(
        "bench: accelerator backend unavailable within the probe budget; "
        "falling back to CPU",
        file=sys.stderr,
    )
    env = dict(os.environ)
    env.update({
        "_KBT_BENCH_CPU": "1",
        "PALLAS_AXON_POOL_IPS": "",
        "JAX_PLATFORMS": "cpu",
        # Carry the probe evidence into the child (see above).
        "_KBT_BENCH_PROBE": json.dumps({
            "attempts": PROBE_INFO["attempts"],
            "probe_devices": PROBE_INFO.get("probe_devices", 0),
        }),
    })
    os.execve(sys.executable, [sys.executable] + sys.argv, env)

import kube_batch_tpu.actions  # noqa: F401
import kube_batch_tpu.plugins  # noqa: F401
from kube_batch_tpu.api import PodPhase, TaskStatus, build_resource_list
from kube_batch_tpu.cache import SchedulerCache
from kube_batch_tpu.framework import close_session, get_action, open_session
from kube_batch_tpu.solver import (
    default_mesh,
    sharded_step,
    solve_jit,
    solve_sharded,
    tensorize,
)
from kube_batch_tpu.utils.test_utils import (
    FakeBinder,
    FakeEvictor,
    FakeStatusUpdater,
    FakeVolumeBinder,
    build_node,
    build_pod,
    build_pod_group,
    build_queue,
)
from tests.actions.test_actions import make_tiers

CONFIGS = {
    # name: (tasks, nodes, queues, groups)
    "small": (1_000, 100, 1, 10),
    "medium": (10_000, 1_000, 4, 100),
    "large": (50_000, 5_000, 5, 500),
}

TIERS_ARGS = (
    ["priority", "gang", "conformance"],
    ["drf", "predicates", "proportion", "nodeorder"],
)


def build_cluster(n_tasks, n_nodes, n_queues, n_groups, seed=0):
    rng = np.random.RandomState(seed)
    cache = SchedulerCache(
        binder=FakeBinder(),
        evictor=FakeEvictor(),
        status_updater=FakeStatusUpdater(),
        volume_binder=FakeVolumeBinder(),
    )
    for q in range(n_queues):
        cache.add_queue(build_queue(f"q{q}", weight=q + 1))
    for j in range(n_nodes):
        cache.add_node(build_node(
            f"n{j}", build_resource_list(cpu="32", memory="128Gi", pods=110)
        ))
    per_group = n_tasks // n_groups
    cpus = rng.choice([250, 500, 1000, 2000, 4000], size=n_tasks)
    mems = rng.choice([256, 512, 1024, 4096, 8192], size=n_tasks)
    t = 0
    for g in range(n_groups):
        queue = f"q{g % n_queues}"
        min_member = int(rng.randint(1, per_group + 1))
        cache.add_pod_group(build_pod_group(
            f"pg{g}", namespace="bench", min_member=min_member, queue=queue
        ))
        for i in range(per_group):
            cache.add_pod(build_pod(
                "bench", f"pg{g}-p{i}", "", PodPhase.PENDING,
                build_resource_list(
                    cpu=f"{int(cpus[t])}m", memory=f"{int(mems[t])}Mi"
                ),
                group_name=f"pg{g}",
            ))
            t += 1
    return cache


def bench_greedy(cfg, seed=0, runs=3):
    """Greedy allocate action wall time (full Execute) on a config.

    The sample subproblem is PINNED — fixed seed, fixed config shape —
    and the reported time is the MEDIAN of ``runs`` independent
    executions on freshly built clusters. The previous single-shot
    number swung ~2x between bench rounds (1.17M vs 2.57M extrapolated
    ms, BENCH_r04 vs r05) purely on allocator/GC noise, and it feeds
    greedy_extrapolated_ms, so the swing looked like a baseline change."""
    n_tasks, n_nodes, n_queues, n_groups = CONFIGS[cfg]
    times = []
    placed = 0
    for _ in range(max(1, runs)):
        cache = build_cluster(n_tasks, n_nodes, n_queues, n_groups, seed)
        ssn = open_session(cache, make_tiers(*TIERS_ARGS))
        action, _ = get_action("allocate")
        start = time.perf_counter()
        action.execute(ssn)
        times.append(time.perf_counter() - start)
        placed = len(cache.binder.binds)
        close_session(ssn)
        cache.shutdown()
    times.sort()
    return times[len(times) // 2], placed, n_tasks * n_nodes


def bench_native_greedy(inputs, repeats=2):
    """Measured native (C++) reference-loop baseline on the SAME snapshot
    arrays the TPU solver consumes (csrc/greedy.cpp) — the fair stand-in
    for the reference's compiled Go loop. Returns (seconds, placed) or
    None when no toolchain is available."""
    try:
        from kube_batch_tpu.native import NativeUnavailable, greedy_allocate
    except Exception:
        return None
    solver_in = inputs.unpack() if hasattr(inputs, "unpack") else inputs
    task_req = np.asarray(solver_in.task_req)
    valid = np.asarray(solver_in.task_valid)
    task_req = task_req[valid]
    task_queue = np.asarray(solver_in.task_queue)[valid]
    node_feas = np.asarray(solver_in.node_feas)
    node_idle = np.asarray(solver_in.node_idle)[node_feas]
    node_cap = np.asarray(solver_in.node_cap)[node_feas]
    qd = np.asarray(solver_in.queue_deserved)
    qa = np.asarray(solver_in.queue_allocated)
    eps = np.asarray(solver_in.eps)
    lr = float(np.asarray(solver_in.lr_weight))
    br = float(np.asarray(solver_in.br_weight))
    try:
        times = []
        for _ in range(repeats):
            t0 = time.perf_counter()
            _, placed = greedy_allocate(
                task_req, task_queue, node_idle, node_cap, qd, qa, eps,
                lr, br,
            )
            times.append(time.perf_counter() - t0)
        return min(times), placed
    except NativeUnavailable:
        return None


def bench_native_masked(inputs, repeats=3):
    """The framework's production CPU path (allocate_tpu routes here when
    no accelerator exists): greedy.cpp's feasibility-aware loop on the
    same factorized snapshot. Returns (seconds, placed) or None."""
    try:
        from kube_batch_tpu.native import NativeUnavailable, solve_native
    except Exception:
        return None
    try:
        times = []
        for _ in range(repeats):
            t0 = time.perf_counter()
            _, placed = solve_native(inputs)
            times.append(time.perf_counter() - t0)
        return min(times), placed
    except NativeUnavailable:
        return None


def bench_tpu(cfg, seed=0, repeats=3):
    """Batched solve on a config: returns (host_snapshot_s, solve_s, placed)."""
    n_tasks, n_nodes, n_queues, n_groups = CONFIGS[cfg]
    cache = build_cluster(n_tasks, n_nodes, n_queues, n_groups, seed)

    t0 = time.perf_counter()
    ssn = open_session(cache, make_tiers(*TIERS_ARGS))
    t_session = time.perf_counter() - t0

    t0 = time.perf_counter()
    inputs, ctx = tensorize(ssn)
    t_snapshot = time.perf_counter() - t0
    from kube_batch_tpu.solver.snapshot import last_tensorize_stats

    sparse_stats = dict(last_tensorize_stats.get("sparse") or {})

    # Compile once, then measure steady-state device latency. Timing
    # includes the device->host fetch of the assignment vector (what a real
    # cycle needs back) so async dispatch cannot flatter the number.
    # With >1 device the node axis is sharded over the mesh (multi-chip
    # scale path); padding + host->device transfer happen ONCE outside the
    # timed loop, exactly like the single-device path's device-resident
    # arrays, so the loop isolates the solve itself.
    import jax

    mesh = default_mesh()
    if mesh is not None:
        step, dev_inputs = sharded_step(inputs, mesh)
    else:
        step, dev_inputs = solve_jit, inputs
    result = jax.block_until_ready(step(dev_inputs))
    times = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        result = step(dev_inputs)
        assigned_host = np.asarray(result.assigned)
        times.append(time.perf_counter() - t0)
    solve_s = min(times)
    placed = int((assigned_host >= 0).sum())
    rounds = int(result.rounds)
    if result.refills is not None:
        sparse_stats["jax"] = {
            "refill_tasks": int(result.refills),
            "refill_rounds": int(result.stages),
        }
    close_session(ssn)
    return {
        "session_s": t_session,
        "snapshot_s": t_snapshot,
        "solve_s": solve_s,
        "placed": placed,
        "rounds": rounds,
        "work": n_tasks * n_nodes,
        "inputs": inputs,
        # Candidate-selection stats of this snapshot (solver/topk.py).
        "sparse": sparse_stats,
        # NumPy-backed SolverInputs for the native baselines — feeding
        # them the device PackedInputs would bill ~140 ms of eager JAX
        # slicing to a C++ loop (r4 delta-profile lesson).
        "host_inputs": ctx.host_inputs,
        # Every task is still Pending (the solve was never applied):
        # bench_cycle reuses this cluster instead of rebuilding it.
        "cache": cache,
    }


def bench_cycle(cfg, seed=0, cache=None, trace_path=None,
                measure_obs=False):
    """Full scheduling cycles through the production allocate_tpu action —
    the number BASELINE.md's <100 ms target is really about (the reference
    hot path is the whole runOnce, scheduler.go:88-103, not the inner
    kernel). Four scenarios:

    - cold:   first cycle on a fresh full-scale pending burst;
    - steady: the very next cycle — every placed job/node changed in
      cold, so the COW snapshot pool re-clones the world (its worst
      case);
    - idle:   one more unchanged cycle — nothing dirty, the pool and
      early-exit tensorize shine (the common 1 Hz case);
    - delta:  a ~1% batch of new gangs arrives, next cycle.

    Each cycle reports open/tensorize/solve/apply/epilogue/close phases
    (from actions.allocate_tpu.last_stats) plus the e2e wall time.
    Attribution flags ride along per cycle: ``apply_handlers_batched``
    / ``apply_job_groups_hint`` (aggregate plugin handler dispatch) and
    ``tensorize_incremental`` / ``tensorize_dirty_nodes`` /
    ``tensorize_full_reason`` (incremental snapshot patching and the
    row counts it actually touched).

    With ``trace_path`` the span tracer records the four cycles and
    exports one Chrome trace-event file (the acceptance artifact: the
    cold cycle's solve/apply overlap shows as concurrent tracks in
    Perfetto). ``measure_obs`` appends an ``obs`` section: tracer
    overhead measured on/off over repeated idle-shape cycles at this
    config, plus span counts per cycle.
    """
    from kube_batch_tpu.actions import allocate_tpu as _atpu
    from kube_batch_tpu.obs.tracer import TRACER

    n_tasks, n_nodes, n_queues, n_groups = CONFIGS[cfg]
    if cache is None:
        # Callers that already built this config's cluster (bench_tpu
        # leaves every task pending) pass it in — a second 50k build
        # costs ~2 min of the driver's deadline.
        cache = build_cluster(n_tasks, n_nodes, n_queues, n_groups, seed)
    else:
        # The passed cache saw a prior session open + tensorize, so the
        # COW pool and the per-pod tensorize caches are warm; a real
        # pending burst arrives with fresh pods. Re-cold BOTH so the
        # cold cycle measures burst-arrival cost: dirty every job
        # (forces re-clone; nodes legitimately stay reused — pod
        # arrivals do not touch them) and drop the per-pod predicate
        # caches via the plugin-owned helper (the attr list lives there).
        from kube_batch_tpu.plugins.predicates import clear_pod_caches

        for job in cache.jobs.values():
            job._ver += 1
            clear_pod_caches(t.pod for t in job.tasks.values())
    action, _ = get_action("allocate_tpu")

    cycle_counter = [0]

    def one_cycle():
        # Same GC deferral as the production Scheduler.run_once: the
        # collection runs after t_close, in what would be think-time.
        from kube_batch_tpu.obs import span
        from kube_batch_tpu.utils import deferred_gc

        TRACER.begin_cycle(cycle_counter[0])
        cycle_counter[0] += 1
        t_start = time.perf_counter()
        with span("cycle"), deferred_gc():
            ssn = open_session(cache, make_tiers(*TIERS_ARGS))
            t_open = time.perf_counter()
            action.execute(ssn)
            t_exec = time.perf_counter()
            close_session(ssn)
            t_close = time.perf_counter()
        out = {
            "open_ms": round((t_open - t_start) * 1e3, 1),
            "action_ms": round((t_exec - t_open) * 1e3, 1),
            "close_ms": round((t_close - t_exec) * 1e3, 1),
            # 3 decimals: the obs section's tracer-overhead comparison
            # needs sub-0.1ms resolution on idle cycles.
            "cycle_ms": round((t_close - t_start) * 1e3, 3),
            # close_session now runs under its own (nested) deferred_gc
            # guard, so a generational collection can never land inside
            # the close and jitter close_ms (r5: 2.1 -> 17.7 ms spikes).
            "close_gc_deferred": True,
        }
        for k, v in _atpu.last_stats.items():
            out[k] = round(v, 1) if isinstance(v, float) else v
        # Drain async bind side effects outside the timed region so the
        # next cycle's timings aren't polluted by this cycle's backlog.
        # A failed drain makes the next cycle's numbers suspect — record it.
        out["drain_ok"] = cache.wait_for_side_effects(timeout=120.0)
        return out

    tracing = trace_path is not None
    if tracing:
        TRACER.reset()
        TRACER.enable()

    def spans_since(mark):
        return TRACER.spans_recorded - mark

    mark = TRACER.spans_recorded
    cold = one_cycle()
    cold["spans"] = spans_since(mark)
    mark = TRACER.spans_recorded
    steady = one_cycle()
    steady["spans"] = spans_since(mark)
    mark = TRACER.spans_recorded
    idle = one_cycle()
    idle["spans"] = spans_since(mark)
    mark = TRACER.spans_recorded

    # ~1% new gangs arrive, drawn from the same shape mix as build_cluster.
    rng = np.random.RandomState(seed + 1)
    new_groups = max(1, n_groups // 100)
    per_group = n_tasks // n_groups

    def add_burst(prefix, groups=None):
        for g in range(groups if groups is not None else new_groups):
            name = f"{prefix}{g}"
            cache.add_pod_group(build_pod_group(
                name, namespace="bench",
                min_member=int(rng.randint(1, per_group + 1)),
                queue=f"q{g % n_queues}",
            ))
            for i in range(per_group):
                cache.add_pod(build_pod(
                    "bench", f"{name}-p{i}", "", PodPhase.PENDING,
                    build_resource_list(
                        cpu=f"{int(rng.choice([250, 500, 1000, 2000, 4000]))}m",
                        memory=f"{int(rng.choice([256, 512, 1024, 4096, 8192]))}Mi",
                    ),
                    group_name=name,
                ))

    add_burst("pgd")
    delta = one_cycle()
    delta["spans"] = spans_since(mark)

    # Degraded-mode floor: one more same-size burst cycle with the
    # fault-containment breaker PINNED open (solver/containment.py) —
    # the whole cycle runs on the native floor with zero device
    # dispatch, exactly what an open breaker costs in production.
    # bench_compare tracks this point like any headline number, so the
    # floor's latency cannot silently regress.
    from kube_batch_tpu.solver import containment

    add_burst("pgx")
    mark = TRACER.spans_recorded
    containment.BREAKER.pin_open("bench-degraded")
    try:
        degraded = one_cycle()
    finally:
        containment.BREAKER.unpin()
    degraded["spans"] = spans_since(mark)

    # --- steady_warm: the warm-started 1%-churn steady state ---------
    # Each round: a ~1% gang burst arrives, the next cycle places it
    # through the warm-start plan (solver/warm.py) — incremental
    # tensorize, selection-cache reuse, residual capacities. The cycle
    # AFTER the last burst absorbs its placement wave as a warm no-op.
    # Reported per-round + median; `warm_outcome`/`tensorize_incremental`
    # are the acceptance flags (warm must ENGAGE, the placement wave
    # must never trip a full rebuild).
    one_cycle()  # settle the degraded round's wave; re-warms the state
    warm_rounds = []
    for r in range(5):
        add_burst(f"pgw{r}_")
        warm_rounds.append(one_cycle())
    absorb = one_cycle()
    warm_med = sorted(
        r["cycle_ms"] for r in warm_rounds
    )[len(warm_rounds) // 2]
    steady_warm = {
        "cycle_ms": round(warm_med, 3),
        "rounds_ms": [round(r["cycle_ms"], 3) for r in warm_rounds],
        "warm_outcome": warm_rounds[-1].get("warm_outcome"),
        "warm_engaged": all(
            r.get("warm_outcome") in ("solve", "noop")
            for r in warm_rounds
        ),
        "tensorize_incremental": all(
            r.get("tensorize_incremental", False) for r in warm_rounds
        ),
        "tensorize_wave_patched": warm_rounds[-1].get(
            "tensorize_wave_patched"
        ),
        "placed_per_round": [r.get("placed", 0) for r in warm_rounds],
        "sparse_engaged": warm_rounds[-1].get("sparse_engaged"),
        "absorb_cycle_ms": absorb["cycle_ms"],
        "absorb_warm_outcome": absorb.get("warm_outcome"),
        "open_ms": warm_rounds[-1].get("open_ms"),
        "action_ms": warm_rounds[-1].get("action_ms"),
        "close_ms": warm_rounds[-1].get("close_ms"),
        "tensorize_ms": warm_rounds[-1].get("tensorize_ms"),
        "solve_ms": warm_rounds[-1].get("solve_ms"),
        "apply_ms": warm_rounds[-1].get("apply_ms"),
    }

    # --- micro_cycle: arrival-to-placement latency ------------------
    # The event-driven fast path (Scheduler.run_micro semantics: full
    # session, micro flag, warm-path-only placement) measured from the
    # moment a burst lands in the mirror to its placements applied, at
    # ~0.1% and ~1% churn.
    def micro_round(prefix, burst_tasks):
        groups = max(1, burst_tasks // per_group)
        add_burst(prefix, groups=groups)
        from kube_batch_tpu.utils import deferred_gc as _dgc

        t0 = time.perf_counter()
        with _dgc():
            ssn = open_session(cache, make_tiers(*TIERS_ARGS))
            ssn.micro_cycle = True
            action.execute(ssn)
            close_session(ssn)
            # Stop the clock INSIDE the guard: the deferred collection
            # at guard exit belongs to think-time, exactly as in
            # one_cycle()/Scheduler.run_once.
            ms = (time.perf_counter() - t0) * 1e3
        stats = dict(_atpu.last_stats)
        cache.wait_for_side_effects(timeout=120.0)
        one_cycle()  # absorb the wave before the next round
        return {
            "arrival_to_placement_ms": round(ms, 3),
            "burst_tasks": groups * per_group,
            "placed": stats.get("placed", 0),
            "warm_outcome": stats.get("warm_outcome"),
            "deferred": stats.get("micro_deferred"),
        }

    micro_cycle = {
        "burst_0p1": micro_round("pgm1_", max(1, n_tasks // 1000)),
        "burst_1p": micro_round("pgm2_", max(1, n_tasks // 100)),
    }

    out = {"cold": cold, "steady": steady, "idle": idle, "delta": delta,
           "degraded": degraded, "steady_warm": steady_warm,
           "micro_cycle": micro_cycle}
    if tracing:
        out["trace_path"] = TRACER.export(trace_path)
        out["trace_spans"] = TRACER.spans_recorded
        out["trace_spans_dropped"] = TRACER.dropped
        TRACER.disable()
    if measure_obs:
        out["obs"] = bench_obs(one_cycle, cache=cache)
        # Quality scorecard cost against the same (still-live) benched
        # cache; amortized against the measured warm steady cycle.
        out["quality"] = bench_quality(
            cache, steady_ms=steady_warm.get("cycle_ms")
        )
    cache.shutdown()
    return out


def bench_obs(one_cycle, runs=7, cache=None):
    """Tracer + telemetry overhead at the benched shape.

    Two tracer measurements, because cycle-to-cycle wall-time variance
    at 50k scale (GC, allocator state) is orders of magnitude larger
    than the microseconds a handful of spans cost:

    - **pinned overhead** = measured per-span cost (tight microbench of
      the enabled span path) x spans recorded per cycle, as a fraction
      of the tracer-OFF cycle median — deterministic, this is the
      number the <1%-of-an-idle-cycle budget is checked against;
    - **a/b delta** = interleaved off/on cycle medians, reported as
      corroborating evidence (expected to sit inside run noise).

    Plus the telemetry enabled-path cost: the full per-cycle
    ``observe_scheduler_cycle`` (flight-record extraction, watermark
    probes, the amortized fairness probe against the REAL benched
    cache) timed over enough cycles to include window rolls and
    fairness refreshes — pinned against the same <1% budget.
    """
    from kube_batch_tpu.obs.tracer import TRACER

    was_enabled = TRACER.enabled
    TRACER.disable()
    one_cycle()  # settle after the caller's last cycle
    off, on = [], []
    span_count = 0
    # Interleaved a/b so slow drift (cache warmth, GC pressure) hits
    # both arms equally.
    for _ in range(runs):
        TRACER.disable()
        off.append(one_cycle()["cycle_ms"])
        TRACER.enable()
        mark = TRACER.spans_recorded
        on.append(one_cycle()["cycle_ms"])
        span_count += TRACER.spans_recorded - mark
    off.sort()
    on.sort()
    off_ms = off[len(off) // 2]
    on_ms = on[len(on) // 2]
    spans_per_cycle = span_count / float(runs)

    # Deterministic per-span cost of the ENABLED recording path.
    probe_n = 20_000
    TRACER.reset()
    TRACER.enable()
    t0 = time.perf_counter()
    for _ in range(probe_n):
        with TRACER.span("obs-probe"):
            pass
    span_cost_us = (time.perf_counter() - t0) / probe_n * 1e6
    TRACER.reset()
    TRACER.enabled = was_enabled

    # Telemetry enabled-path cost: a scratch Telemetry instance (the
    # global one must not absorb bench samples) fed a representative
    # flight record + the real cache, 1024 cycles — covering 16 window
    # rolls, 16 expensive-probe/fairness samples (both on the 64-cycle
    # tier), and a node-total refresh, so the amortized probes are
    # priced in, not dodged.
    from kube_batch_tpu.obs.telemetry import Telemetry

    scratch = Telemetry(window_cycles=64, max_windows=64,
                        raw_capacity=128)
    fake_rec = {
        "e2e_ms": off_ms,
        "phases_ms": {
            "open_session": 2.0,
            "action:allocate_tpu": off_ms * 0.8,
            "close_session": 2.0,
        },
        "solver": {"placed": 0, "tasks": 0, "rounds": 1},
    }
    telem_n = 1024
    t0 = time.perf_counter()
    for _ in range(telem_n):
        scratch.observe_scheduler_cycle(fake_rec, cache=cache)
    telemetry_cost_us = (time.perf_counter() - t0) / telem_n * 1e6

    # Placement-ledger + decision-audit enabled-path cost, pinned
    # against the same <1%-of-an-idle-cycle budget: the per-pod full
    # lifecycle (arrival→placed→dispatched→applied, incl. the
    # Prometheus histogram observes), the per-record audit append, and
    # the per-cycle fixed cost an IDLE cycle actually pays
    # (begin_cycle + the telemetry p99 probe over populated sketches).
    from kube_batch_tpu.obs.latency import AuditLog, PlacementLedger

    scratch_ledger = PlacementLedger()
    lat_n = 5_000
    t0 = time.perf_counter()
    for i in range(lat_n):
        uid = f"obs-lat-{i}"
        job = f"obs-job-{i % 50}"
        scratch_ledger.note_arrival(uid, uid, job)
        scratch_ledger.note_placed(((uid, job),), {job: "q0"})
        scratch_ledger.note_dispatched((uid,))
        scratch_ledger.note_applied(uid)
    latency_pod_cost_us = (time.perf_counter() - t0) / lat_n * 1e6

    scratch_audit = AuditLog(capacity=1024)
    audit_n = 5_000
    t0 = time.perf_counter()
    for i in range(audit_n):
        scratch_audit.append({
            "action": "placed", "job": f"obs-job-{i % 50}",
            "queue": "q0", "count": 1, "kind": "periodic",
            "backend": "native", "warm": "solve", "degraded": False,
        })
    audit_append_cost_us = (time.perf_counter() - t0) / audit_n * 1e6

    cyc_n = 2_000
    t0 = time.perf_counter()
    for i in range(cyc_n):
        scratch_ledger.begin_cycle(i)
        scratch_ledger.telemetry_sample()
    latency_cycle_cost_us = (time.perf_counter() - t0) / cyc_n * 1e6

    overhead_ms = spans_per_cycle * span_cost_us / 1e3
    delta_ms = max(0.0, on_ms - off_ms)
    return {
        "latency_pod_cost_us": round(latency_pod_cost_us, 2),
        "audit_append_cost_us": round(audit_append_cost_us, 2),
        "latency_cycle_cost_us": round(latency_cycle_cost_us, 2),
        "latency_overhead_pct": (
            round(latency_cycle_cost_us / 1e3 / off_ms * 100.0, 3)
            if off_ms else 0.0
        ),
        "telemetry_cost_us": round(telemetry_cost_us, 2),
        "telemetry_overhead_pct": (
            round(telemetry_cost_us / 1e3 / off_ms * 100.0, 3)
            if off_ms else 0.0
        ),
        "idle_cycle_off_ms": round(off_ms, 3),
        "idle_cycle_on_ms": round(on_ms, 3),
        "spans_per_cycle": round(spans_per_cycle, 1),
        "span_cost_us": round(span_cost_us, 2),
        "tracer_overhead_ms": round(overhead_ms, 4),
        "tracer_overhead_pct": (
            round(overhead_ms / off_ms * 100.0, 3) if off_ms else 0.0
        ),
        "ab_delta_ms": round(delta_ms, 3),
        "ab_delta_pct": (
            round(delta_ms / off_ms * 100.0, 2) if off_ms else 0.0
        ),
        "runs": runs,
    }


def bench_quality(cache, steady_ms=None, repeats=5):
    """Placement-quality scorecard cost at the benched shape
    (obs/quality.py): a full ``compute_scorecard`` against the REAL
    benched cache (50k tasks x 5k nodes on the large config), median
    of ``repeats`` with the memo state warm, plus the amortized
    production overhead — per-card cost divided by the
    KBT_QUALITY_EVERY cadence, as a percentage of the measured warm
    steady cycle (the <1% budget the design doc quotes). The benched
    snapshot's headline density/fairness numbers ride along, so the
    committed rounds carry a packing-quality trend next to the latency
    trend."""
    from kube_batch_tpu.obs.quality import (
        DEFAULT_QUALITY_EVERY,
        compute_scorecard,
    )

    state = {}
    card = compute_scorecard(cache, state=state)  # cold: builds memos
    times = []
    for _ in range(max(1, repeats)):
        t0 = time.perf_counter()
        card = compute_scorecard(cache, state=state)
        times.append(time.perf_counter() - t0)
    times.sort()
    card_ms = times[len(times) // 2] * 1e3
    every = DEFAULT_QUALITY_EVERY
    out = {
        "card_ms": round(card_ms, 3),
        "every": every,
        "amortized_ms": round(card_ms / every, 4),
        "nodes": card["nodes"],
        "queues": card["queues"],
        "density_dom": card["density_dom"],
        "density": card["density"],
        "fairness_jain": card["fairness"]["jain"],
        "emptiable_frac": card["frag"]["emptiable_frac"],
    }
    if steady_ms:
        out["overhead_pct_of_steady"] = round(
            100.0 * (card_ms / every) / steady_ms, 3
        )
    return out


def bench_arrival_latency(quick=False, seed=23):
    """Stage-decomposed arrival→bind placement-latency percentiles
    under the high-arrival sim mixes (the ROADMAP item 2 SLI section,
    obs/latency.py): three seeded deterministic-simulator runs —
    ~0.1%-of-the-50k-headline sustained arrivals (with micro cycles
    engaged), ~1% sustained, and a 10k+-pods-per-virtual-second burst
    profile — each reporting the ledger's p50/p95/p99 per stage and
    per (queue, cycle kind).

    Latencies are VIRTUAL seconds off the sim clock, so the values are
    machine-independent and exactly reproducible: bench_compare tracks
    them with ratio semantics (no canary normalization) — a p99 climb
    here is a scheduling-delay regression, not machine drift. (On the
    virtual timeline dispatch/bind collapse to 0 — side effects settle
    within the cycle — and the solve stage carries the real solve wall
    time; the Prometheus histogram and the obs section carry the
    real-time stage split for production cycles.)"""
    from kube_batch_tpu.native import native_available
    from kube_batch_tpu.obs.latency import LEDGER
    from kube_batch_tpu.sim import SimConfig, WorkloadSpec
    from kube_batch_tpu.sim.harness import run_sim

    backend = "native" if native_available() else "auto"

    def mix(cycles, micro_every=0, period=1.0, nodes=64, **spec_kw):
        spec = WorkloadSpec(
            nodes=nodes, node_cpu_m=16000, node_mem_mi=32768,
            duration_cycles=(2, 6), **spec_kw,
        )
        report, records = run_sim(SimConfig(
            cycles=cycles, seed=seed, workload=spec, backend=backend,
            check_invariants=False, micro_every=micro_every,
            period=period,
        ))
        lat = report.latency or {}
        stages = LEDGER.stage_percentiles()
        # Carried-backlog depth off the trace records (replay-stable):
        # congestion verdicts need the SHAPE — a keeping-up scheduler's
        # series plateaus, a falling-behind one climbs monotonically.
        carried = [
            (r.get("stats") or {}).get("carried", 0)
            for r in records if r.get("type") == "cycle"
        ]
        step = max(1, len(carried) // 64)
        return {
            "cycles": cycles,
            "placements": report.placements,
            "carried_depth_max": max(carried) if carried else 0,
            "carried_depth_end": carried[-1] if carried else 0,
            "carried_depth_series": carried[::step],
            "stamped": lat.get("stamped", 0),
            "applied": lat.get("applied", 0),
            "queue_p99_s": lat.get("queue_p99_s", {}),
            "total_p99_s": (stages.get("total") or {}).get("p99_s"),
            "queue_wait_p99_s": (
                (stages.get("queue_wait") or {}).get("p99_s")
            ),
            "gang_total_p99_s": (
                (stages.get("gang_total") or {}).get("p99_s")
            ),
            "stages": stages,
            "by_queue_kind": LEDGER.percentiles(),
            "audit_records": report.audit_records,
        }

    # Mix sizes are pod-arrival equivalents of the 50k-pod headline
    # (avg gang ≈ 2.45 pods): 0.1% ≈ 50 pods/cycle sustained, 1% ≈
    # 500 sustained, burst ≈ 10.3k pods landing in ONE virtual second
    # (the 10k+ arrivals/s-equivalent spike), draining over the rest
    # of the run. Quick mode scales ~10x down — the section's shape
    # (keys, stages) is identical, only the committed large rounds'
    # numbers are the tracked trend.
    scale = 10 if quick else 1
    return {
        "sustained_0p1": mix(
            120 // (2 if quick else 1), micro_every=2,
            arrival_rate=20 / scale,
            arrival_profile="sustained", max_jobs_in_flight=512,
        ),
        "sustained_1p": mix(
            40 // (2 if quick else 1), arrival_rate=200 / scale,
            arrival_profile="sustained", max_jobs_in_flight=2048,
        ),
        "burst": mix(
            30 // (2 if quick else 1), arrival_rate=2,
            arrival_profile="burst",
            burst_every=50, burst_size=4200 // scale,
            max_jobs_in_flight=20000,
        ),
        # Congested micro steady state (r17): sim ticks ARE the micro
        # coalescing windows (period = 5 ms virtual), the periodic
        # cycle demoted to every 8th tick. sustained: 20 jobs/tick ×
        # ~2.45 pods / 5 ms ≈ 10k pod-arrivals per virtual second,
        # continuously — the p99 gate (< 10 ms, i.e. placed in the
        # arrival tick or the next) only holds if the subset-solve
        # micro path keeps pace without waiting on periodic cycles.
        # burst: 400-job storms every 100 ticks against HALF the
        # cluster (32 nodes) so each storm over-subscribes capacity —
        # a real carried backlog forms, the rank-stable subset solves
        # rotate through it, and the depth series must drain back to 0
        # between storms (carried_depth_end is a bench_compare row).
        "congested_10k": mix(
            400 // (4 if quick else 1), micro_every=8, period=0.005,
            arrival_rate=20 / scale,
            arrival_profile="sustained", max_jobs_in_flight=4096,
        ),
        "congested_burst": mix(
            300 // (3 if quick else 1), micro_every=8, period=0.005,
            nodes=32, arrival_rate=4,
            arrival_profile="burst", burst_every=100,
            burst_size=400 // scale, max_jobs_in_flight=8192,
        ),
    }


def bench_serving(quick=False, seed=29):
    """Serving-SLO section (doc/design/serving.md): the congested micro
    steady-state mix (the 50k×5k headline's pod-arrival equivalent,
    10k pod-arrivals per virtual second) with a serving deployment
    stream layered on top — annotated SLO replicas (50 ms
    arrival→bind target), replica churn, a 20% spot slice and two
    topology tiers across the node pool. Reports the latency ledger's
    per-class attainment/violations/budget burn plus the per-class
    arrival→bind p99 (serving queue vs the batch queues).

    Virtual-time values (machine-independent, exactly reproducible):
    bench_compare tracks attainment with a higher-is-better floor and
    the p99s with ratio semantics — an attainment dip or a serving-p99
    climb is a scheduling regression, not machine drift."""
    from kube_batch_tpu.native import native_available
    from kube_batch_tpu.obs.latency import LEDGER
    from kube_batch_tpu.sim import SimConfig, WorkloadSpec
    from kube_batch_tpu.sim.harness import run_sim

    backend = "native" if native_available() else "auto"
    scale = 10 if quick else 1
    cycles = 400 // (4 if quick else 1)
    spec = WorkloadSpec(
        nodes=64, node_cpu_m=16000, node_mem_mi=32768,
        duration_cycles=(2, 6),
        arrival_rate=20 / scale, arrival_profile="sustained",
        max_jobs_in_flight=4096,
        serving_rate=2 / scale, serving_slo_s=0.05,
        serving_churn=0.05, reserved_frac=0.8, node_tiers=2,
    )
    report, _records = run_sim(SimConfig(
        cycles=cycles, seed=seed, workload=spec, backend=backend,
        check_invariants=False, micro_every=8, period=0.005,
    ))
    lat = report.latency or {}
    serving = lat.get("serving") or {}
    # Per-class arrival→bind p99 off the per-queue sketches (serving
    # jobs land on the dedicated "serving" queue, batch on the rest).
    # 0.0 is the expected healthy value at this shape — every placement
    # lands inside its arrival tick on the virtual clock — so the
    # bench_compare ratio rows gate any climb OFF zero.
    per_queue = {"serving": 0.0, "batch": 0.0}
    for queue, kinds in LEDGER.percentiles().items():
        cls = "serving" if queue == "serving" else "batch"
        for stages_of_kind in kinds.values():
            total = stages_of_kind.get("total") or {}
            p99 = total.get("p99_s")
            if p99 is not None and p99 > per_queue[cls]:
                per_queue[cls] = p99
    stages = LEDGER.stage_percentiles()
    return {
        "cycles": cycles,
        "placements": report.placements,
        "attainment_pct": serving.get("attainment_pct"),
        "violations": serving.get("violations"),
        "budget_burn": serving.get("budget_burn"),
        "classes": serving.get("classes", {}),
        "serving_bind_p99_s": per_queue["serving"],
        "batch_bind_p99_s": per_queue["batch"],
        "total_p99_s": (stages.get("total") or {}).get("p99_s"),
    }


def bench_device_cache(cfg="small", seed=0):
    """Device-resident snapshot pack across cold/steady/delta cycles:
    the per-field reuse/patch/upload stats (solver/device_cache.py) for
    the bench JSON. Always exercises the DEVICE pack path (tensorize
    device=True) regardless of how allocate_tpu routes the solve, so
    even a CPU-fallback artifact carries patched-row/bytes-shipped
    evidence for the code in the tree; on a real accelerator run the
    same stats additionally land in every cycle's ``device_*`` keys."""
    from kube_batch_tpu.solver.device_cache import last_pack_stats

    n_tasks, n_nodes, n_queues, n_groups = CONFIGS[cfg]
    cache = build_cluster(n_tasks, n_nodes, n_queues, n_groups, seed)
    tiers = make_tiers(*TIERS_ARGS)
    out = {"config": cfg}

    def pack_summary(t_ms):
        keys = ("uploads", "patches", "reuses", "rows_patched",
                "bytes_shipped", "bytes_total")
        s = {k: last_pack_stats.get(k, 0) for k in keys}
        s["tensorize_ms"] = round(t_ms, 1)
        return s

    def one(label, ssn):
        t0 = time.perf_counter()
        inputs, _ctx = tensorize(ssn)
        out[label] = pack_summary((time.perf_counter() - t0) * 1e3)
        return inputs

    ssn = open_session(cache, tiers)
    one("cold", ssn)      # every field uploads (cold cache)
    one("steady", ssn)    # nothing changed: zero uploads, zero bytes
    # Small churn: allocate ONE whole gang through the session (a full
    # gang is JobReady, so its binds actually reach the cache mirror —
    # partial allocations are session-only and would vanish at the next
    # snapshot), packed onto a couple of nodes so the next pack patches
    # a couple of node rows.
    job = min(
        (j for j in ssn.jobs.values()
         if j.task_status_index.get(TaskStatus.PENDING)),
        key=lambda j: (len(j.task_status_index[TaskStatus.PENDING]),
                       j.uid),
    )
    gang = sorted(
        job.task_status_index[TaskStatus.PENDING].values(),
        key=lambda t: t.uid,
    )
    nodes = sorted(ssn.nodes)[: max(8, n_nodes // 10)]
    ssn.allocate_batch([
        (t, nodes[i % len(nodes)]) for i, t in enumerate(gang)
    ])
    cache.wait_for_side_effects()
    cache.wait_for_bookkeeping()
    close_session(ssn)
    ssn = open_session(cache, tiers)
    one("delta", ssn)     # dirty node rows patch; untouched fields reuse
    close_session(ssn)
    cache.shutdown()
    return out


def _select_scale_ab(mask, task_req, node_idle, eps, k, seed=0):
    """Selection device-vs-host A/B at a scale point. Four timed runs:

    - ``select_ms_host``: host NumPy full pass (cold — what every
      committed round before the device engine measured as
      ``select_ms``);
    - ``select_ms_device``: device-resident full pass, cold — engine
      allocation + every key row built on device + top-K extraction
      (includes first-use jit compiles, like any cold jax number here);
    - ``select_ms_host_warm`` / ``select_ms_device_warm``: the same
      ~1% node churn pushed through both paths with their cross-cycle
      caches warm — the steady-state per-cycle cost a scheduler
      actually pays (both recompute only churned columns);
    - ``select_device_parity``: 1 iff the device slabs were bit-equal
      to the host slabs on BOTH the cold and the churned-warm run.

    ``select_ms`` (the headline the committed rounds track) is the
    steady-state cost of the engaged path: the churned-warm device
    pass when the device path engaged (the engine and jits live for
    the process — cold is a once-per-process cost kept in
    ``select_ms_device``), else the host cold pass (``select_path``
    records which). Returns ``(keys, host_cold_cs)`` — the host
    CandidateSet feeds the solve stage unchanged."""
    from kube_batch_tpu.solver import select_device
    from kube_batch_tpu.solver.topk import select_candidates

    N = node_idle.shape[0]
    zeros = np.zeros_like(node_idle)
    zc = np.zeros(N, np.int32)
    ids = np.arange(N, dtype=np.int64)
    vers = np.zeros(N, np.int64)

    class _Holder:  # anchor for the cross-cycle selection caches
        pass

    # Separate holders per path: the host leg's _SelectionCache rows
    # are GBs at XL shapes and the device path never reads them — one
    # shared holder would just couple the legs through the allocator.
    holder_host = _Holder()
    holder_dev = _Holder()

    def run(idle, vers_, state, holder):
        t0 = time.perf_counter()
        cs_ = select_candidates(
            mask, {}, task_req, task_req, idle, idle, zeros, zc, zc,
            eps, 1.0, 1.0, k, cache_holder=holder,
            node_fp=(ids, vers_, None), device_state=state,
        )
        return round((time.perf_counter() - t0) * 1e3, 1), cs_

    host_ms, cs = run(node_idle, vers, None, holder_host)
    out = {"select_ms": host_ms, "select_ms_host": host_ms,
           "select_path": "host"}
    if cs is None or not select_device.device_select_enabled():
        if cs is not None:
            out["select_path"] = "host:env-disabled"
        return out, cs

    state = select_device.standalone_state(
        node_idle, node_idle, zc, zc, mask.node_ok, mask.group_rows
    )
    dev_ms, dev_cs = run(node_idle, vers, state, holder_dev)
    if dev_cs is None or dev_cs.stats.get("select_path") != "device":
        out["select_path"] = (
            dev_cs.stats.get("select_path", "host")
            if dev_cs is not None else "host"
        )
        return out, cs
    parity = int(
        (dev_cs.cand_idx == cs.cand_idx).all()
        and (dev_cs.cand_info == cs.cand_info).all()
        and (dev_cs.task_cand == cs.task_cand).all()
    )

    # Churned warm cycle: ~1% of nodes lose idle capacity. Production
    # re-places the node stacks through device_cache.pack_partial;
    # standalone mode re-uploads them and carries the engine (resident
    # key matrix + row digests) across, which is the same residency
    # contract.
    rng = np.random.RandomState(seed + 1)
    churn = rng.choice(N, size=max(N // 100, 1), replace=False)
    idle2 = node_idle.copy()
    idle2[churn] = np.maximum(idle2[churn] - 500.0, 0.0)
    vers2 = vers.copy()
    vers2[churn] += 1
    state2 = select_device.standalone_state(
        idle2, idle2, zc, zc, mask.node_ok, mask.group_rows
    )
    state2._engine = state.engine()
    # Device warm before host warm: the warm device pass is the
    # HEADLINE number, and on a burst-throttled single-core box the
    # last leg of a long process pays decayed CPU — the order must not
    # systematically tax the number the committed rounds track.
    dev_warm_ms, dev_warm_cs = run(idle2, vers2, state2, holder_dev)
    host_warm_ms, host_warm_cs = run(idle2, vers2, None, holder_host)
    if (
        host_warm_cs is not None and dev_warm_cs is not None
        and dev_warm_cs.stats.get("select_path") == "device"
    ):
        parity = int(parity and (
            (dev_warm_cs.cand_idx == host_warm_cs.cand_idx).all()
            and (dev_warm_cs.cand_info == host_warm_cs.cand_info).all()
        ))
        out.update(
            select_ms_host_warm=host_warm_ms,
            select_ms_device_warm=dev_warm_ms,
            sel_cache_hits_warm=int(
                dev_warm_cs.stats.get("sel_cache_hits", 0)
            ),
        )
    # Headline = the steady-state per-cycle cost of the engaged path:
    # selection runs EVERY cycle against a process-lifetime engine, so
    # the churned-warm device pass is what a scheduler pays; the cold
    # pass (engine build + first-use jit compiles, once per process)
    # stays reported as select_ms_device. The speedup ratio divides
    # the committed-history select_ms semantic (host cold full pass)
    # by the new steady-state headline.
    steady_ms = out.get("select_ms_device_warm", dev_ms)
    out.update(
        select_ms=steady_ms,
        select_ms_device=dev_ms,
        select_path="device",
        select_device_parity=parity,
        select_device_speedup=round(host_ms / max(steady_ms, 1e-6), 1),
    )
    return out, cs


def bench_sparse_scale(shape="200000x20000", seed=0, wide_mix=False):
    """Sparse-only scale point: shapes where the DENSE solver is
    arithmetically infeasible — at 200k x 20k one [T, N] f32 score
    matrix is 16 GB, at 1M x 100k it is 400 GB (and the solver
    materializes mask + score + key per round), so there is nothing to
    A/B against; the point of this benchmark is that a cycle completes
    AT ALL.

    Solver inputs are built synthetically at the array level: a 200k-pod
    cache/session build measures Python object churn for minutes and
    multiple GB before the solver ever runs, while the solver consumes
    identical columnar arrays either way (the 50k headline config covers
    the full-pipeline path). Candidate selection runs the REAL topk pass
    — A/B'd device-vs-host with a bit-equality check and a churned-warm
    leg (see :func:`_select_scale_ab`) — and the solve runs the REAL
    sparse backend (native when available, else the jitted JAX sparse
    kernels).

    ``wide_mix`` draws requests from a 64x32-value grid instead of the
    5x5 one (the 1M x 100k point): a million-pod cluster has thousands
    of distinct pod shapes, and class diversity is what sizes the slab
    union — with 25 classes x K=64 only 1 600 nodes are ever candidates
    and the refill stage would drain the other ~97% of tasks at full-N
    cost, which is a degenerate workload, not a scale measurement. The
    200k point keeps the original mix so its committed numbers stay
    comparable."""
    from kube_batch_tpu.solver.kernels import SolverInputs
    from kube_batch_tpu.solver.masks import CombinedMask
    from kube_batch_tpu.solver.topk import topk_config

    T, N = (int(x) for x in shape.lower().split("x"))
    rng = np.random.RandomState(seed)
    R = 2
    if wide_mix:
        # ~66% cluster utilisation at 1M x 100k (32-cpu/128Gi nodes):
        # the scale point measures solver throughput, not a thundering
        # -herd overload (that regime is the sim's job).
        cpu_mix = np.linspace(250, 4000, 64).round()
        mem_mix = np.linspace(256, 16384, 32).round()
    else:
        cpu_mix = [250, 500, 1000, 2000, 4000]
        mem_mix = [256, 512, 1024, 4096, 8192]
    task_req = np.c_[
        rng.choice(cpu_mix, T),
        rng.choice(mem_mix, T),
    ].astype(np.float32)
    node_idle = np.tile(
        np.asarray([32000.0, 128 * 1024.0], np.float32), (N, 1)
    )
    eps = np.asarray([10.0, 10.0], np.float32)
    mask = CombinedMask(
        node_ok=np.ones(N, bool),
        task_group=np.zeros(T, np.int32),
        group_rows=np.ones((1, N), bool),
        pair_idx=np.zeros((0,), np.int32),
        pair_rows=np.zeros((0, N), bool),
    )
    tk = topk_config(T, N)
    k = tk.k if tk.enabled else 64
    sel, cs = _select_scale_ab(mask, task_req, node_idle, eps, k, seed)
    out = {
        "shape": f"{T}x{N}",
        "k": int(k),
        **sel,
        "dense_score_bytes": int(T) * int(N) * 4,
        "dense_documented_infeasible": True,
    }
    if cs is None:
        out["error"] = "selection aborted (class budget)"
        return out
    out.update({
        key: cs.stats[key]
        for key in ("classes", "slab_bytes", "truncated_classes")
    })
    inputs = SolverInputs(
        task_req=task_req, task_fit=task_req,
        task_rank=np.arange(T, dtype=np.int32),
        task_job=(np.arange(T) // 10).astype(np.int32),
        task_queue=np.zeros(T, np.int32),
        task_valid=np.ones(T, bool),
        task_group=np.zeros(T, np.int32),
        node_feas=np.ones(N, bool),
        group_feas=np.ones((1, N), bool),
        pair_idx=np.zeros((0,), np.int32),
        pair_feas=np.zeros((0, N), bool),
        score_idx=np.zeros((0,), np.int32),
        score_rows=np.zeros((0, N), np.float32),
        node_idle=node_idle,
        node_releasing=np.zeros_like(node_idle),
        node_cap=node_idle,
        node_task_count=np.zeros(N, np.int32),
        node_max_tasks=np.zeros(N, np.int32),
        queue_deserved=np.full((1, R), np.inf, np.float32),
        queue_allocated=np.zeros((1, R), np.float32),
        eps=eps,
        lr_weight=np.float32(1.0),
        br_weight=np.float32(1.0),
        task_cand=cs.task_cand, cand_idx=cs.cand_idx,
        cand_static=cs.cand_static, cand_info=cs.cand_info,
    )
    native_ok = False
    try:
        from kube_batch_tpu.native import last_solve_stats, solve_native

        t0 = time.perf_counter()
        _assigned, placed = solve_native(inputs)
        native_ok = True
    except Exception:  # NativeUnavailable / no toolchain: jax fallback
        native_ok = False
    if native_ok:
        out.update(
            solve_ms=round((time.perf_counter() - t0) * 1e3, 1),
            backend="native",
            placed=int(placed),
            refill_rounds=int(last_solve_stats.get("refill_rounds", 0)),
            widened=int(last_solve_stats.get("widened", 0)),
        )
        return out
    import jax

    from kube_batch_tpu.solver import solve_sparse_jit

    result = jax.block_until_ready(solve_sparse_jit(inputs))  # compile
    t0 = time.perf_counter()
    result = solve_sparse_jit(inputs)
    assigned = np.asarray(result.assigned)
    out.update(
        solve_ms=round((time.perf_counter() - t0) * 1e3, 1),
        backend=f"jax-{jax.devices()[0].platform}",
        placed=int((assigned >= 0).sum()),
        refill_rounds=int(result.stages),
        refill_tasks=int(result.refills),
    )
    return out


_SHARDED_AB_SCRIPT = r"""
import json, time
import numpy as np
from kube_batch_tpu.utils.backend import force_cpu_devices
assert force_cpu_devices(%(devices)d)
import jax, jax.numpy as jnp
from kube_batch_tpu.solver import (
    default_mesh, make_inputs, pad_tasks, solve_sparse_jit,
    solve_sparse_spmd,
)
from kube_batch_tpu.solver.masks import CombinedMask
from kube_batch_tpu.solver.topk import select_candidates

T, N, K = %(tasks)d, %(nodes)d, 64
rng = np.random.RandomState(7)
R = 2
task_req = np.c_[
    rng.choice(np.linspace(250, 4000, 64).round(), T),
    rng.choice(np.linspace(256, 16384, 32).round(), T),
].astype(np.float32)
node_idle = np.tile(
    np.asarray([32000.0, 128 * 1024.0], np.float32), (N, 1)
)
eps = np.asarray([10.0, 10.0], np.float32)
mask = CombinedMask(
    node_ok=np.ones(N, bool), task_group=np.zeros(T, np.int32),
    group_rows=np.ones((1, N), bool),
    pair_idx=np.zeros((0,), np.int32),
    pair_rows=np.zeros((0, N), bool),
)
cs = select_candidates(
    mask, {}, task_req, task_req, node_idle, node_idle,
    np.zeros_like(node_idle), np.zeros(N, np.int32),
    np.zeros(N, np.int32), eps, 1.0, 1.0, K,
)
inputs = make_inputs(
    task_req=jnp.asarray(task_req), task_fit=jnp.asarray(task_req),
    task_rank=jnp.arange(T, dtype=jnp.int32),
    task_job=jnp.asarray((np.arange(T) // 10).astype(np.int32)),
    task_queue=jnp.zeros(T, jnp.int32),
    node_idle=jnp.asarray(node_idle),
    node_releasing=jnp.zeros((N, R), jnp.float32),
    node_cap=jnp.asarray(node_idle),
    node_task_count=jnp.zeros(N, jnp.int32),
    node_max_tasks=jnp.zeros(N, jnp.int32),
    queue_deserved=jnp.full((1, R), jnp.inf, dtype=jnp.float32),
    queue_allocated=jnp.zeros((1, R), jnp.float32),
    eps=jnp.asarray(eps),
    lr_weight=jnp.asarray(1.0, jnp.float32),
    br_weight=jnp.asarray(1.0, jnp.float32),
    task_cand=jnp.asarray(cs.task_cand),
    cand_idx=jnp.asarray(cs.cand_idx),
    cand_static=jnp.asarray(cs.cand_static),
    cand_info=jnp.asarray(cs.cand_info),
)
mesh = default_mesh()
out = {"devices": mesh.size, "shape": f"{T}x{N}", "k": K}

def timed(fn, *a, **kw):
    r = jax.block_until_ready(fn(*a, **kw))  # compile
    t0 = time.perf_counter()
    r = fn(*a, **kw)
    assigned = np.asarray(r.assigned)
    return (time.perf_counter() - t0) * 1e3, assigned

single_ms, single_a = timed(solve_sparse_jit, inputs)
padded = pad_tasks(inputs, mesh.size)
flat_ms, flat_a = timed(solve_sparse_spmd, padded, mesh)
# Static byte accounting of the commit collective this dispatch ran
# (delta-packed exchange vs the legacy full-state broadcast).
from kube_batch_tpu.solver import spmd as _spmd
out.update({k: int(v) for k, v in _spmd.last_commit_stats.items()})
two_ms, two_a = timed(
    solve_sparse_spmd, padded, mesh, two_level=True
)
out.update(
    single_ms=round(single_ms, 1),
    flat_ms=round(flat_ms, 1),
    two_level_ms=round(two_ms, 1),
    parity=int((single_a == flat_a[:T]).all()),
    placed=int((single_a >= 0).sum()),
    two_level_placed=int((two_a[:T] >= 0).sum()),
)
print("SHARDED_AB " + json.dumps(out))
"""


def bench_sharded_vs_single(tasks=65536, nodes=4096, devices=4):
    """Sharded-vs-single sparse A/B on a forced 4-device host mesh, in
    a SUBPROCESS (the host device count is frozen at backend init, and
    the main bench must keep its real topology). On an oversubscribed
    CPU mesh the shards serialize, so the honest target here is
    ``parity == 1`` (flat bit-equal to single) and completion of both
    sharded modes, not wall-clock speedup — the timings exist so
    committed rounds track the collective overhead trend."""
    import subprocess
    import sys

    env = dict(os.environ)
    env.update({"PALLAS_AXON_POOL_IPS": "", "JAX_PLATFORMS": "cpu"})
    env.pop("XLA_FLAGS", None)  # subprocess owns its device count
    script = _SHARDED_AB_SCRIPT % {
        "devices": devices, "tasks": tasks, "nodes": nodes,
    }
    proc = subprocess.run(
        [sys.executable, "-c", script], capture_output=True, text=True,
        timeout=1800, env=env,
        cwd=os.path.dirname(os.path.abspath(__file__)),
    )
    for line in proc.stdout.splitlines():
        if line.startswith("SHARDED_AB "):
            return json.loads(line[len("SHARDED_AB "):])
    return {
        "error": f"subprocess exit {proc.returncode}",
        "stderr": proc.stderr[-2000:],
    }


_TWOLEVEL_QUALITY_SCRIPT = r"""
import json
from kube_batch_tpu.utils.backend import force_cpu_devices
assert force_cpu_devices(%(devices)d)
from kube_batch_tpu import metrics
from kube_batch_tpu.sim import SimConfig, WorkloadSpec
from kube_batch_tpu.sim.harness import run_sim

report, _ = run_sim(SimConfig(
    cycles=%(cycles)d, seed=%(seed)d, backend="sparse", topk=8,
    workload=WorkloadSpec(
        nodes=%(nodes)d, arrival_rate=4.0, max_jobs_in_flight=128,
    ),
    check_invariants=True,
))
out = {
    "placements": int(report.placements),
    "violations": len(report.violations),
    "cycle_errors": int(report.cycle_errors),
    "bind_failures": int(report.bind_failures),
    "jobs_completed": int(report.jobs_completed),
    "sharded_solves": int(metrics.solver_sparse_sharded.total()),
}
print("TWOLEVEL_Q " + json.dumps(out))
"""


def bench_twolevel_quality(devices=4, cycles=60, seed=9, nodes=32):
    """Sim-based placement-quality study for the two-level (per-rack)
    sharded solve vs the bit-equal flat mode: the same seeded workload
    runs through the FULL production cycle on a forced 4-device host
    mesh with ``KBT_SPARSE_SHARD_MODE`` pinning each mode, and the
    placement outcomes are compared. Two-level is quality-approximate
    by design (each rack solves against only its own node block before
    the psum reconcile), so the numbers that matter are the placement
    delta and that the invariant checker stays clean in BOTH modes —
    the default-policy decision in doc/design/sparse-candidate-solver.md
    cites this study. Subprocesses for the same reason as
    :func:`bench_sharded_vs_single` (host device count is frozen at
    backend init)."""
    import subprocess
    import sys

    def one(mode):
        env = dict(os.environ)
        env.update({
            "PALLAS_AXON_POOL_IPS": "", "JAX_PLATFORMS": "cpu",
            "KBT_SOLVER": "jax", "KBT_SPARSE_SHARD_MODE": mode,
        })
        env.pop("XLA_FLAGS", None)  # subprocess owns its device count
        script = _TWOLEVEL_QUALITY_SCRIPT % {
            "devices": devices, "cycles": cycles, "seed": seed,
            "nodes": nodes,
        }
        proc = subprocess.run(
            [sys.executable, "-c", script], capture_output=True,
            text=True, timeout=1800, env=env,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
        for line in proc.stdout.splitlines():
            if line.startswith("TWOLEVEL_Q "):
                return json.loads(line[len("TWOLEVEL_Q "):])
        return {
            "error": f"subprocess exit {proc.returncode}",
            "stderr": proc.stderr[-2000:],
        }

    flat = one("flat")
    two = one("two-level")
    out = {
        "devices": devices, "cycles": cycles, "nodes": nodes,
        "flat": flat, "two_level": two,
    }
    if flat.get("placements"):
        out["placements_delta_pct"] = round(
            100.0 * (two.get("placements", 0) - flat["placements"])
            / flat["placements"], 2,
        )
    return out


def bench_integrity(cfg="large", seed=0):
    """Cluster-truth anti-entropy + post-solve validation cost at the
    headline shape (doc/design/robustness.md, event-stream hardening):

    - ``sweep_cold_ms``: first sweep (builds the per-object digest
      caches);
    - ``sweep_steady_ms``: median consistent-mirror sweep — the cost a
      production cycle amortizes over KBT_ANTIENTROPY_EVERY;
    - ``sweep_divergent_ms``: sweep over a 1%-divergent mirror (watch
      detached, 1% of pods bound + a slice deleted behind the cache's
      back), with detected/repaired counts asserted;
    - ``validation_ms``: post-solve validation of a full placement
      vector (O(placements) mask + capacity recheck), plus the
      tampered-vector rejection cost and ``validation_pct_of_steady``
      vs the steady cycle — the <1% budget the tracer overhead is also
      pinned against.
    """
    from kube_batch_tpu.cluster import InProcessCluster
    from kube_batch_tpu.solver.validate import validate_placements

    n_tasks, n_nodes, n_queues, n_groups = CONFIGS[cfg]
    rng = np.random.RandomState(seed)
    cluster = InProcessCluster(simulate_kubelet=False)
    cache = SchedulerCache(
        cluster=cluster,
        binder=FakeBinder(),
        evictor=FakeEvictor(),
        status_updater=FakeStatusUpdater(),
        volume_binder=FakeVolumeBinder(),
    )
    for q in range(n_queues):
        cluster.create_queue(build_queue(f"q{q}", weight=q + 1))
    for j in range(n_nodes):
        cluster.create_node(build_node(
            f"n{j}", build_resource_list(cpu="32", memory="128Gi", pods=110)
        ))
    per_group = n_tasks // n_groups
    cpus = rng.choice([250, 500, 1000, 2000, 4000], size=n_tasks)
    mems = rng.choice([256, 512, 1024, 4096, 8192], size=n_tasks)
    t = 0
    pods = []
    for g in range(n_groups):
        cluster.create_pod_group(build_pod_group(
            f"pg{g}", namespace="bench",
            min_member=int(rng.randint(1, per_group + 1)),
            queue=f"q{g % n_queues}",
        ))
        for i in range(per_group):
            pod = build_pod(
                "bench", f"pg{g}-p{i}", "", PodPhase.PENDING,
                build_resource_list(
                    cpu=f"{int(cpus[t])}m", memory=f"{int(mems[t])}Mi"
                ),
                group_name=f"pg{g}",
            )
            cluster.create_pod(pod)
            pods.append(pod)
            t += 1
    cache.start_ingest()

    ae = cache.antientropy
    t0 = time.perf_counter()
    ae.sweep()
    sweep_cold_ms = (time.perf_counter() - t0) * 1e3
    steady = []
    for _ in range(3):
        t0 = time.perf_counter()
        rep = ae.sweep()
        steady.append((time.perf_counter() - t0) * 1e3)
    assert not rep["detected"], rep
    sweep_steady_ms = sorted(steady)[1]
    # Churned variant: one benign cluster write moves the event rv, so
    # the sweep pays the full truth listing + O(pods) witness loop —
    # what a real 1%-churn steady state pays every
    # KBT_ANTIENTROPY_EVERY cycles (the rv-unchanged shortcut above is
    # the idle-cluster case).
    churned = []
    for _ in range(3):
        cluster.update("Pod", pods[0])
        t0 = time.perf_counter()
        rep = ae.sweep()
        churned.append((time.perf_counter() - t0) * 1e3)
    assert not rep["detected"], rep
    sweep_churned_ms = sorted(churned)[1]

    # 1% divergence injected behind the cache's back: the watch is
    # detached, a slice of pods is bound (missed-bind) and a smaller
    # slice deleted (phantom-task), then the sweep must find + repair
    # every one of them through the stamping handlers.
    cluster.remove_watch(cache._on_watch_event)
    n_div = max(2, n_tasks // 100)
    picks = rng.choice(len(pods), size=n_div, replace=False)
    for k, idx in enumerate(picks):
        pod = pods[int(idx)]
        if k % 8 == 0:
            cluster.delete_pod(pod)
        else:
            try:
                cluster.bind_pod(pod, f"n{int(idx) % n_nodes}")
            except ValueError:
                pass  # already bound by an earlier pick
    cluster.add_watch(cache._on_watch_event)
    t0 = time.perf_counter()
    div = ae.sweep(budget=None)
    sweep_divergent_ms = (time.perf_counter() - t0) * 1e3
    detected = sum(div["detected"].values())
    repaired = sum(div["repaired"].values())

    # Post-solve validation cost on a FULL placement vector.
    ssn = open_session(cache, make_tiers(*TIERS_ARGS))
    try:
        inputs, ctx = tensorize(ssn, device=False)
        T, N = len(ctx.tasks), len(ctx.nodes)
        a = (np.arange(T) % N).astype(np.int64)
        times = []
        for _ in range(5):
            t0 = time.perf_counter()
            bad, reasons = validate_placements(ctx, a)
            times.append((time.perf_counter() - t0) * 1e3)
        validation_ms = sorted(times)[2]
        # Steady-churn-sized vector (1% of tasks placed — what a warm
        # steady cycle actually proposes): the per-STEADY-cycle
        # validation cost the <1% pin is quoted against; the full
        # vector above is the cold-burst worst case.
        a_steady = np.full(T, -1, dtype=np.int64)
        n_churn = max(1, T // 100)
        a_steady[:n_churn] = a[:n_churn]
        times = []
        for _ in range(5):
            t0 = time.perf_counter()
            validate_placements(ctx, a_steady)
            times.append((time.perf_counter() - t0) * 1e3)
        validation_steady_ms = sorted(times)[2]
        tampered = a.copy()
        tampered[: min(16, T)] = 2**30
        t0 = time.perf_counter()
        bad_t, reasons_t = validate_placements(ctx, tampered)
        validation_reject_ms = (time.perf_counter() - t0) * 1e3
        assert reasons_t.get("bad-index", 0) >= 1, reasons_t
    finally:
        close_session(ssn)
    cache.shutdown()

    return {
        "config": cfg,
        "pods": n_tasks,
        "nodes": n_nodes,
        "sweep_cold_ms": round(sweep_cold_ms, 2),
        "sweep_steady_ms": round(sweep_steady_ms, 2),
        "sweep_churned_ms": round(sweep_churned_ms, 2),
        "sweep_divergent_ms": round(sweep_divergent_ms, 2),
        "divergence_injected": int(n_div),
        "divergence_detected": int(detected),
        "divergence_repaired": int(repaired),
        "validation_ms": round(validation_ms, 3),
        "validation_steady_ms": round(validation_steady_ms, 3),
        "validation_reject_ms": round(validation_reject_ms, 3),
    }


def bench_sim(cycles=80, seed=11):
    """Deterministic-simulator throughput: seeded fault run through the
    full production cycle (virtual clock, so the measured time is pure
    scheduling+churn work), once with the invariant checker and once
    without — the checker's overhead must stay a small fraction of the
    cycle or long-horizon CI runs get expensive."""
    from kube_batch_tpu.native import native_available
    from kube_batch_tpu.sim import SimConfig, WorkloadSpec
    from kube_batch_tpu.sim.harness import run_sim

    backend = "native" if native_available() else "auto"

    def one(check):
        report, _ = run_sim(SimConfig(
            cycles=cycles,
            seed=seed,
            faults="bind:0.05,node-flap:0.02",
            workload=WorkloadSpec(nodes=12),
            backend=backend,
            check_invariants=check,
        ))
        return report

    checked = one(True)
    unchecked = one(False)
    out = {
        "cycles": cycles,
        "backend": backend,
        "placements": checked.placements,
        "violations": len(checked.violations),
        "cycles_per_sec": round(checked.cycles_per_sec, 1),
        "cycles_per_sec_nocheck": round(unchecked.cycles_per_sec, 1),
        "invariant_check_ms_per_cycle": round(
            checked.check_seconds / cycles * 1e3, 3
        ),
        "invariant_check_overhead_pct": round(
            100.0 * checked.check_seconds
            / max(checked.wall_seconds, 1e-9), 1
        ),
    }
    return out


def bench_recovery(cfg="large", seed=0):
    """Cold-takeover failover recovery at the benched shape
    (doc/design/robustness.md, failover section): a predecessor died
    mid-bind-drain leaving a populated cluster + a bind-intent journal
    with every classification class represented; measure what a
    successor pays before it can schedule — fresh-cache ingest of the
    whole cluster, the journal scan + reconcile (incl. gang re-drives
    and one eviction), and its first post-recovery scheduling cycle."""
    from kube_batch_tpu.api.objects import DEFAULT_SCHEDULER_NAME
    from kube_batch_tpu.cache.recovery import reconcile_journal
    from kube_batch_tpu.cluster import InProcessCluster

    n_tasks, n_nodes, n_queues, n_groups = CONFIGS[cfg]
    rng = np.random.RandomState(seed)
    cluster = InProcessCluster(simulate_kubelet=True)
    for q in range(n_queues):
        cluster.create_queue(build_queue(f"q{q}", weight=q + 1))
    for j in range(n_nodes):
        cluster.create_node(build_node(
            f"n{j}", build_resource_list(cpu="32", memory="128Gi", pods=110)
        ))
    per_group = n_tasks // n_groups
    # ~1/16 of the gangs were mid-dispatch at the crash; the rest are
    # the predecessor's steady-state placements (bound + Running).
    inflight_from = n_groups - max(2, n_groups // 16)
    cpus = rng.choice([250, 500, 1000, 2000], size=n_tasks)
    mems = rng.choice([256, 512, 1024, 4096], size=n_tasks)
    t = 0
    journaled = 0
    intents = []
    for g in range(n_groups):
        inflight = g >= inflight_from
        # The last in-flight gang targets a node that died with the
        # leader — unrepairable, recovery must evict its partial
        # placement (the all-or-nothing arm).
        node_gone = inflight and g == n_groups - 1
        cluster.create_pod_group(build_pod_group(
            f"pg{g}", namespace="bench",
            min_member=per_group if inflight else int(
                rng.randint(1, per_group + 1)
            ),
            queue=f"q{g % n_queues}",
        ))
        tasks = []
        for i in range(per_group):
            target = f"n{t % n_nodes}"
            pod = build_pod(
                "bench", f"pg{g}-p{i}", "",
                PodPhase.PENDING,
                build_resource_list(
                    cpu=f"{int(cpus[t])}m", memory=f"{int(mems[t])}Mi"
                ),
                group_name=f"pg{g}",
            )
            cluster.create_pod(pod)
            if not inflight:
                cluster.bind_pod(pod, target)
            else:
                lot = i % 5
                if node_gone:
                    # Half bound (to evict), half lost to a dead node.
                    if lot < 2:
                        cluster.bind_pod(pod, target)
                    else:
                        target = "nGONE"
                elif lot < 2:
                    cluster.bind_pod(pod, target)  # applied, marked
                elif lot == 2:
                    cluster.bind_pod(pod, target)  # applied, mark lost
                # lot > 2: lost — recovery re-drives to complete
                tasks.append({
                    "uid": pod.uid, "pod": f"bench/{pod.name}",
                    "node": target, "job": f"bench/pg{g}",
                    "mark": "applied" if lot < 2 else None,
                })
            t += 1
        if tasks:
            journaled += len(tasks)
            seq = cluster.append_bind_intent({
                "leader": "bench-dead-leader",
                "tasks": [
                    {k: v for k, v in task.items() if k != "mark"}
                    for task in tasks
                ],
                "gangs": {f"bench/pg{g}": per_group},
            })
            intents.append(seq)
            for task in tasks:
                if task["mark"]:
                    cluster.mark_bind_intent(seq, task["uid"], task["mark"])

    # The successor: fresh cache, full ingest, reconcile, first cycle.
    t0 = time.perf_counter()
    cache = SchedulerCache(
        cluster=cluster, scheduler_name=DEFAULT_SCHEDULER_NAME,
        default_queue="q0",
    )
    cache.start_ingest()
    ingest_s = time.perf_counter() - t0

    t1 = time.perf_counter()
    report = reconcile_journal(cluster, "bench-successor")
    reconcile_s = time.perf_counter() - t1
    cache.wait_for_side_effects()

    t2 = time.perf_counter()
    ssn = open_session(cache, make_tiers(*TIERS_ARGS))
    action, _ = get_action("allocate_tpu")
    action.execute(ssn)
    close_session(ssn)
    first_cycle_s = time.perf_counter() - t2
    cache.wait_for_side_effects()
    cache.shutdown()
    return {
        "shape": f"{n_tasks}x{n_nodes}",
        "intents": len(intents),
        "tasks_journaled": journaled,
        "ingest_ms": round(ingest_s * 1e3, 1),
        "reconcile_ms": round(reconcile_s * 1e3, 1),
        "first_cycle_ms": round(first_cycle_s * 1e3, 1),
        "takeover_ms": round(
            (ingest_s + reconcile_s + first_cycle_s) * 1e3, 1
        ),
        "outcomes": dict(sorted(report.outcomes.items())),
        "gangs_repaired": len(report.gangs_repaired),
        "gangs_evicted": len(report.gangs_evicted),
        "recovery_errors": report.errors,
    }


def run_smoke():
    """``bench.py --smoke`` (the `make bench-smoke` target): small
    shapes through the full production cycle with the sparse solver
    FORCED (KBT_SOLVER_TOPK defaults to 8 here so the small config
    engages it), asserting via the cycle stats that the candidate path
    actually ran — exit 4 when it silently fell back to dense."""
    os.environ.setdefault("KBT_SOLVER_TOPK", "8")
    cycle = bench_cycle("small")
    cold = cycle.get("cold", {})
    engaged = bool(cold.get("sparse_engaged"))
    print(json.dumps({
        "metric": "bench-smoke-sparse",
        "sparse_engaged": engaged,
        "sparse_k": cold.get("sparse_k"),
        "sparse_refill_rounds": cold.get("sparse_refill_rounds"),
        "cold_solve_ms": cold.get("solve_ms"),
        "backend": cold.get("backend"),
        "cycle": cycle,
    }))
    if not engaged:
        print("bench-smoke: sparse path did NOT engage", file=sys.stderr)
        sys.exit(4)
    # Steady-cycle assertion (mirror of the sparse-engaged check): the
    # cycle after a placement wave must ride the incremental tensorize —
    # a full_reason there means the wave dirtied its way past the
    # narrow-ledger patching, the exact regression the warm-start work
    # removed (ROADMAP item 1 / the retired cycle.steady.cycle_ms
    # allowlist entry).
    steady = cycle.get("steady", {})
    warm = cycle.get("steady_warm", {})
    steady_ok = (
        steady.get("tensorize_incremental", True)
        and "tensorize_full_reason" not in steady
        and warm.get("warm_engaged", False)
        and warm.get("tensorize_incremental", False)
    )
    if not steady_ok:
        print(
            "bench-smoke: steady cycle did NOT stay incremental "
            f"(steady={ {k: v for k, v in steady.items() if 'tensorize' in k} }, "
            f"warm_engaged={warm.get('warm_engaged')})",
            file=sys.stderr,
        )
        sys.exit(5)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true",
                    help="small+medium only (CI-sized)")
    ap.add_argument("--config", choices=list(CONFIGS), default=None)
    ap.add_argument("--profile", metavar="DIR", default=None,
                    help="capture a JAX profiler trace of the headline "
                         "solve into DIR (view with TensorBoard)")
    ap.add_argument(
        "--require-accelerator", action="store_true",
        default=os.environ.get("TPU_BATCH_BENCH_REQUIRE_DEVICE") == "1",
        help="fail loudly (exit 3) when no accelerator backend is "
             "reachable instead of silently benchmarking the CPU "
             "fallback (also: TPU_BATCH_BENCH_REQUIRE_DEVICE=1)",
    )
    ap.add_argument(
        "--smoke", action="store_true",
        help="sparse-path smoke (make bench-smoke): small config "
             "through the full cycle with KBT_SOLVER_TOPK forced; "
             "exit 4 unless the sparse solver engaged",
    )
    ap.add_argument(
        "--shape", default=None, metavar="TxN",
        help="extra sparse-only scale point (e.g. 200000x20000); the "
             "default large run includes 200000x20000 automatically",
    )
    ap.add_argument(
        "--shape-xl", default=None, metavar="TxN",
        help="headline sparse scale point with the wide class mix "
             "(default large run: 1000000x100000 — dense [T,N] is 400 "
             "GB there, completion itself is the result)",
    )
    ap.add_argument(
        "--trace", metavar="PATH", default=None,
        help="export one Chrome trace-event JSON of the benched "
             "production cycles to PATH (open in Perfetto)",
    )
    args = ap.parse_args()
    _ensure_live_backend(require_accelerator=args.require_accelerator)
    if args.smoke:
        run_smoke()
        return

    headline_cfg = args.config or ("medium" if args.quick else "large")

    # Python greedy action on the small config (sanity datapoint only).
    greedy_s, greedy_placed, greedy_work = bench_greedy("small")

    tpu = bench_tpu(headline_cfg)
    solve_ms = tpu["solve_s"] * 1e3

    if args.profile:
        # Profiler hook (SURVEY.md §5 tracing parity: latency histograms
        # + JAX profiler for the solver): trace one steady-state solve.
        import jax

        with jax.profiler.trace(args.profile):
            jax.block_until_ready(
                solve_sharded(tpu["inputs"], default_mesh())
            )

    # vs_baseline: measured NATIVE reference loop at the headline scale
    # (the honest Go-loop stand-in); falls back to the O(T*N)-extrapolated
    # Python greedy when no native toolchain exists.
    native = bench_native_greedy(tpu["host_inputs"])
    headline_work = CONFIGS[headline_cfg][0] * CONFIGS[headline_cfg][1]
    greedy_extrapolated_s = greedy_s * headline_work / greedy_work
    extra = {}
    if native is not None:
        native_s, native_placed = native
        speedup = native_s / tpu["solve_s"]
        extra = {
            "native_greedy_ms": round(native_s * 1e3, 1),
            "native_greedy_placed": native_placed,
            "baseline_kind": "native-greedy-measured",
        }
    else:
        speedup = greedy_extrapolated_s / tpu["solve_s"]
        extra = {"baseline_kind": "python-greedy-extrapolated"}

    import jax

    headline_ms = solve_ms
    headline_placed = tpu["placed"]
    headline_solve_s = tpu["solve_s"]
    headline_rounds = tpu["rounds"]
    if jax.devices()[0].platform == "cpu":
        # The tunneled chip is intermittent; when this run fell back to
        # CPU, point at the committed on-device evidence so a CPU
        # artifact doesn't read as "never ran on TPU". Defensive: a
        # clobbered artifact must not kill the bench after measuring.
        val_path = os.path.join(
            os.path.dirname(os.path.abspath(__file__)),
            "tpu_validation_r3.json",
        )
        try:
            with open(val_path) as f:
                val = json.load(f)
            if isinstance(val, dict):
                extra["last_tpu_validation"] = {
                    "headline_ms": val.get("headline_ms"),
                    "vs_baseline": val.get("vs_baseline"),
                    "recorded": val.get("started"),
                    "artifact": os.path.basename(val_path),
                }
        except (OSError, ValueError):
            pass
        # No accelerator: the framework's production path is the native
        # loop (allocate_tpu routes there — candidate-sparsified when
        # the snapshot carries slabs), so THAT is the honest headline;
        # the batched-kernel CPU time is kept as a side metric.
        masked = bench_native_masked(tpu["host_inputs"])
        if masked is not None:
            masked_s, masked_placed = masked
            headline_ms = masked_s * 1e3
            headline_placed = masked_placed
            headline_solve_s = masked_s
            headline_rounds = 1  # sequential loop, not the JAX rounds
            extra["jax_solve_cpu_ms"] = round(solve_ms, 1)
            extra["jax_solver_rounds"] = tpu["rounds"]
            extra["solver_path"] = "native-masked-cpu-fallback"
            from kube_batch_tpu.native.greedy import (
                last_solve_stats as _nstats,
            )

            if _nstats.get("sparse"):
                extra["solver_path"] = "native-sparse-cpu-fallback"
                tpu["sparse"]["native"] = {
                    key: _nstats.get(key, 0)
                    for key in ("refill_rounds", "fallback_scans",
                                "widened", "classes", "k")
                }
                # Dense A/B on the SAME snapshot (slabs stripped): the
                # direct evidence for the sparse speedup, in-artifact.
                dense_in = tpu["host_inputs"]._replace(
                    task_cand=None, cand_idx=None,
                    cand_static=None, cand_info=None,
                )
                dense_masked = bench_native_masked(dense_in)
                if dense_masked is not None:
                    extra["native_masked_dense_ms"] = round(
                        dense_masked[0] * 1e3, 1
                    )
                    extra["sparse_vs_dense_native"] = round(
                        dense_masked[0] / masked_s, 2
                    )
            # Speedup must compare against the value actually reported:
            # native baseline when measured, else the extrapolated greedy
            # vs the headline (NOT the JAX solve the headline replaced).
            if native is not None:
                speedup = native[0] / masked_s
            else:
                speedup = greedy_extrapolated_s / masked_s

    # Full production cycles (open+tensorize+solve+apply+close) at the
    # headline scale: cold burst, unchanged steady state, 1%-delta arrival.
    # Guarded: a crash/hang here must not lose the already-measured headline
    # (round-1 lesson — a bench that dies records nothing).
    try:
        cycle = bench_cycle(
            headline_cfg, cache=tpu["cache"], trace_path=args.trace,
            measure_obs=True,
        )
    except Exception as exc:  # pragma: no cover - defensive
        cycle = {"error": f"{type(exc).__name__}: {exc}"}
    obs = cycle.pop("obs", None) if isinstance(cycle, dict) else None
    quality = (
        cycle.pop("quality", None) if isinstance(cycle, dict) else None
    )

    # Device-resident snapshot pack stats (small config: the mechanics,
    # not the scale — the headline cycles carry device_* keys whenever
    # the jax path solved them). Guarded like the cycles.
    try:
        device_cache = bench_device_cache("small")
    except Exception as exc:  # pragma: no cover - defensive
        device_cache = {"error": f"{type(exc).__name__}: {exc}"}

    # Sparse-only scale point: shapes the dense path cannot touch. Part
    # of the default large run; --shape overrides. Guarded — an OOM or
    # toolchain failure here must not lose the headline.
    sparse_scale = None
    scale_shape = args.shape or (
        "200000x20000" if headline_cfg == "large" else None
    )
    if scale_shape:
        try:
            sparse_scale = bench_sparse_scale(scale_shape)
        except Exception as exc:  # pragma: no cover - defensive
            sparse_scale = {"error": f"{type(exc).__name__}: {exc}"}

    # Headline raw-scale point (1M x 100k, wide class mix) + the
    # sharded-vs-single sparse A/B (subprocess, forced 4-device host
    # mesh). Both guarded — an OOM or subprocess failure must not lose
    # the rest of the run.
    sparse_scale_xl = None
    xl_shape = args.shape_xl or (
        "1000000x100000" if headline_cfg == "large" else None
    )
    if xl_shape:
        try:
            sparse_scale_xl = bench_sparse_scale(xl_shape, wide_mix=True)
        except Exception as exc:  # pragma: no cover - defensive
            sparse_scale_xl = {"error": f"{type(exc).__name__}: {exc}"}
    sharded_vs_single = None
    twolevel_quality = None
    if headline_cfg == "large":
        try:
            sharded_vs_single = bench_sharded_vs_single()
        except Exception as exc:  # pragma: no cover - defensive
            sharded_vs_single = {"error": f"{type(exc).__name__}: {exc}"}
        # Two-level placement-quality study (full-cycle sim, both
        # sharded modes forced in turn); guarded like the A/B above.
        try:
            twolevel_quality = bench_twolevel_quality()
        except Exception as exc:  # pragma: no cover - defensive
            twolevel_quality = {"error": f"{type(exc).__name__}: {exc}"}

    # Long-horizon simulator throughput + invariant-checker overhead
    # (guarded like the other sections).
    try:
        sim = bench_sim()
    except Exception as exc:  # pragma: no cover - defensive
        sim = {"error": f"{type(exc).__name__}: {exc}"}

    # Cold-takeover failover recovery at the headline shape (journal
    # scan + reconcile + first post-recovery cycle); guarded.
    try:
        recovery = bench_recovery(headline_cfg)
    except Exception as exc:  # pragma: no cover - defensive
        recovery = {"error": f"{type(exc).__name__}: {exc}"}

    # Arrival→bind placement-latency percentiles under the high-arrival
    # sim mixes (virtual-time, machine-independent; guarded).
    try:
        arrival_latency = bench_arrival_latency(
            quick=headline_cfg != "large"
        )
    except Exception as exc:  # pragma: no cover - defensive
        arrival_latency = {"error": f"{type(exc).__name__}: {exc}"}

    # Serving-SLO attainment + per-class bind p99 under the mixed
    # congested regime (virtual-time, machine-independent; guarded).
    try:
        serving = bench_serving(quick=headline_cfg != "large")
    except Exception as exc:  # pragma: no cover - defensive
        serving = {"error": f"{type(exc).__name__}: {exc}"}

    # Anti-entropy sweep + post-solve validation cost at the headline
    # shape, with the steady-cycle-relative budgets the <1% pin is
    # quoted against (guarded like every section).
    try:
        integrity = bench_integrity(headline_cfg)
        steady_ms = None
        if isinstance(cycle, dict):
            sw = cycle.get("steady_warm") or cycle.get("steady") or {}
            steady_ms = sw.get("cycle_ms")
        if steady_ms:
            integrity["validation_pct_of_steady"] = round(
                100.0 * integrity["validation_steady_ms"] / steady_ms, 3
            )
            every = int(os.environ.get("KBT_ANTIENTROPY_EVERY", "256"))
            integrity["sweep_every"] = every
            # Amortized off the CHURNED sweep — the honest steady-state
            # cost (churn moves the cluster rv every cycle, so the
            # idle-cluster shortcut never fires there).
            integrity["sweep_amortized_pct_of_steady"] = round(
                100.0 * (integrity["sweep_churned_ms"] / every)
                / steady_ms, 3,
            )
            integrity["integrity_pct_of_steady"] = round(
                integrity["sweep_amortized_pct_of_steady"]
                + integrity["validation_pct_of_steady"], 3,
            )
    except Exception as exc:  # pragma: no cover - defensive
        integrity = {"error": f"{type(exc).__name__}: {exc}"}

    dev0 = jax.devices()[0]
    provenance = {
        "platform": str(dev0.platform),
        "device_kind": str(getattr(dev0, "device_kind", "")),
        "num_devices": len(jax.devices()),
        **PROBE_INFO,
    }

    print(json.dumps({
        "metric": f"gang-cycle-solve-latency-{headline_cfg}"
                  f"-{CONFIGS[headline_cfg][0]}x{CONFIGS[headline_cfg][1]}",
        "value": round(headline_ms, 3),
        "unit": "ms",
        "vs_baseline": round(speedup, 1),
        "pods_placed": headline_placed,
        "pods_placed_per_sec": round(headline_placed / headline_solve_s, 1),
        "solver_rounds": headline_rounds,
        "host_snapshot_ms": round(tpu["snapshot_s"] * 1e3, 1),
        "session_open_ms": round(tpu["session_s"] * 1e3, 1),
        "greedy_small_ms": round(greedy_s * 1e3, 1),
        "greedy_extrapolated_ms": round(greedy_extrapolated_s * 1e3, 1),
        "device": str(jax.devices()[0].platform),
        "device_provenance": provenance,
        "cycle": cycle,
        "obs": obs,
        "quality": quality,
        "device_cache": device_cache,
        "solver_sparse": tpu["sparse"],
        "sim": sim,
        "recovery": recovery,
        "arrival_latency": arrival_latency,
        "serving": serving,
        "integrity": integrity,
        **({"sparse_scale": sparse_scale} if sparse_scale else {}),
        **({"sparse_scale_xl": sparse_scale_xl} if sparse_scale_xl
           else {}),
        **({"sharded_vs_single": sharded_vs_single} if sharded_vs_single
           else {}),
        **({"twolevel_quality": twolevel_quality} if twolevel_quality
           else {}),
        **extra,
    }))


if __name__ == "__main__":
    main()
