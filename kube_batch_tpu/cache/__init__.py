"""Scheduler cache (mirrors reference pkg/scheduler/cache)."""

from .cache import (
    DefaultBinder,
    DefaultEvictor,
    DefaultStatusUpdater,
    DefaultVolumeBinder,
    SchedulerCache,
    new_scheduler_cache,
)
from .interface import Binder, Cache, Evictor, StatusUpdater, VolumeBinder
from .util import create_shadow_pod_group, job_terminated, shadow_pod_group
