"""Post-solve placement validation (solver/validate.py) and its
allocate_tpu ladder integration: a corrupted solver result must never
reach bind dispatch — a device rung's rejection re-solves one rung
down, the native floor drops the offenders."""

import numpy as np
import pytest

from kube_batch_tpu.actions import allocate_tpu as atpu
from kube_batch_tpu.api import PodPhase, build_resource_list
from kube_batch_tpu.framework import close_session, open_session
from kube_batch_tpu.metrics import metrics as m
from kube_batch_tpu.obs import RECORDER
from kube_batch_tpu.solver import containment, tensorize
from kube_batch_tpu.solver.validate import validate_placements
from kube_batch_tpu.utils.test_utils import (
    build_node,
    build_pod,
    build_pod_group,
    build_queue,
)

from tests.actions.test_actions import (
    DEFAULT_TIERS_ARGS,
    make_cache,
    make_tiers,
    req,
    run_action,
)


@pytest.fixture(autouse=True)
def _fresh_containment():
    containment.reset_breaker()
    containment.set_device_fault_hook(None)
    containment.set_result_tamper_hook(None)
    containment.configure(None)
    yield
    containment.reset_breaker()
    containment.set_device_fault_hook(None)
    containment.set_result_tamper_hook(None)
    containment.configure(None)


def _pending_cluster(groups=3, pods=4, nodes=6):
    c = make_cache()
    c.add_queue(build_queue("default"))
    for j in range(nodes):
        c.add_node(build_node(
            f"n{j}", build_resource_list(cpu="4", memory="8Gi")
        ))
    for g in range(groups):
        c.add_pod_group(build_pod_group(
            f"pg{g}", namespace="ns", min_member=1
        ))
        for i in range(pods):
            c.add_pod(build_pod(
                "ns", f"pg{g}-p{i}", "", PodPhase.PENDING, req(),
                group_name=f"pg{g}",
            ))
    return c


def _tensorized(cache):
    ssn = open_session(cache, make_tiers(*DEFAULT_TIERS_ARGS))
    inputs, ctx = tensorize(ssn, device=False)
    return ssn, inputs, ctx


class TestValidatePlacements:
    def test_clean_assignment_passes(self):
        c = _pending_cluster()
        ssn, _inputs, ctx = _tensorized(c)
        T, N = len(ctx.tasks), len(ctx.nodes)
        a = (np.arange(T) % N).astype(np.int64)
        bad, reasons = validate_placements(ctx, a)
        assert bad.size == 0 and reasons == {}
        close_session(ssn)
        c.shutdown()

    def test_bad_index_rejected(self):
        c = _pending_cluster()
        ssn, _inputs, ctx = _tensorized(c)
        T, N = len(ctx.tasks), len(ctx.nodes)
        a = (np.arange(T) % N).astype(np.int64)
        a[3] = N + 7
        a[5] = 2**30
        bad, reasons = validate_placements(ctx, a)
        assert sorted(bad.tolist()) == [3, 5]
        assert reasons == {"bad-index": 2}
        close_session(ssn)
        c.shutdown()

    def test_negative_bad_index_rejected(self):
        """A corrupted NEGATIVE index (sign flip) is bad-index, not
        'unplaced' — only the -1 sentinel means unassigned."""
        c = _pending_cluster()
        ssn, _inputs, ctx = _tensorized(c)
        T, N = len(ctx.tasks), len(ctx.nodes)
        a = (np.arange(T) % N).astype(np.int64)
        a[2] = -7
        a[4] = -1  # legitimate unassigned: never flagged
        bad, reasons = validate_placements(ctx, a)
        assert bad.tolist() == [2]
        assert reasons == {"bad-index": 1}
        close_session(ssn)
        c.shutdown()

    def test_infeasible_rejected(self):
        c = _pending_cluster()
        ssn, _inputs, ctx = _tensorized(c)
        T, N = len(ctx.tasks), len(ctx.nodes)
        a = np.full(T, -1, dtype=np.int64)
        a[0] = 0
        # Forge infeasibility: flip the mask's node_ok bit for node 0
        # — the validator must see placement 0 violating the mask the
        # solve was (supposedly) given.
        ctx.mask.node_ok[0] = False
        bad, reasons = validate_placements(ctx, a)
        assert bad.tolist() == [0]
        assert reasons == {"infeasible": 1}
        close_session(ssn)
        c.shutdown()

    def test_gross_capacity_rejected(self):
        c = _pending_cluster()
        ssn, _inputs, ctx = _tensorized(c)
        T = len(ctx.tasks)
        # Every task piled on node 0: 12 x 1cpu vs 4 cpu allocatable —
        # gross oversubscription far past epsilon slack.
        a = np.zeros(T, dtype=np.int64)
        bad, reasons = validate_placements(ctx, a)
        assert reasons.get("capacity", 0) == T
        assert bad.size == T
        close_session(ssn)
        c.shutdown()

    def test_unassigned_vector_trivially_clean(self):
        c = _pending_cluster()
        ssn, _inputs, ctx = _tensorized(c)
        a = np.full(len(ctx.tasks), -1, dtype=np.int64)
        bad, reasons = validate_placements(ctx, a)
        assert bad.size == 0 and reasons == {}
        close_session(ssn)
        c.shutdown()


class TestLadderIntegration:
    def test_corrupted_device_result_rejected_before_dispatch(
        self, monkeypatch
    ):
        """The acceptance assert, end-to-end through the real action: a
        tampered device result is rejected by validation BEFORE any
        bind dispatches, the ladder descends one rung, and the cycle
        completes with the trusted floor's placements only."""
        monkeypatch.setenv("KBT_SOLVER", "jax")
        monkeypatch.delenv("KBT_SOLVER_TOPK", raising=False)
        tampers = []

        def tamper(assigned):
            # First device fetch only: rewrite two placements out of
            # the node universe (a silent miscompute).
            if tampers:
                return assigned
            tampers.append(1)
            arr = np.array(assigned, copy=True)
            sel = np.nonzero(np.asarray(arr) >= 0)[0]
            arr[sel[:2]] = 2**30
            return arr

        containment.set_result_tamper_hook(tamper)
        before = m.solver_output_rejected.get(("bad-index",))
        before_fb = m.solver_fallback.get(("dense", "native", "rejected"))
        RECORDER.begin_cycle()
        c = _pending_cluster()
        run_action(c, "allocate_tpu")
        assert c.wait_for_side_effects()
        rec = RECORDER.end_cycle()
        # No bind ever targeted the corrupted out-of-universe "node",
        # and every task still placed (via the floor).
        assert len(c.binder.binds) == 12
        assert all(host.startswith("n") for host in c.binder.binds.values())
        ladder = atpu.last_stats["solve_ladder"]
        assert [(e["rung"], e["outcome"]) for e in ladder] == [
            ("dense", "rejected"), ("native", "ok"),
        ]
        assert ladder[0]["reasons"] == {"bad-index": 2}
        assert atpu.last_stats["validation_rejected"] == 2
        assert atpu.last_stats["solve_degraded"] is True
        assert rec["solver"]["ladder"] == ladder
        assert m.solver_output_rejected.get(("bad-index",)) == before + 2
        assert m.solver_fallback.get(
            ("dense", "native", "rejected")
        ) == before_fb + 1
        assert containment.last_fallback["reason"] == "rejected"
        c.shutdown()

    def test_rejection_feeds_breaker(self, monkeypatch):
        monkeypatch.setenv("KBT_SOLVER", "jax")
        monkeypatch.delenv("KBT_SOLVER_TOPK", raising=False)

        def tamper(assigned):
            arr = np.array(assigned, copy=True)
            sel = np.nonzero(np.asarray(arr) >= 0)[0]
            if sel.size:
                arr[sel[:1]] = 2**30
            return arr

        containment.set_result_tamper_hook(tamper)
        c = _pending_cluster()
        run_action(c, "allocate_tpu")
        assert c.wait_for_side_effects()
        assert containment.BREAKER.failure_streak >= 1
        c.shutdown()

    def test_native_floor_drops_offenders(self, monkeypatch):
        """A native-floor validation failure (nothing below it) drops
        the offending placements and dispatches the rest."""
        monkeypatch.setenv("KBT_SOLVER", "native")
        c = _pending_cluster()
        orig = validate_placements

        calls = []

        def fake_validate(ctx, assigned):
            bad, reasons = orig(ctx, assigned)
            if not calls:
                calls.append(1)
                sel = np.nonzero(np.asarray(assigned)[: len(ctx.tasks)]
                                 >= 0)[0]
                return sel[:2], {"infeasible": 2}
            return bad, reasons

        monkeypatch.setattr(
            "kube_batch_tpu.solver.validate.validate_placements",
            fake_validate,
        )
        run_action(c, "allocate_tpu")
        assert c.wait_for_side_effects()
        ladder = atpu.last_stats["solve_ladder"]
        assert ladder[0]["outcome"] == "rejected-dropped"
        assert ladder[0]["rejected"] == 2
        assert len(c.binder.binds) == 10  # 12 minus the 2 dropped
        c.shutdown()
