"""kbtlint CLI driver (``make kbtlint``).

Exit codes: 0 clean, 1 unallowlisted findings (or stale allowlist
entries, or self-test failure), 2 usage/internal error.
"""

from __future__ import annotations

import argparse
import sys
import time

from . import core
from .selftest import run_selftest


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="kbtlint",
        description="project-invariant static analysis for tpu-batch",
    )
    parser.add_argument(
        "--pass", dest="passes", action="append", default=None,
        help="run only this pass (repeatable); default: all",
    )
    parser.add_argument(
        "--allow-file", default=core.ALLOWLIST_PATH,
        help="allowlist JSON (default tools/kbtlint/allowlist.json)",
    )
    parser.add_argument(
        "--no-allowlist", action="store_true",
        help="report raw findings (bring-up mode)",
    )
    parser.add_argument(
        "--self-test", action="store_true",
        help="verify each pass flags its known-bad fixture and accepts "
             "its known-good one",
    )
    parser.add_argument(
        "--list-passes", action="store_true",
    )
    parser.add_argument(
        "--budget-seconds", type=float, default=None,
        help="fail (exit 1) when the full run exceeds this wall-clock "
             "budget — new passes must not silently make CI crawl",
    )
    ns = parser.parse_args(argv)

    if ns.self_test:
        failures = run_selftest()
        for failure in failures:
            print(f"SELF-TEST FAIL: {failure}", file=sys.stderr)
        if failures:
            return 1
        print("kbtlint self-test: all seeded violations detected")
        return 0

    passes = core.all_passes()
    if ns.list_passes:
        for name in sorted(passes):
            print(name)
        return 0
    if ns.passes:
        unknown = set(ns.passes) - set(passes)
        if unknown:
            print(f"unknown pass(es): {sorted(unknown)}", file=sys.stderr)
            return 2
        passes = {k: v for k, v in passes.items() if k in ns.passes}

    t0 = time.time()
    project = core.load_project()
    findings = []
    for name in sorted(passes):
        findings.extend(passes[name](project))

    if ns.no_allowlist:
        kept, suppressed, stale = findings, [], []
    else:
        try:
            entries = core.load_allowlist(ns.allow_file)
        except (core.AllowlistError, ValueError) as exc:
            print(f"allowlist error: {exc}", file=sys.stderr)
            return 2
        # A --pass subset run must not report the other passes'
        # entries as stale: only entries whose pass actually ran can
        # legitimately have matched nothing.
        entries = [e for e in entries if e.pass_id in passes]
        kept, suppressed, stale = core.apply_allowlist(findings, entries)

    for finding in kept:
        print(finding.render())
    for entry in stale:
        print(
            f"STALE allowlist entry (matched nothing): pass={entry.pass_id} "
            f"file={entry.file} match={entry.match!r} — delete it or fix "
            f"the match; dead suppressions hide the next real finding",
        )
    elapsed = time.time() - t0
    print(
        f"kbtlint: {len(passes)} pass(es) over {len(project.files)} "
        f"file(s) in {elapsed:.1f}s — {len(kept)} finding(s), "
        f"{len(suppressed)} allowlisted, {len(stale)} stale "
        f"allowlist entr(y/ies)",
        file=sys.stderr,
    )
    over_budget = (
        ns.budget_seconds is not None and elapsed > ns.budget_seconds
    )
    if over_budget:
        print(
            f"kbtlint: BUDGET EXCEEDED — {elapsed:.1f}s > "
            f"{ns.budget_seconds:.1f}s wall-clock budget; a pass "
            f"regressed (profile with --pass, or raise the Makefile "
            f"budget deliberately)",
            file=sys.stderr,
        )
    return 1 if (kept or stale or over_budget) else 0


if __name__ == "__main__":
    sys.exit(main())
