"""k8s-manifest loader (cli/manifests.py): a kube-batch user's CRD YAML
(PodGroup/Queue in scheduling.incubator.k8s.io v1alpha1 or v1alpha2, core
v1 Pod/Node) must load and schedule end-to-end."""

import threading
import time

import pytest
import yaml

from kube_batch_tpu.api import GROUP_NAME_ANNOTATION_KEY, PodPhase
from kube_batch_tpu.cache import SchedulerCache
from kube_batch_tpu.cli.manifests import apply_manifests, parse_manifest
from kube_batch_tpu.cluster import InProcessCluster
from kube_batch_tpu.scheduler import Scheduler

MANIFESTS = f"""
apiVersion: scheduling.incubator.k8s.io/v1alpha1
kind: Queue
metadata:
  name: default
spec:
  weight: 4
---
apiVersion: scheduling.incubator.k8s.io/v1alpha2
kind: PodGroup
metadata:
  name: qj-1
  namespace: default
spec:
  minMember: 2
  queue: default
---
apiVersion: v1
kind: Node
metadata:
  name: node-a
  labels: {{zone: a}}
status:
  allocatable: {{cpu: "4", memory: 8Gi, pods: "20"}}
  capacity: {{cpu: "4", memory: 8Gi, pods: "20"}}
---
apiVersion: v1
kind: Pod
metadata:
  name: qj-1-0
  namespace: default
  annotations:
    {GROUP_NAME_ANNOTATION_KEY}: qj-1
spec:
  containers:
  - name: main
    resources:
      requests: {{cpu: 500m, memory: 256Mi}}
---
apiVersion: v1
kind: Pod
metadata:
  name: qj-1-1
  namespace: default
  annotations:
    {GROUP_NAME_ANNOTATION_KEY}: qj-1
spec:
  tolerations:
  - key: dedicated
    operator: Equal
    value: ml
    effect: NoSchedule
  containers:
  - name: main
    resources:
      requests: {{cpu: 500m, memory: 256Mi}}
"""


def test_both_crd_versions_parse():
    docs = list(yaml.safe_load_all(MANIFESTS))
    kinds = [parse_manifest(d)[0] for d in docs]
    assert kinds == ["Queue", "PodGroup", "Node", "Pod", "Pod"]
    _, queue = parse_manifest(docs[0])
    assert queue.spec.weight == 4
    _, pg = parse_manifest(docs[1])
    assert pg.spec.min_member == 2
    _, pod = parse_manifest(docs[4])
    assert pod.spec.tolerations[0].value == "ml"
    assert pod.metadata.annotations[GROUP_NAME_ANNOTATION_KEY] == "qj-1"


def test_unsupported_version_rejected():
    with pytest.raises(ValueError, match="unsupported"):
        parse_manifest({
            "apiVersion": "scheduling.incubator.k8s.io/v1beta1",
            "kind": "PodGroup",
        })


def test_manifests_schedule_end_to_end():
    cluster = InProcessCluster(simulate_kubelet=True)
    n = apply_manifests(cluster, yaml.safe_load_all(MANIFESTS))
    assert n == 5
    cache = SchedulerCache(cluster=cluster)
    sched = Scheduler(cache, schedule_period=0.05)
    stop = threading.Event()
    t = threading.Thread(target=sched.run, args=(stop,), daemon=True)
    t.start()
    deadline = time.time() + 20
    done = False
    while time.time() < deadline:
        pods = cluster.list_objects("Pod")
        if all(p.status.phase == PodPhase.RUNNING for p in pods):
            done = True
            break
        time.sleep(0.05)
    stop.set()
    t.join(timeout=5)
    assert done, [
        (p.metadata.name, p.status.phase, p.spec.node_name)
        for p in cluster.list_objects("Pod")
    ]
    for p in cluster.list_objects("Pod"):
        assert p.spec.node_name == "node-a"
