"""Serving-subsystem sim acceptance (doc/design/serving.md):

- **bit-parity**: a batch-only mix places byte-identically with the
  serving plugin loaded vs a conf without it — the all-default
  BatchMask/empty-score-rows contract holds through the REAL
  solver/cache/action stack, not just at combine level;
- **mixed congested run**: serving deployments layered on a batch
  stream under micro cycles hold the >= 99% attainment target with
  zero invariant violations (the serving-floor family armed every
  cycle);
- **warm-path parity**: the same mixed stream with the warm-start
  state machine disabled (KBT_WARM=0) still holds every invariant —
  the serving mask/score rows flow through the cold tensorize path
  identically.
"""

from kube_batch_tpu.sim import SimConfig, WorkloadSpec
from kube_batch_tpu.sim.harness import SIM_DEFAULT_CONF, run_sim
from kube_batch_tpu.sim.trace import diff_placements

CONF_WITHOUT_SERVING = SIM_DEFAULT_CONF.replace("  - name: serving\n", "")


def mixed_spec(**kw):
    """Serving deployments + batch gangs over a heterogeneous pool
    (two generations, two tiers, a 20% spot slice)."""
    kw.setdefault("nodes", 16)
    kw.setdefault("node_cpu_m", 16000)
    kw.setdefault("node_mem_mi", 32768)
    kw.setdefault("arrival_rate", 3.0)
    kw.setdefault("serving_rate", 0.5)
    kw.setdefault("serving_slo_s", 0.05)
    kw.setdefault("serving_churn", 0.05)
    kw.setdefault("reserved_frac", 0.8)
    kw.setdefault("node_tiers", 2)
    return WorkloadSpec(**kw)


class TestServingSim:
    def test_batch_only_bit_parity_with_serving_plugin_loaded(self):
        assert "serving" in SIM_DEFAULT_CONF
        assert "serving" not in CONF_WITHOUT_SERVING
        runs = {}
        for label, conf in (
            ("with", SIM_DEFAULT_CONF), ("without", CONF_WITHOUT_SERVING),
        ):
            report, trace = run_sim(SimConfig(
                cycles=60, seed=5, conf=conf, backend="dense",
                faults="bind:0.05",
                workload=WorkloadSpec(nodes=10, arrival_rate=1.5),
            ))
            assert report.violations == []
            assert report.cycle_errors == 0
            assert report.placements > 50
            runs[label] = (report, trace)
        assert diff_placements(
            runs["with"][1][1:], runs["without"][1][1:]
        ) == []
        # A batch-only mix must never engage the serving accounting.
        with_serving = (runs["with"][0].latency or {}).get("serving") or {}
        assert with_serving.get("classes") in (None, {})
        assert with_serving.get("violations", 0) == 0

    def test_mixed_congested_run_holds_slo_and_invariants(self):
        report, _trace = run_sim(SimConfig(
            cycles=160, seed=1, backend="dense",
            micro_every=8, period=0.005,
            workload=mixed_spec(),
        ))
        assert report.violations == []
        assert report.cycle_errors == 0
        serving = (report.latency or {}).get("serving") or {}
        cls = serving.get("classes", {}).get("serving", {})
        # The run must have genuinely exercised the subsystem...
        assert cls.get("placed", 0) > 20
        # ...and hold the acceptance target on the virtual clock.
        assert cls["attainment_pct"] >= 99.0
        assert serving["budget_burn"] <= 1.0

    def test_mixed_run_invariants_hold_with_warm_path_disabled(
        self, monkeypatch
    ):
        monkeypatch.setenv("KBT_WARM", "0")
        report, _trace = run_sim(SimConfig(
            cycles=80, seed=1, backend="dense",
            micro_every=8, period=0.005,
            workload=mixed_spec(),
        ))
        assert report.violations == []
        assert report.cycle_errors == 0
        serving = (report.latency or {}).get("serving") or {}
        assert serving.get("classes", {}).get(
            "serving", {}
        ).get("placed", 0) > 10
