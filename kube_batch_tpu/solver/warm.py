"""Warm-started steady cycles: carry the previous solve's verdicts.

A periodic cycle at steady state re-derives a conclusion it already
reached one period ago: every pending task it re-solves was left
unassigned by the previous cycle, against capacities that have only
SHRUNK since (the scheduler's own placements), budgets that have only
tightened, and feasibility that has not moved. CvxCluster (PAPERS.md)
gets its 100-1000x on granular allocation problems from exactly this
solution-reuse structure. This module is the state machine that decides,
per cycle, how much of the previous solve survives:

``noop``
    No job gained schedulable work since the previous solve and every
    delta precondition holds — the previous cycle's verdicts ARE this
    cycle's verdicts, bit-for-bit, and the solve/selection/apply phases
    are skipped entirely. Only the cache maintenance half of tensorize
    runs (``tensorize(warm_noop=True)``: node-array + predicate-column
    patching against the narrow ledger). Exactness argument: the solver
    runs rounds to a fixed point, and the cluster state at this snapshot
    IS the previous solve's fixed point (placements applied exactly the
    deltas the solve committed; nothing else moved, per the
    preconditions below) — re-running the rounds would accept nothing in
    round one and stop.

``solve``
    New work arrived (dirty jobs with pending tasks) and NO unassigned
    tasks were carried over — the problem contains exactly the new work,
    solved against the residual capacities already resident in the
    incremental tensorize / device caches. This is the steady
    placement-wave regime: cycle cost scales with churn.

``subset``
    New work arrived WHILE unassigned tasks are carried. The new work
    (plus a bounded, rotating drain batch of carried jobs) solves as a
    rank-stable SUBSET problem: tensorize runs its ordering pipeline
    over the FULL pending pool — cheap host numpy — and slices solver
    tensors to the subset rows, each carrying its GLOBAL rank
    (``tensorize(rank_pool=...)``), which the kernels consume for both
    priority ordering and bid-key tie hashes. Exactness: under this
    plan's preconditions every carried task outside the subset sits at
    the previous solve's fixed point (failed, job-broken, or budget-
    gated) against capacities that only shrink and budgets that only
    tighten, so the full problem would leave it unassigned and its rows
    contribute exact zeros to every queue/node reduction (x + 0.0 == x
    in f32) — the subset solve's placements are bit-equal to the full
    solve restricted to the subset, and the full solve places nothing
    else. This retires the former ``carried-interleave`` full-solve
    fallback: congested cycles (carried backlog + arrivals) now cost
    O(churn), not O(pending).

Events that merely VOID a carried verdict no longer force a full
solve: a third-party node event (capacity may have GROWN — every
carried verdict re-solves), a mutated carried job (completion,
preempt, partial-gang revert), or a moved queue budget (that queue's
carried jobs re-solve) each FOLD the affected carried jobs into the
subset instead. The exactness argument is unchanged — a re-solved row
is trivially exact, and only rows whose preconditions still hold stay
outside the subset. This is what keeps the micro path primary in the
congested regime, where completions dirty nodes every coalescing
window.

fallback (full solve, labeled by reason)
    The remaining precondition failures re-solve everything from the
    ground truth — bit-parity with a cold scheduler is the invariant
    the randomized churn tests pin. Reasons:

    - ``cold`` / ``stale``: no warm state, or a snapshot generation gap
      (some cycle's ledger drained without a warm save AND without the
      deferred-micro dirt fold below);
    - ``node-dirty``: a third-party node event with NO pending work
      anywhere (nothing to subset-solve — the periodic path refreshes);
    - ``releasing``: Releasing capacity exists — the pipeline epilogue
      may place carried tasks, outside the fixed-point argument;
    - ``mesh-changed``: the solver's device layout token moved since
      the save (KBT_SPARSE_SHARD_MODE flip — the device set itself is
      process-constant — or a node->rack map move under two-level mode:
      the token carries the rack-permutation digest suffix): the flat
      sharded mode is bit-parity but the two-level mode is not, so
      carried verdicts conservatively void whenever the layout a solve
      would run under differs from the one that produced them;
    - ``drift``: the warm-noop tensorize found node rows dirty beyond
      the narrow ledger (a session-side mutation the plan could not
      see) — the cycle re-runs as a full solve.

A micro cycle that still hits a fallback places nothing and defers —
but its session has already DRAINED the cache's dirty ledgers, so
``note_deferred`` folds the drained deltas into the state
(``pending_*`` sets) and keeps the snapshot-generation continuity;
without it one defer would strand every following micro cycle on
``stale`` until the next periodic solve.

The state lives on the SchedulerCache (``_warm_solve_state``), the same
lifetime pattern as the tensorize/device caches. ``plan_warm`` is
called by allocate_tpu before tensorize; ``save_warm_state`` after the
apply/verdict phases of every solving cycle (and ``advance_noop`` after
a no-op cycle).
"""

from __future__ import annotations

import logging
import os
from typing import Dict, List, Optional, Tuple

from ..api import TaskStatus

logger = logging.getLogger(__name__)


class WarmSolveState:
    """Carried verdicts of the most recent solve (see module doc)."""

    __slots__ = (
        "valid", "snap_gen", "carried", "queue_deserved", "has_releasing",
        "mesh_token", "drain_cursor",
        "pending_dirty_jobs", "pending_dirty_nodes", "pending_narrow",
    )

    def __init__(self):
        self.valid = False
        self.snap_gen = -1
        # Dirty-ledger deltas drained by DEFERRED micro cycles
        # (note_deferred): the next plan unions them with its session's
        # ledgers, so a defer never loses churn information. Cleared on
        # every successful warm save / noop advance.
        self.pending_dirty_jobs: set = set()
        self.pending_dirty_nodes: set = set()
        self.pending_narrow: set = set()
        # Rotating position into the sorted carried-uid list: each
        # subset solve drains the next KBT_MICRO_DRAIN carried jobs so
        # every carried verdict is refreshed within
        # ceil(carried / drain) subset cycles. Advanced only by subset
        # solves — a pure function of solve history, so replay-stable.
        self.drain_cursor = 0
        # Solver device-layout token at save time
        # (sharding.prospective_layout_token); None until a sharded
        # dispatch has pinned the device count.
        self.mesh_token = None
        # job uid -> (job clone object, clone _ver at save, pending
        # remainder at save). Identity+ver pins "untouched"; a
        # narrow-dirty re-clone passes iff its pending count still
        # equals the remainder (a bind-bookkeeping revert would grow
        # it, and a reverted task must be re-solved).
        self.carried: Dict[str, tuple] = {}
        # queue uid -> deserved Resource clone (None when no budget
        # plugin had an opinion) for every queue owning carried jobs.
        self.queue_deserved: Dict[str, object] = {}
        self.has_releasing = True  # conservative until first save


def warm_state_of(cache) -> Optional[WarmSolveState]:
    if cache is None:
        return None
    ws = getattr(cache, "_warm_solve_state", None)
    if ws is None:
        ws = WarmSolveState()
        try:
            cache._warm_solve_state = ws
        except Exception:  # slots-only stand-in cache
            return None
    return ws


def warm_enabled() -> bool:
    return os.environ.get("KBT_WARM", "1") != "0"


def _layout_token():
    """The solver device-layout token a solve dispatched now would run
    under (None before any sharded dispatch — see
    sharding.prospective_layout_token; never probes the backend, so
    the native-route and pre-init paths stay hang-safe)."""
    from . import sharding

    return sharding.prospective_layout_token()


def _res_eq(a, b) -> bool:
    """Exact Resource equality (Resource.__eq__); None-tolerant."""
    if a is None or b is None:
        return a is None and b is None
    return a == b


def _deserved_of(ssn, queue) -> Optional[object]:
    """The queue's deserved budget (first plugin with an opinion wins —
    the same resolution tensorize uses for its budget vectors)."""
    for fn in ssn.queue_budget_fns.values():
        budget = fn(queue)
        if budget is not None:
            return budget[0]
    return None


def plan_warm(ssn) -> Tuple[str, List]:
    """Classify this cycle against the warm state. Returns
    ``(outcome, live_jobs)``: outcome ``noop``/``solve`` when the warm
    path engages, else the fallback reason; ``live_jobs`` is the set of
    jobs with new schedulable work (empty for noop and for fallbacks,
    where the full solve covers everything anyway)."""
    if not warm_enabled():
        return "disabled", []
    ws = warm_state_of(ssn.cache)
    if ws is None or not ws.valid:
        return "cold", []
    if getattr(ssn, "snap_gen", 0) != ws.snap_gen + 1:
        return "stale", []
    cur_token = _layout_token()
    if (
        cur_token is not None
        and ws.mesh_token is not None
        and cur_token != ws.mesh_token
    ):
        # The solver's device layout moved under the carried verdicts
        # (mode flip; device count is process-constant): conservatively
        # re-solve — the two-level mode is not bit-parity.
        return "mesh-changed", []
    if ws.has_releasing:
        return "releasing", []

    # The effective delta since the last warm processing: this
    # session's drained ledgers plus anything deferred micro cycles
    # drained before it (note_deferred).
    dirty_jobs = set(ssn.dirty_jobs) | ws.pending_dirty_jobs
    node_dirty = bool(ssn.dirty_nodes) or bool(ws.pending_dirty_nodes)
    narrow = set(ssn.dirty_jobs_narrow) | ws.pending_narrow

    pending_key = TaskStatus.PENDING
    carried = ws.carried
    live: List = []
    seen = set()
    # Sorted: the walk order must be replay-stable (kbtlint
    # replay-determinism) now that the union is a fresh set.
    for uid in sorted(dirty_jobs):
        job = ssn.jobs.get(uid)
        if job is not None and job.task_status_index.get(pending_key):
            live.append(job)
            seen.add(uid)

    # Carried verdicts whose preconditions no longer hold are FOLDED
    # into the subset (re-solved against current residuals/budgets)
    # instead of forcing a full solve — re-solved rows are trivially
    # exact, and only rows whose preconditions still hold stay outside.
    forced: List = []
    remaining: Dict[str, List] = {}  # queue uid -> kept-out carried jobs
    for uid, (obj, ver, remainder) in carried.items():
        if uid in seen:
            # Full-dirty carried job: its re-solve is part of the live
            # set; the carried verdict is simply superseded.
            continue
        job = ssn.jobs.get(uid)
        if job is None:
            # Deleted carried job: the full problem no longer contains
            # it — the entry is dead (advance/save paths prune it).
            continue
        if node_dirty:
            # Third-party node event: capacities may have GROWN, so any
            # carried verdict might now be placeable — every carried
            # job re-solves inside the subset.
            forced.append(job)
            seen.add(uid)
            continue
        if job is obj and job._ver == ver:
            remaining.setdefault(obj.queue, []).append(job)
            continue
        if (
            uid in narrow
            and len(job.task_status_index.get(pending_key) or ()) == remainder
        ):
            # Bind-only churn with the exact unassigned remainder left
            # pending: the job is in precisely the state the previous
            # solve ended in.
            remaining.setdefault(job.queue, []).append(job)
            continue
        # Mutated carried job (completion, preempt, partial-gang
        # revert) or a drifted remainder: its old verdict is void —
        # re-solve it.
        forced.append(job)
        seen.add(uid)

    # A narrow-dirty job that is NOT carried but has pending tasks means
    # a bind-bookkeeping revert put an assigned task back — re-solve it.
    for uid in sorted(narrow):
        if uid in carried or uid in seen:
            continue
        job = ssn.jobs.get(uid)
        if job is not None and job.task_status_index.get(pending_key):
            live.append(job)
            seen.add(uid)

    # Budget re-check over the queues whose carried jobs would stay
    # OUTSIDE the subset: a moved deserved budget voids exactly that
    # queue's kept-out verdicts — fold them in too. Sorted: the walk
    # must be replay-stable (kbtlint replay-determinism).
    for quid in sorted(remaining):
        queue = ssn.queues.get(quid)
        cur = _deserved_of(ssn, queue) if queue is not None else None
        if not _res_eq(cur, ws.queue_deserved.get(quid)):
            for job in remaining[quid]:
                forced.append(job)
                seen.add(job.uid)

    if not live and not forced:
        if node_dirty:
            # A node event with no pending work anywhere: nothing to
            # subset-solve — let the full path refresh the arrays.
            return "node-dirty", []
        return "noop", []
    if carried:
        # Carried unassigned tasks interleave with the new work: solve
        # the new work (plus every voided carried verdict) as a
        # rank-stable SUBSET problem (see module doc;
        # tensorize(rank_pool=...) carries global ranks so ordering and
        # tie hashes match the full problem restricted to these rows).
        return "subset", live + forced
    return "solve", live


def micro_drain_limit() -> int:
    """KBT_MICRO_DRAIN: carried jobs re-examined per subset solve."""
    try:
        return max(0, int(os.environ.get("KBT_MICRO_DRAIN", "32")))
    except ValueError:
        return 32


def subset_jobs(ssn: "object", live: List) -> List:
    """The subset bundle's job list: the live jobs plus a bounded drain
    batch of carried jobs — the next ``KBT_MICRO_DRAIN`` in rotating
    sorted-uid order, so every carried verdict is refreshed within
    ``ceil(carried / drain)`` subset cycles. Any superset of ``live``
    is parity-safe: carried tasks are inert in the full problem under
    this plan's preconditions, in or out of the subset. The cursor
    advances only here, a pure function of solve history, so sim
    replays stay byte-stable."""
    ws = warm_state_of(ssn.cache)
    jobs = list(live)
    if ws is None or not ws.carried:
        return jobs
    seen = {j.uid for j in live}
    uids = sorted(u for u in ws.carried if u not in seen)
    if not uids:
        return jobs
    n = min(micro_drain_limit(), len(uids))
    cur = ws.drain_cursor % len(uids)
    picked = [uids[(cur + i) % len(uids)] for i in range(n)]
    ws.drain_cursor = (cur + n) % len(uids)
    for uid in picked:
        job = ssn.jobs.get(uid)
        if job is not None:
            jobs.append(job)
    return jobs


def note_deferred(ssn: "object") -> None:
    """A micro cycle deferred (plan fallback) after its session already
    DRAINED the cache's dirty ledgers: fold the drained deltas into the
    warm state so the next plan still sees them, and keep the
    snapshot-generation continuity — without this a single defer would
    strand every following micro cycle on ``stale`` until the next
    periodic solve."""
    ws = warm_state_of(ssn.cache)
    if ws is None or not ws.valid:
        return
    ws.pending_dirty_jobs.update(ssn.dirty_jobs)
    ws.pending_dirty_nodes.update(ssn.dirty_nodes)
    ws.pending_narrow.update(ssn.dirty_jobs_narrow)
    ws.snap_gen = getattr(ssn, "snap_gen", 0)


def advance_noop(ssn) -> None:
    """A no-op cycle consumed one snapshot generation; keep continuity.
    Carried entries that passed the plan via the NARROW remainder check
    (a bind re-minted the job's clone) are re-pinned to the current
    clone — otherwise the very next cycle's identity check would fail
    against the drained ledger and force a spurious carried-changed
    full solve after every partial placement wave. Entries whose job
    was deleted are pruned (the full problem no longer contains them)."""
    ws = warm_state_of(ssn.cache)
    if ws is None:
        return
    ws.snap_gen = getattr(ssn, "snap_gen", 0)
    ws.mesh_token = _layout_token()
    ws.pending_dirty_jobs.clear()
    ws.pending_dirty_nodes.clear()
    ws.pending_narrow.clear()
    for uid, (obj, ver, remainder) in list(ws.carried.items()):
        job = ssn.jobs.get(uid)
        if job is None:
            del ws.carried[uid]
        elif job is not obj or job._ver != ver:
            ws.carried[uid] = (job, job._ver, remainder)


def invalidate(cache) -> None:
    ws = getattr(cache, "_warm_solve_state", None)
    if ws is not None:
        ws.valid = False


def save_warm_state(ssn, ctx, assigned) -> int:
    """Record this solve's carried verdicts (called post-apply). With
    ``ctx is None`` (an idle cycle: nothing pending) the carried set is
    empty — the strongest warm state there is. After a SUBSET solve
    (``ctx.subset_jobs``) carried entries OUTSIDE the subset keep their
    verdicts — re-pinned to the current clone where narrow bind churn
    re-minted it, like :func:`advance_noop` — and subset jobs'
    entries are superseded by this solve's unassigned rows. Returns the
    carried job count (stats)."""
    ws = warm_state_of(ssn.cache)
    if ws is None:
        return 0
    carried: Dict[str, tuple] = {}
    has_releasing = True
    subset = getattr(ctx, "subset_jobs", None) if ctx is not None else None
    if subset is not None and ws.valid:
        for uid, (obj, ver, remainder) in ws.carried.items():
            if uid in subset:
                continue
            job = ssn.jobs.get(uid)
            if job is None:
                continue
            if job is not obj or job._ver != ver:
                carried[uid] = (job, job._ver, remainder)
            else:
                carried[uid] = (obj, ver, remainder)
    if ctx is None:
        # Idle: no pending tasks at all. Releasing presence from the
        # tensorize cache's freshly absorbed columns.
        tc = getattr(ssn.cache, "_tensorize_cache", None)
        if tc is not None and tc.releasing is not None and len(
            getattr(tc, "node_objs", None) or ()
        ) == len(ssn.nodes):
            has_releasing = bool(tc.releasing.any())
    else:
        import numpy as np

        has_releasing = bool(ctx.has_releasing)
        T = len(ctx.tasks)
        a = np.asarray(assigned[:T])
        for i in np.nonzero(a < 0)[0].tolist():
            task = ctx.tasks[i]
            if task.job in carried:
                continue
            job = ssn.jobs.get(task.job)
            if job is None:
                continue
            carried[task.job] = (
                job, job._ver,
                len(job.task_status_index.get(TaskStatus.PENDING) or ()),
            )
    deserved: Dict[str, object] = {}
    for uid, (job, _v, _r) in carried.items():
        quid = job.queue
        if quid in deserved:
            continue
        queue = ssn.queues.get(quid)
        d = _deserved_of(ssn, queue) if queue is not None else None
        deserved[quid] = d.clone() if d is not None else None
    ws.carried = carried
    ws.queue_deserved = deserved
    ws.has_releasing = has_releasing
    ws.snap_gen = getattr(ssn, "snap_gen", 0)
    ws.mesh_token = _layout_token()
    ws.pending_dirty_jobs.clear()
    ws.pending_dirty_nodes.clear()
    ws.pending_narrow.clear()
    ws.valid = True
    return len(carried)
