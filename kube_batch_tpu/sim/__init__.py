"""Deterministic cluster simulator.

The long-horizon harness the single-cycle parity tests cannot provide:
an event-driven loop with a virtual clock that drives the REAL
``Scheduler``/``SchedulerCache``/actions stack against a seeded
synthetic cluster (``workload``), injects faults at deterministic seams
(``faults``), asserts the kube-batch contract after every cycle
(``invariants``), and records a bit-replayable JSONL trace (``trace``).
``harness.ClusterSimulator`` wires it together; ``cli`` exposes
``python -m kube_batch_tpu sim``.

Determinism rules (doc/design/simulator.md): no wall-clock reads, no
RNG outside the seeded generators, all async cache work barriered at
cycle end — so the same (seed, spec) or a recorded trace reproduces
identical per-cycle placements, and the same trace can be replayed
under a different solver backend for a long-horizon parity diff.
"""

from .clock import RealClock, VirtualClock
from .faults import FaultInjector, SimBindFailure, parse_fault_spec
from .harness import ClusterSimulator, SimConfig, SimReport
from .invariants import InvariantChecker, Violation
from .soak import DetectorResult, SoakVerdict, run_detectors
from .trace import TraceReader, TraceWriter, placement_counts
from .workload import WorkloadGenerator, WorkloadSpec

__all__ = [
    "ClusterSimulator",
    "DetectorResult",
    "FaultInjector",
    "InvariantChecker",
    "RealClock",
    "SimBindFailure",
    "SimConfig",
    "SimReport",
    "SoakVerdict",
    "run_detectors",
    "TraceReader",
    "TraceWriter",
    "VirtualClock",
    "Violation",
    "WorkloadGenerator",
    "WorkloadSpec",
    "parse_fault_spec",
    "placement_counts",
]
