"""Long-horizon telemetry: per-cycle time-series with rollup windows.

PR 5 made a *single* cycle observable (flight recorder, spans,
verdicts); this module watches the *trajectory*. Every scheduling cycle
folds one small ``{key: float}`` sample — the flight-recorder record's
phase timings and solver attribution, plus resource-watermark probes
(host RSS, allocator blocks, JAX live buffers / device memory, jit and
patch-jit cache sizes, device-resident snapshot bytes, tracer/flight
ring occupancy and drops, metrics label-series cardinality, verdict
registry size, GC collection counts, per-queue fairness drift) — into:

- a **raw ring**: the last N per-cycle samples verbatim (fixed
  capacity, default 512), the "what just happened" view served by
  ``/debug/timeseries``;
- **rollup windows**: every W cycles the open window closes carrying
  count/sum/min/max and a quantile sketch per key (fixed window-ring
  capacity, oldest windows drop with a counter). Windows are what the
  soak-mode leak/drift detectors (``sim/soak.py``) fit trends over: a
  100k-cycle run at W=200 is 500 windows of a few hundred bytes each,
  so the full horizon stays resident at O(1) memory per cycle.

The enabled path is deliberately cheap — one dict of floats, one lock,
a handful of ``/proc`` and counter reads; the bench ``obs`` section
pins its cost against the same <1 %-of-an-idle-cycle budget as the span
tracer. ``KBT_TELEMETRY=0`` disables the scheduler feed entirely.

The quantile sketch is DDSketch-style (log-spaced buckets, relative
error <= ``alpha``): deterministic, mergeable, O(1) insert, and its
error bound is testable (tests/unit/test_telemetry.py pins it).
"""

from __future__ import annotations

import logging
import math
import os
import time
import weakref
from collections import deque
from typing import Dict, List, Optional

from ..utils.lockdebug import witness_writes, wrap_lock

logger = logging.getLogger(__name__)

TELEMETRY_ENV = "KBT_TELEMETRY"            # "0" disables the feed
TELEMETRY_WINDOW_ENV = "KBT_TELEMETRY_WINDOW"      # cycles per window
TELEMETRY_WINDOWS_ENV = "KBT_TELEMETRY_WINDOWS"    # window ring capacity
DEFAULT_WINDOW_CYCLES = 64
DEFAULT_MAX_WINDOWS = 1024
DEFAULT_RAW_CAPACITY = 512
# Fairness probes are O(jobs) (aggregate sums + a water-fill, several
# ms at the 50k/500-job bench shape); amortize them across cycles —
# drift is a windowed-mean quantity, so sparse samples lose nothing
# but resolution (a 195-cycle soak window still gets ~3 samples).
FAIRNESS_EVERY = 64
# The non-O(1)/slow watermark probes — the /proc RSS read (hundreds of
# µs on some kernels) and jax.live_arrays() (O(live buffers): ~0.5 ms
# at 5k arrays, several ms at bench scale) — run every Nth cycle; the
# cheap counter reads run every cycle. Rollup windows tolerate sparse
# keys, so the amortized series just carries 1/N the samples (a
# 100k-cycle soak still gets ~1.5k points per slow series). Intervals
# sized so the whole enabled path stays under the 1% idle-cycle budget
# (bench obs telemetry_overhead_pct).
EXPENSIVE_EVERY = 64
# The cluster-total Resource sum is O(nodes); refresh it only when the
# node count changes or this many fairness probes have passed
# (allocatable changes without node add/remove are rare).
_NODE_TOTAL_REFRESH = 16


def telemetry_enabled_from_env() -> bool:
    return os.environ.get(TELEMETRY_ENV, "1") != "0"


class QuantileSketch:
    """Log-bucketed quantile sketch (DDSketch style).

    Positive values land in bucket ``ceil(log_gamma(v))`` with
    ``gamma = (1 + alpha) / (1 - alpha)``; the bucket's midpoint
    estimate ``2 * gamma^i / (gamma + 1)`` is within relative error
    ``alpha`` of any value in it. Zero/negative values (idle phases,
    signed drift series) are tracked exactly at their min — quantiles
    over them return that min, keeping the relative-error contract
    vacuous rather than wrong. Bounded: past ``max_buckets`` the lowest
    buckets collapse together (coarse at the cheap end, exact error at
    the tail, which is what latency series need).
    """

    __slots__ = ("alpha", "_gamma", "_log_gamma", "max_buckets",
                 "buckets", "count", "low_count", "low_min")

    def __init__(self, alpha: float = 0.05, max_buckets: int = 512):
        self.alpha = alpha
        self._gamma = (1.0 + alpha) / (1.0 - alpha)
        self._log_gamma = math.log(self._gamma)
        self.max_buckets = max_buckets
        self.buckets: Dict[int, int] = {}
        self.count = 0
        self.low_count = 0       # values <= 0
        self.low_min = 0.0

    def add(self, value: float) -> None:
        self.count += 1
        if value <= 0.0:
            if self.low_count == 0 or value < self.low_min:
                self.low_min = value
            self.low_count += 1
            return
        idx = math.ceil(math.log(value) / self._log_gamma)
        self.buckets[idx] = self.buckets.get(idx, 0) + 1
        if len(self.buckets) > self.max_buckets:
            self._collapse_lowest()

    def _collapse_lowest(self) -> None:
        lo = sorted(self.buckets)[:2]
        if len(lo) == 2:
            self.buckets[lo[1]] = (
                self.buckets.pop(lo[0]) + self.buckets.get(lo[1], 0)
            )

    def merge(self, other: "QuantileSketch") -> None:
        """Fold ``other`` into this sketch (log-bucket counts add
        exactly — DDSketch mergeability). Lives HERE, next to the
        fields it touches, so callers never poke sketch internals; the
        same ``max_buckets`` coalescing as :meth:`add` applies."""
        self.count += other.count
        if other.low_count:
            if self.low_count == 0 or other.low_min < self.low_min:
                self.low_min = other.low_min
            self.low_count += other.low_count
        for idx, c in other.buckets.items():
            self.buckets[idx] = self.buckets.get(idx, 0) + c
        while len(self.buckets) > self.max_buckets:
            self._collapse_lowest()

    def quantile(self, q: float) -> float:
        """Value estimate at quantile ``q`` in [0, 1]; 0.0 when empty."""
        if self.count == 0:
            return 0.0
        rank = q * (self.count - 1)
        if rank < self.low_count:
            return self.low_min
        seen = self.low_count
        for idx in sorted(self.buckets):
            seen += self.buckets[idx]
            if seen > rank:
                return 2.0 * self._gamma ** idx / (self._gamma + 1.0)
        idx = max(self.buckets)
        return 2.0 * self._gamma ** idx / (self._gamma + 1.0)


class _KeyStats:
    __slots__ = ("count", "sum", "min", "max", "sketch")

    def __init__(self):
        self.count = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = -math.inf
        self.sketch = QuantileSketch()

    def add(self, v: float) -> None:
        self.count += 1
        self.sum += v
        if v < self.min:
            self.min = v
        if v > self.max:
            self.max = v
        self.sketch.add(v)

    def to_dict(self) -> dict:
        s = self.sketch
        return {
            "count": self.count,
            "sum": round(self.sum, 6),
            "mean": round(self.sum / self.count, 6) if self.count else 0.0,
            "min": round(self.min, 6),
            "max": round(self.max, 6),
            "p50": round(s.quantile(0.5), 6),
            "p90": round(s.quantile(0.9), 6),
            "p99": round(s.quantile(0.99), 6),
        }


class Telemetry:
    """Per-cycle sample sink: raw ring + rollup windows (see module
    docstring). All mutation happens on the scheduler thread once per
    cycle; the lock exists for the HTTP/dump readers."""

    def __init__(
        self,
        window_cycles: Optional[int] = None,
        max_windows: Optional[int] = None,
        raw_capacity: int = DEFAULT_RAW_CAPACITY,
    ):
        if window_cycles is None:
            window_cycles = int(os.environ.get(
                TELEMETRY_WINDOW_ENV, DEFAULT_WINDOW_CYCLES
            ))
        if max_windows is None:
            max_windows = int(os.environ.get(
                TELEMETRY_WINDOWS_ENV, DEFAULT_MAX_WINDOWS
            ))
        self._lock = wrap_lock("obs.telemetry")
        self._cache_ref = None          # weakref to the fed SchedulerCache
        self._fair_state: dict = {}     # fairness probe memo (node total)
        self.configure(window_cycles, max_windows, raw_capacity)
        # KBT_LOCK_DEBUG=2 write-witness (no-op otherwise). configure()
        # re-arms are fine: it writes under the lock.
        witness_writes(self, "obs.telemetry", (
            "window_cycles", "max_windows", "raw_capacity", "_raw",
            "_windows", "_open", "_open_start", "_open_cycles",
            "cycles_observed", "windows_rolled", "windows_dropped",
            "_last_cycle",
        ))

    def configure(
        self,
        window_cycles: int,
        max_windows: Optional[int] = None,
        raw_capacity: Optional[int] = None,
    ) -> None:
        """(Re)size and reset — the soak harness calls this so a 100k
        run's windows all fit the ring."""
        with self._lock:
            self.window_cycles = max(1, int(window_cycles))
            if max_windows is not None:
                self.max_windows = max(2, int(max_windows))
            elif not hasattr(self, "max_windows"):
                self.max_windows = DEFAULT_MAX_WINDOWS
            if raw_capacity is not None:
                self.raw_capacity = max(2, int(raw_capacity))
            elif not hasattr(self, "raw_capacity"):
                self.raw_capacity = DEFAULT_RAW_CAPACITY
            self._raw: deque = deque(maxlen=self.raw_capacity)
            self._windows: deque = deque(maxlen=self.max_windows)
            self._open: Dict[str, _KeyStats] = {}
            self._open_start: Optional[int] = None
            self._open_cycles = 0
            self.cycles_observed = 0
            self.windows_rolled = 0
            self.windows_dropped = 0
            self._last_cycle: Optional[int] = None

    def reset(self) -> None:
        self.configure(self.window_cycles)

    # -- ingest --------------------------------------------------------------

    def observe_values(self, values: Dict[str, float],
                       cycle: Optional[int] = None) -> None:
        """Fold one cycle's sample dict in. ``cycle`` defaults to a
        running counter; the raw ring keeps the dict verbatim."""
        with self._lock:
            if cycle is None:
                cycle = (
                    self._last_cycle + 1
                    if self._last_cycle is not None
                    else self.cycles_observed
                )
            # Deferred roll: a full window is closed by the NEXT
            # cycle's first sample (or flush()), not by its own last
            # sample — ``annotate_cycle`` additions arrive after
            # ``observe_values`` for the same cycle and must land in
            # the window that cycle belongs to, boundary cycles
            # included.
            if self._open_cycles >= self.window_cycles:
                self._roll_locked(
                    self._last_cycle if self._last_cycle is not None
                    else cycle
                )
            self._last_cycle = cycle
            self.cycles_observed += 1
            if self._open_start is None:
                self._open_start = cycle
            self._raw.append({"cycle": cycle, **values})
            for key, v in values.items():
                stats = self._open.get(key)
                if stats is None:
                    stats = self._open[key] = _KeyStats()
                try:
                    stats.add(float(v))
                except (TypeError, ValueError):
                    continue
            self._open_cycles += 1

    def annotate_cycle(self, values: Dict[str, float]) -> None:
        """Merge extra keys into the OPEN window without advancing the
        cycle count (the simulator's post-cycle additions: invariant
        violations, placements — they land after run_once already fed
        the window)."""
        with self._lock:
            for key, v in values.items():
                stats = self._open.get(key)
                if stats is None:
                    stats = self._open[key] = _KeyStats()
                try:
                    stats.add(float(v))
                except (TypeError, ValueError):
                    continue
            if self._raw:
                self._raw[-1].update(values)

    def _roll_locked(self, end_cycle: int) -> None:
        if not self._open:
            self._open_start = None
            self._open_cycles = 0
            return
        if len(self._windows) == self._windows.maxlen:
            self.windows_dropped += 1
        # Closed windows are stored SERIALIZED (one str per window, not
        # ~40 key-dicts of floats): the telemetry layer watches for
        # leaks, so its own resident footprint must be negligible —
        # with object windows the ring itself was the largest residual
        # allocator growth a 100k-cycle soak saw. Readers parse on
        # demand (end-of-run detectors, HTTP snapshots — both rare).
        import json

        # _open_start is None when the window only ever saw
        # annotate_cycle content (e.g. every cycle in it errored before
        # the observe_values feed): anchor it to end_cycle so readers
        # doing midpoint arithmetic never meet a None.
        self._windows.append(json.dumps({
            "start_cycle": (
                self._open_start if self._open_start is not None
                else end_cycle
            ),
            "end_cycle": end_cycle,
            "cycles": self._open_cycles,
            "t": round(time.time(), 3),
            "keys": {k: s.to_dict() for k, s in self._open.items()},
        }))
        self.windows_rolled += 1
        self._open = {}
        self._open_start = None
        self._open_cycles = 0

    def flush(self) -> None:
        """Close the open window early (end of a soak run: the tail
        cycles — including a deferred full window and its post-cycle
        annotations — must reach the detectors)."""
        with self._lock:
            if self._open_cycles or self._open:
                self._roll_locked(
                    self._last_cycle if self._last_cycle is not None else 0
                )

    # -- the production feed -------------------------------------------------

    def observe_scheduler_cycle(self, rec: Optional[dict],
                                cache=None) -> Dict[str, float]:
        """The per-cycle entry point ``Scheduler.run_once`` calls:
        extract the flight record's numeric attribution, add watermark
        (and, amortized, fairness) probes, fold the sample in, and push
        the watermark gauges to Prometheus. Returns the sample (bench
        uses it)."""
        values: Dict[str, float] = {}
        if rec:
            e2e = rec.get("e2e_ms")
            if e2e is not None:
                values["e2e_ms"] = float(e2e)
            for phase, ms in (rec.get("phases_ms") or {}).items():
                values[f"phase_ms:{phase}"] = float(ms)
            solver = rec.get("solver") or {}
            for key in ("placed", "tasks", "rounds",
                        "device_bytes_shipped", "device_rows_patched"):
                v = solver.get(key)
                if v is not None:
                    values[f"solver:{key}"] = float(v)
            # Placement-quality card (obs/quality.py, attached before
            # end_cycle on the KBT_QUALITY_EVERY cadence) → quality:*
            # series; cycles without a card simply lack the keys
            # (rollup windows tolerate sparse series).
            quality = rec.get("quality")
            if quality:
                try:
                    from .quality import telemetry_values

                    values.update(telemetry_values(quality))
                except Exception:  # pragma: no cover - probes only
                    logger.exception("quality telemetry flatten failed")
        if cache is not None:
            self._cache_ref = weakref.ref(cache)
        values.update(collect_watermarks(
            cache=cache,
            expensive=self.cycles_observed % EXPENSIVE_EVERY == 0,
        ))
        # Placement-latency series (obs/latency.py): ledger occupancy
        # (the leak watermark) + per-queue p99 arrival→bind latency —
        # the series the soak drift detector bounds so a slow
        # scheduling-latency regression fails a soak instead of hiding.
        try:
            from .latency import LEDGER

            if LEDGER.enabled:
                values.update(LEDGER.telemetry_sample())
        except Exception:  # pragma: no cover - probes must never kill
            logger.exception("placement-latency telemetry probe failed")
        fairness_ran = False
        if cache is not None and self.cycles_observed % FAIRNESS_EVERY == 0:
            try:
                values.update(collect_fairness(cache, self._fair_state))
                fairness_ran = True
            except Exception:  # pragma: no cover - forensics only
                logger.exception("fairness probe failed")
        self.observe_values(values)
        with self._lock:
            # A concurrent configure() rebinds the rings; snapshot the
            # watermark inputs under the same lock every other reader
            # holds (kbtlint guarded-by).
            raw_occupancy = len(self._raw)
            windows_rolled = self.windows_rolled
        try:
            from .. import metrics

            metrics.update_telemetry_watermarks(
                values,
                raw_occupancy=raw_occupancy,
                windows_rolled=windows_rolled,
                fairness_ran=fairness_ran,
            )
        except Exception:  # pragma: no cover - metrics must never kill
            logger.exception("telemetry metrics export failed")
        return values

    def attached_cache(self):
        """The most recently fed SchedulerCache (HTTP probes), or None."""
        ref = self._cache_ref
        return ref() if ref is not None else None

    # -- read side -----------------------------------------------------------

    def windows(self) -> List[dict]:
        import json

        with self._lock:
            raw = list(self._windows)
        return [json.loads(w) for w in raw]

    def raw(self, limit: Optional[int] = None) -> List[dict]:
        with self._lock:
            records = list(self._raw)
        return records[-limit:] if limit else records

    def keys(self) -> List[str]:
        seen = set()
        for w in self.windows():
            seen.update(w["keys"])
        with self._lock:
            seen.update(self._open)
        return sorted(seen)

    def snapshot(self, recent_raw: int = 64,
                 recent_windows: Optional[int] = None) -> dict:
        """The ``/debug/timeseries`` payload (also embedded in flight
        dumps): config, counters, the rolled windows (all of them, or
        the newest ``recent_windows``), and the newest raw samples."""
        import json

        # Copy refs under the lock, parse outside it (like windows()):
        # json.loads over up to max_windows serialized strings takes
        # milliseconds, and the scheduler's per-cycle feed blocks on
        # the same lock.
        with self._lock:
            windows = list(self._windows)
            raw = list(self._raw)[-recent_raw:]
            open_keys = sorted(self._open)
            meta = {
                "window_cycles": self.window_cycles,
                "max_windows": self.max_windows,
                "raw_capacity": self.raw_capacity,
                "cycles_observed": self.cycles_observed,
                "windows_rolled": self.windows_rolled,
                "windows_dropped": self.windows_dropped,
            }
        if recent_windows is not None:
            windows = windows[-recent_windows:]
        return {
            "type": "telemetry",
            **meta,
            "open_window_keys": open_keys,
            "windows": [json.loads(w) for w in windows],
            "raw_recent": raw,
        }


# -- watermark probes --------------------------------------------------------

_PAGE_SIZE = os.sysconf("SC_PAGE_SIZE") if hasattr(os, "sysconf") else 4096


def _rss_bytes() -> Optional[float]:
    try:
        with open("/proc/self/statm") as f:
            return float(int(f.read().split()[1]) * _PAGE_SIZE)
    except (OSError, ValueError, IndexError):
        return None


def collect_watermarks(cache=None, expensive: bool = True) -> Dict[str, float]:
    """One sample of every resource watermark the soak detectors fit
    growth on. Everything is guarded: a probe that cannot run (no
    /proc, jax not imported yet) is simply absent from the sample —
    detectors skip absent series. Nothing here *imports* heavy modules;
    probes only read state of subsystems already loaded.

    ``expensive=False`` skips the probes that are not O(1) counter
    reads (the /proc RSS read, ``jax.live_arrays``) — the scheduler
    feed passes it on 63 of 64 cycles (``EXPENSIVE_EVERY``) to stay
    inside the 1% cycle budget; on-demand callers (/debug/vars, soak
    window boundaries) get the full set."""
    import gc
    import sys

    values: Dict[str, float] = {}
    if expensive:
        rss = _rss_bytes()
        if rss is not None:
            values["rss_bytes"] = rss
        # NOT an O(1) counter on modern CPython: walks the allocator's
        # segments, ~250 µs on a 50k-scale heap.
        values["alloc_blocks"] = float(sys.getallocatedblocks())
    try:
        values["gc_gen2_collections"] = float(
            gc.get_stats()[2]["collections"]
        )
    except (IndexError, KeyError, TypeError):  # pragma: no cover
        pass

    # Observability rings (self-watermarks: the recorder infrastructure
    # must not itself leak).
    from .flightrecorder import RECORDER
    from .tracer import TRACER

    values["tracer_ring"] = float(len(TRACER._events))
    values["tracer_dropped"] = float(TRACER.dropped)
    values["flight_ring"] = float(len(RECORDER._ring))

    if expensive:
        # Iterates every registered metric's label map (O(series)).
        try:
            from .. import metrics

            values["metrics_series"] = float(
                metrics.REGISTRY.series_count()
            )
        except Exception:  # pragma: no cover - registry drift
            pass
    try:
        from . import explain

        values["explain_verdicts"] = float(len(explain.all_verdicts()))
    except Exception:  # pragma: no cover
        pass

    if "jax" in sys.modules:
        try:
            import jax

            if expensive:
                values["jax_live_buffers"] = float(
                    len(jax.live_arrays())
                )
            in_use = 0
            have = False
            for dev in jax.local_devices():
                stats = dev.memory_stats()
                if stats and "bytes_in_use" in stats:
                    in_use += stats["bytes_in_use"]
                    have = True
            if have:
                values["jax_device_memory_bytes"] = float(in_use)
        except Exception:  # pragma: no cover - backend quirk
            pass
    if expensive and "kube_batch_tpu.solver.kernels" in sys.modules:
        try:
            from ..solver.kernels import jit_compilation_count

            values["jit_cache_entries"] = float(jit_compilation_count())
        except Exception:  # pragma: no cover
            pass
    if cache is not None:
        dc = getattr(cache, "_device_snapshot_cache", None)
        if dc is not None:
            values["device_resident_bytes"] = float(
                sum(arr.nbytes for arr in dc.host.values())
            )
    # Carried-backlog depth (solver/warm.py): unplaced jobs the subset
    # solves are rotating through. A congested-but-keeping-up scheduler
    # holds this roughly flat; sustained growth means arrivals are
    # outpacing what the micro steady state retires — the soak growth
    # detector bounds the windowed slope.
    if cache is not None:
        ws = getattr(cache, "_warm_solve_state", None)
        if ws is not None and getattr(ws, "valid", False):
            values["carried_backlog_depth"] = float(len(ws.carried))
    return values


def collect_fairness(cache, state: Optional[dict] = None) -> Dict[str, float]:
    """Per-queue fairness drift: ``(allocated - deserved)`` on the
    dominant dimension, as a fraction of cluster capacity. Positive
    values mean the queue holds more than its water-filled deserved
    share; the soak detector bounds the windowed mean. Uses the
    maintained JobInfo aggregates (``allocated`` / ``total_request``)
    so the probe is O(jobs), and memoizes the O(nodes) cluster total in
    ``state`` keyed on the node count."""
    from ..api import Resource
    from ..sim.invariants import water_fill

    state = state if state is not None else {}
    with cache.mutex:
        queues = {q.name: q.weight for q in cache.queues.values()}
        if len(queues) < 2:
            return {}
        n_nodes = len(cache.nodes)
        probes = state.get("probes", 0) + 1
        state["probes"] = probes
        if (
            state.get("n_nodes") != n_nodes
            or probes % _NODE_TOTAL_REFRESH == 1
            or "total" not in state
        ):
            total = Resource.empty()
            for node in cache.nodes.values():
                if node.node is not None and node.ready():
                    total.add(node.allocatable)
            state["total"] = total
            state["n_nodes"] = n_nodes
        total = state["total"]
        allocated = {q: Resource.empty() for q in queues}
        requests = {q: Resource.empty() for q in queues}
        for job in cache.jobs.values():
            if job.queue not in queues:
                continue
            allocated[job.queue].add(job.allocated)
            requests[job.queue].add(job.total_request)
    deserved = water_fill(total, queues, requests)
    out: Dict[str, float] = {}
    for q in sorted(queues):
        drift = 0.0
        for dim in total.resource_names():
            cap = total.get(dim)
            if cap <= 0:
                continue
            d = (allocated[q].get(dim) - deserved[q].get(dim)) / cap
            if abs(d) > abs(drift):
                drift = d
        out[f"fairness_drift:{q}"] = drift
    return out


TELEMETRY = Telemetry()
