"""Scheduler core loop.

Mirrors reference pkg/scheduler/scheduler.go (:35 struct, :45 NewScheduler,
:63 Run — wait.Until(runOnce, period), :88 runOnce: OpenSession → execute
configured actions in order → CloseSession, with per-action latency metrics)
and pkg/scheduler/util.go (:44 loadSchedulerConf, :32 defaultSchedulerConf).
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Callable, List, Optional, Tuple

from . import metrics
from .conf import DEFAULT_SCHEDULER_CONF, Tier, parse_scheduler_conf
from .framework import Action, close_session, get_action, open_session
from .obs import RECORDER, export_trace, span
from .obs.tracer import TRACER, maybe_enable_from_env
from .utils import deferred_gc
from .utils.lockdebug import witness_writes, wrap_lock

logger = logging.getLogger(__name__)

# The running loop's watchdog (set by Scheduler.run when it starts
# one): the /debug/vars handler has no Scheduler reference, so the
# degraded-mode surface reads the live state from here.
ACTIVE_WATCHDOG: Optional["LoopWatchdog"] = None

# Most recent lease-TTL sanity verdict (Scheduler.check_lease_ttl —
# called by cli/server.py once the elector exists); surfaced in
# /debug/vars' robustness block like ACTIVE_WATCHDOG.
LEASE_TTL_CHECK: Optional[dict] = None


class LoopWatchdog:
    """No-cycle-progress detector: the last line of the solver
    fault-containment layer (doc/design/robustness.md).

    The in-cycle deadlines (``AsyncSolveHandle.fetch(timeout=...)``)
    bound the SOLVE; this thread bounds the whole cycle, catching hangs
    the fetch deadline cannot see — a wedged plugin, a deadlocked
    session close, a foreign call outside the solve. The scheduler
    stamps ``cycle_begin``/``cycle_end`` around ``run_once``; when a
    cycle stays in flight past ``budget`` seconds the watchdog trips
    ONCE for that cycle: flight recorder dumped (KBT_FLIGHT_DIR),
    ``scheduler_watchdog_trips_total`` bumped, and the ``on_trip``
    fencing callback fired — which tells the leader-election layer to
    stop renewing and release the lease, and fences the cache so the
    side-effect threads of this now-deposed leader can issue no binds.
    The wedged process is left to the operator (it may be unkillable
    from inside); what matters is the CLUSTER moves on to a new leader
    that is not hostage to this one's lease."""

    def __init__(
        self,
        budget: float,
        on_trip: Optional[Callable[[str], None]] = None,
        interval: Optional[float] = None,
    ):
        self.budget = float(budget)
        self.interval = interval or max(0.2, min(5.0, self.budget / 4.0))
        self.on_trip = on_trip
        self.trips = 0
        self.last_trip: Optional[dict] = None
        self._lock = wrap_lock("scheduler.watchdog")
        self._inflight_since: Optional[float] = None
        self._inflight_cycle: Optional[int] = None
        self._tripped_cycle: Optional[int] = None
        self._thread: Optional[threading.Thread] = None
        self._stop: Optional[threading.Event] = None
        # KBT_LOCK_DEBUG=2 write-witness (no-op otherwise). _thread/
        # _stop stay out: start() runs once before the thread exists.
        witness_writes(self, "scheduler.watchdog", (
            "_inflight_since", "_inflight_cycle", "_tripped_cycle",
            "trips", "last_trip",
        ))

    def cycle_begin(self, cycle: int) -> None:
        with self._lock:
            self._inflight_since = time.monotonic()
            self._inflight_cycle = cycle

    def cycle_end(self) -> None:
        with self._lock:
            self._inflight_since = None
            self._inflight_cycle = None

    def start(self, stop_event: threading.Event) -> None:
        self._stop = stop_event
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="kbt-loop-watchdog"
        )
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.check()
            except Exception:  # pragma: no cover - watchdog must survive
                logger.exception("loop watchdog check failed")

    def check(self, now: Optional[float] = None) -> bool:
        """One poll; returns True iff it tripped. Public so tests (and
        embedders without the thread) can drive it synchronously."""
        now = time.monotonic() if now is None else now
        with self._lock:
            since, cycle = self._inflight_since, self._inflight_cycle
            if (
                since is None
                or now - since <= self.budget
                or cycle == self._tripped_cycle
            ):
                return False
            self._tripped_cycle = cycle  # once per wedged cycle
            age = now - since
            self.trips += 1
            self.last_trip = {
                "cycle": cycle, "age_seconds": round(age, 3),
                "budget_seconds": self.budget, "ts": time.time(),
            }
        logger.error(
            "loop watchdog TRIPPED: cycle %s in flight %.1fs (budget "
            "%.1fs) — dumping flight recorder and fencing leadership",
            cycle, age, self.budget,
        )
        metrics.register_watchdog_trip()
        try:
            RECORDER.dump_on_error()
        except Exception:  # pragma: no cover - forensics only
            logger.exception("watchdog flight dump failed")
        if self.on_trip is not None:
            try:
                self.on_trip(
                    f"watchdog: cycle {cycle} exceeded "
                    f"{self.budget:.1f}s no-progress budget"
                )
            except Exception:  # pragma: no cover - fencing is best-effort
                logger.exception("watchdog on_trip fencing hook failed")
        return True

    def state_dict(self) -> dict:
        """/debug/vars snapshot."""
        with self._lock:
            inflight = (
                round(time.monotonic() - self._inflight_since, 3)
                if self._inflight_since is not None else None
            )
            return {
                "budget_seconds": self.budget,
                "trips": self.trips,
                "last_trip": dict(self.last_trip) if self.last_trip else None,
                "cycle_inflight_seconds": inflight,
            }


def load_scheduler_conf(confstr: str) -> Tuple[List[Action], List[Tier]]:
    """YAML policy → (ordered actions, plugin tiers). Misconfigured action
    names are a hard error (reference scheduler/util.go:44-72)."""
    conf = parse_scheduler_conf(confstr)
    actions: List[Action] = []
    for name in conf.actions.split(","):
        name = name.strip()
        if not name:
            continue
        action, found = get_action(name)
        if not found:
            raise ValueError(f"failed to find Action {name}, ignore it")
        actions.append(action)
    return actions, conf.tiers


class _WallClock:
    """Default scheduler pacing: real time. The simulator injects
    ``sim.clock.VirtualClock`` (same surface) to drive thousands of
    cycles in virtual time; ``real`` gates wall-clock-bounded side work
    (the think-time side-effect drain)."""

    real = True

    def now(self) -> float:
        return time.perf_counter()

    def wait(self, event: threading.Event, seconds: float) -> bool:
        if seconds <= 0:
            return event.is_set()
        return event.wait(seconds)


class Scheduler:
    # Per-cycle error backoff (capped exponential): a persistently
    # failing cycle must not busy-spin the loop, and a transient fault
    # (an injected bind storm, a wedged backend probe) must not kill the
    # process — the reference's wait.Until keeps the loop alive the same
    # way.
    CYCLE_ERROR_BACKOFF_BASE = 0.5
    CYCLE_ERROR_BACKOFF_MAX = 30.0

    def __init__(
        self,
        cache,
        scheduler_conf: Optional[str] = None,
        schedule_period: float = 1.0,
        clock=None,
    ):
        """scheduler_conf: YAML policy string or path to one; defaults to the
        reference default policy (allocate, backfill; 2 plugin tiers)."""
        # Ensure builtin registries are populated (blank-import analog,
        # reference cmd/kube-batch/main.go:33-35).
        from . import actions as _actions  # noqa: F401
        from . import plugins as _plugins  # noqa: F401

        self.cache = cache
        self.schedule_period = schedule_period
        self.clock = clock or _WallClock()
        self._error_streak = 0
        self._cycle_count = 0
        # Solver fault containment: stamp the process-wide solve budget
        # from this scheduler's period (solver/containment.py; the
        # simulator overrides it after construction with a small
        # real-time budget). The loop watchdog's no-progress budget sits
        # ABOVE the fetch deadline — the fetch recovering a hung solve
        # must never race the watchdog fencing the leader for it.
        from .solver import containment

        containment.configure_from_period(schedule_period)
        # solve_budget() (not the stamped value): the fetch deadline
        # honors a KBT_SOLVE_BUDGET override, and the watchdog budget
        # must track the deadline it sits above — otherwise a raised
        # solve budget lets the watchdog fence a healthy leader
        # mid-solve. 4x, not 2x: the degradation ladder's worst case is
        # THREE sequential budget-bounded rung attempts in one cycle
        # (sparse fails just under the budget, dense likewise, native
        # floor solves) — a cycle actively recovering down the ladder
        # must never be fenced as wedged.
        solve_budget = containment.solve_budget()
        default_budget = 4.0 * solve_budget + 10.0 * schedule_period
        env_budget = os.environ.get("KBT_WATCHDOG_BUDGET")
        self.watchdog_budget = default_budget
        if env_budget:
            try:
                parsed = float(env_budget)
            except ValueError:
                logger.warning(
                    "unparseable KBT_WATCHDOG_BUDGET=%r ignored "
                    "(using %.1fs)", env_budget, default_budget,
                )
            else:
                # <= 0 disables the watchdog (same as KBT_WATCHDOG=0):
                # a 0-second budget would fence a healthy leader on the
                # first poll of any in-flight cycle.
                self.watchdog_budget = parsed
        # Fencing callbacks beyond the cache (cli/server.py appends the
        # leader elector's fence); fired from the watchdog thread.
        self.fence_hooks: List[Callable[[str], None]] = []
        self.watchdog: Optional[LoopWatchdog] = None
        # Event-driven micro-cycles (KBT_MICRO=0 opts out): pod
        # arrivals wake the loop during think time and a bounded fast
        # path places them through the warm-start plan without waiting
        # for the period (doc/design/cycle-pipeline.md §micro steady
        # state). Under sustained arrivals micro cycles are the PRIMARY
        # placement path — noop/solve/subset warm outcomes all place —
        # and the periodic cycle is the reconciliation/fairness sweep
        # (preempt/reclaim, anti-entropy, journal pruning). A micro
        # cycle whose warm plan cannot engage places nothing and
        # defers.
        self.micro_enabled = os.environ.get("KBT_MICRO", "1") == "1"
        try:
            self.micro_max_per_period = max(
                1, int(os.environ.get("KBT_MICRO_MAX", "64"))
            )
        except ValueError:
            self.micro_max_per_period = 64
        # Coalescing window: KBT_MICRO_BATCH_MS=auto (default) tunes it
        # from the arrival-rate EWMA each micro wake-up — wait long
        # enough to coalesce ~KBT_MICRO_BATCH_TARGET arrivals, clamped
        # to [KBT_MICRO_BATCH_MIN_MS, KBT_MICRO_BATCH_MAX_MS]. A fixed
        # millisecond value pins it (the pre-r17 behavior).
        batch_ms = os.environ.get("KBT_MICRO_BATCH_MS", "auto")
        self.micro_batch_auto = batch_ms.strip().lower() in ("", "auto")
        if self.micro_batch_auto:
            self.micro_batch_window = 0.005
        else:
            try:
                self.micro_batch_window = max(0.0, float(batch_ms) / 1e3)
            except ValueError:
                self.micro_batch_auto = True
                self.micro_batch_window = 0.005

        def _ms_env(name: str, default: str) -> float:
            try:
                return max(
                    0.0, float(os.environ.get(name, default)) / 1e3
                )
            except ValueError:
                return float(default) / 1e3

        self.micro_batch_min = _ms_env("KBT_MICRO_BATCH_MIN_MS", "1")
        self.micro_batch_max = max(
            self.micro_batch_min, _ms_env("KBT_MICRO_BATCH_MAX_MS", "20")
        )
        try:
            self.micro_batch_target = max(
                1, int(os.environ.get("KBT_MICRO_BATCH_TARGET", "64"))
            )
        except ValueError:
            self.micro_batch_target = 64
        # Early periodic fairness pass (doc/design/serving.md): when a
        # pending serving pod has outlived its placement-latency target,
        # or the warm carried backlog is deeper than this threshold, the
        # think-time tail is cut short and the periodic cycle — the
        # preempt/reclaim/fairness authority — runs NOW instead of after
        # a micro-cycle storm finishes riding the period out (0
        # disables the backlog trigger).
        try:
            self.serving_early_backlog = max(
                0, int(os.environ.get("KBT_SERVING_EARLY_BACKLOG", "1024"))
            )
        except ValueError:
            self.serving_early_backlog = 1024
        self.early_fairness_passes = 0
        # Arrival-rate EWMA for the auto-tune (real-clock only: the
        # simulator drives micro cycles deterministically via
        # --micro-every and never enters _micro_wait, so this estimator
        # carries no replay taint).
        self._arrival_rate = 0.0
        self._arrival_count = 0
        self._arrival_mark = time.perf_counter()
        self.micro_window_last = self.micro_batch_window
        self._micro_arrival = threading.Event()
        self.micro_cycles_run = 0
        # KBT_TRACE_DIR arms the span tracer for the whole loop; the
        # trace file is written on loop exit and on cycle errors.
        maybe_enable_from_env()
        # Placement-latency ledger clock: stamps ride the scheduler's
        # injectable clock, so the simulator's ledger (and its audit
        # stream) run on virtual time — replay-deterministic by
        # construction (obs/latency.py).
        from .obs.latency import LEDGER

        LEDGER.configure(clock=self.clock.now)
        # Per-cycle telemetry feed (KBT_TELEMETRY=0 disables).
        from .obs.telemetry import telemetry_enabled_from_env

        self._telemetry = telemetry_enabled_from_env()
        confstr = scheduler_conf or DEFAULT_SCHEDULER_CONF
        if "\n" not in confstr and confstr.endswith((".yaml", ".yml")):
            with open(confstr) as f:
                confstr = f.read()
        self.actions, self.tiers = load_scheduler_conf(confstr)
        # Successor-recovery note for the first post-recovery cycle's
        # flight record (recover_from_journal sets it; run_once drains
        # it into RECORDER.annotate("recovery", ...)).
        self._pending_recovery_note: Optional[dict] = None

    def check_lease_ttl(self, lease_duration: float) -> dict:
        """Lease-TTL sanity check (called by the server once the
        elector exists): a lease TTL shorter than the watchdog's
        no-progress budget means a healthy-but-slow leader — one the
        watchdog would deliberately NOT fence, e.g. a cycle riding the
        degradation ladder through three budget-bounded rung attempts —
        can lose its lease mid-cycle if it stalls hard enough to miss
        renewals, handing the cluster a split recovery the fencing
        order was designed to prevent. Warn loudly and export the
        verdict (/debug/vars robustness.lease_ttl)."""
        global LEASE_TTL_CHECK

        verdict = {
            "lease_duration_seconds": float(lease_duration),
            "watchdog_budget_seconds": float(self.watchdog_budget),
            "sane": (
                self.watchdog_budget <= 0
                or lease_duration >= self.watchdog_budget
            ),
        }
        if not verdict["sane"]:
            logger.warning(
                "elector lease TTL %.1fs is SHORTER than the watchdog "
                "no-progress budget %.1fs: a healthy-but-slow leader "
                "can lose its lease mid-cycle before the watchdog "
                "would fence it — raise the lease duration or lower "
                "KBT_WATCHDOG_BUDGET",
                lease_duration, self.watchdog_budget,
            )
        LEASE_TTL_CHECK = verdict
        return verdict

    def recover_from_journal(self):
        """Successor recovery pass (cache/recovery.py): after lease
        acquisition and cache sync, reconcile the bind-intent journal
        a dead predecessor left behind against cluster truth — classify
        every in-flight bind, re-drive or revert, repair partial gangs
        — BEFORE the first scheduling cycle plans against a state it
        doesn't understand. Returns the RecoveryReport, or None when
        the cluster has no journal seam or KBT_RECOVERY=0."""
        cluster = getattr(self.cache, "cluster", None)
        if cluster is None or not getattr(
            cluster, "supports_bind_journal", False
        ):
            return None
        if os.environ.get("KBT_RECOVERY", "1") == "0":
            return None
        from .cache.recovery import reconcile_journal

        identity = getattr(
            self.cache, "leader_identity", f"scheduler-{os.getpid()}"
        )
        report = reconcile_journal(cluster, identity)
        if report.intents_scanned or report.tasks_classified:
            self._pending_recovery_note = report.summary()
        return report

    def run_once_guarded(self) -> bool:
        """One cycle that cannot kill the loop: exceptions are logged,
        counted (``scheduler_cycle_errors_total``), and folded into the
        error streak that drives :meth:`cycle_error_backoff`. Returns
        True iff the cycle completed. Shared by :meth:`run` and the
        simulator's cycle driver, so a sim fault run exercises exactly
        the production error path."""
        try:
            try:
                self.run_once()
            finally:
                # An errored cycle still ENDED — the watchdog only
                # fences cycles that never come back.
                if self.watchdog is not None:
                    self.watchdog.cycle_end()
        except Exception as exc:
            self._error_streak += 1
            metrics.register_cycle_error()
            # Flight-recorder forensics: the open cycle record absorbs
            # the failing phase + traceback and is committed to the
            # ring; a dump file lands in KBT_FLIGHT_DIR when set, and a
            # Chrome trace alongside it when tracing is armed.
            RECORDER.record_error(exc)
            RECORDER.dump_on_error()
            export_trace(tag="trace-cycle-error")
            logger.exception(
                "scheduling cycle failed (streak %d, next backoff %.1fs)",
                self._error_streak, self.cycle_error_backoff(),
            )
            return False
        self._error_streak = 0
        return True

    def cycle_error_backoff(self) -> float:
        """Current retry delay: base * 2^(streak-1), capped."""
        if self._error_streak <= 0:
            return 0.0
        return min(
            self.CYCLE_ERROR_BACKOFF_BASE * (2 ** (self._error_streak - 1)),
            self.CYCLE_ERROR_BACKOFF_MAX,
        )

    def run(self, stop_event: Optional[threading.Event] = None) -> None:
        """reference scheduler.go:63-85"""
        from .obs import install_sigusr1

        stop = stop_event or threading.Event()
        clock = self.clock
        # Live-process forensics: SIGUSR1 dumps the flight-recorder ring
        # (no-op on non-main threads — the sim drives cycles directly).
        install_sigusr1()
        # Loop watchdog (KBT_WATCHDOG=0 disables): only the free-running
        # production loop gets one — run_once embedders and the
        # simulator bound their cycles themselves.
        if (os.environ.get("KBT_WATCHDOG", "1") != "0"
                and self.watchdog_budget > 0):
            self._run_stop = stop
            self.watchdog = LoopWatchdog(
                self.watchdog_budget, on_trip=self._on_watchdog_trip
            )
            self.watchdog.start(stop)
            global ACTIVE_WATCHDOG
            ACTIVE_WATCHDOG = self.watchdog
        self.cache.run(stop)
        self.cache.wait_for_cache_sync(stop)
        # Failover recovery BEFORE the first cycle: a successor must
        # classify the dead predecessor's in-flight binds (and repair
        # any gang left below minMember) before planning placements on
        # top of them. Guarded — a recovery error must not keep a
        # healthy leader from scheduling.
        try:
            self.recover_from_journal()
        except Exception:
            logger.exception("startup journal recovery failed; continuing")
        if self.micro_enabled:
            # Arm the arrival wake-up: pending pods of ours landing in
            # the mirror set the event the think-time wait below parks
            # on (cache/event_handlers.add_pod → _notify_arrival).
            arm = getattr(self.cache, "set_arrival_listener", None)
            if arm is not None:
                arm(self._note_arrival)
        while not stop.is_set():
            start = clock.now()
            if not self.run_once_guarded():
                clock.wait(stop, self.cycle_error_backoff())
                continue
            elapsed = clock.now() - start
            remaining = max(0.0, self.schedule_period - elapsed)
            if remaining > 0 and clock.real:
                # Think-time drain: absorb this cycle's async bind/evict
                # backlog while the loop would otherwise sleep, so the
                # next cycle's overlapped solve window starts from an
                # empty side-effect queue (allocate_tpu parks on the
                # same queue inside the solve's shadow). Sliced waits so
                # the stop event stays responsive mid-drain.
                deadline = time.perf_counter() + remaining
                try:
                    while not stop.is_set():
                        left = deadline - time.perf_counter()
                        if left <= 0:
                            break
                        if self.cache.wait_for_side_effects(
                            timeout=min(0.2, left)
                        ):
                            break
                except Exception:
                    logger.exception("think-time side-effect drain failed")
                if self.micro_enabled and self._micro_wait(stop, deadline):
                    # Fairness pressure (serving SLO burning or deep
                    # carried backlog): skip the rest of the think time
                    # and run the periodic fairness pass immediately.
                    self.early_fairness_passes += 1
                    continue
                remaining = max(0.0, deadline - time.perf_counter())
            clock.wait(stop, remaining)
        # Loop exit with tracing armed (KBT_TRACE_DIR): persist the
        # buffered spans so an operator-stopped run leaves a trace.
        export_trace(tag="trace")

    def _note_arrival(self) -> None:
        """Cache arrival-listener hook (one tick per arriving pod of
        ours): feed the rate estimator and wake the think-time wait."""
        self._arrival_count += 1
        self._micro_arrival.set()

    def _micro_tuned_window(self) -> float:
        """The coalescing window for the next micro cycle. Serving
        arrivals always get the MINIMUM window — coalescing buys
        throughput, and a serving pod pays for every waited millisecond
        out of its placement-latency SLO budget, so they are the
        highest-coalescing-priority class. With
        ``KBT_MICRO_BATCH_MS=auto`` (default) the window is otherwise
        sized from the ledger's MEASURED solve-stage p99
        (obs/latency.py): waiting to coalesce is free exactly while the
        wait stays below the per-cycle solve cost it amortizes, so the
        window tracks what solves actually cost on this cluster rather
        than a raw arrival-count guess. The arrival-rate EWMA remains
        as the cold-start fallback until the ledger has applied
        samples; clamped to [MIN_MS, MAX_MS] either way. A fixed value
        returns unchanged."""
        from .obs.latency import LEDGER

        if LEDGER.serving_arrival_pending():
            self.micro_window_last = self.micro_batch_min
            return self.micro_batch_min
        if not self.micro_batch_auto:
            self.micro_window_last = self.micro_batch_window
            return self.micro_batch_window
        window = None
        try:
            solve = LEDGER.stage_percentiles().get("solve")
            if solve and solve.get("count", 0) >= 8:
                window = min(
                    self.micro_batch_max,
                    max(self.micro_batch_min, float(solve["p99_s"])),
                )
        except Exception:  # pragma: no cover - tuning must not wedge
            window = None
        if window is None:
            now = time.perf_counter()
            dt = now - self._arrival_mark
            if dt >= 0.5:
                inst = self._arrival_count / dt
                self._arrival_count = 0
                self._arrival_mark = now
                self._arrival_rate = (
                    inst
                    if self._arrival_rate == 0.0
                    else 0.7 * self._arrival_rate + 0.3 * inst
                )
            rate = self._arrival_rate
            if rate <= 0.0:
                window = self.micro_batch_min
            else:
                window = min(
                    self.micro_batch_max,
                    max(
                        self.micro_batch_min,
                        self.micro_batch_target / rate,
                    ),
                )
        self.micro_window_last = window
        return window

    def _fairness_pressure(self) -> bool:
        """Whether the periodic fairness pass should run EARLY: a
        pending serving pod has outlived its placement-latency target
        (its SLO is burning while only warm-plan micro placements run),
        or the warm carried backlog is deeper than
        ``KBT_SERVING_EARLY_BACKLOG`` (deep carried work starves behind
        a micro-cycle storm — only the periodic preempt/reclaim sweep
        can evict room for it)."""
        from .obs.latency import LEDGER

        if LEDGER.serving_pressure():
            return True
        if self.serving_early_backlog <= 0:
            return False
        ws = getattr(self.cache, "_warm_solve_state", None)
        if ws is None or not getattr(ws, "valid", False):
            return False
        return len(ws.carried) > self.serving_early_backlog

    def _micro_wait(self, stop, deadline: float) -> bool:
        """Think-time tail with event-driven placement: park on the
        arrival event until the period deadline; each wake-up runs one
        bounded micro cycle (after the coalescing window — auto-tuned
        from the ledger's measured solve p99 by default — so a gang's
        pod burst lands in one cycle), at most ``micro_max_per_period``
        per period. A micro-cycle error falls through to the normal
        per-cycle error accounting — the periodic loop's backoff is not
        engaged (the next periodic cycle is the recovery authority).

        Returns True when fairness pressure (serving SLO burning, deep
        carried backlog — :meth:`_fairness_pressure`) says the periodic
        pass must run NOW; the run loop then skips the rest of the
        think time. The park is sliced so pressure that develops
        between arrivals (a pending serving deadline expiring) is seen
        within ~a quarter second, not at the period boundary."""
        used = 0
        while not stop.is_set():
            if self._fairness_pressure():
                return True
            if used >= self.micro_max_per_period:
                return False
            left = deadline - time.perf_counter()
            if left <= 0:
                return False
            if not self._micro_arrival.wait(timeout=min(left, 0.25)):
                continue
            window = self._micro_tuned_window()
            if window > 0:
                stop.wait(window)
            self._micro_arrival.clear()
            used += 1
            try:
                self.run_micro()
            except Exception:  # pragma: no cover - guarded inside
                logger.exception("micro cycle failed")
        return False

    def run_micro(self) -> bool:
        """One event-driven micro cycle: the allocate fast path between
        periodic cycles. Opens a REAL session (full plugin state — the
        placements it makes are exactly what the periodic cycle would
        have made) but runs only the micro-capable actions, each told
        via ``ssn.micro_cycle`` to place ONLY through the warm-start
        plan: if the plan cannot engage, the cycle places nothing and
        defers to the next periodic cycle, which remains the
        fairness/preempt authority. Returns True iff the cycle
        completed without error."""
        cycle = self._cycle_count
        self._cycle_count += 1
        TRACER.begin_cycle(cycle)
        RECORDER.begin_cycle(cycle, kind="micro")
        from .obs.latency import LEDGER

        LEDGER.begin_cycle(cycle, kind="micro")
        if self.watchdog is not None:
            self.watchdog.cycle_begin(cycle)
        cycle_start = time.perf_counter()
        ok = True
        try:
            with span("cycle"):
                with deferred_gc():
                    RECORDER.phase("open_session")
                    t0 = time.perf_counter()
                    with span("open_session"):
                        ssn = open_session(
                            self.cache, self.tiers, micro=True
                        )
                    RECORDER.phase_done(
                        "open_session", (time.perf_counter() - t0) * 1e3
                    )
                    try:
                        for action in self.actions:
                            if not getattr(action, "micro_capable", False):
                                continue
                            name = action.name()
                            RECORDER.phase(f"action:{name}")
                            action_start = time.perf_counter()
                            with span(f"action:{name}"):
                                action.initialize()
                                action.execute(ssn)
                                action.un_initialize()
                            elapsed = time.perf_counter() - action_start
                            metrics.update_action_duration(name, elapsed)
                            RECORDER.phase_done(
                                f"action:{name}", elapsed * 1e3
                            )
                    except BaseException:
                        RECORDER.mark_failed_phase()
                        raise
                    finally:
                        RECORDER.phase("close_session")
                        t0 = time.perf_counter()
                        with span("close_session"):
                            close_session(ssn)
                        RECORDER.phase_done(
                            "close_session", (time.perf_counter() - t0) * 1e3
                        )
        except Exception as exc:
            ok = False
            metrics.register_cycle_error()
            RECORDER.record_error(exc)
            RECORDER.dump_on_error()
            logger.exception("micro cycle failed")
        finally:
            if self.watchdog is not None:
                self.watchdog.cycle_end()
        e2e = time.perf_counter() - cycle_start
        metrics.update_e2e_duration(e2e)
        RECORDER.phase("done")
        # Quality scorecard BEFORE end_cycle: the card rides in this
        # cycle's still-open flight record (micro cycles count toward
        # the KBT_QUALITY_EVERY cadence exactly like the telemetry
        # probes — under micro-primary steady state the card must not
        # go stale). Guarded: a probe failure never fails a cycle.
        try:
            from .obs.quality import QUALITY

            QUALITY.annotate_cycle(self.cache)
        except Exception:
            logger.exception("quality cycle feed failed")
        rec = RECORDER.end_cycle(ok=ok, e2e_ms=round(e2e * 1e3, 3))
        self.micro_cycles_run += 1
        if self._telemetry:
            try:
                from .obs.telemetry import TELEMETRY

                TELEMETRY.observe_scheduler_cycle(rec, cache=self.cache)
            except Exception:
                logger.exception("telemetry cycle feed failed")
        return ok

    def _on_watchdog_trip(self, reason: str) -> None:
        """Fencing half of a watchdog trip: this (possibly wedged)
        process must lose the power to mutate the cluster BEFORE a
        successor takes the lease — cache side-effect threads refuse
        binds/evicts from here on, and every registered fence hook
        (the leader elector: stop renewing, release) fires."""
        # Cache fence FIRST: it is non-blocking by construction (its
        # own lock, never cache.mutex — the wedged cycle may hold the
        # mutex), while the elector's fence can block draining its
        # renew thread. Releasing the lease before the fence lands
        # would let this leader's queued side-effect threads keep
        # binding while a successor starts placing the same tasks —
        # the process must lose bind power BEFORE anyone else can
        # take the lease.
        fence = getattr(self.cache, "fence", None)
        if fence is not None:
            fence(reason)
        for hook in self.fence_hooks:
            try:
                hook(reason)
            except Exception:  # pragma: no cover - fencing best-effort
                logger.exception("fence hook failed")
        # A fenced scheduler can never bind again — stop the run loop
        # so the process exits (and a supervisor restarts it) instead
        # of spinning CacheFencedError cycles forever. With an elector
        # the lost-leadership path stops it anyway; standalone (no
        # fence hooks) this is the only exit.
        run_stop = getattr(self, "_run_stop", None)
        if run_stop is not None:
            run_stop.set()

    def run_once(self) -> None:
        """One scheduling cycle (reference scheduler.go:88-103). GC is
        deferred for the cycle's duration — collections triggered by the
        apply phase's allocation burst otherwise stop the world mid-cycle
        (~350 ms at 50k tasks); the deferred collection runs in the
        scheduler's think-time gap instead (utils/gc_guard.py).

        Instrumented end to end: every phase runs under a tracer span
        and stamps the flight recorder's open cycle record, so an error
        dump names the phase that raised and the Chrome trace shows the
        phase timeline across the overlap window's worker threads."""
        cycle = self._cycle_count
        self._cycle_count += 1
        TRACER.begin_cycle(cycle)
        RECORDER.begin_cycle(cycle)
        from .obs.latency import LEDGER

        LEDGER.begin_cycle(cycle, kind="periodic")
        if self._pending_recovery_note is not None:
            # First post-recovery cycle: the failover reconciliation's
            # outcome rides in this cycle's flight record, so an error
            # dump (or the sim's trace) shows what recovery changed
            # underneath the cycle that then ran.
            RECORDER.annotate("recovery", self._pending_recovery_note)
            self._pending_recovery_note = None
        if self.watchdog is not None:
            self.watchdog.cycle_begin(cycle)
        # Anti-entropy sweep (cache/antientropy.py) BEFORE the session
        # opens: divergence repairs land in the mirror + dirty ledger
        # first, so this cycle's snapshot — and the warm-start plan
        # judging it — already sees the reconciled world. Periodic
        # cycles only (run_micro never sweeps); cadence and budget are
        # the sweeper's own (KBT_ANTIENTROPY_EVERY), and a sweep failure
        # never fails the cycle.
        with span("antientropy"):
            self.cache.run_antientropy_if_due()
        cycle_start = time.perf_counter()
        with span("cycle"):
            with deferred_gc():
                RECORDER.phase("open_session")
                t0 = time.perf_counter()
                with span("open_session"):
                    ssn = open_session(self.cache, self.tiers)
                RECORDER.phase_done(
                    "open_session", (time.perf_counter() - t0) * 1e3
                )
                try:
                    for action in self.actions:
                        name = action.name()
                        RECORDER.phase(f"action:{name}")
                        action_start = time.perf_counter()
                        with span(f"action:{name}"):
                            action.initialize()
                            action.execute(ssn)
                            action.un_initialize()
                        elapsed = time.perf_counter() - action_start
                        metrics.update_action_duration(name, elapsed)
                        RECORDER.phase_done(
                            f"action:{name}", elapsed * 1e3
                        )
                except BaseException:
                    # Pin the phase that actually raised before the
                    # finally's close_session overwrites it — the error
                    # dump must name the FAILING phase.
                    RECORDER.mark_failed_phase()
                    raise
                finally:
                    RECORDER.phase("close_session")
                    t0 = time.perf_counter()
                    with span("close_session"):
                        close_session(ssn)
                    RECORDER.phase_done(
                        "close_session", (time.perf_counter() - t0) * 1e3
                    )
        e2e = time.perf_counter() - cycle_start
        metrics.update_e2e_duration(e2e)
        RECORDER.phase("done")
        # Quality scorecard BEFORE end_cycle (see run_micro).
        try:
            from .obs.quality import QUALITY

            QUALITY.annotate_cycle(self.cache)
        except Exception:
            logger.exception("quality cycle feed failed")
        rec = RECORDER.end_cycle(e2e_ms=round(e2e * 1e3, 3))
        # Long-horizon telemetry: fold this cycle's record + resource
        # watermarks into the time-series (obs/telemetry.py). Guarded —
        # a probe failure must never fail a cycle.
        if self._telemetry:
            try:
                from .obs.telemetry import TELEMETRY

                TELEMETRY.observe_scheduler_cycle(rec, cache=self.cache)
            except Exception:
                logger.exception("telemetry cycle feed failed")
