"""Seeded synthetic workload generator.

Emits per-cycle EVENT DICTS (the trace's lingua franca — the harness
applies the same dicts whether they come from this generator or from a
replayed trace): gang arrivals drawn from a size/req mix, completions
after a seeded fully-running duration, and planned node add/drain
churn. All randomness flows from one named ``random.Random`` stream so
a (seed, spec) pair always yields the same event sequence; nothing here
reads the wall clock (timestamps are virtual-time values the harness
passes in).
"""

from __future__ import annotations

import random
from dataclasses import dataclass, field
from typing import Dict, List, Sequence, Tuple


@dataclass
class WorkloadSpec:
    """Knobs of the synthetic cluster + arrival process."""

    nodes: int = 12
    node_cpu_m: int = 8000          # per-node allocatable millicores
    node_mem_mi: int = 16384        # per-node allocatable MiB
    queues: Dict[str, int] = field(
        default_factory=lambda: {"default": 1, "batch": 2}
    )
    # (gang size, weight) mix; min_member == size (full gangs).
    gang_sizes: Sequence[Tuple[int, float]] = (
        (1, 0.45), (2, 0.25), (4, 0.2), (8, 0.1)
    )
    # (cpu_m, mem_mi, weight) per-task request mix.
    reqs: Sequence[Tuple[int, int, float]] = (
        (500, 512, 0.6), (1000, 1024, 0.3), (2000, 2048, 0.1)
    )
    arrival_rate: float = 1.5       # expected job arrivals per cycle
    # Arrival profile (the high-arrival SLI mixes, obs/latency.py):
    # - "poisson":   seeded Poisson draws at arrival_rate (default);
    # - "sustained": exactly round(arrival_rate) jobs EVERY cycle — a
    #   flat firehose with no draw jitter (the 10k+ arrivals/s-
    #   equivalent sustained mix is this with a large rate);
    # - "burst":     Poisson base rate plus a spike of burst_size jobs
    #   every burst_every cycles (thundering-herd arrival waves).
    arrival_profile: str = "poisson"
    burst_every: int = 16           # cycles between burst spikes
    burst_size: int = 64            # jobs per burst spike
    duration_cycles: Tuple[int, int] = (4, 16)  # fully-running lifetime
    max_jobs_in_flight: int = 64    # arrival back-pressure bound
    # Planned churn: per-cycle probability of one node-add / node-drain
    # event (drain deletes the node; its pods are killed and recreated
    # as Pending by the harness — the replicaset-controller analog).
    node_add_rate: float = 0.0
    node_drain_rate: float = 0.0
    min_nodes: int = 4
    max_nodes: int = 64
    # -- serving mix (doc/design/serving.md). serving_rate == 0 keeps
    # the generator BYTE-IDENTICAL to the batch-only stream: no extra
    # rng draws, no label/annotation keys on any event (the batch-only
    # bit-parity contract rides on this).
    serving_rate: float = 0.0       # expected serving arrivals per cycle
    serving_sizes: Sequence[Tuple[int, float]] = (
        (2, 0.5), (4, 0.35), (8, 0.15)
    )
    serving_duration: Tuple[int, int] = (32, 128)  # long-lived deployments
    serving_slo_s: float = 2.0      # placement-latency target (virtual s)
    serving_floor_frac: float = 0.5  # replica floor = ceil(size * frac)
    serving_reserved_frac: float = 0.5  # P(job is spot-excluded)
    serving_gen_frac: float = 0.25  # P(job pins one TPU generation)
    serving_churn: float = 0.0      # per-cycle P(one replica churns)
    serving_queue: str = "serving"
    # Node classes (labels ride node-add events, only when a serving
    # mix is configured): generation/tier cycle deterministically over
    # the node index; reserved_frac of nodes are reserved, rest spot
    # (10% granularity).
    reserved_frac: float = 1.0
    node_generations: Sequence[str] = ("v5e", "v5p")
    node_tiers: int = 1

    def to_dict(self) -> dict:
        return {
            "nodes": self.nodes,
            "node_cpu_m": self.node_cpu_m,
            "node_mem_mi": self.node_mem_mi,
            "queues": dict(self.queues),
            "gang_sizes": [list(g) for g in self.gang_sizes],
            "reqs": [list(r) for r in self.reqs],
            "arrival_rate": self.arrival_rate,
            "arrival_profile": self.arrival_profile,
            "burst_every": self.burst_every,
            "burst_size": self.burst_size,
            "duration_cycles": list(self.duration_cycles),
            "max_jobs_in_flight": self.max_jobs_in_flight,
            "node_add_rate": self.node_add_rate,
            "node_drain_rate": self.node_drain_rate,
            "min_nodes": self.min_nodes,
            "max_nodes": self.max_nodes,
            "serving_rate": self.serving_rate,
            "serving_sizes": [list(s) for s in self.serving_sizes],
            "serving_duration": list(self.serving_duration),
            "serving_slo_s": self.serving_slo_s,
            "serving_floor_frac": self.serving_floor_frac,
            "serving_reserved_frac": self.serving_reserved_frac,
            "serving_gen_frac": self.serving_gen_frac,
            "serving_churn": self.serving_churn,
            "serving_queue": self.serving_queue,
            "reserved_frac": self.reserved_frac,
            "node_generations": list(self.node_generations),
            "node_tiers": self.node_tiers,
        }


def _poisson(rng: random.Random, lam: float) -> int:
    """Knuth inverse-transform Poisson sample off the seeded stream."""
    if lam <= 0:
        return 0
    import math

    limit = math.exp(-lam)
    k, p = 0, 1.0
    while True:
        p *= rng.random()
        if p <= limit:
            return k
        k += 1


def _weighted(rng: random.Random, mix: Sequence[tuple]):
    """Pick an entry from a (..., weight) mix."""
    total = sum(m[-1] for m in mix)
    x = rng.random() * total
    for m in mix:
        x -= m[-1]
        if x <= 0:
            return m
    return mix[-1]


class WorkloadGenerator:
    """Per-cycle event emitter; the harness feeds back observed state
    (which jobs are fully running, which nodes exist) through the
    ``running_since`` / ``node_names`` arguments — both derived from
    deterministic cluster state, so the feedback loop stays replayable."""

    def __init__(self, spec: WorkloadSpec, seed: int):
        self.spec = spec
        self.rng = random.Random(f"{seed}/workload")
        self._job_seq = 0
        self._node_seq = spec.nodes
        # name -> {"duration": d, "min_member": m}; jobs the generator
        # considers alive (created, not yet deleted).
        self.alive: Dict[str, dict] = {}
        self._pending_delete: List[str] = []

    # -- bootstrap -----------------------------------------------------------

    def _serving_enabled(self) -> bool:
        return self.spec.serving_rate > 0

    def initial_events(self) -> List[dict]:
        queues = dict(self.spec.queues)
        if self._serving_enabled():
            queues.setdefault(self.spec.serving_queue, 2)
        events = [
            {"kind": "queue-add", "name": name, "weight": weight}
            for name, weight in sorted(queues.items())
        ]
        events.extend(
            self._node_event(f"sim-node-{i:03d}", i)
            for i in range(self.spec.nodes)
        )
        return events

    def _node_event(self, name: str, index: int) -> dict:
        event = {
            "kind": "node-add",
            "name": name,
            "cpu_m": self.spec.node_cpu_m,
            "mem_mi": self.spec.node_mem_mi,
        }
        if self._serving_enabled():
            event["labels"] = self._node_labels(index)
        return event

    def _node_labels(self, index: int) -> Dict[str, str]:
        """Node-class labels (api/serving.py schema), a pure function
        of the node INDEX so churn-added nodes land in deterministic
        classes under replay."""
        from ..api import (
            CAPACITY_SPOT,
            CAPACITY_TYPE_LABEL_KEY,
            TOPOLOGY_TIER_LABEL_KEY,
            TPU_GENERATION_LABEL_KEY,
        )

        spec = self.spec
        labels: Dict[str, str] = {}
        if spec.node_generations:
            labels[TPU_GENERATION_LABEL_KEY] = spec.node_generations[
                index % len(spec.node_generations)
            ]
        if spec.node_tiers > 1:
            labels[TOPOLOGY_TIER_LABEL_KEY] = str(index % spec.node_tiers)
        reserved_slots = int(round(max(0.0, min(1.0, spec.reserved_frac)) * 10))
        if index % 10 >= reserved_slots:
            labels[CAPACITY_TYPE_LABEL_KEY] = CAPACITY_SPOT
        return labels

    # -- per cycle -----------------------------------------------------------

    def events_for_cycle(
        self,
        cycle: int,
        running_since: Dict[str, int],
        node_names: Sequence[str],
    ) -> List[dict]:
        spec, rng = self.spec, self.rng
        events: List[dict] = []

        # Deletions scheduled by last cycle's completions run first so
        # the job's Succeeded pods leave before new arrivals land.
        for name in self._pending_delete:
            events.append({"kind": "job-delete", "name": name})
            self.alive.pop(name, None)
        self._pending_delete = []

        # Completions: a job that has been fully running for its seeded
        # duration succeeds now and is deleted next cycle (exercising
        # the terminated-job cleanup path in between).
        for name in sorted(self.alive):
            since = running_since.get(name)
            if since is None:
                continue
            if cycle - since >= self.alive[name]["duration"]:
                events.append({"kind": "job-complete", "name": name})
                self._pending_delete.append(name)

        # Node churn (planned, seeded).
        n_nodes = len(node_names)
        if (
            spec.node_add_rate > 0
            and n_nodes < spec.max_nodes
            and rng.random() < spec.node_add_rate
        ):
            index = self._node_seq
            self._node_seq += 1
            events.append(self._node_event(f"sim-node-{index:03d}", index))
        if (
            spec.node_drain_rate > 0
            and n_nodes > spec.min_nodes
            and rng.random() < spec.node_drain_rate
        ):
            victim = rng.choice(sorted(node_names))
            events.append(
                {"kind": "node-remove", "name": victim, "reason": "drain"}
            )

        # Serving arrivals + replica churn FIRST (highest-priority
        # class; their draws only happen when a serving mix is
        # configured, so batch-only streams stay byte-identical).
        if self._serving_enabled():
            events.extend(
                self._serving_events(cycle, running_since)
            )

        # Arrivals (profile-shaped; every random draw stays on the one
        # seeded stream so (seed, spec) still pins the event sequence).
        if spec.arrival_profile == "sustained":
            arrivals = max(0, int(round(spec.arrival_rate)))
        else:
            arrivals = _poisson(rng, spec.arrival_rate)
            if (
                spec.arrival_profile == "burst"
                and spec.burst_every > 0
                and cycle % spec.burst_every == 0
            ):
                arrivals += max(0, int(spec.burst_size))
        for _ in range(arrivals):
            if len(self.alive) - len(self._pending_delete) >= (
                spec.max_jobs_in_flight
            ):
                break
            size = int(_weighted(rng, spec.gang_sizes)[0])
            cpu_m, mem_mi, _ = _weighted(rng, spec.reqs)
            queue = sorted(spec.queues)[
                rng.randrange(len(spec.queues))
            ]
            duration = rng.randint(*spec.duration_cycles)
            name = f"simjob-{self._job_seq:05d}"
            self._job_seq += 1
            self.alive[name] = {"duration": duration, "min_member": size}
            events.append({
                "kind": "job-create",
                "name": name,
                "queue": queue,
                "replicas": size,
                "min_member": size,
                "cpu_m": int(cpu_m),
                "mem_mi": int(mem_mi),
                "duration": duration,
            })
        return events

    # -- serving mix ---------------------------------------------------------

    def _serving_events(
        self, cycle: int, running_since: Dict[str, int]
    ) -> List[dict]:
        """Serving deployment arrivals (annotated per the api/serving.py
        schema) and replica churn: one replica of a running serving job
        is deleted and a fresh Pending replacement created — the
        rolling-restart analog, re-measuring placement latency on the
        replacement."""
        import math

        from ..api import (
            REPLICA_FLOOR_ANNOTATION_KEY,
            RESERVED_ONLY_ANNOTATION_KEY,
            SLO_SECONDS_ANNOTATION_KEY,
            TPU_GENERATIONS_ANNOTATION_KEY,
            WORKLOAD_CLASS_ANNOTATION_KEY,
            WORKLOAD_CLASS_SERVING,
        )

        spec, rng = self.spec, self.rng
        events: List[dict] = []

        # Replica churn on one running serving job.
        if spec.serving_churn > 0 and rng.random() < spec.serving_churn:
            candidates = sorted(
                name for name, meta in self.alive.items()
                if meta.get("serving")
                and meta.get("replicas")
                and name in running_since
                and name not in self._pending_delete
            )
            if candidates:
                job = candidates[rng.randrange(len(candidates))]
                meta = self.alive[job]
                victim = meta["replicas"].pop(0)
                churned = meta.get("churned", 0)
                meta["churned"] = churned + 1
                replacement = f"{job}-c{churned}"
                meta["replicas"].append(replacement)
                events.append({
                    "kind": "pod-delete",
                    "pod": f"sim/{victim}",
                })
                events.append({
                    "kind": "pod-recreate",
                    "job": job,
                    "names": [replacement],
                })

        arrivals = _poisson(rng, spec.serving_rate)
        for _ in range(arrivals):
            if len(self.alive) - len(self._pending_delete) >= (
                spec.max_jobs_in_flight
            ):
                break
            size = int(_weighted(rng, spec.serving_sizes)[0])
            cpu_m, mem_mi, _ = _weighted(rng, spec.reqs)
            duration = rng.randint(*spec.serving_duration)
            floor = max(
                1, math.ceil(size * max(0.0, spec.serving_floor_frac))
            )
            annotations = {
                WORKLOAD_CLASS_ANNOTATION_KEY: WORKLOAD_CLASS_SERVING,
                SLO_SECONDS_ANNOTATION_KEY: str(spec.serving_slo_s),
                REPLICA_FLOOR_ANNOTATION_KEY: str(floor),
            }
            if rng.random() < spec.serving_reserved_frac:
                annotations[RESERVED_ONLY_ANNOTATION_KEY] = "1"
            if (
                spec.node_generations
                and rng.random() < spec.serving_gen_frac
            ):
                annotations[TPU_GENERATIONS_ANNOTATION_KEY] = (
                    spec.node_generations[
                        rng.randrange(len(spec.node_generations))
                    ]
                )
            name = f"simserve-{self._job_seq:05d}"
            self._job_seq += 1
            self.alive[name] = {
                "duration": duration,
                "min_member": 1,
                "serving": True,
                "replicas": [f"{name}-{i}" for i in range(size)],
                "churned": 0,
            }
            events.append({
                "kind": "job-create",
                "name": name,
                "queue": spec.serving_queue,
                "replicas": size,
                "min_member": 1,
                "cpu_m": int(cpu_m),
                "mem_mi": int(mem_mi),
                "duration": duration,
                "annotations": dict(annotations),
                "replica_floor": floor,
            })
        return events

