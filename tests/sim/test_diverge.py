"""Event-stream fault storms (doc/design/simulator.md): the full
divergence grammar through the real scheduler — drops/dups/reorders/
stale deliveries absorbed or repaired, injected relist failures
retried, corrupted solver results rejected — with zero invariant
violations, every divergence repaired by run end, and byte-equal
placement replay."""

import pytest

from kube_batch_tpu.sim.harness import ClusterSimulator, SimConfig
from kube_batch_tpu.sim.trace import TraceReader
from kube_batch_tpu.sim.workload import WorkloadSpec

STORM = (
    "event-drop:0.06,event-dup:0.06,event-reorder:0.05,"
    "event-stale:0.05,relist-fail:0.25,solver-corrupt:0.04,bind:0.03"
)


def run_sim(tmp_path, cycles=120, seed=15, faults=STORM, replay=None,
            trace_name="diverge.jsonl"):
    cfg = SimConfig(
        cycles=cycles,
        seed=seed,
        faults=faults,
        backend="dense",
        workload=WorkloadSpec(
            nodes=10, queues={"default": 1, "batch": 2},
            arrival_rate=1.5, node_add_rate=0.02, node_drain_rate=0.02,
        ),
        trace_path=str(tmp_path / trace_name),
        replay=replay,
        antientropy_every=1,
    )
    sim = ClusterSimulator(cfg)
    report = sim.run()
    return report, cfg


class TestDivergeStorm:
    def test_storm_repairs_everything(self, tmp_path):
        report, cfg = run_sim(tmp_path)
        assert report.violations == []
        assert report.cycle_errors == 0
        # Every grammar kind actually fired.
        for kind in ("event-drop", "event-dup", "event-reorder",
                     "event-stale", "relist-fail", "solver-corrupt"):
            assert report.fault_counts.get(kind, 0) > 0, (
                kind, report.fault_counts,
            )
        integrity = report.integrity
        assert integrity is not None
        assert integrity["unrepaired_end"] == 0
        # Drops created real divergence and the machinery repaired it.
        assert sum(integrity["divergence_detected"].values()) > 0
        assert (
            integrity["divergence_detected"]
            == integrity["divergence_repaired"]
        )
        # Corrupted solver results were rejected before dispatch.
        assert integrity["validation_rejected"] > 0
        # The ingest guards absorbed dup/stale deliveries.
        assert integrity["anomalies"].get("duplicate", 0) > 0
        assert integrity["anomalies"].get("stale", 0) > 0

    def test_storm_replays_byte_equal(self, tmp_path):
        report, cfg = run_sim(tmp_path)
        assert report.violations == []
        replay = TraceReader.load(cfg.trace_path)
        report2, _ = run_sim(
            tmp_path, replay=replay, trace_name="diverge-replay.jsonl"
        )
        assert report2.replay_mismatches == []
        assert report2.violations == []
        assert report2.integrity["unrepaired_end"] == 0
        # Placement totals identical (the byte-level check is the
        # per-cycle verifier feeding replay_mismatches).
        assert report2.placements == report.placements

    def test_event_faults_require_nothing_special_native(self, tmp_path):
        """Event-stream kinds work on the native backend too (they hit
        the watch seam, not the device) — only solver-corrupt needs a
        device rung."""
        report, _ = run_sim(
            tmp_path, cycles=60,
            faults="event-drop:0.08,event-dup:0.08,relist-fail:0.3",
        )
        assert report.violations == []
        assert report.integrity["unrepaired_end"] == 0
        assert report.fault_counts.get("event-drop", 0) > 0

    def test_solver_corrupt_rejected_on_native_backend_spec(self):
        """solver-corrupt without a device backend is a vacuous storm —
        rejected up front like the other device kinds."""
        cfg = SimConfig(
            cycles=10, seed=1, faults="solver-corrupt:0.5",
            backend="native",
        )
        with pytest.raises(ValueError, match="device backend"):
            ClusterSimulator(cfg)


@pytest.mark.slow
class TestDivergeAcceptance:
    def test_2k_storm(self, tmp_path):
        """The DIVERGE_r15 acceptance shape: 2k cycles, all six kinds,
        zero violations, every divergence repaired, replay byte-equal."""
        report, cfg = run_sim(tmp_path, cycles=2000, seed=15)
        assert report.violations == []
        assert report.cycle_errors == 0
        assert report.integrity["unrepaired_end"] == 0
        replay = TraceReader.load(cfg.trace_path)
        report2, _ = run_sim(
            tmp_path, replay=replay, trace_name="diverge-2k-replay.jsonl"
        )
        assert report2.replay_mismatches == []
