"""ClusterSimulator: the event loop that drives the real scheduler.

One simulated cycle:

1. apply this cycle's EVENTS (workload arrivals/completions/churn —
   from the seeded generator, or verbatim from a replayed trace);
2. apply + arm this cycle's FAULTS (planned from the seeded fault
   stream, or from the trace);
3. run ONE real scheduling cycle (``Scheduler.run_once_guarded`` — the
   production ``run_once``, crash faults included);
4. BARRIER: wait out every async bind/evict side effect, then drain the
   cache's resync and cleanup queues deterministically — virtual time
   only advances when the world has settled, which is what makes the
   run replayable;
5. post-cycle cleanup (pods orphaned by a mid-cycle node death), gang
   degradation bookkeeping, invariant check, trace record.

The scheduler, cache, plugins, and actions are the production objects —
the simulator only owns the clock, the churn, and the assertions.
"""

from __future__ import annotations

import logging
import os
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from .. import metrics
from ..api import PodPhase, build_resource_list
from ..cache import SchedulerCache
from ..cluster import InProcessCluster
from ..obs import RECORDER
from ..obs.quality import (
    QUALITY,
    compute_scorecard,
    replay_view,
    telemetry_values,
)
from ..obs.tracer import TRACER
from ..scheduler import Scheduler
from ..utils.test_utils import build_node, build_pod, build_pod_group, build_queue
from .clock import VirtualClock
from .failover import CUT_POINTS, SimClusterEndpoint
from .faults import FaultInjector, parse_fault_spec
from .invariants import InvariantChecker
from .trace import TRACE_VERSION, TraceReader, TraceWriter, canon
from .workload import WorkloadGenerator, WorkloadSpec

logger = logging.getLogger(__name__)

SIM_NAMESPACE = "sim"

SIM_DEFAULT_CONF = """
actions: "allocate_tpu, backfill"
tiers:
- plugins:
  - name: priority
  - name: gang
  - name: conformance
- plugins:
  - name: drf
  - name: predicates
  - name: proportion
  - name: nodeorder
  - name: serving
"""

# Backend name -> env overrides (None = unset). "auto" leaves the
# process environment alone.
_BACKEND_ENV = {
    "dense": {"KBT_SOLVER": "jax", "KBT_SOLVER_TOPK": "off"},
    "sparse": {"KBT_SOLVER": "jax"},
    "native": {"KBT_SOLVER": "native", "KBT_SOLVER_TOPK": None},
}


@dataclass
class SimConfig:
    cycles: int = 200
    seed: int = 0
    faults: str = ""
    workload: WorkloadSpec = field(default_factory=WorkloadSpec)
    conf: str = SIM_DEFAULT_CONF
    backend: str = "auto"           # auto | dense | sparse | native
    topk: Optional[int] = None      # sparse K override (KBT_SOLVER_TOPK)
    period: float = 1.0             # virtual seconds per cycle
    trace_path: Optional[str] = None
    replay: Optional[TraceReader] = None
    # Replay only the first N recorded cycles (soak replay-bisect:
    # reproduce the state just past a detector's suspect window).
    replay_limit: Optional[int] = None
    check_invariants: bool = True
    recreate_killed: bool = True    # controller analog for killed pods
    # Chrome trace-event export of the whole run (--trace-out): spans
    # carry the virtual clock's timestamp in their args.
    trace_out: Optional[str] = None
    # Soak mode (--soak): telemetry records every cycle (window size
    # scaled so the whole horizon fits the window ring), and the
    # leak/drift detectors (sim/soak.py) run over the rolled windows
    # at the end; their verdict lands in report.soak and the telemetry
    # windows are dumped next to the trace (or to telemetry_out).
    soak: bool = False
    telemetry_out: Optional[str] = None
    # Event-driven micro-cycle mode (--micro-every N, N >= 2): only
    # every Nth sim cycle runs the full periodic scheduling cycle; the
    # cycles in between run Scheduler.run_micro — the bounded warm-path
    # fast cycle — against that cycle's arrivals. The invariant checker
    # still runs EVERY cycle, so the micro path carries the same
    # correctness obligations as the periodic one. 0 disables.
    micro_every: int = 0
    # Failover kill drill (--kill-at): cycle -> cut point; the leader
    # is hard-stopped at that cut (sim/failover.py) and a successor
    # instance takes the lease and recovers. Probabilistic kills ride
    # the fault spec as leader-kill:p instead.
    kill_plan: Dict[int, str] = field(default_factory=dict)
    # Virtual-time lease TTL for the drill's takeover wait.
    lease_duration: float = 15.0
    # Decision-audit dump (--audit-out): the placement ledger's audit
    # stream as canonical JSONL — virtual-clock-stamped, so a replay's
    # dump is byte-identical to the recording's (make latency-smoke
    # pins this). Defaults to <trace>.audit.jsonl when a trace is
    # recorded.
    audit_out: Optional[str] = None
    # Per-cycle placement-quality scorecard stream (--quality-out):
    # canonical JSONL, one card per cycle — byte-identical under a
    # same-config --replay (the in-trace comparison additionally
    # strips the path-dependent solver deltas; obs/quality.py).
    quality_out: Optional[str] = None
    # Anti-entropy sweep cadence override for the run (None = the
    # process default, KBT_ANTIENTROPY_EVERY): event-fault storms run
    # at 1 so every cycle's divergence is swept before its invariant
    # check. Recorded in the trace header — the sweep repairs mutate
    # scheduling state, so replay must run the same cadence.
    antientropy_every: Optional[int] = None


@dataclass
class SimReport:
    cycles: int = 0
    placements: int = 0
    violations: List[dict] = field(default_factory=list)
    fault_counts: Dict[str, int] = field(default_factory=dict)
    bind_failures: int = 0
    cycle_errors: int = 0
    replay_mismatches: List[int] = field(default_factory=list)
    jobs_created: int = 0
    jobs_completed: int = 0
    wall_seconds: float = 0.0
    check_seconds: float = 0.0
    # Flight-recorder dump files written alongside the JSONL trace
    # (one per invariant-violation/cycle-error event) and the exported
    # Chrome trace path, when armed.
    flight_dumps: List[str] = field(default_factory=list)
    trace_out: Optional[str] = None
    # Soak-mode verdict (sim/soak.py): detector results, tripped series,
    # the telemetry dump path, and replay-bisect hints.
    soak: Optional[dict] = None
    # End-of-run circuit-breaker snapshot (solver/containment.py): a
    # chaos run asserts re-promotion (state == closed once the injected
    # fault windows end) straight off the report.
    breaker: Optional[dict] = None
    # Failover drill bookkeeping: one entry per leader kill (cut,
    # cycle, takeover wait, recovery outcome summary).
    leader_kills: int = 0
    failovers: List[dict] = field(default_factory=list)
    recovery_failures: int = 0
    # Placement-latency ledger engagement summary (obs/latency.py) and
    # the decision-audit dump written alongside the trace.
    latency: Optional[dict] = None
    audit_records: int = 0
    audit_path: Optional[str] = None
    # Cluster-truth integrity summary (event-stream hardening +
    # anti-entropy): absorbed anomalies, relists, divergence
    # detected/repaired, post-solve validation rejections, and the
    # end-of-run cleanliness verdict (unrepaired_end must be 0 for the
    # DIVERGE acceptance artifact; --require-divergence-repaired).
    integrity: Optional[dict] = None
    # Placement-quality scorecard: replay-compared card mismatches
    # (exit 2, same class as placement divergence) and the end-of-run
    # summary the A/B study driver (sim/study.py) pairs across seeds.
    quality_mismatches: List[int] = field(default_factory=list)
    quality: Optional[dict] = None

    @property
    def cycles_per_sec(self) -> float:
        return self.cycles / self.wall_seconds if self.wall_seconds else 0.0

    def to_dict(self) -> dict:
        return {
            "cycles": self.cycles,
            "placements": self.placements,
            "violations": self.violations,
            "fault_counts": {
                k: v for k, v in sorted(self.fault_counts.items()) if v
            },
            "bind_failures": self.bind_failures,
            "cycle_errors": self.cycle_errors,
            "replay_mismatches": self.replay_mismatches,
            "jobs_created": self.jobs_created,
            "jobs_completed": self.jobs_completed,
            "wall_seconds": round(self.wall_seconds, 3),
            "cycles_per_sec": round(self.cycles_per_sec, 1),
            "invariant_check_seconds": round(self.check_seconds, 3),
            "flight_dumps": list(self.flight_dumps),
            "trace_out": self.trace_out,
            **({"soak": self.soak} if self.soak is not None else {}),
            **({"breaker": self.breaker} if self.breaker is not None
               else {}),
            **({
                "leader_kills": self.leader_kills,
                "failovers": list(self.failovers),
                "recovery_failures": self.recovery_failures,
            } if self.leader_kills else {}),
            **({
                "latency": self.latency,
                "audit_records": self.audit_records,
                "audit_path": self.audit_path,
            } if self.latency is not None else {}),
            **({"integrity": self.integrity}
               if self.integrity is not None else {}),
            **({"quality": self.quality}
               if self.quality is not None else {}),
            **({"quality_mismatches": list(self.quality_mismatches)}
               if self.quality_mismatches else {}),
        }


class _RecordingBinder:
    """Outermost binder layer: records successful binds (the cycle's
    placements). Appends AFTER the inner bind returns, so injected
    failures never show up as placements."""

    def __init__(self, inner):
        self.inner = inner
        self.records: List[Tuple[str, str]] = []

    def bind(self, pod, hostname: str) -> None:
        self.inner.bind(pod, hostname)
        self.records.append((f"{pod.namespace}/{pod.name}", hostname))

    def drain(self) -> List[List[str]]:
        out = sorted(self.records)
        self.records = []
        return [list(p) for p in out]


class ClusterSimulator:
    def __init__(self, cfg: SimConfig):
        if cfg.replay is not None:
            # The recorded run's identity lives in its header: the bind
            # fault seam re-decides per-attempt failures from
            # (seed, fault spec), so replaying under CLI defaults would
            # silently inject a DIFFERENT fault pattern and report it as
            # scheduler divergence.
            header = cfg.replay.header
            cfg.seed = header.get("seed", cfg.seed)
            cfg.faults = header.get("faults", cfg.faults)
            cfg.period = header.get("period", cfg.period)
            # The cycle-kind schedule (periodic vs micro) is part of
            # the recorded run's semantics; so is the drill's lease TTL
            # (it decides the recorded takeover wait).
            cfg.micro_every = header.get("micro_every", cfg.micro_every)
            cfg.lease_duration = header.get(
                "lease_duration", cfg.lease_duration
            )
            cfg.antientropy_every = header.get(
                "antientropy_every", cfg.antientropy_every
            )
            cfg.cycles = len(cfg.replay.cycles)
            if cfg.replay_limit is not None:
                cfg.cycles = min(cfg.cycles, max(1, cfg.replay_limit))
        self.cfg = cfg
        self.clock = VirtualClock()
        # Validate BEFORE mutating process state: a bad fault spec must
        # not leak env overrides or a live cache thread pool.
        fault_spec = parse_fault_spec(cfg.faults)
        # Device-fault kinds fire inside the device-solve
        # materialization and the canary probe; the native backend
        # never dispatches either, so such a run would count injected
        # faults while exercising nothing — reject it like an unknown
        # kind rather than green-lighting a vacuous chaos run.
        device_kinds = [
            k for k in (
                "solver-exc", "solver-hang", "backend-loss",
                "solver-corrupt",
            )
            if fault_spec.get(k)
        ]
        if cfg.backend == "native" and device_kinds:
            raise ValueError(
                f"fault kinds {device_kinds} require a device backend "
                "(dense/sparse); --backend native never runs a device "
                "solve, so they would inject nothing"
            )
        self._env_backup: Dict[str, Optional[str]] = {}
        self._apply_backend_env(cfg.backend, cfg.topk)
        if cfg.antientropy_every is not None:
            # Same backup/restore discipline as the backend env: the
            # sweep cadence is part of the run's recorded semantics.
            self._env_backup.setdefault(
                "KBT_ANTIENTROPY_EVERY",
                os.environ.get("KBT_ANTIENTROPY_EVERY"),
            )
            os.environ["KBT_ANTIENTROPY_EVERY"] = str(
                cfg.antientropy_every
            )
        # Fault-containment state is process-global; a run must start
        # from a closed breaker and must not inherit (or leak) a device
        # fault hook — breaker state bleeding from a recording run into
        # its replay would silently desynchronize them.
        from ..solver import containment as _containment

        self._containment = _containment
        _containment.reset_breaker()
        # Placement-latency ledger + decision audit are process-global
        # (like the breaker): a run must start them empty, or a second
        # sim in the same process inherits the first's entries and its
        # replay can never be byte-identical. The scheduler built in
        # _build_instance installs the virtual clock.
        from ..obs.latency import AUDIT, LEDGER

        LEDGER.reset()
        AUDIT.reset()
        # The quality monitor's churn counters are process-global too
        # (fed by the cache's evict/bind seams); a run starts them from
        # zero, and reset() re-reads the KBT_QUALITY* env the run may
        # have been launched under.
        QUALITY.reset()
        self._quality_enabled = QUALITY.enabled
        # Failover drill state: device-kind memo (successor instances
        # must re-stamp the 0.5 s solve budget their Scheduler
        # construction resets) and the kill switchboard.
        self._device_kinds = device_kinds
        for cut in sorted(set(cfg.kill_plan.values())):
            if cut not in CUT_POINTS:
                raise ValueError(
                    f"unknown leader-kill cut {cut!r} "
                    f"(known: {', '.join(CUT_POINTS)})"
                )
        self._failover_enabled = (
            bool(fault_spec.get("leader-kill")) or bool(cfg.kill_plan)
        )
        if cfg.replay is not None and not self._failover_enabled:
            # Replay re-applies kills from the RECORDED fault events,
            # so lease bookkeeping (whose takeover wait is part of the
            # compared failover block) must arm off the trace, not the
            # (empty) CLI spec.
            self._failover_enabled = any(
                f.get("kind") == "leader-kill"
                for rec in cfg.replay.cycles
                for f in rec.get("faults", [])
            )
        self.instance_id = 0
        try:
            self.cluster = InProcessCluster(simulate_kubelet=True)
            self.injector = FaultInjector(fault_spec, cfg.seed)
            self.injector.attach_cluster(self.cluster)
            # The active scheduler instance (endpoint/cache/binder/
            # scheduler); failover discards it and builds a successor.
            self._build_instance()
            # Small REAL-time solve budget, stamped AFTER the Scheduler
            # (whose constructor stamps the period-derived one): an
            # injected hang costs a fraction of a second of wall time,
            # not the production 30 s. Only when device faults are
            # actually planned — the deadline measures WALL time, and a
            # fault-free (or native) soak on a contended box must not
            # turn a >0.5 s scheduling stall of a healthy solve into a
            # SolveTimeout cycle error. The hook is the chaos seam the
            # solver-exc/solver-hang/backend-loss kinds fire through.
            # (_build_instance re-stamps it for successors too.)
            _containment.set_device_fault_hook(
                self.injector.device_fault_hook()
            )
            # solver-corrupt tamper seam: rewrites a device rung's
            # fetched assignment vector on armed cycles; the post-solve
            # validation layer must reject it before dispatch.
            _containment.set_result_tamper_hook(
                self.injector.result_tamper_hook()
            )
            if cfg.backend in ("dense", "sparse"):
                # Pre-warm the breaker's canary jit so an in-run probe
                # costs milliseconds against the 0.5 s budget — probe
                # success must never hinge on a cold compile racing the
                # deadline (that would make replays timing-dependent).
                try:
                    _containment._canary_probe(timeout=60.0)
                except Exception:
                    logger.exception("sim canary prewarm failed")
            self.checker = InvariantChecker()
            # Soak runs stream the trace to disk without the in-memory
            # record list (O(cycles) RAM the leak detector would —
            # correctly — flag as a linear alloc_blocks climb).
            self.writer = TraceWriter(
                cfg.trace_path, retain=not cfg.soak
            )
            self.replaying = cfg.replay is not None
            if self.replaying:
                self.generator = None
            else:
                self.generator = WorkloadGenerator(cfg.workload, cfg.seed)
        except BaseException:
            if getattr(self, "cache", None) is not None:
                self.cache.shutdown()
            # Undo the process-global containment stamps made above —
            # close() is unreachable when __init__ raises, and a leaked
            # 0.5 s wall-clock budget / fault hook would poison later
            # solves in the same process.
            _containment.set_device_fault_hook(None)
            _containment.set_result_tamper_hook(None)
            _containment.configure(None)
            self._restore_env()
            raise

        self.report = SimReport()
        # Integrity accounting: cross-instance run totals, and the
        # process-global validation-rejection baseline (metrics persist
        # across sims in one process; only this run's delta counts).
        self._integrity_totals: Dict[str, object] = {}
        self._rejected_prev = int(metrics.solver_output_rejected.total())
        # Soak mode: telemetry records every cycle; size the rollup
        # window so the WHOLE horizon fits the window ring (100k cycles
        # at /512 → ~195-cycle windows, 512 windows resident), and
        # force-enable the scheduler's per-cycle feed.
        if cfg.soak:
            from ..obs.telemetry import TELEMETRY

            TELEMETRY.configure(
                window_cycles=max(4, cfg.cycles // 512),
                max_windows=1024,
                raw_capacity=512,
            )
            self.scheduler._telemetry = True
        # Chrome-trace export of the run: enable the global tracer and
        # stamp every span with the virtual clock, so the exported
        # timeline can be correlated with trace-cycle records.
        self._tracing = cfg.trace_out is not None
        if self._tracing:
            TRACER.reset()
            TRACER.enable()
            TRACER.annotator = lambda: {"vtime": self.clock.now()}
        # Deterministic bookkeeping.
        self._seq = 0                      # event timestamp tiebreaker
        self._job_specs: Dict[str, dict] = {}
        self._rebirths: Dict[str, int] = {}
        self._running_since: Dict[str, int] = {}
        # Generate-mode future event queues (flap returns, recreations).
        self._scheduled: Dict[int, List[dict]] = {}
        # Quality-card delta state, harness-owned: the scheduler's
        # cadence-gated feed keeps its own (QUALITY._prev/_state), so
        # the two delta streams never corrupt each other. The series
        # dict keeps four floats per cycle for the end-of-run summary
        # (bounded and tiny even at soak horizons); the card stream
        # itself goes straight to disk.
        self._quality_state: dict = {}
        if self._quality_enabled:
            # Swallow the process's pre-existing solver counter totals
            # so the first card's solver deltas measure THIS run, not
            # whatever ran earlier in the process — a replay in the
            # same process must produce byte-identical cards.
            from ..obs.quality import _solver_deltas

            _solver_deltas(self._quality_state)
        self._quality_churn: Dict[str, float] = {}
        self._quality_series: Dict[str, List[float]] = {}
        self._quality_file = None
        if cfg.quality_out:
            parent = os.path.dirname(os.path.abspath(cfg.quality_out))
            os.makedirs(parent, exist_ok=True)
            self._quality_file = open(cfg.quality_out, "w")

    # -- environment ---------------------------------------------------------

    def _apply_backend_env(self, backend: str, topk: Optional[int]) -> None:
        overrides = dict(_BACKEND_ENV.get(backend, {}))
        if backend == "sparse":
            overrides["KBT_SOLVER_TOPK"] = str(topk or 64)
        for key, value in overrides.items():
            self._env_backup[key] = os.environ.get(key)
            if value is None:
                os.environ.pop(key, None)
            else:
                os.environ[key] = value

    def _restore_env(self) -> None:
        for key, value in self._env_backup.items():
            if value is None:
                os.environ.pop(key, None)
            else:
                os.environ[key] = value
        self._env_backup = {}

    # -- lifecycle -----------------------------------------------------------

    def close(self) -> None:
        try:
            self.cache.shutdown()
        finally:
            self._containment.set_device_fault_hook(None)
            self._containment.set_result_tamper_hook(None)
            self._containment.configure(None)
            self.writer.close()
            if self._quality_file is not None:
                self._quality_file.close()
                self._quality_file = None
            if self._tracing:
                try:
                    self.report.trace_out = TRACER.export(
                        self.cfg.trace_out
                    )
                except OSError:
                    logger.exception("sim trace export failed")
                TRACER.annotator = None
                TRACER.disable()
            self._restore_env()

    def run(self) -> SimReport:
        cfg = self.cfg
        started = time.perf_counter()
        try:
            self._write_header()
            self._bootstrap()
            for cycle in range(cfg.cycles):
                self._run_cycle(cycle)
                self.clock.advance(cfg.period)
            self.report.cycles = cfg.cycles
            self._finish_integrity()
            self.report.breaker = self._containment.BREAKER.state_dict()
            self._finish_latency()
            self._finish_quality()
            if cfg.soak:
                self._finish_soak()
        finally:
            self.report.wall_seconds = time.perf_counter() - started
            self.close()
        return self.report

    def _write_header(self) -> None:
        cfg = self.cfg
        if self.replaying:
            header = dict(cfg.replay.header)
            header["replayed"] = True
            header["backend"] = cfg.backend
        else:
            header = {
                "type": "header",
                "version": TRACE_VERSION,
                "seed": cfg.seed,
                "cycles": cfg.cycles,
                "faults": cfg.faults,
                "backend": cfg.backend,
                "period": cfg.period,
                "micro_every": cfg.micro_every,
                "lease_duration": cfg.lease_duration,
                "antientropy_every": cfg.antientropy_every,
                "workload": cfg.workload.to_dict(),
            }
            if cfg.kill_plan:
                # Provenance only — replay re-applies kills from the
                # recorded fault events, not from the plan.
                header["kill_plan"] = {
                    str(c): cut for c, cut in sorted(cfg.kill_plan.items())
                }
        self.writer.write(header)

    def _bootstrap(self) -> None:
        if self.replaying:
            return  # cycle 0's recorded events carry the bootstrap
        for event in self.generator.initial_events():
            self._scheduled.setdefault(0, []).append(event)

    # -- scheduler instances (failover drill) --------------------------------

    def _build_instance(self) -> None:
        """(Re)build the ACTIVE scheduler instance: its own cluster
        endpoint (the process-death seam, sim/failover.py), a fresh
        SchedulerCache ingesting the shared cluster, the recording
        binder stack, and a real Scheduler. Instance 0 is the bootstrap
        leader; later instances are failover successors."""
        cfg = self.cfg
        self.endpoint = SimClusterEndpoint(
            self.cluster, cfg.seed, fault_injector=self.injector
        )
        self.cache = SchedulerCache(
            cluster=self.endpoint,
            scheduler_name="tpu-batch",
            default_queue="default",
        )
        self.cache.leader_identity = f"sim-leader-{self.instance_id}"
        # Relist rate limiting gates on the VIRTUAL clock, so record
        # and replay allow/deny every gap-repair relist identically.
        self.cache._relist_clock = self.clock.now
        # Integrity deltas restart with the instance (a successor's
        # cache counts from zero).
        self._integrity_prev = None
        self.cache.binder = self.binder = _RecordingBinder(
            self.injector.wrap_binder(self.cache.binder)
        )
        # Ingest without the background resync/cleanup loops: the
        # sim drains those queues itself at deterministic points.
        self.cache.start_ingest()
        self.scheduler = Scheduler(
            self.cache,
            scheduler_conf=cfg.conf,
            schedule_period=cfg.period,
            clock=self.clock,
        )
        if self._device_kinds:
            # Scheduler construction re-stamped the period-derived
            # budget; restore the drill's small wall-clock one.
            self._containment.configure(solve_budget=0.5)
        if self._failover_enabled:
            # Virtual-time lease: the drill's takeover waits out the
            # real TTL on the virtual clock (renewed per cycle).
            self.cluster.try_acquire_lease(
                SIM_NAMESPACE, "leader", self.cache.leader_identity,
                cfg.lease_duration, now=self.clock.now(),
            )

    def _failover(self, cycle: int, cut: str) -> dict:
        """Process-death aftermath: finalize the dead instance, wait
        out the (virtual) lease TTL, build the successor, and run the
        production recovery pass — returning the trace's failover block
        (wall-clock-free, so record and replay compare byte-equal)."""
        dead_cache = self.cache
        dead_endpoint = self.endpoint
        dead_binder = self.binder
        dead_identity = dead_cache.leader_identity
        # The dead instance's side effects were already barriered by
        # _run_cycle's step-4 kill branch (before the injector's seam
        # drain); the landed-bind set is final here.
        dead_endpoint.finalize_death()
        dead_cache.shutdown()

        # Lease takeover: a killed leader released nothing, so the
        # successor must wait out the TTL in virtual time.
        self.instance_id += 1
        successor_id = f"sim-leader-{self.instance_id}"
        takeover_wait = 0.0
        if not self.cluster.try_acquire_lease(
            SIM_NAMESPACE, "leader", successor_id,
            self.cfg.lease_duration, now=self.clock.now(),
        ):
            takeover_wait = self.cfg.lease_duration + 1.0
            self.clock.advance(takeover_wait)
            if not self.cluster.try_acquire_lease(
                SIM_NAMESPACE, "leader", successor_id,
                self.cfg.lease_duration, now=self.clock.now(),
            ):
                raise RuntimeError(
                    "failover: successor could not take the expired lease"
                )

        self._build_instance()
        # Landed binds of the dead leader are this cycle's placements:
        # carry them into the successor's recorder so the trace (and
        # the replay verifier) sees them where they happened.
        self.binder.records.extend(dead_binder.records)

        # The production successor-recovery pass (cache/recovery.py via
        # the Scheduler entry point): classify the dead leader's
        # surviving intents, complete or evict partial gangs.
        report = self.scheduler.recover_from_journal()
        summary = report.summary() if report is not None else {}
        if report is not None:
            if report.errors:
                self.report.recovery_failures += report.errors
            for item in report.evicted:
                job_key = item["job"]
                self.checker.mark_degraded(job_key, cycle)
                ns, _, job_name = job_key.partition("/")
                pod_ns, _, pod_name = item["pod"].partition("/")
                if (
                    not self.replaying
                    and self.cfg.recreate_killed
                    and job_name in self._job_specs
                ):
                    self._schedule_recreation(job_name, pod_name, cycle)
        # Wall-clock fields are forensics, not semantics: the trace's
        # failover block must be bit-equal between record and replay.
        summary.pop("duration_ms", None)
        info = {
            "cut": cut,
            "cycle": cycle,
            "killed": dead_identity,
            "successor": successor_id,
            "takeover_wait_s": round(takeover_wait, 3),
            "binds_refused": dead_endpoint.binds_refused,
            "marks_dropped": dead_endpoint.marks_dropped,
            "recovery": summary,
        }
        self.report.leader_kills += 1
        self.report.failovers.append(info)
        return info

    # -- the cycle -----------------------------------------------------------

    def _run_cycle(self, cycle: int) -> None:
        cfg = self.cfg

        # 0. arm the event-stream fault seam for the whole cycle window
        # (workload events apply before the scheduling step; the seam
        # disarms in end_cycle, so post-event cleanup and the settle
        # drains run fault-free and the cycle converges).
        self.injector.begin_cycle_events(cycle)

        # 1. events
        if self.replaying:
            rec = (
                cfg.replay.cycles[cycle]
                if cycle < len(cfg.replay.cycles) else {}
            )
            events = list(rec.get("events", []))
            fault_events = list(rec.get("faults", []))
        else:
            rec = None
            events = self._scheduled.pop(cycle, [])
            events.extend(self.generator.events_for_cycle(
                cycle, self._running_since, self._node_names()
            ))
        for event in events:
            self._apply_event(event, cycle)
        if not self.replaying:
            # Faults are planned AFTER this cycle's events have landed:
            # targeting pre-event state would let a flap pick a node
            # drained this very cycle (its scheduled return would then
            # resurrect a permanently-removed node) or an evict pick a
            # pod whose job-delete already ran (a recorded "fault" that
            # injected nothing).
            fault_events = self.injector.plan_cycle(
                cycle, self._node_names(), self._running_pod_keys()
            )
            planned_cut = cfg.kill_plan.get(cycle)
            if planned_cut is not None and not any(
                f["kind"] == "leader-kill" for f in fault_events
            ):
                fault_events.append(
                    {"kind": "leader-kill", "cut": planned_cut}
                )

        # 2. faults
        doomed: List[str] = []
        solver_fault = crash_fault = corrupt_fault = False
        kill_cut: Optional[str] = None
        device_fault = None  # "exc" | "hang" for this cycle's solves
        for fault in fault_events:
            kind = fault["kind"]
            self.report.fault_counts[kind] = (
                self.report.fault_counts.get(kind, 0) + 1
            )
            metrics.register_sim_fault(kind)
            if kind == "node-flap":
                self._kill_node(fault["name"], cycle, reason="flap")
                if not self.replaying:
                    self._scheduled.setdefault(
                        cycle + fault["down_for"], []
                    ).append(self._node_add_event(fault["name"]))
            elif kind == "node-death":
                doomed.append(fault["name"])
            elif kind == "evict":
                self._kill_pod(fault["pod"], cycle)
            elif kind == "solver":
                solver_fault = True
            elif kind == "crash":
                crash_fault = True
            elif kind == "solver-exc":
                device_fault = "exc"
            elif kind == "solver-hang":
                # A planned hang wins over a planned exception: it
                # exercises the strictly harsher path (deadline
                # abandonment + immediate quarantine).
                device_fault = "hang"
            elif kind == "backend-loss":
                self.injector.note_backend_loss(cycle, fault["down_for"])
            elif kind == "solver-corrupt":
                corrupt_fault = True
            elif kind == "leader-kill":
                kill_cut = fault["cut"]

        # 3. one real scheduling cycle. In micro mode only every Nth
        # cycle is periodic; the rest run the bounded warm-path micro
        # cycle (crash-fault cycles always run periodic so the injected
        # crash action actually executes; a leader kill needs the full
        # dispatch pipeline its cut points are defined against).
        micro_cycle = (
            cfg.micro_every > 1
            and cycle % cfg.micro_every != 0
            and not crash_fault
            and kill_cut is None
        )
        if self._failover_enabled and kill_cut is None:
            # The live leader renews its lease each cycle; a killed
            # leader deliberately does NOT — its last renewal is what
            # the successor's takeover must wait out.
            self.cluster.try_acquire_lease(
                SIM_NAMESPACE, "leader", self.cache.leader_identity,
                cfg.lease_duration, now=self.clock.now(),
            )
        if kill_cut is not None:
            self.endpoint.arm_kill(kill_cut, cycle)
        self.injector.begin_cycle(
            cycle, doomed_nodes=doomed, solver_fault=device_fault,
            corrupt=corrupt_fault,
        )
        prev_solver = None
        if solver_fault:
            prev_solver = os.environ.get("KBT_SOLVER")
            os.environ["KBT_SOLVER"] = "native"
        if crash_fault:
            self.scheduler.actions.insert(
                0, self.injector.crash_action_factory()
            )
        try:
            if micro_cycle:
                ok = self.scheduler.run_micro()
            else:
                ok = self.scheduler.run_once_guarded()
        finally:
            if crash_fault:
                self.scheduler.actions.pop(0)
            if solver_fault:
                if prev_solver is None:
                    os.environ.pop("KBT_SOLVER", None)
                else:
                    os.environ["KBT_SOLVER"] = prev_solver
        if not ok:
            self.report.cycle_errors += 1
            # Forensics alongside the JSONL trace: the flight recorder's
            # last record carries the failing phase + traceback
            # (committed by run_once_guarded's error path).
            self._flight_dump(cycle, "cycle-error")
            # The guarded production loop would back off; virtual time
            # pays the same penalty.
            self.clock.advance(self.scheduler.cycle_error_backoff())

        # 4. barrier + deterministic queue drains. The event-fault
        # reorder stash flushes FIRST: a stashed swap delivered at this
        # fixed point means the settle's gap checkpoints see only
        # genuine drops as stream holes. A killed leader's
        # instance is only barriered on its in-flight (refusing) side
        # effects — BEFORE end_cycle, so the bind seam's forensics are
        # complete when drained; its resync/cleanup queues die with the
        # process and the successor settles after recovery instead.
        self.injector.flush_events()
        if kill_cut is not None:
            if not self.cache.wait_for_side_effects(timeout=60.0):
                logger.warning(
                    "sim: dead leader side effects still in flight"
                )
        else:
            self._settle()
        seam = self.injector.end_cycle()
        if cycle % 256 == 255:
            # Periodic deterministic GC of dead pods' bind-attempt
            # counters (leak over long soaks; dead uids never bind
            # again so pruning changes no fault decision). Runs on the
            # settled cluster, so record and replay prune identically.
            self.injector.prune_bind_attempts(
                p.uid for p in self.cluster.list_objects("Pod")
            )
        for pod_key, _host in seam["bind_failures"]:
            self._degrade_pod(pod_key, cycle)
        self.report.bind_failures += len(seam["bind_failures"])
        # Hash-decided bind faults (a subset of the seam failures — the
        # rest are doomed-node rejections) count as injected faults too.
        for _ in range(seam["bind_faults"]):
            metrics.register_sim_fault("bind")
        if seam["bind_faults"]:
            self.report.fault_counts["bind"] = (
                self.report.fault_counts.get("bind", 0)
                + seam["bind_faults"]
            )
        # Event-stream fault forensics (hash-decided at the delivery
        # seam, like the bind faults): count them, and register every
        # DROPPED event's subject with the invariant checker — the
        # mirror is knowingly diverged until the relist/anti-entropy
        # machinery repairs it, and the checker judges that repair
        # (suppressed subjects must all clear by run end).
        for kind, n in seam.get("event_faults", {}).items():
            self.report.fault_counts[kind] = (
                self.report.fault_counts.get(kind, 0) + n
            )
            for _ in range(n):
                metrics.register_sim_fault(kind)
        if seam.get("relist_fails"):
            n = seam["relist_fails"]
            self.report.fault_counts["relist-fail"] = (
                self.report.fault_counts.get("relist-fail", 0) + n
            )
            for _ in range(n):
                metrics.register_sim_fault("relist-fail")
        dropped = seam.get("events_dropped", ())
        if dropped:
            self.checker.note_divergence(
                cycle,
                uids=[s for k, _e, s in dropped if k == "Pod"],
                nodes=[s for k, _e, s in dropped if k == "Node"],
            )

        # 4b. failover: the killed leader is torn down, the successor
        # takes the lease, runs the production journal-recovery pass,
        # and the world settles under the NEW instance before the
        # invariant check judges the failover boundary.
        failover_info = None
        if kill_cut is not None:
            failover_info = self._failover(cycle, kill_cut)
            self._settle()

        # 5. post-cycle cleanup (orphans of mid-cycle node deaths)
        if self.replaying:
            post_events = list((rec or {}).get("post_events", []))
        else:
            post_events = self._plan_post_events(cycle, doomed, seam)
        for event in post_events:
            self._apply_event(event, cycle)
        if post_events:
            self._settle()

        placements = self.binder.drain()
        self._update_running_since(cycle)
        # Per-cycle integrity delta (anomalies absorbed, relists,
        # divergence detected/repaired, validation rejections) — part
        # of the trace record as FORENSICS; deliberately NOT
        # replay-compared (see the note at the replay verifier below):
        # which cycle a gap confirmation lands on depends on worker-
        # thread rv assignment order. Placements + the end-state
        # repair gate are the determinism contract.
        integrity_delta = self._integrity_delta()

        # 6. invariants
        violations = []
        if cfg.check_invariants:
            t0 = time.perf_counter()
            violations = [
                v.to_dict() for v in self.checker.check(
                    self.cache, cycle, namespace=SIM_NAMESPACE
                )
            ]
            self.report.check_seconds += time.perf_counter() - t0
            for v in violations:
                metrics.register_sim_violation(v["invariant"])
            self.report.violations.extend(violations)
            if violations:
                self._flight_dump(cycle, "violation")
        metrics.register_sim_cycle()
        self.report.placements += len(placements)

        # Per-cycle placement-quality card on the SETTLED world (the
        # sim bypasses the production KBT_QUALITY_EVERY cadence — sim
        # clusters are small). Churn deltas come from the process-
        # global monitor's seam counters against the harness-owned
        # prev, so the scheduler's own cadence feed stays untouched.
        quality_card = None
        if self._quality_enabled:
            try:
                quality_card = compute_scorecard(
                    self.cache,
                    churn=QUALITY.churn_delta(self._quality_churn),
                    state=self._quality_state,
                )
            except Exception:
                logger.exception("sim quality card failed")
        if quality_card is not None:
            for key, val in (
                ("density_dom", quality_card["density_dom"]),
                ("jain", quality_card["fairness"]["jain"]),
                ("churn_per_placement",
                 quality_card["churn"]["per_placement"]),
                ("emptiable_frac",
                 quality_card["frag"]["emptiable_frac"]),
            ):
                self._quality_series.setdefault(key, []).append(
                    float(val)
                )
            if self._quality_file is not None:
                self._quality_file.write(canon(quality_card) + "\n")

        stats = self._cycle_stats()
        if cfg.soak:
            # Soak-only series: invariant/error counts (bounded at zero
            # by the drift detectors) and the cluster's population —
            # folded into the cycle's open telemetry window, which
            # run_once already started with the watermark probes.
            from ..obs.telemetry import TELEMETRY

            if not ok:
                # An errored cycle never reaches run_once's telemetry
                # feed, so the series' internal cycle counter would
                # drift from the trace's cycle numbers — and with it
                # every replay-bisect pointer. Feed the missing sample
                # at the true trace cycle; the explicit index also
                # realigns the counter for all later cycles.
                TELEMETRY.observe_values({}, cycle=cycle)
            soak_values = {
                "invariant_violations": float(len(violations)),
                "sim_cycle_errors": 0.0 if ok else 1.0,
                "placements": float(len(placements)),
                "pods": float(stats["pods"]),
                "pending": float(stats["pending"]),
                "running": float(stats["running"]),
                "nodes": float(stats["nodes"]),
                "jobs": float(stats["jobs"]),
            }
            if quality_card is not None:
                # quality:* series — the drift detectors (sim/soak.py)
                # bound unfairness and churn-per-placement over the
                # soak horizon.
                soak_values.update(telemetry_values(quality_card))
            TELEMETRY.annotate_cycle(soak_values)

        record = {
            "type": "cycle",
            "cycle": cycle,
            "events": events,
            "faults": fault_events,
            "post_events": post_events,
            "placements": placements,
            "bind_failures": [list(b) for b in seam["bind_failures"]],
            "stats": stats,
            "violations": violations,
        }
        if failover_info is not None:
            record["failover"] = failover_info
        if integrity_delta is not None:
            record["integrity"] = integrity_delta
        if quality_card is not None:
            record["quality"] = quality_card
        self.writer.write(record)
        if self.replaying and rec is not None:
            if placements != rec.get("placements", []):
                self.report.replay_mismatches.append(cycle)
            elif failover_info != rec.get("failover"):
                # The failover boundary is part of the replay contract:
                # the successor must classify, re-drive and evict
                # identically, or the drill is not deterministic.
                self.report.replay_mismatches.append(cycle)
            elif (
                quality_card is not None
                and "quality" in rec
                and replay_view(quality_card)
                != replay_view(rec["quality"])
            ):
                # Minus the path-dependent solver deltas, a card is a
                # pure function of the replayed cluster state: a
                # mismatch means the replayed WORLD diverged even
                # though the placements matched. (Traces recorded
                # before the quality block, or under KBT_QUALITY=0 on
                # either side, skip the comparison.)
                self.report.quality_mismatches.append(cycle)
            # The integrity block is deliberately NOT byte-compared:
            # which CYCLE a gap confirmation / relist lands on depends
            # on the cluster's event-rv assignment order across
            # concurrent side-effect workers (a dropped terminal rv's
            # hole only becomes visible once a later write passes it).
            # The true determinism contract — placements, and the
            # end-state "every divergence repaired" gate
            # (--require-divergence-repaired) — holds in both runs;
            # the per-cycle block stays in the record as forensics.

    def _integrity_snapshot(self) -> dict:
        cur = self.cache.integrity_state()
        return {
            "anomalies": dict(cur["event_anomalies"]),
            "relists": {
                k: v for k, v in cur["relists"].items() if v
            },
            "detected": dict(cur["divergence_detected"]),
            "repaired": dict(cur["divergence_repaired"]),
        }

    def _integrity_delta(self) -> Optional[dict]:
        """This cycle's integrity activity as deltas of the cache's
        cumulative counters (plus the validation-rejection metric),
        folded into the run totals. None when nothing happened — the
        common case, keeping clean traces byte-identical to pre-
        integrity recordings."""
        cur = self._integrity_snapshot()
        prev = self._integrity_prev or {}
        self._integrity_prev = cur
        rejected_now = int(metrics.solver_output_rejected.total())
        d_rejected = rejected_now - self._rejected_prev
        self._rejected_prev = rejected_now
        out: Dict[str, object] = {}
        for key in ("anomalies", "relists", "detected", "repaired"):
            base = prev.get(key, {})
            delta = {
                k: v - base.get(k, 0)
                for k, v in sorted(cur[key].items())
                if v - base.get(k, 0)
            }
            if delta:
                out[key] = delta
        if d_rejected:
            out["rejected"] = d_rejected
        if not out:
            return None
        for key, val in out.items():
            if key == "rejected":
                self._integrity_totals["rejected"] = (
                    self._integrity_totals.get("rejected", 0) + val
                )
            else:
                totals = self._integrity_totals.setdefault(key, {})
                for k, v in val.items():
                    totals[k] = totals.get(k, 0) + v
        return out

    def _finish_integrity(self) -> None:
        """End of run: flush any stashed event, settle, run an
        UNBUDGETED anti-entropy reconcile, verify the next sweep finds
        nothing, and run one final invariant check — every injected
        divergence must provably have cleared (unrepaired_end = 0 is
        the DIVERGE acceptance gate; --require-divergence-repaired)."""
        self.injector.flush_events()
        self._settle()
        # Controller-analog cleanup of pods orphaned on dead nodes by
        # the FINAL cycles: every earlier cycle's step-5 post events
        # handled its predecessors, but a pod ghost-bound in the last
        # cycle (bind landed while a dropped node-delete kept the
        # ghost in the mirror) has no later cycle to clean it — and
        # its conservation flag would stay suppressed forever.
        # Deterministic in replay too: it reads settled cluster state.
        post = self._plan_post_events(
            self.cfg.cycles, [], {"bind_failures": []}
        )
        for event in post:
            self._apply_event(event, self.cfg.cycles)
        if post:
            self._settle()
        unrepaired = 0
        verify_detected: dict = {}
        reconcile_failed = False
        try:
            self.cache.antientropy.sweep(budget=None)
            self._settle()
            verify = self.cache.antientropy.sweep(budget=None)
            verify_detected = dict(sorted(verify["detected"].items()))
            unrepaired = sum(verify["detected"].values())
        except Exception:
            logger.exception("final anti-entropy reconcile failed")
            reconcile_failed = True
        if self.cfg.check_invariants:
            final = [
                v.to_dict() for v in self.checker.check(
                    self.cache, self.cfg.cycles, namespace=SIM_NAMESPACE
                )
            ]
            for v in final:
                metrics.register_sim_violation(v["invariant"])
            self.report.violations.extend(final)
        self._integrity_delta()  # fold the final sweeps into the totals
        totals = self._integrity_totals
        self.report.integrity = {
            "anomalies": dict(sorted(
                totals.get("anomalies", {}).items()
            )),
            "relists": dict(sorted(totals.get("relists", {}).items())),
            "divergence_detected": dict(sorted(
                totals.get("detected", {}).items()
            )),
            "divergence_repaired": dict(sorted(
                totals.get("repaired", {}).items()
            )),
            "validation_rejected": totals.get("rejected", 0),
            "suppressed_violations": self.checker.suppressed_total,
            "unrepaired_end": (
                unrepaired
                + self.checker.outstanding_divergence()
                + (1 if reconcile_failed else 0)
            ),
            # Forensics for a nonzero verdict: what the verify sweep
            # still saw, and which exempt subjects never cleared.
            "unrepaired_verify": verify_detected,
            "unrepaired_outstanding": sorted(
                list(self.checker.diverged_uids)
                + list(self.checker.diverged_nodes)
            ),
        }

    def _finish_latency(self) -> None:
        """End of run: land the placement ledger's engagement summary
        in the report and dump the decision-audit stream (JSONL,
        virtual-clock-stamped → byte-identical under replay) alongside
        the trace or to --audit-out."""
        from ..obs.latency import AUDIT, LEDGER

        if not LEDGER.enabled:
            return
        self.report.latency = LEDGER.summary()
        self.report.audit_records = AUDIT.meta()["records"]
        path = self.cfg.audit_out or (
            f"{self.cfg.trace_path}.audit.jsonl"
            if self.cfg.trace_path else None
        )
        if path:
            try:
                self.report.audit_path = AUDIT.dump_jsonl(path)
            except OSError:
                logger.exception("sim audit dump failed")

    def _finish_quality(self) -> None:
        """End of run: fold the per-cycle card series into the report's
        quality summary — the medians are what the A/B study driver
        (sim/study.py) pairs across seeds."""
        series = self._quality_series
        if not any(series.values()):
            return
        import statistics

        summary: Dict[str, object] = {
            key: {
                "mean": round(statistics.fmean(vals), 6),
                "median": round(statistics.median(vals), 6),
                "last": round(vals[-1], 6),
            }
            for key, vals in sorted(series.items()) if vals
        }
        summary["cards"] = len(series.get("density_dom", ()))
        summary["counters"] = {
            k: round(v, 6) for k, v in QUALITY.counters().items()
        }
        if self.cfg.quality_out:
            summary["stream"] = self.cfg.quality_out
        self.report.quality = summary

    def _finish_soak(self) -> None:
        """End of a soak run: close the tail window, fit the leak/drift
        detectors over the rolled windows, dump the telemetry
        (alongside the JSONL trace, or to --telemetry-out), and land
        the verdict in the report. Detector trips do NOT raise — the
        CLI turns them into exit code 4 so the report still prints."""
        import json as _json

        from ..obs.telemetry import TELEMETRY
        from .soak import SoakVerdict, run_detectors

        TELEMETRY.flush()
        windows = TELEMETRY.windows()
        verdict = SoakVerdict(
            detectors=run_detectors(windows),
            trace_path=self.cfg.trace_path,
        )
        dump_path = self.cfg.telemetry_out or (
            f"{self.cfg.trace_path}.telemetry.json"
            if self.cfg.trace_path else None
        )
        if dump_path:
            try:
                # Set before to_dict so the on-disk dump names itself;
                # reset if the write fails.
                verdict.telemetry_dump = dump_path
                payload = TELEMETRY.snapshot(recent_raw=128)
                payload["soak"] = verdict.to_dict()
                payload["config"] = {
                    "cycles": self.cfg.cycles,
                    "seed": self.cfg.seed,
                    "faults": self.cfg.faults,
                    "backend": self.cfg.backend,
                    "workload": self.cfg.workload.to_dict(),
                }
                parent = os.path.dirname(os.path.abspath(dump_path))
                os.makedirs(parent, exist_ok=True)
                with open(dump_path, "w") as f:
                    _json.dump(payload, f, sort_keys=True)
            except OSError:
                verdict.telemetry_dump = None
                logger.exception("soak telemetry dump failed")
        self.report.soak = verdict.to_dict()
        for trip in verdict.tripped:
            logger.error("soak detector tripped: %s", trip.message)
        for hint in verdict.replay_hints():
            logger.error("soak replay-bisect: %s", hint)

    def _flight_dump(self, cycle: int, reason: str) -> None:
        """Write the flight-recorder ring next to the JSONL trace (no-op
        without a trace path — the ring still holds the records for
        callers that read the recorder directly)."""
        base = self.cfg.trace_path
        if not base:
            return
        path = f"{base}.flight-{reason}-c{cycle}.json"
        try:
            RECORDER.dump_to(path, reason=f"sim-{reason}")
            self.report.flight_dumps.append(path)
        except OSError:
            logger.exception("sim flight dump failed")

    # -- settling ------------------------------------------------------------

    def _settle(self) -> None:
        """Quiesce: all async side effects done, resync/cleanup queues
        drained (in sorted order — queue arrival order depends on worker
        timing), repeated until a full pass changes nothing."""
        for _ in range(8):
            if not self.cache.wait_for_side_effects(timeout=60.0):
                logger.warning("sim settle: side effects still in flight")
            resynced = self.cache.drain_resync_queue()
            cleaned = self.cache.drain_cleanup_queue()
            if not resynced and not cleaned:
                return
        logger.warning("sim settle: world still churning after 8 passes")

    # -- event application ---------------------------------------------------

    def _next_ts(self, cycle: int) -> float:
        self._seq += 1
        return cycle * self.cfg.period + self._seq * 1e-6

    def _node_names(self) -> List[str]:
        return sorted(
            n.name for n in self.cluster.list_objects("Node")
        )

    def _running_pod_keys(self) -> List[str]:
        return sorted(
            f"{p.namespace}/{p.name}"
            for p in self.cluster.list_objects("Pod")
            if p.namespace == SIM_NAMESPACE
            and p.status.phase == PodPhase.RUNNING
        )

    def _node_add_event(self, name: str) -> dict:
        spec = self.cfg.workload
        return {
            "kind": "node-add", "name": name,
            "cpu_m": spec.node_cpu_m, "mem_mi": spec.node_mem_mi,
        }

    def _apply_event(self, event: dict, cycle: int) -> None:
        kind = event["kind"]
        if kind == "queue-add":
            q = build_queue(event["name"], weight=event["weight"])
            q.metadata.uid = f"uid-queue-{event['name']}"
            q.metadata.creation_timestamp = self._next_ts(cycle)
            self.cluster.create_queue(q)
        elif kind == "node-add":
            node = build_node(
                event["name"],
                build_resource_list(
                    cpu=f"{event['cpu_m']}m",
                    memory=f"{event['mem_mi']}Mi",
                    pods=110,
                ),
                labels=event.get("labels"),
            )
            node.metadata.uid = f"uid-node-{event['name']}"
            node.metadata.creation_timestamp = self._next_ts(cycle)
            self.cluster.create_node(node)
        elif kind == "node-remove":
            self._kill_node(event["name"], cycle, reason=event.get(
                "reason", "drain"
            ))
        elif kind == "job-create":
            self._create_job(event, cycle)
        elif kind == "job-complete":
            self._complete_job(event["name"], cycle)
        elif kind == "job-delete":
            self._delete_job(event["name"])
        elif kind == "pod-recreate":
            self._recreate_pods(event, cycle)
        elif kind == "pod-delete":
            self._kill_pod(event["pod"], cycle, recreate=False)
        else:
            raise ValueError(f"unknown sim event kind {kind!r}")

    def _create_job(self, event: dict, cycle: int) -> None:
        name = event["name"]
        self._job_specs[name] = dict(event)
        self.report.jobs_created += 1
        ts = self._next_ts(cycle)
        pg = build_pod_group(
            name, namespace=SIM_NAMESPACE,
            min_member=event["min_member"], queue=event["queue"],
        )
        pg.metadata.uid = f"uid-pg-{name}"
        pg.metadata.creation_timestamp = ts
        self.cluster.create_pod_group(pg)
        req = build_resource_list(
            cpu=f"{event['cpu_m']}m", memory=f"{event['mem_mi']}Mi"
        )
        for i in range(event["replicas"]):
            self._create_pod(name, f"{name}-{i}", req, ts)

    def _create_pod(self, job: str, pod_name: str, req, ts: float) -> None:
        pod = build_pod(
            SIM_NAMESPACE, pod_name, "", PodPhase.PENDING, dict(req),
            group_name=job,
        )
        # Serving annotations (api/serving.py schema) ride the job
        # spec, so churn/fault replacements inherit the class, SLO
        # target and replica floor of the pods they replace.
        extra = self._job_specs.get(job, {}).get("annotations")
        if extra:
            pod.metadata.annotations.update(extra)
        pod.metadata.creation_timestamp = ts
        self.cluster.create_pod(pod)

    def _complete_job(self, name: str, cycle: int) -> None:
        self.report.jobs_completed += 1
        for pod in self._job_pods(name):
            if pod.status.phase == PodPhase.RUNNING:
                pod.status.phase = PodPhase.SUCCEEDED
                self.cluster.update("Pod", pod)
        self._running_since.pop(name, None)

    def _delete_job(self, name: str) -> None:
        for pod in self._job_pods(name):
            self.cluster.delete_pod(pod)
        for pg in self.cluster.list_objects("PodGroup"):
            if pg.namespace == SIM_NAMESPACE and pg.name == name:
                self.cluster.delete("PodGroup", pg)
        self._job_specs.pop(name, None)
        self._running_since.pop(name, None)
        self._rebirths = {
            k: v for k, v in self._rebirths.items()
            if not k.startswith(f"{name}-")
        }

    def _job_pods(self, job: str):
        from ..api.objects import GROUP_NAME_ANNOTATION_KEY

        return sorted(
            (
                p for p in self.cluster.list_objects("Pod")
                if p.namespace == SIM_NAMESPACE
                and p.metadata.annotations.get(
                    GROUP_NAME_ANNOTATION_KEY
                ) == job
            ),
            key=lambda p: p.name,
        )

    def _job_of_pod(self, pod) -> Optional[str]:
        from ..api.objects import GROUP_NAME_ANNOTATION_KEY

        return pod.metadata.annotations.get(GROUP_NAME_ANNOTATION_KEY)

    def _kill_node(self, name: str, cycle: int, reason: str) -> None:
        for node in self.cluster.list_objects("Node"):
            if node.name == name:
                self.cluster.delete("Node", node)
                break
        for pod in sorted(
            (
                p for p in self.cluster.list_objects("Pod")
                if p.namespace == SIM_NAMESPACE
                and p.spec.node_name == name
            ),
            key=lambda p: p.name,
        ):
            self._kill_pod(f"{pod.namespace}/{pod.name}", cycle)

    def _kill_pod(self, pod_key: str, cycle: int, recreate: bool = True) -> None:
        ns, _, name = pod_key.partition("/")
        pod = self.cluster.get_pod(ns, name)
        if pod is None:
            return
        job = self._job_of_pod(pod)
        self.cluster.delete_pod(pod)
        if job:
            self.checker.mark_degraded(f"{ns}/{job}", cycle)
            if (
                recreate
                and not self.replaying
                and self.cfg.recreate_killed
                and job in self._job_specs
            ):
                self._schedule_recreation(job, name, cycle)

    def _schedule_recreation(self, job: str, pod_name: str, cycle: int) -> None:
        # "simjob-00001-3r2" → base "simjob-00001-3": rebirths of a
        # rebirth share the original replica's generation counter.
        stem, dash, tail = pod_name.rpartition("-")
        base = f"{stem}{dash}{tail.split('r', 1)[0]}"
        gen = self._rebirths.get(base, 0) + 1
        self._rebirths[base] = gen
        self._scheduled.setdefault(cycle + 1, []).append({
            "kind": "pod-recreate",
            "job": job,
            "names": [f"{base}r{gen}"],
        })

    def _recreate_pods(self, event: dict, cycle: int) -> None:
        job = event["job"]
        spec = self._job_specs.get(job)
        if spec is None:
            return  # job finished in the meantime
        req = build_resource_list(
            cpu=f"{spec['cpu_m']}m", memory=f"{spec['mem_mi']}Mi"
        )
        ts = self._next_ts(cycle)
        for name in event["names"]:
            if self.cluster.get_pod(SIM_NAMESPACE, name) is not None:
                continue
            self._create_pod(job, name, req, ts)

    def _degrade_pod(self, pod_key: str, cycle: int) -> None:
        ns, _, name = pod_key.partition("/")
        pod = self.cluster.get_pod(ns, name)
        if pod is None:
            return
        job = self._job_of_pod(pod)
        if job:
            self.checker.mark_degraded(f"{ns}/{job}", cycle)

    def _plan_post_events(self, cycle, doomed, seam) -> List[dict]:
        """Generate mode: clean up after mid-cycle node deaths — the
        node object (when no bind got to kill it first) and the Running
        pods orphaned on it."""
        post: List[dict] = []
        live_nodes = set(self._node_names())
        removed_now = set()
        for name in doomed:
            if name in live_nodes:
                # The node-remove event's application (_kill_node)
                # deletes this node's pods and schedules their
                # recreations itself — listing them here too would
                # recreate each orphan TWICE (r<N> and r<N+1>),
                # permanently inflating the job.
                post.append({
                    "kind": "node-remove", "name": name, "reason": "death",
                })
                live_nodes.discard(name)
                removed_now.add(name)
        for pod in self.cluster.list_objects("Pod"):
            node_name = pod.spec.node_name
            if (
                pod.namespace == SIM_NAMESPACE
                and node_name
                and node_name not in live_nodes
                and node_name not in removed_now
            ):
                # Orphans of a node the injector already deleted
                # mid-cycle: no node-remove event will clean these up.
                post.append({
                    "kind": "pod-delete",
                    "pod": f"{pod.namespace}/{pod.name}",
                })
                job = self._job_of_pod(pod)
                if (
                    job is not None
                    and self.cfg.recreate_killed
                    and job in self._job_specs
                ):
                    self._schedule_recreation(job, pod.name, cycle)
        post.sort(key=lambda e: (e["kind"], e.get("name", e.get("pod", ""))))
        return post

    # -- observation ---------------------------------------------------------

    def _update_running_since(self, cycle: int) -> None:
        running: Dict[str, int] = {}
        for pod in self.cluster.list_objects("Pod"):
            if (
                pod.namespace == SIM_NAMESPACE
                and pod.status.phase == PodPhase.RUNNING
            ):
                job = self._job_of_pod(pod)
                if job:
                    running[job] = running.get(job, 0) + 1
        for job, count in running.items():
            spec = self._job_specs.get(job)
            if spec is None:
                continue
            if count >= spec["min_member"]:
                self._running_since.setdefault(job, cycle)
        # A gang knocked below min_member (node death, eviction) is no
        # longer fully running: its completion clock restarts when the
        # reborn members bind — otherwise a half-dead job would still
        # "succeed" on schedule with its rebirths sitting Pending.
        for job in list(self._running_since):
            spec = self._job_specs.get(job)
            if spec is None:
                continue
            if running.get(job, 0) < spec["min_member"]:
                del self._running_since[job]

    def _cycle_stats(self) -> dict:
        pods = [
            p for p in self.cluster.list_objects("Pod")
            if p.namespace == SIM_NAMESPACE
        ]
        return {
            "nodes": len(self.cluster.list_objects("Node")),
            "jobs": len(self._job_specs),
            "pods": len(pods),
            "running": sum(
                1 for p in pods if p.status.phase == PodPhase.RUNNING
            ),
            "pending": sum(
                1 for p in pods if p.status.phase == PodPhase.PENDING
            ),
            # Carried-backlog depth (solver/warm.py): a pure function
            # of solve history, so replay-stable — congested-regime
            # benches read the series straight off the trace records.
            "carried": self._carried_depth(),
        }

    def _carried_depth(self) -> int:
        ws = getattr(self.cache, "_warm_solve_state", None)
        if ws is None or not getattr(ws, "valid", False):
            return 0
        return len(ws.carried)


def run_sim(cfg: SimConfig) -> Tuple[SimReport, List[dict]]:
    """Run one simulation; returns (report, trace records)."""
    sim = ClusterSimulator(cfg)
    report = sim.run()
    return report, sim.writer.records
