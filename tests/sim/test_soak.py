"""Soak-mode leak/drift detectors (sim/soak.py) + the --soak harness
wiring: a seeded synthetic leak must trip, a clean run must not, and a
trip must carry a usable replay-bisect pointer."""

import json
import random

from kube_batch_tpu.obs.telemetry import Telemetry
from kube_batch_tpu.sim.soak import (
    DriftPolicy,
    GrowthPolicy,
    SoakVerdict,
    check_drift,
    check_growth,
    fit_linear,
    run_detectors,
)


def make_windows(series, window_cycles=4):
    """Roll a dict of per-cycle series through a real Telemetry
    instance — the detectors consume exactly what production rolls."""
    n = max(len(v) for v in series.values())
    t = Telemetry(window_cycles=window_cycles, max_windows=4096,
                  raw_capacity=8)
    for c in range(n):
        t.observe_values(
            {k: float(v[c]) for k, v in series.items() if c < len(v)},
            cycle=c,
        )
    t.flush()
    return t.windows()


def test_fit_linear_exact_and_noisy():
    slope, intercept, r2 = fit_linear([(x, 2.0 * x + 1.0)
                                       for x in range(10)])
    assert abs(slope - 2.0) < 1e-9 and abs(intercept - 1.0) < 1e-9
    assert r2 > 0.999
    rng = random.Random(5)
    noisy = [(x, 100.0 + rng.uniform(-5, 5)) for x in range(50)]
    slope, _i, r2 = fit_linear(noisy)
    assert r2 < 0.3  # noise around a flat line must not look explained


def test_synthetic_leak_trips_growth_detector():
    """A seeded linear leak (~4 KB/cycle on a 50 MB baseline over 2000
    cycles) must trip: slope fits with high R^2 and the projected
    growth clears the rss floors."""
    rng = random.Random(11)
    base = 50e6
    series = [base + 4096.0 * c + rng.uniform(-20e3, 20e3)
              for c in range(2000)]
    windows = make_windows({"rss_bytes": series})
    result = check_growth(
        windows, "rss_bytes",
        GrowthPolicy(abs_floor=4 * 1024 * 1024, rel_floor=0.05),
    )
    assert result is not None and result.tripped, result
    assert result.r2 > 0.9
    assert result.suspect_cycles is not None
    a, b = result.suspect_cycles
    assert 0 <= a <= b < 2000


def test_clean_noisy_series_does_not_trip():
    """Flat noise (GC sawtooth amplitude) must not trip: either the fit
    explains nothing (low R^2) or the growth misses the floors."""
    rng = random.Random(13)
    series = [50e6 + rng.uniform(-2e6, 2e6) for _ in range(2000)]
    windows = make_windows({"rss_bytes": series})
    result = check_growth(
        windows, "rss_bytes",
        GrowthPolicy(abs_floor=4 * 1024 * 1024, rel_floor=0.05),
    )
    assert result is not None and not result.tripped, result


def test_warmup_growth_is_forgiven():
    """Caches filling during warmup then flat steady state: the
    post-warmup fit must not trip."""
    series = (
        [50e6 + c * 100e3 for c in range(400)]        # warmup climb
        + [90e6] * 1600                                # flat forever
    )
    windows = make_windows({"rss_bytes": series})
    result = check_growth(
        windows, "rss_bytes",
        GrowthPolicy(abs_floor=8 * 1024 * 1024, rel_floor=0.05),
    )
    assert result is not None and not result.tripped, result


def test_absent_and_short_series_skipped():
    windows = make_windows({"x": [1.0] * 16})
    assert check_growth(windows, "missing", GrowthPolicy(1.0)) is None
    short = make_windows({"x": [1.0] * 8})  # 2 windows < MIN_WINDOWS
    assert check_growth(short, "x", GrowthPolicy(1.0)) is None


def test_drift_detector_patience():
    """One breaching window is a gang landing; `patience` consecutive
    windows is systematic drift."""
    spike = [0.0] * 40 + [0.6] * 4 + [0.0] * 156     # one bad window
    sustained = [0.0] * 40 + [0.6] * 60 + [0.0] * 100
    policy = DriftPolicy(bound=0.35, patience=3, signed=False)
    w_spike = make_windows({"fairness_drift:q": spike})
    r = check_drift(w_spike, "fairness_drift:q", policy)
    assert r is not None and not r.tripped, r
    w_sus = make_windows({"fairness_drift:q": sustained})
    r = check_drift(w_sus, "fairness_drift:q", policy)
    assert r is not None and r.tripped
    assert r.suspect_cycles is not None


def test_drift_unsigned_ignores_negative():
    """Under-service (negative drift) must not trip the positive-only
    fairness bound."""
    series = [-0.9] * 200
    windows = make_windows({"fairness_drift:q": series})
    r = check_drift(
        windows, "fairness_drift:q",
        DriftPolicy(bound=0.35, patience=3, signed=False),
    )
    assert r is not None and not r.tripped


def test_violations_bounded_at_zero():
    windows = make_windows({
        "invariant_violations": [0.0] * 100 + [1.0] * 4 + [0.0] * 96,
    })
    r = check_drift(
        windows, "invariant_violations", DriftPolicy(bound=0.0, patience=1)
    )
    assert r is not None and r.tripped


def test_zero_bound_series_trip_inside_warmup():
    """Hard invariants (cycle errors, violations) are exempt from the
    25% warmup skip: an error-only-at-startup bug must still fail the
    soak."""
    from kube_batch_tpu.sim.soak import DRIFT_POLICY

    windows = make_windows({
        "sim_cycle_errors": [1.0] * 4 + [0.0] * 196,
    })
    r = check_drift(
        windows, "sim_cycle_errors", DRIFT_POLICY["sim_cycle_errors"]
    )
    assert r is not None and r.tripped
    # Without the exemption the breach sits entirely in skipped warmup.
    r2 = check_drift(
        windows, "sim_cycle_errors", DriftPolicy(bound=0.0, patience=1)
    )
    assert r2 is not None and not r2.tripped


def test_run_detectors_prefix_matching_and_report():
    rng = random.Random(2)
    windows = make_windows({
        "rss_bytes": [50e6 + rng.uniform(-1e5, 1e5) for _ in range(400)],
        "fairness_drift:default": [0.5] * 400,
        "fairness_drift:batch": [0.0] * 400,
    })
    results = run_detectors(windows)
    by_series = {r.series: r for r in results}
    assert by_series["fairness_drift:default"].tripped
    assert not by_series["fairness_drift:batch"].tripped
    assert not by_series["rss_bytes"].tripped
    verdict = SoakVerdict(detectors=results, trace_path="/tmp/t.jsonl")
    d = verdict.to_dict()
    assert d["tripped"] == ["fairness_drift:default"]
    hints = verdict.replay_hints()
    assert len(hints) == 1 and "--replay /tmp/t.jsonl" in hints[0]
    # The clamp flag is --replay-cycles (--cycles is ignored in replay
    # mode, which recomputes it from the trace length).
    assert "--replay-cycles" in hints[0]
    json.dumps(d)


# -- harness wiring ----------------------------------------------------------

def test_soak_smoke_clean_run(tmp_path):
    """A short clean soak through the REAL harness: telemetry recorded,
    detectors evaluated, dump written, zero trips — the `make
    soak-smoke` contract at test scale."""
    from kube_batch_tpu.sim import SimConfig, WorkloadSpec
    from kube_batch_tpu.sim.harness import run_sim

    trace = str(tmp_path / "soak.jsonl")
    report, _records = run_sim(SimConfig(
        cycles=60,
        seed=5,
        workload=WorkloadSpec(nodes=6, arrival_rate=1.0),
        soak=True,
        trace_path=trace,
    ))
    assert report.cycles == 60
    assert report.soak is not None
    assert report.soak["tripped"] == [], report.soak
    dump_path = report.soak["telemetry_dump"]
    assert dump_path == trace + ".telemetry.json"
    with open(dump_path) as f:
        dump = json.load(f)
    assert dump["cycles_observed"] == 60
    assert dump["soak"]["detectors"]
    # The on-disk dump names itself (set before serialization).
    assert dump["soak"]["telemetry_dump"] == dump_path
    assert dump["config"]["cycles"] == 60
    # Soak streams the trace: no in-memory record list, but the file
    # has header + 60 cycle lines.
    with open(trace) as f:
        lines = f.read().splitlines()
    assert len(lines) == 61
    # Detector coverage: the invariant/error series were recorded.
    keys = set()
    for w in dump["windows"]:
        keys.update(w["keys"])
    assert {"invariant_violations", "sim_cycle_errors",
            "e2e_ms", "alloc_blocks"} <= keys


def test_replay_limit_clamps_cycles(tmp_path):
    """The replay-bisect entry point: --replay-cycles N replays only
    the first N recorded cycles."""
    from kube_batch_tpu.sim import SimConfig, TraceReader, WorkloadSpec
    from kube_batch_tpu.sim.harness import run_sim

    trace = str(tmp_path / "t.jsonl")
    full, _ = run_sim(SimConfig(
        cycles=20, seed=9,
        workload=WorkloadSpec(nodes=4, arrival_rate=1.0),
        trace_path=trace,
    ))
    assert full.cycles == 20
    clipped, _ = run_sim(SimConfig(
        replay=TraceReader.load(trace), replay_limit=7,
    ))
    assert clipped.cycles == 7
    assert clipped.replay_mismatches == []


def test_soak_cli_exit_code_on_trip(tmp_path, monkeypatch):
    """CLI: a tripped detector exits 4 and prints the bisect hints.
    Trip deterministically by tightening the fairness bound to an
    impossible level via a patched policy."""
    import kube_batch_tpu.sim.soak as soak_mod
    from kube_batch_tpu.sim.cli import main

    monkeypatch.setattr(
        soak_mod, "DRIFT_POLICY",
        {"e2e_ms": soak_mod.DriftPolicy(bound=-1.0, patience=1,
                                        signed=False)},
    )
    monkeypatch.setattr(soak_mod, "GROWTH_POLICY", {})
    rc = main([
        "--cycles", "40", "--seed", "5", "--soak", "--quiet",
        "--nodes", "4",
        "--trace", str(tmp_path / "s.jsonl"),
    ])
    assert rc == 4


def test_stranded_carried_backlog_trips_growth_detector():
    """A subset-solve bug that STRANDS carried tasks (each storm
    leaves a residue the rotation never retires) shows up as a
    sustained linear climb in the carried_backlog_depth watermark —
    the production policy must trip it, with a usable bisect window."""
    from kube_batch_tpu.sim.soak import GROWTH_POLICY

    rng = random.Random(17)
    policy = GROWTH_POLICY["carried_backlog_depth"]
    # Bursty congestion riding a leak: storms spike the depth, drains
    # pull it back, but every cycle strands ~0.25 jobs for good.
    stranded = [
        0.25 * c + (120.0 if c % 100 < 8 else 0.0)
        + rng.uniform(0, 10.0)
        for c in range(2000)
    ]
    windows = make_windows({"carried_backlog_depth": stranded})
    result = check_growth(windows, "carried_backlog_depth", policy)
    assert result is not None and result.tripped, result
    assert result.suspect_cycles is not None


def test_bursty_but_draining_backlog_does_not_trip():
    """Legitimate congestion: storms push the carried depth high and
    the micro steady state drains it back — high and bursty but flat.
    The policy's floors must let this soak pass."""
    from kube_batch_tpu.sim.soak import GROWTH_POLICY

    rng = random.Random(19)
    policy = GROWTH_POLICY["carried_backlog_depth"]
    draining = [
        (200.0 - 2.5 * (c % 100) if c % 100 < 80 else 0.0)
        + rng.uniform(0, 10.0)
        for c in range(2000)
    ]
    windows = make_windows({"carried_backlog_depth": draining})
    result = check_growth(windows, "carried_backlog_depth", policy)
    assert result is not None and not result.tripped, result
