"""Runtime lock-order harness (utils/lockdebug.py): order-asserting
proxies, both-traceback forensics, the leaf-fence rule, Condition
integration, and zero-wrapping when disabled
(doc/design/static-analysis.md)."""

import threading

import pytest

from kube_batch_tpu.utils import lockdebug
from kube_batch_tpu.utils.lockdebug import (
    GuardedWriteViolation,
    LockOrderViolation,
    witness_writes,
    wrap_lock,
)


@pytest.fixture(autouse=True)
def _armed(monkeypatch):
    monkeypatch.setenv(lockdebug.LOCK_DEBUG_ENV, "1")
    lockdebug.reset()
    yield
    lockdebug.reset()


def test_disabled_returns_raw_lock(monkeypatch):
    monkeypatch.setenv(lockdebug.LOCK_DEBUG_ENV, "0")
    lock = threading.Lock()
    assert wrap_lock("t.raw", lock) is lock


def test_consistent_order_passes():
    a, b = wrap_lock("t.a"), wrap_lock("t.b")
    for _ in range(3):
        with a:
            with b:
                pass


def test_reverse_order_raises_with_both_sites():
    a, b = wrap_lock("t.a"), wrap_lock("t.b")
    with a:
        with b:
            pass
    with pytest.raises(LockOrderViolation) as exc:
        with b:
            with a:
                pass
    message = str(exc.value)
    # Both acquisition sites, not just the second one (the forensics
    # PR 7 needed a production deadlock to obtain).
    assert "this acquisition" in message
    assert "reverse order" in message
    assert lockdebug.VIOLATIONS


def test_leaf_fence_rule():
    leaf = wrap_lock("cache.fence_lock")
    other = wrap_lock("t.other")
    with pytest.raises(LockOrderViolation, match="leaf-lock"):
        with leaf:
            with other:
                pass
    # The reverse nesting is legal: fence acquired as innermost.
    lockdebug.reset()
    with other:
        with leaf:
            pass


def test_self_deadlock_on_plain_lock_raises_instead_of_hanging():
    lock = wrap_lock("t.plain")
    with pytest.raises(LockOrderViolation, match="self-deadlock"):
        with lock:
            with lock:
                pass


def test_rlock_reentry_allowed():
    lock = wrap_lock("t.rl", threading.RLock())
    with lock:
        with lock:
            assert True


def test_edges_are_per_name_not_per_object():
    # Two cache instances share the lock NAME: order learned on one
    # applies to the other (that is the point — the invariant is about
    # the component, not the instance).
    a1, b1 = wrap_lock("t.a"), wrap_lock("t.b")
    a2, b2 = wrap_lock("t.a"), wrap_lock("t.b")
    with a1:
        with b1:
            pass
    with pytest.raises(LockOrderViolation):
        with b2:
            with a2:
                pass


def test_condition_wait_keeps_bookkeeping_exact():
    cond = threading.Condition(wrap_lock("t.cond", threading.RLock()))
    outer = wrap_lock("t.outer")
    released = []

    def waiter():
        with cond:
            cond.wait(timeout=5)
            # After wake-up the held stack must show the cond lock
            # again: acquiring another lock records the edge cleanly.
            with outer:
                released.append(True)

    thread = threading.Thread(target=waiter)
    thread.start()
    import time

    time.sleep(0.1)
    with cond:
        cond.notify_all()
    thread.join(5)
    assert released == [True]
    # wait() released the cond lock: the notifier's acquisition above
    # must NOT have recorded outer->cond or cond->outer inversions.
    with pytest.raises(LockOrderViolation):
        with outer:
            # now an inversion: outer held while acquiring cond after
            # cond->outer was recorded by the waiter
            with cond._lock:
                pass


def test_violation_list_bounded():
    a, b = wrap_lock("t.a"), wrap_lock("t.b")
    with a:
        with b:
            pass
    for _ in range(5):
        try:
            with b:
                with a:
                    pass
        except LockOrderViolation:
            pass
    assert len(lockdebug.VIOLATIONS) == 5


class _Guarded:
    """Minimal shared-state class in the project shape: lock first,
    state, then witness registration as the LAST line of __init__."""

    def __init__(self, lock_name="t.witness"):
        self._lock = wrap_lock(lock_name)
        self.state = "closed"  # pre-arming: must not trip
        self.count = 0
        witness_writes(self, lock_name, ("state", "count"))

    def set_state(self, value):
        with self._lock:
            self.state = value

    def racy_set(self, value):
        self.state = value


class TestWriteWitness:
    def test_noop_below_level_2(self, monkeypatch):
        monkeypatch.setenv(lockdebug.LOCK_DEBUG_ENV, "1")
        obj = _Guarded()
        obj.racy_set("open")  # witness unarmed: plain write
        assert obj.state == "open"
        assert type(obj).__name__ == "_Guarded"

    def test_guarded_write_passes(self, monkeypatch):
        monkeypatch.setenv(lockdebug.LOCK_DEBUG_ENV, "2")
        obj = _Guarded("t.w2")
        obj.set_state("open")
        assert obj.state == "open"

    def test_unguarded_write_raises_with_site(self, monkeypatch):
        monkeypatch.setenv(lockdebug.LOCK_DEBUG_ENV, "2")
        obj = _Guarded("t.w3")
        with pytest.raises(GuardedWriteViolation) as exc:
            obj.racy_set("open")
        message = str(exc.value)
        assert "t.w3" in message
        assert "write site" in message
        assert "racy_set" in message  # the writing frame is named
        assert any("guarded-write" in v for v in lockdebug.VIOLATIONS)

    def test_init_writes_exempt(self, monkeypatch):
        # Construction writes precede witness_writes at the end of
        # __init__ — building the object must not trip.
        monkeypatch.setenv(lockdebug.LOCK_DEBUG_ENV, "2")
        obj = _Guarded("t.w4")
        assert obj.state == "closed"

    def test_unregistered_attr_unchecked(self, monkeypatch):
        monkeypatch.setenv(lockdebug.LOCK_DEBUG_ENV, "2")
        obj = _Guarded("t.w5")
        obj.note = "free"  # not in the registered set

    def test_holding_wrong_lock_still_raises(self, monkeypatch):
        monkeypatch.setenv(lockdebug.LOCK_DEBUG_ENV, "2")
        obj = _Guarded("t.w6")
        other = wrap_lock("t.other6")
        with other:
            with pytest.raises(GuardedWriteViolation):
                obj.state = "open"

    def test_sampling_skips_unsampled_writes(self, monkeypatch):
        monkeypatch.setenv(lockdebug.LOCK_DEBUG_ENV, "2")
        monkeypatch.setenv(lockdebug.WITNESS_SAMPLE_ENV, "1000000")
        lockdebug.reset()  # re-resolve the sample cache
        obj = _Guarded("t.w7")
        # With a huge sample stride, unguarded writes slip through —
        # sampling trades coverage for cost, deliberately.
        for _ in range(5):
            obj.racy_set("open")
        assert obj.state == "open"

    def test_breaker_registered_and_clean(self, monkeypatch):
        monkeypatch.setenv(lockdebug.LOCK_DEBUG_ENV, "2")
        from kube_batch_tpu.solver.containment import reset_breaker

        breaker = reset_breaker()
        assert "witnessed" in type(breaker).__name__
        breaker.record_device_failure("t")
        breaker.record_device_success()
        assert breaker.state_dict()["failure_streak"] == 0
        with pytest.raises(GuardedWriteViolation):
            breaker.failure_streak = 99
        reset_breaker()

    def test_witness_disarms_when_level_drops(self, monkeypatch):
        """Regression: a witnessed instance outlives the env flag (the
        class swap is permanent) — a global like containment.BREAKER
        registered under level 2 must stop raising once the level
        drops, or every later same-process test that stages state by
        direct write fails on test order."""
        monkeypatch.setenv(lockdebug.LOCK_DEBUG_ENV, "2")
        obj = _Guarded("t.w8")
        monkeypatch.setenv(lockdebug.LOCK_DEBUG_ENV, "0")
        obj.racy_set("open")  # witnessed class, level 0: plain write
        assert obj.state == "open"

    def test_flightrecorder_registered_and_clean(self, monkeypatch):
        monkeypatch.setenv(lockdebug.LOCK_DEBUG_ENV, "2")
        from kube_batch_tpu.obs.flightrecorder import FlightRecorder

        rec = FlightRecorder(capacity=4)
        rec.begin_cycle(0)
        rec.phase("solve")
        rec.end_cycle(ok=True)
        assert len(rec.snapshot()) == 1


def test_wrapped_cache_snapshot_roundtrip():
    """A real SchedulerCache built under the flag: named proxies on
    mutex/fence/inflight-cond, and the snapshot/bind paths run clean
    (the chaos/micro smokes run the full storm; this is the unit-sized
    version)."""
    from kube_batch_tpu.cache.cache import SchedulerCache

    cache = SchedulerCache()
    assert type(cache.mutex).__name__ == "_OrderAssertingRLock"
    snap = cache.snapshot()
    assert snap is not None
    cache.fence("test")  # leaf path: must not acquire anything
    assert cache.fence_reason() == "test"
    cache.unfence()
    cache.shutdown()
