"""Plugin-builder and action registries.

Mirrors reference framework/plugins.go (:30 RegisterPluginBuilder,
:45 GetPluginBuilder, :58 RegisterAction, :66 GetAction). Thread-safe global
maps; plugins/actions self-register at import time (the reference uses
package init(), triggered by blank imports in cmd/kube-batch/main.go:33-35 —
here ``kube_batch_tpu.plugins``/``.actions`` package import does the same).
"""

from __future__ import annotations

from typing import Callable, Dict, Optional, Tuple

from ..utils.lockdebug import wrap_lock
from .arguments import Arguments
from .interface import Action, Plugin

PluginBuilder = Callable[[Arguments], Plugin]

_lock = wrap_lock("framework.registry")
_plugin_builders: Dict[str, PluginBuilder] = {}
_actions: Dict[str, Action] = {}


def register_plugin_builder(name: str, builder: PluginBuilder) -> None:
    with _lock:
        _plugin_builders[name] = builder


def get_plugin_builder(name: str) -> Optional[PluginBuilder]:
    with _lock:
        return _plugin_builders.get(name)


def register_action(action: Action) -> None:
    with _lock:
        _actions[action.name()] = action


def get_action(name: str) -> Tuple[Optional[Action], bool]:
    with _lock:
        act = _actions.get(name)
        return act, act is not None


def cleanup_plugin_builders() -> None:
    with _lock:
        _plugin_builders.clear()
