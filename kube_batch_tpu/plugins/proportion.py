"""Proportion plugin: queue-level weighted fair share via water-filling.

Mirrors reference plugins/proportion/proportion.go:
- Iterative water-filling distributes cluster capacity to queues by weight
  until remaining is empty or every queue's request is met (:100-147).
- QueueOrderFn by share = max(allocated/deserved) (:156-168, :241-253).
- ReclaimableFn: victim ok if its queue stays >= deserved after removal
  (:171-195).
- OverusedFn: deserved <= allocated (:198-208).
- Event handlers keep allocated/share live (:211-234).
"""

from __future__ import annotations

from typing import Dict

from ..api import (
    QueueInfo,
    Resource,
    min_resource,
    share as share_fn,
)
from ..api.types import TaskStatus
from ..framework import EventHandler, Plugin, register_plugin_builder


class _QueueAttr:
    __slots__ = ("queue_id", "name", "weight", "deserved", "allocated", "request", "share")

    def __init__(self, queue_id: str, name: str, weight: int):
        self.queue_id = queue_id
        self.name = name
        self.weight = weight
        self.deserved = Resource.empty()
        self.allocated = Resource.empty()
        self.request = Resource.empty()
        self.share = 0.0


class ProportionPlugin(Plugin):
    def __init__(self, arguments=None):
        self.arguments = arguments or {}
        self.total_resource = Resource.empty()
        self.queue_attrs: Dict[str, _QueueAttr] = {}

    def name(self) -> str:
        return "proportion"

    def _update_share(self, attr: _QueueAttr) -> None:
        res = 0.0
        for rn in attr.deserved.resource_names():
            s = share_fn(attr.allocated.get(rn), attr.deserved.get(rn))
            if s > res:
                res = s
        attr.share = res

    def on_session_open(self, ssn) -> None:
        from .drf import fold_reuse_enabled

        # Shared per-session aggregate (one O(nodes) pass for all
        # plugins, not one each).
        self.total_resource = ssn.total_node_allocatable()

        # Cross-session fold reuse: the per-job PENDING walk (request =
        # allocated + pending) is the O(tasks) term of this open; an
        # unchanged job keeps its snapshot clone (identity + _ver), so
        # its pending sum from the previous open is still exact and the
        # walk runs only for churned jobs. The queue aggregation itself
        # stays O(jobs) Resource adds — small constant, no task walks.
        store = (
            ssn.cache.plugin_fold if fold_reuse_enabled(ssn.cache) else None
        )
        pend_cache: Dict[str, tuple] = (
            store.setdefault("proportion", {}) if store is not None else {}
        )

        # Build queue attributes from jobs (reference :66-99).
        for job in ssn.jobs.values():
            if job.queue not in ssn.queues:
                continue
            if job.queue not in self.queue_attrs:
                queue = ssn.queues[job.queue]
                self.queue_attrs[queue.uid] = _QueueAttr(
                    queue.uid, queue.name, queue.weight
                )
            attr = self.queue_attrs[job.queue]
            # allocated-status sum == the maintained JobInfo.allocated
            # aggregate; only the PENDING index still needs a per-task
            # walk (request = allocated + pending). Steady-state session
            # opens stop re-summing every placed task.
            attr.allocated.add(job.allocated)
            attr.request.add(job.allocated)
            ent = pend_cache.get(job.uid)
            if ent is not None and ent[0] is job and ent[1] == job._ver:
                pending = ent[2]
            else:
                pending = Resource.empty()
                for t in job.task_status_index.get(
                    TaskStatus.PENDING, {}
                ).values():
                    pending.add(t.resreq)
                pend_cache[job.uid] = (job, job._ver, pending)
            attr.request.add(pending)
        if store is not None and len(pend_cache) > len(ssn.jobs) + 1024:
            # Bound the store against deleted-job residue (entries are
            # self-invalidating, so this is memory hygiene only).
            live = {
                uid: ent for uid, ent in pend_cache.items()
                if uid in ssn.jobs
            }
            store["proportion"] = live

        # Water-filling (reference :100-147).
        remaining = self.total_resource.clone()
        meet: Dict[str, bool] = {}
        while True:
            total_weight = sum(
                a.weight for a in self.queue_attrs.values() if a.queue_id not in meet
            )
            if total_weight == 0:
                break
            increased = Resource.empty()
            decreased = Resource.empty()
            for attr in self.queue_attrs.values():
                if attr.queue_id in meet:
                    continue
                old_deserved = attr.deserved.clone()
                attr.deserved.add(
                    remaining.clone().multi(attr.weight / total_weight)
                )
                if attr.request.less(attr.deserved):
                    attr.deserved = min_resource(attr.deserved, attr.request)
                    meet[attr.queue_id] = True
                self._update_share(attr)
                inc, dec = attr.deserved.diff(old_deserved)
                increased.add(inc)
                decreased.add(dec)
            remaining.sub(increased)
            remaining.add(decreased)
            if remaining.is_empty():
                break

        def queue_order_fn(l: QueueInfo, r: QueueInfo) -> int:
            ls, rs = self.queue_attrs[l.uid].share, self.queue_attrs[r.uid].share
            if ls == rs:
                return 0
            return -1 if ls < rs else 1

        ssn.add_queue_order_fn(self.name(), queue_order_fn)

        def reclaimable_fn(reclaimer, reclaimees):
            victims = []
            allocations: Dict[str, Resource] = {}
            for reclaimee in reclaimees:
                job = ssn.jobs.get(reclaimee.job)
                attr = (
                    self.queue_attrs.get(job.queue)
                    if job is not None else None
                )
                if attr is None:
                    # Untracked queue (see _attr_of): proportion has no
                    # share opinion, so it neither protects nor offers
                    # the task — raising here would abort reclaim.
                    continue
                if job.queue not in allocations:
                    allocations[job.queue] = attr.allocated.clone()
                allocated = allocations[job.queue]
                if allocated.less(reclaimee.resreq):
                    continue
                allocated.sub(reclaimee.resreq)
                if attr.deserved.less_equal(allocated):
                    victims.append(reclaimee)
            return victims

        ssn.add_reclaimable_fn(self.name(), reclaimable_fn)

        def overused_fn(queue: QueueInfo) -> bool:
            attr = self.queue_attrs.get(queue.uid)
            if attr is None:
                return False
            return attr.deserved.less_equal(attr.allocated)

        ssn.add_overused_fn(self.name(), overused_fn)

        def queue_budget_fn(queue: QueueInfo):
            attr = self.queue_attrs.get(queue.uid)
            if attr is None:
                return None
            return attr.deserved, attr.allocated

        ssn.add_queue_budget_fn(self.name(), queue_budget_fn)

        def _attr_of(task):
            # A task whose job sits on a queue proportion never tracked
            # (e.g. a shadow job on a deleted/missing queue — the same
            # jobs on_session_open skips) has no share bookkeeping; an
            # event handler raising here would abort the caller's whole
            # allocate, so skip instead.
            job = ssn.jobs.get(task.job)
            if job is None:
                return None
            return self.queue_attrs.get(job.queue)

        def on_allocate(event):
            attr = _attr_of(event.task)
            if attr is None:
                return
            attr.allocated.add(event.task.resreq)
            self._update_share(attr)

        def on_deallocate(event):
            attr = _attr_of(event.task)
            if attr is None:
                return
            attr.allocated.sub(event.task.resreq)
            self._update_share(attr)

        def _attr_of_job(job):
            # Same skip rule as _attr_of, with the job already resolved.
            return self.queue_attrs.get(job.queue)

        def on_allocate_batch(batches):
            # Aggregate fold of on_allocate: the deserved/allocated math
            # is associative over a batch, so each per-job JobBatchEvent
            # costs one Resource add on its queue attr and each touched
            # queue one share update — ~#jobs work for a 50k-task apply
            # (proportion.go:211-234's per-event form).
            touched = {}
            for b in batches:
                attr = _attr_of_job(b.job)
                if attr is None:
                    continue
                attr.allocated.add(b.delta)
                touched[id(attr)] = attr
            for attr in touched.values():
                self._update_share(attr)

        def on_evict_batch(batches):
            # Aggregate fold of on_deallocate.
            touched = {}
            for b in batches:
                attr = _attr_of_job(b.job)
                if attr is None:
                    continue
                attr.allocated.sub(b.delta)
                touched[id(attr)] = attr
            for attr in touched.values():
                self._update_share(attr)

        ssn.add_event_handler(
            EventHandler(
                allocate_func=on_allocate,
                deallocate_func=on_deallocate,
                batch_allocate_func=on_allocate_batch,
                batch_deallocate_func=on_evict_batch,
            )
        )

    def on_session_close(self, ssn) -> None:
        self.total_resource = Resource.empty()
        self.queue_attrs = {}


register_plugin_builder("proportion", lambda args: ProportionPlugin(args))
