"""Device-resident snapshot: persistent solver buffers + delta patches.

PR 1 made the HOST side of the snapshot incremental (fingerprint-patched
columnar arrays in ``_TensorizeCache``); this module extends the same
"keep the problem resident, ship only deltas" move onto the device.
Every ``tensorize(device=True)`` used to rebuild a fresh
:class:`~.kernels.PackedInputs` and re-ship all of it host→device — a
round trip per stacked buffer, ~6 MB at 50k×5k, every cycle, even when
a 1% delta changed a few hundred rows. CvxCluster (PAPERS.md) gets its
100-1000× on granular allocation problems from exactly this shape of
re-solve: the operator stays resident, only the changed entries move.

The cache holds, per PackedInputs field, the device buffer AND the
exact host copy it was built from. Packing a new snapshot then becomes,
per field:

- **reuse** — bit-identical host array → hand back the resident buffer,
  zero bytes shipped (the steady-state no-churn cycle);
- **patch** — same shape/dtype, few dirty rows → ship only those rows
  and scatter them in with ONE jitted ``.at[rows].set`` whose input
  buffer is **donated**, so XLA updates the resident allocation in
  place instead of materializing a second copy;
- **full upload** — cold cache, shape/dtype drift (bucket growth,
  resource-layout change), or bulk dirtiness past the patch break-even
  (same ~25% rule as the host-side ``_refresh_node_arrays``).

Change detection is a host-side diff against the cached host copy —
O(array bytes) of numpy compare, a few ms at 50k×5k and **exact by
construction**: the dirty-name ledger (``ClusterInfo.dirty_jobs/nodes``
→ clone fingerprints) decides which HOST rows get recomputed, and the
diff here is what guarantees the device buffers converge to those rows
bit-for-bit no matter which path produced them. Parity is therefore a
structural property, pinned by tests/solver/test_device_cache.py.

Shapes stay stable across cycles because tensorize buckets every axis
(``_task_bucket``/``_pow2``/128-multiples), so the patch jits compile
once per (buffer shape, row-bucket) pair and the solver jit never
retraces on a steady stream of deltas (tests/solver/test_retrace_guard
pins this).

OWNERSHIP: the returned PackedInputs buffers belong to the cache and
are valid until the next ``pack()`` on the same scheduler cache — a
later patch DONATES the old buffer, which deletes it under any holder.
Consume the inputs within the cycle (the action does); copy to host
(``np.asarray``) anything that must outlive it.
"""

from __future__ import annotations

import functools
import logging
from typing import Dict, Optional

import numpy as np

from ..utils.lockdebug import wrap_lock
from .contracts import contracts_enabled, validate_packed

logger = logging.getLogger(__name__)

# Forensics of the most recent pack() (bench/metrics attribution, read
# by actions.allocate_tpu and bench.py). Single-threaded by
# construction, like snapshot.last_tensorize_stats.
last_pack_stats: dict = {}

# Axis along which cycle-to-cycle deltas are row-shaped, per
# PackedInputs field (stacked buffers carry their stack dim first).
_ROW_AXIS = {
    "task_f32": 1,
    "task_i32": 1,
    "node_f32": 1,
    "node_i32": 1,
    "group_feas": 0,
    "pair_idx": 0,
    "pair_feas": 0,
    "score_idx": 0,
    "score_rows": 0,
    "queue_f32": 1,
    "misc": 0,
    # Candidate slabs (solver/topk.py): class-row deltas, same donated
    # row-scatter machinery as the other factorized rows.
    "cand_idx": 0,
    "cand_static": 0,
    "cand_info": 1,
}

# Past this dirty fraction a full upload beats row patching (mirrors
# the host-side bulk-dirty rule in snapshot._refresh_node_arrays).
_BULK_DIRTY_DEN = 4
# Buffers below this size are cheaper to re-ship whole than to run a
# scatter program over (also keeps tiny fields like ``misc`` from
# minting patch-jit entries).
_MIN_PATCH_BYTES = 4096

# Row-bucket axes that have minted a patch jit (for retrace counting).
_patch_axes_used: set = set()
_patch_axes_lock = wrap_lock("solver.patch_axes")


def _row_bucket(n: int) -> int:
    """Power-of-two bucket for the patched-row axis so a churning dirty
    count does not mint a new jit per cycle."""
    if n <= 0:
        return 1
    return 1 << (n - 1).bit_length()


@functools.lru_cache(maxsize=None)
def _patcher(axis: int):
    """Jitted donated row-scatter along ``axis``. Padded row indices
    point one past the end and are dropped (``mode='drop'``), so the
    row bucket never writes garbage."""
    import jax

    def patch(buf, rows, vals):
        idx = (slice(None),) * axis + (rows,)
        return buf.at[idx].set(vals, mode="drop")

    return jax.jit(patch, donate_argnums=(0,))


def patch_jit_cache_size() -> int:
    """Total compiled variants across every patch jit minted so far —
    one term of the retrace-regression guard."""
    total = 0
    with _patch_axes_lock:
        axes = tuple(_patch_axes_used)
    for axis in axes:
        try:
            total += _patcher(axis)._cache_size()
        except Exception:  # pragma: no cover - private-API drift
            pass
    return total


class DeviceSnapshotCache:
    """Per-scheduler-cache device residency for the solver's inputs.

    Lives on the SchedulerCache object (``_device_snapshot_cache``
    attribute), giving it exactly the lifetime of the mirror it
    shadows — same pattern as ``snapshot._TensorizeCache``."""

    __slots__ = ("host", "dev", "layout_token", "placement", "_deferred")

    def __init__(self):
        # field -> exact host copy of what is resident on device
        self.host: Dict[str, np.ndarray] = {}
        # field -> jax.Array resident buffer
        self.dev: Dict[str, object] = {}
        # Stats of a pack_partial() awaiting merge into the next pack()
        # (the two are one logical pack per cycle).
        self._deferred: Optional[dict] = None
        # Solver device-layout key (sharding.packed_sparse_placement):
        # resident buffers are only reusable under the layout they were
        # placed for — a mesh/mode flip voids them all (labeled
        # ``mesh-change`` full re-upload).
        self.layout_token = None
        # jax.sharding.Sharding applied at upload time (None = default
        # single-device placement).
        self.placement = None

    def drop(self) -> None:
        """Release every resident buffer (shutdown / tests)."""
        self.host.clear()
        self.dev.clear()

    # ------------------------------------------------------------------

    def _diff_rows(self, name: str, arr: np.ndarray, cached: np.ndarray):
        axis = _ROW_AXIS[name]
        neq = arr != cached
        if neq.ndim > 1:
            red = tuple(i for i in range(neq.ndim) if i != axis)
            dirty = neq.any(axis=red)
        else:
            dirty = neq
        return np.nonzero(dirty)[0], arr.shape[axis]

    def _upload(self, name: str, arr: np.ndarray, reason: str, stats):
        import jax.numpy as jnp

        if self.placement is not None:
            import jax

            dev = jax.device_put(arr, self.placement)
        else:
            dev = jnp.asarray(arr)
        self.host[name] = arr
        self.dev[name] = dev
        stats["uploads"] += 1
        stats["bytes_shipped"] += arr.nbytes
        stats["full_reasons"][name] = reason
        stats["field_outcomes"][name] = "upload"
        return dev

    def _patch(self, name: str, arr: np.ndarray, rows: np.ndarray, stats):
        import jax.numpy as jnp

        axis = _ROW_AXIS[name]
        nrows = arr.shape[axis]
        K = _row_bucket(rows.size)
        # Padded indices = nrows (one past the end): dropped by the
        # scatter, so the bucket costs shipping, not correctness.
        rows_p = np.full(K, nrows, dtype=np.int32)
        rows_p[:rows.size] = rows
        vals = np.take(arr, rows, axis=axis)
        vshape = list(vals.shape)
        vshape[axis] = K
        vals_p = np.zeros(tuple(vshape), dtype=arr.dtype)
        sl = [slice(None)] * vals.ndim
        sl[axis] = slice(0, rows.size)
        vals_p[tuple(sl)] = vals
        with _patch_axes_lock:
            _patch_axes_used.add(axis)
        dev = _patcher(axis)(
            self.dev[name], jnp.asarray(rows_p), jnp.asarray(vals_p)
        )
        self.host[name] = arr
        self.dev[name] = dev
        stats["patches"] += 1
        stats["rows_patched"] += int(rows.size)
        stats["bytes_shipped"] += vals_p.nbytes + rows_p.nbytes
        stats["field_outcomes"][name] = "patch"
        return dev

    def _pack_field(self, name: str, arr: np.ndarray,
                    cold_reason: str, stats: dict):
        """Reuse/patch/upload decision for ONE stacked field (shared by
        :meth:`pack` and :meth:`pack_partial`)."""
        cached = self.host.get(name)
        dev = self.dev.get(name)
        if cached is None or dev is None:
            return self._upload(name, arr, cold_reason, stats)
        if cached.shape != arr.shape or cached.dtype != arr.dtype:
            return self._upload(name, arr, "shape-change", stats)
        rows, nrows = self._diff_rows(name, arr, cached)
        if rows.size == 0:
            stats["reuses"] += 1
            stats["field_outcomes"][name] = "reuse"
            return dev
        if arr.nbytes < _MIN_PATCH_BYTES:
            return self._upload(name, arr, "small-buffer", stats)
        if rows.size * _BULK_DIRTY_DEN > nrows:
            return self._upload(name, arr, "bulk-dirty", stats)
        return self._patch(name, arr, rows, stats)

    def _empty_stats(self) -> dict:
        return {
            "reuses": 0,
            "patches": 0,
            "uploads": 0,
            "rows_patched": 0,
            "bytes_shipped": 0,
            "slab_bytes_shipped": 0,
            "bytes_total": 0,
            "full_reasons": {},
            "field_outcomes": {},
        }

    def _enter_layout(self, placement, layout_token, stats) -> str:
        cold_reason = "cold"
        if layout_token != self.layout_token:
            if self.host:
                self.drop()
                cold_reason = "mesh-change"
                stats["layout_change"] = True
            self.layout_token = layout_token
        self.placement = placement
        return cold_reason

    def pack_partial(self, arrays: Dict[str, np.ndarray],
                     placement: Optional[object] = None,
                     layout_token: Optional[str] = None) -> Dict[str, object]:
        """Place a SUBSET of the cycle's stacked fields on device ahead
        of the full :meth:`pack` — the device-resident selection pass
        (solver/select_device.py) needs the node stacks and group rows
        resident BEFORE the candidate slabs it produces can exist. The
        later pack() sees bit-identical host arrays and reuses these
        buffers; the traffic stats here are deferred and merged into
        that pack()'s ledger so per-cycle accounting stays whole."""
        stats = self._empty_stats()
        cold_reason = self._enter_layout(placement, layout_token, stats)
        out = {
            name: self._pack_field(name, arr, cold_reason, stats)
            for name, arr in arrays.items()
        }
        if self._deferred is None:
            self._deferred = stats
        else:  # two partials before a pack: fold counters forward
            for key in ("patches", "uploads", "rows_patched",
                        "bytes_shipped"):
                self._deferred[key] += stats[key]
            self._deferred["full_reasons"].update(stats["full_reasons"])
            self._deferred["field_outcomes"].update(
                stats["field_outcomes"]
            )
        return out

    def pack(self, arrays: Dict[str, np.ndarray],
             placement: Optional[object] = None,
             layout_token: Optional[str] = None):
        """Build a :class:`~.kernels.PackedInputs` from stacked host
        arrays, reusing/patching resident device buffers per field (see
        module docstring for the reuse/patch/upload decision). Records
        per-cycle forensics in :data:`last_pack_stats` and exports the
        aggregate counters through ``metrics``.

        ``placement``/``layout_token`` parameterize residency by the
        solver's device layout (sharding.packed_sparse_placement): a
        token change drops every resident buffer — a buffer laid out
        for one mesh/mode cannot be patched into another — and the
        whole snapshot re-uploads under the new placement, labeled
        ``mesh-change``."""
        from .kernels import PackedInputs

        if contracts_enabled():
            # Runtime twin of the kbtlint shape-contracts pass: every
            # stacked buffer against the declaration table, symbolic
            # dims bound across fields (KBT_CHECK_CONTRACTS=1).
            validate_packed(arrays, where="device_cache.pack")

        stats = self._empty_stats()
        cold_reason = self._enter_layout(placement, layout_token, stats)
        fields: Dict[str, object] = {}
        for name, arr in arrays.items():
            stats["bytes_total"] += arr.nbytes
            shipped_before = stats["bytes_shipped"]
            fields[name] = self._pack_field(name, arr, cold_reason, stats)
            if name.startswith("cand"):
                stats["slab_bytes_shipped"] += (
                    stats["bytes_shipped"] - shipped_before
                )

        # Fold in a preceding pack_partial (same cycle, same logical
        # pack): its uploads/patches are real traffic; a field it
        # already placed shows as "reuse" above, so surface the partial
        # outcome instead for forensics.
        deferred, self._deferred = self._deferred, None
        if deferred is not None:
            for key in ("patches", "uploads", "rows_patched",
                        "bytes_shipped"):
                stats[key] += deferred[key]
            stats["full_reasons"].update(deferred["full_reasons"])
            for f, outcome in deferred["field_outcomes"].items():
                if outcome != "reuse":
                    if stats["field_outcomes"].get(f) == "reuse":
                        stats["reuses"] -= 1
                    stats["field_outcomes"][f] = outcome
            if deferred.get("layout_change"):
                stats["layout_change"] = True

        last_pack_stats.clear()
        last_pack_stats.update(stats)
        try:
            from .. import metrics

            metrics.update_device_cache(stats)
        except Exception:  # pragma: no cover - metrics must never kill
            logger.exception("device-cache metrics export failed")
        return PackedInputs(**fields)


def device_cache_of(cache) -> Optional[DeviceSnapshotCache]:
    """The scheduler cache's device snapshot cache, created on first
    use; None for slots-only stand-ins (then callers pack uncached)."""
    if cache is None:
        return None
    dc = getattr(cache, "_device_snapshot_cache", None)
    if dc is None:
        dc = DeviceSnapshotCache()
        try:
            cache._device_snapshot_cache = dc
        except Exception:
            return None
    return dc
