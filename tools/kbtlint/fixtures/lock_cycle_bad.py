"""kbtlint self-test fixture: a lock-order CYCLE (known-bad).

``forward`` takes a→b, ``backward`` takes b→a: two threads running one
each deadlock. The lock-order pass must report the cycle.
"""

import threading


class Worker:
    def __init__(self):
        self.lock_a = threading.Lock()
        self.lock_b = threading.Lock()

    def forward(self):
        with self.lock_a:
            with self.lock_b:
                return 1

    def backward(self):
        with self.lock_b:
            with self.lock_a:
                return 2
