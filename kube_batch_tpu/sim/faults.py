"""Deterministic fault injection.

Spec grammar (doc/design/simulator.md): comma-separated
``kind:probability`` terms, e.g. ``"bind:0.05,node-flap:0.02"``.

| kind           | seam | effect |
|----------------|------|--------|
| ``bind``       | Binder wrapper | bind side effect raises; the cache's resync path re-pends the task |
| ``node-flap``  | pre-cycle      | node removed (pods killed + recreated Pending), returns after a seeded 1-4 cycles |
| ``node-death`` | mid-cycle      | node doomed for the cycle: every bind to it fails AND the first one deletes the node under the in-flight batch; permanent |
| ``evict``      | pre-cycle      | one seeded Running pod deleted (external eviction race); recreated Pending |
| ``solver``     | per-cycle env  | forces ``KBT_SOLVER=native`` for the cycle (accelerator-backend failure → native fallback) |
| ``crash``      | action shim    | in-cycle EXCEPTION injection: a raising action is prepended for the cycle; the SAME process absorbs it through the guarded-cycle error backoff and keeps scheduling. NOT a crash analog for process death — see ``leader-kill`` |
| ``leader-kill``| cluster endpoint | PROCESS-death analog: the leader is hard-stopped at a seeded cut point (``pre-solve`` / ``post-solve-pre-drain`` / ``mid-bind-drain`` / ``mid-close``, sim/failover.py) — nothing fences, nothing unwinds, its surviving writes stay in the cluster; a successor instance takes the lease and runs journal recovery (cache/recovery.py) |
| ``solver-exc`` | device-fault hook | the device-solve materialization raises for the cycle; the containment ladder must re-solve on a lower rung |
| ``solver-hang``| device-fault hook | the device-solve materialization outsleeps the solve budget; the fetch deadline must abandon it and drop to native |
| ``backend-loss``| device-fault hook | device solves AND the breaker's canary probe raise for a seeded 1-4 cycles (device lost); the breaker must hold open until the window closes, then re-promote |
| ``event-drop``  | watch interceptor | a Pod/Node watch event is never delivered — the mirror silently diverges; gap detection (relist) + the anti-entropy sweep must repair it |
| ``event-dup``   | watch interceptor | the event is delivered twice (same rv); the ingest guard must absorb the duplicate |
| ``event-reorder``| watch interceptor | delivery SWAP: stashed and delivered after the next event (flushed at the cycle barrier) |
| ``event-stale`` | watch interceptor | the object's previous event (older rv) is redelivered after the current one; the per-object guard must skip it |
| ``relist-fail`` | relist seam | list_for_relist raises a typed TransientClusterError (hash per call); the deterministic-jitter retry ladder absorbs it |
| ``solver-corrupt``| result tamper hook | a device rung's fetched assignment vector is rewritten to out-of-universe indices; post-solve validation must reject it before any bind dispatches |

The device-fault kinds are armed through
``solver.containment.set_device_fault_hook`` — the hook fires inside
the fetch-side materialization and the canary probe, exactly where a
real accelerator fault lands. All three are planned per cycle from the
seeded stream (the hang/raise DECISION is planned; only its wall-time
cost is real), so chaos runs replay bit-identically.

Two determinism regimes:
- cycle-planned faults (flap/death/evict/solver/crash) are drawn from a
  seeded stream in the sim thread BEFORE the cycle runs and recorded in
  the trace as fault events;
- per-bind failures are decided by a pure hash of
  ``(seed, pod uid, attempt#)`` — bind side effects run concurrently on
  the cache's worker pool, so a shared RNG stream there would make the
  decision order (hence the decisions) timing-dependent. A hash keyed
  on stable identities is thread-safe AND replays bit-identically.
"""

from __future__ import annotations

import random
import time
from typing import Callable, Dict, List, Optional, Sequence, Set, Tuple

from ..utils.determinism import hash01 as _hash01
from ..utils.lockdebug import wrap_lock

# _hash01: stable uniform [0,1) from identity parts (independent of
# PYTHONHASHSEED and thread timing) — the shared implementation in
# utils/determinism, under the name the sim package (and
# sim/failover.py) has always used.

FAULT_KINDS = (
    "bind", "node-flap", "node-death", "evict", "solver", "crash",
    "solver-exc", "solver-hang", "backend-loss", "leader-kill",
    "event-drop", "event-dup", "event-reorder", "event-stale",
    "relist-fail", "solver-corrupt",
)

# Event-stream fault kinds fire at the WATCH DELIVERY seam (the
# injector's interceptor wraps the cache's watch handler via
# SimClusterEndpoint.add_watch) and only on Pod/Node events — the
# kinds the cache's relist + anti-entropy reconcile cover.
EVENT_FAULT_KINDS = (
    "event-drop", "event-dup", "event-reorder", "event-stale",
)
_EVENT_FAULT_TARGET_KINDS = frozenset({"Pod", "Node"})


class SimBindFailure(RuntimeError):
    """Injected bind failure (distinguishable from real bind errors)."""


class SimSolverFault(RuntimeError):
    """Injected device-solve failure (solver-exc / backend-loss; raised
    from the containment layer's device fault hook)."""


def parse_fault_spec(spec: str) -> Dict[str, float]:
    """``"bind:0.05,node-flap:0.02"`` → ``{"bind": 0.05, ...}``.
    Unknown kinds and out-of-range probabilities are hard errors — a
    typo silently injecting nothing would green-light a broken run."""
    out: Dict[str, float] = {}
    for term in (spec or "").split(","):
        term = term.strip()
        if not term:
            continue
        kind, sep, prob = term.partition(":")
        kind = kind.strip()
        if kind not in FAULT_KINDS:
            raise ValueError(
                f"unknown fault kind {kind!r} (known: {', '.join(FAULT_KINDS)})"
            )
        if not sep:
            raise ValueError(f"fault term {term!r} missing ':probability'")
        p = float(prob)
        if not 0.0 <= p <= 1.0:
            raise ValueError(f"fault probability out of [0,1]: {term!r}")
        out[kind] = p
    return out


class _FaultyBinder:
    """Binder wrapper: consults the injector before delegating."""

    def __init__(self, inner, injector: "FaultInjector"):
        self.inner = inner
        self.injector = injector

    def bind(self, pod, hostname: str) -> None:
        self.injector.on_bind(pod, hostname)
        self.inner.bind(pod, hostname)


class _CrashAction:
    """Prepended for a crash-fault cycle: run_once raises, the guarded
    scheduler loop must absorb it."""

    def name(self) -> str:
        return "sim-crash"

    def initialize(self) -> None:
        pass

    def un_initialize(self) -> None:
        pass

    def execute(self, ssn) -> None:
        raise SimBindFailure("injected scheduler-cycle crash")


class FaultInjector:
    def __init__(self, spec: Dict[str, float], seed: int):
        self.spec = dict(spec or {})
        self.seed = seed
        self.rng = random.Random(f"{seed}/faults")
        self._lock = wrap_lock("sim.faults")
        self._bind_attempts: Dict[str, int] = {}
        self._cycle = -1
        self._active = False
        # Mid-cycle death state: nodes doomed this cycle, and the
        # cluster handle used to delete them under the in-flight batch.
        self._doomed: Set[str] = set()
        self._cluster = None
        self._killed_mid_cycle: Set[str] = set()
        # Device-fault state (solver-exc / solver-hang / backend-loss):
        # the per-cycle armed fault and the backend-loss window's end
        # cycle (exclusive). Consulted by the containment-layer hook.
        self._solver_fault: Optional[str] = None
        self._backend_loss_until = -1
        # Result-corruption state (solver-corrupt): armed per cycle;
        # the containment tamper hook rewrites a device rung's fetched
        # assignment vector deterministically (hash of seed+cycle).
        self._corrupt_cycle = False
        # Event-stream fault state (event-drop/dup/reorder/stale,
        # relist-fail): armed for the whole cycle window (events apply
        # BEFORE the scheduling step, so arming rides
        # begin_cycle_events, not begin_cycle). Decisions are pure
        # hashes of (seed, fault, object key, per-key delivery seq) —
        # the bind-seam determinism regime: deliveries happen on
        # concurrent watch/side-effect threads, so a shared RNG stream
        # would be timing-dependent.
        self._events_active = False
        self._event_cycle = -1
        self._event_seq: Dict[Tuple[str, str], int] = {}
        self._reorder_stash: List[tuple] = []
        self._stale_memo: Dict[Tuple[str, str], tuple] = {}
        self._event_forensics: Dict[str, int] = {}
        self._dropped_events: List[Tuple[str, str, str]] = []
        self._relist_calls = 0
        self._relist_fails = 0
        self._wrapped_inner = None
        # Forensics drained by the harness each cycle. _bind_faults
        # counts the hash-decided failures only (doomed-node rejections
        # ride under their planned node-death event).
        self._bind_failures: List[Tuple[str, str]] = []
        self._bind_faults = 0

    # -- wiring --------------------------------------------------------------

    def wrap_binder(self, binder):
        if binder is None:
            return None
        return _FaultyBinder(binder, self)

    def attach_cluster(self, cluster) -> None:
        self._cluster = cluster

    crash_action_factory = _CrashAction

    # -- cycle planning (sim thread, deterministic stream) -------------------

    def plan_cycle(
        self,
        cycle: int,
        node_names: Sequence[str],
        running_pods: Sequence[str],
    ) -> List[dict]:
        """Draw this cycle's planned faults. Returns trace-ready fault
        event dicts; the harness applies them (and ``begin_cycle`` arms
        the bind/doom seams)."""
        rng, spec = self.rng, self.spec
        events: List[dict] = []
        p_flap = spec.get("node-flap", 0.0)
        if p_flap and node_names and rng.random() < p_flap:
            victim = rng.choice(sorted(node_names))
            down_for = rng.randint(1, 4)
            events.append({
                "kind": "node-flap", "name": victim, "down_for": down_for,
            })
        p_death = spec.get("node-death", 0.0)
        if p_death and node_names and rng.random() < p_death:
            victim = rng.choice(sorted(node_names))
            events.append({"kind": "node-death", "name": victim})
        p_evict = spec.get("evict", 0.0)
        if p_evict and running_pods and rng.random() < p_evict:
            victim = rng.choice(sorted(running_pods))
            events.append({"kind": "evict", "pod": victim})
        if spec.get("solver", 0.0) and rng.random() < spec["solver"]:
            events.append({"kind": "solver"})
        if spec.get("crash", 0.0) and rng.random() < spec["crash"]:
            events.append({"kind": "crash"})
        if (
            spec.get("solver-exc", 0.0)
            and rng.random() < spec["solver-exc"]
        ):
            events.append({"kind": "solver-exc"})
        if (
            spec.get("solver-hang", 0.0)
            and rng.random() < spec["solver-hang"]
        ):
            events.append({"kind": "solver-hang"})
        p_loss = spec.get("backend-loss", 0.0)
        if p_loss and rng.random() < p_loss:
            events.append({
                "kind": "backend-loss", "down_for": rng.randint(1, 4),
            })
        if (
            spec.get("solver-corrupt", 0.0)
            and rng.random() < spec["solver-corrupt"]
        ):
            events.append({"kind": "solver-corrupt"})
        p_kill = spec.get("leader-kill", 0.0)
        if p_kill and rng.random() < p_kill:
            from .failover import CUT_POINTS

            events.append({
                "kind": "leader-kill", "cut": rng.choice(CUT_POINTS),
            })
        return events

    # -- cycle arming --------------------------------------------------------

    def begin_cycle(self, cycle: int, doomed_nodes: Sequence[str] = (),
                    solver_fault: Optional[str] = None,
                    corrupt: bool = False) -> None:
        with self._lock:
            self._cycle = cycle
            self._active = True
            self._doomed = set(doomed_nodes)
            self._killed_mid_cycle = set()
            self._solver_fault = solver_fault  # "exc" | "hang" | None
            self._corrupt_cycle = bool(corrupt)

    def begin_cycle_events(self, cycle: int) -> None:
        """Arm the event-stream fault seam for this cycle's whole
        window (workload events apply BEFORE the scheduling step, so
        this is called ahead of :meth:`begin_cycle`)."""
        with self._lock:
            self._events_active = True
            self._event_cycle = cycle
            self._relist_calls = 0

    def note_backend_loss(self, cycle: int, down_for: int) -> None:
        """Open (or extend) a backend-loss window: device solves AND
        the breaker's canary probe fail until ``cycle + down_for``."""
        with self._lock:
            self._backend_loss_until = max(
                self._backend_loss_until, cycle + int(down_for)
            )

    def device_fault_hook(self):
        """The callable the harness installs via
        ``solver.containment.set_device_fault_hook``. Runs inside the
        device-solve materialization (``stage="solve"``) and the
        breaker canary (``stage="probe"``); raising fails the stage,
        outsleeping the budget simulates a hung XLA sync. Decisions are
        pure functions of the planned per-cycle state — thread-safe and
        replay-deterministic like the bind hash seam."""

        def hook(stage: str) -> None:
            with self._lock:
                if not self._active:
                    return
                loss = self._cycle < self._backend_loss_until
                fault = self._solver_fault
            if loss:
                raise SimSolverFault(
                    f"injected backend loss ({stage} stage)"
                )
            if stage != "solve" or fault is None:
                return
            if fault == "exc":
                raise SimSolverFault("injected device-solve exception")
            # "hang": outsleep the fetch deadline; the abandoned
            # deadline thread wakes later and its result is discarded.
            from ..solver.containment import solve_budget

            time.sleep(min(3.0 * solve_budget(), 5.0))

        return hook

    # -- event-stream fault seam (watch delivery interceptor) ----------------

    @staticmethod
    def _event_subject(kind: str, obj) -> str:
        if kind == "Pod":
            try:
                return obj.uid
            except AttributeError:
                pass
        return obj.metadata.name

    def _decide_event_fault_locked(self, kind: str, obj) -> Optional[str]:
        """One delivery's fault decision (caller holds the lock):
        drop > reorder > dup > stale, each drawn from a pure hash of
        (seed, fault, kind, key, per-key delivery seq)."""
        if not self._events_active or kind not in _EVENT_FAULT_TARGET_KINDS:
            return None
        key = self._event_subject(kind, obj)
        seq = self._event_seq.get((kind, key), 0)
        self._event_seq[(kind, key)] = seq + 1
        for fault in EVENT_FAULT_KINDS:
            p = self.spec.get(fault, 0.0)
            if p and _hash01(self.seed, fault, kind, key, seq) < p:
                return fault
        return None

    def wrap_watch_handler(self, handler: Callable) -> Callable:
        """Interpose the event-stream fault seam between the cluster's
        watch fan-out and the cache's ingest (installed by
        SimClusterEndpoint.add_watch). Deliveries run OUTSIDE the
        injector lock; only decisions and the reorder stash are locked.
        Faulted kinds: Pod/Node (the reconcile scope of the cache's
        relist + anti-entropy sweep)."""

        def intercept(kind: str, event_type: str, obj: object,
                      rv: Optional[int] = None) -> None:
            deliveries: List[tuple] = []
            with self._lock:
                # Any arriving event flushes a stashed reordered one —
                # delivered AFTER the current event (the swap).
                flush, self._reorder_stash = self._reorder_stash, []
                action = self._decide_event_fault_locked(kind, obj)
                memo_key = (kind, self._event_subject(kind, obj))
                prev = self._stale_memo.get(memo_key)
                if action == "event-drop":
                    self._event_forensics["event-drop"] = (
                        self._event_forensics.get("event-drop", 0) + 1
                    )
                    self._dropped_events.append(
                        (kind, event_type, memo_key[1])
                    )
                    deliveries = flush
                elif action == "event-reorder":
                    self._event_forensics["event-reorder"] = (
                        self._event_forensics.get("event-reorder", 0) + 1
                    )
                    self._reorder_stash = [(kind, event_type, obj, rv)]
                    deliveries = flush
                else:
                    deliveries = [(kind, event_type, obj, rv)] + flush
                    if action == "event-dup":
                        self._event_forensics["event-dup"] = (
                            self._event_forensics.get("event-dup", 0) + 1
                        )
                        deliveries.append((kind, event_type, obj, rv))
                    elif action == "event-stale" and prev is not None:
                        self._event_forensics["event-stale"] = (
                            self._event_forensics.get("event-stale", 0)
                            + 1
                        )
                        # Redeliver the key's PREVIOUS event (older rv)
                        # after the current one — a genuinely stale
                        # arrival the cache guard must absorb.
                        deliveries.append(prev)
                if action != "event-drop":
                    if event_type == "DELETED":
                        self._stale_memo.pop(memo_key, None)
                    else:
                        self._stale_memo[memo_key] = (
                            kind, event_type, obj, rv
                        )
            for d in deliveries:
                handler(*d)

        # Remember the inner target so flush_events can late-deliver a
        # stashed reordered event at the harness's barrier. The wrapper
        # takes 4 positional args so the versioning cluster's arity
        # detection hands it the rv stamp.
        self._wrapped_inner = handler
        return intercept

    def flush_events(self) -> None:
        """Deliver any stashed reordered event (the harness calls this
        at its deterministic barrier, before the settle drains — a
        reorder is a SWAP, never a loss)."""
        with self._lock:
            stashes, self._reorder_stash = self._reorder_stash, []
        handler = getattr(self, "_wrapped_inner", None)
        if handler is None:
            return
        for kind, event_type, obj, rv in stashes:
            handler(kind, event_type, obj, rv)

    def on_relist(self, kind: str) -> None:
        """The relist/anti-entropy read seam
        (SimClusterEndpoint.list_for_relist): while armed, each list
        call fails with a typed TransientClusterError by a pure hash of
        (seed, cycle, call#) — exercising the capped-exponential retry
        ladder while staying replay-deterministic. Per-call draws keep
        the full-ladder-failure probability at p^attempts, so a failed
        reconcile defers to the next sweep instead of wedging."""
        p = self.spec.get("relist-fail", 0.0)
        with self._lock:
            if not self._events_active or p <= 0:
                return
            call = self._relist_calls
            self._relist_calls += 1
            fail = _hash01(
                self.seed, "relist-fail", self._event_cycle, kind, call
            ) < p
            if fail:
                self._relist_fails += 1
        if fail:
            from ..cluster.errors import TransientClusterError

            raise TransientClusterError(
                f"injected relist failure ({kind} list, cycle "
                f"{self._event_cycle})"
            )

    def result_tamper_hook(self) -> Callable:
        """The callable installed via
        ``solver.containment.set_result_tamper_hook``: on a
        solver-corrupt cycle, rewrite a deterministic subset of a
        device rung's assignments to out-of-universe node indices — a
        silent device miscompute the post-solve validation layer must
        reject before bind dispatch."""

        def tamper(assigned: object) -> object:
            import numpy as np

            with self._lock:
                armed = self._active and self._corrupt_cycle
                cycle = self._cycle
            if not armed:
                return assigned
            arr = np.array(assigned, copy=True)
            sel = np.nonzero(np.asarray(arr) >= 0)[0]
            if sel.size == 0:
                return assigned
            k = min(4, int(sel.size))
            for j in range(k):
                pick = sel[
                    int(_hash01(self.seed, "corrupt", cycle, j)
                        * sel.size)
                ]
                arr[pick] = 2**30 - j  # far outside any node universe
            return arr

        return tamper

    def prune_bind_attempts(self, live_uids) -> int:
        """Drop per-pod bind-attempt counters for pods that no longer
        exist. A dead pod's counter is unreachable: its uid never binds
        again (the controller analog recreates killed pods under
        generation-suffixed names — ``<base>r<gen>``, harness
        ``_schedule_recreate`` — so a uid, once dead, never recurs),
        so pruning cannot change any fault decision — but
        keeping them leaks one dict entry + uid string per pod that
        ever bound, forever (the soak leak detector found this as a
        perfectly linear alloc_blocks climb). The harness calls this at
        a deterministic barrier with the settled cluster's live uids."""
        live = set(live_uids)
        with self._lock:
            dead = [u for u in self._bind_attempts if u not in live]
            for uid in dead:
                del self._bind_attempts[uid]
        return len(dead)

    def end_cycle(self) -> dict:
        """Disarm and drain the cycle's bind-seam + event-seam
        forensics. The harness flushes the reorder stash BEFORE its
        settle barrier, so by the time this runs no event is in
        flight."""
        with self._lock:
            self._active = False
            self._events_active = False
            self._corrupt_cycle = False
            failures = sorted(self._bind_failures)
            self._bind_failures = []
            killed = sorted(self._killed_mid_cycle)
            self._doomed = set()
            bind_faults = self._bind_faults
            self._bind_faults = 0
            event_faults = dict(sorted(self._event_forensics.items()))
            self._event_forensics = {}
            dropped = sorted(self._dropped_events)
            self._dropped_events = []
            relist_fails = self._relist_fails
            self._relist_fails = 0
        return {
            "bind_failures": failures,
            "nodes_killed": killed,
            "bind_faults": bind_faults,
            "event_faults": event_faults,
            "events_dropped": dropped,
            "relist_fails": relist_fails,
        }

    # -- the bind seam (side-effect pool threads) ----------------------------

    def on_bind(self, pod, hostname: str) -> None:
        key = f"{pod.namespace}/{pod.name}"
        with self._lock:
            if not self._active:
                return
            doomed = hostname in self._doomed
            kill_node = doomed and hostname not in self._killed_mid_cycle
            if kill_node:
                self._killed_mid_cycle.add(hostname)
            if not doomed:
                p = self.spec.get("bind", 0.0)
                if p <= 0:
                    # No bind faults configured: do not even track the
                    # attempt counter — it is only hash input, and a
                    # per-pod-uid dict entry on every bind is a leak
                    # over a 100k-cycle soak.
                    return
                attempt = self._bind_attempts.get(pod.uid, 0)
                self._bind_attempts[pod.uid] = attempt + 1
                fail = _hash01(
                    self.seed, "bind", pod.uid, attempt
                ) < p
                if not fail:
                    return
                # Planned faults (flap/death/evict/...) are counted by
                # the harness when it applies their events; only the
                # per-bind hash decisions are counted here.
                self._bind_faults += 1
            self._bind_failures.append((key, hostname))
        if kill_node and self._cluster is not None:
            # Delete the node UNDER the in-flight bind batch: the watch
            # event lands in the cache synchronously, so the remaining
            # staged binds of this node see it vanish mid-cycle.
            for node in self._cluster.list_objects("Node"):
                if node.name == hostname:
                    self._cluster.delete("Node", node)
                    break
        raise SimBindFailure(
            f"injected {'node-death' if doomed else 'bind'} failure: "
            f"{key} -> {hostname}"
        )
