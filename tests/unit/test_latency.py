"""Placement-latency ledger + decision-audit-log tests
(kube_batch_tpu/obs/latency.py, doc/design/observability.md §5):
arrival→bind stage stamping through the REAL cache/action pipeline,
gang last-member semantics, bind-failure and evict requeues restarting
the clock, ledger GC with the pod/job (the metrics-GC pattern — no
per-pod leak), explain verdicts carrying cycles-waited, the audit
ring's bounds + deterministic dump, and the HTTP surfaces."""

import json
import time
import urllib.request

import pytest

from kube_batch_tpu import metrics
from kube_batch_tpu.api import PodPhase, TaskStatus, build_resource_list
from kube_batch_tpu.cache import SchedulerCache
from kube_batch_tpu.framework import close_session, get_action, open_session
from kube_batch_tpu.obs import explain
from kube_batch_tpu.obs.latency import (
    AUDIT,
    LEDGER,
    AuditLog,
    PlacementLedger,
)
from kube_batch_tpu.utils.test_utils import (
    FakeBinder,
    FakeEvictor,
    FakeStatusUpdater,
    FakeVolumeBinder,
    build_node,
    build_pod,
    build_pod_group,
    build_queue,
)
from tests.actions.test_actions import make_tiers

TIERS_ARGS = (
    ["priority", "gang", "conformance"],
    ["drf", "predicates", "proportion", "nodeorder"],
)


class FakeClock:
    def __init__(self, t=0.0):
        self.t = float(t)

    def now(self):
        return self.t

    def tick(self, dt):
        self.t += dt


@pytest.fixture(autouse=True)
def _clean_state():
    LEDGER.reset()
    AUDIT.reset()
    explain.clear()
    yield
    LEDGER.reset()
    AUDIT.reset()
    LEDGER.configure(clock=time.monotonic)
    explain.clear()


def _ledger_with_clock():
    ledger = PlacementLedger()
    clock = FakeClock()
    ledger.configure(clock=clock.now)
    return ledger, clock


def _cache(**kwargs):
    return SchedulerCache(
        binder=kwargs.pop("binder", FakeBinder()),
        evictor=FakeEvictor(),
        status_updater=FakeStatusUpdater(),
        volume_binder=FakeVolumeBinder(),
        **kwargs,
    )


def _run_allocate_tpu(cache):
    ssn = open_session(cache, make_tiers(*TIERS_ARGS))
    action, _ = get_action("allocate_tpu")
    action.execute(ssn)
    return ssn


# -- ledger unit: stage math -------------------------------------------------


def test_pod_lifecycle_stage_decomposition():
    ledger, clock = _ledger_with_clock()
    ledger.note_arrival("u1", "t/p1", "t/job")
    clock.tick(5.0)
    ledger.note_placed(
        (("u1", "t/job"),), {"t/job": "q0"}, kind="periodic", solve_s=1.0
    )
    ledger.note_dispatched(("u1",))
    clock.tick(2.0)
    ledger.note_applied("u1")
    assert ledger.applied == 1
    assert ledger.entry_count() == 0  # entry dropped at applied
    stages = ledger.percentiles()["q0"]["periodic"]
    assert abs(stages["queue_wait"]["p50_s"] - 4.0) < 0.25
    assert abs(stages["solve"]["p50_s"] - 1.0) < 0.1
    assert abs(stages["bind"]["p50_s"] - 2.0) < 0.15
    assert abs(stages["total"]["p50_s"] - 7.0) < 0.4
    assert stages["dispatch"]["p50_s"] == 0.0


def test_micro_cycle_kind_keys_series():
    ledger, clock = _ledger_with_clock()
    ledger.note_arrival("u1", "t/p1", "t/job")
    clock.tick(1.0)
    ledger.note_placed((("u1", "t/job"),), {"t/job": "q0"}, kind="micro")
    ledger.note_dispatched(("u1",))
    ledger.note_applied("u1")
    assert "micro" in ledger.percentiles()["q0"]


def test_gang_latency_is_last_members_applied():
    ledger, clock = _ledger_with_clock()
    ledger.note_arrival("u1", "t/p1", "t/gang")
    clock.tick(1.0)
    ledger.note_arrival("u2", "t/p2", "t/gang")
    clock.tick(4.0)
    ledger.note_placed(
        (("u1", "t/gang"), ("u2", "t/gang")), {"t/gang": "q0"}
    )
    ledger.note_dispatched(("u1", "u2"))
    ledger.note_applied("u1")
    assert ledger.gang_samples == 0  # one member still pending
    clock.tick(4.0)
    ledger.note_applied("u2")
    assert ledger.gang_samples == 1
    gang = ledger.percentiles()["q0"]["periodic"]["gang_total"]
    # Last member applied at t=9, first arrival at t=0.
    assert abs(gang["p50_s"] - 9.0) < 0.5
    assert gang["count"] == 1
    # Per-member series kept alongside: two total samples.
    assert ledger.percentiles()["q0"]["periodic"]["total"]["count"] == 2


def test_bind_failure_restarts_clock():
    ledger, clock = _ledger_with_clock()
    ledger.note_arrival("u1", "t/p1", "t/job")
    clock.tick(3.0)
    ledger.note_placed((("u1", "t/job"),), {"t/job": "q0"})
    ledger.note_dispatched(("u1",))
    ledger.note_bind_failed("u1")
    assert ledger.bind_failures == 1 and ledger.requeues == 1
    clock.tick(7.0)
    ledger.note_placed((("u1", "t/job"),), {"t/job": "q0"})
    ledger.note_dispatched(("u1",))
    ledger.note_applied("u1")
    total = ledger.percentiles()["q0"]["periodic"]["total"]
    # Measured from the requeue (t=3), not the first arrival.
    assert abs(total["p50_s"] - 7.0) < 0.4


def test_ledger_gc_with_pod_and_job_no_leak():
    ledger, _clock = _ledger_with_clock()
    for j in range(4):
        for i in range(8):
            ledger.note_arrival(f"u{j}-{i}", f"t/p{j}-{i}", f"t/job{j}")
    assert ledger.entry_count() == 32
    ledger.forget_pod("u0-0")
    assert ledger.entry_count() == 31
    for j in range(4):
        ledger.forget_job(f"t/job{j}")
    assert ledger.entry_count() == 0
    assert ledger.job_wait_info("t/job0") is None


def test_sketch_merge_matches_direct_adds():
    """stage_percentiles merges per-key sketches via
    QuantileSketch.merge — merged quantiles must match a sketch that
    saw every value directly (DDSketch mergeability)."""
    from kube_batch_tpu.obs.telemetry import QuantileSketch

    direct = QuantileSketch()
    a, b = QuantileSketch(), QuantileSketch()
    for i in range(200):
        v = 0.001 * (i + 1)
        direct.add(v)
        (a if i % 2 else b).add(v)
    a.merge(b)
    for q in (0.5, 0.95, 0.99):
        assert a.quantile(q) == direct.quantile(q)
    assert a.count == direct.count


def test_requeue_recreated_entry_keeps_job_attribution():
    """An applied pod's entry is gone; a later evict re-creates it
    UNDER ITS JOB so the re-placement's gang accounting and per-queue
    series stay attributed (a job-less orphan would fall out of both)."""
    ledger, clock = _ledger_with_clock()
    ledger.note_arrival("u1", "t/p1", "t/gang")
    ledger.note_placed((("u1", "t/gang"),), {"t/gang": "q0"})
    ledger.note_dispatched(("u1",))
    ledger.note_applied("u1")
    assert ledger.entry_count() == 0
    clock.tick(2.0)
    ledger.note_requeued("u1", "evicted", job="t/gang")
    assert ledger.entry_count() == 1
    assert ledger.job_wait_info("t/gang") is not None
    clock.tick(3.0)
    ledger.note_placed((("u1", "t/gang"),), {"t/gang": "q0"})
    ledger.note_dispatched(("u1",))
    ledger.note_applied("u1")
    # Second wave closed under the job: a second gang-member sample
    # (and no orphan entry left behind).
    assert ledger.entry_count() == 0
    total = ledger.percentiles()["q0"]["periodic"]["total"]
    assert total["count"] == 2


def test_disabled_ledger_is_inert():
    ledger, _clock = _ledger_with_clock()
    ledger.configure(enabled=False)
    ledger.note_arrival("u1", "t/p1", "t/job")
    ledger.note_placed((("u1", "t/job"),), {})
    ledger.note_applied("u1")
    assert ledger.entry_count() == 0 and ledger.stamped == 0


# -- cache/action integration ------------------------------------------------


def _gang_cache(n=2, cpu="1000m"):
    cache = _cache()
    cache.add_queue(build_queue("default", weight=1))
    cache.add_node(build_node(
        "n1", build_resource_list(cpu="8", memory="16Gi", pods=110)
    ))
    cache.add_pod_group(build_pod_group(
        "g", namespace="t", min_member=n, queue="default"
    ))
    for i in range(n):
        cache.add_pod(build_pod(
            "t", f"p{i}", "", PodPhase.PENDING,
            build_resource_list(cpu=cpu, memory="1Gi"),
            group_name="g",
        ))
    return cache


def test_arrival_to_bind_through_real_pipeline():
    cache = _gang_cache()
    assert LEDGER.stamped == 2  # add_pod stamped both arrivals
    before = metrics.pod_placement_latency.count(
        ("total", "default", "periodic")
    )
    ssn = _run_allocate_tpu(cache)
    try:
        assert cache.wait_for_side_effects(timeout=30.0)
        assert LEDGER.applied == 2
        assert LEDGER.entry_count() == 0
        stages = LEDGER.percentiles()["default"]["periodic"]
        for stage in ("queue_wait", "solve", "dispatch", "bind",
                      "total", "gang_total"):
            assert stage in stages, stage
        assert LEDGER.gang_samples == 1
        # Prometheus histogram observed at the applied seam.
        after = metrics.pod_placement_latency.count(
            ("total", "default", "periodic")
        )
        assert after - before == 2
        # Audit: one placed record for the job.
        placed = [r for r in AUDIT.records() if r["action"] == "placed"]
        assert placed and placed[-1]["job"] == "t/g"
        assert placed[-1]["count"] == 2
    finally:
        close_session(ssn)
        cache.shutdown()


class FailingBinder:
    def bind(self, pod, hostname):
        raise RuntimeError("injected bind failure")


def test_bind_failure_requeues_through_cache():
    cache = _gang_cache()
    cache.binder = FailingBinder()
    ssn = _run_allocate_tpu(cache)
    try:
        assert cache.wait_for_side_effects(timeout=30.0)
        assert LEDGER.applied == 0
        assert LEDGER.bind_failures == 2
        assert LEDGER.entry_count() == 2  # entries survive, requeued
    finally:
        close_session(ssn)
        cache.shutdown()


def test_evict_restarts_clock_through_cache():
    cache = _gang_cache()
    ssn = _run_allocate_tpu(cache)
    try:
        assert cache.wait_for_side_effects(timeout=30.0)
        requeues_before = LEDGER.requeues
        job = cache.jobs["t/g"]
        task = next(iter(
            job.task_status_index[TaskStatus.BINDING].values()
        ))
        cache.evict(task, "test preemption")
        assert cache.wait_for_side_effects(timeout=30.0)
        assert LEDGER.requeues == requeues_before + 1
    finally:
        close_session(ssn)
        cache.shutdown()


def test_job_cleanup_gcs_ledger_entries():
    cache = _gang_cache()
    try:
        assert LEDGER.entry_count() == 2
        for i in range(2):
            cache.delete_pod(cache.jobs["t/g"].tasks[f"t-p{i}"].pod)
        assert LEDGER.entry_count() == 0
    finally:
        cache.shutdown()


# -- explain wiring ----------------------------------------------------------


def test_verdict_carries_cycles_waited():
    cache = _cache()
    cache.add_queue(build_queue("default", weight=1))
    cache.add_node(build_node(
        "n1", build_resource_list(cpu="8", memory="16Gi", pods=110),
        labels={"zone": "a"},
    ))
    cache.add_pod_group(build_pod_group(
        "blocked", namespace="t", min_member=1, queue="default"
    ))
    cache.add_pod(build_pod(
        "t", "b0", "", PodPhase.PENDING,
        build_resource_list(cpu="1000m", memory="1Gi"),
        group_name="blocked", selector={"zone": "nowhere"},
    ))
    ssn = _run_allocate_tpu(cache)
    close_session(ssn)
    # Churn a node so the second cycle actually SOLVES (an unchanged
    # cycle takes the warm no-op path, which re-derives no verdicts —
    # cycles_waited counts solving cycles by design).
    cache.add_node(build_node(
        "n2", build_resource_list(cpu="8", memory="16Gi", pods=110),
        labels={"zone": "b"},
    ))
    ssn = _run_allocate_tpu(cache)
    try:
        verdict = explain.get_verdict("t/blocked")
        assert verdict is not None
        assert verdict.detail["cycles_waited"] == 2
        assert "waiting_since" in verdict.detail
        assert "waiting_seconds" in verdict.detail
        # The diagnosis prose answers "how long and why" in one query.
        diag = explain.diagnose_job(ssn, ssn.jobs["t/blocked"])
        assert "waiting 2 solve cycle(s)" in explain.format_diagnosis(
            diag
        )
        # One unassigned audit record per touched cycle.
        unassigned = [
            r for r in AUDIT.records()
            if r["action"] == "unassigned" and r["job"] == "t/blocked"
        ]
        assert len(unassigned) == 2
        assert unassigned[-1]["reason"] == explain.REASON_PREDICATE
        assert unassigned[-1]["waited_cycles"] == 2
    finally:
        close_session(ssn)
        cache.shutdown()


# -- audit log ---------------------------------------------------------------


def test_audit_ring_bounds_and_deterministic_dump(tmp_path):
    audit = AuditLog(capacity=16)
    for i in range(40):
        audit.append({
            "action": "placed", "job": f"t/j{i}", "queue": "q",
            "count": 1,
        })
    meta = audit.meta()
    assert meta["records"] == 16
    assert meta["dropped"] == 24
    assert meta["seq"] == 40
    lines = audit.dump_lines()
    assert lines == audit.dump_lines()  # deterministic re-dump
    parsed = [json.loads(line) for line in lines]
    assert parsed[0]["seq"] == 25 and parsed[-1]["seq"] == 40
    path = audit.dump_jsonl(str(tmp_path / "audit.jsonl"))
    assert open(path).read().splitlines() == lines


def test_micro_defer_restamps_requeued_and_splits_stages():
    """A deferred micro cycle placed nothing: its arrival batch must be
    re-stamped ``requeued`` (reason ``micro-defer:<outcome>``) so the
    wait until the periodic pickup is attributed to the defer — the
    requeue RESTARTS the clock, and the eventual placement's total
    measures from the requeue, not the first arrival."""
    from kube_batch_tpu.scheduler import Scheduler
    from kube_batch_tpu.solver import warm

    cache = _cache()
    cache.add_queue(build_queue("q0", weight=1))
    cache.add_node(build_node(
        "n0", build_resource_list(cpu="8", memory="32Gi", pods=110),
    ))
    conf = (
        'actions: "allocate_tpu"\n'
        "tiers:\n"
        "- plugins:\n"
        "  - name: priority\n"
        "  - name: gang\n"
        "  - name: conformance\n"
        "- plugins:\n"
        "  - name: drf\n"
        "  - name: predicates\n"
        "  - name: proportion\n"
        "  - name: nodeorder\n"
    )
    sched = Scheduler(cache, scheduler_conf=conf)
    # After Scheduler init: the constructor installs its own clock.
    clock = FakeClock(10.0)
    LEDGER.configure(enabled=True, clock=clock.now)
    cache.add_pod_group(build_pod_group(
        "pg0", namespace="ns", min_member=1, queue="q0",
    ))
    cache.add_pod(build_pod(
        "ns", "pg0-p0", "", PodPhase.PENDING,
        build_resource_list(cpu="250m", memory="256Mi"),
        group_name="pg0",
    ))
    sched.run_once()
    assert cache.wait_for_side_effects(timeout=30.0)
    assert cache.wait_for_bookkeeping(timeout=30.0)
    # Void the warm state so the next micro cycle MUST defer (cold).
    warm.invalidate(cache)
    clock.tick(0.001)
    cache.add_pod_group(build_pod_group(
        "pgd", namespace="ns", min_member=1, queue="q0",
    ))
    cache.add_pod(build_pod(
        "ns", "pgd-p0", "", PodPhase.PENDING,
        build_resource_list(cpu="250m", memory="256Mi"),
        group_name="pgd",
    ))
    requeues_before = LEDGER.requeues
    assert sched.run_micro()
    entry = next(
        e for e in LEDGER._entries.values() if e.pod == "ns/pgd-p0"
    )
    assert entry.stage == "requeued"
    assert entry.requeues == 1
    assert entry.last_reason == "micro-defer:cold"
    assert LEDGER.requeues == requeues_before + 1
    # Periodic pickup 4 ms after the defer: total is measured from the
    # requeue stamp (0.004s), NOT the original arrival (0.005s).
    clock.tick(0.004)
    sched.run_once()
    assert cache.wait_for_side_effects(timeout=30.0)
    done = next(d for d in LEDGER._done if d["pod"] == "ns/pgd-p0")
    assert done["requeues"] == 1
    assert done["total_s"] == pytest.approx(0.004, abs=1e-6)
    cache.shutdown()


def test_audit_records_carry_no_wall_clock():
    """Replay byte-stability contract: nothing wall-clock-shaped in a
    record — only the ledger clock (vclock) and the cycle counter."""
    clock = FakeClock(7.0)
    LEDGER.configure(clock=clock.now)
    LEDGER.begin_cycle(3, kind="micro")
    AUDIT.append({"action": "placed", "job": "t/j", "queue": "q",
                  "count": 1})
    rec = AUDIT.records()[-1]
    assert rec["vclock"] == 7.0 and rec["cycle"] == 3
    assert rec["kind"] == "micro"
    assert "ts" not in rec and "t_start" not in rec


# -- HTTP surface ------------------------------------------------------------


def test_debug_latency_endpoint_and_vars():
    from kube_batch_tpu.cli import start_metrics_server

    cache = _gang_cache()
    ssn = _run_allocate_tpu(cache)
    assert cache.wait_for_side_effects(timeout=30.0)
    server, _thread = start_metrics_server("127.0.0.1:0")
    try:
        port = server.server_address[1]
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/debug/latency", timeout=5
        ) as resp:
            doc = json.loads(resp.read().decode())
        assert doc["type"] == "placement-latency"
        assert doc["applied"] == 2
        assert "default" in doc["percentiles"]
        assert doc["audit"]["records"] >= 1
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/debug/vars", timeout=5
        ) as resp:
            dvars = json.loads(resp.read().decode())
        assert dvars["latency"]["applied"] == 2
        assert "total" in dvars["latency"]["stage_p99_s"]
    finally:
        server.shutdown()
        close_session(ssn)
        cache.shutdown()
