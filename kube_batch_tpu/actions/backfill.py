"""Backfill action (reference actions/backfill/backfill.go:40-73): every
pending BestEffort task (empty resource request) goes to the first node
that passes predicates.

The reference leaves non-zero-request backfill and queue balancing as
TODOs (backfill.go:44, :67-69). tpu-batch implements both as the OPT-IN
``backfill_extended`` action (select it in the policy's ``actions``
list; plain ``backfill`` keeps strict reference parity): resourced
pending tasks — including those held back only by their queue's
deserved-share budget — may fill capacity nothing else can use.

Safety argument (the this-cycle guarantee): backfill runs AFTER
allocate, which runs to a fixed point — every task allocate WANTED to
place and could fit is placed. What remains pending yet placeable is
exactly what allocate's own shortcuts strand: chiefly members behind a
broken head-of-line task ("tasks are priority-ordered: if one fails,
the rest would too", allocate.go:144-148 — an assumption mixed-size
jobs violate), and tasks of overused queues. Consuming residual idle
for them cannot steal a this-cycle placement from anyone: a task that
did not fit node idle before a backfill still does not fit after idle
shrinks.

Letting an overused queue exceed its deserved share here is deliberate
use-it-or-lose-it balancing; the share is only borrowed — the moment
the deserving queue's demand becomes placeable, reclaim evicts down to
gang minAvailable floors (reclaim-action.md). Operators should prefer
elastic jobs (minMember < replicas) for backfill workloads, since
reclaim never breaches a gang's own floor.
"""

from __future__ import annotations

import logging

from ..api import TaskStatus
from ..framework import Action, register_action
from ..utils.scheduler_helper import FeasibilityMemo, get_node_list

logger = logging.getLogger(__name__)


class BackfillAction(Action):
    def __init__(self, extended: bool = False):
        self.extended = extended

    def name(self) -> str:
        return "backfill_extended" if self.extended else "backfill"

    def execute(self, ssn) -> None:
        # Cycle-scoped spec-keyed feasibility cache for the resourced
        # path (same throughput reasoning as reclaim's: a saturated
        # cluster can hold thousands of unplaceable pending tasks, and
        # they must not each pay a full predicate pass per cycle).
        memo = FeasibilityMemo(ssn) if self.extended else None
        for job in ssn.jobs.values():
            for task in list(
                job.task_status_index.get(TaskStatus.PENDING, {}).values()
            ):
                if not task.init_resreq.is_empty():
                    if self.extended:
                        self._backfill_resourced(ssn, task, memo)
                    # else reference parity: backfill only places tasks
                    # with an EMPTY resource request (BestEffort),
                    # backfill.go:45-49.
                    continue
                for node in get_node_list(ssn.nodes):
                    try:
                        ssn.predicate_fn(task, node)
                    except Exception:
                        continue
                    try:
                        ssn.allocate(task, node.name)
                    except Exception:
                        logger.exception(
                            "Failed to bind Task %s on %s", task.uid, node.name
                        )
                        continue
                    break

    @staticmethod
    def _backfill_resourced(ssn, task, memo: FeasibilityMemo) -> None:
        """Place one resourced pending task onto residual idle (see the
        module docstring's safety argument). First fit; gang gating
        still applies through ssn.allocate, so members of gangs that
        cannot reach minMember this cycle are held at the session layer
        and never dispatch."""
        for node in memo.feasible(task):
            if not task.init_resreq.less_equal(node.idle):
                continue
            try:
                ssn.allocate(task, node.name)
            except Exception:
                logger.exception(
                    "Failed to backfill Task %s on %s", task.uid, node.name
                )
                continue
            return


register_action(BackfillAction())
register_action(BackfillAction(extended=True))
