"""Warm-started steady cycles: carry the previous solve's verdicts.

A periodic cycle at steady state re-derives a conclusion it already
reached one period ago: every pending task it re-solves was left
unassigned by the previous cycle, against capacities that have only
SHRUNK since (the scheduler's own placements), budgets that have only
tightened, and feasibility that has not moved. CvxCluster (PAPERS.md)
gets its 100-1000x on granular allocation problems from exactly this
solution-reuse structure. This module is the state machine that decides,
per cycle, how much of the previous solve survives:

``noop``
    No job gained schedulable work since the previous solve and every
    delta precondition holds — the previous cycle's verdicts ARE this
    cycle's verdicts, bit-for-bit, and the solve/selection/apply phases
    are skipped entirely. Only the cache maintenance half of tensorize
    runs (``tensorize(warm_noop=True)``: node-array + predicate-column
    patching against the narrow ledger). Exactness argument: the solver
    runs rounds to a fixed point, and the cluster state at this snapshot
    IS the previous solve's fixed point (placements applied exactly the
    deltas the solve committed; nothing else moved, per the
    preconditions below) — re-running the rounds would accept nothing in
    round one and stop.

``solve``
    New work arrived (dirty jobs with pending tasks) and NO unassigned
    tasks were carried over — the problem contains exactly the new work,
    solved against the residual capacities already resident in the
    incremental tensorize / device caches. This is the steady
    placement-wave regime: cycle cost scales with churn.

fallback (full solve, labeled by reason)
    Any delta precondition failure re-solves everything from the ground
    truth — bit-parity with a cold scheduler is the invariant the
    randomized churn tests pin. Reasons:

    - ``cold`` / ``stale``: no warm state, or a snapshot generation gap
      (some cycle's ledger drained without a warm save);
    - ``node-dirty``: a third-party node event (death, watch update,
      eviction) — capacities may have GROWN, carried verdicts void;
    - ``releasing``: Releasing capacity exists — the pipeline epilogue
      may place carried tasks, outside the fixed-point argument;
    - ``carried-changed``: a carried job was mutated by anything other
      than the scheduler's own binds (completion, preempt, partial-gang
      revert), or its pending remainder drifted from the solve's;
    - ``deserved-changed``: a carried job's queue budget (proportion's
      water-filled deserved) moved — a previously budget-blocked task
      might now pass;
    - ``carried-interleave``: new work arrived WHILE unassigned tasks
      are carried. The subset problem would order/tie-break differently
      than the full problem (progressive-filling keys and bid-key
      hashes are rank-dependent), so bit-parity forces the full solve;
    - ``mesh-changed``: the solver's device layout token moved since
      the save (KBT_SPARSE_SHARD_MODE flip — the device set itself is
      process-constant — or a node->rack map move under two-level mode:
      the token carries the rack-permutation digest suffix): the flat
      sharded mode is bit-parity but the two-level mode is not, so
      carried verdicts conservatively void whenever the layout a solve
      would run under differs from the one that produced them;
    - ``drift``: the warm-noop tensorize found node rows dirty beyond
      the narrow ledger (a session-side mutation the plan could not
      see) — the cycle re-runs as a full solve.

The state lives on the SchedulerCache (``_warm_solve_state``), the same
lifetime pattern as the tensorize/device caches. ``plan_warm`` is
called by allocate_tpu before tensorize; ``save_warm_state`` after the
apply/verdict phases of every solving cycle (and ``advance_noop`` after
a no-op cycle).
"""

from __future__ import annotations

import logging
import os
from typing import Dict, List, Optional, Tuple

from ..api import TaskStatus

logger = logging.getLogger(__name__)


class WarmSolveState:
    """Carried verdicts of the most recent solve (see module doc)."""

    __slots__ = (
        "valid", "snap_gen", "carried", "queue_deserved", "has_releasing",
        "mesh_token",
    )

    def __init__(self):
        self.valid = False
        self.snap_gen = -1
        # Solver device-layout token at save time
        # (sharding.prospective_layout_token); None until a sharded
        # dispatch has pinned the device count.
        self.mesh_token = None
        # job uid -> (job clone object, clone _ver at save, pending
        # remainder at save). Identity+ver pins "untouched"; a
        # narrow-dirty re-clone passes iff its pending count still
        # equals the remainder (a bind-bookkeeping revert would grow
        # it, and a reverted task must be re-solved).
        self.carried: Dict[str, tuple] = {}
        # queue uid -> deserved Resource clone (None when no budget
        # plugin had an opinion) for every queue owning carried jobs.
        self.queue_deserved: Dict[str, object] = {}
        self.has_releasing = True  # conservative until first save


def warm_state_of(cache) -> Optional[WarmSolveState]:
    if cache is None:
        return None
    ws = getattr(cache, "_warm_solve_state", None)
    if ws is None:
        ws = WarmSolveState()
        try:
            cache._warm_solve_state = ws
        except Exception:  # slots-only stand-in cache
            return None
    return ws


def warm_enabled() -> bool:
    return os.environ.get("KBT_WARM", "1") != "0"


def _layout_token():
    """The solver device-layout token a solve dispatched now would run
    under (None before any sharded dispatch — see
    sharding.prospective_layout_token; never probes the backend, so
    the native-route and pre-init paths stay hang-safe)."""
    from . import sharding

    return sharding.prospective_layout_token()


def _res_eq(a, b) -> bool:
    """Exact Resource equality (Resource.__eq__); None-tolerant."""
    if a is None or b is None:
        return a is None and b is None
    return a == b


def _deserved_of(ssn, queue) -> Optional[object]:
    """The queue's deserved budget (first plugin with an opinion wins —
    the same resolution tensorize uses for its budget vectors)."""
    for fn in ssn.queue_budget_fns.values():
        budget = fn(queue)
        if budget is not None:
            return budget[0]
    return None


def plan_warm(ssn) -> Tuple[str, List]:
    """Classify this cycle against the warm state. Returns
    ``(outcome, live_jobs)``: outcome ``noop``/``solve`` when the warm
    path engages, else the fallback reason; ``live_jobs`` is the set of
    jobs with new schedulable work (empty for noop and for fallbacks,
    where the full solve covers everything anyway)."""
    if not warm_enabled():
        return "disabled", []
    ws = warm_state_of(ssn.cache)
    if ws is None or not ws.valid:
        return "cold", []
    if getattr(ssn, "snap_gen", 0) != ws.snap_gen + 1:
        return "stale", []
    cur_token = _layout_token()
    if (
        cur_token is not None
        and ws.mesh_token is not None
        and cur_token != ws.mesh_token
    ):
        # The solver's device layout moved under the carried verdicts
        # (mode flip; device count is process-constant): conservatively
        # re-solve — the two-level mode is not bit-parity.
        return "mesh-changed", []
    if ssn.dirty_nodes:
        return "node-dirty", []
    if ws.has_releasing:
        return "releasing", []

    pending_key = TaskStatus.PENDING
    carried = ws.carried
    live: List = []
    seen = set()
    for uid in ssn.dirty_jobs:
        job = ssn.jobs.get(uid)
        if job is not None and job.task_status_index.get(pending_key):
            live.append(job)
            seen.add(uid)

    narrow = ssn.dirty_jobs_narrow
    for uid, (obj, ver, remainder) in carried.items():
        if uid in seen:
            # Full-dirty carried job: its re-solve is part of the live
            # set; the carried verdict is simply superseded.
            continue
        job = ssn.jobs.get(uid)
        if job is None:
            return "carried-changed", []
        if job is obj and job._ver == ver:
            continue
        if (
            uid in narrow
            and len(job.task_status_index.get(pending_key) or ()) == remainder
        ):
            # Bind-only churn with the exact unassigned remainder left
            # pending: the job is in precisely the state the previous
            # solve ended in.
            continue
        return "carried-changed", []

    # A narrow-dirty job that is NOT carried but has pending tasks means
    # a bind-bookkeeping revert put an assigned task back — re-solve it.
    for uid in narrow:
        if uid in carried or uid in seen:
            continue
        job = ssn.jobs.get(uid)
        if job is not None and job.task_status_index.get(pending_key):
            live.append(job)
            seen.add(uid)

    if carried:
        quids = {obj.queue for (obj, _v, _r) in carried.values()}
        # Sorted: the budget re-check must walk queues in a replay-
        # stable order (kbtlint replay-determinism).
        for quid in sorted(quids):
            queue = ssn.queues.get(quid)
            cur = _deserved_of(ssn, queue) if queue is not None else None
            if not _res_eq(cur, ws.queue_deserved.get(quid)):
                return "deserved-changed", []

    if not live:
        return "noop", []
    if carried:
        # Carried unassigned tasks would interleave with the new work:
        # subset ordering/tie-breaking diverges from the full problem,
        # so bit-parity demands the full solve.
        return "carried-interleave", live
    return "solve", live


def advance_noop(ssn) -> None:
    """A no-op cycle consumed one snapshot generation; keep continuity.
    Carried entries that passed the plan via the NARROW remainder check
    (a bind re-minted the job's clone) are re-pinned to the current
    clone — otherwise the very next cycle's identity check would fail
    against the drained ledger and force a spurious carried-changed
    full solve after every partial placement wave."""
    ws = warm_state_of(ssn.cache)
    if ws is None:
        return
    ws.snap_gen = getattr(ssn, "snap_gen", 0)
    ws.mesh_token = _layout_token()
    for uid, (obj, ver, remainder) in list(ws.carried.items()):
        job = ssn.jobs.get(uid)
        if job is not None and (job is not obj or job._ver != ver):
            ws.carried[uid] = (job, job._ver, remainder)


def invalidate(cache) -> None:
    ws = getattr(cache, "_warm_solve_state", None)
    if ws is not None:
        ws.valid = False


def save_warm_state(ssn, ctx, assigned) -> int:
    """Record this solve's carried verdicts (called post-apply). With
    ``ctx is None`` (an idle cycle: nothing pending) the carried set is
    empty — the strongest warm state there is. Returns the carried job
    count (stats)."""
    ws = warm_state_of(ssn.cache)
    if ws is None:
        return 0
    carried: Dict[str, tuple] = {}
    has_releasing = True
    if ctx is None:
        # Idle: no pending tasks at all. Releasing presence from the
        # tensorize cache's freshly absorbed columns.
        tc = getattr(ssn.cache, "_tensorize_cache", None)
        if tc is not None and tc.releasing is not None and len(
            getattr(tc, "node_objs", None) or ()
        ) == len(ssn.nodes):
            has_releasing = bool(tc.releasing.any())
    else:
        import numpy as np

        has_releasing = bool(ctx.has_releasing)
        T = len(ctx.tasks)
        a = np.asarray(assigned[:T])
        for i in np.nonzero(a < 0)[0].tolist():
            task = ctx.tasks[i]
            if task.job in carried:
                continue
            job = ssn.jobs.get(task.job)
            if job is None:
                continue
            carried[task.job] = (
                job, job._ver,
                len(job.task_status_index.get(TaskStatus.PENDING) or ()),
            )
    deserved: Dict[str, object] = {}
    for uid, (job, _v, _r) in carried.items():
        quid = job.queue
        if quid in deserved:
            continue
        queue = ssn.queues.get(quid)
        d = _deserved_of(ssn, queue) if queue is not None else None
        deserved[quid] = d.clone() if d is not None else None
    ws.carried = carried
    ws.queue_deserved = deserved
    ws.has_releasing = has_releasing
    ws.snap_gen = getattr(ssn, "snap_gen", 0)
    ws.mesh_token = _layout_token()
    ws.valid = True
    return len(carried)
