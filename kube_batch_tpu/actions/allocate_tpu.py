"""allocate_tpu: the batched TPU drop-in for the allocate action.

The BASELINE.json north star: a new action, selectable in the scheduler
policy exactly where ``allocate`` goes, that snapshots the session into
dense tensors, runs the JAX assignment kernel once, and drives the stock
``ssn.allocate`` path with the result — so gang gating, event handlers
(DRF/proportion share updates), dispatch-on-JobReady, and bind side effects
all behave exactly as in the greedy path (framework/session.go:237-289).

Semantics vs the greedy `allocate` action:
- identical predicate + resource-fit + epsilon rules (in-kernel);
- identical scorer formulas (LeastRequested/Balanced recomputed against
  the evolving idle state, static affinity scores precomputed);
- queue fair-share budgets enforced per solver round instead of per task;
- assignments are applied host-side in global priority order, so session
  bookkeeping matches what the greedy loop would produce for the same
  assignment set.

Pipelining onto Releasing resources (allocate.go:175-181) is handled in a
host-side epilogue for tasks the kernel left unassigned.
"""

from __future__ import annotations

import logging
import os
import time

import numpy as np

from .. import metrics
from ..api import Resource
from ..framework import Action, register_action
from ..obs import RECORDER, span
from ..obs.tracer import TRACER
from ..solver import solve_sharded, tensorize
from ..utils.lockdebug import wrap_lock
from ..utils.scheduler_helper import prioritize_nodes, select_best_node

logger = logging.getLogger(__name__)

# Phase timings of the most recent execute(), read by the bench harness
# (bench.py:bench_cycle). The same phases feed /metrics via
# metrics.update_solver_phase — BASELINE.md's <100 ms target is for the
# WHOLE cycle, not the kernel, so the budget split must be observable.
# Single-threaded by construction: one scheduler loop mutates it, bench
# reads it between cycles.
last_stats: dict = {}


def _record_phase(phase: str, ms: float) -> None:
    last_stats[phase + "_ms"] = ms
    metrics.update_solver_phase(phase, ms / 1e3)


def _use_native_solver() -> bool:
    """Route the solve to native/greedy.cpp when no accelerator exists.

    The batched auction solver is built for the MXU; on a CPU-only host it
    is slower than a compiled sequential loop (round-1 bench: 7.5x slower
    than native/greedy.cpp at 50k x 5k), so the production fallback is the
    native feasibility-aware loop (greedy_allocate_masked) consuming the
    same factorized snapshot. KBT_SOLVER=jax|native overrides the
    dispatch (tests pin =jax to exercise the kernel on the virtual CPU
    mesh)."""
    forced = os.environ.get("KBT_SOLVER", "").lower()
    if forced == "native":
        return True
    if forced == "jax":
        return False
    # Guarded backend access: a cold in-process jax.devices() with a
    # wedged tunnel plugin registered hangs forever — the scheduling
    # loop must never take that risk (probe happens in a bounded
    # subprocess at most once per process; wedged → CPU + native).
    from ..utils.backend import ensure_live_backend

    if ensure_live_backend() == 0:
        return True
    import jax

    if jax.devices()[0].platform != "cpu":
        return False
    try:
        from ..native import native_available

        return native_available()
    except Exception:
        return False


class _AbandonableWorker:
    """One persistent single-slot executor that can be ABANDONED when
    its occupant blows a deadline: the slot is wedged inside a foreign
    blocking call (greedy.cpp via ctypes, or an XLA device→host sync)
    that cannot be cancelled, so :meth:`abandon` detaches the pool (no
    wait — the thread dies whenever the call returns, its result
    unread) and the next submit lazily builds a fresh slot instead of
    queueing behind the hang forever. A persistent worker, not a
    thread per call: the block point is on the steady-cycle hot path
    with a ~1% overhead budget."""

    def __init__(self, name: str):
        self._name = name
        self._pool = None
        # Per-instance identity: the native-solve and device-sync
        # workers are distinct locks and must not alias in the
        # KBT_LOCK_DEBUG order harness.
        self._lock = wrap_lock(f"action.worker.{name}")

    def submit(self, fn):
        with self._lock:
            if self._pool is None:
                from concurrent.futures import ThreadPoolExecutor

                self._pool = ThreadPoolExecutor(
                    max_workers=1, thread_name_prefix=self._name
                )
            return self._pool.submit(fn)

    def abandon(self):
        with self._lock:
            pool, self._pool = self._pool, None
        if pool is not None:
            pool.shutdown(wait=False)


# Single worker for the native in-flight solve: the ctypes call into
# greedy.cpp releases the GIL, so the scheduler thread's host work
# genuinely overlaps the C++ rounds. One scheduler loop → one slot.
_NATIVE_WORKER = _AbandonableWorker("kbt-native-solve")

# Deadline-bounded device→host syncs, same single-slot contract.
_DEVICE_SYNC_WORKER = _AbandonableWorker("kbt-device-sync")


class AsyncSolveHandle:
    """One in-flight batched solve with a SINGLE block point.

    - jax backends: the jitted solve returns device futures immediately
      (XLA async dispatch); :meth:`fetch` performs the one
      device→host sync, on the assignment vector only.
    - native backend: greedy.cpp runs on a worker thread (ctypes
      releases the GIL for the foreign call), same fetch contract.

    The session registers the handle at launch
    (``Session.register_inflight_solve``) so ``Statement``
    commit/discard and session close DRAIN it before touching the world
    the solve snapshotted — commit/discard semantics are unchanged: no
    transaction boundary can run concurrently with an outstanding
    solve. ``fetch`` memoizes BOTH outcomes: the result, and — fault
    containment — the failure, so a second fetch of a failed handle
    re-raises a typed :class:`~..solver.containment.SolveFailed`
    instead of hitting a consumed future.

    ``fetch(timeout=...)`` is the solve deadline: on expiry the handle
    is ABANDONED — the future/device result is detached, a late arrival
    is discarded, and :class:`SolveTimeout` is raised (and memoized) so
    the caller's degradation ladder re-solves on a lower rung.
    """

    __slots__ = (
        "backend", "rounds", "refills", "stages", "reconcile_rounds",
        "native_stats",
        "_future", "_result", "_assigned", "_error", "_fault_hook",
    )

    def __init__(self, backend: str):
        self.backend = backend
        self.rounds = 0
        # Sparse-solve forensics, populated by fetch(): jax path reports
        # SolverResult.refills/stages (None on a dense solve) and
        # reconcile_rounds (sharded sparse only), native path snapshots
        # native.greedy.last_solve_stats.
        self.refills = None
        self.stages = None
        self.reconcile_rounds = None
        self.native_stats = None
        self._future = None
        self._result = None
        self._assigned = None
        self._error = None
        self._fault_hook = None

    @classmethod
    def launch(cls, inputs, use_native: bool, max_rounds: int,
               fault_hook=None, allow_pallas: bool = True,
               ) -> "AsyncSolveHandle":
        if use_native:
            handle = cls("native")
            from ..native import solve_native

            # Worker-thread span adopted under the launching span: the
            # exported trace shows the C++ rounds as a concurrent track
            # nested under this cycle.
            parent = TRACER.capture()

            def traced_solve():
                with TRACER.adopt(parent), span("native_solve"):
                    return solve_native(inputs)

            handle._future = _NATIVE_WORKER.submit(traced_solve)
            return handle
        import jax

        handle = cls(f"jax-{jax.devices()[0].platform}")
        # Sim chaos seam (containment.device_fault_hook): consulted in
        # the fetch-side materialization, where a raise/hang lands
        # exactly where a real device fault would.
        handle._fault_hook = fault_hook
        # solve_sharded shards the node axis over all visible devices
        # (the multi-chip scale path) and falls back to the cached
        # single-device jit when only one device exists. The call
        # returns the moment dispatch completes.
        handle._result = solve_sharded(
            inputs, max_rounds=max_rounds, allow_pallas=allow_pallas
        )
        return handle

    def done(self) -> bool:
        """Non-blocking completion poll (best-effort on jax backends
        that do not expose buffer readiness)."""
        if self._assigned is not None or self._error is not None:
            return True
        if self._future is not None:
            return self._future.done()
        try:
            return bool(self._result.assigned.is_ready())
        except AttributeError:  # pragma: no cover - older jax
            return True

    def _fetch_native(self, timeout):
        from ..solver.containment import SolveTimeout

        if timeout is None:
            assigned, _ = self._future.result()
        else:
            from concurrent.futures import TimeoutError as FutTimeout

            try:
                assigned, _ = self._future.result(timeout=timeout)
            except FutTimeout as exc:
                # The worker slot is stuck in a foreign call; give the
                # next native solve a fresh executor and abandon this
                # future (its late result is never read).
                _NATIVE_WORKER.abandon()
                raise SolveTimeout(
                    f"native solve exceeded its {timeout:.3f}s budget; "
                    f"worker abandoned"
                ) from exc
        self._assigned = np.asarray(assigned)
        self.rounds = 1
        from ..native.greedy import last_solve_stats

        self.native_stats = dict(last_solve_stats)

    def _fetch_jax(self, timeout):
        from ..solver.containment import SolveTimeout

        result, hook = self._result, self._fault_hook

        def materialize():
            if hook is not None:
                hook("solve")
            return np.asarray(result.assigned)

        if timeout is None:
            self._assigned = materialize()
        else:
            # Deadline-bounded device→host sync on the persistent
            # single-worker executor (not a thread per cycle — this is
            # the steady-cycle hot path): a hung XLA solve is abandoned
            # at the budget (SolveTimeout) with its worker slot, its
            # late result discarded unread.
            from concurrent.futures import TimeoutError as FutTimeout

            fut = _DEVICE_SYNC_WORKER.submit(materialize)
            try:
                self._assigned = fut.result(timeout=timeout)
            except FutTimeout as exc:
                _DEVICE_SYNC_WORKER.abandon()
                raise SolveTimeout(
                    f"{self.backend} solve exceeded its {timeout:.3f}s "
                    f"budget; abandoned (late result will be discarded)"
                ) from exc
        self.rounds = int(result.rounds)
        if result.refills is not None:
            self.refills = int(result.refills)
        if result.stages is not None:
            self.stages = int(result.stages)
        rr = getattr(result, "reconcile_rounds", None)
        if rr is not None:
            self.reconcile_rounds = int(rr)

    def fetch(self, timeout=None) -> np.ndarray:
        """The block point: the assignment vector as a host array.
        Memoized both ways — a second fetch of a completed handle is
        free, a second fetch of a FAILED handle re-raises the memoized
        failure as ``SolveFailed`` (never a consumed-future error)."""
        from ..solver.containment import SolveFailed

        if self._assigned is not None:
            return self._assigned
        if self._error is not None:
            raise SolveFailed(
                f"{self.backend} solve already failed: {self._error!r}"
            ) from self._error
        try:
            if self._future is not None:
                self._fetch_native(timeout)
            else:
                self._fetch_jax(timeout)
        except BaseException as exc:
            self._error = exc
            # Detach: the failed future/device result is dead to us;
            # anything arriving late is discarded with these refs.
            self._future = None
            self._result = None
            if not isinstance(exc, Exception):
                # KeyboardInterrupt/SystemExit must terminate, not be
                # rewrapped into the degradation ladder's Exception
                # handling (a Ctrl-C at the block point would otherwise
                # be absorbed as a "device failure" and the loop would
                # keep running).
                raise
            if isinstance(exc, SolveFailed):
                raise
            raise SolveFailed(
                f"{self.backend} solve failed: {exc}"
            ) from exc
        return self._assigned

    def failed(self) -> bool:
        return self._error is not None

    def drain(self) -> None:
        """Guard-path fetch: block until the solve is out of flight,
        swallowing errors (the caller is tearing down or about to
        mutate state; a failed solve must not mask that path). Deadline
        -bounded like the action's own fetch — a hung solve must not
        wedge a transaction boundary or session close either."""
        from ..solver import containment

        try:
            self.fetch(timeout=containment.solve_budget())
        except Exception:  # pragma: no cover - defensive
            logger.exception("in-flight solve drain failed")


def _restamp_deferred(ssn, outcome: str) -> None:
    """A deferred micro cycle placed NOTHING: re-stamp the arrival
    batch's pending pods as ``requeued`` in the placement-latency
    ledger, so the wait they accrue until the periodic cycle picks them
    up is attributed to the defer (requeue counter + restarted clock)
    instead of silently absorbed into ``queue_wait``."""
    from ..api import TaskStatus
    from ..obs import latency as latency_mod

    if not latency_mod.LEDGER.enabled:
        return
    try:
        pending_key = TaskStatus.PENDING
        for uid in ssn.dirty_jobs:
            job = ssn.jobs.get(uid)
            if job is None:
                continue
            for t in (
                job.task_status_index.get(pending_key) or {}
            ).values():
                latency_mod.LEDGER.note_requeued(
                    t.uid, f"micro-defer:{outcome}", job=uid
                )
    except Exception:  # pragma: no cover - metrics must never kill
        logger.exception("micro-defer requeue restamp failed")


class AllocateTpuAction(Action):
    # Eligible for the scheduler's event-driven micro cycles
    # (Scheduler.run_micro): in micro mode the action places only
    # through the warm-start plan and defers otherwise.
    micro_capable = True

    def __init__(self, max_rounds: int = 256):
        self.max_rounds = max_rounds

    def name(self) -> str:
        return "allocate_tpu"

    # -- fault-containment ladder -------------------------------------------

    def _launch_rung(self, rung: str, inputs, ctx) -> AsyncSolveHandle:
        """One rung's dispatch. ``native`` consumes the host-side
        :class:`SolverInputs` that every tensorize (device or not)
        leaves on the context — the floor must never touch a device
        that just failed, not even to read the fallback bundle."""
        from ..solver import containment

        if rung == "native":
            return AsyncSolveHandle.launch(
                ctx.host_inputs, True, self.max_rounds
            )
        return AsyncSolveHandle.launch(
            inputs, False, self.max_rounds,
            fault_hook=containment.device_fault_hook(),
            # The pallas bid pass hashes ROW POSITIONS; a warm subset
            # bundle carries non-contiguous global ranks, so it must
            # stay on the jnp kernels for tie-hash bit-parity.
            allow_pallas=getattr(ctx, "subset_jobs", None) is None,
        )

    def _solve_ladder(self, ssn, rungs, inputs, ctx, handle, budget,
                      ladder):
        """Fetch with degradation: any failure in a device rung re-solves
        the SAME cycle on the next rung down (sparse → dense → native);
        a deadline expiry jumps straight to the native floor (the device
        is wedged — a dense re-dispatch would just burn another budget)
        and quarantines the backend via the breaker. Returns
        ``(assigned, final_handle)``; raises ``SolveFailed`` only when
        the native floor itself fails (the guarded loop absorbs it).

        ``ladder`` accumulates one record per attempt — the flight
        record / verdict / bench attribution of which rungs ran."""
        from ..solver import containment as _containment
        from ..solver.containment import (
            BREAKER,
            SolveFailed,
            SolveTimeout,
            note_fallback,
            strip_candidates,
        )
        from ..solver.validate import validate_placements

        idx = 0
        cur_inputs = inputs
        while True:
            rung = rungs[idx]
            try:
                if handle is None:
                    handle = self._launch_rung(rung, cur_inputs, ctx)
                    ssn.register_inflight_solve(handle)
                assigned = handle.fetch(timeout=budget)
            except Exception as exc:
                ssn.register_inflight_solve(None)
                handle = None
                timed_out = isinstance(exc, SolveTimeout)
                reason = "timeout" if timed_out else "exception"
                exc_name = type(exc.__cause__ or exc).__name__
                ladder.append({
                    "rung": rung, "outcome": reason, "exc": exc_name,
                })
                if rung == "native":
                    # The floor failed: nothing below it — surface the
                    # typed failure to the guarded cycle loop.
                    if isinstance(exc, SolveFailed):
                        raise
                    raise SolveFailed(
                        f"native floor solve failed: {exc}"
                    ) from exc
                BREAKER.record_device_failure(
                    reason, exc=exc_name, open_now=timed_out
                )
                nxt = "native" if timed_out else rungs[idx + 1]
                idx = rungs.index(nxt)
                metrics.register_solver_fallback(rung, nxt, reason)
                note_fallback(rung, nxt, reason, exc=exc_name)
                logger.error(
                    "solve rung %r failed (%s: %s); re-solving this "
                    "cycle on %r", rung, reason, exc_name, nxt,
                )
                if nxt == "dense":
                    cur_inputs = strip_candidates(cur_inputs)
                continue
            # --- post-solve placement validation ----------------------
            # The last gate before the result can reach bind dispatch:
            # recheck every proposed placement against the feasibility
            # mask + a capacity recount, O(placements) host-side. A
            # device rung is additionally exposed to the sim's
            # solver-corrupt tamper seam here — exactly where a silent
            # device miscompute would land.
            if rung != "native":
                assigned = _containment.apply_result_tamper(assigned)
            bad, vreasons = validate_placements(ctx, assigned)
            if bad.size:
                for reason in sorted(vreasons):
                    metrics.register_solver_output_rejected(
                        reason, vreasons[reason]
                    )
                if rung != "native":
                    # Corrupted device output: same containment as a
                    # rung exception — feed the breaker's failure
                    # streak and re-solve this cycle ONE rung down.
                    ssn.register_inflight_solve(None)
                    handle = None
                    ladder.append({
                        "rung": rung, "outcome": "rejected",
                        "rejected": int(bad.size),
                        "reasons": dict(sorted(vreasons.items())),
                    })
                    BREAKER.record_device_failure(
                        "rejected", exc="ValidationRejected"
                    )
                    nxt = rungs[idx + 1]
                    idx = rungs.index(nxt)
                    metrics.register_solver_fallback(
                        rung, nxt, "rejected"
                    )
                    note_fallback(
                        rung, nxt, "rejected", exc="ValidationRejected"
                    )
                    logger.error(
                        "solve rung %r output failed post-solve "
                        "validation (%s; %d placement(s)); re-solving "
                        "this cycle on %r", rung, vreasons,
                        int(bad.size), nxt,
                    )
                    if nxt == "dense":
                        cur_inputs = strip_candidates(cur_inputs)
                    continue
                # Native floor: nothing below it — DROP the offending
                # placements (they never reach bind dispatch) and keep
                # the rest of the cycle's work.
                assigned = np.array(assigned, copy=True)
                assigned[bad] = -1
                ladder.append({
                    "rung": rung, "outcome": "rejected-dropped",
                    "rejected": int(bad.size),
                    "reasons": dict(sorted(vreasons.items())),
                })
                logger.error(
                    "native-floor output failed post-solve validation "
                    "(%s); dropped %d placement(s) before dispatch",
                    vreasons, int(bad.size),
                )
            if rung != "native" and not ladder:
                # Only a CLEAN device cycle resets the failure streak.
                # A cycle rescued by a lower device rung (sparse failed,
                # dense solved) still had a device-path failure — if
                # dense kept resetting the streak, a persistently broken
                # sparse program would burn a failed dispatch every
                # cycle forever without ever reaching the breaker
                # threshold.
                BREAKER.record_device_success()
            ladder.append({"rung": rung, "outcome": "ok"})
            return assigned, handle

    @staticmethod
    def _releasing_candidates(ssn, ctx):
        """Nodes that actually hold Releasing capacity (the only ones
        the pipeline epilogue can use). In the common no-eviction cycle
        this is empty and the O(leftovers x nodes) epilogue pass is
        skipped. Candidates are narrowed with one numpy pass over the
        snapshot's releasing matrix (releasing only accumulates task
        resreqs, whose dims are always in the layout, so a non-empty
        releasing always has a nonzero row) — the per-node Python walk
        cost ~10 ms at 5k nodes on every cycle, releasing or not.
        Assignment-independent, so it runs in the solve's overlap
        window."""
        if not ctx.has_releasing:
            return []
        rel_rows = np.asarray(
            ctx.host_inputs.node_releasing[: len(ctx.nodes)]
        )
        return [
            (j, ssn.nodes[ctx.nodes[j].name])
            for j in np.nonzero(rel_rows.any(axis=1))[0].tolist()
            if not ssn.nodes[ctx.nodes[j].name].releasing.is_empty()
        ]

    def execute(self, ssn) -> None:
        # Clear BEFORE tensorize: if it raises, readers (bench cycle
        # block, metrics) must see an empty dict, not the previous
        # cycle's timings attributed to the failed cycle.
        last_stats.clear()
        # Backend decision BEFORE tensorize: the native CPU path consumes
        # the host NumPy arrays directly (device=False), skipping the
        # host→device pack and the per-field eager slices of unpack() —
        # together ~180 ms of the 50k delta cycle (r4/r5 profiles) spent
        # shuttling data through JAX for a solve that runs in C++.
        use_native = _use_native_solver()
        # Circuit-breaker gate (solver/containment.py), also before
        # tensorize: an OPEN breaker pins the cycle to the native floor
        # without touching the quarantined device at all — no device
        # pack, no dispatch, no per-cycle failure latency. allow_device
        # ticks the cooldown and, at expiry, runs the bounded canary
        # probe (success re-promotes this very cycle).
        from ..solver import containment

        breaker_pinned = False
        if not use_native and not containment.BREAKER.allow_device():
            use_native = True
            breaker_pinned = True
            last_stats["breaker_pinned"] = True

        # --- warm-start plan (solver/warm.py) -------------------------
        # Decide how much of the previous cycle's solve survives BEFORE
        # tensorize: a ``noop`` outcome skips the task side, selection,
        # solve, and apply outright (the previous verdicts are this
        # cycle's verdicts, bit-for-bit); ``solve`` means the problem is
        # exactly the new work against residual capacities; any other
        # outcome is a labeled full-solve fallback.
        from ..solver import warm as warm_mod

        micro = bool(getattr(ssn, "micro_cycle", False))
        warm_outcome, warm_live = warm_mod.plan_warm(ssn)
        last_stats["warm_outcome"] = warm_outcome
        if micro and warm_outcome not in ("noop", "solve", "subset"):
            # Micro cycles place ONLY through the warm path: a plan
            # fallback means a full solve, which belongs to the
            # periodic cycle (the fairness/preempt authority). Place
            # nothing and defer.
            last_stats["micro_deferred"] = warm_outcome
            metrics.register_warm_start(warm_outcome)
            metrics.register_micro_cycle("deferred")
            warm_mod.note_deferred(ssn)
            _restamp_deferred(ssn, warm_outcome)
            return
        if warm_outcome == "noop":
            t0 = time.perf_counter()
            with span("tensorize"):
                tensorize(ssn, warm_noop=True)
            _record_phase("tensorize", (time.perf_counter() - t0) * 1e3)
            from ..solver.snapshot import last_tensorize_stats

            ts = dict(last_tensorize_stats)
            drift = ts.get("incremental") is False or (
                ts.get("dirty_nodes", 0) != ts.get("wave_patched", 0)
            )
            for k, v in ts.items():
                last_stats[f"tensorize_{k}"] = v
            if not drift:
                warm_mod.advance_noop(ssn)
                metrics.register_warm_start("noop")
                if micro:
                    metrics.register_micro_cycle("noop")
                try:
                    from ..obs import explain

                    explain.record_idle_cycle(ssn)
                except Exception:  # pragma: no cover - forensics only
                    logger.exception("idle-cycle verdict GC failed")
                RECORDER.annotate("solver", {
                    "warm": "noop",
                    "tensorize_wave_patched": ts.get("wave_patched"),
                })
                return
            # Node rows moved beyond the narrow ledger: a session-side
            # mutation the plan could not see. Void the carried state
            # and fall through to the full solve (the arrays are clean
            # now; the re-tensorize below is cheap). In a MICRO cycle
            # the fallthrough is not allowed — same contract as the
            # plan-time fallbacks above: place nothing, defer the full
            # solve to the periodic cycle.
            warm_outcome = "drift"
            last_stats["warm_outcome"] = warm_outcome
            warm_mod.invalidate(ssn.cache)
            if micro:
                last_stats["micro_deferred"] = warm_outcome
                metrics.register_warm_start(warm_outcome)
                metrics.register_micro_cycle("deferred")
                _restamp_deferred(ssn, warm_outcome)
                return
        metrics.register_warm_start(warm_outcome)

        tensorize_kw = {}
        if warm_outcome == "subset":
            # Rank-stable subset bundle (solver/warm.py): the new work
            # plus a bounded rotating drain batch of carried jobs, with
            # GLOBAL ranks computed over the full pending pool so the
            # solve is bit-equal to the full problem restricted to
            # these rows.
            sub = warm_mod.subset_jobs(ssn, warm_live)
            last_stats["warm_subset_jobs"] = len(sub)
            tensorize_kw = dict(
                include_jobs=sub, rank_pool=list(ssn.jobs.values()),
            )
        t0 = time.perf_counter()
        with span("tensorize"):
            try:
                inputs, ctx = tensorize(
                    ssn, device=not use_native, **tensorize_kw
                )
            except Exception as exc:
                if use_native:
                    raise
                # Device pack failed (dead backend, OOM during the
                # host→device upload): same containment as a dispatch
                # failure — quarantine via the breaker and rebuild
                # host-side for the native floor.
                exc_name = type(exc).__name__
                containment.BREAKER.record_device_failure(
                    "exception", exc=exc_name
                )
                metrics.register_solver_fallback(
                    "device", "native", "tensorize"
                )
                containment.note_fallback(
                    "device", "native", "tensorize", exc=exc_name
                )
                logger.error(
                    "device tensorize failed (%s); re-packing "
                    "host-side for the native floor", exc_name,
                )
                use_native = True
                inputs, ctx = tensorize(ssn, device=False, **tensorize_kw)
        _record_phase("tensorize", (time.perf_counter() - t0) * 1e3)
        # Incremental-tensorize forensics (dirty-row counts, fallback
        # reasons) for the bench/BENCH attribution.
        from ..solver.snapshot import last_tensorize_stats

        for k, v in last_tensorize_stats.items():
            last_stats[f"tensorize_{k}"] = v
        if inputs is None:
            # Idle cycle: nothing to solve, but verdicts recorded on
            # earlier cycles must not outlive the jobs they describe
            # (the reason gauge and /debug/jobs GC live in the verdict
            # pass, which only runs after a real solve).
            try:
                from ..obs import explain

                explain.record_idle_cycle(ssn)
            except Exception:  # pragma: no cover - forensics only
                logger.exception("idle-cycle verdict GC failed")
            if warm_outcome == "subset":
                # The subset's rows all vanished host-side (every live
                # pending task empty-resreq): nothing to solve, but the
                # carried verdicts STAND — advance like a noop cycle,
                # never wipe them as an idle save would.
                warm_mod.advance_noop(ssn)
                ws = warm_mod.warm_state_of(ssn.cache)
                last_stats["warm_carried"] = (
                    len(ws.carried) if ws is not None else 0
                )
            else:
                # An idle cycle leaves the strongest warm state there
                # is: zero carried verdicts.
                last_stats["warm_carried"] = warm_mod.save_warm_state(
                    ssn, None, None
                )
            if micro:
                metrics.register_micro_cycle("noop")
            return
        if breaker_pinned:
            # Counted here, not at the gate: the metric's documented
            # semantics are ladder descents — a cycle actually re-solved
            # on a lower rung — and an idle cycle (inputs None above)
            # solves nothing, so a breaker open across an idle stretch
            # must not tick one phantom descent per period.
            metrics.register_solver_fallback(
                "device", "native", "breaker-open"
            )

        # Degradation-ladder rungs for this cycle, top first. The top
        # rung is whatever the backend decision + tensorize produced
        # (candidate slabs → sparse program); every device cycle keeps
        # dense and the native CPU floor below it, so a runtime device
        # fault degrades scheduling quality, never the cycle.
        if use_native:
            rungs = ["native"]
        else:
            cand = getattr(inputs, "cand_idx", None)
            sparse_slabs = cand is not None and int(cand.shape[0]) > 0
            rungs = (["sparse"] if sparse_slabs else []) + [
                "dense", "native"
            ]

        t0 = time.perf_counter()
        # OVERLAPPED solve: launch is async (device rounds via XLA
        # dispatch, native rounds on a GIL-releasing worker thread);
        # the window below runs host work that does not depend on the
        # assignment, and handle.fetch() is the single block point.
        with span("solve_dispatch", jax_annotate=True):
            try:
                handle = self._launch_rung(rungs[0], inputs, ctx)
            except Exception as exc:
                # Synchronous dispatch failure (trace/compile error,
                # device lost at launch): enter the ladder handle-less.
                # Its first iteration re-launches this rung inside the
                # guarded try, so the failure descends rungs instead of
                # escaping the cycle — the one uncontained window the
                # async fetch path would otherwise leave.
                handle = None
                logger.error(
                    "solve dispatch on rung %r raised %s; deferring "
                    "to the degradation ladder",
                    rungs[0], type(exc).__name__,
                )
        ssn.register_inflight_solve(handle)
        t_launch = time.perf_counter()
        last_stats["solve_launch_ms"] = (t_launch - t0) * 1e3

        # --- overlap window -------------------------------------------
        # Device-cache pack forensics (dirty-ledger bookkeeping).
        if not use_native:
            from ..solver.device_cache import last_pack_stats

            for k, v in last_pack_stats.items():
                if k == "full_reasons":
                    if v:
                        last_stats["device_full_reasons"] = dict(v)
                else:
                    last_stats[f"device_{k}"] = v
        # Epilogue prep: the Releasing-capacity candidate scan reads
        # only the snapshot, never the assignment.
        with span("overlap_window"):
            releasing_nodes = self._releasing_candidates(ssn, ctx)
            if handle is not None and not handle.done():
                # The previous cycle's async bind/evict side effects
                # drain on their worker threads; parking here (bounded)
                # yields the GIL to them inside the solve's shadow
                # instead of letting the backlog contend with the apply
                # phase. Bool: did the previous cycle's bind queue
                # fully drain inside the overlap window (vs the bounded
                # wait timing out with backlog left).
                with span("bind_drain"):
                    last_stats["overlap_binds_drained"] = (
                        ssn.cache.wait_for_side_effects(timeout=0.02)
                    )
        last_stats["overlap_ms"] = (
            time.perf_counter() - t_launch
        ) * 1e3

        t_block = time.perf_counter()
        # The block point, now deadline-bounded and ladder-guarded: any
        # device-rung exception re-solves THIS cycle one rung down, a
        # budget expiry abandons the handle and drops to the native
        # floor (quarantining the backend via the breaker). Only a
        # native-floor failure escapes to the guarded cycle loop.
        ladder: list = []
        budget = containment.solve_budget()
        with span("solve_block", jax_annotate=True):
            assigned, handle = self._solve_ladder(
                ssn, rungs, inputs, ctx, handle, budget, ladder
            )
        ssn.register_inflight_solve(None)
        rounds, backend = handle.rounds, handle.backend
        metrics.update_solver_cycle(rounds, backend)
        last_stats["solve_block_ms"] = (
            time.perf_counter() - t_block
        ) * 1e3
        _record_phase("solve", (time.perf_counter() - t0) * 1e3)
        last_stats.update(backend=backend, rounds=rounds)
        last_stats["solve_ladder"] = ladder
        rejected_total = sum(e.get("rejected", 0) for e in ladder)
        if rejected_total:
            # Post-solve validation rejected placements somewhere on the
            # ladder (descended rung and/or native-floor drops).
            last_stats["validation_rejected"] = rejected_total
        if len(ladder) > 1:
            # Rung descents happened: flag the cycle as degraded so the
            # bench/flight-record readers need no ladder parsing.
            last_stats["solve_degraded"] = True

        # Sparse-solve attribution: whether this cycle's solve ran the
        # candidate-sparsified path, how much refill work it needed, and
        # why it fell back to dense when it did (bench + Prometheus).
        tsparse = last_stats.get("tensorize_sparse") or {}
        engaged = False
        refill_rounds = 0
        fallback_reason = None
        if backend == "native":
            ns = handle.native_stats or {}
            engaged = bool(ns.get("sparse"))
            refill_rounds = int(ns.get("refill_rounds", 0))
            if engaged:
                last_stats["sparse_fallback_scans"] = ns.get(
                    "fallback_scans", 0
                )
                last_stats["sparse_widened"] = ns.get("widened", 0)
        else:
            engaged = handle.refills is not None
            if engaged:
                # Refill ROUNDS = compacted dense stages that drained
                # the refill-flagged tasks; the task count rides along.
                refill_rounds = int(handle.stages or 0)
                last_stats["sparse_refill_tasks"] = handle.refills
            elif tsparse.get("enabled"):
                # tensorize built slabs but the final solve ran dense:
                # a ladder descent stripped them (the sparse rung
                # failed), or a legacy explicit-staged call ignored
                # them.
                fallback_reason = (
                    "ladder-degraded" if len(ladder) > 1
                    else "sharded-mesh"
                )
        if not engaged and fallback_reason is None:
            fallback_reason = tsparse.get("reason")
        last_stats["sparse_engaged"] = engaged
        if engaged:
            last_stats["sparse_k"] = tsparse.get("k")
            last_stats["sparse_refill_rounds"] = refill_rounds
        elif fallback_reason:
            last_stats["sparse_fallback_reason"] = fallback_reason
        metrics.update_solver_sparse(engaged, refill_rounds,
                                     fallback_reason)
        # Sharded-sparse attribution: whether the FINAL successful rung
        # ran the slab solve sharded over the mesh, under which mode,
        # and how many cross-shard reconciliation rounds it took
        # (sharding.last_dispatch reflects the last solve_sharded
        # dispatch — exactly the winning rung's).
        from ..solver import sharding as sharding_mod

        disp = sharding_mod.last_dispatch
        sharded_engaged = bool(
            engaged and backend != "native"
            and disp.get("sparse_sharded")
        )
        last_stats["sparse_sharded_engaged"] = sharded_engaged
        if sharded_engaged:
            last_stats["sparse_shard_mode"] = disp.get("mode")
            last_stats["sparse_shard_count"] = disp.get("shards")
            if handle.reconcile_rounds is not None:
                last_stats["sparse_reconcile_rounds"] = (
                    handle.reconcile_rounds
                )
            metrics.register_sparse_sharded(disp.get("mode"))
            # Delta-packed commit accounting (spmd.note_commit_stats):
            # per-round wire bytes of the code+accept-bit exchange vs
            # the full-state broadcast it replaced.
            from ..solver import spmd as spmd_mod

            for key in (
                "commit_bytes_exchanged",
                "commit_bytes_full_broadcast",
                "commit_bytes_per_round",
            ):
                if key in spmd_mod.last_commit_stats:
                    last_stats[key] = spmd_mod.last_commit_stats[key]
        # Which path produced the candidate slabs (device-resident
        # selection vs labeled host fallback) — tensorize stats carry
        # the label; the device counter is incremented at the source.
        if tsparse.get("select_path"):
            last_stats["select_path"] = tsparse.get("select_path")
        try:
            from ..solver.kernels import jit_compilation_count

            count = jit_compilation_count()
            last_stats["jit_variants"] = count
            metrics.update_solver_jit_cache(count)
        except Exception:  # pragma: no cover - forensics only
            logger.exception("jit cache census failed")

        t0 = time.perf_counter()
        # ctx.tasks is already in global priority-rank order. The
        # sequential guard ("does this task still fit the node, given
        # everything applied before it?") is evaluated for ALL assignments
        # at once. Sequential semantics being reproduced: each allocation
        # checks its own init_resreq against idle (allocate_tpu guard /
        # node_info.go:161-171), while applied allocations shrink idle by
        # RESREQ (add_task subtracts resreq, not init_resreq). So per
        # node, in priority order: exclusive-prefix(resreq) + own
        # init_resreq < idle + eps per dim (less_equal's epsilon,
        # resource_info.go:253-277). When everything fits — the invariant
        # the kernel's capacity accounting guarantees — the whole set is
        # applied via the batched session path; on drift (should not
        # happen) fall back to the per-task guarded loop.
        T = len(ctx.tasks)
        a = np.asarray(assigned[:T])
        sel = np.nonzero(a >= 0)[0]
        all_fit = True
        order = seg_starts = nodes_sorted = None
        if sel.size:
            nodes_sel = a[sel]
            order = np.argsort(nodes_sel, kind="stable")
            nodes_sorted = nodes_sel[order]
            req_sel = ctx.task_req_host[sel]  # shared with the job view
            req_rows = req_sel[order]
            fit_rows = ctx.task_fit_host[sel][order]
            cum = np.cumsum(req_rows, axis=0)
            seg_starts = np.nonzero(
                np.diff(nodes_sorted, prepend=-1)
            )[0]
            base = np.zeros_like(cum)
            base[seg_starts[1:]] = cum[seg_starts[1:] - 1]
            # exclusive within-node prefix of resreq consumption
            prefix = cum - req_rows - np.maximum.accumulate(base, axis=0)
            idle = ctx.node_idle_host[nodes_sorted]
            eps = ctx.layout.eps().astype(np.float64)
            all_fit = bool((prefix + fit_rows < idle + eps).all())
        placed_tasks: list = []
        if all_fit:
            if sel.size:
                # Per-node groups straight from the fit guard's
                # segmentation — the session path never re-groups with
                # per-task dict passes, and each group carries its
                # aggregate resreq delta (a cumsum difference) so node
                # accounting skips per-task Resource math too.
                layout = ctx.layout
                mib = 1024.0 * 1024.0

                def row_to_resource(row):
                    delta = Resource(row[0], row[1] * mib)
                    for k, name in enumerate(layout.scalars):
                        v = float(row[2 + k])
                        if v:
                            delta.add_scalar(name, v)
                    return delta

                getter = ctx.tasks.__getitem__
                tasks_sorted = list(map(getter, sel[order].tolist()))
                seg_list = seg_starts.tolist()
                seg_ends = seg_list[1:] + [len(tasks_sorted)]
                zero = np.zeros_like(cum[0])
                node_groups = []
                for s, e in zip(seg_list, seg_ends):
                    row = cum[e - 1] - (cum[s - 1] if s else zero)
                    node_groups.append((
                        ctx.nodes[int(nodes_sorted[s])].name,
                        tasks_sorted[s:e],
                        row_to_resource(row),
                    ))
                # Per-JOB groups with aggregate resreq deltas, same
                # cumsum-difference trick on a job-sorted view: the
                # session's apply tail then runs ~#jobs aggregate
                # updates (status-index move, job.allocated, plugin
                # batch handlers) instead of 50k per-task passes.
                job_idx = np.asarray(
                    ctx.host_inputs.task_job[:T]
                )[sel]
                jorder = np.argsort(job_idx, kind="stable")
                jtasks = list(map(getter, sel[jorder].tolist()))
                jcum = np.cumsum(req_sel[jorder], axis=0)
                jstarts = np.nonzero(
                    np.diff(job_idx[jorder], prepend=-1)
                )[0].tolist()
                jends = jstarts[1:] + [len(jtasks)]
                job_groups = []
                for s, e in zip(jstarts, jends):
                    row = jcum[e - 1] - (jcum[s - 1] if s else zero)
                    job_groups.append((
                        jtasks[s].job, jtasks[s:e], row_to_resource(row)
                    ))
                placed = ssn.allocate_batch_grouped(
                    node_groups, job_groups=job_groups
                )
                if placed == len(tasks_sorted):
                    placed_tasks = tasks_sorted
                else:
                    # Staging dropped tasks (vanished node, volume
                    # failure): only tasks whose status actually moved
                    # count as placed — the ledger/audit must not
                    # claim pods the apply path dropped.
                    from ..api import allocated_status

                    placed_tasks = [
                        t for t in tasks_sorted
                        if allocated_status(t.status)
                    ]
            else:
                placed = 0
        else:
            logger.warning(
                "solver assignment drifted from session accounting; "
                "applying with the per-task guard"
            )
            placed = 0
            for i in sel:
                task, node_name = ctx.tasks[i], ctx.nodes[a[i]].name
                node = ssn.nodes[node_name]
                if not task.init_resreq.less_equal(node.idle):
                    logger.warning(
                        "solver assignment no longer fits: task %s on %s",
                        task.uid, node_name,
                    )
                    continue
                try:
                    ssn.allocate(task, node_name)
                    placed += 1
                    placed_tasks.append(task)
                except Exception:
                    logger.exception(
                        "Failed to bind Task %s on %s", task.uid, node_name
                    )

        _record_phase("apply", (time.perf_counter() - t0) * 1e3)
        TRACER.complete("apply", t0)
        last_stats["placed"] = placed
        # Apply sub-phase forensics from the batched session path.
        from ..framework.session import last_apply_stats

        for k, v in last_apply_stats.items():
            last_stats[f"apply_{k}"] = v

        # Placement-latency ledger + decision audit (obs/latency.py):
        # stamp every task the solve placed (cycle kind, warm outcome,
        # winning rung, this cycle's solve time) and append one audit
        # record per placed job. Cost is O(placed) — zero on the idle
        # cycle the <1% obs budget is pinned against. Deterministic
        # fields only: the sim's audit stream must replay byte-equal.
        cycle_kind = "micro" if micro else "periodic"
        try:
            from ..obs import latency as latency_mod

            if placed_tasks and latency_mod.LEDGER.enabled:
                placed_by_job: dict = {}
                for task in placed_tasks:
                    placed_by_job[task.job] = (
                        placed_by_job.get(task.job, 0) + 1
                    )
                job_queues = {}
                for job_uid in placed_by_job:
                    job = ssn.jobs.get(job_uid)
                    if job is not None:
                        job_queues[job_uid] = job.queue
                latency_mod.LEDGER.note_placed(
                    ((task.uid, task.job) for task in placed_tasks),
                    job_queues,
                    kind=cycle_kind,
                    solve_s=(
                        last_stats.get("tensorize_ms", 0.0)
                        + last_stats.get("solve_ms", 0.0)
                        + last_stats.get("apply_ms", 0.0)
                    ) / 1e3,
                )
                for job_uid, count in placed_by_job.items():
                    latency_mod.AUDIT.append({
                        "action": "placed",
                        "job": job_uid,
                        "queue": job_queues.get(job_uid, ""),
                        "count": count,
                        "kind": cycle_kind,
                        "backend": backend,
                        "warm": warm_outcome,
                        "degraded": len(ladder) > 1 or breaker_pinned,
                    })
        except Exception:  # pragma: no cover - forensics only
            logger.exception("placement-latency ledger update failed")

        t0 = time.perf_counter()
        # Epilogue: pipeline unassigned tasks onto Releasing resources
        # (allocate.go:168-181), a host-side pass over the leftovers.
        # Same gates as greedy: the task must pass predicates on the node
        # (kernel feas mask), its queue must not be overused
        # (allocate.go:94-95), and among eligible nodes the best-scored one
        # wins, mirroring PrioritizeNodes → SelectBestNode. The candidate
        # set was computed in the solve's overlap window.
        leftovers = enumerate(ctx.tasks) if releasing_nodes else ()
        for i, task in leftovers:
            if int(assigned[i]) >= 0:
                continue
            job = ssn.jobs.get(task.job)
            if job is None:
                continue
            queue = ssn.queues.get(job.queue)
            if queue is not None and ssn.overused(queue):
                continue
            feas_row = ctx.mask.row(i)
            candidates = [
                node
                for j, node in releasing_nodes
                if feas_row[j]
                and task.init_resreq.less_equal(node.releasing)
            ]
            if not candidates:
                continue
            priority_list = prioritize_nodes(
                task, candidates, ssn.node_prioritizers()
            )
            best = ssn.nodes[select_best_node(priority_list)]
            delta = best.idle.clone()
            delta.fit_delta(task.init_resreq)
            job.record_fit_delta(best.name, delta)
            try:
                ssn.pipeline(task, best.name)
            except Exception:
                logger.exception(
                    "Failed to pipeline Task %s on %s", task.uid, best.name
                )

        _record_phase("epilogue", (time.perf_counter() - t0) * 1e3)
        TRACER.complete("epilogue", t0)

        # --- explainability + flight-recorder attribution --------------
        # Per-job verdicts for everything the solve left unassigned
        # (obs/explain.py), classified from the cycle's own evidence —
        # cost scales with the unassigned count. The flight recorder's
        # open cycle record absorbs the cycle's solver attribution so
        # an error/SIGUSR1 dump carries it without re-deriving.
        t0 = time.perf_counter()
        with span("verdicts"):
            try:
                from ..obs import explain

                # "exhausted" = the sparse solve reported pressure past
                # its truncated slabs (native per-task scan-overflow
                # fallbacks). Truncation ALONE is normal and both
                # backends refill to exact verdicts — see
                # explain._classify.
                ns = handle.native_stats or {}
                sparse_info = {
                    "engaged": engaged,
                    "k": tsparse.get("k"),
                    "truncated": bool(tsparse.get("truncated_classes")),
                    "exhausted": bool(
                        engaged and ns.get("fallback_scans", 0)
                    ),
                    "refill_rounds": refill_rounds,
                    "fallback_reason": fallback_reason,
                }
                reason_counts = explain.record_cycle_verdicts(
                    ssn, ctx, assigned, sparse=sparse_info
                )
                if reason_counts:
                    last_stats["unschedulable_reasons"] = reason_counts
            except Exception:  # pragma: no cover - forensics only
                logger.exception("verdict recording failed")
                reason_counts = {}
        last_stats["verdicts_ms"] = (time.perf_counter() - t0) * 1e3
        # Warm-state save: this solve's unassigned remainder becomes the
        # carried-verdict set the next cycle's plan checks against.
        last_stats["warm_carried"] = warm_mod.save_warm_state(
            ssn, ctx, assigned
        )
        if micro:
            metrics.register_micro_cycle("solve")
        RECORDER.annotate("solver", {
            "backend": backend,
            "rounds": rounds,
            "placed": placed,
            "tasks": len(ctx.tasks),
            "warm": warm_outcome,
            "warm_carried": last_stats["warm_carried"],
            # Fault-containment attribution: the rung sequence this
            # cycle actually ran (one entry per attempt), the breaker's
            # state after it, and the last ladder descent — the flight
            # record's "why is this cycle degraded" answer.
            "ladder": list(ladder),
            "degraded": len(ladder) > 1 or breaker_pinned,
            "breaker_state": containment.BREAKER.state,
            "sparse_engaged": engaged,
            "sparse_k": tsparse.get("k") if engaged else None,
            "sparse_refill_rounds": refill_rounds if engaged else None,
            "sparse_sharded": sharded_engaged,
            "sparse_shard_mode": (
                last_stats.get("sparse_shard_mode")
                if sharded_engaged else None
            ),
            "fallback_reason": fallback_reason,
            "device_bytes_shipped": last_stats.get("device_bytes_shipped"),
            "device_rows_patched": last_stats.get("device_rows_patched"),
            "unschedulable_reasons": reason_counts,
        })
        logger.debug(
            "allocate_tpu placed %d/%d tasks in %d rounds",
            placed, len(ctx.tasks), rounds,
        )


register_action(AllocateTpuAction())
