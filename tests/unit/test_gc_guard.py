"""deferred_gc: GC state is process-wide, so concurrent guards from
different threads must never strand GC disabled (advisor r4 finding)."""

import gc
import threading

from kube_batch_tpu.utils.gc_guard import deferred_gc


def test_nested_reenables_only_at_outermost():
    assert gc.isenabled()
    with deferred_gc(collect_generation=-1):
        assert not gc.isenabled()
        with deferred_gc(collect_generation=-1):
            assert not gc.isenabled()
        assert not gc.isenabled()  # inner exit must not re-enable
    assert gc.isenabled()


def test_exception_restores_gc():
    try:
        with deferred_gc(collect_generation=-1):
            raise RuntimeError("boom")
    except RuntimeError:
        pass
    assert gc.isenabled()


def test_concurrent_guards_do_not_strand_gc_disabled():
    # Two threads overlap their guards in every interleaving the
    # barriers can force; GC must be enabled once both exit.
    assert gc.isenabled()
    inside = threading.Barrier(3, timeout=10)  # 2 workers + main
    release = threading.Event()

    def worker():
        with deferred_gc(collect_generation=-1):
            inside.wait()      # both threads hold a guard concurrently
            release.wait(10)   # first exiter leaves while other holds

    threads = [threading.Thread(target=worker) for _ in range(2)]
    for t in threads:
        t.start()
    inside.wait()
    assert not gc.isenabled()
    release.set()
    for t in threads:
        t.join(10)
    assert gc.isenabled()
