"""``python -m kube_batch_tpu`` — the scheduler binary."""

from .cli import main

if __name__ == "__main__":
    main()
