"""Bind-intent journal: the durable commit-dispatch seam
(doc/design/robustness.md, failover section).

Covers the cluster-side stores (InProcessCluster in-memory,
KubeCluster Lease-annotation via FakeKube) and the cache wiring:
intents appended BEFORE side effects, applied/failed marks as binds
drain, self-pruning on full resolution, and the KBT_BIND_JOURNAL kill
switch."""

import threading

import pytest

from kube_batch_tpu import metrics
from kube_batch_tpu.api import PodPhase, build_resource_list
from kube_batch_tpu.cache import SchedulerCache
from kube_batch_tpu.cluster import InProcessCluster
from kube_batch_tpu.utils.test_utils import (
    build_node,
    build_pod,
    build_pod_group,
    build_queue,
)


def req(cpu="500m", mem="512Mi"):
    return build_resource_list(cpu=cpu, memory=mem)


def record_for(uids, node="n1", job="ns/pg1", minm=2, leader="L0"):
    return {
        "leader": leader,
        "tasks": [
            {"uid": u, "pod": f"ns/{u}", "node": node, "job": job}
            for u in uids
        ],
        "gangs": {job: minm},
    }


class TestInProcessJournal:
    def test_append_assigns_monotone_seqs_and_lists_sorted(self):
        c = InProcessCluster(simulate_kubelet=False)
        s1 = c.append_bind_intent(record_for(["a"]))
        s2 = c.append_bind_intent(record_for(["b"]))
        assert s2 > s1
        recs = c.list_bind_intents()
        assert [r["seq"] for r in recs] == [s1, s2]
        assert recs[0]["marks"] == {}
        assert recs[0]["tasks"][0]["uid"] == "a"

    def test_partial_marks_keep_record_full_marks_self_prune(self):
        c = InProcessCluster(simulate_kubelet=False)
        seq = c.append_bind_intent(record_for(["a", "b"]))
        assert c.mark_bind_intent(seq, "a", "applied") is False
        recs = c.list_bind_intents()
        assert recs[0]["marks"] == {"a": "applied"}
        # Second (last) mark resolves the record: self-pruned.
        assert c.mark_bind_intent(seq, "b", "failed") is True
        assert c.list_bind_intents() == []
        # Marking a pruned/unknown seq is a no-op, not an error.
        assert c.mark_bind_intent(seq, "a", "applied") is False

    def test_remove_and_listed_copies_are_isolated(self):
        c = InProcessCluster(simulate_kubelet=False)
        seq = c.append_bind_intent(record_for(["a"]))
        listed = c.list_bind_intents()[0]
        listed["marks"]["a"] = "applied"  # caller-side mutation
        assert c.list_bind_intents()[0]["marks"] == {}
        c.remove_bind_intent(seq)
        assert c.list_bind_intents() == []


class TestCacheJournalWiring:
    def make(self, **env):
        cluster = InProcessCluster(simulate_kubelet=True)
        cluster.create_queue(build_queue("default", weight=1))
        cluster.create_node(
            build_node("n1", build_resource_list(
                cpu="8", memory="16Gi", pods=110,
            ))
        )
        cluster.create_pod_group(
            build_pod_group("pg1", namespace="ns", min_member=2)
        )
        for name in ("p1", "p2"):
            cluster.create_pod(build_pod(
                "ns", name, "", PodPhase.PENDING, req(), group_name="pg1"
            ))
        cache = SchedulerCache(cluster=cluster)
        cache.start_ingest()
        return cluster, cache

    def tasks_of(self, cache, job="ns/pg1"):
        with cache.mutex:
            return sorted(
                (t.clone() for t in cache.jobs[job].tasks.values()),
                key=lambda t: t.name,
            )

    def test_bind_batch_journals_then_marks_applied_and_self_prunes(self):
        cluster, cache = self.make()
        assert cache.journal_enabled
        before = metrics.bind_journal_intents.get(("appended",))
        tasks = self.tasks_of(cache)
        for t in tasks:
            t.node_name = "n1"
        cache.bind_batch(tasks)
        assert cache.wait_for_side_effects()
        # Both binds landed and were marked: the record resolved away.
        assert cluster.list_bind_intents() == []
        assert metrics.bind_journal_intents.get(("appended",)) == before + 1
        assert metrics.bind_journal_intents.get(("applied",)) >= 2
        assert cluster.get_pod("ns", "p1").spec.node_name == "n1"
        cache.shutdown()

    def test_bind_failure_marks_failed_and_resolves(self):
        cluster, cache = self.make()

        class Boom:
            def bind(self, pod, hostname):
                raise RuntimeError("injected bind failure")

        cache.binder = Boom()
        tasks = self.tasks_of(cache)
        for t in tasks:
            t.node_name = "n1"
        failed_before = metrics.bind_journal_intents.get(("failed",))
        cache.bind_batch(tasks)
        assert cache.wait_for_side_effects()
        assert cluster.list_bind_intents() == []
        assert (
            metrics.bind_journal_intents.get(("failed",))
            >= failed_before + 2
        )
        cache.shutdown()

    def test_single_bind_path_journals_too(self):
        cluster, cache = self.make()
        task = self.tasks_of(cache)[0]
        cache.bind(task, "n1")
        assert cache.wait_for_side_effects()
        assert cluster.list_bind_intents() == []
        assert cluster.get_pod("ns", "p1").spec.node_name == "n1"
        cache.shutdown()

    def test_env_kill_switch_disables_journaling(self, monkeypatch):
        monkeypatch.setenv("KBT_BIND_JOURNAL", "0")
        cluster, cache = self.make()
        assert not cache.journal_enabled
        tasks = self.tasks_of(cache)
        for t in tasks:
            t.node_name = "n1"
        cache.bind_batch(tasks)
        assert cache.wait_for_side_effects()
        assert cluster.list_bind_intents() == []
        assert cluster.get_pod("ns", "p1").spec.node_name == "n1"
        cache.shutdown()

    def test_journal_append_failure_never_blocks_binds(self):
        cluster, cache = self.make()

        def boom(record):
            raise RuntimeError("journal store down")

        cluster.append_bind_intent = boom
        tasks = self.tasks_of(cache)
        for t in tasks:
            t.node_name = "n1"
        cache.bind_batch(tasks)
        assert cache.wait_for_side_effects()
        # Binds landed unjournaled (availability over recoverability).
        assert cluster.get_pod("ns", "p1").spec.node_name == "n1"
        cache.shutdown()

    def test_gang_min_member_recorded_in_intent(self):
        cluster, cache = self.make()
        captured = {}
        orig = cluster.append_bind_intent

        def spy(record):
            captured.update(record)
            return orig(record)

        cluster.append_bind_intent = spy
        tasks = self.tasks_of(cache)
        for t in tasks:
            t.node_name = "n1"
        cache.bind_batch(tasks)
        assert cache.wait_for_side_effects()
        assert captured["gangs"] == {"ns/pg1": 2}
        assert captured["leader"] == cache.leader_identity
        assert sorted(t["uid"] for t in captured["tasks"]) == sorted(
            t.uid for t in tasks
        )
        cache.shutdown()


class TestKubeLeaseJournal:
    """Lease-annotation journal on the real-cluster adapter, served by
    the in-memory FakeKube API server (Lease CRUD with optimistic
    concurrency)."""

    @pytest.fixture()
    def kube(self):
        from kube_batch_tpu.cluster.kube import KubeCluster, KubeConfig
        from kube_batch_tpu.utils.fake_kube import FakeKube

        server = FakeKube()
        cluster = KubeCluster(KubeConfig(server.url), watch_kinds=())
        cluster.journal_namespace = "kube-system"
        try:
            yield server, cluster
        finally:
            server.close()

    def test_append_mark_list_remove_roundtrip(self, kube):
        _server, cluster = kube
        assert cluster.supports_bind_journal
        s1 = cluster.append_bind_intent(record_for(["a", "b"]))
        s2 = cluster.append_bind_intent(record_for(["c"], job="ns/pg2"))
        assert s2 == s1 + 1
        recs = cluster.list_bind_intents()
        assert [r["seq"] for r in recs] == [s1, s2]
        # Partial mark persists; full marks self-prune through the CAS.
        assert cluster.mark_bind_intent(s1, "a", "applied") is False
        assert cluster.list_bind_intents()[0]["marks"] == {"a": "applied"}
        assert cluster.mark_bind_intent(s1, "b", "failed") is True
        assert [r["seq"] for r in cluster.list_bind_intents()] == [s2]
        cluster.remove_bind_intent(s2)
        assert cluster.list_bind_intents() == []
        # Seq survives pruning: the counter rides the same annotation.
        assert cluster.append_bind_intent(record_for(["d"])) == s2 + 1

    def test_journal_survives_adapter_restart(self, kube):
        """The failover property: a SECOND adapter (the successor's
        process) reads the first one's intents back."""
        server, cluster = kube
        seq = cluster.append_bind_intent(record_for(["a"]))

        from kube_batch_tpu.cluster.kube import KubeCluster, KubeConfig

        successor = KubeCluster(KubeConfig(server.url), watch_kinds=())
        successor.journal_namespace = "kube-system"
        recs = successor.list_bind_intents()
        assert [r["seq"] for r in recs] == [seq]
        assert recs[0]["tasks"][0]["uid"] == "a"


class TestConcurrentJournal:
    def test_concurrent_appends_and_marks_stay_consistent(self):
        """The journal seam is called from the cache's side-effect pool
        — concurrent appenders/markers must neither lose records nor
        deadlock (cluster.store lock)."""
        c = InProcessCluster(simulate_kubelet=False)
        seqs = []
        lock = threading.Lock()

        def worker(i):
            seq = c.append_bind_intent(record_for([f"t{i}"]))
            with lock:
                seqs.append(seq)
            c.mark_bind_intent(seq, f"t{i}", "applied")

        threads = [
            threading.Thread(target=worker, args=(i,)) for i in range(16)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert len(set(seqs)) == 16
        assert c.list_bind_intents() == []  # all resolved
