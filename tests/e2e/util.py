"""E2E helpers (reference test/e2e/util.go).

A ``Context`` runs the REAL ``Scheduler`` loop in a daemon thread against an
``InProcessCluster`` with the hollow-kubelet simulation on (the kubemark
analog): binds flip pods to Running, evictions delete pods. Jobs are
created as PodGroup + pods like ``createJob`` (util.go:300); waiters poll
phases like ``waitPodGroupReady``/``waitTasksReady`` (util.go:462-488).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from kube_batch_tpu.api import PodPhase, PriorityClass, build_resource_list
from kube_batch_tpu.api.objects import ObjectMeta
from kube_batch_tpu.cache import new_scheduler_cache
from kube_batch_tpu.cluster import InProcessCluster
from kube_batch_tpu.scheduler import Scheduler
from kube_batch_tpu.utils.test_utils import (
    build_node,
    build_pod,
    build_pod_group,
    build_queue,
)

ONE_CPU = build_resource_list(cpu="1000m", memory="1Gi")
HALF_CPU = build_resource_list(cpu="500m", memory="512Mi")

DEFAULT_CONF = """
actions: "allocate, backfill"
tiers:
- plugins:
  - name: priority
  - name: gang
  - name: conformance
- plugins:
  - name: drf
  - name: predicates
  - name: proportion
  - name: nodeorder
"""

PREEMPT_CONF = """
actions: "allocate, backfill, preempt"
tiers:
- plugins:
  - name: priority
  - name: gang
  - name: conformance
- plugins:
  - name: drf
  - name: predicates
  - name: proportion
  - name: nodeorder
"""

RECLAIM_CONF = """
actions: "reclaim, allocate, backfill"
tiers:
- plugins:
  - name: priority
  - name: gang
  - name: conformance
- plugins:
  - name: drf
  - name: predicates
  - name: proportion
  - name: nodeorder
"""


@dataclass
class JobSpec:
    """reference test/e2e/util.go taskSpec/jobSpec (simplified to pods)."""

    name: str
    namespace: str = "test"
    queue: str = "default"
    replicas: int = 1
    min_member: Optional[int] = None  # default: replicas
    req: Dict = field(default_factory=lambda: dict(ONE_CPU))
    priority: Optional[int] = None
    priority_class_name: str = ""
    labels: Optional[Dict[str, str]] = None
    selector: Optional[Dict[str, str]] = None


class Context:
    """reference test/e2e/util.go:100 initTestContext (standalone)."""

    def __init__(
        self,
        nodes: int = 2,
        node_cpu: str = "4",
        node_mem: str = "8Gi",
        queues: Optional[Dict[str, int]] = None,
        conf: str = DEFAULT_CONF,
        period: float = 0.02,
    ):
        self.cluster = InProcessCluster(simulate_kubelet=True)
        for name, weight in (queues or {"default": 1}).items():
            self.cluster.create_queue(build_queue(name, weight=weight))
        self.nodes = []
        for i in range(nodes):
            node = build_node(
                f"node-{i}",
                build_resource_list(cpu=node_cpu, memory=node_mem, pods=110),
            )
            self.nodes.append(node)
            self.cluster.create_node(node)
        self.cache = new_scheduler_cache(self.cluster, "tpu-batch", "default")
        self.scheduler = Scheduler(
            self.cache, scheduler_conf=conf, schedule_period=period
        )
        self.stop = threading.Event()
        self.thread = threading.Thread(
            target=self.scheduler.run, args=(self.stop,), daemon=True
        )

    def __enter__(self):
        self.thread.start()
        return self

    def __exit__(self, *exc):
        self.stop.set()
        self.thread.join(timeout=10)

    # -- object creation ----------------------------------------------------

    def create_priority_class(self, name: str, value: int) -> None:
        self.cluster.create_priority_class(
            PriorityClass(metadata=ObjectMeta(name=name), value=value)
        )

    def create_job(self, spec: JobSpec) -> List:
        """reference util.go:300 createJob: PodGroup + replica pods."""
        min_member = spec.min_member if spec.min_member is not None else spec.replicas
        self.cluster.create_pod_group(build_pod_group(
            spec.name, namespace=spec.namespace, min_member=min_member,
            queue=spec.queue, priority_class_name=spec.priority_class_name,
        ))
        pods = []
        for i in range(spec.replicas):
            pod = build_pod(
                spec.namespace, f"{spec.name}-{i}", "", PodPhase.PENDING,
                dict(spec.req), group_name=spec.name, labels=spec.labels,
                selector=spec.selector, priority=spec.priority,
            )
            pods.append(pod)
        # Pods may be customized by the caller before creation.
        return pods

    def submit(self, pods: List) -> None:
        for pod in pods:
            self.cluster.create_pod(pod)

    def create_and_submit(self, spec: JobSpec) -> List:
        pods = self.create_job(spec)
        self.submit(pods)
        return pods

    # -- waiters (reference util.go:462-488) --------------------------------

    def _await(self, fn, timeout: float = 10.0, interval: float = 0.02) -> bool:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if fn():
                return True
            time.sleep(interval)
        return fn()

    def pods(self, namespace: str = "test") -> List:
        return [
            p for p in self.cluster.list_objects("Pod")
            if p.namespace == namespace
        ]

    def running_pods(self, job: str, namespace: str = "test") -> List:
        return [
            p for p in self.pods(namespace)
            if p.name.startswith(f"{job}-") and p.status.phase == PodPhase.RUNNING
        ]

    def wait_tasks_ready(self, job: str, n: int, namespace: str = "test",
                         timeout: float = 10.0) -> bool:
        """reference util.go waitTasksReady: ≥n pods of the job Running."""
        return self._await(
            lambda: len(self.running_pods(job, namespace)) >= n, timeout
        )

    def wait_job_gone(self, job: str, namespace: str = "test",
                      timeout: float = 10.0) -> bool:
        return self._await(
            lambda: not [
                p for p in self.pods(namespace)
                if p.name.startswith(f"{job}-")
            ],
            timeout,
        )

    def wait_pod_group_phase(self, name: str, phase: str,
                             namespace: str = "test",
                             timeout: float = 10.0) -> bool:
        def check():
            for pg in self.cluster.list_objects("PodGroup"):
                if pg.name == name and pg.namespace == namespace:
                    return pg.status.phase == phase
            return False
        return self._await(check, timeout)

    def settle(self, cycles: float = 5.0) -> None:
        """Let the scheduler run a few cycles (for negative assertions)."""
        time.sleep(self.scheduler.schedule_period * cycles + 0.1)
